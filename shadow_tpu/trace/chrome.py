"""Chrome trace-event JSON export (loads in Perfetto / about:tracing).

Sim-time channel: rounds and spans as nested slices on one track
(spans open with "B"/close with "E"; each round is a complete "X"
slice inside its span; device-span aborts are instants).  Timestamps
are simulated microseconds — the timeline IS the simulation.

Sim-netstat channel: per-connection COUNTER tracks ("C" events) on a
third process — cwnd/ssthresh, srtt, buffer occupancy and cumulative
retransmits per sampled round, capped to the top connections by
retransmit count so a 10k-host export stays loadable.

Wall-time channel: per-phase slices on a second "process" with real
(relative) microseconds — where a dispatch's wall time went.
"""

from __future__ import annotations

from shadow_tpu.trace.events import (EL_NAMES, FAM_NAMES, FR_ROUND,
                                     FR_SPAN_ABORT, FR_SPAN_COMMIT,
                                     FR_SPAN_START, iter_records)

PID_SIM = 1
PID_WALL = 2
PID_NETSTAT = 3
PID_SYSCALL = 4
PID_FABRIC = 5
PID_KERN = 6

# Default per-entity counter-track cap; the CLI overrides it from the
# experimental.chrome_top_n knob (one knob for every track family).
DEFAULT_TOP_N = 16

# Counter tracks per exported connection: (track suffix, args built
# from a TEL_REC tuple — see trace/events.py for the field order).
NETSTAT_TRACKS = (
    # ssthresh is elided while still at its "infinite" pre-loss value
    # (RFC 6928 slow start) — plotting 2^31 would flatten the track.
    ("cwnd", lambda r: {"cwnd": r[6]}
     | ({"ssthresh": r[7]} if r[7] < (1 << 30) else {})),
    ("srtt-ms", lambda r: {"srtt": r[8] / 1e6}),
    ("buffers", lambda r: {"sndbuf": r[11], "rcvbuf": r[12]}),
    ("retransmits", lambda r: {"rtx": r[13], "sack-skips": r[14]}),
)


def netstat_events(tel_bytes: bytes, top_n: int = DEFAULT_TOP_N) -> list:
    """Per-connection counter events from telemetry-sim.bin.  Keeps
    the top_n connections by final retransmit count (ties broken by
    connection key, so the selection is deterministic — the same
    ranking `tools/trace net` prints)."""
    from shadow_tpu.net.graph import format_ip
    from shadow_tpu.trace.netstat import (group_by_conn,
                                          top_by_retransmits)

    by_conn = group_by_conn(tel_bytes)
    ranked = top_by_retransmits(by_conn, top_n)
    ev: list = [_meta(PID_NETSTAT, 0, "process_name",
                      "sim-netstat (per-connection TCP)")]
    for key in ranked:
        host, lport, rport, rip = key
        name = f"h{host}:{lport}->{format_ip(rip)}:{rport}"
        for suffix, args_of in NETSTAT_TRACKS:
            for rec in by_conn[key]:
                ev.append({"ph": "C", "pid": PID_NETSTAT, "tid": 0,
                           "ts": rec[0] / 1e3,
                           "name": f"{name} {suffix}",
                           "args": args_of(rec)})
    return ev


def _meta(pid: int, tid: int, what: str, name: str) -> dict:
    return {"ph": "M", "pid": pid, "tid": tid, "name": what,
            "args": {"name": name}}


def fabric_events(fab_bytes: bytes, top_n: int = DEFAULT_TOP_N) -> list:
    """Per-link counter tracks from fabric-sim.bin's FB section:
    CoDel depth + head sojourn, token-bucket balances and the
    cumulative link packet counters, for the top_n hosts by peak
    sampled queue depth (ties broken by host id — the same ranking
    `tools/trace fabric` prints)."""
    from shadow_tpu.trace.fabricstat import (group_by_host,
                                             top_by_peak_depth)

    by_host = group_by_host(fab_bytes)
    ranked = top_by_peak_depth(by_host, top_n)
    ev: list = []
    if not ranked:
        return ev
    ev.append(_meta(PID_FABRIC, 0, "process_name",
                    f"fabric observatory (top {len(ranked)} of "
                    f"{len(by_host)} links)"))
    for host in ranked:
        for rec in by_host[host]:
            ts = rec[0] / 1e3
            ev.append({"ph": "C", "pid": PID_FABRIC, "tid": 0,
                       "ts": ts, "name": f"h{host} queue",
                       "args": {"depth": rec[3],
                                "sojourn-ms": rec[5] / 1e6}})
            ev.append({"ph": "C", "pid": PID_FABRIC, "tid": 0,
                       "ts": ts, "name": f"h{host} bucket",
                       "args": {"out-bal": max(rec[9], 0),
                                "in-bal": max(rec[11], 0)}})
            ev.append({"ph": "C", "pid": PID_FABRIC, "tid": 0,
                       "ts": ts, "name": f"h{host} link",
                       "args": {"pkts-out": rec[13],
                                "pkts-in": rec[15]}})
    return ev


def kern_events(ks_bytes: bytes) -> list:
    """Per-stage counter tracks from kernel-sim.bin (the device-kernel
    observatory): one "C" event per committed span per occupied
    stage, at the span's entry time — active lanes plus occupancy in
    permille, so Perfetto plots each stage's lane utilization across
    the run.  Record count is already bounded (one per committed
    span), so no top-N cap applies."""
    from shadow_tpu.trace.events import (KS_EXCHANGE, KS_NAMES,
                                         iter_ks_records)

    ev: list = []
    seen = False
    for t, family, hosts, rounds, trips, fires, lanes in \
            iter_ks_records(ks_bytes):
        if not seen:
            ev.append(_meta(PID_KERN, 0, "process_name",
                            "device-kernel observatory (per-stage "
                            "lane occupancy)"))
            seen = True
        fam = FAM_NAMES[family] if 0 <= family < len(FAM_NAMES) \
            else str(family)
        ts = t / 1e3
        slots = max(hosts * trips, 1)
        for i, name in enumerate(KS_NAMES):
            if fires[i] == 0 and lanes[i] == 0:
                continue
            args = {"lanes": lanes[i]}
            if i != KS_EXCHANGE:
                # exchange is a per-round stage (lanes = packets
                # staged): the lane-occupancy law does not apply.
                args["occupancy-permille"] = (lanes[i] * 1000) // slots
            ev.append({"ph": "C", "pid": PID_KERN, "tid": 0,
                       "ts": ts, "name": f"{fam} {name}",
                       "args": args})
    return ev


def syscall_events(sc_bytes: bytes, top_n: int = DEFAULT_TOP_N) -> list:
    """Per-process syscall slices + counter tracks from
    syscalls-sim.bin (the syscall observatory's record channel).

    One thread track per (host, pid) — capped to the top_n processes
    by record count (ties broken by key, so the selection is
    deterministic — same precedent as the netstat counter tracks),
    tids assigned in sorted key order.  Each track carries an "X"
    slice per dispatch record (sim µs, duration = the record's
    entry->exit span) and a cumulative per-process syscall counter
    ("C" events; shim-handled batches bump it by their drained
    count)."""
    from shadow_tpu.host.syscalls_native import syscall_name
    from shadow_tpu.trace.events import (SC_NAMES, SC_SHIM,
                                         iter_sc_records)

    by_proc: dict = {}
    for rec in iter_sc_records(sc_bytes):
        by_proc.setdefault((rec[2], rec[3]), []).append(rec)
    ev: list = []
    if not by_proc:
        return ev
    keep = sorted(sorted(by_proc,
                         key=lambda k: (-len(by_proc[k]), k))[:top_n])
    ev.append(_meta(PID_SYSCALL, 0, "process_name",
                    f"syscall observatory (top {len(keep)} of "
                    f"{len(by_proc)} processes)"))
    for tid, key in enumerate(keep, start=1):
        host, pid = key
        ev.append(_meta(PID_SYSCALL, tid, "thread_name",
                        f"h{host} pid{pid}"))
        count = 0
        for (t0, t1, _h, _p, rtid, sysno, _rc, disp, aux) in \
                by_proc[key]:
            count += aux if disp == SC_SHIM else 1
            if sysno >= 0:
                ev.append({"ph": "X", "pid": PID_SYSCALL, "tid": tid,
                           "ts": t0 / 1e3,
                           "dur": max((t1 - t0) / 1e3, 0.001),
                           "name": syscall_name(sysno),
                           "args": {"disposition": SC_NAMES[disp],
                                    "tid": rtid}})
            ev.append({"ph": "C", "pid": PID_SYSCALL, "tid": tid,
                       "ts": t1 / 1e3,
                       "name": f"h{host} pid{pid} syscalls",
                       "args": {"count": count}})
    return ev


def chrome_trace(sim_bytes: bytes, wall: dict | None = None,
                 tel_bytes: bytes = b"", sc_bytes: bytes = b"",
                 fab_bytes: bytes = b"",
                 top_n: int = DEFAULT_TOP_N,
                 ks_bytes: bytes = b"") -> dict:
    """Build the trace-event JSON object from the raw channel data.

    `sim_bytes` is flight-sim.bin's content; `wall` is the parsed
    flight-wall.json dict (or None); `tel_bytes` is
    telemetry-sim.bin's content (per-connection counter tracks);
    `sc_bytes` is syscalls-sim.bin's content (per-process syscall
    slices + counter tracks); `fab_bytes` is fabric-sim.bin's FB
    section (per-link counter tracks); `ks_bytes` is kernel-sim.bin's
    content (per-stage lane-occupancy counter tracks).  `top_n` caps
    every per-entity track family (the experimental.chrome_top_n
    knob)."""
    ev: list[dict] = [
        _meta(PID_SIM, 0, "process_name", "sim-time (simulated µs)"),
        _meta(PID_SIM, 1, "thread_name", "rounds & spans"),
    ]
    open_spans = 0
    round_idx = 0
    span_rounds_seen = 0  # FR_ROUND records inside the open span
    for t, kind, a, b, c in iter_records(sim_bytes):
        us = t / 1e3
        if kind == FR_SPAN_START:
            fam = FAM_NAMES[a] if 0 <= a < len(FAM_NAMES) else str(a)
            ev.append({"ph": "B", "pid": PID_SIM, "tid": 1, "ts": us,
                       "name": f"span:{fam}",
                       "args": {"round": c}})
            open_spans += 1
            span_rounds_seen = 0
        elif kind == FR_SPAN_COMMIT:
            if open_spans:
                ev.append({"ph": "E", "pid": PID_SIM, "tid": 1,
                           "ts": us,
                           "args": {"rounds": c, "packets": b}})
                open_spans -= 1
            # Engine spans already advanced round_idx via their
            # drained per-round records; device spans carry none, so
            # only the uncovered remainder advances the counter here.
            round_idx += max(c - span_rounds_seen, 0)
            span_rounds_seen = 0
        elif kind == FR_SPAN_ABORT:
            fam = FAM_NAMES[a] if 0 <= a < len(FAM_NAMES) else str(a)
            ev.append({"ph": "i", "pid": PID_SIM, "tid": 1, "ts": us,
                       "s": "t", "name": f"abort:{fam}",
                       "args": {"code": b}})
        elif kind == FR_ROUND:
            reason = EL_NAMES[a] if 0 <= a < len(EL_NAMES) else str(a)
            start_us = c / 1e3
            ev.append({"ph": "X", "pid": PID_SIM, "tid": 1,
                       "ts": start_us,
                       "dur": max(us - start_us, 0.001),
                       "name": f"round {round_idx}",
                       "args": {"reason": reason, "packets": b}})
            round_idx += 1
            if open_spans:
                span_rounds_seen += 1
    # Unbalanced opens (a trace cut mid-span) get synthetic closes so
    # viewers never see a dangling "B".
    last_us = ev[-1].get("ts", 0) if ev else 0
    for _ in range(open_spans):
        ev.append({"ph": "E", "pid": PID_SIM, "tid": 1, "ts": last_us})

    if tel_bytes:
        ev.extend(netstat_events(tel_bytes, top_n))

    if sc_bytes:
        ev.extend(syscall_events(sc_bytes, top_n))

    if fab_bytes:
        ev.extend(fabric_events(fab_bytes, top_n))

    if ks_bytes:
        ev.extend(kern_events(ks_bytes))

    if wall and wall.get("events"):
        ev.append(_meta(PID_WALL, 0, "process_name",
                        "wall-time (profiling µs)"))
        ev.append(_meta(PID_WALL, 1, "thread_name", "phases"))
        for t0, dur, name in wall["events"]:
            ev.append({"ph": "X", "pid": PID_WALL, "tid": 1,
                       "ts": t0 / 1e3, "dur": max(dur / 1e3, 0.001),
                       "name": name})
    return {"traceEvents": ev, "displayTimeUnit": "ms"}
