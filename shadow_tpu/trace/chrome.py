"""Chrome trace-event JSON export (loads in Perfetto / about:tracing).

Sim-time channel: rounds and spans as nested slices on one track
(spans open with "B"/close with "E"; each round is a complete "X"
slice inside its span; device-span aborts are instants).  Timestamps
are simulated microseconds — the timeline IS the simulation.

Wall-time channel: per-phase slices on a second "process" with real
(relative) microseconds — where a dispatch's wall time went.
"""

from __future__ import annotations

from shadow_tpu.trace.events import (EL_NAMES, FAM_NAMES, FR_ROUND,
                                     FR_SPAN_ABORT, FR_SPAN_COMMIT,
                                     FR_SPAN_START, iter_records)

PID_SIM = 1
PID_WALL = 2


def _meta(pid: int, tid: int, what: str, name: str) -> dict:
    return {"ph": "M", "pid": pid, "tid": tid, "name": what,
            "args": {"name": name}}


def chrome_trace(sim_bytes: bytes, wall: dict | None = None) -> dict:
    """Build the trace-event JSON object from the raw channel data.

    `sim_bytes` is flight-sim.bin's content; `wall` is the parsed
    flight-wall.json dict (or None)."""
    ev: list[dict] = [
        _meta(PID_SIM, 0, "process_name", "sim-time (simulated µs)"),
        _meta(PID_SIM, 1, "thread_name", "rounds & spans"),
    ]
    open_spans = 0
    round_idx = 0
    span_rounds_seen = 0  # FR_ROUND records inside the open span
    for t, kind, a, b, c in iter_records(sim_bytes):
        us = t / 1e3
        if kind == FR_SPAN_START:
            fam = FAM_NAMES[a] if 0 <= a < len(FAM_NAMES) else str(a)
            ev.append({"ph": "B", "pid": PID_SIM, "tid": 1, "ts": us,
                       "name": f"span:{fam}",
                       "args": {"round": c}})
            open_spans += 1
            span_rounds_seen = 0
        elif kind == FR_SPAN_COMMIT:
            if open_spans:
                ev.append({"ph": "E", "pid": PID_SIM, "tid": 1,
                           "ts": us,
                           "args": {"rounds": c, "packets": b}})
                open_spans -= 1
            # Engine spans already advanced round_idx via their
            # drained per-round records; device spans carry none, so
            # only the uncovered remainder advances the counter here.
            round_idx += max(c - span_rounds_seen, 0)
            span_rounds_seen = 0
        elif kind == FR_SPAN_ABORT:
            fam = FAM_NAMES[a] if 0 <= a < len(FAM_NAMES) else str(a)
            ev.append({"ph": "i", "pid": PID_SIM, "tid": 1, "ts": us,
                       "s": "t", "name": f"abort:{fam}",
                       "args": {"code": b}})
        elif kind == FR_ROUND:
            reason = EL_NAMES[a] if 0 <= a < len(EL_NAMES) else str(a)
            start_us = c / 1e3
            ev.append({"ph": "X", "pid": PID_SIM, "tid": 1,
                       "ts": start_us,
                       "dur": max(us - start_us, 0.001),
                       "name": f"round {round_idx}",
                       "args": {"reason": reason, "packets": b}})
            round_idx += 1
            if open_spans:
                span_rounds_seen += 1
    # Unbalanced opens (a trace cut mid-span) get synthetic closes so
    # viewers never see a dangling "B".
    last_us = ev[-1].get("ts", 0) if ev else 0
    for _ in range(open_spans):
        ev.append({"ph": "E", "pid": PID_SIM, "tid": 1, "ts": last_us})

    if wall and wall.get("events"):
        ev.append(_meta(PID_WALL, 0, "process_name",
                        "wall-time (profiling µs)"))
        ev.append(_meta(PID_WALL, 1, "thread_name", "phases"))
        for t0, dur, name in wall["events"]:
            ev.append({"ph": "X", "pid": PID_WALL, "tid": 1,
                       "ts": t0 / 1e3, "dur": max(dur / 1e3, 0.001),
                       "name": name})
    return {"traceEvents": ev, "displayTimeUnit": "ms"}
