"""The flight recorder: one sim-time channel + one wall-time channel.

The two channels never mix.  `SimChannel` is stamped exclusively with
simulated nanoseconds and round indices — analysis pass 3 forbids any
wall-clock read inside the class, with no pragma escape — so the
written `flight-sim.bin` is byte-identical across runs whenever the
recorded DECISIONS are deterministic (serial schedulers, pinned
device-span routing); under wall-clock-driven auto routing it
faithfully logs the routes taken while simulation state stays
byte-identical regardless.  `WallChannel` is the profiling side:
per-phase wall aggregates plus a bounded per-instance event list for
the Chrome trace export; the determinism gate strips its artifact.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from shadow_tpu.trace.events import FR_ROUND, REC, REC_DTYPE


class SimChannel:
    """Deterministic event stream (simulated time only).

    Records are appended pre-packed (events.REC) so the in-memory
    representation IS the artifact: `to_bytes()` is a join, and two
    identical simulations produce identical byte streams.  A capacity
    cap drops (and counts) the tail instead of growing without bound —
    the drop point is a function of the event sequence alone, so a
    capped stream is still deterministic.
    """

    def __init__(self, cap: int = 1 << 22):
        self._chunks: list[bytes] = []
        self._cap = cap
        self.records = 0
        self.dropped = 0

    def event(self, t: int, kind: int, a: int, b: int, c: int) -> None:
        if self.records >= self._cap:
            self.dropped += 1
            return
        self._chunks.append(REC.pack(int(t), kind, int(a), int(b),
                                     int(c)))
        self.records += 1

    def extend_engine(self, buf: bytes, engine_dropped: int,
                      reason: int) -> None:
        """Append a drained engine flight-ring buffer (fixed records,
        layout twinned with FlightRec in netplane.cpp), re-stamping
        the manager's refined eligibility reason onto the engine's
        generic per-round records."""
        if not buf:
            self.dropped += int(engine_dropped)
            return
        arr = np.frombuffer(bytearray(buf), dtype=REC_DTYPE)
        rounds = arr["kind"] == FR_ROUND
        arr["a"][rounds] = reason
        n = len(arr)
        if self.records + n > self._cap:
            keep = max(self._cap - self.records, 0)
            self.dropped += n - keep
            arr = arr[:keep]
            n = keep
        if n:
            self._chunks.append(arr.tobytes())
            self.records += n
        self.dropped += int(engine_dropped)

    def to_bytes(self) -> bytes:
        return b"".join(self._chunks)


def grid_sampled(start: int, window_end: int,
                 interval_ns: int) -> bool:
    """The stateless grid-crossing sampling rule every
    interval-sampled channel shares: a round [start, window_end)
    samples iff it crosses a grid boundary.  C++ twins:
    Engine::tel_sample_round / fab_sample_round; device twins: the
    round_body guards in ops/tcp_span.py and ops/phold_span.py.
    Both boundaries are path-independent, so the sampled-round set —
    and with it each channel — is path-independent by construction."""
    iv = interval_ns if interval_ns > 0 else 1
    return start // iv != window_end // iv


class FixedRecordChannel:
    """Shared machinery of the interval-sampled fixed-record sim-time
    channels (sim-netstat's NetstatChannel, the fabric observatory's
    FabricChannel): records append pre-packed so the in-memory
    representation IS the artifact, and a capacity cap drops (and
    counts) the tail at a point that is a function of the record
    sequence alone — a capped stream is still deterministic.
    Subclasses pin REC_SIZE (the fixed record width) and FILE, and
    add their own record()/sample walkers.  Like SimChannel, no
    subclass may read wall clocks (analysis pass 3's `sim-channel`
    rule, no pragma escape)."""

    REC_SIZE = 1  # subclass: bytes per fixed record
    FILE = ""

    def __init__(self, interval_ns: int = 0, cap: int = 1 << 22):
        self.interval_ns = int(interval_ns)
        self._chunks: list[bytes] = []
        self._cap = cap
        self.records = 0
        self.dropped = 0

    def sampled(self, start: int, window_end: int) -> bool:
        return grid_sampled(start, window_end, self.interval_ns)

    def extend(self, buf: bytes, producer_dropped: int = 0) -> None:
        """Append pre-packed records (an engine ring drain or a
        device-span driver's batch)."""
        n = len(buf) // self.REC_SIZE
        if self.records + n > self._cap:
            keep = max(self._cap - self.records, 0)
            self.dropped += n - keep
            buf = buf[:keep * self.REC_SIZE]
            n = keep
        if n:
            self._chunks.append(bytes(buf))
            self.records += n
        self.dropped += int(producer_dropped)

    def to_bytes(self) -> bytes:
        return b"".join(self._chunks)


class WallChannel:
    """Wall-clock phase profiling: per-phase aggregate totals plus a
    bounded (t0, duration, name) event list for slice rendering."""

    def __init__(self, max_events: int = 200_000):
        self.phases: dict[str, list] = {}  # name -> [total_ns, count]
        self.events: list = []             # (t0_rel_ns, dur_ns, name)
        self.dropped_events = 0
        self._max_events = max_events
        self._epoch = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] wall-time channel epoch

    def now(self) -> int:
        return time.perf_counter_ns()  # shadow-lint: allow[wall-clock] wall-time channel is the profiling side

    def add(self, name: str, dur_ns: int, t0_ns: int | None = None
            ) -> None:
        slot = self.phases.get(name)
        if slot is None:
            slot = self.phases[name] = [0, 0]
        slot[0] += int(dur_ns)
        slot[1] += 1
        if t0_ns is not None:
            if len(self.events) < self._max_events:
                self.events.append((int(t0_ns) - self._epoch,
                                    int(dur_ns), name))
            else:
                self.dropped_events += 1

    def totals(self) -> dict:
        """name -> total seconds (rounded), for one-line summaries."""
        return {name: round(ns / 1e9, 3)
                for name, (ns, _cnt) in sorted(self.phases.items())}

    def as_dict(self) -> dict:
        return {
            "phases": {name: {"ns": ns, "count": cnt}
                       for name, (ns, cnt) in sorted(
                           self.phases.items())},
            "events": [list(e) for e in self.events],
            "dropped_events": self.dropped_events,
        }


class FlightRecorder:
    """Bundle of the two channels plus the artifact writer.

    `sim=False` builds a wall-only recorder (phase profiling without
    the event stream) — what bench.py uses so recorded rungs carry the
    per-phase breakdown without paying for event capture."""

    SIM_FILE = "flight-sim.bin"
    WALL_FILE = "flight-wall.json"

    def __init__(self, sim: bool = True, sim_cap: int = 1 << 22):
        self.sim = SimChannel(sim_cap) if sim else None
        self.wall = WallChannel()

    def write(self, data_dir: str) -> None:
        if self.sim is not None:
            with open(os.path.join(data_dir, self.SIM_FILE), "wb") as f:
                f.write(self.sim.to_bytes())
        with open(os.path.join(data_dir, self.WALL_FILE), "w") as f:
            json.dump(self.wall.as_dict(), f, indent=1)
