"""Syscall observatory: per-syscall telemetry for managed processes.

Third sim-time channel next to the flight recorder and sim-netstat
(docs/OBSERVABILITY.md "syscall observatory"), plus the wall-time side
that answers ROADMAP item 2's question — what does one syscall round
trip (shim futex wake -> Python service -> resume) actually cost, and
where does the wall go?

**Sim-time channel** (`SyscallChannel`, `syscalls-sim.bin`): fixed
40-byte records (trace/events.py SC_REC, size twinned with shim.c's
SC_REC_BYTES) — one per managed-process syscall DISPATCH, stamped with
sim entry/exit time, host/pid/tid, the raw syscall number, a result
class and exactly one SC_* disposition.  Records buffer PER HOST: a
host is single-threaded by construction, so its record order is its
(scheduler-independent, deterministic) event execution order, and the
written artifact is the host-id-ordered concatenation — byte-identical
across runs AND across serial / thread_per_core / tpu schedulers.
Like the other sim channels this code must never read wall clocks
(analysis pass 3's sim-channel rule covers SyscallChannel and
HostSyscallLog with no pragma escape).

**Wall-time side** (`HostScWall` per host, merged by
`SyscallObservatory`): every round trip's wall cost attributed to
ipc-wait (blocked in the futex channel recv) vs dispatch (the
simulated kernel) vs resume (strace/signals/response send), plus
per-syscall-family totals and log-scale histograms for p50/p99.  The
memory-manager copy component is reported from the MemoryManager
aggregate counters (a subset of dispatch).  Everything lands in
`metrics.wall.ipc.*`; the per-round managed-host delta feeds the
flight recorder's `syscall-service` phase.

The disposition COUNTERS (Host.sc_disp) are always on — integer adds,
like drop attribution — and surface in `metrics.sim.syscalls.*`; this
module's channels are the opt-in part
(`experimental.syscall_observatory: off | wall | on`).
"""

from __future__ import annotations

import os
import time

from shadow_tpu.trace.events import SC_REC, SC_REC_BYTES

# Log-scale wall histogram: bucket i covers [256 << i, 256 << (i+1))
# ns; bucket 0 also absorbs everything below 256 ns and the top bucket
# everything above ~34 s.  28 integers per family — cheap enough to
# keep per syscall name.
N_BUCKETS = 28
_BASE_SHIFT = 8  # 256 ns


def bucket_of(ns: int) -> int:
    b = max(int(ns), 1).bit_length() - 1 - _BASE_SHIFT
    if b < 0:
        return 0
    return b if b < N_BUCKETS else N_BUCKETS - 1


def percentile_ns(buckets, q: float) -> int:
    """Approximate q-quantile (0..1) from a log-bucket histogram: the
    geometric midpoint of the bucket holding the q-th sample."""
    total = sum(buckets)
    if not total:
        return 0
    want = q * total
    seen = 0
    for i, n in enumerate(buckets):
        seen += n
        if seen >= want:
            lo = 1 << (_BASE_SHIFT + i)
            return int(lo * 1.5)
    return 1 << (_BASE_SHIFT + N_BUCKETS)


class HostSyscallLog:
    """One host's slice of the sim-time syscall channel.  Appended
    only by the thread executing this host's events; capacity-capped
    at a point that is a function of the record sequence alone, so a
    capped stream is still deterministic."""

    __slots__ = ("chunks", "records", "dropped", "_cap")

    def __init__(self, cap: int):
        self.chunks: list[bytes] = []
        self.records = 0
        self.dropped = 0
        self._cap = cap

    def rec(self, t_enter: int, t_exit: int, host: int, pid: int,
            tid: int, sysno: int, rclass: int, disp: int,
            aux: int = 0) -> None:
        if self.records >= self._cap:
            self.dropped += 1
            return
        self.chunks.append(SC_REC.pack(
            int(t_enter), int(t_exit), host, pid, tid, sysno,
            rclass, disp, aux))
        self.records += 1


class SyscallChannel:
    """Deterministic per-syscall record stream (simulated time only).

    Owns the per-host logs; `collect()` concatenates them in host-id
    order — the canonical artifact order (per-host order is event
    execution order, which the cross-scheduler parity contract already
    pins)."""

    FILE = "syscalls-sim.bin"

    def __init__(self, cap_per_host: int = 1 << 20):
        self._cap = cap_per_host
        self._logs: list[HostSyscallLog] = []

    def host_log(self) -> HostSyscallLog:
        log = HostSyscallLog(self._cap)
        self._logs.append(log)
        return log

    @property
    def records(self) -> int:
        return sum(log.records for log in self._logs)

    @property
    def dropped(self) -> int:
        return sum(log.dropped for log in self._logs)

    def to_bytes(self) -> bytes:
        # _logs is appended in host-build order == host-id order.
        return b"".join(b"".join(log.chunks) for log in self._logs)

    def write(self, data_dir: str) -> None:
        with open(os.path.join(data_dir, self.FILE), "wb") as f:
            f.write(self.to_bytes())


class HostScWall:
    """Per-host wall-clock profile of the syscall seam.  Host-serial
    (only the thread executing the host's events touches it); the
    observatory merges across hosts at report time."""

    __slots__ = ("families", "wait_ns", "dispatch_ns", "resume_ns",
                 "trips", "app_dispatches", "app_dispatch_ns",
                 "_active", "_registered")

    def __init__(self, active_set: set):
        self.families: dict[str, list] = {}  # name -> [count, ns, buckets]
        self.wait_ns = 0
        self.dispatch_ns = 0
        self.resume_ns = 0
        self.trips = 0
        # Internal-app dispatches (no IPC legs) accounted apart so
        # `ipc.round_trips`/`wait_ns` measure ONLY managed round trips
        # — the number ROADMAP item 2's batching must amortize.
        self.app_dispatches = 0
        self.app_dispatch_ns = 0
        self._active = active_set
        self._registered = False

    @staticmethod
    def now() -> int:
        return time.perf_counter_ns()  # shadow-lint: allow[wall-clock] syscall-observatory wall side

    def trip(self, name: str, wait_ns: int, dispatch_ns: int,
             resume_ns: int, ipc: bool = True) -> None:
        if not self._registered:
            self._registered = True
            self._active.add(self)  # GIL-atomic; iterated between rounds
        if ipc:
            self.wait_ns += wait_ns
            self.dispatch_ns += dispatch_ns
            self.resume_ns += resume_ns
            self.trips += 1
        else:
            self.app_dispatches += 1
            self.app_dispatch_ns += dispatch_ns
        fam = self.families.get(name)
        if fam is None:
            fam = self.families[name] = [0, 0, [0] * N_BUCKETS]
        total = wait_ns + dispatch_ns + resume_ns
        fam[0] += 1
        fam[1] += total
        fam[2][bucket_of(total)] += 1


class SyscallObservatory:
    """Bundle: mode, the opt-in channels, per-host wall profiles, and
    the metrics/artifact writers the manager calls."""

    def __init__(self, mode: str, hosts, death_poll_ns: int = 0):
        assert mode in ("wall", "on")
        self.mode = mode
        # Effective waitpid safety-net poll slice (the
        # experimental.managed_death_poll knob) — reported in
        # metrics.wall.ipc so the configured value is visible next to
        # the waits it bounds.
        self.death_poll_ns = death_poll_ns
        self.channel = SyscallChannel() if mode == "on" else None
        self.active: set[HostScWall] = set()
        for h in hosts:
            h.sc_wall = HostScWall(self.active)
            if self.channel is not None:
                h.sc_log = self.channel.host_log()
        # MemoryManager counters are process-global and cumulative
        # (prior sims in the same interpreter included): snapshot the
        # baseline so this run's copy cost reports as a delta.
        self._mem_base = self._mem_totals()
        self._round_snap = 0

    @staticmethod
    def _mem_totals() -> tuple:
        from shadow_tpu.host.managed import MemoryManager as MM
        return (MM.total_read_ns, MM.total_write_ns,
                MM.total_read_bytes, MM.total_write_bytes, MM.total_calls)

    def memcopy_delta(self) -> dict:
        now = self._mem_totals()
        base = self._mem_base
        return {"read_ns": now[0] - base[0], "write_ns": now[1] - base[1],
                "read_bytes": now[2] - base[2],
                "write_bytes": now[3] - base[3],
                "calls": now[4] - base[4]}

    def round_phase_delta(self) -> int:
        """Wall ns spent in the syscall seam since the last call —
        the flight recorder's per-round `syscall-service` phase.
        Called between rounds (host threads quiesced)."""
        total = 0
        for w in self.active:
            total += (w.wait_ns + w.dispatch_ns + w.resume_ns
                      + w.app_dispatch_ns)
        delta = total - self._round_snap
        self._round_snap = total
        return delta

    def merged_families(self) -> dict:
        """name -> [count, total_ns, buckets] merged across hosts."""
        out: dict[str, list] = {}
        for w in self.active:
            for name, (cnt, ns, buckets) in w.families.items():
                slot = out.get(name)
                if slot is None:
                    out[name] = [cnt, ns, list(buckets)]
                else:
                    slot[0] += cnt
                    slot[1] += ns
                    for i, n in enumerate(buckets):
                        slot[2][i] += n
        return out

    def wall_summary(self) -> dict:
        """The `metrics.wall.ipc` block: phase totals, memcopy delta,
        and per-family count/total/p50/p99."""
        wait = dispatch = resume = trips = 0
        app_n = app_ns = 0
        for w in self.active:
            wait += w.wait_ns
            dispatch += w.dispatch_ns
            resume += w.resume_ns
            trips += w.trips
            app_n += w.app_dispatches
            app_ns += w.app_dispatch_ns
        fams = {}
        for name, (cnt, ns, buckets) in sorted(self.merged_families()
                                               .items()):
            fams[name] = {"count": cnt, "total_ns": ns,
                          "p50_ns": percentile_ns(buckets, 0.50),
                          "p99_ns": percentile_ns(buckets, 0.99)}
        return {"round_trips": trips, "wait_ns": wait,
                "dispatch_ns": dispatch, "resume_ns": resume,
                "app_dispatches": app_n, "app_dispatch_ns": app_ns,
                "death_poll_ns": self.death_poll_ns,
                "memcopy": self.memcopy_delta(), "families": fams}

    def ingest_metrics(self, reg) -> None:
        reg.ingest("ipc", self.wall_summary(), channel="wall")
        if self.channel is not None:
            reg.gauge("syscalls.records", channel="sim").set(
                self.channel.records)
            reg.gauge("syscalls.dropped", channel="sim").set(
                self.channel.dropped)

    def write(self, data_dir: str) -> None:
        if self.channel is not None:
            self.channel.write(data_dir)
