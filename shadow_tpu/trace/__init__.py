"""Deterministic flight recorder & performance observatory.

Two strictly separated channels (docs/OBSERVABILITY.md):

- **sim-time channel** (`recorder.SimChannel`): fixed-size binary
  records stamped with simulated nanoseconds and round index — span
  start/abort/commit, per-round scheduler decisions with their
  device-eligibility reason code, packet-plane milestones.  The
  channel is byte-identical across runs of the same config whenever
  span/dispatch routing is deterministic (serial schedulers,
  `tpu_device_spans: off`/`force`; the determinism gate diffs the
  written `flight-sim.bin` artifact on its serial leg).  Under
  wall-clock-driven AUTO routing the channel faithfully records the
  routes actually taken — simulation STATE stays byte-identical
  either way; only the decision log may differ.  The channel itself
  MUST NOT read wall clocks: analysis pass 3 fails any wall-clock
  read inside `SimChannel`, pragma or not.

- **wall-time channel** (`recorder.WallChannel`): per-phase wall
  timings (host loop, SoA export, dtype conversion, XLA compile vs
  execute, import, barrier wait) and per-dispatch telemetry.  Pure
  profiling: the determinism gate strips it.

The record layout and the event/reason enums are twinned with
`native/netplane.cpp` (the engine's fixed-record ring buffer, drained
per round through the span-export path) and registered in analysis
pass 1 — enum drift fails `scripts/lint` before it can corrupt a
trace.

`metrics.MetricsRegistry` is the single sink for counters/gauges/
histograms (it replaces the hand-built `sim-stats.json` dispatch
block), and `audit.EligibilityAudit` assigns every conservative round
exactly one reason code so "why is this round not on the device?" is
a one-command report: `python -m shadow_tpu.tools.trace`.
"""

from __future__ import annotations

from shadow_tpu.trace.audit import EligibilityAudit
from shadow_tpu.trace.metrics import MetricsRegistry
from shadow_tpu.trace.netstat import NetstatChannel
from shadow_tpu.trace.recorder import FlightRecorder

__all__ = ["EligibilityAudit", "FlightRecorder", "MetricsRegistry",
           "NetstatChannel"]
