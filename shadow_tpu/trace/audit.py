"""Device-eligibility audit: one reason code per conservative round.

The round loop (core/manager.py) calls `add(code, rounds)` at every
accounting point — device span commit, C++ span commit, and each
per-round iteration — with exactly one EL_* code, so the counts always
sum to the simulation's total round count.  That invariant turns
coverage questions ("0/1622 rounds on device — why?") into a
one-command attribution report:

    python -m shadow_tpu.tools.trace <data-dir>
"""

from __future__ import annotations

from shadow_tpu.trace.events import (EL_DEVICE_SHARDED, EL_DEVICE_SPAN,
                                     EL_ENGINE_EXCHANGE, EL_ENGINE_SPAN,
                                     EL_ENGINE_UNSHARDED, EL_N, EL_NAMES,
                                     EL_SVC_QUIESCENT)


class EligibilityAudit:
    def __init__(self):
        self.counts = [0] * EL_N

    def add(self, code: int, n: int = 1) -> None:
        self.counts[code] += n

    def total(self) -> int:
        return sum(self.counts)

    def as_dict(self) -> dict:
        """reason-name -> round count (nonzero codes only)."""
        return {EL_NAMES[i]: c for i, c in enumerate(self.counts) if c}

    def device_rounds(self) -> int:
        return (self.counts[EL_DEVICE_SPAN]
                + self.counts[EL_DEVICE_SHARDED])

    def span_rounds(self) -> int:
        return (self.device_rounds()
                + sum(self.counts[EL_ENGINE_SPAN:EL_ENGINE_SPAN + 8])
                + self.counts[EL_ENGINE_EXCHANGE]
                + self.counts[EL_ENGINE_UNSHARDED]
                + self.counts[EL_SVC_QUIESCENT])


def render_report(counts: dict, total_rounds: int) -> str:
    """The attribution table (CLI + `./setup trace` smoke target).

    `counts` is reason-name -> rounds (the `metrics.wall.eligibility`
    block of sim-stats.json, or `EligibilityAudit.as_dict()`)."""
    lines = ["device-eligibility audit (one reason per round):"]
    accounted = 0
    width = max([len(k) for k in counts] + [12])
    for name, n in sorted(counts.items(), key=lambda kv: -kv[1]):
        pct = 100.0 * n / total_rounds if total_rounds else 0.0
        lines.append(f"  {name:<{width}}  {n:>10}  {pct:5.1f}%")
        accounted += n
    if total_rounds and accounted == total_rounds:
        lines.append(f"  {'total':<{width}}  {accounted:>10}  100.0%  "
                     f"(all rounds accounted)")
    else:
        lines.append(f"  total {accounted} != rounds {total_rounds} — "
                     f"ACCOUNTING GAP")
    return "\n".join(lines)
