"""Sim-netstat: the deterministic per-connection TCP telemetry channel.

A second sim-time channel next to the flight recorder's event stream
(docs/OBSERVABILITY.md "sim-netstat"): fixed 96-byte records
(trace/events.py TEL_REC, twinned with netplane.cpp's TelRec) sampling
every live TCP connection's control state — cwnd, ssthresh, srtt, RTO
+ backoff, send/recv buffer occupancy, retransmit and SACK counts — at
conservative-round boundaries.  Records are keyed by simulated time
and connection identity only, so the written `telemetry-sim.bin` is
byte-diffed by the determinism gate exactly like `flight-sim.bin`,
and the three execution paths (Python object path, C++ engine,
device span) must produce identical streams for identical sims.

Sampling cadence is the STATELESS grid-crossing rule, identical on
all three paths: a round [start, window_end) emits samples iff
`start // interval != window_end // interval` (interval 0/1 = every
round).  Both boundaries are path-independent, so the sampled-round
set — and with it the channel — is path-independent by construction.

Within a sampled round, records are ordered by (host, local port,
peer port, peer IP); the engine ring, the device-span driver and the
object-path walker below all emit that order.  In mixed sims the
engine plane's records precede the object plane's (homogeneous runs —
what the parity gates compare — are globally host-sorted either way);
object-path hosts are not sampled inside C++ spans (they have no
events there, so their connection state is unchanged).

Like `SimChannel`, this class must never read wall clocks: analysis
pass 3's `sim-channel` rule covers it with no pragma escape.
"""

from __future__ import annotations

import os

from shadow_tpu.trace.events import TEL_REC, TEL_REC_BYTES
from shadow_tpu.trace.recorder import FixedRecordChannel, grid_sampled

# Connection states excluded from sampling (tcp/connection.py values;
# a CLOSED conn is dead, a LISTEN conn has no transfer state).
_CLOSED = 0
_LISTEN = 1

# The grid-crossing rule (kept importable here — this module anchors
# the twin documentation; trace/recorder.grid_sampled is the one
# implementation every channel shares).
sampled = grid_sampled


class NetstatChannel(FixedRecordChannel):
    """Deterministic per-connection sample stream (simulated time
    only; trace/recorder.FixedRecordChannel carries the shared
    cap/extend machinery)."""

    FILE = "telemetry-sim.bin"
    REC_SIZE = TEL_REC_BYTES

    def record(self, t: int, host: int, lport: int, rport: int,
               rip: int, conn) -> None:
        """One object-path connection sample (tcp/connection.py)."""
        if self.records >= self._cap:
            self.dropped += 1
            return
        self._chunks.append(TEL_REC.pack(
            int(t), host, lport, rport, rip, conn.state,
            conn.cong.cwnd, conn.cong.ssthresh, conn.srtt, conn.rto,
            conn._rto_backoff, conn.send_buf_len, conn.recv_buf_len,
            conn.retransmit_count, conn.sacked_skip_count,
            conn.ce_seen))
        self.records += 1

    def sample_object_hosts(self, hosts, t: int) -> None:
        """Sample every object-path host's live TCP connections.
        Hosts on the native plane are skipped — their connections
        live engine-side and the engine ring samples them."""
        for h in hosts:
            if h.plane is not None or not h.net_built():
                continue
            rows = []
            for s in iter_host_tcp_sockets(h):
                conn = s.conn
                if conn is None or conn.state in (_CLOSED, _LISTEN):
                    continue
                if s.local is None or s.peer is None:
                    continue
                rows.append((s.local[1], s.peer[1], s.peer[0], conn))
            rows.sort(key=lambda r: r[:3])
            for lport, rport, rip, conn in rows:
                self.record(t, h.id, lport, rport, rip, conn)

    def write(self, data_dir: str) -> None:
        with open(os.path.join(data_dir, self.FILE), "wb") as f:
            f.write(self.to_bytes())


def iter_records(buf: bytes):
    """Yield (t, host, lport, rport, rip, state, cwnd, ssthresh,
    srtt, rto, backoff, sndbuf, rcvbuf, rtx, sacks, marks) tuples."""
    for off in range(0, len(buf) - len(buf) % TEL_REC_BYTES,
                     TEL_REC_BYTES):
        yield TEL_REC.unpack_from(buf, off)


def iter_host_tcp_sockets(host):
    """Every TCP socket associated on a host, deduped across its
    interfaces (wildcard binds associate on both lo and eth0) — THE
    'live sockets of a host' walk shared by the telemetry sampler and
    the manager's stream-totals summary, so the two can never disagree
    about which sockets exist."""
    seen: dict = {}
    for iface in (host.lo, host.eth0):
        for s in iface.associated_sockets():
            if getattr(s, "conn", None) is not None \
                    or getattr(s, "listening", False):
                seen[id(s)] = s
    return seen.values()


def group_by_conn(tel_bytes: bytes) -> dict:
    """Telemetry records grouped by connection identity:
    (host, lport, rport, rip) -> [records in time order]."""
    by_conn: dict = {}
    for rec in iter_records(tel_bytes):
        by_conn.setdefault(rec[1:5], []).append(rec)
    return by_conn


def top_by_retransmits(by_conn: dict, n: int) -> list:
    """The top-n connection keys by FINAL retransmit count, ties
    broken by connection key — the one deterministic ranking the CLI
    report and the Chrome counter-track export both render."""
    return sorted(by_conn, key=lambda k: (-by_conn[k][-1][13], k))[:n]
