"""Device-kernel observatory: per-micro-op firing & lane-occupancy
telemetry for the span kernels.

The FIFTH sim-time channel next to the flight recorder, sim-netstat,
the syscall observatory and the fabric observatory
(docs/OBSERVABILITY.md "Device-kernel observatory").  One fixed
KS_REC record per COMMITTED device span (`kernel-sim.bin`): both span
families (ops/phold_span.py, ops/tcp_span.py) thread a per-stage
counter block — a fire count and an active-lane sum per fused
micro-op stage — through the `lax.while_loop` carry, and the driver
packs one record at span commit.  Aborted spans roll back and record
nothing, so the channel obeys an exact conservation law: per family,
the sum of `trips` over committed records equals the dispatch split's
`micro_iters` counter, and every micro-op stage's fire count is
bounded by its per-iteration pass count (at most 2 — the relay/drain
double pass) times the trips.  The per-round exchange stage is
bounded by `rounds` instead.

Records append in span-commit order — the manager's round loop is the
single producer — so under pinned device routing (the forced-device
differential gates, `tpu_device_spans: force`) the artifact is
byte-identical across runs; rounds served off the device leave no
records, so a run with no device spans writes an empty artifact on
every scheduler.  The determinism gate byte-diffs the file like every
other sim channel.

Occupancy is `lanes / (hosts x trips)` — the fraction of the kernel's
host-lane slots a stage actually used over the span — the number the
crossover attack (ROADMAP item 3) needs per stage: a stage with 1%
occupancy burns 99% of its vectorized width on masked-out lanes.

Like `SimChannel`, this class must never read wall clocks: analysis
pass 3's `sim-channel` rule covers it with no pragma escape.
"""

from __future__ import annotations

import os

from shadow_tpu.trace.events import (FAM_NAMES, FAM_PHOLD, FAM_TCP,
                                     KS_EXCHANGE, KS_N, KS_NAMES,
                                     KS_REC, KS_REC_BYTES,
                                     iter_ks_records)
from shadow_tpu.trace.recorder import FixedRecordChannel

# Max per-iteration passes any micro-op stage takes in the fused
# dispatch (the relay/reassembly double pass); the fires bound the
# conservation check enforces per record.
STAGE_MAX_PASSES = 2

# Occupancy threshold (permille) below which a stage reads as
# "mostly masked-out lanes" — the one value `trace kern`, the
# `trace explain` hint and the tests share.
LOW_OCCUPANCY_PERMILLE = 50

# Span family -> the runner key the dispatch split / fn_cache blocks
# use (derived from the FAM_* codes so a new family cannot drift the
# renderers silently; `family_label` is the human name).
DISPATCH_KEYS = {FAM_PHOLD: "phold", FAM_TCP: "tcp"}


def family_label(family: int) -> str:
    return FAM_NAMES[family] if 0 <= family < len(FAM_NAMES) \
        else str(family)


class KernChannel(FixedRecordChannel):
    """Deterministic per-span stage-counter stream (simulated time
    only; trace/recorder.FixedRecordChannel carries the shared
    cap/extend machinery)."""

    FILE = "kernel-sim.bin"
    REC_SIZE = KS_REC_BYTES

    def record_span(self, t: int, family: int, hosts: int,
                    rounds: int, trips: int, fires, lanes) -> None:
        """One committed span's counter block (fires/lanes are KS_N
        int sequences straight from the kernel output arrays)."""
        if self.records >= self._cap:
            self.dropped += 1
            return
        self._chunks.append(KS_REC.pack(
            int(t), int(family), int(hosts), int(rounds), int(trips),
            *(int(x) for x in fires), *(int(x) for x in lanes)))
        self.records += 1

    def write(self, data_dir: str) -> None:
        with open(os.path.join(data_dir, self.FILE), "wb") as f:
            f.write(self.to_bytes())


# ---------------------------------------------------------------------
# Report helpers (tools/trace `kern`, the Chrome export, bench's
# crossover ladder and the tests share these so every surface renders
# — and gates — the same numbers).
# ---------------------------------------------------------------------

def family_totals(ks_bytes: bytes) -> dict:
    """Aggregate the record stream per span family: {family: {"spans",
    "rounds", "trips", "hosts", "fires"[KS_N], "lanes"[KS_N]}}.
    `hosts` is the kernel's lane width (constant per family — one
    runner per Manager)."""
    out: dict = {}
    for t, family, hosts, rounds, trips, fires, lanes in \
            iter_ks_records(ks_bytes):
        ent = out.setdefault(family, {
            "spans": 0, "rounds": 0, "trips": 0, "hosts": hosts,
            "fires": [0] * KS_N, "lanes": [0] * KS_N})
        ent["spans"] += 1
        ent["rounds"] += rounds
        ent["trips"] += trips
        ent["hosts"] = max(ent["hosts"], hosts)
        for i in range(KS_N):
            ent["fires"][i] += fires[i]
            ent["lanes"][i] += lanes[i]
    return out


def occupancy_permille(ent: dict, stage: int) -> int:
    """A stage's lane occupancy in permille: active-lane-iterations
    over the total lane slots (hosts x trips) the span loop offered.
    Integer arithmetic — deterministic on every surface.  Returns -1
    for the exchange stage: it is a per-ROUND hop whose lanes count
    packets staged, not lane slots — running it through the micro-op
    occupancy law would read as false lane waste (every renderer and
    the low-occupancy hint skip negatives)."""
    if stage == KS_EXCHANGE:
        return -1
    slots = ent["hosts"] * ent["trips"]
    if slots <= 0:
        return 0
    return (ent["lanes"][stage] * 1000) // slots


def low_occupancy_stages(ent: dict) -> list:
    """[(stage name, occupancy permille)] for every MICRO-OP stage
    that fired but used under LOW_OCCUPANCY_PERMILLE of its lane
    slots — THE shared rule behind `trace kern`'s verdict line and
    `trace explain`'s remediation hint."""
    out = []
    for i in range(KS_N):
        occ = occupancy_permille(ent, i)
        if ent["fires"][i] > 0 and 0 <= occ < LOW_OCCUPANCY_PERMILLE:
            out.append((KS_NAMES[i], occ))
    return out


def attribution(ent: dict, dispatch_wall_s: float) -> dict:
    """Per-stage cost attribution for one family: {stage_name:
    {"fires", "lanes", "occupancy_permille", "share_permille",
    "us_per_host_round"}}.  The share model attributes the measured
    device dispatch wall proportionally to each stage's active-lane
    sum (lane-iterations are the unit of vectorized work the kernels
    execute), so the per-stage `us_per_host_round` columns sum to the
    fitted device slope — the before/after per stage the overlap and
    lane-parallel kernel work (ROADMAP item 3) needs."""
    total_lanes = sum(ent["lanes"]) or 1
    hr = ent["hosts"] * ent["rounds"]
    slope_us = (dispatch_wall_s * 1e6 / hr) if hr > 0 else 0.0
    out: dict = {}
    for i in range(KS_N):
        if ent["fires"][i] == 0 and ent["lanes"][i] == 0:
            continue
        share = ent["lanes"][i] * 1000 // total_lanes
        out[KS_NAMES[i]] = {
            "fires": ent["fires"][i],
            "lanes": ent["lanes"][i],
            "occupancy_permille": occupancy_permille(ent, i),
            "share_permille": share,
            "us_per_host_round": round(
                slope_us * ent["lanes"][i] / total_lanes, 4),
        }
    return out


def family_warm_wall_s(dispatch: dict, family: int) -> float:
    """A family's WARM device dispatch wall from the dispatch split:
    total dispatch wall minus the fn-cache build wall (the first
    dispatch of each built kernel pays trace+XLA compile — attribution
    wants the steady state, not the compiler)."""
    key = DISPATCH_KEYS.get(family)
    if key is None:
        return 0.0
    block = dispatch.get(f"device_span_{key}") or {}
    wall = float(block.get("dispatch_wall_s", 0.0))
    build = float((dispatch.get("fn_cache") or {}).get(
        key, {}).get("build_wall_s", 0.0))
    return max(wall - build, 0.0)


def check_conservation(ks_bytes: bytes, dispatch: dict,
                       channel_dropped: int = 0) -> tuple[bool, list]:
    """The channel's conservation law against the dispatch split
    (metrics.wall.dispatch of sim-stats.json): per family, committed
    trips sum EXACTLY to the runner's micro_iters counter, and every
    record's per-stage fires stay inside the pass bound.  Returns
    (ok, [human-readable problem lines]); a capped channel (dropped
    records) skips the exact-sum leg honestly instead of reporting a
    false gap."""
    problems: list = []
    fam_key = {f: f"device_span_{k}" for f, k in DISPATCH_KEYS.items()}
    totals = family_totals(ks_bytes)
    for t, family, hosts, rounds, trips, fires, lanes in \
            iter_ks_records(ks_bytes):
        for i in range(KS_N):
            bound = rounds if i == KS_EXCHANGE \
                else STAGE_MAX_PASSES * trips
            if fires[i] > bound:
                problems.append(
                    f"span@{t}: stage {KS_NAMES[i]} fires {fires[i]} "
                    f"> bound {bound}")
            if lanes[i] > fires[i] * max(hosts, 1) \
                    and i != KS_EXCHANGE:
                problems.append(
                    f"span@{t}: stage {KS_NAMES[i]} lanes {lanes[i]} "
                    f"exceed fires x hosts")
    for family, ent in sorted(totals.items()):
        key = fam_key.get(family)
        block = dispatch.get(key) if key else None
        if block is None:
            problems.append(
                f"family {family_label(family)}: no {key} dispatch "
                f"block to reconcile against")
            continue
        micro = int(block.get("micro_iters", 0))
        if channel_dropped == 0 and ent["trips"] != micro:
            problems.append(
                f"family {family_label(family)}: committed trips "
                f"{ent['trips']} != dispatch micro_iters {micro}")
        if channel_dropped and ent["trips"] > micro:
            problems.append(
                f"family {family_label(family)}: committed trips "
                f"{ent['trips']} exceed dispatch micro_iters {micro} "
                f"(capped channel may undercount, never overcount)")
    return (not problems, problems)


def render_table(ks_bytes: bytes, dispatch: dict, out=None) -> None:
    """The per-stage table `tools/trace kern` prints: fires, lanes,
    occupancy and the attributed share of each family's measured
    device slope."""
    import sys
    if out is None:
        out = sys.stdout
    for family, ent in sorted(family_totals(ks_bytes).items()):
        wall_s = family_warm_wall_s(dispatch, family)
        hr = ent["hosts"] * ent["rounds"]
        slope = wall_s * 1e6 / hr if hr else 0.0
        print(f"family {family_label(family)}: {ent['spans']} spans, "
              f"{ent['rounds']} rounds, {ent['trips']} micro-iters, "
              f"{ent['hosts']} lanes/stage"
              + (f", warm slope {slope:.2f} us/host/round"
                 if slope else ""), file=out)
        print(f"  {'stage':<12} {'fires':>10} {'lanes':>12} "
              f"{'occ %':>7} {'share %':>8} {'us/host/rnd':>12}",
              file=out)
        att = attribution(ent, wall_s)
        for sname in KS_NAMES:
            row = att.get(sname)
            if row is None:
                continue
            # exchange is a per-round stage: lane occupancy does not
            # apply (occupancy_permille returns -1 there).
            occ = row["occupancy_permille"]
            occ_s = f"{occ / 10:>7.1f}" if occ >= 0 else f"{'—':>7}"
            print(f"  {sname:<12} {row['fires']:>10} "
                  f"{row['lanes']:>12} {occ_s} "
                  f"{row['share_permille'] / 10:>8.1f} "
                  f"{row['us_per_host_round']:>12.4f}", file=out)
