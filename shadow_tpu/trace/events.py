"""Flight-recorder record layout and event/reason enums.

Every constant here is a TWIN of the same definition in
native/netplane.cpp (the engine's fixed-record flight ring); analysis
pass 1 diffs both sides through the contract registry
(shadow_tpu/analysis/twin_constants.py), so a drifted value or a
reordered reason table fails `scripts/lint` in seconds instead of
silently corrupting traces.

Record layout (FLIGHT_REC_BYTES, little-endian, no padding):

    int64  t       simulated nanoseconds of the event
    int32  kind    FR_* event kind
    int32  a       kind-specific: eligibility reason (FR_ROUND),
                   span family (FR_SPAN_*)
    int64  b       kind-specific: packets (FR_ROUND/FR_SPAN_COMMIT),
                   abort code (FR_SPAN_ABORT)
    int64  c       kind-specific: window start ns (FR_ROUND),
                   round index (FR_SPAN_START), rounds (FR_SPAN_COMMIT)
"""

from __future__ import annotations

import struct

FLIGHT_REC_BYTES = 32

# Event kinds (C++ twin: the FR_* enum in netplane.cpp).
FR_ROUND = 0        # one conservative round executed
FR_SPAN_START = 1   # multi-round span entered (engine or device)
FR_SPAN_COMMIT = 2  # span committed: rounds/packets imported
FR_SPAN_ABORT = 3   # device span aborted (transactional rollback)
FR_N = 4

# Span families (Python-side only: the engine records no span events —
# the manager orchestrates spans and stamps these itself).
FAM_CPP = 0     # C++ engine run_span
FAM_PHOLD = 1   # device-resident PHOLD/udp-mesh family
FAM_TCP = 2     # device-resident TCP steady-stream family
FAM_NAMES = ("engine", "device-phold", "device-tcp")

# Device-eligibility reason codes (C++ twin: EL_* enum + EL_NAMES
# string table in netplane.cpp).  Every conservative round is assigned
# EXACTLY ONE of these by the manager's round loop; the audit report
# (tools/trace) therefore always sums to the total round count.
EL_DEVICE_SPAN = 0        # stepped inside a device-resident span
EL_ENGINE_SPAN = 1        # stepped inside a C++ engine span
EL_ENGINE_ROUTED = 2      # C++ span: EWMA measured it faster
EL_ENGINE_COLD = 3        # C++ span: device compile budget not earned
EL_ENGINE_ABORT = 4       # C++ span: device span aborted (rollback)
EL_ENGINE_TRANSIENT = 5   # C++ span: device family transiently out
EL_ENGINE_FAMILY = 6      # C++ span: no device-span family fits
EL_ENGINE_OFF = 7         # C++ span: tpu_device_spans=off
EL_ENGINE_PYLIMIT = 8     # C++ span capped before an object host
EL_ROUND_BOUNDARY = 9     # per-round: heartbeat/limit boundary
EL_ROUND_OUTBOX = 10      # per-round: object-path outbox pending
EL_ROUND_GATE = 11        # per-round: route model holds the device
EL_ROUND_CALLBACK = 12    # per-round: callback-capable host present
EL_ROUND_FORCED = 13      # per-round: forced-device audit mode
EL_ROUND_SCHED = 14       # per-round: non-span scheduler
EL_OBJ_PCAP = 15          # object-path host due now: pcap capture
EL_OBJ_CPU = 16           # object-path host due now: CPU model
EL_OBJ_PYTASK = 17        # engine host with transient Python work
EL_OBJ_OTHER = 18         # object-path host due now: other config
EL_N = 19

# Order must mirror the EL_* values above AND the C++ EL_NAMES table
# (pass 1 checks both directions).
EL_NAMES = (
    "device-span",
    "engine-span",
    "engine-span:routed",
    "engine-span:cold-budget",
    "engine-span:abort-rollback",
    "engine-span:transient",
    "engine-span:ineligible-family",
    "engine-span:device-off",
    "engine-span:py-limit",
    "per-round:boundary",
    "per-round:outbox",
    "per-round:span-gate",
    "per-round:callback-host",
    "per-round:forced-device",
    "per-round:scheduler",
    "object-path:pcap",
    "object-path:cpu-model",
    "object-path:py-task",
    "object-path:other",
)
assert len(EL_NAMES) == EL_N
assert len(FAM_NAMES) == FAM_TCP + 1

REC = struct.Struct("<qiiqq")
assert REC.size == FLIGHT_REC_BYTES

# numpy structured dtype for bulk decode (field order == REC).
REC_DTYPE = [("t", "<i8"), ("kind", "<i4"), ("a", "<i4"),
             ("b", "<i8"), ("c", "<i8")]


def pack(t: int, kind: int, a: int, b: int, c: int) -> bytes:
    return REC.pack(t, kind, a, b, c)


def iter_records(buf: bytes):
    """Yield (t, kind, a, b, c) tuples from a packed record stream."""
    for off in range(0, len(buf) - len(buf) % FLIGHT_REC_BYTES,
                     FLIGHT_REC_BYTES):
        yield REC.unpack_from(buf, off)
