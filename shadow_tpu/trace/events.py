"""Flight-recorder record layout and event/reason enums.

Every constant here is a TWIN of the same definition in
native/netplane.cpp (the engine's fixed-record flight ring); analysis
pass 1 diffs both sides through the contract registry
(shadow_tpu/analysis/twin_constants.py), so a drifted value or a
reordered reason table fails `scripts/lint` in seconds instead of
silently corrupting traces.

Record layout (FLIGHT_REC_BYTES, little-endian, no padding):

    int64  t       simulated nanoseconds of the event
    int32  kind    FR_* event kind
    int32  a       kind-specific: eligibility reason (FR_ROUND),
                   span family (FR_SPAN_*)
    int64  b       kind-specific: packets (FR_ROUND/FR_SPAN_COMMIT),
                   abort code (FR_SPAN_ABORT)
    int64  c       kind-specific: window start ns (FR_ROUND),
                   round index (FR_SPAN_START), rounds (FR_SPAN_COMMIT)
"""

from __future__ import annotations

import struct

FLIGHT_REC_BYTES = 32

# Event kinds (C++ twin: the FR_* enum in netplane.cpp).  The
# FR_FAULT_* kinds are the deterministic fault-injection records
# (docs/CHECKPOINT.md): stamped by the manager's round loop — the ONE
# fault choke point — at the round boundary where each configured
# fault applies, with `a` = the target host id.
FR_ROUND = 0        # one conservative round executed
FR_SPAN_START = 1   # multi-round span entered (engine or device)
FR_SPAN_COMMIT = 2  # span committed: rounds/packets imported
FR_SPAN_ABORT = 3   # device span aborted (transactional rollback)
FR_FAULT_KILL = 4       # host_kill applied (a = host id)
FR_FAULT_RESTORE = 5    # host_restore-from-snapshot applied
FR_FAULT_LINK_DOWN = 6  # link_down applied
FR_FAULT_LINK_UP = 7    # link_up applied
FR_FAULT_BLACKHOLE = 8  # nic_blackhole applied
FR_FAULT_CLEAR = 9      # nic_clear applied
FR_FAULT_QUARANTINE = 10  # containment quarantine applied (a = host
#                           id) — a wall-side failure (binary death,
#                           hang watchdog, spawn failure) resolved
#                           into host_kill semantics at a round
#                           boundary, or a replayed ledger/faults
#                           `quarantine` op (docs/ROBUSTNESS.md)
FR_N = 11

# Span families (Python-side only: the engine records no span events —
# the manager orchestrates spans and stamps these itself).
FAM_CPP = 0     # C++ engine run_span
FAM_PHOLD = 1   # device-resident PHOLD/udp-mesh family
FAM_TCP = 2     # device-resident TCP steady-stream family
FAM_NAMES = ("engine", "device-phold", "device-tcp")

# Device-eligibility reason codes (C++ twin: EL_* enum + EL_NAMES
# string table in netplane.cpp).  Every conservative round is assigned
# EXACTLY ONE of these by the manager's round loop; the audit report
# (tools/trace) therefore always sums to the total round count.
EL_DEVICE_SPAN = 0        # stepped inside a device-resident span
EL_ENGINE_SPAN = 1        # stepped inside a C++ engine span
EL_ENGINE_ROUTED = 2      # C++ span: EWMA measured it faster
EL_ENGINE_COLD = 3        # C++ span: device compile budget not earned
EL_ENGINE_ABORT = 4       # C++ span: device span aborted (rollback)
EL_ENGINE_TRANSIENT = 5   # C++ span: device family transiently out
EL_ENGINE_FAMILY = 6      # C++ span: no device-span family fits
EL_ENGINE_OFF = 7         # C++ span: tpu_device_spans=off
EL_ENGINE_PYLIMIT = 8     # C++ span capped before an object host
EL_ROUND_BOUNDARY = 9     # per-round: heartbeat/limit boundary
EL_ROUND_OUTBOX = 10      # per-round: object-path outbox pending
EL_ROUND_GATE = 11        # per-round: route model holds the device
EL_ROUND_CALLBACK = 12    # per-round: callback-capable host present
EL_ROUND_FORCED = 13      # per-round: forced-device audit mode
EL_ROUND_SCHED = 14       # per-round: non-span scheduler
EL_OBJ_PCAP = 15          # object-path host due now: pcap capture
EL_OBJ_CPU = 16           # object-path host due now: CPU model
EL_OBJ_PYTASK = 17        # engine host with transient Python work
EL_OBJ_OTHER = 18         # object-path host due now: other config
# Shard-routing sub-reasons (tpu_shards > 1, ISSUE 11): why rounds
# did or did not land inside a MESH-SHARDED device span.
EL_DEVICE_SHARDED = 19    # stepped inside a sharded device span
EL_ENGINE_EXCHANGE = 20   # C++ span: sharded exchange over capacity
EL_ENGINE_UNSHARDED = 21  # C++ span: host axis % tpu_shards != 0
# Syscall service plane (ISSUE 13): rounds served inside a C++ span
# while every managed process sat parked on a condition with no
# expiry inside the window — the quiescence gate turned the managed
# hosts' park state into span coverage instead of per-round servicing.
EL_SVC_QUIESCENT = 22     # C++ span: managed hosts quiescent
EL_N = 23

# Order must mirror the EL_* values above AND the C++ EL_NAMES table
# (pass 1 checks both directions).
EL_NAMES = (
    "device-span",
    "engine-span",
    "engine-span:routed",
    "engine-span:cold-budget",
    "engine-span:abort-rollback",
    "engine-span:transient",
    "engine-span:ineligible-family",
    "engine-span:device-off",
    "engine-span:py-limit",
    "per-round:boundary",
    "per-round:outbox",
    "per-round:span-gate",
    "per-round:callback-host",
    "per-round:forced-device",
    "per-round:scheduler",
    "object-path:pcap",
    "object-path:cpu-model",
    "object-path:py-task",
    "object-path:other",
    "device-span:sharded",
    "engine-span:exchange-capacity",
    "engine-span:shard-unaligned",
    "engine-span:managed-quiescent",
)
assert len(EL_NAMES) == EL_N
assert len(FAM_NAMES) == FAM_TCP + 1

# ---------------------------------------------------------------------
# Sim-netstat: packet-drop attribution causes + the per-connection TCP
# telemetry record (C++ twins: the TEL_* enum, TEL_NAMES table and
# TelRec struct in netplane.cpp; registered fail-closed in analysis
# pass 1 like FR_*/EL_*).  Every packet drop — on the object path, the
# C++ engine path and the device-span path alike — is attributed to
# EXACTLY ONE cause code, so the per-cause counters provably sum to
# the sim's packets_dropped total (docs/PARITY.md conservation table).
TEL_CODEL = 0          # CoDel AQM control-law drop
TEL_RTR_LIMIT = 1      # router inbound queue hard limit
TEL_LOSS_EDGE = 2      # random loss on a graph edge (inet-loss)
TEL_UNREACHABLE = 3    # no path in the latency matrix
TEL_NO_ROUTE = 4       # destination IP resolves to no host
TEL_NO_SOCKET = 5      # no association listens on the 4-tuple
TEL_TCP_STATE = 6      # tcp-closed / tcp-stray / tcp-dup-syn
TEL_BACKLOG_FULL = 7   # listener accept backlog full
TEL_UDP_FILTER = 8     # connected-UDP source filter
TEL_RECVBUF_FULL = 9   # UDP receive queue full
TEL_BUCKET_DEFER = 10  # token-bucket defer-queue overflow (the relay
#                        parks exactly one packet and the bucket always
#                        admits >= 1 MTU, so this is structurally 0 —
#                        kept so a future bounded defer queue cannot
#                        drop unattributed)
# Fault injection (docs/CHECKPOINT.md): packets that die because a
# configured fault took their endpoint away.  HOST_DOWN = the
# destination host was killed (arrivals drop at their recorded,
# path-independent arrival instant; conservation stays exact because
# the packet never entered any queue ledger); LINK_DOWN = a NIC-level
# fault (link_down both directions, nic_blackhole inbound only).
TEL_HOST_DOWN = 11     # arrival at a killed host
TEL_LINK_DOWN = 12     # NIC link down / blackholed
TEL_WIRE_N = 13        # causes above count in packets_dropped
# TCP receiver discards: the packet itself was delivered (counted
# received, not dropped) but the receiver discarded payload — these
# retransmit later, so they sit OUTSIDE the packets_dropped sum.
TEL_REASM_FULL = 13    # out-of-window segment not stashed
TEL_RECVWIN_TRUNC = 14 # in-order bytes beyond the receive buffer
TEL_N = 15

# Order mirrors the TEL_* values above AND the C++ TEL_NAMES table
# (pass 1 checks both directions).
TEL_NAMES = (
    "codel",
    "router-queue",
    "loss-edge",
    "unreachable",
    "no-route",
    "no-socket",
    "tcp-state",
    "backlog-full",
    "udp-filter",
    "recv-buffer-full",
    "bucket-defer-overflow",
    "host-down",
    "link-down",
    "reassembly-full",
    "recv-window-trunc",
)
assert len(TEL_NAMES) == TEL_N
assert TEL_WIRE_N == TEL_REASM_FULL

# Drop-reason string -> cause code (C++ twin: tel_cause_of).  An
# unmapped reason is counted as `unattributed`, which the conservation
# gate (tests/test_netstat.py) rejects — adding a drop site without a
# cause mapping fails the next tier-1 run, not a release.
TEL_BY_REASON = {
    "codel": TEL_CODEL,
    "rtr-limit": TEL_RTR_LIMIT,
    "inet-loss": TEL_LOSS_EDGE,
    "unreachable": TEL_UNREACHABLE,
    "no-route": TEL_NO_ROUTE,
    "no-socket": TEL_NO_SOCKET,
    "tcp-closed": TEL_TCP_STATE,
    "tcp-stray": TEL_TCP_STATE,
    "tcp-dup-syn": TEL_TCP_STATE,
    "accept-backlog-full": TEL_BACKLOG_FULL,
    "udp-connected-filter": TEL_UDP_FILTER,
    "rcvbuf-full": TEL_RECVBUF_FULL,
    "host-down": TEL_HOST_DOWN,
    "link-down": TEL_LINK_DOWN,
}

# ECN mark attribution (C++ twins: the MARK_* enum + MARK_NAMES table
# in netplane.cpp; registered fail-closed in analysis pass 1 like
# TEL_*).  Every CE rewrite by a queue's marking law is attributed to
# EXACTLY ONE cause — the leg of the DCTCP-K instantaneous threshold
# that fired (packets checked first) — so the per-cause counters
# provably sum to the fabric ledger's marked_pkts total.  Marked
# packets still FORWARD: they sit on the delivered side of the
# byte-conservation invariant, never the dropped side.
MARK_THRESH_PKTS = 0   # queue depth >= DCTCP_K_PKTS at enqueue
MARK_THRESH_BYTES = 1  # queued bytes >= DCTCP_K_BYTES at enqueue
MARK_N = 2

# Order mirrors the MARK_* values above AND the C++ MARK_NAMES table.
MARK_NAMES = (
    "dctcp-k-pkts",
    "dctcp-k-bytes",
)
assert len(MARK_NAMES) == MARK_N

# Per-connection telemetry record (TEL_REC_BYTES, little-endian, no
# padding; C++ twin: struct TelRec):
#
#     int64   t          simulated ns (the sampled round's window end)
#     int32   host       host id
#     uint16  lport      connection identity: local port,
#     uint16  rport        peer port,
#     uint32  rip          peer IP (the local IP is the host's)
#     int32   state      TCP state (connection.py constants)
#     int64[10]          cwnd, ssthresh, srtt, rto, rto_backoff,
#                        send-buffer bytes, recv-buffer bytes,
#                        retransmits, SACK-skipped retransmits,
#                        marks (cumulative CE-marked arrivals this
#                        endpoint OBSERVED — TcpConnection.ce_seen;
#                        the per-flow mark-rate telemetry the sweep
#                        dataset and `trace fct` report)
TEL_REC_BYTES = 104
TEL_REC = struct.Struct("<qiHHIi10q")
assert TEL_REC.size == TEL_REC_BYTES

# numpy structured dtype for bulk encode/decode (field order == TEL_REC).
TEL_DTYPE = [("t", "<i8"), ("host", "<i4"), ("lport", "<u2"),
             ("rport", "<u2"), ("rip", "<u4"), ("state", "<i4"),
             ("cwnd", "<i8"), ("ssthresh", "<i8"), ("srtt", "<i8"),
             ("rto", "<i8"), ("backoff", "<i8"), ("sndbuf", "<i8"),
             ("rcvbuf", "<i8"), ("rtx", "<i8"), ("sacks", "<i8"),
             ("marks", "<i8")]

# ---------------------------------------------------------------------
# Syscall observatory (docs/OBSERVABILITY.md "syscall observatory"):
# per-syscall disposition codes + the fixed per-syscall record of the
# third sim-time channel (`syscalls-sim.bin`).  The SC_* enum's C twin
# lives in native/shim.c — the shim side of the interposition stack,
# which owns the SC_SHIM sequence counter (locally-answered time reads
# counted into the IPC block without a round trip) — and is registered
# fail-closed in analysis pass 1 exactly like FR_*/EL_*/TEL_*.  Every
# Python-dispatched syscall (managed-process ABI dispatch AND internal-
# app dispatch) is credited EXACTLY ONE code, so the disposition
# counters cross-check against per-process strace line counts
# (tools/trace `sys`).  Engine-resident apps dispatch C++-side and sit
# outside this accounting (their counts merge into syscalls_by_name).
SC_SERVICED = 0   # emulated by the simulated kernel (done / error)
SC_PARKED = 1     # parked on a SyscallCondition (re-dispatched on wake)
SC_NATIVE = 2     # natively injected (DO_NATIVE / exit short-circuits)
SC_SHIM = 3       # answered shim-side (time family), no round trip
SC_PROTO = 4      # IPC protocol error ended the conversation
SC_N = 5

# Order must mirror the SC_* values above (and the C enum in shim.c).
SC_NAMES = (
    "serviced",
    "parked-on-condition",
    "natively-injected",
    "shim-handled",
    "protocol-error",
)
assert len(SC_NAMES) == SC_N

# Result classes (Python-side only, like FAM_*): what the dispatch
# returned, orthogonal to HOW the call was routed.
RC_OK = 0      # completed with a non-error value
RC_ERR = 1     # completed with -errno
RC_NATIVE = 2  # executed natively; the manager never saw the value
RC_NONE = 3    # no result this dispatch (parked / protocol error)
RC_NAMES = ("ok", "error", "native", "none")

# Per-syscall record (SC_REC_BYTES, little-endian, no padding; the
# size constant is twinned with SC_REC_BYTES in native/shim.c):
#
#     int64  t_enter   simulated ns at dispatch
#     int64  t_exit    simulated ns when the response lands (equal to
#                      t_enter unless CPU latency deferred the answer)
#     int32  host      host id
#     int32  pid       emulated pid
#     int32  tid       emulated tid
#     int32  sysno     x86-64 syscall number; -1 for SC_SHIM batches
#                      (no single dispatch behind them)
#     int16  rclass    RC_* result class
#     int16  disp      SC_* disposition (exactly one per record)
#     int32  aux       SC_SHIM: locally-answered call count drained
#                      from the shim counter; 0 otherwise
SC_REC_BYTES = 40
SC_REC = struct.Struct("<qqiiiihhi")
assert SC_REC.size == SC_REC_BYTES

# numpy structured dtype for bulk decode (field order == SC_REC).
SC_DTYPE = [("t_enter", "<i8"), ("t_exit", "<i8"), ("host", "<i4"),
            ("pid", "<i4"), ("tid", "<i4"), ("sysno", "<i4"),
            ("rclass", "<i2"), ("disp", "<i2"), ("aux", "<i4")]


def iter_sc_records(buf: bytes):
    """Yield (t_enter, t_exit, host, pid, tid, sysno, rclass, disp,
    aux) tuples from a packed syscall-record stream."""
    for off in range(0, len(buf) - len(buf) % SC_REC_BYTES,
                     SC_REC_BYTES):
        yield SC_REC.unpack_from(buf, off)


# ---------------------------------------------------------------------
# Fabric observatory (docs/OBSERVABILITY.md "Fabric observatory"): the
# FOURTH sim-time channel (`fabric-sim.bin`).  Two record families in
# one artifact behind a small counted header (FAB_HDR): per-queue
# samples (FB_REC) at conservative-round boundaries, then per-flow
# lifecycle records (FCT_REC) from which `trace fct` derives
# flow-completion-time percentiles.  The FB_*/FCT_* constants are
# twinned with native/netplane.cpp and registered fail-closed in
# analysis pass 1 exactly like FR_*/EL_*/TEL_*.
#
# Activity flags (one bit per queue class; a host is sampled in a
# round iff any bit is set — the rule is a pure function of simulation
# state, so the sampled set is path-independent):
FB_ACT_CODEL = 1    # router inbound CoDel queue non-empty
FB_ACT_TB_OUT = 2   # inet-out token-bucket relay parked on a refill
FB_ACT_TB_IN = 4    # inet-in token-bucket relay parked on a refill
FB_ACT_LINK = 8     # the eth link has ever forwarded a packet

# Per-queue sample record (FB_REC_BYTES, little-endian, no padding;
# C++ twin: struct FabRec):
#
#     int64   t         simulated ns (the sampled round's window end)
#     int32   host      host id
#     int32   flags     FB_ACT_* activity mask (why this host sampled)
#     int64[14]         qdepth (CoDel packets), qbytes, sojourn
#                       (head-of-queue wait ns), qenq (cumulative push
#                       attempts), qdrops (cumulative CoDel+hard-limit
#                       drops), qmarks (cumulative CE marks by the
#                       DCTCP-K threshold law — live on all three
#                       paths; by-cause split in the MARK_* counters),
#                       r1_bal / r1_stalls (inet-out bucket balance at
#                       the boundary / cumulative refill stalls),
#                       r2_bal / r2_stalls (inet-in twin),
#                       psent / bsent / precv / brecv (cumulative
#                       per-link eth packets/bytes forwarded)
FB_REC_BYTES = 128
FB_REC = struct.Struct("<qii14q")
assert FB_REC.size == FB_REC_BYTES

# numpy structured dtype for bulk encode/decode (field order == FB_REC).
FB_DTYPE = [("t", "<i8"), ("host", "<i4"), ("flags", "<i4"),
            ("qdepth", "<i8"), ("qbytes", "<i8"), ("sojourn", "<i8"),
            ("qenq", "<i8"), ("qdrops", "<i8"), ("qmarks", "<i8"),
            ("r1_bal", "<i8"), ("r1_stalls", "<i8"),
            ("r2_bal", "<i8"), ("r2_stalls", "<i8"),
            ("psent", "<i8"), ("bsent", "<i8"), ("precv", "<i8"),
            ("brecv", "<i8")]

# Flow-lifecycle flags (C++ twin: the FCT_F_* enum in netplane.cpp).
FCT_F_COMPLETE = 1  # connection reached CLOSED before the artifact
FCT_F_RECEIVER = 2  # this endpoint received more than it sent

# Per-flow lifecycle record (FCT_REC_BYTES, little-endian, no padding;
# C++ twin: struct FctRec — the engine's per-host flow log entry):
#
#     int64   t_first    first data byte sent or delivered (-1: none)
#     int64   t_last     last data byte sent or delivered
#     int32   host       host id
#     uint16  lport      flow identity: local port,
#     uint16  rport        peer port,
#     uint32  rip          peer IP (the local IP is the host's)
#     int32   flags      FCT_F_* bits
#     int64[4]           bytes_in (payload delivered in order),
#                        bytes_out (payload first-transmitted),
#                        retransmits,
#                        marks (cumulative CE-marked arrivals this
#                        endpoint observed — ce_seen at teardown/sweep;
#                        marks/segment is the flow's mark rate)
FCT_REC_BYTES = 64
FCT_REC = struct.Struct("<qqiHHIi4q")
assert FCT_REC.size == FCT_REC_BYTES

# numpy structured dtype for bulk decode (field order == FCT_REC).
FCT_DTYPE = [("t_first", "<i8"), ("t_last", "<i8"), ("host", "<i4"),
             ("lport", "<u2"), ("rport", "<u2"), ("rip", "<u4"),
             ("flags", "<i4"), ("bytes_in", "<i8"),
             ("bytes_out", "<i8"), ("rtx", "<i8"), ("marks", "<i8")]

# fabric-sim.bin layout: FAB_HDR, then fb_records FB_RECs, then
# fct_records FCT_RECs.  The header is Python-side only (the manager
# packs the artifact from every producer), so it has no C++ twin.
FAB_MAGIC = 0x46425354  # "FBST"
FAB_VERSION = 1
FAB_HDR = struct.Struct("<IIQQ")  # magic, version, fb_n, fct_n
FAB_HDR_BYTES = 24
assert FAB_HDR.size == FAB_HDR_BYTES


def split_fabric(buf: bytes) -> tuple[bytes, bytes]:
    """fabric-sim.bin content -> (fb_bytes, fct_bytes); raises
    ValueError on a malformed header or truncated sections."""
    if len(buf) < FAB_HDR_BYTES:
        raise ValueError("fabric artifact shorter than its header")
    magic, version, fb_n, fct_n = FAB_HDR.unpack_from(buf, 0)
    if magic != FAB_MAGIC or version != FAB_VERSION:
        raise ValueError(f"bad fabric header {magic:#x} v{version}")
    fb_end = FAB_HDR_BYTES + fb_n * FB_REC_BYTES
    fct_end = fb_end + fct_n * FCT_REC_BYTES
    if len(buf) < fct_end:
        raise ValueError("fabric artifact truncated")
    return buf[FAB_HDR_BYTES:fb_end], buf[fb_end:fct_end]


def iter_fb_records(fb_bytes: bytes):
    """Yield (t, host, flags, qdepth, qbytes, sojourn, qenq, qdrops,
    qmarks, r1_bal, r1_stalls, r2_bal, r2_stalls, psent, bsent, precv,
    brecv) tuples from a packed FB_REC stream."""
    for off in range(0, len(fb_bytes) - len(fb_bytes) % FB_REC_BYTES,
                     FB_REC_BYTES):
        yield FB_REC.unpack_from(fb_bytes, off)


def iter_fct_records(fct_bytes: bytes):
    """Yield (t_first, t_last, host, lport, rport, rip, flags,
    bytes_in, bytes_out, rtx, marks) tuples from a packed FCT_REC
    stream."""
    for off in range(0, len(fct_bytes) - len(fct_bytes) % FCT_REC_BYTES,
                     FCT_REC_BYTES):
        yield FCT_REC.unpack_from(fct_bytes, off)


# ---------------------------------------------------------------------
# Device-kernel observatory (docs/OBSERVABILITY.md "Device-kernel
# observatory"): the FIFTH sim-time channel (`kernel-sim.bin`).  One
# fixed KS_REC record per COMMITTED device span, carrying a per-stage
# counter block threaded through the span kernels' `lax.while_loop`
# carry: for every fused micro-op stage a FIRE count (micro-iterations
# in which >= 1 lane ran the stage) and an ACTIVE-LANE sum (lanes
# occupying the stage, summed over iterations).  Occupancy is
# lanes / (hosts x trips); the conservation law is that the per-family
# sum of `trips` over committed records equals the dispatch split's
# `micro_iters` counter exactly (aborted spans roll back and record
# nothing).  The KS_* enum and the KS_NAMES table are twinned with
# native/netplane.cpp — the authoritative fail-closed registry pass 1
# scans, even though the stages execute in the JAX kernels — so stage
# drift or a reordered name table fails `scripts/lint`.
#
# Stage semantics (both families unless noted):
#   pop         arrival/timer event pop (all due lanes)
#   step        app stepper (phold op_step M/S; tcp op_app)
#   codel       router-inbound CoDel drain (the r2 relay)
#   on-packet   TCP on_packet header processing (tcp only)
#   reassembly  TCP reassembly drain (tcp only)
#   ack         TCP ack_data decision (tcp only)
#   push        TCP push_data segmentation (tcp only)
#   flush       TCP flush notify decision (tcp only)
#   inet-out    inet-out relay drain (the r1 relay)
#   arm         timer-arm / status tail (phold op_stage2; tcp op_arm)
#   timers      timer handling (phold: inline timer pops; tcp: op_tmr)
#   exchange    sharded cross-shard staging hop (per round, lanes =
#               packets staged — a per-round stage, not a micro-op)
KS_POP = 0
KS_STEP = 1
KS_CODEL = 2
KS_ON_PACKET = 3
KS_REASM = 4
KS_ACK = 5
KS_PUSH = 6
KS_FLUSH = 7
KS_INET_OUT = 8
KS_ARM = 9
KS_TIMERS = 10
KS_EXCHANGE = 11
KS_N = 12

# Order mirrors the KS_* values above AND the C++ KS_NAMES table
# (pass 1 checks both directions).
KS_NAMES = (
    "pop",
    "step",
    "codel",
    "on-packet",
    "reassembly",
    "ack",
    "push",
    "flush",
    "inet-out",
    "arm",
    "timers",
    "exchange",
)
assert len(KS_NAMES) == KS_N

# Per-committed-span record (KS_REC_BYTES, little-endian, no padding;
# the size constant is twinned with native/netplane.cpp):
#
#     int64   t          span entry window start (simulated ns)
#     int32   family     FAM_* span family (phold covers udp-mesh too)
#     int32   hosts      H — the kernel's host-lane width
#     int64   rounds     conservative rounds committed by this span
#     int64   trips      micro-loop while-iterations in this span
#     int64[KS_N]        fires per stage
#     int64[KS_N]        active-lane sums per stage
KS_REC_BYTES = 224
KS_REC = struct.Struct("<qiiqq24q")
assert KS_REC.size == KS_REC_BYTES


def iter_ks_records(buf: bytes):
    """Yield (t, family, hosts, rounds, trips, fires_tuple,
    lanes_tuple) from a packed KS_REC stream."""
    for off in range(0, len(buf) - len(buf) % KS_REC_BYTES,
                     KS_REC_BYTES):
        rec = KS_REC.unpack_from(buf, off)
        yield (rec[0], rec[1], rec[2], rec[3], rec[4],
               rec[5:5 + KS_N], rec[5 + KS_N:5 + 2 * KS_N])


REC = struct.Struct("<qiiqq")
assert REC.size == FLIGHT_REC_BYTES

# numpy structured dtype for bulk decode (field order == REC).
REC_DTYPE = [("t", "<i8"), ("kind", "<i4"), ("a", "<i4"),
             ("b", "<i8"), ("c", "<i8")]


def pack(t: int, kind: int, a: int, b: int, c: int) -> bytes:
    return REC.pack(t, kind, a, b, c)


def iter_records(buf: bytes):
    """Yield (t, kind, a, b, c) tuples from a packed record stream."""
    for off in range(0, len(buf) - len(buf) % FLIGHT_REC_BYTES,
                     FLIGHT_REC_BYTES):
        yield REC.unpack_from(buf, off)
