"""Metrics registry: counters / gauges / histograms in two channels.

One sink replaces the ad-hoc `sim-stats.json` dispatch block.  Every
metric declares its channel:

- ``sim``  — deterministic given the config: the determinism gate
  byte-diffs these (two identical runs must agree).
- ``wall`` — scheduler/routing/profiling telemetry (dispatch splits,
  eligibility histograms, phase timings): the gate STRIPS the whole
  subtree structurally, so there is no hand-maintained normalize list
  to keep in sync with metric names.

Dotted names nest in the output: ``dispatch.span_rounds`` renders as
``{"dispatch": {"span_rounds": ...}}`` under the metric's channel in
``sim-stats.json``'s ``metrics`` block.
"""

from __future__ import annotations

CHANNELS = ("sim", "wall")


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def add(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Keyed histogram (bucket label -> count)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value: dict = {}

    def observe(self, key: str, n: int = 1) -> None:
        self.value[key] = self.value.get(key, 0) + n


class MetricsRegistry:
    def __init__(self):
        # name -> (channel, metric)
        self._metrics: dict[str, tuple] = {}

    def _get(self, name: str, channel: str, factory):
        if channel not in CHANNELS:
            raise ValueError(f"unknown metrics channel {channel!r}")
        ent = self._metrics.get(name)
        if ent is None:
            ent = (channel, factory())
            self._metrics[name] = ent
        elif ent[0] != channel:
            raise ValueError(f"metric {name!r} re-registered on channel "
                             f"{channel!r} (was {ent[0]!r})")
        return ent[1]

    def counter(self, name: str, channel: str = "wall") -> Counter:
        return self._get(name, channel, Counter)

    def gauge(self, name: str, channel: str = "wall") -> Gauge:
        return self._get(name, channel, Gauge)

    def histogram(self, name: str, channel: str = "wall") -> Histogram:
        return self._get(name, channel, Histogram)

    def ingest(self, prefix: str, mapping: dict,
               channel: str = "wall") -> None:
        """Bulk-set gauges from a (possibly nested) dict — the
        migration path for counter sets maintained elsewhere (the
        propagator's dispatch split, a runner's abort counters)."""
        for key, val in mapping.items():
            name = f"{prefix}.{key}" if prefix else str(key)
            if isinstance(val, dict):
                self.ingest(name, val, channel)
            else:
                self.gauge(name, channel).set(val)

    def as_stats(self) -> dict:
        """The `metrics` block for sim-stats.json: one nested dict per
        channel (dotted names split into sub-dicts)."""
        out: dict = {ch: {} for ch in CHANNELS}
        for name, (channel, metric) in sorted(self._metrics.items()):
            node = out[channel]
            parts = name.split(".")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = metric.value
        return out
