"""Fabric observatory: the deterministic per-link queue telemetry and
flow-completion-time channel.

The FOURTH sim-time channel next to the flight recorder, sim-netstat
and the syscall observatory (docs/OBSERVABILITY.md "Fabric
observatory").  Two record families share one artifact
(`fabric-sim.bin`, trace/events.py FAB_HDR framing):

- **FB_REC** queue samples: every ACTIVE interface/router queue at
  conservative-round boundaries — CoDel depth/bytes/head-sojourn plus
  its cumulative enqueue/drop/mark counters, both token-bucket relays'
  balance and refill-stall counts, and the eth link's cumulative
  packets/bytes forwarded.  A host is active iff any FB_ACT_* bit is
  set; the rule is a pure function of simulation state, so the sampled
  set is path-independent.
- **FCT_REC** flow lifecycle records: one per TCP endpoint that ever
  carried payload — first/last data byte, in/out byte counts and
  retransmits — logged at connection teardown and swept from the
  still-associated remainder when the artifact is written, then
  globally sorted by flow identity so emission order can never leak
  into the bytes.

Sampling cadence is the same STATELESS grid-crossing rule sim-netstat
uses (`start // interval != window_end // interval`); both boundaries
are path-independent, so the sampled-round set is too.  The engine
ring (netplane.cpp fab_sample_round), the device-span buffers
(ops/tcp_span.py / ops/phold_span.py round_body) and the object-path
walker below all emit records in ascending host-id order within a
round, so `fabric-sim.bin` is byte-diffed by the determinism gate AND
byte-identical across serial/thread_per_core/tpu and the forced-device
differential.

Like `SimChannel`, this class must never read wall clocks: analysis
pass 3's `sim-channel` rule covers it with no pragma escape.
"""

from __future__ import annotations

import os

from shadow_tpu.trace.events import (FAB_HDR, FAB_MAGIC, FAB_VERSION,
                                     FB_ACT_CODEL, FB_ACT_LINK,
                                     FB_ACT_TB_IN, FB_ACT_TB_OUT,
                                     FB_REC, FB_REC_BYTES, FCT_F_COMPLETE,
                                     FCT_F_RECEIVER, FCT_REC)
from shadow_tpu.trace.recorder import FixedRecordChannel

# tcp/connection.py state values (a CLOSED conn is a completed flow).
_CLOSED = 0

# Relay pending state (net/relay.py _PENDING twin value).
_RELAY_PENDING = 1


def host_queue_sample(host, t: int) -> tuple | None:
    """One object-path host's FB_REC field tuple at sim time `t`, or
    None when no FB_ACT_* bit is set.  THE single reading of the
    active rule and the queue fields on the object path — the
    conservation sweep reuses it so the two can never disagree."""
    codel = host.router._inbound
    r1 = host.relay_inet_out
    r2 = host.relay_inet_in
    eth = host.eth0
    flags = 0
    depth = len(codel)
    if depth > 0:
        flags |= FB_ACT_CODEL
    if r1._state == _RELAY_PENDING:
        flags |= FB_ACT_TB_OUT
    if r2._state == _RELAY_PENDING:
        flags |= FB_ACT_TB_IN
    if eth.packets_sent + eth.packets_received > 0:
        flags |= FB_ACT_LINK
    if not flags:
        return None
    head = codel.peek_entry()
    sojourn = (t - head[1]) if head is not None else 0
    return (t, host.id, flags, depth, codel._bytes, sojourn,
            codel.enqueued_count, codel.dropped_count,
            codel.marked_count,
            r1._bucket.peek_balance(t) if r1._bucket is not None else -1,
            r1.stalls,
            r2._bucket.peek_balance(t) if r2._bucket is not None else -1,
            r2.stalls,
            eth.packets_sent, eth.bytes_sent,
            eth.packets_received, eth.bytes_received)


def host_fabric_counters(host) -> tuple:
    """One object-path host's fabric counter tuple, field-for-field
    the engine's `fabric_counters(hid)`: (enq_pkts, enq_bytes,
    fwd_pkts, fwd_bytes, drop_pkts, drop_bytes, marked, qdepth,
    qbytes, peak_depth, r1_stalls, r2_stalls, psent, bsent, precv,
    brecv, parked_pkts, parked_bytes)."""
    codel = host.router._inbound
    eth = host.eth0
    r2 = host.relay_inet_in
    parked = r2._pending_packet
    return (codel.enqueued_count, codel.enqueued_bytes,
            r2.forwarded_pkts, r2.forwarded_bytes,
            codel.dropped_count, codel.dropped_bytes,
            codel.marked_count, len(codel), codel._bytes,
            codel.peak_depth, host.relay_inet_out.stalls,
            r2.stalls, eth.packets_sent,
            eth.bytes_sent, eth.packets_received, eth.bytes_received,
            1 if parked is not None else 0,
            parked.total_size() if parked is not None else 0)


class FabricChannel(FixedRecordChannel):
    """Deterministic per-queue sample stream (simulated time only;
    trace/recorder.FixedRecordChannel carries the shared cap/extend
    machinery).  Flow records are NOT streamed — the manager sweeps
    them once at artifact-write time (write takes the flow rows)."""

    FILE = "fabric-sim.bin"
    REC_SIZE = FB_REC_BYTES

    def record(self, fields: tuple) -> None:
        """One pre-assembled FB_REC field tuple (host_queue_sample)."""
        if self.records >= self._cap:
            self.dropped += 1
            return
        self._chunks.append(FB_REC.pack(*fields))
        self.records += 1

    def sample_object_hosts(self, hosts, t: int) -> None:
        """Sample every active object-path host's queues.  Hosts on
        the native plane are skipped — their queues live engine-side
        and the engine ring samples them.  `hosts` is the manager's
        id-ordered list, so emission order is ascending host id."""
        for h in hosts:
            if h.plane is not None or not h.net_built():
                continue
            fields = host_queue_sample(h, t)
            if fields is not None:
                self.record(fields)

    def write(self, data_dir: str, flow_rows: list) -> None:
        """Write the framed artifact: header, FB section, then the
        flow records sorted by their full field tuple (flow identity
        first) — emission order can never reach the bytes."""
        fb = self.to_bytes()
        rows = sorted(flow_rows)
        fct = b"".join(FCT_REC.pack(*r) for r in rows)
        hdr = FAB_HDR.pack(FAB_MAGIC, FAB_VERSION,
                           len(fb) // FB_REC_BYTES, len(rows))
        with open(os.path.join(data_dir, self.FILE), "wb") as f:
            f.write(hdr + fb + fct)


def flow_row(host_id: int, lport: int, rport: int, rip: int,
             conn) -> tuple | None:
    """One endpoint's FCT_REC field tuple from a (live or torn-down)
    object-path connection, or None when the flow never carried
    payload.  Field order == trace/events.py FCT_REC; the C++ twin is
    Engine::fct_row."""
    if conn.fct_first < 0:
        return None
    flags = 0
    if conn.state == _CLOSED:
        flags |= FCT_F_COMPLETE
    if conn.fct_bytes_in > conn.fct_bytes_out:
        flags |= FCT_F_RECEIVER
    return (conn.fct_first, conn.fct_last, host_id, lport, rport, rip,
            flags, conn.fct_bytes_in, conn.fct_bytes_out,
            conn.retransmit_count, conn.ce_seen)


def object_host_flow_rows(host) -> list:
    """All of one object-path host's flow rows: the teardown log plus
    every still-associated connection with payload history (the twin
    of the engine's fct_flows sweep)."""
    from shadow_tpu.trace.netstat import iter_host_tcp_sockets
    rows = list(host.fct_log)
    for s in iter_host_tcp_sockets(host):
        conn = s.conn
        if conn is None or s.local is None or s.peer is None:
            continue
        row = flow_row(host.id, s.local[1], s.peer[1], s.peer[0], conn)
        if row is not None:
            rows.append(row)
    return rows


def emit_device_rows(channel, st_np, n_hosts: int) -> None:
    """Pack a device span's buffered fabric rows (fab_* output arrays
    from ops/tcp_span.py or ops/phold_span.py) into FB_REC records and
    append them to `channel`.  Per sampled round, ACTIVE hosts
    (flags != 0) in ascending host-id order — byte-identical to the
    engine ring's records for the same rounds.  `qmarks` samples the
    kernels' live codel_marked column (the DCTCP-K marking law runs
    inside each span's enqueue micro-op)."""
    if channel is None:
        return
    import numpy as np

    from shadow_tpu.trace.events import FB_DTYPE
    fn = int(st_np.get("fab_n", 0))
    if fn == 0:
        return
    flags = np.asarray(st_np["fab_flags"][:fn], dtype=np.int32)
    sel = flags.reshape(-1) != 0
    count = int(sel.sum())
    if count == 0:
        return
    arr = np.zeros(count, dtype=np.dtype(FB_DTYPE))
    arr["t"] = np.repeat(np.asarray(st_np["fab_t"][:fn],
                                    dtype=np.int64), n_hosts)[sel]
    arr["host"] = np.tile(np.arange(n_hosts, dtype=np.int32), fn)[sel]
    arr["flags"] = flags.reshape(-1)[sel]
    for name in ("qdepth", "qbytes", "sojourn", "qenq", "qdrops",
                 "qmarks", "r1_bal", "r1_stalls", "r2_bal",
                 "r2_stalls", "psent", "bsent", "precv", "brecv"):
        arr[name] = np.asarray(st_np[f"fab_{name}"][:fn],
                               dtype=np.int64).reshape(-1)[sel]
    channel.extend(arr.tobytes())


# ---------------------------------------------------------------------
# Report helpers (tools/trace `fabric` / `fct`, the Chrome export and
# bench.py share these so every surface renders the same numbers).
# ---------------------------------------------------------------------

def group_by_host(fb_bytes: bytes) -> dict:
    """FB records grouped by host id -> [records in time order]."""
    from shadow_tpu.trace.events import iter_fb_records
    by_host: dict = {}
    for rec in iter_fb_records(fb_bytes):
        by_host.setdefault(rec[1], []).append(rec)
    return by_host


def top_by_peak_depth(by_host: dict, n: int) -> list:
    """Top-n host ids by peak sampled CoDel depth, ties broken by host
    id — the one deterministic ranking the CLI table and the Chrome
    per-link counter tracks both render."""
    return sorted(by_host,
                  key=lambda h: (-max(r[3] for r in by_host[h]), h))[:n]


def percentile(sorted_vals: list, permille: int) -> int:
    """Nearest-rank percentile (ceil(p*n)-1) over a pre-sorted list,
    in integer arithmetic (permille: 500 = p50, 990 = p99, 999 =
    p999) — deterministic, and the tail percentiles of small samples
    resolve to the max instead of collapsing onto the median."""
    n = len(sorted_vals)
    if not n:
        return 0
    idx = max((permille * n + 999) // 1000 - 1, 0)
    return sorted_vals[min(idx, n - 1)]


def receiver_rows(fct_rows) -> list:
    """The per-FLOW view of an endpoint-record list: the RECEIVER
    endpoint of every flow (the canonical FCT vantage — first byte
    leaves the sender, last byte reaches the receiver), falling back
    to the whole list when no receiver records exist (one-sided
    traffic).  Both simulated endpoints of a flow leave a record, so
    counting records would double every flow; this is THE one
    de-duplication rule `trace fct`, bench's fabric block and the
    tests share."""
    rows = [r for r in fct_rows if r[0] >= 0]
    recv = [r for r in rows if r[6] & FCT_F_RECEIVER]
    return recv if recv else rows


def fct_table(fct_rows) -> dict:
    """Flow-completion-time percentiles per flow class.  A flow's
    class is its service port (the smaller of the two ports — the
    well-known side); every column — count, completions, bytes AND
    the percentiles — is computed over the same receiver-endpoint
    population (receiver_rows), so one flow counts once.  Returns
    {class_port: {"flows", "complete", "bytes", "marks",
    "mark_permille", "p50_ns", "p99_ns", "p999_ns"}} —
    `mark_permille` is CE-marked arrivals per 1000 received segments
    (segments estimated at one MSS each), the per-flow mark-rate view
    ROADMAP item 4 asks for."""
    from shadow_tpu.tcp.connection import MSS
    by_class: dict = {}
    for (t0, t1, _host, lport, rport, _rip, flags, bin_, bout,
         _rtx, marks) in receiver_rows(fct_rows):
        cls = min(lport, rport)
        ent = by_class.setdefault(cls, {"durs": [], "complete": 0,
                                        "bytes": 0, "marks": 0,
                                        "segs": 0})
        ent["durs"].append(t1 - t0)
        if flags & FCT_F_COMPLETE:
            ent["complete"] += 1
        ent["bytes"] += max(bin_, bout)
        ent["marks"] += marks
        ent["segs"] += max((max(bin_, bout) + MSS - 1) // MSS, 1)
    out: dict = {}
    for cls, ent in sorted(by_class.items()):
        durs = sorted(ent["durs"])
        out[cls] = {
            "flows": len(durs),
            "complete": ent["complete"],
            "bytes": ent["bytes"],
            "marks": ent["marks"],
            "mark_permille": (ent["marks"] * 1000) // ent["segs"],
            "p50_ns": percentile(durs, 500),
            "p99_ns": percentile(durs, 990),
            "p999_ns": percentile(durs, 999),
        }
    return out
