"""JAX platform pinning helper.

The site's TPU plugin (axon) force-sets `jax_platforms` at interpreter
startup, so the JAX_PLATFORMS env var alone is NOT sufficient to keep a
process off the TPU tunnel — the config must be re-asserted before any
backend initializes. Every entry point that honors the env var (tests,
bench, driver entries) calls this one helper.
"""

import os


def honor_platform_env(default: str | None = None) -> None:
    """Re-assert JAX_PLATFORMS (or `default`) as the jax_platforms config.

    Call before the first jax.devices()/device_put. No-op if neither the
    env var nor `default` is set.
    """
    want = os.environ.get("JAX_PLATFORMS") or default
    if want:
        import jax
        jax.config.update("jax_platforms", want)


def force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    honor_platform_env()
