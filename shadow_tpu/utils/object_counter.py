"""Object lifecycle accounting (ref: src/main/utility/counter.rs's
ObjectCounter + manager.rs:553-565 leak report at exit).

Every pollable simulated object (StatusOwner subclass: sockets, pipes,
eventfds, timerfds, epolls) counts its allocation at construction and
its deallocation when the last reference releases it (mark_dealloc).  The manager writes the table
to sim-stats.json and warns about classes with alloc != dealloc — in a
GC'd runtime a "leak" means a descriptor that was never close()d,
which is exactly the fd-lifecycle bug class the reference's counter
exists to catch.  Counters are lock-protected: host threads under the
thread-pool schedulers allocate concurrently.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_alloc: dict[str, int] = {}
_dealloc: dict[str, int] = {}


def count_alloc(kind: str) -> None:
    with _lock:
        _alloc[kind] = _alloc.get(kind, 0) + 1


def count_dealloc(kind: str) -> None:
    with _lock:
        _dealloc[kind] = _dealloc.get(kind, 0) + 1


def mark_dealloc(obj) -> None:
    """Count `obj` deallocated exactly once — called when its last fd
    reference releases it (descriptor.py) or when simulator code
    destroys a never-registered object (e.g. a listener tearing down
    never-accepted children).  Keyed off real release, NOT the S_CLOSED
    status bit: a RST'd TCP socket is CLOSED-readable while the app
    still leaks the fd, and that leak must stay visible."""
    if getattr(obj, "_oc_dead", False):
        return
    obj._oc_dead = True
    count_dealloc(type(obj).__name__)


def snapshot() -> dict:
    return {kind: {"allocated": _alloc.get(kind, 0),
                   "deallocated": _dealloc.get(kind, 0)}
            for kind in sorted(set(_alloc) | set(_dealloc))}


def leaks() -> dict[str, int]:
    return {kind: v["allocated"] - v["deallocated"]
            for kind, v in snapshot().items()
            if v["allocated"] != v["deallocated"]}


def reset() -> None:
    """Fresh accounting for a new simulation (tests run many)."""
    with _lock:
        _alloc.clear()
        _dealloc.clear()
