"""SI-unit value parsing for config files.

Accepts the same value syntax as the reference's config layer
(src/main/utility/units.rs): a number plus an optional unit with decimal
(K/M/G/T) or binary (Ki/Mi/Gi/Ti) prefixes, e.g. "10 ms", "1 Gbit",
"16 MiB". Times normalize to nanoseconds, bandwidths to bits/sec,
sizes to bytes.
"""

from __future__ import annotations

import re

_DECIMAL = {"": 1, "k": 10**3, "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12}
_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40}

_TIME_UNITS = {
    "ns": 1, "nanosecond": 1, "nanoseconds": 1,
    "us": 10**3, "μs": 10**3, "microsecond": 10**3, "microseconds": 10**3,
    "ms": 10**6, "millisecond": 10**6, "milliseconds": 10**6,
    "s": 10**9, "sec": 10**9, "second": 10**9, "seconds": 10**9,
    "min": 60 * 10**9, "minute": 60 * 10**9, "minutes": 60 * 10**9,
    "h": 3600 * 10**9, "hour": 3600 * 10**9, "hours": 3600 * 10**9,
}

_VALUE_RE = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*([A-Za-zμ]*)\s*$")


def _split(value: str):
    m = _VALUE_RE.match(value)
    if not m:
        raise ValueError(f"cannot parse unit value: {value!r}")
    num = float(m.group(1)) if "." in m.group(1) else int(m.group(1))
    return num, m.group(2)


def parse_time_ns(value) -> int:
    """'10 ms' / '1s' / bare int (seconds, matching the config spec) -> ns."""
    if isinstance(value, (int, float)):
        return int(value * 10**9)
    num, unit = _split(value)
    if unit == "":
        return int(num * 10**9)
    if unit not in _TIME_UNITS:
        raise ValueError(f"unknown time unit {unit!r} in {value!r}")
    return int(num * _TIME_UNITS[unit])


def _parse_prefixed(value: str, suffixes: tuple[str, ...], what: str) -> int:
    num, unit = _split(value)
    for suffix in sorted(suffixes, key=len, reverse=True):
        if unit.endswith(suffix):
            prefix = unit[: len(unit) - len(suffix)]
            if prefix in _BINARY:
                return int(num * _BINARY[prefix])
            if prefix in _DECIMAL:
                return int(num * _DECIMAL[prefix])
    raise ValueError(f"cannot parse {what} value: {value!r}")


def parse_bandwidth_bits(value) -> int:
    """'1 Gbit' / '100 Mbit' -> bits per second."""
    if isinstance(value, int):
        return value
    return _parse_prefixed(value, ("bit", "bits", "bps"), "bandwidth")


def parse_bytes(value) -> int:
    """'16 MiB' / '131072 B' / bare int (bytes) -> bytes."""
    if isinstance(value, int):
        return value
    return _parse_prefixed(value, ("B", "byte", "bytes"), "byte-size")
