"""Sim-time-aware logger (ref: src/main/core/logger/shadow_logger.rs).

Log records carry wall time, level, simulated time, and host context —
the reference's load-bearing line shape (docs/log_format.md; downstream
tools parse the heartbeat lines).  Records are buffered and flushed in
batches so logging inside the event loop costs an append, not a write
syscall per line (the reference uses a lock-free queue + flusher
thread; a bounded buffer with explicit flush points keeps this
single-threaded and deterministic in output order).
"""

from __future__ import annotations

import sys
import time as _walltime

_LEVELS = {"error": 0, "warning": 1, "info": 2, "debug": 3, "trace": 4}


def _fmt_sim(ns: int | None) -> str:
    if ns is None:
        return "n/a"
    sec, rem = divmod(ns, 10**9)
    return f"{sec // 3600:02d}:{(sec // 60) % 60:02d}:{sec % 60:02d}." \
           f"{rem:09d}"


class ShadowLogger:
    """Buffered, leveled, sim-time-stamped logging to stderr."""

    def __init__(self, level: str = "info", stream=None,
                 flush_every: int = 64):
        self.level = _LEVELS.get(level, 2)
        self.stream = stream if stream is not None else sys.stderr
        self.flush_every = flush_every
        self._buf: list[str] = []
        self._warned: set[str] = set()
        self._t0 = _walltime.monotonic()  # shadow-lint: allow[wall-clock] log timestamps only

    def set_level(self, level: str) -> None:
        self.level = _LEVELS.get(level, 2)

    def enabled(self, level: str) -> bool:
        return _LEVELS.get(level, 2) <= self.level

    def log(self, level: str, msg: str, sim_ns: int | None = None,
            host: str | None = None) -> None:
        lvl = _LEVELS.get(level, 2)
        if lvl > self.level:
            return
        wall = _walltime.monotonic() - self._t0  # shadow-lint: allow[wall-clock] log timestamps only
        ctx = f" [{host}]" if host else ""
        self._buf.append(f"{wall:09.6f} [{level}] {_fmt_sim(sim_ns)}"
                         f"{ctx} {msg}\n")
        if lvl <= _LEVELS["warning"] or len(self._buf) >= self.flush_every:
            self.flush()

    def warn_once(self, key: str, msg: str, sim_ns: int | None = None,
                  host: str | None = None) -> None:
        """One-shot warning (e.g. an unsupported-but-survivable syscall
        feature) — diagnosable without flooding the log."""
        if key in self._warned:
            return
        self._warned.add(key)
        self.log("warning", msg, sim_ns=sim_ns, host=host)

    def error(self, msg: str, **kw) -> None:
        self.log("error", msg, **kw)

    def warning(self, msg: str, **kw) -> None:
        self.log("warning", msg, **kw)

    def info(self, msg: str, **kw) -> None:
        self.log("info", msg, **kw)

    def debug(self, msg: str, **kw) -> None:
        self.log("debug", msg, **kw)

    def flush(self) -> None:
        if self._buf:
            self.stream.write("".join(self._buf))
            self._buf.clear()
            self.stream.flush()


# Process-wide logger; the manager re-levels it from general.log_level.
LOG = ShadowLogger()
