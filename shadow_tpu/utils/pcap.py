"""Pcap capture (ref: src/main/utility/pcap_writer.rs, hooked at
src/main/host/network/interface.rs:45-51).

Writes classic libpcap format (magic 0xA1B2C3D4, LINKTYPE_RAW=101) with
synthesized IPv4+TCP/UDP headers — enough for wireshark/tcpdump to
dissect simulated flows. Timestamps are emulated time.
"""

from __future__ import annotations

import struct

from shadow_tpu.core import simtime
from shadow_tpu.net import packet as pkt

_LINKTYPE_RAW = 101


def _ipv4_header(p, total_len: int) -> bytes:
    header = struct.pack(
        ">BBHHHBBH4s4s",
        0x45, 0, total_len, (p.src_host_id * 31 + p.seq) & 0xFFFF,
        0x4000,  # don't fragment
        64, p.protocol, 0,
        p.src_ip.to_bytes(4, "big"), p.dst_ip.to_bytes(4, "big"))
    checksum = _inet_checksum(header)
    return header[:10] + struct.pack(">H", checksum) + header[12:]


def _inet_checksum(data: bytes) -> int:
    total = 0
    for i in range(0, len(data) - 1, 2):
        total += (data[i] << 8) | data[i + 1]
    if len(data) % 2:
        total += data[-1] << 8
    while total >> 16:
        total = (total & 0xFFFF) + (total >> 16)
    return ~total & 0xFFFF


def _transport_header(p) -> bytes:
    if p.protocol == pkt.PROTO_UDP:
        return struct.pack(">HHHH", p.src_port, p.dst_port,
                           8 + len(p.payload), 0)
    t = p.tcp
    flags = t.flags if t is not None else 0
    seq = t.seq if t is not None else 0
    ack = t.ack if t is not None else 0
    window = t.window if t is not None else 0
    return struct.pack(">HHIIBBHHH", p.src_port, p.dst_port, seq, ack,
                       5 << 4, flags & 0xFF, min(window, 0xFFFF), 0, 0)


class _Fields:
    """Duck-typed packet view for write_fields (what _ipv4_header /
    _transport_header read)."""

    __slots__ = ("src_host_id", "seq", "protocol", "src_ip", "src_port",
                 "dst_ip", "dst_port", "payload", "tcp")

    class _Tcp:
        __slots__ = ("seq", "ack", "flags", "window")

        def __init__(self, seq, ack, flags, window):
            self.seq = seq
            self.ack = ack
            self.flags = flags
            self.window = window

    def __init__(self, src_host_id, seq, proto, src_ip, src_port,
                 dst_ip, dst_port, payload, tcp):
        self.src_host_id = src_host_id
        self.seq = seq
        self.protocol = proto
        self.src_ip = src_ip
        self.src_port = src_port
        self.dst_ip = dst_ip
        self.dst_port = dst_port
        self.payload = payload
        self.tcp = None if tcp is None else self._Tcp(*tcp)


class PcapWriter:
    def __init__(self, path: str, capture_size: int = 65535):
        self._f = open(path, "wb")
        self.capture_size = capture_size
        self._f.write(struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0,
                                  capture_size, _LINKTYPE_RAW))

    def write_packet(self, sim_now: int, p) -> None:
        self._write(sim_now, p)

    def write_fields(self, sim_now: int, src_host_id: int, seq: int,
                     proto: int, src_ip: int, src_port: int, dst_ip: int,
                     dst_port: int, payload: bytes, tcp) -> None:
        """Field-level entry point: the engine's pcap records (no
        Packet object) ride the same frame builder as write_packet, so
        engine-captured and object-path files are byte-identical."""
        self._write(sim_now, _Fields(src_host_id, seq, proto, src_ip,
                                     src_port, dst_ip, dst_port,
                                     payload, tcp))

    def _write(self, sim_now: int, p) -> None:
        emu = simtime.emulated_from_sim(sim_now)
        ip_payload = _transport_header(p) + p.payload
        frame = _ipv4_header(p, 20 + len(ip_payload)) + ip_payload
        snap = frame[:self.capture_size]
        self._f.write(struct.pack("<IIII", emu // simtime.NSEC_PER_SEC,
                                  (emu % simtime.NSEC_PER_SEC) // 1000,
                                  len(snap), len(frame)))
        self._f.write(snap)

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()
