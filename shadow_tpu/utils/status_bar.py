"""Terminal status bar / progress printer.

Ref: src/main/utility/status_bar.rs:1-209 and its wiring in
controller.rs:43-52,116-154 — a redrawing one-line bar on a TTY, a
plain line printer otherwise, showing % complete, simulated vs real
time, and sim-seconds per wall-second.
"""

from __future__ import annotations

import sys
import time


class StatusPrinter:
    """Plain line printer (non-TTY / logging-friendly)."""

    def __init__(self, stop_time_ns: int, out=None):
        self.stop = max(stop_time_ns, 1)
        self.out = out if out is not None else sys.stderr
        self.wall_start = time.perf_counter()  # shadow-lint: allow[wall-clock] display only

    def update(self, sim_now_ns: int) -> None:
        wall = time.perf_counter() - self.wall_start  # shadow-lint: allow[wall-clock] display only
        pct = 100.0 * sim_now_ns / self.stop
        rate = (sim_now_ns / 1e9) / wall if wall > 0 else 0.0
        print(f"[shadow-tpu] {pct:5.1f}% — simulated {sim_now_ns / 1e9:.3f}s "
              f"in {wall:.1f}s real ({rate:.2f} sim-sec/wall-sec)",
              file=self.out, flush=True)

    def finish(self, sim_now_ns: int) -> None:
        self.update(sim_now_ns)


class StatusBar(StatusPrinter):
    """Redrawing single-line bar for interactive terminals."""

    WIDTH = 30

    def update(self, sim_now_ns: int) -> None:
        wall = time.perf_counter() - self.wall_start  # shadow-lint: allow[wall-clock] display only
        frac = min(sim_now_ns / self.stop, 1.0)
        filled = int(frac * self.WIDTH)
        bar = "=" * filled + ">" + " " * (self.WIDTH - filled)
        rate = (sim_now_ns / 1e9) / wall if wall > 0 else 0.0
        self.out.write(f"\r[{bar[:self.WIDTH]}] {frac * 100:5.1f}% "
                       f"{sim_now_ns / 1e9:8.3f}s sim  "
                       f"{rate:6.2f} sim-s/s ")
        self.out.flush()

    def finish(self, sim_now_ns: int) -> None:
        self.update(sim_now_ns)
        self.out.write("\n")
        self.out.flush()


def make_status(stop_time_ns: int, out=None):
    """Bar on a TTY, line printer otherwise (controller.rs:43-52)."""
    stream = out if out is not None else sys.stderr
    if hasattr(stream, "isatty") and stream.isatty():
        return StatusBar(stop_time_ns, stream)
    return StatusPrinter(stop_time_ns, stream)
