"""UDP sockets (ref: src/main/host/descriptor/socket/inet/udp.rs).

A UDP socket is a pair of bounded packet queues: the send queue drains
through the interface/relay/token-bucket path; the recv queue fills from
the interface demux. Status bits drive poll/epoll/blocking syscalls.
"""

from __future__ import annotations

import errno
from collections import deque

from shadow_tpu.host.status import (S_ACTIVE, S_READABLE, S_WRITABLE,
                                    S_CLOSED, StatusOwner)
from shadow_tpu.net import packet as pkt
from shadow_tpu.net.graph import LOCALHOST_IP

INADDR_ANY = 0
# No IP fragmentation is modeled (same simplification as the reference's
# UDP socket): a datagram must fit one MTU-sized packet, which also
# guarantees every packet conforms to the token-bucket burst capacity.
UDP_MAX_PAYLOAD = pkt.MTU - pkt.IPV4_HEADER_SIZE - pkt.UDP_HEADER_SIZE

EPHEMERAL_LO = 32_768
EPHEMERAL_HI = 65_536


class UdpSocket(StatusOwner):
    def __init__(self, host, send_buf: int, recv_buf: int):
        super().__init__()
        self.protocol = pkt.PROTO_UDP
        self.local = None       # (ip, port) after bind
        self.peer = None        # (ip, port) after connect
        self._ifaces = []       # interfaces we're associated on
        # Separate send queue per interface: the loopback relay must never
        # drain remote-destined packets (which would bypass the upload
        # token bucket) and vice versa.
        self._send_q: dict[str, deque] = {"lo": deque(), "eth0": deque()}
        self._send_bytes = 0
        self._send_max = send_buf
        self._recv_q: deque = deque()
        self._recv_bytes = 0
        self._recv_max = recv_buf
        self.drops_full_recv = 0
        self._status = S_ACTIVE | S_WRITABLE
        self.nonblocking = False
        self.reuseaddr = False

    # ------------------------------------------------------------------
    # Binding
    # ------------------------------------------------------------------

    def _pick_interfaces(self, host, ip: int):
        if ip == INADDR_ANY:
            return [host.lo, host.eth0]
        if ip == LOCALHOST_IP:
            return [host.lo]
        if ip == host.eth0.ip:
            return [host.eth0]
        raise OSError(errno.EADDRNOTAVAIL, "cannot bind non-local address")

    def bind(self, host, ip: int, port: int) -> None:
        if self.local is not None:
            raise OSError(errno.EINVAL, "already bound")
        ifaces = self._pick_interfaces(host, ip)
        if port == 0:
            port = self._ephemeral_port(host, ifaces)
        else:
            from shadow_tpu.net.interface import check_bind_port
            check_bind_port(ifaces, self.protocol, port, self.reuseaddr)
        for iface in ifaces:
            iface.associate(self, self.protocol, port)
        self._ifaces = ifaces
        self.local = (ip, port)

    def _ephemeral_port(self, host, ifaces) -> int:
        # Random ephemeral ports from the host's deterministic stream
        # (reference: udp.rs uses the host RNG the same way).
        for _ in range(64):
            port = host.rng.randrange(EPHEMERAL_LO, EPHEMERAL_HI)
            if not any(i.port_in_use(self.protocol, port) for i in ifaces):
                return port
        # Dense occupancy: linear probe, still deterministic.
        for port in range(EPHEMERAL_LO, EPHEMERAL_HI):
            if not any(i.port_in_use(self.protocol, port) for i in ifaces):
                return port
        raise OSError(errno.EADDRINUSE, "no free ephemeral ports")

    def connect(self, host, ip: int, port: int) -> None:
        """UDP connect: set the default/filter peer."""
        if self.local is None:
            self.bind(host, INADDR_ANY, 0)
        self.peer = (ip, port)

    # ------------------------------------------------------------------
    # Send path
    # ------------------------------------------------------------------

    def sendto(self, host, data: bytes, dst) -> int:
        if dst is None:
            if self.peer is None:
                raise OSError(errno.EDESTADDRREQ, "no destination")
            dst = self.peer
        if len(data) > UDP_MAX_PAYLOAD:
            raise OSError(errno.EMSGSIZE, "datagram too large")
        if self.local is None:
            self.bind(host, INADDR_ANY, 0)
        size = len(data) + pkt.UDP_HEADER_SIZE + pkt.IPV4_HEADER_SIZE
        if self._send_bytes + size > self._send_max:
            # Clear WRITABLE so a blocked sender only retries after the
            # relay drains something (pull_out_packet re-sets it) —
            # otherwise an already-satisfied condition would re-fire at the
            # same instant and spin the thread forever.
            self.adjust_status(host, 0, S_WRITABLE)
            raise BlockingIOError(errno.EWOULDBLOCK, "send buffer full")
        dst_ip, dst_port = dst
        src_ip = self.local[0]
        if src_ip == INADDR_ANY:
            src_ip = LOCALHOST_IP if dst_ip == LOCALHOST_IP else host.eth0.ip
        seq = host.next_packet_seq()
        p = pkt.Packet(host.id, seq, self.protocol, src_ip, self.local[1],
                       dst_ip, dst_port, payload=bytes(data))
        p.priority = seq
        iface = host.lo if dst_ip == LOCALHOST_IP else host.eth0
        self._send_q[iface.name].append(p)
        self._send_bytes += size
        iface.notify_socket_has_packets(host, self)
        return len(data)

    def peek_next_packet_priority(self, iface):
        q = self._send_q[iface.name]
        return q[0].priority if q else None

    def pull_out_packet(self, host, iface):
        q = self._send_q[iface.name]
        if not q:
            return None
        p = q.popleft()
        self._send_bytes -= p.total_size()
        if not self.has_status(S_CLOSED):
            self.adjust_status(host, S_WRITABLE, 0)
        return p

    # ------------------------------------------------------------------
    # Receive path
    # ------------------------------------------------------------------

    def push_in_packet(self, host, packet) -> bool:
        if self.peer is not None and \
                (packet.src_ip, packet.src_port) != self.peer:
            host.trace_drop(packet, "udp-connected-filter")
            return False
        size = packet.total_size()
        if self._recv_bytes + size > self._recv_max:
            self.drops_full_recv += 1
            host.trace_drop(packet, "rcvbuf-full")
            return False
        self._recv_q.append(packet)
        self._recv_bytes += size
        self.adjust_status(host, S_READABLE, 0)
        return True

    def recvfrom(self, host, bufsize: int, peek: bool = False):
        if not self._recv_q:
            raise BlockingIOError(errno.EWOULDBLOCK, "no data")
        if peek:
            p = self._recv_q[0]
            return p.payload[:bufsize], (p.src_ip, p.src_port)
        p = self._recv_q.popleft()
        self._recv_bytes -= p.total_size()
        if not self._recv_q:
            self.adjust_status(host, 0, S_READABLE)
        return p.payload[:bufsize], (p.src_ip, p.src_port)

    # ------------------------------------------------------------------

    def close(self, host) -> None:
        for iface in self._ifaces:
            if self.local is not None:
                iface.disassociate(self.protocol, self.local[1])
        self._ifaces = []
        self.adjust_status(host, S_CLOSED, S_ACTIVE | S_READABLE | S_WRITABLE)
