"""File status bits and status listeners.

Ref: the C StatusListener (src/main/host/status_listener.c) and the file
state bits used across descriptor/*.rs. Every pollable object (socket,
pipe, eventfd, timerfd, epoll) carries a status bitmask; listeners
(epoll entries, blocked-syscall conditions) subscribe to a mask and fire
when any watched bit *changes*.
"""

from __future__ import annotations

# Status bits (descriptor/mod.rs FileState)
S_ACTIVE = 1 << 0      # open and usable
S_READABLE = 1 << 1
S_WRITABLE = 1 << 2
S_CLOSED = 1 << 3
S_ERROR = 1 << 4
S_SOCKET_ALLOWING_CONNECT = 1 << 5  # listener with room in accept queue


class StatusOwner:
    """Mixin holding a status bitmask + listener registry."""

    def __init__(self):
        self._status = 0
        self._listeners: list = []  # (mask, callback) pairs
        from shadow_tpu.utils.object_counter import count_alloc
        count_alloc(type(self).__name__)

    @property
    def status(self) -> int:
        return self._status

    def has_status(self, mask: int) -> bool:
        return bool(self._status & mask)

    def add_status_listener(self, mask: int, callback) -> object:
        """callback(owner, changed_bits, host). Returns a removal handle."""
        handle = [mask, callback, True]
        self._listeners.append(handle)
        return handle

    def remove_status_listener(self, handle) -> None:
        handle[2] = False
        try:
            self._listeners.remove(handle)
        except ValueError:
            pass

    def adjust_status(self, host, set_mask: int, clear_mask: int = 0) -> None:
        old = self._status
        new = (old | set_mask) & ~clear_mask
        if new == old:
            return
        self._status = new
        changed = old ^ new
        # Copy: callbacks may add/remove listeners reentrantly.
        for handle in list(self._listeners):
            mask, callback, alive = handle
            if alive and (changed & mask):
                callback(self, changed, host)
