"""Host: one emulated Linux system (ref: src/main/host/host.rs).

Owns the private event queue, the network devices (lo/eth0 interfaces,
CoDel router, three bandwidth relays), the deterministic per-host RNG,
process table, and the canonical packet trace. A host is single-threaded
by construction — only cross-host packet pushes touch it from outside,
and only between rounds (TPU scheduler) or under the queue lock (CPU
scheduler), mirroring the reference's Root-token concurrency argument
(SURVEY.md section 5.2).
"""

from __future__ import annotations

import threading
from collections import deque

from shadow_tpu.core.event import (Event, EventQueue, KIND_LOCAL, KIND_PACKET,
                                   TaskRef)
from shadow_tpu.core.rng import HostRng
from shadow_tpu.core.simtime import TIME_NEVER
from shadow_tpu.net.graph import LOCALHOST_IP, format_ip
from shadow_tpu.net.interface import NetworkInterface
from shadow_tpu.net.packet import PROTO_TCP
from shadow_tpu.net.relay import Relay
from shadow_tpu.net.router import Router
from shadow_tpu.net.token_bucket import TokenBucket
from shadow_tpu.trace.events import MARK_N, SC_N, TEL_BY_REASON, TEL_N

# Canonical trace kinds, in tiebreak order: a packet sent and dropped at
# the same instant sorts SND before DRP.
TRACE_SND = 0
TRACE_DRP = 1
TRACE_RCV = 2
_TRACE_NAMES = {TRACE_SND: "SND", TRACE_DRP: "DRP", TRACE_RCV: "RCV"}


class Host:
    # Fault-injection state (docs/CHECKPOINT.md; netplane.cpp HostPlane
    # twins): a DOWN host consumes no events — packet arrivals drop
    # with the host-down cause at their recorded (path-independent)
    # arrival instant, local tasks/timers discard silently.  LINK_DOWN
    # drops both directions at the NIC, BLACKHOLE arrivals only.
    # Class-level defaults so snapshots from older archives and
    # direct constructions behave (flags flip per instance).
    down = False
    link_down = False
    blackhole = False
    # Syscall-transcript recording for internal-app threads (set by the
    # manager when a `checkpoint:` block is configured; ckpt/replay.py
    # rebuilds generator frames from the transcripts on resume).
    ckpt_record = False
    strace_mode = None  # set by the manager at build
    # Per-host TCP stack options (`tcp: {cc, ecn}` config block; the
    # manager overrides at build).  Class-level defaults so direct
    # constructions and older snapshots get the reno/no-ECN stack.
    tcp_cc = "reno"
    tcp_ecn = False
    # DCTCP marking threshold (experimental.dctcp_k_pkts/_bytes; the
    # manager overrides at build and ckpt restore re-applies the
    # RESUMED config's values — K is config, not snapshotted state, so
    # `tools/ckpt fork` can sweep it from one warm archive).
    dctcp_k_pkts = 20
    dctcp_k_bytes = 30_000
    # Failure containment plane (svc/containment.py): set by the
    # manager on hosts carrying managed processes; None everywhere
    # else.  The spawn stagger is its wall-only companion knob.
    containment = None
    spawn_stagger_ns = 0

    def __init__(self, host_id: int, name: str, ip: int, node_index: int,
                 seed: int, bw_down_bits: int, bw_up_bits: int,
                 qdisc: str = "fifo", mtu: int = 1500):
        self.id = host_id
        self.name = name
        self.ip = ip
        self.bw_down_bits = bw_down_bits
        self.bw_up_bits = bw_up_bits
        self.node_index = node_index
        self.rng = HostRng(seed, host_id)
        self.queue = EventQueue()
        # Cross-host deliveries land in a locked inbox, not the heap: the
        # owner pops its heap without a lock (heapq is not thread-safe),
        # and conservative windows guarantee inbox events are never needed
        # mid-round (their time is >= window end). Drained at execute().
        self._inbox: deque = deque()
        self._inbox_lock = threading.Lock()
        self._inbox_min = TIME_NEVER  # earliest undrained delivery
        self._now = 0
        self._event_seq = 0
        self._packet_seq = 0
        self.processes: dict[int, object] = {}
        self._next_pid = 1000
        self.data_path = None  # set by the manager; per-host output dir
        # AF_UNIX name table: fs paths + '@'-prefixed abstract namespace
        # (ref: abstract_unix_ns.rs; paths never touch the real fs).
        self.unix_ns: dict[str, object] = {}
        # Host CPU model (cpu.rs): None unless host_cpu_threshold is
        # configured, so the hot loop pays nothing by default.
        self.cpu = None
        self.cpu_event_cost_ns = 0
        # Unblocked-syscall latency model knobs (configuration.rs:464-480
        # analogs; overridden by the manager from experimental config).
        self.syscall_latency_ns = 1_000
        self.max_unapplied_ns = 20_000
        # Native preemption (preempt.rs): 0 = disabled.
        self.preempt_native_ns = 0
        self.preempt_sim_ns = 0
        # Native file I/O billing: simulated ns per KiB moved by
        # DO_NATIVE byte-I/O syscalls (0 = not modeled).
        self.native_io_ns_per_kib = 0

        # Network plane (host.rs:209-344 construction order) — built
        # LAZILY via __getattr__ on first touch of any of the six
        # objects: engine-resident hosts never use them, and at 100k
        # hosts their construction was the bulk of Manager build time.
        self._net_qdisc = qdisc
        self._net_mtu = mtu

        # Set by the scheduler before the first round.
        self._send_packet_fn = None

        # Native data plane (shadow_tpu/native/plane.py): when attached,
        # the C++ engine owns this host's inet sockets/queues/timers and
        # the event/packet seq counters; None = pure-Python object path.
        self.plane = None
        self._nsocks: dict[int, object] = {}  # engine token -> proxy
        self._send_native_fn = None           # propagator.send_native
        self._native_merged = (0, 0, 0, 0)    # counters merged so far
        self._app_sys_merged: dict = {}       # engine-app syscalls merged

        # Shared next-event snapshot (manager._nt): each host writes its
        # own slot at the end of execute(); cross-host deliveries lower
        # the destination slot under the inbox lock.  The manager's
        # barrier is then one min() over the list instead of a peek
        # into every host's queues each round.
        self._nt_list = None
        # Shared bool slot (Manager array): True while this host has
        # Python-side work (heap entries / undrained inbox) and so must
        # skip the engine-only fast path.
        self._py_work_arr = None
        # Permanently pinned py-work flag (syscall service plane's
        # quiescence gate): a managed-process host's packets always
        # need Python-side servicing, so its slot must never recompute
        # to False — the engine's span loop relies on the flag to stop
        # before any window that would touch this host (netplane.cpp
        # span_eligible).
        self.py_pinned = False

        # Canonical packet trace: (time, kind, src_host, pkt_seq, text).
        self.trace_entries: list = []
        self.tracing_enabled = True

        # Counters for sim-stats (sim_stats.rs).
        self.counters = {"events": 0, "packets_sent": 0, "packets_recv": 0,
                         "packets_dropped": 0, "syscalls": 0}
        # Sim-netstat drop attribution (trace/events.py TEL_*; the
        # netplane HostPlane::drop_causes twin): every trace_drop maps
        # its reason to exactly one cause, so the wire causes sum to
        # counters["packets_dropped"].  Unattributed = a reason with no
        # TEL_BY_REASON entry; the conservation gate rejects it.
        self.drop_causes = [0] * TEL_N
        self.drop_unattributed = 0
        self._native_causes_merged = (0,) * (TEL_N + 1)
        # ECN mark attribution (trace/events.py MARK_*; the netplane
        # HostPlane::mark_causes twin): every CE rewrite by this
        # host's router queue credits exactly one cause, so the
        # per-cause counters sum to the queue's marked_count.
        self.mark_causes = [0] * MARK_N
        self._native_marks_merged = (0,) * MARK_N
        # Fabric-observatory flow lifecycle (trace/fabricstat.py):
        # FCT_REC field tuples of connections torn down before the
        # artifact was written (netplane.cpp HostPlane::fct_log twin).
        # Always on — appends happen only at connection teardown.
        self.fct_log: list = []
        # Per-syscall-name histogram (sim_stats.rs syscall counts; merged
        # into sim-stats.json by the manager).
        self.syscall_counts: dict[str, int] = {}
        # Syscall-observatory dispositions (trace/events.py SC_*):
        # every Python-dispatched syscall — managed-ABI and internal-app
        # alike — credited exactly one code; always on (integer adds,
        # like drop attribution).  Engine-resident apps dispatch
        # C++-side and sit outside this accounting.
        self.sc_disp = [0] * SC_N
        # Set by the manager when experimental.syscall_observatory is
        # wall/on: the per-host wall profile (trace/sctrace.HostScWall)
        # and — mode "on" — this host's slice of the per-syscall
        # sim-time record channel (HostSyscallLog).  Both are touched
        # only by the thread executing this host's events.
        self.sc_wall = None
        self.sc_log = None
        # perf_timers feature (perf_timer.rs): cumulative wall ns spent
        # executing this host's events; filled by the manager when
        # experimental.use_perf_timers is on.
        self.perf_exec_ns = 0

    def count_syscall(self, name: str) -> None:
        self.counters["syscalls"] += 1
        counts = self.syscall_counts
        counts[name] = counts.get(name, 0) + 1

    # ------------------------------------------------------------------
    # Clock & scheduling
    # ------------------------------------------------------------------

    def now(self) -> int:
        return self._now

    def next_event_seq(self) -> int:
        if self.plane is not None:
            # One shared counter: engine-internal draws (timer arms,
            # relay parks) interleave with Python draws exactly as the
            # object path would.
            return self.plane.engine.next_event_seq(self.id)
        s = self._event_seq
        self._event_seq += 1
        return s

    def next_packet_seq(self) -> int:
        if self.plane is not None:
            return self.plane.engine.next_packet_seq(self.id)
        s = self._packet_seq
        self._packet_seq += 1
        return s

    _NET_ATTRS = frozenset({"lo", "eth0", "router", "relay_loopback",
                            "relay_inet_out", "relay_inet_in"})

    def __getattr__(self, name):
        # Lazy network-plane construction (only ever reached when the
        # attribute is missing, i.e. before the first build; afterwards
        # normal instance-attribute lookup wins with zero overhead).
        if name in Host._NET_ATTRS:
            self._build_net_plane()
            return object.__getattribute__(self, name)
        raise AttributeError(name)

    def net_built(self) -> bool:
        return "lo" in self.__dict__

    def _build_net_plane(self) -> None:
        qdisc = self._net_qdisc
        self.lo = NetworkInterface(LOCALHOST_IP, "lo", qdisc)
        self.eth0 = NetworkInterface(self.ip, "eth0", qdisc)
        self.router = Router()
        self._build_relays()

    def _build_relays(self) -> None:
        """The three relays hold pop-closures over the interfaces, so
        they are rebuilt (not unpickled) on checkpoint restore —
        __setstate__ re-applies their mutable state afterwards."""
        mtu = self._net_mtu
        self.relay_loopback = Relay(
            "lo", lambda host, now: self.lo.pop_packet(host, now), None)
        self.relay_inet_out = Relay(
            "inet-out", lambda host, now: self.eth0.pop_packet(host, now),
            TokenBucket.for_bandwidth(self.bw_up_bits, mtu))
        self.relay_inet_in = Relay(
            "inet-in",
            lambda host, now: self.router.pop_inbound(host, now),
            TokenBucket.for_bandwidth(self.bw_down_bits, mtu))

    def schedule_task_at(self, time: int, task: TaskRef) -> None:
        assert time >= self._now, f"task {task} scheduled in the past"
        self.queue.push(Event(time, KIND_LOCAL, self.id,
                              self.next_event_seq(), task))
        if self._py_work_arr is not None:
            self._py_work_arr[self.id] = True

    def schedule_task(self, delay_ns: int, task: TaskRef) -> None:
        self.schedule_task_at(self._now + delay_ns, task)

    # ------------------------------------------------------------------
    # Round execution (host.rs:749-793)
    # ------------------------------------------------------------------

    def drain_inbox(self) -> None:
        """Move cross-host deliveries into the heap (owner thread only)."""
        if not self._inbox:
            return
        with self._inbox_lock:
            events, self._inbox = self._inbox, deque()
            self._inbox_min = TIME_NEVER
        for ev in events:
            self.queue.push(ev)

    def execute(self, until: int) -> None:
        if self.plane is not None:
            self._execute_native(until)
            return
        self.drain_inbox()
        if self.down:
            self._execute_down(until)
            return
        q = self.queue
        cpu = self.cpu
        nic_dead = self.link_down or self.blackhole
        while True:
            t = q.peek_time()
            if t is None or t >= until:
                break
            ev = q.pop()
            if nic_dead and ev.kind == KIND_PACKET:
                # NIC fault: the arrival dies at its recorded instant
                # (engine twin: the run_until inbox-pop check) — it
                # never enters any queue ledger, so fabric
                # conservation stays exact.
                self._now = ev.time
                self.counters["events"] += 1
                self.trace_drop(ev.data, "link-down", at_time=ev.time)
                continue
            if cpu is not None:
                # CPU-model push-back (cpu.rs + host.rs:760-777): while
                # the modeled CPU is saturated, events slip forward.
                cpu.update_time(ev.time)
                d = cpu.delay()
                if d > 0:
                    ev.time += d
                    q.push(ev)
                    continue
            self._now = ev.time
            self.counters["events"] += 1
            if ev.kind == KIND_PACKET:
                self.router.route_incoming_packet(self, ev.data)
            else:
                ev.data.execute(self)
            if cpu is not None and self.cpu_event_cost_ns:
                # Deterministic event-cost feed: a flooded host's CPU
                # saturates and later events slip (the reference feeds
                # native wall time here — nondeterministic, perf_timers
                # gated; a fixed modeled cost keeps runs bit-identical).
                cpu.add_delay(self.cpu_event_cost_ns)
        self._update_nt_slot()

    def _execute_down(self, until: int) -> None:
        """A killed host's round: drain every due event as a drop
        (packets -> host-down attribution at the event's recorded
        instant) or a silent discard (tasks/timers — its kernel state
        is frozen).  Event counting matches the engine twin
        (run_until's down branch) so sim-stats agree across paths."""
        q = self.queue
        while True:
            t = q.peek_time()
            if t is None or t >= until:
                break
            ev = q.pop()
            self._now = ev.time
            self.counters["events"] += 1
            if ev.kind == KIND_PACKET:
                self.trace_drop(ev.data, "host-down", at_time=ev.time)
        self._update_nt_slot()

    def _execute_native(self, until: int) -> None:
        """Round execution with the native plane: the engine runs whole
        batches of its own events (inbox packet arrivals + relay/TCP
        deadlines) in one C call, bounded by the Python heap's head key
        and the window end, under the one total order (time, kind, src,
        seq).  A batch breaks whenever an engine event called back into
        Python (a status change may have scheduled a task that now
        precedes the engine's next event), so the merged dispatch order
        stays bit-identical to the object path's single heap."""
        self.drain_inbox()
        q = self.queue
        heap = q._heap
        eng = self.plane.engine
        hid = self.id
        run_until = eng.run_until
        n_total = 0
        if self.down:
            # Dead plane host: engine-side events drain as drops inside
            # run_until's down branch; Python-side events drain here
            # (packets attribute host-down, tasks discard).  Drops
            # generate no new events, so one engine pass suffices.
            n, last = run_until(hid, until, 1, 0, 0, until)
            n_total += n
            if n and last > self._now:
                self._now = last
            while heap and heap[0][0] < until:
                ev = q.pop()
                self._now = ev.time
                n_total += 1
                if ev.kind == KIND_PACKET:
                    if type(ev.data) is int:
                        eng.deliver(hid, ev.data, ev.time)
                    else:
                        self.trace_drop(ev.data, "host-down",
                                        at_time=ev.time)
            self.counters["events"] += n_total
            self._update_nt_slot()
            return
        while True:
            if heap:
                lt, lk, lsrc, lseq = heap[0][:4]
            else:
                lt, lk, lsrc, lseq = until, 1, 0, 0
            n, last = run_until(hid, lt, lk, lsrc, lseq, until)
            if n:
                n_total += n
                if last > self._now:
                    self._now = last
                continue  # re-evaluate: a callback may have scheduled
            if not heap or heap[0][0] >= until:
                break
            ev = q.pop()
            self._now = ev.time
            n_total += 1
            data = ev.data
            if ev.kind == KIND_PACKET:
                # Mixed-plane only: a packet object from an object-path
                # host (engine-origin packets ride the engine inbox).
                if type(data) is int:
                    eng.deliver(hid, data, ev.time)
                else:
                    self.router.route_incoming_packet(self, data)
            else:
                data.execute(self)
        self.counters["events"] += n_total
        self._update_nt_slot()

    def _update_nt_slot(self) -> None:
        if self._nt_list is not None:
            t = self.next_event_time()
            if t is None:
                t = TIME_NEVER
            # Under the threaded CPU schedulers another host's execute
            # can deliver into our inbox concurrently; folding the
            # locked inbox minimum in keeps the slot from going stale-
            # high (losing an event until some later round).
            with self._inbox_lock:
                if self._inbox_min < t:
                    t = self._inbox_min
                self._nt_list[self.id] = t
                if self._py_work_arr is not None:
                    # Partition-flag recompute must share this lock: a
                    # concurrent deliverer sets the flag True under it,
                    # and an unlocked False store here could land last
                    # and strand the delivered event on the engine-only
                    # fast path.  A pinned host (managed processes)
                    # never recomputes to False.
                    self._py_work_arr[self.id] = \
                        bool(self.queue._heap) or bool(self._inbox) \
                        or self.py_pinned

    def next_event_time(self):
        t = self.queue.peek_time()
        if self.plane is not None:
            d = self.plane.engine.peek_next(self.id)
            if d is not None and (t is None or d[0] < t):
                return d[0]
        return t

    # ------------------------------------------------------------------
    # Packet plane wiring
    # ------------------------------------------------------------------

    def get_packet_device(self, dst_ip: int):
        """Where does a packet addressed to `dst_ip` go next?
        (host.rs:909-917)"""
        if dst_ip == LOCALHOST_IP:
            return self.lo
        if dst_ip == self.eth0.ip:
            return self.eth0
        return self.router

    def notify_router_has_packets(self) -> None:
        self.relay_inet_in.notify(self)

    def notify_interface_has_packets(self, iface) -> None:
        if iface is self.lo:
            self.relay_loopback.notify(self)
        else:
            self.relay_inet_out.notify(self)

    def send_packet(self, packet) -> None:
        """Cross-host exit point — the scheduler owns propagation."""
        self.counters["packets_sent"] += 1
        self._send_packet_fn(self, packet)

    def deliver_packet_event(self, event) -> None:
        """Cross-host entry point (any thread): enqueue into the inbox.
        The event's time is >= the current window end (propagation clamp),
        so the owner cannot need it before its next drain."""
        with self._inbox_lock:
            self._inbox.append(event)
            if event.time < self._inbox_min:
                self._inbox_min = event.time
            nt = self._nt_list
            if nt is not None and event.time < nt[self.id]:
                nt[self.id] = event.time
            if self._py_work_arr is not None:
                self._py_work_arr[self.id] = True

    # ------------------------------------------------------------------
    # Processes
    # ------------------------------------------------------------------

    def add_application(self, start_time_ns: int, spawn_fn) -> None:
        """Schedule a process spawn at its configured start time
        (host.rs:363-427)."""
        self.schedule_task_at(start_time_ns, TaskRef("process-spawn", spawn_fn))

    def register_process(self, process) -> int:
        pid = self._next_pid
        self._next_pid += 1
        self.processes[pid] = process
        return pid

    def processes_running(self) -> int:
        return sum(1 for p in self.processes.values() if not p.exited)

    # ------------------------------------------------------------------
    # Canonical packet trace (the determinism gate's byte-diff target)
    # ------------------------------------------------------------------

    def trace_packet(self, kind: int, packet, extra: str = "",
                     at_time: int | None = None) -> None:
        if not self.tracing_enabled:
            return
        proto = "tcp" if packet.protocol == PROTO_TCP else "udp"
        text = (f"{_TRACE_NAMES[kind]} {proto} "
                f"{format_ip(packet.src_ip)}:{packet.src_port}>"
                f"{format_ip(packet.dst_ip)}:{packet.dst_port} "
                f"len={len(packet.payload)} id={packet.src_host_id}.{packet.seq}"
                f"{' ' + extra if extra else ''}")
        t = self._now if at_time is None else at_time
        self.trace_entries.append(
            (t, kind, packet.src_host_id, packet.seq, text))

    def trace_drop(self, packet, reason: str,
                   at_time: int | None = None) -> None:
        """`at_time` lets the batched propagator record drops at the send
        instant after the round has moved on; canonical sorting makes the
        resulting trace identical to the scalar path's."""
        self.counters["packets_dropped"] += 1
        cause = TEL_BY_REASON.get(reason)
        if cause is not None:
            self.drop_causes[cause] += 1
        else:
            self.drop_unattributed += 1
        self.trace_packet(TRACE_DRP, packet, reason, at_time=at_time)

    def count_mark(self, cause: int) -> None:
        """One CE mark by this host's router queue, attributed to the
        MARK_* threshold leg that fired (router.route_incoming_packet
        passes this as the CoDel push's on_mark)."""
        self.mark_causes[cause] += 1

    def trace_snd(self, packet) -> None:
        self.trace_packet(TRACE_SND, packet)

    def trace_rcv(self, packet) -> None:
        self.counters["packets_recv"] += 1
        self.trace_packet(TRACE_RCV, packet)

    def merge_native_counters(self) -> None:
        """Fold the engine's packet counters into self.counters
        (incremental: safe to call from heartbeats and final stats)."""
        if self.plane is None:
            return
        sent, recv, dropped, ev = self.plane.engine.counters(self.id)
        ps, pr, pd, pe = self._native_merged
        self.counters["packets_sent"] += sent - ps
        self.counters["packets_recv"] += recv - pr
        self.counters["packets_dropped"] += dropped - pd
        # Events executed by the engine's batch path (run_hosts); the
        # Python wrapper path counts its own.
        self.counters["events"] += ev - pe
        self._native_merged = (sent, recv, dropped, ev)
        # Engine drop-cause counters (same delta discipline; the tuple
        # carries TEL_N causes + the unattributed tail).
        causes = self.plane.engine.drop_causes(self.id)
        prev = self._native_causes_merged
        for i in range(TEL_N):
            self.drop_causes[i] += causes[i] - prev[i]
        self.drop_unattributed += causes[TEL_N] - prev[TEL_N]
        self._native_causes_merged = tuple(causes)
        # ECN mark-cause counters (same delta discipline).
        marks = self.plane.engine.mark_causes(self.id)
        prev = self._native_marks_merged
        for i in range(MARK_N):
            self.mark_causes[i] += marks[i] - prev[i]
        self._native_marks_merged = tuple(marks)
        # Engine-app syscalls (counted C++-side at the exact points the
        # Python dispatch would) fold into the same histograms.
        app_sys = self.plane.engine.app_syscalls(self.id)
        if app_sys:
            prev = self._app_sys_merged
            total = 0
            for name, n in app_sys.items():
                delta = n - prev.get(name, 0)
                if delta:
                    self.syscall_counts[name] = \
                        self.syscall_counts.get(name, 0) + delta
                    total += delta
            self.counters["syscalls"] += total
            self._app_sys_merged = dict(app_sys)

    def set_tracing(self, enabled: bool) -> None:
        self.tracing_enabled = enabled
        if self.plane is not None:
            self.plane.engine.set_tracing(self.id, enabled)

    def trace_lines(self) -> list[str]:
        """Canonically sorted, scheduler-independent trace lines."""
        entries = self.trace_entries
        if self.plane is not None:
            entries = entries + self.plane.engine.trace_entries(self.id)
        out = []
        for time, kind, src, seq, text in sorted(entries):
            out.append(f"{time} {self.name} {text}")
        return out

    # ------------------------------------------------------------------
    # Checkpoint serialization (shadow_tpu/ckpt/, docs/CHECKPOINT.md)
    # ------------------------------------------------------------------

    # Manager-owned / unpicklable references a snapshot deliberately
    # drops; ckpt/restore._rewire re-attaches them on resume.
    _CKPT_SKIP = ("_inbox_lock", "_nt_list", "_py_work_arr",
                  "_send_packet_fn", "_send_native_fn", "plane", "dns",
                  "syscall_handler", "syscall_handler_native",
                  "sc_wall", "sc_log",
                  # run-local output path: snapshots must not embed the
                  # data directory (identical sims -> identical bytes)
                  "data_path",
                  # failure-containment plane + wall-only spawn knob:
                  # manager-owned / wall-side — restore rewires from
                  # the RESUMING config (docs/ROBUSTNESS.md)
                  "containment", "spawn_stagger_ns")

    def __getstate__(self):
        d = dict(self.__dict__)
        for k in Host._CKPT_SKIP:
            d.pop(k, None)
        if "lo" in d:
            # The relays hold pop-closures over the interfaces: strip
            # them to their mutable state; __setstate__ rebuilds the
            # closures and re-applies it.
            d["_relay_state"] = tuple(
                r.ckpt_state() for r in (self.relay_loopback,
                                         self.relay_inet_out,
                                         self.relay_inet_in))
            for k in ("relay_loopback", "relay_inet_out",
                      "relay_inet_in"):
                d.pop(k, None)
        return d

    def __setstate__(self, d):
        relay_state = d.pop("_relay_state", None)
        self.__dict__.update(d)
        self.__dict__.setdefault("py_pinned", False)
        self._inbox_lock = threading.Lock()
        self._nt_list = None
        self._py_work_arr = None
        self._send_packet_fn = None
        self._send_native_fn = None
        self.plane = None
        self.dns = None
        self.syscall_handler = None
        self.syscall_handler_native = None
        self.sc_wall = None
        self.sc_log = None
        self.data_path = None
        self.containment = None
        self.spawn_stagger_ns = 0
        if relay_state is not None:
            self._build_relays()
            for relay, state in zip((self.relay_loopback,
                                     self.relay_inet_out,
                                     self.relay_inet_in), relay_state):
                relay.ckpt_restore(state)
