"""AF_UNIX sockets, fully emulated (ref: socket/unix.rs, 2,419 LoC,
plus abstract_unix_ns.rs).

Unix sockets must be emulated, not passed native: a native blocking
read would park the real OS thread inside the kernel, stalling the
manager's event pump on wall-clock time — the same reason inet sockets
and pipes are simulated.  Transfers are host-local buffer moves with no
network latency, like the reference.

Namespace: per-host (`host.unix_ns`), holding both filesystem-style
paths and the Linux abstract namespace (leading NUL).  Filesystem bind
does NOT create a real directory entry — file I/O is native in our
split, but socket files only matter to other in-sim sockets, and a
phantom fs entry would leak across hosts.  An app stat()ing its own
socket file is the known divergence.

SCM_RIGHTS fd passing is modeled for both fd spaces: EMULATED fds ride
the message as objects and register into the receiver's table at
recvmsg (cross-process works because fd objects are manager-side);
NATIVE fds are pulled from the sender with pidfd_getfd and delivered
through the receiver's transfer socket (see managed.py _do_fdxfer),
preserving the shared open file description.  Stream ancillary
attaches at the sender's byte watermark and is delivered with the
read that reaches it.
"""

from __future__ import annotations

import errno

from shadow_tpu.host.status import (S_ACTIVE, S_CLOSED, S_READABLE,
                                    S_SOCKET_ALLOWING_CONNECT, S_WRITABLE)
from shadow_tpu.host.status import StatusOwner

BUF_MAX = 212_992  # net.core.wmem_default'ish


class UnixSocket(StatusOwner):
    """One AF_UNIX endpoint: stream or dgram, bindable, connectable.

    Stream data lives in the RECEIVER's `_recv_buf`; dgram in a
    datagram queue with source addresses.
    """

    def __init__(self, host, stream: bool):
        super().__init__()
        self.host = host
        self.stream = stream
        self.nonblocking = False
        self.bound_name: str | None = None   # "@name" for abstract
        self.peer: "UnixSocket | None" = None
        self.listening = False
        self._backlog = 0
        self._pending: list = []             # listener: accepted peers
        self._recv_buf = bytearray()         # stream bytes
        self._dgrams: list = []              # (data, src_name, anc)
        self._dgram_waiters: list = []       # senders parked on our queue
        # SCM_RIGHTS in flight: stream ancillary as (watermark, objs)
        # against the total-bytes counters; dgram ancillary rides the
        # datagram tuple.  take_ancillary() drains what a recvmsg
        # delivery reached.
        self._anc_stream: list = []
        self._rx_total = 0                   # bytes ever buffered
        self._rx_read = 0                    # bytes ever consumed
        self._last_anc: list = []
        self._eof = False
        self._status = S_ACTIVE | (0 if stream else S_WRITABLE)

    # -- address book --------------------------------------------------

    def bind(self, host, name: str) -> None:
        if self.bound_name is not None:
            raise OSError(errno.EINVAL, "already bound")
        ns = host.unix_ns
        if name in ns:
            raise OSError(errno.EADDRINUSE, name)
        ns[name] = self
        self.bound_name = name

    def listen(self, host, backlog: int) -> None:
        if not self.stream:
            raise OSError(errno.EOPNOTSUPP, "dgram listen")
        if self.bound_name is None:
            raise OSError(errno.EINVAL, "listen on unbound socket")
        self.listening = True
        self._backlog = max(1, backlog)
        self.adjust_status(host, S_SOCKET_ALLOWING_CONNECT, 0)

    # -- stream connection setup --------------------------------------

    def connect(self, host, name: str) -> None:
        if self.peer is not None:
            raise OSError(errno.EISCONN, "already connected")
        target = host.unix_ns.get(name)
        if target is None:
            raise OSError(errno.ECONNREFUSED
                          if self.stream else errno.ENOENT, name)
        if self.stream:
            if not target.listening:
                raise OSError(errno.ECONNREFUSED, name)
            if len(target._pending) >= target._backlog:
                # Blocking connect waits for accept-queue room; the
                # caller parks on the LISTENER's allowing-connect bit.
                target.adjust_status(host, 0, S_SOCKET_ALLOWING_CONNECT)
                err = BlockingIOError(errno.EAGAIN, "backlog full")
                err.listener = target
                raise err
            server = UnixSocket(host, stream=True)
            server.bound_name = target.bound_name
            server.peer = self
            self.peer = server
            server.adjust_status(host, S_WRITABLE, 0)
            self.adjust_status(host, S_WRITABLE, 0)
            target._pending.append(server)
            target.adjust_status(host, S_READABLE, 0)
        else:
            # Dgram connect just fixes the default destination.
            self.peer = target

    def accept(self, host) -> "UnixSocket":
        if not self.listening:
            raise OSError(errno.EINVAL, "not listening")
        if not self._pending:
            raise BlockingIOError(errno.EWOULDBLOCK, "no pending")
        child = self._pending.pop(0)
        if not self._pending:
            self.adjust_status(host, 0, S_READABLE)
        # Queue room again: wake blocked connect()ers.
        self.adjust_status(host, S_SOCKET_ALLOWING_CONNECT, 0)
        return child

    # -- data plane ----------------------------------------------------

    def sendto(self, host, data: bytes, dest_name: str | None,
               anc: list | None = None):
        if self.stream:
            peer = self.peer
            if peer is None:
                raise OSError(errno.ENOTCONN, "not connected")
            if peer.has_status(S_CLOSED) or peer._eof:
                raise OSError(errno.EPIPE, "peer closed")
            room = BUF_MAX - len(peer._recv_buf)
            if room <= 0:
                self.adjust_status(host, 0, S_WRITABLE)
                raise BlockingIOError(errno.EWOULDBLOCK, "buffer full")
            take = data[:room]
            if not take:
                # Zero-length stream send transfers nothing — including
                # ancillary fds (Linux queues no skb).
                if anc:
                    from shadow_tpu.host.descriptor import _decref
                    for obj in anc:
                        _decref(obj, host)
                return 0
            if anc:
                # Attach at the current watermark: delivered with the
                # read that reaches this byte position.
                peer._anc_stream.append((peer._rx_total, list(anc)))
            peer._recv_buf += take
            peer._rx_total += len(take)
            peer.adjust_status(host, S_READABLE, 0)
            if len(peer._recv_buf) >= BUF_MAX:
                self.adjust_status(host, 0, S_WRITABLE)
            return len(take)
        # dgram
        if dest_name is not None:
            target = host.unix_ns.get(dest_name)
            if target is None:
                raise OSError(errno.ENOENT, dest_name)
        else:
            target = self.peer
            if target is None:
                raise OSError(errno.ENOTCONN, "no destination")
        if target.has_status(S_CLOSED):
            raise OSError(errno.ECONNREFUSED, "peer closed")
        queued = sum(len(d) for d, _s, _a in target._dgrams)
        if queued + len(data) > BUF_MAX:
            # Park on our own WRITABLE bit; the receiver wakes us when
            # it drains (without this the permanently-set bit would
            # re-fire the blocked syscall forever at the same instant).
            self.adjust_status(host, 0, S_WRITABLE)
            if self not in target._dgram_waiters:
                target._dgram_waiters.append(self)
            raise BlockingIOError(errno.EWOULDBLOCK, "receiver full")
        target._dgrams.append((bytes(data), self.bound_name or "",
                               list(anc) if anc else []))
        target.adjust_status(host, S_READABLE, 0)
        return len(data)

    def recvfrom(self, host, bufsize: int, peek: bool = False):
        if self.stream:
            if not self._recv_buf:
                if self._eof or (self.peer is not None
                                 and self.peer.has_status(S_CLOSED)):
                    return b"", None
                raise BlockingIOError(errno.EWOULDBLOCK, "empty")
            if peek:
                return bytes(self._recv_buf[:bufsize]), None
            limit = bufsize
            ws = self._anc_stream
            if ws:
                # Linux never returns bytes spanning two SCM scopes: a
                # read stops before the first boundary (plain data
                # first), and a read that consumed a boundary stops
                # before the next one.
                first = ws[0][0]
                if self._rx_read < first:
                    limit = min(limit, first - self._rx_read)
                elif len(ws) > 1:
                    limit = min(limit, ws[1][0] - self._rx_read)
            out = bytes(self._recv_buf[:limit])
            del self._recv_buf[:limit]
            self._rx_read += len(out)
            while self._anc_stream and self._anc_stream[0][0] < \
                    self._rx_read:
                self._last_anc.extend(self._anc_stream.pop(0)[1])
            if not self._recv_buf and not self._eof:
                self.adjust_status(host, 0, S_READABLE)
            peer = self.peer
            if peer is not None and not peer.has_status(S_CLOSED):
                peer.adjust_status(host, S_WRITABLE, 0)
            return out, None
        if not self._dgrams:
            raise BlockingIOError(errno.EWOULDBLOCK, "empty")
        if peek:
            data, src, _anc = self._dgrams[0]
            return data[:bufsize], src
        data, src, anc = self._dgrams.pop(0)
        self._last_anc.extend(anc)
        if not self._dgrams:
            self.adjust_status(host, 0, S_READABLE)
        if self._dgram_waiters:
            waiters, self._dgram_waiters = self._dgram_waiters, []
            for w in waiters:
                if not w.has_status(S_CLOSED):
                    w.adjust_status(host, S_WRITABLE, 0)
        return data[:bufsize], src

    def take_ancillary(self) -> list:
        """Objects delivered by the reads since the last call —
        consumed by recvmsg; a plain recv discards them (like Linux
        closing unclaimed SCM_RIGHTS fds)."""
        out, self._last_anc = self._last_anc, []
        return out

    def next_read_has_native_fds(self) -> bool:
        """Would the next read surface NativeFdRef ancillary?  Lets
        recvmmsg stop a batch BEFORE consuming such a message (the
        fd-transfer dance patches one cmsg per syscall, so the message
        must head its own batch).  Stream case: ancillary surfaces only
        when the next read starts at/past its SCM-scope watermark (the
        read limiter stops earlier reads at the boundary)."""
        from shadow_tpu.host.descriptor import NativeFdRef
        if self.stream:
            ws = self._anc_stream
            if not ws or ws[0][0] > self._rx_read:
                return False
            return any(isinstance(o, NativeFdRef) for o in ws[0][1])
        if not self._dgrams:
            return False
        return any(isinstance(o, NativeFdRef) for o in self._dgrams[0][2])

    def bytes_available(self) -> int:
        if self.stream:
            return len(self._recv_buf)
        return len(self._dgrams[0][0]) if self._dgrams else 0

    def shutdown(self, host, how: str = "wr") -> None:
        peer = self.peer
        if how in ("wr", "rdwr") and peer is not None:
            peer._eof = True
            peer.adjust_status(host, S_READABLE, 0)
        if how in ("rd", "rdwr"):
            self._eof = True

    def close(self, host) -> None:
        if self.bound_name is not None and \
                host.unix_ns.get(self.bound_name) is self:
            del host.unix_ns[self.bound_name]
        # Release in-flight SCM_RIGHTS references (Linux closes fds
        # still riding a destroyed socket) — without this a carried
        # pipe end never reaches refcount 0 and its reader never sees
        # EOF.
        from shadow_tpu.host.descriptor import _decref
        pending = list(self._last_anc)
        for _w, objs in self._anc_stream:
            pending.extend(objs)
        for _d, _s, objs in self._dgrams:
            pending.extend(objs)
        self._last_anc = []
        self._anc_stream = []
        for obj in pending:
            _decref(obj, host)
        peer = self.peer
        if self.listening:
            # Wake connect()ers parked on backlog room; their retry
            # sees the dead listener and fails ECONNREFUSED.
            self.adjust_status(host, S_SOCKET_ALLOWING_CONNECT, 0)
        self.adjust_status(host, S_CLOSED,
                           S_ACTIVE | S_READABLE | S_WRITABLE |
                           S_SOCKET_ALLOWING_CONNECT)
        if peer is not None and self.stream:
            peer._eof = True
            # EOF is readable; writers notice EPIPE via the wake.
            peer.adjust_status(host, S_READABLE | S_WRITABLE, 0)
        from shadow_tpu.utils.object_counter import mark_dealloc
        for child in self._pending:
            # Never-accepted connections: tear down BOTH ends so the
            # client sees EOF/EPIPE instead of blocking forever.
            child._eof = True
            child.adjust_status(host, S_CLOSED, S_ACTIVE)
            mark_dealloc(child)
            client = child.peer
            if client is not None:
                client._eof = True
                client.adjust_status(host, S_READABLE | S_WRITABLE, 0)
        self._pending.clear()
        if self._dgram_waiters:
            # Parked senders retry and get ENOENT/ECONNREFUSED.
            waiters, self._dgram_waiters = self._dgram_waiters, []
            for w in waiters:
                w.adjust_status(host, S_WRITABLE, 0)


def unix_socketpair(host, stream: bool):
    """socketpair(AF_UNIX): two mutually-connected unnamed endpoints."""
    a = UnixSocket(host, stream)
    b = UnixSocket(host, stream)
    a.peer = b
    b.peer = a
    a._status |= S_WRITABLE
    b._status |= S_WRITABLE
    return a, b
