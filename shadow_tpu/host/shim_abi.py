"""Manager-side view of the shim IPC block (mirror of native/shim_ipc.h).

The manager maps the same file the shim maps and speaks the futex SPSC
protocol directly from Python via `ctypes` — x86-64's total store order
plus CPython's sequential execution give the release/acquire semantics
the two-word protocol needs, and the per-message futex syscalls dominate
the cost anyway.  (Ref: the simulator side of
src/lib/shadow-shim-helper-rs/src/ipc.rs.)

One block carries IPC_N_CHANS channel pairs: channel 0 is the process's
main thread, the rest are allocated as the process clones threads (the
reference shmallocs a fresh IPCData per ManagedThread,
managed_thread.rs:113).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import mmap
import os
import struct

# --- constants mirrored from native/shim_ipc.h ---------------------
MAGIC = 0x53545055
# v8: syscall service plane — svc_flags header word (OFF_SVC below)
# and the consumer-side FUTEX_WAKE dropped from both directions (the
# alternating protocol means no one ever waits for an EMPTY slot).
VERSION = 8
FILE_SIZE = 24576

N_CHANS = 64
CHANS_OFF = 576
CHAN_STRIDE = 320
CHAN_TO_SHADOW = 0
CHAN_TO_SHIM = 72
CHAN_UNAPPLIED = 2 * 72 + 8 * 16  # after clone_regs[15] + clone_chan_idx
# Shim-side SC_SHIM sequence counter (syscall observatory): locally-
# answered time syscalls since the last drain.  C twin: SC_CHAN_LOCAL_OFF
# in native/shim.c (static_assert-pinned to the struct; analysis pass 1
# diffs the two values).
CHAN_SC_LOCAL = 2 * 72 + 8 * 17
PATH_MAX = 160

SLOT_EMPTY = 0
SLOT_READY = 1
SLOT_CLOSED = 2

EV_NULL = 0
EV_START_REQ = 1
EV_SYSCALL = 2
EV_CLONE_DONE = 3
EV_SIGNAL_DONE = 4
EV_FORK_DONE = 5
EV_XFER_DONE = 6
EV_START_RES = 16
EV_SYSCALL_COMPLETE = 17
EV_SYSCALL_DO_NATIVE = 18
EV_CLONE_RES = 19
EV_SIGNAL = 20
EV_FORK_RES = 21
EV_SYSCALL_COMPLETE_FDXFER = 22

OFF_MAGIC = 0
OFF_VERSION = 4
OFF_SIM_TIME = 8
OFF_AUXV = 16
OFF_SIGSEGV = 32
OFF_SELF_PATH = 48
OFF_FORK_PATH = 48 + PATH_MAX
OFF_PRELOAD = 48 + 2 * PATH_MAX
# Syscall service plane (IPC v8): manager-written advisory flags the
# shim reads to pick spin-then-wait for responses.  C twin:
# SC_SVC_FLAGS_OFF in native/shim.c (static_assert-pinned to the
# struct; analysis pass 1 diffs the two values).
OFF_SVC = 48 + 3 * PATH_MAX
SVC_ACTIVE = 1  # SHIM_SVC_ACTIVE
SLOT_EV_OFF = 8
EV_STRUCT = struct.Struct("<II7q")  # kind, pad, num, args[6]

_SYS_futex = 202
FUTEX_WAIT = 0
FUTEX_WAKE = 1

_libc = ctypes.CDLL(None, use_errno=True)


class _Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


def _futex_wait(addr: int, expected: int, timeout_ns: int | None) -> int:
    """Returns 0 on wake/value-change, -1 with errno on timeout/EINTR."""
    if timeout_ns is None:
        ts = None
    else:
        ts = ctypes.byref(_Timespec(timeout_ns // 1_000_000_000,
                                    timeout_ns % 1_000_000_000))
    r = _libc.syscall(_SYS_futex, ctypes.c_void_p(addr), FUTEX_WAIT,
                      expected, ts, None, 0)
    return r


def _futex_wake(addr: int) -> None:
    _libc.syscall(_SYS_futex, ctypes.c_void_p(addr), FUTEX_WAKE, 1,
                  None, None, 0)


class ChannelClosed(Exception):
    """The peer marked the slot CLOSED (process died / torn down)."""


class ChannelTimeout(Exception):
    """recv timed out (used to poll for child death)."""


class Channel:
    """One thread's request/response slot pair inside an IpcBlock."""

    __slots__ = ("block", "index", "_to_shadow", "_to_shim", "_unapplied",
                 "_sc_local")

    def __init__(self, block: "IpcBlock", index: int):
        self.block = block
        self.index = index
        base = CHANS_OFF + index * CHAN_STRIDE
        self._to_shadow = base + CHAN_TO_SHADOW
        self._to_shim = base + CHAN_TO_SHIM
        self._unapplied = base + CHAN_UNAPPLIED
        self._sc_local = base + CHAN_SC_LOCAL

    def send_to_shim(self, kind: int, num: int = 0,
                     args: tuple = (0, 0, 0, 0, 0, 0)) -> None:
        blk = self.block
        off = self._to_shim
        # Slot must be EMPTY per the alternating protocol.
        EV_STRUCT.pack_into(blk._mm, off + SLOT_EV_OFF, kind, 0, num, *args)
        blk._store_u32(off, SLOT_READY)
        _futex_wake(blk._addr + off)

    def recv_from_shim(self, timeout_ns: int | None = None):
        """Block until the shim publishes an event; returns (kind, num,
        args).  Raises ChannelTimeout after `timeout_ns` so the caller
        can check for child death, ChannelClosed on CLOSED."""
        blk = self.block
        off = self._to_shadow
        while True:
            st = blk._load_u32(off)
            if st == SLOT_READY:
                kind, _pad, num, *args = EV_STRUCT.unpack_from(
                    blk._mm, off + SLOT_EV_OFF)
                # IPC v8: no wake after the EMPTY flip — the shim's
                # send asserts EMPTY instead of waiting for it, so the
                # wake was one wasted futex syscall per event.
                blk._store_u32(off, SLOT_EMPTY)
                return kind, num, args
            if st == SLOT_CLOSED:
                raise ChannelClosed
            r = _futex_wait(blk._addr + off, st, timeout_ns)
            if r != 0:
                err = ctypes.get_errno()
                import errno as _e
                if err == _e.ETIMEDOUT and timeout_ns is not None:
                    # Re-check once: the word may have flipped between
                    # the timeout and now.
                    if blk._load_u32(off) not in (SLOT_READY, SLOT_CLOSED):
                        raise ChannelTimeout
                # EAGAIN (value changed) / EINTR: loop and re-check.

    def take_unapplied_ns(self) -> int:
        """Drain the shim-accumulated native-I/O latency (safe while the
        shim is parked awaiting our response — the slot protocol orders
        the accesses)."""
        mm = self.block._mm
        (ns,) = struct.unpack_from("<Q", mm, self._unapplied)
        if ns:
            struct.pack_into("<Q", mm, self._unapplied, 0)
        return ns

    def take_local_count(self) -> int:
        """Drain the count of syscalls the shim answered locally (the
        time family; SC_SHIM disposition) since the last drain — same
        slot-protocol ordering argument as take_unapplied_ns."""
        mm = self.block._mm
        (n,) = struct.unpack_from("<Q", mm, self._sc_local)
        if n:
            struct.pack_into("<Q", mm, self._sc_local, 0)
        return n

    def mark_closed(self) -> None:
        """Wake the shim thread with CLOSED on both slots."""
        blk = self.block
        if blk.closed:
            return
        for off in (self._to_shadow, self._to_shim):
            blk._store_u32(off, SLOT_CLOSED)
            _futex_wake(blk._addr + off)


class IpcBlock:
    """One managed process's IPC block, backed by a /dev/shm file."""

    def __init__(self, path: str):
        self.path = path
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, FILE_SIZE)
            self._mm = mmap.mmap(fd, FILE_SIZE)
        finally:
            os.close(fd)
        self._addr = ctypes.addressof(
            ctypes.c_char.from_buffer(self._mm))
        struct.pack_into("<II", self._mm, 0, MAGIC, VERSION)
        self.closed = False
        self._chan_used = [False] * N_CHANS
        self._chan_used[0] = True  # main thread

    def channel(self, index: int) -> Channel:
        return Channel(self, index)

    def alloc_channel(self) -> int | None:
        """Reserve a channel index for a newly cloned thread."""
        for i, used in enumerate(self._chan_used):
            if not used:
                self._chan_used[i] = True
                return i
        return None

    def free_channel(self, index: int) -> None:
        self._chan_used[index] = False

    # -- raw words --------------------------------------------------

    def _load_u32(self, off: int) -> int:
        return struct.unpack_from("<I", self._mm, off)[0]

    def _store_u32(self, off: int, value: int) -> None:
        struct.pack_into("<I", self._mm, off, value)

    def set_sim_time(self, sim_ns: int) -> None:
        struct.pack_into("<Q", self._mm, OFF_SIM_TIME, sim_ns)

    def set_auxv_random(self, lo: int, hi: int) -> None:
        struct.pack_into("<QQ", self._mm, OFF_AUXV, lo, hi)

    def _write_cstr(self, off: int, value: str) -> None:
        data = value.encode()
        if len(data) >= PATH_MAX:
            raise ValueError(f"IPC path/value too long ({len(data)} >= "
                             f"{PATH_MAX}): {value!r}")
        self._mm[off:off + len(data) + 1] = data + b"\0"

    def set_self_path(self, path: str) -> None:
        self._write_cstr(OFF_SELF_PATH, path)

    def set_fork_path(self, path: str) -> None:
        self._write_cstr(OFF_FORK_PATH, path)

    def set_preload(self, value: str) -> None:
        self._write_cstr(OFF_PRELOAD, value)

    def set_sigsegv_action(self, handler: int, flags: int) -> None:
        """Publish the app's emulated SIGSEGV sigaction for the shim's
        chaining fault handler (the shim owns the native SIGSEGV slot
        for rdtsc emulation)."""
        struct.pack_into("<QQ", self._mm, OFF_SIGSEGV, handler, flags)

    def set_svc_flags(self, flags: int) -> None:
        """Advertise service-plane state to the shim (IPC v8): with
        SVC_ACTIVE set the shim spins briefly before parking in
        FUTEX_WAIT for a response.  Advisory only — byte identity
        never depends on it."""
        struct.pack_into("<I", self._mm, OFF_SVC, flags)

    # -- teardown ---------------------------------------------------

    def mark_closed(self) -> None:
        """Tear down: wake every thread with CLOSED on every slot."""
        for i in range(N_CHANS):
            self.channel(i).mark_closed()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        # Release the ctypes view before closing the mmap.
        self._addr = None
        import gc
        gc.collect()
        try:
            self._mm.close()
        except BufferError:
            pass  # a ctypes view still alive somewhere; the OS cleans up
        try:
            os.unlink(self.path)
        except OSError:
            pass
