"""Non-socket pollable descriptors: pipes, eventfd, timerfd.

Ref: src/main/host/descriptor/{pipe.rs,eventfd.rs,timerfd.rs} plus the
shared-buffer machinery pipes use.  All are StatusOwners so poll/epoll/
blocking conditions watch them uniformly.
"""

from __future__ import annotations

import errno

from shadow_tpu.core.event import TaskRef
from shadow_tpu.host.status import (S_ACTIVE, S_CLOSED, S_READABLE,
                                    S_WRITABLE, StatusOwner)

PIPE_CAPACITY = 65_536  # Linux default pipe buffer


class _PipeBuffer:
    """The shared byte channel between the two pipe ends."""

    __slots__ = ("data", "capacity", "reader", "writer")

    def __init__(self, capacity: int = PIPE_CAPACITY):
        self.data = bytearray()
        self.capacity = capacity
        self.reader = None
        self.writer = None


class PipeEnd(StatusOwner):
    """One end of a unidirectional pipe (pipe.rs)."""

    def __init__(self, buffer: _PipeBuffer, is_writer: bool):
        super().__init__()
        self.buf = buffer
        self.is_writer = is_writer
        self.nonblocking = False
        if is_writer:
            buffer.writer = self
            self._status = S_ACTIVE | S_WRITABLE
        else:
            buffer.reader = self
            self._status = S_ACTIVE

    # -- writer side --------------------------------------------------

    def write_bytes(self, host, data: bytes) -> int:
        if not self.is_writer:
            raise OSError(errno.EBADF, "read end of pipe")
        buf = self.buf
        if buf.reader is None or buf.reader.has_status(S_CLOSED):
            raise OSError(errno.EPIPE, "broken pipe")
        room = buf.capacity - len(buf.data)
        if room <= 0:
            self.adjust_status(host, 0, S_WRITABLE)
            raise BlockingIOError(errno.EWOULDBLOCK, "pipe full")
        take = data[:room]
        buf.data += take
        if buf.reader is not None:
            buf.reader.adjust_status(host, S_READABLE, 0)
        if len(buf.data) >= buf.capacity:
            self.adjust_status(host, 0, S_WRITABLE)
        return len(take)

    # -- reader side --------------------------------------------------

    def read_bytes(self, host, n: int) -> bytes:
        if self.is_writer:
            raise OSError(errno.EBADF, "write end of pipe")
        buf = self.buf
        if not buf.data:
            if buf.writer is None or buf.writer.has_status(S_CLOSED):
                return b""  # EOF
            raise BlockingIOError(errno.EWOULDBLOCK, "pipe empty")
        out = bytes(buf.data[:n])
        del buf.data[:n]
        if not buf.data:
            self.adjust_status(host, 0, S_READABLE)
        if buf.writer is not None:
            buf.writer.adjust_status(host, S_WRITABLE, 0)
        return out

    def bytes_available(self) -> int:
        return len(self.buf.data) if not self.is_writer else 0

    def close(self, host) -> None:
        self.adjust_status(host, S_CLOSED,
                           S_ACTIVE | S_READABLE | S_WRITABLE)
        buf = self.buf
        if self.is_writer:
            buf.writer = None
            if buf.reader is not None:
                # Readers see EOF: readable-with-no-data (read returns 0).
                buf.reader.adjust_status(host, S_READABLE, 0)
        else:
            buf.reader = None
            if buf.writer is not None:
                # Writers get EPIPE; wake them via WRITABLE.
                buf.writer.adjust_status(host, S_WRITABLE, 0)


def make_pipe(capacity: int = PIPE_CAPACITY):
    buf = _PipeBuffer(capacity)
    return PipeEnd(buf, is_writer=False), PipeEnd(buf, is_writer=True)


class EventFd(StatusOwner):
    """eventfd(2): a 64-bit kernel counter (eventfd.rs)."""

    def __init__(self, initval: int = 0, semaphore: bool = False):
        super().__init__()
        self.counter = initval
        self.semaphore = semaphore
        self.nonblocking = False
        self._status = S_ACTIVE | S_WRITABLE | (S_READABLE if initval else 0)

    def read_value(self, host) -> int:
        if self.counter == 0:
            raise BlockingIOError(errno.EWOULDBLOCK, "eventfd zero")
        if self.semaphore:
            value, self.counter = 1, self.counter - 1
        else:
            value, self.counter = self.counter, 0
        if self.counter == 0:
            self.adjust_status(host, 0, S_READABLE)
        self.adjust_status(host, S_WRITABLE, 0)
        return value

    def write_value(self, host, value: int) -> None:
        if value >= (1 << 64) - 1:
            raise OSError(errno.EINVAL, "eventfd overflow value")
        if self.counter + value >= (1 << 64) - 1:
            self.adjust_status(host, 0, S_WRITABLE)
            raise BlockingIOError(errno.EWOULDBLOCK, "eventfd would overflow")
        self.counter += value
        if self.counter:
            self.adjust_status(host, S_READABLE, 0)

    def close(self, host) -> None:
        self.adjust_status(host, S_CLOSED,
                           S_ACTIVE | S_READABLE | S_WRITABLE)


class TimerFd(StatusOwner):
    """timerfd(2): expiration counter driven by the event queue
    (timerfd.rs + host/timer.rs)."""

    def __init__(self):
        super().__init__()
        self.nonblocking = False
        self.expirations = 0
        self._interval_ns = 0
        self._next_expire_ns = None  # absolute sim time, None = disarmed
        self._generation = 0  # revokes stale expiry tasks
        self._status = S_ACTIVE

    def arm(self, host, first_ns: int, interval_ns: int,
            absolute: bool) -> None:
        """first_ns==0 disarms (timerfd_settime semantics)."""
        self._generation += 1
        self.expirations = 0
        self.adjust_status(host, 0, S_READABLE)
        if first_ns == 0:
            self._next_expire_ns = None
            self._interval_ns = 0
            return
        when = first_ns if absolute else host.now() + first_ns
        # An absolute time already in the past fires immediately.
        when = max(when, host.now())
        self._next_expire_ns = when
        self._interval_ns = interval_ns
        self._schedule(host)

    def disarm_remaining(self):
        """(it_value, it_interval) remaining, for timerfd_gettime."""
        return self._next_expire_ns, self._interval_ns

    def _schedule(self, host) -> None:
        gen = self._generation
        when = self._next_expire_ns

        def fire(h):
            if gen != self._generation or self._next_expire_ns != when:
                return
            self.expirations += 1
            if self._interval_ns > 0:
                self._next_expire_ns = when + self._interval_ns
                self._schedule(h)
            else:
                self._next_expire_ns = None
            self.adjust_status(h, S_READABLE, 0)

        host.schedule_task_at(when, TaskRef("timerfd-expire", fire))

    def read_expirations(self, host) -> int:
        if self.expirations == 0:
            raise BlockingIOError(errno.EWOULDBLOCK, "timer not expired")
        n, self.expirations = self.expirations, 0
        self.adjust_status(host, 0, S_READABLE)
        return n

    def close(self, host) -> None:
        self._generation += 1
        self.adjust_status(host, S_CLOSED, S_ACTIVE | S_READABLE)


class SignalFd(StatusOwner):
    """signalfd(2): queued signals read as signalfd_siginfo records.

    Scope model (one approximation, chosen to be safe): each SignalFd
    serves exactly ONE process (fork clones the object into the child,
    diverging from the kernel's shared description only for post-fork
    mask updates).  Readiness tracks the process's SHARED pending queue
    only; a read drains the shared queue plus the reading thread's own
    private queue.  A tgkill-directed blocked signal therefore never
    shows as poll-readable (the kernel shows it readable to that one
    thread) — the conservative miss, preferred over either cross-thread
    signal stealing or a shared status word asserting readability the
    blocked reader cannot drain (a same-instant wake livelock).
    """

    def __init__(self, process, mask: int):
        super().__init__()
        self.process = process
        self.mask = mask
        self.nonblocking = False
        self._status = S_ACTIVE
        process.signal_fds.append(self)

    def clone_for(self, process) -> "SignalFd":
        """fork: the child gets its own view bound to itself."""
        child = SignalFd(process, self.mask)
        child.nonblocking = self.nonblocking
        return child

    def _shared_pending(self):
        from shadow_tpu.host import signals as S
        return sorted(s for s in self.process.signals.pending_process
                      if self.mask & S.bit(s))

    def refresh(self, host) -> None:
        if self._shared_pending():
            self.adjust_status(host, S_READABLE, 0)
        else:
            self.adjust_status(host, 0, S_READABLE)

    def read_infos(self, host, process, thread, max_records: int):
        import struct as _struct
        from shadow_tpu.host import signals as S
        pend = set(self._shared_pending())
        tpend = getattr(thread, "sig_pending", set())
        pend |= {s for s in tpend if self.mask & S.bit(s)}
        matched = sorted(pend)[:max_records]
        if not matched:
            raise BlockingIOError(11, "no signals pending")
        out = bytearray()
        for signo in matched:
            self.process.signals.pending_process.discard(signo)
            tpend.discard(signo)
            code, pid, status = self.process.signals.take_info(signo)
            # signalfd_siginfo: ssi_signo u32@0, ssi_errno i32@4,
            # ssi_code i32@8, ssi_pid u32@12, ssi_uid u32@16,
            # ssi_fd i32@20, ssi_tid u32@24, ssi_band u32@28,
            # ssi_overrun u32@32, ssi_trapno u32@36, ssi_status i32@40.
            out += _struct.pack("<IiiII", signo, 0, code, pid & 0xFFFFFFFF,
                                0) + b"\0" * 20 + \
                _struct.pack("<i", status) + b"\0" * 84
        self.process.refresh_signal_fds(host)
        return bytes(out)

    def close(self, host) -> None:
        if self in self.process.signal_fds:
            self.process.signal_fds.remove(self)
        self.adjust_status(host, S_CLOSED,
                           S_ACTIVE | S_READABLE | S_WRITABLE)
