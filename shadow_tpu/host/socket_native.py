"""Native-plane socket proxies.

Thin Python faces over sockets that live inside the C++ data-plane
engine (native/netplane.cpp).  Each proxy mirrors the API of its
object-path twin (host/socket_tcp.py TcpSocket / host/socket_udp.py
UdpSocket) toward the syscall layer: same methods, same exceptions,
same `local`/`peer`/`nonblocking` attributes, same StatusOwner
behavior — but every data-plane operation is one C call.

Status bits are pushed FROM the engine via the plane callback (the
engine's adjust_status twin fires on every effective change), so
`self._status` mirrors the engine mask without polling, and listeners
(conditions, epoll) fire at exactly the instants the object path fires
them.

The classes are deliberately named `TcpSocket`/`UdpSocket`: the object
counter keys lifecycle accounting by type name, and sim-stats must not
depend on which plane a scheduler uses.
"""

from __future__ import annotations

import errno

from shadow_tpu.host.status import (S_ACTIVE, S_CLOSED, S_READABLE,
                                    S_WRITABLE, StatusOwner)
from shadow_tpu.net import packet as pkt

_ERR_MSG = {
    errno.EISCONN: "already connected",
    errno.ENOTCONN: "not connected",
    errno.ECONNRESET: "connection reset",
    errno.ETIMEDOUT: "connection timed out",
    errno.ECONNREFUSED: "connection refused",
    errno.EADDRINUSE: "address already in use",
    errno.EADDRNOTAVAIL: "cannot bind non-local address",
    errno.EPIPE: "not established",
    errno.EINVAL: "invalid operation",
    errno.EMSGSIZE: "datagram too large",
    errno.EDESTADDRREQ: "no destination",
    errno.EOPNOTSUPP: "operation not supported",
    errno.EALREADY: "connect in progress",
    errno.EINPROGRESS: "connect started",
}


class _ConnView:
    """getsockopt's window into the autotuned connection buffers."""
    __slots__ = ("send_buf_max", "recv_buf_max")

    def __init__(self, send_buf_max: int, recv_buf_max: int):
        self.send_buf_max = send_buf_max
        self.recv_buf_max = recv_buf_max


def _raise(code: int):
    e = -code if code < 0 else code
    if e in (errno.EAGAIN, errno.EWOULDBLOCK):
        raise BlockingIOError(errno.EWOULDBLOCK, "would block")
    raise OSError(e, _ERR_MSG.get(e, "socket error"))


class _NativeSocket(StatusOwner):
    """Shared proxy behavior: status mirroring + address caching."""

    def __init__(self, host, plane, tok: int, initial_status: int):
        super().__init__()
        self.plane = plane
        self.tok = tok
        self.local = None
        self.peer = None
        self.nonblocking = False
        self._status = initial_status
        host._nsocks[tok] = self

    @property
    def reuseaddr(self) -> bool:
        return getattr(self, "_reuseaddr", False)

    @reuseaddr.setter
    def reuseaddr(self, v: bool) -> None:
        self._reuseaddr = bool(v)
        self.plane.engine.sock_set(self.tok, "reuseaddr", 1 if v else 0)

    # Engine-pushed status change (plane callback CB_STATUS).
    def apply_status(self, host, set_mask: int, clear_mask: int) -> None:
        self.adjust_status(host, set_mask, clear_mask)

    def bytes_available(self) -> int:
        """FIONREAD/SIOCINQ (glibc's resolver sizes its second DNS read
        with this — zero here breaks name resolution)."""
        return self.plane.engine.sock_inq(self.tok)

    def _refresh_addr(self) -> None:
        (hl, lip, lport), (hp_, pip, pport) = self.plane.engine.sock_addr(
            self.tok)
        self.local = (lip, lport) if hl else None
        self.peer = (pip, pport) if hp_ else None


class TcpSocket(_NativeSocket):
    """Native-plane TCP socket proxy (twin: host/socket_tcp.py)."""

    def __init__(self, host, send_buf: int, recv_buf: int,
                 send_autotune: bool = True, recv_autotune: bool = True,
                 _tok: int | None = None):
        plane = host.plane
        if _tok is None:
            _tok = plane.engine.tcp_socket(host.id, send_buf, recv_buf,
                                           send_autotune, recv_autotune)
            status = S_ACTIVE
        else:
            status = plane.engine.sock_status(_tok)  # accept-queue child
        super().__init__(host, plane, _tok, status)
        self.protocol = pkt.PROTO_TCP
        self._nodelay = False
        self.listening = False  # SO_ACCEPTCONN mirror

    @property
    def nodelay(self) -> bool:
        return self._nodelay

    @nodelay.setter
    def nodelay(self, v: bool) -> None:
        self._nodelay = bool(v)
        # Flag-only set (no clock in hand): engine defers the Nagle
        # flush; setsockopt goes through set_nodelay below instead.
        self.plane.engine.tcp_set_nodelay(self.tok, 1 if v else 0, -1)

    def set_nodelay(self, host, v: bool) -> None:
        """setsockopt(TCP_NODELAY): Linux flushes Nagle-held data on
        enable — the engine runs the push_data + flush at now."""
        self._nodelay = bool(v)
        self.plane.engine.tcp_set_nodelay(self.tok, 1 if v else 0,
                                          host.now())

    @property
    def conn(self):
        """Buffer-sizing view for getsockopt parity with the object
        path's conn (autotuned SO_SNDBUF/SO_RCVBUF); None before
        connect/accept, like the twin."""
        bufs = self.plane.engine.tcp_bufs(self.tok)
        if bufs is None:
            return None
        return _ConnView(bufs[0], bufs[1])

    def bind(self, host, ip: int, port: int) -> None:
        r = self.plane.engine.sock_bind(self.tok, ip, port)
        if r < 0:
            _raise(r)
        self.local = (ip, r)

    def listen(self, host, backlog: int = 128) -> None:
        r = self.plane.engine.tcp_listen(self.tok, backlog)
        if r == -errno.EISCONN:
            raise OSError(errno.EISCONN, "already connected")
        if r < 0:
            raise OSError(errno.EINVAL, "listen before bind")
        self.listening = True

    def connect(self, host, ip: int, port: int):
        from shadow_tpu.host.condition import SyscallCondition
        from shadow_tpu.native.plane import R_BLOCK
        if self.nonblocking:
            self.plane.engine.sock_set(self.tok, "nonblocking", 1)
        r = self.plane.engine.tcp_connect(self.tok, ip, port, host.now())
        self._refresh_addr()
        if r == 0:
            return 0
        if r == R_BLOCK:
            return SyscallCondition(file=self, mask=S_WRITABLE | S_CLOSED)
        _raise(r)

    def accept(self, host):
        r = self.plane.engine.tcp_accept(self.tok, host.now())
        if r < 0:
            _raise(r)
        child = host._nsocks[r]
        child._refresh_addr()
        return child

    def sendto(self, host, data: bytes, dst=None) -> int:
        r = self.plane.engine.tcp_sendto(self.tok, bytes(data), host.now())
        if r < 0:
            _raise(r)
        return r

    def recv(self, host, bufsize: int, peek: bool = False) -> bytes:
        r = self.plane.engine.tcp_recv(self.tok, bufsize, peek, host.now())
        if isinstance(r, int):
            _raise(r)
        return r

    def recvfrom(self, host, bufsize: int, peek: bool = False):
        return self.recv(host, bufsize, peek=peek), self.peer

    def shutdown(self, host, how: str = "wr") -> None:
        if "w" in how:
            self.plane.engine.tcp_shutdown(self.tok, host.now())

    def close(self, host) -> None:
        self.plane.engine.sock_close(self.tok, host.now())
        # Drop the registry entry: post-close engine transitions (e.g.
        # TIME_WAIT expiry) find no proxy, which is fine — the app-facing
        # S_CLOSED was already applied during the close call itself.
        host._nsocks.pop(self.tok, None)

    def tcp_info(self):
        """(state, error, srtt, cwnd, rto, rtx_count, sacked_skips,
        eff_mss) — diagnostics parity with the object path's conn."""
        return self.plane.engine.tcp_info(self.tok)


class UdpSocket(_NativeSocket):
    """Native-plane UDP socket proxy (twin: host/socket_udp.py)."""

    def __init__(self, host, send_buf: int, recv_buf: int):
        plane = host.plane
        tok = plane.engine.udp_socket(host.id, send_buf, recv_buf)
        super().__init__(host, plane, tok, S_ACTIVE | S_WRITABLE)
        self.protocol = pkt.PROTO_UDP

    def bind(self, host, ip: int, port: int) -> None:
        r = self.plane.engine.sock_bind(self.tok, ip, port)
        if r < 0:
            _raise(r)
        self.local = (ip, r)

    def connect(self, host, ip: int, port: int) -> None:
        if self.local is None:
            self.bind(host, 0, 0)
        self.peer = (ip, port)
        # Mirror into the engine for the connected-filter on receive.
        self.plane.engine.udp_connect(self.tok, ip, port)

    def sendto(self, host, data: bytes, dst) -> int:
        if dst is None:
            has_dst, dst_ip, dst_port = False, 0, 0
        else:
            has_dst, (dst_ip, dst_port) = True, dst
        r = self.plane.engine.udp_sendto(self.tok, bytes(data), has_dst,
                                         dst_ip, dst_port, host.now())
        if r < 0:
            _raise(r)
        self._refresh_addr()
        return r

    def recvfrom(self, host, bufsize: int, peek: bool = False):
        r = self.plane.engine.udp_recvfrom(self.tok, bufsize, peek)
        if isinstance(r, int):
            _raise(r)
        data, src_ip, src_port = r
        return data, (src_ip, src_port)

    def push_reply(self, host, payload: bytes, src_ip: int,
                   src_port: int) -> None:
        """dns_wire answer path: a crafted datagram straight into the
        receive queue (twin: push_in_packet of a locally-built packet)."""
        self.plane.engine.udp_push_reply(self.tok, payload, src_ip,
                                         src_port, host.now())

    def close(self, host) -> None:
        self.plane.engine.sock_close(self.tok, host.now())
        host._nsocks.pop(self.tok, None)
