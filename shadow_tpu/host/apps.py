"""Internal application registry.

The reference points host configs at real binaries (tgen, iperf, tor);
until the interposition backend lands, configs name *internal apps* —
Python generators driven through the same syscall seam (process.py).
`path: udp-sink` in YAML resolves here.

Apps yield syscall tuples and receive results; OSErrors raise at the
yield point. They are deliberately written like the C apps they stand in
for: sockets, blocking calls, no access to simulator internals.
"""

from __future__ import annotations

APP_REGISTRY: dict = {}


def app(name: str):
    def register(fn):
        APP_REGISTRY[name] = fn
        return fn
    return register


def lookup(path: str):
    return APP_REGISTRY.get(path)


# ---------------------------------------------------------------------------
# UDP workloads (tgen-style file transfer / flood / sink)
# ---------------------------------------------------------------------------

@app("udp-flood")
def udp_flood(process, argv):
    """udp-flood <dst> <port> <count> <size> [interval_ns]"""
    dst, port, count, size = argv[0], int(argv[1]), int(argv[2]), int(argv[3])
    interval = int(argv[4]) if len(argv) > 4 else 0
    fd = yield ("socket", "udp")
    dst_ip = yield ("resolve", dst)
    payload = b"x" * size
    sent = 0
    for i in range(count):
        yield ("sendto", fd, payload, (dst_ip, port))
        sent += size
        if interval > 0:
            yield ("nanosleep", interval)
    yield ("write", 1, f"sent {count} datagrams {sent} bytes\n")
    yield ("close", fd)
    return 0


@app("udp-sink")
def udp_sink(process, argv):
    """udp-sink <port> [expected_bytes] — exits 0 once expected bytes seen;
    runs forever without the argument (stopped by sim end)."""
    port = int(argv[0])
    expect = int(argv[1]) if len(argv) > 1 else None
    fd = yield ("socket", "udp")
    yield ("bind", fd, (0, port))
    got = 0
    n = 0
    while expect is None or got < expect:
        data, src = yield ("recvfrom", fd, 65536)
        got += len(data)
        n += 1
    t = yield ("sim_time",)
    yield ("write", 1, f"received {n} datagrams {got} bytes t={t}\n")
    yield ("close", fd)
    return 0


@app("udp-echo-server")
def udp_echo_server(process, argv):
    port = int(argv[0])
    fd = yield ("socket", "udp")
    yield ("bind", fd, (0, port))
    while True:
        data, src = yield ("recvfrom", fd, 65536)
        yield ("sendto", fd, data, src)


@app("udp-pinger")
def udp_pinger(process, argv):
    """udp-pinger <dst> <port> <count> — RTT measurement over UDP echo."""
    dst, port, count = argv[0], int(argv[1]), int(argv[2])
    fd = yield ("socket", "udp")
    dst_ip = yield ("resolve", dst)
    for i in range(count):
        t0 = yield ("sim_time",)
        yield ("sendto", fd, b"ping%d" % i, (dst_ip, port))
        data, src = yield ("recvfrom", fd, 65536)
        t1 = yield ("sim_time",)
        yield ("write", 1, f"rtt={t1 - t0}\n")
    yield ("close", fd)
    return 0


@app("tgen-server")
def tgen_server(process, argv):
    """tgen-server <port> — serves: each connection sends a line
    'GET <nbytes>', receives that many bytes back, then EOF. The
    tgen-equivalent file-transfer server (reference test workloads use
    the real tgen binary the same way)."""
    port = int(argv[0])
    fd = yield ("socket", "tcp")
    yield ("bind", fd, (0, port))
    yield ("listen", fd, 64)

    def serve(conn_fd):
        def handler():
            req = b""
            while not req.endswith(b"\n"):
                chunk = yield ("recv", conn_fd, 4096)
                if chunk == b"":
                    yield ("close", conn_fd)
                    return
                req += chunk
            n = int(req.decode().split()[1])
            payload = b"D" * 65536
            sent = 0
            while sent < n:
                take = min(65536, n - sent)
                sent += yield ("send", conn_fd, payload[:take])
            yield ("shutdown", conn_fd, "wr")
            # Drain until the client closes, then release the fd.
            while (yield ("recv", conn_fd, 4096)) != b"":
                pass
            yield ("close", conn_fd)
        return handler

    while True:
        conn_fd, peer = yield ("accept", fd)
        yield ("spawn_thread", serve(conn_fd))


@app("tgen-client")
def tgen_client(process, argv):
    """tgen-client <server> <port> <nbytes> [count] — performs `count`
    sequential downloads of nbytes each and reports completion times."""
    server, port, nbytes = argv[0], int(argv[1]), int(argv[2])
    count = int(argv[3]) if len(argv) > 3 else 1
    ip = yield ("resolve", server)
    for i in range(count):
        t0 = yield ("sim_time",)
        fd = yield ("socket", "tcp")
        yield ("connect", fd, (ip, port))
        yield ("send", fd, f"GET {nbytes}\n".encode())
        got = 0
        while got < nbytes:
            chunk = yield ("recv", fd, 1 << 16)
            if chunk == b"":
                break
            got += len(chunk)
        yield ("close", fd)
        t1 = yield ("sim_time",)
        ok = "ok" if got == nbytes else f"SHORT {got}"
        yield ("write", 1, f"transfer {i} {ok} bytes={got} ns={t1 - t0}\n")
    return 0


@app("phold")
def phold(process, argv):
    """phold <port> <my_index> <n_init> <mean_delay_ns> <peer...> — the
    classic PHOLD PDES benchmark (ref: src/test/phold): each host seeds
    `n_init` messages; every received message triggers one new message
    to a pseudo-random peer after a pseudo-exponential delay.  Runs
    until the simulation ends (expected_final_state: running).  All
    randomness is a per-host deterministic LCG, so traces are
    byte-identical across schedulers and runs."""
    port, my_index, n_init = int(argv[0]), int(argv[1]), int(argv[2])
    mean_delay = int(argv[3])
    peers = argv[4:]
    if not peers:
        yield ("write", 2, "phold: no peers configured\n")
        return 1

    state = [(my_index * 2654435761 + 12345) & 0xFFFFFFFF]

    def rnd() -> int:
        state[0] = (state[0] * 1664525 + 1013904223) & 0xFFFFFFFF
        return state[0]

    def exp_delay() -> int:
        # Pseudo-exponential via summed uniforms (integer-only).
        u = (rnd() % 1000) + (rnd() % 1000) + 1
        return max(1, (u * mean_delay) // 1000)

    fd = yield ("socket", "udp")
    yield ("bind", fd, (0, port))
    ips = []
    for peer in peers:
        ip = yield ("resolve", peer)
        ips.append(ip)

    def fire():
        yield ("nanosleep", exp_delay())
        yield ("sendto", fd, b"phold", (ips[rnd() % len(ips)], port))

    def seeder():
        for _ in range(n_init):
            yield from fire()

    yield ("spawn_thread", seeder)
    n = 0
    while True:
        _data, _src = yield ("recvfrom", fd, 64)
        n += 1
        yield from fire()


@app("udp-mesh")
def udp_mesh(process, argv):
    """udp-mesh <port> <count> <size> <peer1> <peer2> ... — every host
    floods every peer while sinking its own port; the 100-host benchmark
    workload (BASELINE config 2)."""
    port, count, size = int(argv[0]), int(argv[1]), int(argv[2])
    peers = argv[3:]
    fd = yield ("socket", "udp")
    yield ("bind", fd, (0, port))

    def sender():
        payload = b"m" * size
        ips = []
        for peer in peers:
            ip = yield ("resolve", peer)
            ips.append(ip)
        for i in range(count):
            for ip in ips:
                yield ("sendto", fd, payload, (ip, port))
        yield ("write", 1, f"mesh sent {count * len(peers)}\n")

    yield ("spawn_thread", sender)
    expect = count * len(peers) * size
    got = 0
    while got < expect:
        data, src = yield ("recvfrom", fd, 65536)
        got += len(data)
    yield ("write", 1, f"mesh received {got} bytes\n")
    return 0


@app("http-server")
def http_server(process, argv):
    """http-server <port> [nbytes] — minimal HTTP/1.1 server for the
    real-app gating tests (ref: examples/apps/{curl,wget2} run real
    clients against an in-sim server the same way).  Serves a fixed
    'X'*nbytes body with Content-Length and closes the connection."""
    port = int(argv[0])
    nbytes = int(argv[1]) if len(argv) > 1 else 1024
    fd = yield ("socket", "tcp")
    yield ("bind", fd, (0, port))
    yield ("listen", fd, 64)

    def serve(conn_fd):
        def handler():
            req = b""
            while b"\r\n\r\n" not in req and b"\n\n" not in req:
                chunk = yield ("recv", conn_fd, 4096)
                if chunk == b"":
                    yield ("close", conn_fd)
                    return
                req += chunk
            line = req.split(b"\r\n", 1)[0].decode(errors="replace")
            yield ("write", 1, f"request: {line}\n")
            body = b"X" * nbytes
            head = (f"HTTP/1.1 200 OK\r\n"
                    f"Content-Type: text/plain\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    f"Connection: close\r\n\r\n").encode()
            data = head + body
            sent = 0
            while sent < len(data):
                sent += yield ("send", conn_fd, data[sent:sent + 65536])
            yield ("shutdown", conn_fd, "wr")
            while (yield ("recv", conn_fd, 4096)) != b"":
                pass
            yield ("close", conn_fd)
        return handler

    while True:
        conn_fd, peer = yield ("accept", fd)
        yield ("spawn_thread", serve(conn_fd))
