"""Linux x86-64 ABI syscall dispatch for managed (real-binary) processes.

The manager-side half of the reference's ~170-entry dispatch table
(src/main/host/syscall/handler/mod.rs:335-642 + the per-family handlers
in handler/*.rs), re-targeted at our simulated kernel objects.  Calls
arrive as raw (number, 6 registers); results use the same triad the
internal-app handler uses, plus "native":

  ("done", rv) | ("error", OSError) | ("block", condition)
  | ("native",)  — execute in the child through the trampoline
  | ("exit", code)

Fd-space policy (differs from the reference, which virtualizes every
fd): descriptors created by the simulated kernel live at EMU_FD_BASE
and above; anything below routes to the native kernel via DO_NATIVE.
File I/O therefore stays native (real fs inside the child), while
sockets, pipes, eventfds, timerfds, epoll, time and randomness are
simulated.  The base is set low enough that select(2)'s fd_set covers
emulated fds, high enough that native fds (lowest-free allocation)
rarely collide; a collision aborts the process rather than
misbehaving silently.
"""

from __future__ import annotations

import ctypes
import errno
import os as _os
import struct

from shadow_tpu.core import simtime
from shadow_tpu.host.condition import MultiSyscallCondition, SyscallCondition
from shadow_tpu.host.epoll import (EPOLL_CTL_ADD, EPOLL_CTL_DEL,
                                   EPOLL_CTL_MOD, EpollFile)
from shadow_tpu.host.files import EventFd, PipeEnd, TimerFd, make_pipe
from shadow_tpu.host.socket_netlink import NetlinkSocket
from shadow_tpu.host.socket_udp import UdpSocket
from shadow_tpu.host.socket_unix import UnixSocket, unix_socketpair
from shadow_tpu.host.status import (S_CLOSED, S_ERROR, S_READABLE,
                                    S_SOCKET_ALLOWING_CONNECT, S_WRITABLE)

EMU_FD_BASE = 400  # leaves room for select() fd_sets (FD_SETSIZE=1024)
# Upper edge of the emulated window: the shim relocates native fds
# that land in [EMU_FD_BASE, EMU_FD_LIMIT) to >= its move floor (which
# is always >= EMU_FD_LIMIT), so numbers past the limit are native
# again.  Emulated registration refuses to grow past the window
# (EMFILE) rather than alias relocated native fds.
EMU_FD_LIMIT = 2048

# pidfd_getfd(2): duplicate a managed process's native fd into the
# manager (allowed: every managed process is the manager's direct
# child, so Yama's descendant rule passes).  Python 3.12 exposes
# pidfd_open but not pidfd_getfd.
_SYS_pidfd_getfd = 438
_libc_syscall = ctypes.CDLL(None, use_errno=True).syscall


def _pidfd_pull(process, fd: int):
    """Duplicate `fd` out of `process` into the manager; returns the
    manager-side fd or None (bad fd / no pidfd support)."""
    pid = getattr(process, "native_pid", None)
    if pid is None:
        return None
    pidfd = getattr(process, "_pidfd", None)
    if pidfd is None:
        try:
            pidfd = _os.pidfd_open(pid)
        except OSError:
            return None
        process._pidfd = pidfd
    r = _libc_syscall(_SYS_pidfd_getfd, pidfd, fd, 0)
    return r if r >= 0 else None

# --- x86-64 syscall numbers (linux-api equivalents we dispatch on) ---
SYS = {
    0: "read", 1: "write", 3: "close", 5: "fstat", 7: "poll",
    8: "lseek", 13: "rt_sigaction",
    14: "rt_sigprocmask", 15: "rt_sigreturn",
    16: "ioctl", 19: "readv", 20: "writev", 22: "pipe", 23: "select",
    24: "sched_yield", 32: "dup", 33: "dup2", 34: "pause", 35: "nanosleep",
    36: "getitimer", 38: "setitimer",
    37: "alarm", 39: "getpid", 41: "socket", 42: "connect", 43: "accept",
    44: "sendto", 45: "recvfrom", 46: "sendmsg", 47: "recvmsg",
    48: "shutdown", 49: "bind", 50: "listen", 51: "getsockname",
    52: "getpeername", 53: "socketpair", 54: "setsockopt",
    55: "getsockopt", 56: "clone", 57: "fork", 58: "vfork", 59: "execve",
    60: "exit", 61: "wait4", 62: "kill", 63: "uname", 72: "fcntl",
    96: "gettimeofday", 98: "getrusage", 99: "sysinfo", 100: "times", 102: "getuid",
    104: "getgid", 107: "geteuid", 108: "getegid", 110: "getppid",
    109: "setpgid", 111: "getpgrp", 112: "setsid", 121: "getpgid",
    124: "getsid", 127: "rt_sigpending", 128: "rt_sigtimedwait",
    130: "rt_sigsuspend", 131: "sigaltstack", 157: "prctl",
    186: "gettid", 200: "tkill", 203: "sched_setaffinity",
    204: "sched_getaffinity", 201: "time", 202: "futex",
    234: "tgkill",
    213: "epoll_create", 218: "set_tid_address", 228: "clock_gettime",
    229: "clock_getres", 230: "clock_nanosleep", 231: "exit_group",
    232: "epoll_wait", 233: "epoll_ctl", 247: "waitid", 257: "openat",
    270: "pselect6", 271: "ppoll", 281: "epoll_pwait", 283: "timerfd_create",
    284: "eventfd", 286: "timerfd_settime", 287: "timerfd_gettime",
    262: "newfstatat", 282: "signalfd", 288: "accept4",
    289: "signalfd4", 290: "eventfd2", 291: "epoll_create1", 292: "dup3",
    299: "recvmmsg", 307: "sendmmsg",
    293: "pipe2", 302: "prlimit64", 317: "seccomp", 318: "getrandom",
    332: "statx", 435: "clone3", 436: "close_range",
    # Custom pseudo-syscalls (ref shadow_syscalls.rs): the shim's
    # preemption handler yields with this number.
    0x53544001: "shadow_yield",
}
_NUM = {name: num for num, name in SYS.items()}


def syscall_name(num: int) -> str:
    return SYS.get(num, f"syscall_{num}")


# --- constants -------------------------------------------------------
AF_UNIX = 1
AF_INET = 2
AF_NETLINK = 16
SOCK_STREAM = 1
SOCK_DGRAM = 2
SOCK_SEQPACKET = 5
SOCK_NONBLOCK = 0o4000
SOCK_CLOEXEC = 0o2000000

MSG_DONTWAIT = 0x40
MSG_PEEK = 0x02

POLLIN = 0x001
POLLPRI = 0x002
POLLOUT = 0x004
POLLERR = 0x008
POLLHUP = 0x010
POLLNVAL = 0x020

O_NONBLOCK = 0o4000
O_CLOEXEC = 0o2000000
O_WRONLY = 0o1
O_RDWR = 0o2
FD_CLOEXEC = 1

F_GETFD = 1
F_SETFD = 2
F_GETFL = 3
F_SETFL = 4
F_DUPFD = 0
F_DUPFD_CLOEXEC = 1030

FIONREAD = 0x541B
FIONBIO = 0x5421

SOL_SOCKET = 1
SO_REUSEADDR = 2
SO_ERROR = 4
SO_SNDBUF = 7
SO_RCVBUF = 8
SO_ACCEPTCONN = 30
SO_DOMAIN = 39
SO_TYPE = 3

TIMER_ABSTIME = 1
CLOCK_REALTIME = 0

SIGSYS = 31

EFD_SEMAPHORE = 1
EFD_NONBLOCK = O_NONBLOCK
TFD_NONBLOCK = O_NONBLOCK

_MAX_IO = 1 << 20  # clamp reads/writes we marshal through the manager

_TIMESPEC = struct.Struct("<qq")
_TIMEVAL = struct.Struct("<qq")
_POLLFD = struct.Struct("<ihh")
_EPOLL_EVENT = struct.Struct("<IQ")  # packed on x86-64
_IOVEC = struct.Struct("<QQ")


def _done(value=0):
    return ("done", int(value))


def _error(code):
    return ("error", OSError(code, ""))


def _native():
    return ("native",)


def _block(condition):
    return ("block", condition)


def _pack_sockaddr_in(ip: int, port: int) -> bytes:
    return struct.pack("<H", AF_INET) + struct.pack(">H", port) + \
        int(ip).to_bytes(4, "big") + b"\0" * 8


def _pack_siginfo(signo: int, si_code: int = 0, si_pid: int = 0,
                  si_status: int = 0) -> bytes:
    """x86-64 siginfo_t (128 bytes): si_signo@0, si_errno@4, si_code@8,
    si_pid@16, si_uid@20, si_status@24 (the CLD_* union arm)."""
    return struct.pack("<iiiiiii", signo, 0, si_code, 0, si_pid, 0,
                       si_status) + b"\0" * 100


def _unix_name(raw: bytes) -> str:
    """sockaddr_un -> namespace key ('@...' = abstract, '' = unnamed);
    `raw` is already trimmed to addrlen, which delimits abstract names."""
    path = raw[2:]
    if not path:
        return ""
    if path[0] == 0:
        return "@" + path[1:].rstrip(b"\0").decode(errors="surrogateescape")
    return path.split(b"\0", 1)[0].decode(errors="surrogateescape")


def _pack_sockaddr_un(name) -> bytes:
    if not name:
        return struct.pack("<H", AF_UNIX)
    if name.startswith("@"):
        return struct.pack("<H", AF_UNIX) + b"\0" + \
            name[1:].encode(errors="surrogateescape")
    return struct.pack("<H", AF_UNIX) + \
        name.encode(errors="surrogateescape") + b"\0"


def _write_addr(process, addr_ptr, len_ptr, sa) -> None:
    """Write a sockaddr clamped to the caller's buffer length (the
    kernel truncates; sockaddr_un is variable-length so an unclamped
    write could clobber plugin memory past a short buffer)."""
    if not addr_ptr or sa is None:
        return
    if len_ptr:
        want = struct.unpack("<I", process.mem.read(len_ptr, 4))[0]
        process.mem.write(addr_ptr, sa[:want])
        process.mem.write(len_ptr, struct.pack("<I", len(sa)))
    else:
        process.mem.write(addr_ptr, sa)


def _pack_peer_addr(peer):
    """Family-aware source-address rendering for recvfrom/recvmsg."""
    if peer is None:
        return None
    if isinstance(peer, str):
        return _pack_sockaddr_un(peer)
    if isinstance(peer, tuple) and peer and peer[0] == "netlink":
        return struct.pack("<HHII", AF_NETLINK, 0, 0, 0)
    if isinstance(peer, tuple) and len(peer) == 2:
        return _pack_sockaddr_in(*peer)
    return None


def _unpack_sockaddr_in(raw: bytes):
    if len(raw) < 8:
        raise OSError(errno.EINVAL, "short sockaddr")
    family = struct.unpack_from("<H", raw, 0)[0]
    if family != AF_INET:
        raise OSError(errno.EAFNOSUPPORT, f"family {family}")
    port = struct.unpack_from(">H", raw, 2)[0]
    ip = int.from_bytes(raw[4:8], "big")
    return ip, port


class NativeSyscallHandler:
    """One per manager (like the internal-app SyscallHandler)."""

    def __init__(self, send_buf: int = 131_072, recv_buf: int = 174_760,
                 send_autotune: bool = True, recv_autotune: bool = True):
        self.send_buf = send_buf
        self.recv_buf = recv_buf
        self.send_autotune = send_autotune
        self.recv_autotune = recv_autotune

    # ------------------------------------------------------------------

    def dispatch(self, host, process, thread, num: int, args,
                 restarted: bool):
        name = SYS.get(num)
        if name is None:
            return _native()
        method = getattr(self, "sys_" + name, None)
        if method is None:
            return _native()
        try:
            return method(host, process, thread, restarted, *args)
        except OSError as e:
            return _error(e.errno if e.errno else errno.EINVAL)

    # -- fd helpers ----------------------------------------------------

    @staticmethod
    def _is_emu(process, fd: int) -> bool:
        if EMU_FD_BASE <= fd < EMU_FD_LIMIT:
            return True
        # Low emulated fds: an emulated object dup2'd onto a native fd
        # number (shells/git redirect emulated pipes onto the child's
        # stdio before exec).
        low = getattr(process, "fds_low", None)
        return low is not None and low.get_opt(fd) is not None

    @staticmethod
    def _emu(process, fd: int):
        if fd < EMU_FD_BASE:
            low = getattr(process, "fds_low", None)
            obj = low.get_opt(fd) if low is not None else None
            if obj is None:
                raise OSError(errno.EBADF, "bad low emulated fd")
            return obj
        return process.fds.get(fd - EMU_FD_BASE)

    @staticmethod
    def _register(process, obj, cloexec: bool = False) -> int:
        fd = process.fds.register(obj, cloexec=cloexec) + EMU_FD_BASE
        if fd >= EMU_FD_LIMIT:
            # Window exhausted: unregister and refuse like a full
            # kernel fd table (aliasing a relocated native fd would
            # corrupt dispatch).
            try:
                process.fds.close_fd(None, fd - EMU_FD_BASE)
            except Exception:
                pass
            raise OSError(errno.EMFILE, "emulated fd window exhausted")
        return fd

    # ------------------------------------------------------------------
    # Sockets
    # ------------------------------------------------------------------

    def sys_socket(self, host, process, thread, restarted, domain, type_,
                   protocol, *_):
        domain &= 0xffffffff
        base_type = type_ & 0xff
        cloexec = bool(type_ & SOCK_CLOEXEC)
        if domain == AF_UNIX and base_type in (SOCK_STREAM, SOCK_DGRAM):
            # Emulated (socket/unix.rs parity): a native blocking unix
            # read would park the OS thread in the kernel and stall the
            # event pump on wall-clock time.  SEQPACKET is refused (a
            # stream emulation would silently lose record boundaries).
            sock = UnixSocket(host, stream=base_type != SOCK_DGRAM)
            sock.nonblocking = bool(type_ & SOCK_NONBLOCK)
            return _done(self._register(process, sock, cloexec=cloexec))
        if domain == AF_UNIX:
            # SEQPACKET etc.: refuse rather than fall through to a
            # native socket (blocking hazard + wrong namespace).
            return _error(errno.ESOCKTNOSUPPORT)
        if domain == AF_NETLINK:
            if protocol != 0:  # only NETLINK_ROUTE is modeled
                return _error(errno.EPROTONOSUPPORT)
            sock = NetlinkSocket(host)
            sock.nonblocking = bool(type_ & SOCK_NONBLOCK)
            return _done(self._register(process, sock, cloexec=cloexec))
        if domain != AF_INET or base_type not in (SOCK_STREAM, SOCK_DGRAM):
            return _native()
        native = host.plane is not None
        if base_type == SOCK_DGRAM:
            if native:
                from shadow_tpu.host.socket_native import \
                    UdpSocket as NativeUdp
                sock = NativeUdp(host, self.send_buf, self.recv_buf)
            else:
                sock = UdpSocket(host, self.send_buf, self.recv_buf)
        elif native:
            from shadow_tpu.host.socket_native import \
                TcpSocket as NativeTcp
            sock = NativeTcp(host, self.send_buf, self.recv_buf,
                             send_autotune=self.send_autotune,
                             recv_autotune=self.recv_autotune)
        else:
            from shadow_tpu.host.socket_tcp import TcpSocket
            sock = TcpSocket(host, self.send_buf, self.recv_buf,
                             send_autotune=self.send_autotune,
                             recv_autotune=self.recv_autotune)
        sock.nonblocking = bool(type_ & SOCK_NONBLOCK)
        return _done(self._register(process, sock,
                                    cloexec=bool(type_ & SOCK_CLOEXEC)))

    def sys_bind(self, host, process, thread, restarted, fd, addr_ptr,
                 addrlen, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        raw = process.mem.read(addr_ptr, min(addrlen, 128))
        if isinstance(sock, UnixSocket):
            sock.bind(host, _unix_name(raw))
            return _done(0)
        if isinstance(sock, NetlinkSocket):
            nl_pid = struct.unpack_from("<I", raw, 4)[0] \
                if len(raw) >= 8 else 0
            sock.bind(host, nl_pid)
            return _done(0)
        ip, port = _unpack_sockaddr_in(raw)
        sock.bind(host, ip, port)
        return _done(0)

    def sys_connect(self, host, process, thread, restarted, fd, addr_ptr,
                    addrlen, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        raw = process.mem.read(addr_ptr, min(addrlen, 128))
        if isinstance(sock, UnixSocket):
            try:
                sock.connect(host, _unix_name(raw))  # host-local
            except BlockingIOError as e:
                if sock.nonblocking:
                    return _error(errno.EAGAIN)
                # Blocking connect waits for accept-queue room.
                return _block(SyscallCondition(
                    file=e.listener, mask=S_SOCKET_ALLOWING_CONNECT))
            return _done(0)
        if isinstance(sock, NetlinkSocket):
            return _done(0)
        ip, port = _unpack_sockaddr_in(raw)
        # connect() is restart-safe: re-entry with the same args returns
        # 0 once established / raises the handshake error.
        result = sock.connect(host, ip, port)
        if isinstance(result, SyscallCondition):
            return _block(result)
        return _done(0)

    def sys_listen(self, host, process, thread, restarted, fd, backlog, *_):
        if not self._is_emu(process, fd):
            return _native()
        self._emu(process, fd).listen(host, backlog or 128)
        return _done(0)

    def _accept_common(self, host, process, fd, addr_ptr, len_ptr, flags):
        sock = self._emu(process, fd)
        try:
            child = sock.accept(host)
        except BlockingIOError:
            if sock.nonblocking:
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=sock, mask=S_READABLE))
        child.nonblocking = bool(flags & SOCK_NONBLOCK)
        newfd = self._register(process, child,
                               cloexec=bool(flags & SOCK_CLOEXEC))
        if isinstance(child, UnixSocket):
            peer_name = child.peer.bound_name if child.peer else None
            _write_addr(process, addr_ptr, len_ptr,
                        _pack_sockaddr_un(peer_name or ""))
            return _done(newfd)
        if addr_ptr and child.peer is not None:
            sa = _pack_sockaddr_in(*child.peer)
            if len_ptr:
                want = struct.unpack(
                    "<I", process.mem.read(len_ptr, 4))[0]
                process.mem.write(addr_ptr, sa[:want])
                process.mem.write(len_ptr, struct.pack("<I", len(sa)))
            else:
                process.mem.write(addr_ptr, sa)
        return _done(newfd)

    def sys_accept(self, host, process, thread, restarted, fd, addr_ptr,
                   len_ptr, *_):
        if not self._is_emu(process, fd):
            return _native()
        return self._accept_common(host, process, fd, addr_ptr, len_ptr, 0)

    def sys_accept4(self, host, process, thread, restarted, fd, addr_ptr,
                    len_ptr, flags, *_):
        if not self._is_emu(process, fd):
            return _native()
        return self._accept_common(host, process, fd, addr_ptr, len_ptr,
                                   flags)

    def _sock_send(self, host, process, sock, data: bytes, dst, flags: int):
        """Uniform send: inet (dst = (ip, port)), unix (dst = name str),
        netlink (dst ignored)."""
        if getattr(sock, "protocol", None) == 17:  # UDP, either plane
            # Port-53 interception must also catch the connect()+send()
            # shape libc's resolver uses (dst comes from the socket
            # peer).
            effective_dst = dst if dst is not None \
                else getattr(sock, "peer", None)
            if effective_dst is not None and effective_dst[1] == 53:
                handled = self._try_answer_dns(host, sock, data,
                                               effective_dst)
                if handled is not None:
                    return handled
        try:
            n = sock.sendto(host, data, dst)
        except BlockingIOError:
            if sock.nonblocking or (flags & MSG_DONTWAIT):
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=sock, mask=S_WRITABLE))
        return _done(n)

    def sys_sendto(self, host, process, thread, restarted, fd, buf_ptr,
                   length, flags, addr_ptr, addrlen):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        data = process.mem.read(buf_ptr, min(length, _MAX_IO))
        if isinstance(sock, (UnixSocket, NetlinkSocket)):
            dest = None
            if addr_ptr and addrlen and isinstance(sock, UnixSocket):
                dest = _unix_name(
                    process.mem.read(addr_ptr, min(addrlen, 128)))
            return self._sock_send(host, process, sock, data, dest,
                                   flags)
        dst = None
        if addr_ptr and addrlen:
            dst = _unpack_sockaddr_in(
                process.mem.read(addr_ptr, min(addrlen, 128)))
        return self._sock_send(host, process, sock, data, dst, flags)

    def sys_recvfrom(self, host, process, thread, restarted, fd, buf_ptr,
                     length, flags, addr_ptr, len_ptr):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        try:
            data, peer = self._sock_recv(host, sock, min(length, _MAX_IO),
                                         peek=bool(flags & MSG_PEEK))
        except BlockingIOError:
            if sock.nonblocking or (flags & MSG_DONTWAIT):
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=sock, mask=S_READABLE))
        process.mem.write(buf_ptr, data)
        self._discard_ancillary(host, sock)
        _write_addr(process, addr_ptr, len_ptr, _pack_peer_addr(peer))
        return _done(len(data))

    @staticmethod
    def _try_answer_dns(host, sock, data: bytes, dst):
        """Port-53 interception: answer A queries from the sim DNS
        (net/dns_wire.py) by dropping the response straight into the
        socket's receive queue, as if the resolver replied instantly.
        Returns a dispatch result or None to let the datagram travel
        the simulated network normally."""
        from shadow_tpu.net import dns_wire
        from shadow_tpu.net import packet as pkt
        resp = dns_wire.answer_query(
            data, lambda name: host.dns.ip_for_name(name))
        if resp is None:
            return None
        if sock.local is None:
            sock.bind(host, 0, 0)  # INADDR_ANY, ephemeral
        if hasattr(sock, "push_reply"):  # native-plane UDP proxy
            sock.push_reply(host, resp, dst[0], 53)
            return _done(len(data))
        local_ip = sock.local[0] or host.ip  # == eth0.ip
        reply = pkt.Packet(host.id, host.next_packet_seq(), pkt.PROTO_UDP,
                           dst[0], 53, local_ip, sock.local[1],
                           payload=resp)
        sock.push_in_packet(host, reply)
        return _done(len(data))

    @staticmethod
    def _discard_ancillary(host, sock) -> None:
        """A plain recv/read consumed bytes carrying SCM_RIGHTS the
        caller gave no control buffer for: Linux closes those fds."""
        if isinstance(sock, UnixSocket):
            objs = sock.take_ancillary()
            if objs:
                from shadow_tpu.host.descriptor import _decref
                for obj in objs:
                    _decref(obj, host)

    @staticmethod
    def _sock_recv(host, sock, bufsize: int, peek: bool = False):
        """Uniform recv across UDP (datagram+peer) and TCP (stream)."""
        result = sock.recvfrom(host, bufsize, peek=peek)
        if isinstance(result, tuple):
            return result
        return result, getattr(sock, "peer", None)

    def sys_sendmsg(self, host, process, thread, restarted, fd, msg_ptr,
                    flags, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        name_ptr, namelen, iov_ptr, iovlen = self._read_msghdr(process,
                                                               msg_ptr)
        data = self._gather_iov(process, iov_ptr, iovlen)
        if isinstance(sock, (UnixSocket, NetlinkSocket)):
            control_ptr, controllen = struct.unpack(
                "<QQ", process.mem.read(msg_ptr + 32, 16))
            anc = None
            if controllen and isinstance(sock, UnixSocket):
                anc = self._parse_scm_rights(process, control_ptr,
                                             controllen)
                if anc is None:
                    return _error(errno.EINVAL)
            dest = None
            if name_ptr and namelen and isinstance(sock, UnixSocket):
                dest = _unix_name(
                    process.mem.read(name_ptr, min(namelen, 128)))
            if anc:
                try:
                    n = sock.sendto(host, data, dest, anc=anc)
                except BlockingIOError:
                    from shadow_tpu.host.descriptor import _decref
                    for obj in anc:
                        _decref(obj, host)
                    if sock.nonblocking or (flags & MSG_DONTWAIT):
                        return _error(errno.EWOULDBLOCK)
                    return _block(SyscallCondition(file=sock,
                                                   mask=S_WRITABLE))
                except OSError:
                    # EPIPE/ENOTCONN/...: the in-flight refs must not
                    # outlive the failed send.
                    from shadow_tpu.host.descriptor import _decref
                    for obj in anc:
                        _decref(obj, host)
                    raise
                return _done(n)
            return self._sock_send(host, process, sock, data, dest,
                                   flags)
        dst = None
        if name_ptr and namelen:
            dst = _unpack_sockaddr_in(
                process.mem.read(name_ptr, min(namelen, 128)))
        return self._sock_send(host, process, sock, data, dst, flags)

    def sys_sendmmsg(self, host, process, thread, restarted, fd, vec_ptr,
                     vlen, flags, *_):
        """glibc's resolver sends the A and AAAA queries in one
        sendmmsg (res_send.c) — without this the port-53 interception
        never sees the queries.  mmsghdr = msghdr (56) + msg_len (4) +
        pad (4)."""
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        vlen = min(int(vlen), 64)
        sent = 0
        for i in range(vlen):
            msg_ptr = vec_ptr + i * 64
            name_ptr, namelen, iov_ptr, iovlen = self._read_msghdr(
                process, msg_ptr)
            data = self._gather_iov(process, iov_ptr, iovlen)
            dst = None
            if name_ptr and namelen:
                raw = process.mem.read(name_ptr, min(namelen, 128))
                # Same family split as sys_sendto/sys_sendmsg: a unix
                # dgram destination is a namespace key, not (ip, port).
                if isinstance(sock, UnixSocket):
                    dst = _unix_name(raw)
                else:
                    dst = _unpack_sockaddr_in(raw)
            result = self._sock_send(host, process, sock, data, dst,
                                     flags)
            if result[0] != "done":
                # Error/blocked mid-batch: report what already went out
                # (Linux semantics), else surface the first failure.
                return _done(sent) if sent else result
            process.mem.write(msg_ptr + 56,
                              struct.pack("<I", int(result[1])))
            sent += 1
        return _done(sent)

    def sys_recvmmsg(self, host, process, thread, restarted, fd, vec_ptr,
                     vlen, flags, timeout_ptr, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        vlen = min(int(vlen), 64)
        got = 0
        for i in range(vlen):
            msg_ptr = vec_ptr + i * 64
            name_ptr, _namelen, iov_ptr, iovlen = self._read_msghdr(
                process, msg_ptr)
            total = sum(l for _p, l in self._iovecs(process, iov_ptr,
                                                    iovlen))
            if got and isinstance(sock, UnixSocket) \
                    and sock.next_read_has_native_fds():
                # A native-fd message must head its own batch (one
                # cmsg transfer dance per syscall): stop here, the
                # next recvmmsg/recvmsg delivers it with the fds
                # intact.  Linux legally returns short batches.
                return _done(got)
            try:
                data, peer = self._sock_recv(host, sock,
                                             min(total, _MAX_IO),
                                             peek=bool(flags & MSG_PEEK))
            except BlockingIOError:
                if got:
                    return _done(got)
                if sock.nonblocking or (flags & MSG_DONTWAIT) \
                        or (restarted and timeout_ptr):
                    # restarted with a timeout armed = the condition
                    # fired; no data now means the timeout won.  With a
                    # NULL timeout a spurious wake just re-blocks.
                    return _error(errno.EWOULDBLOCK)
                timeout_at = None
                if timeout_ptr:
                    sec, nsec = _TIMESPEC.unpack(
                        process.mem.read(timeout_ptr, 16))
                    timeout_at = host.now() + sec * 10**9 + nsec
                return _block(SyscallCondition(file=sock,
                                               mask=S_READABLE,
                                               timeout_at=timeout_at))
            self._scatter_iov(process, iov_ptr, iovlen, data)
            xfer = None
            if isinstance(sock, UnixSocket):
                # recvmmsg is recvmsg in a loop: ancillary delivers per
                # message through the same path.  Native fds are only
                # possible on the batch's FIRST message (the guard
                # above stops before consuming one later).
                objs = sock.take_ancillary()
                if objs:
                    xfer = self._deliver_scm_rights(host, process,
                                                    msg_ptr, objs,
                                                    allow_native=(got
                                                                  == 0))
                else:
                    process.mem.write(msg_ptr + 40,
                                      struct.pack("<Q", 0))
                    process.mem.write(msg_ptr + 48,
                                      struct.pack("<i", 0))
            if name_ptr:
                sa = _pack_peer_addr(peer)
                if sa is not None:
                    process.mem.write(name_ptr, sa[:_namelen])
                    process.mem.write(msg_ptr + 8,
                                      struct.pack("<I", len(sa)))
            process.mem.write(msg_ptr + 56,
                              struct.pack("<I", len(data)))
            got += 1
            if xfer is not None:
                # Close the batch at 1: the transfer dance patches this
                # message's cmsg placeholders after the syscall result.
                return ("done_fdxfer", got) + xfer[1:]
        return _done(got)

    def _parse_scm_rights(self, process, control_ptr, controllen):
        """cmsghdr walk: returns the transferred file objects (each
        incref'd for the in-flight reference), or None on EINVAL.
        Emulated fds resolve to their table objects; NATIVE fds are
        pulled out of the sender with pidfd_getfd and ride the queue
        as NativeFdRef wrappers (ref: socket/unix.rs fd passing)."""
        from shadow_tpu.host.descriptor import NativeFdRef, _incref
        SOL_SOCKET_C, SCM_RIGHTS = 1, 1
        if controllen > 4096:  # > SCM_MAX_FD-worth: refuse, don't clip
            return None
        raw = process.mem.read(control_ptr, controllen)
        objs = []

        def bail():
            from shadow_tpu.utils.object_counter import mark_dealloc
            for o in objs:
                if isinstance(o, NativeFdRef):
                    o.close(None)
                    mark_dealloc(o)
            return None

        off = 0
        while off + 16 <= len(raw):
            clen, level, ctype = struct.unpack_from("<QII", raw, off)
            if clen < 16 or off + clen > len(raw) + 7:
                return bail()
            if level != SOL_SOCKET_C or ctype != SCM_RIGHTS:
                return bail()
            nfds = (min(clen, len(raw) - off) - 16) // 4
            for i in range(nfds):
                (fd,) = struct.unpack_from("<i", raw, off + 16 + 4 * i)
                if self._is_emu(process, fd):
                    try:
                        objs.append(self._emu(process, fd))
                    except OSError:
                        return bail()
                else:
                    mgr_fd = _pidfd_pull(process, fd)
                    if mgr_fd is None:
                        return bail()
                    objs.append(NativeFdRef(mgr_fd))
            off += (clen + 7) & ~7  # CMSG_ALIGN
        for obj in objs:
            _incref(obj)
        return objs

    def _deliver_scm_rights(self, host, process, msg_ptr, objs,
                            allow_native: bool = True):
        """Register the transferred objects as fresh fds in the
        receiver and write one SCM_RIGHTS cmsg; discards (like Linux
        closing unclaimed fds) when no/too-small control buffer, with
        MSG_CTRUNC in msg_flags.

        Emulated objects register into the table directly.  NativeFdRef
        objects cannot: the real fd must materialize inside the
        receiving process, so their cmsg slots get a -1 placeholder and
        the return value is ("fdxfer", pairs, refs, msg_ptr) — the
        ManagedThread then ships the real fds over the process's
        transfer socket and the shim patches the placeholders (pairs =
        [(app_addr_of_slot, mgr_fd)]).  Returns None when no transfer
        is needed."""
        from shadow_tpu.host.descriptor import NativeFdRef, _decref
        MSG_CTRUNC = 0x8
        control_ptr, controllen = struct.unpack(
            "<QQ", process.mem.read(msg_ptr + 32, 16))
        nfit = 0
        if control_ptr and controllen >= 20:
            nfit = min(len(objs), (controllen - 16) // 4)
        # Linux delivers as many fds as fit and truncates the rest.
        truncated = nfit < len(objs)
        for obj in objs[nfit:]:
            _decref(obj, host)
        if nfit == 0:
            process.mem.write(msg_ptr + 48,
                              struct.pack("<i", MSG_CTRUNC))
            process.mem.write(msg_ptr + 40, struct.pack("<Q", 0))
            return None
        # The transfer dance carries at most the shim's XFER_MAX_FDS in
        # one datagram; beyond that, surplus native fds truncate (the
        # kernel's own ceiling is SCM_MAX_FD=253 per message).
        XFER_MAX_FDS = 64
        fds = []     # fd numbers written into the cmsg (compacted)
        emu_fds = [] # the emulated subset, for failure-path rewrite
        pairs = []   # (app address of the int slot, manager-side fd)
        refs = []    # NativeFdRefs to release after the transfer
        for obj in objs[:nfit]:
            if isinstance(obj, NativeFdRef):
                if not allow_native or len(pairs) >= XFER_MAX_FDS:
                    # recvmmsg batch path / over-cap: no transfer
                    # available; drop the fd like a truncation (Linux
                    # shortens the array — never delivers a hole).
                    _decref(obj, host)
                    truncated = True
                    continue
                # Slot index = position in the COMPACTED array.
                pairs.append((control_ptr + 16 + 4 * len(fds),
                              obj.mgr_fd))
                refs.append(obj)
                fds.append(-1)  # patched by the shim after transfer
            else:
                fds.append(self._register(process, obj))
                emu_fds.append(fds[-1])
                _decref(obj, host)  # table registration took its own ref
        if not fds:
            process.mem.write(msg_ptr + 48,
                              struct.pack("<i", MSG_CTRUNC))
            process.mem.write(msg_ptr + 40, struct.pack("<Q", 0))
            return None
        cmsg = struct.pack("<QII", 16 + 4 * len(fds), 1, 1)
        cmsg += b"".join(struct.pack("<i", fd) for fd in fds)
        process.mem.write(control_ptr, cmsg)
        process.mem.write(msg_ptr + 40, struct.pack("<Q", len(cmsg)))
        process.mem.write(msg_ptr + 48, struct.pack(
            "<i", MSG_CTRUNC if truncated else 0))
        if pairs:
            return ("fdxfer", pairs, refs, msg_ptr, control_ptr,
                    emu_fds)
        return None

    def sys_recvmsg(self, host, process, thread, restarted, fd, msg_ptr,
                    flags, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        name_ptr, _namelen, iov_ptr, iovlen = self._read_msghdr(process,
                                                                msg_ptr)
        total = sum(l for _p, l in self._iovecs(process, iov_ptr, iovlen))
        try:
            data, peer = self._sock_recv(host, sock, min(total, _MAX_IO),
                                         peek=bool(flags & MSG_PEEK))
        except BlockingIOError:
            if sock.nonblocking or (flags & MSG_DONTWAIT):
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=sock, mask=S_READABLE))
        self._scatter_iov(process, iov_ptr, iovlen, data)
        if name_ptr:
            sa = _pack_peer_addr(peer)
            if sa is not None:
                process.mem.write(name_ptr, sa[:_namelen])
                process.mem.write(msg_ptr + 8,
                                  struct.pack("<I", len(sa)))
        if isinstance(sock, UnixSocket):
            objs = sock.take_ancillary()
            if objs:
                xfer = self._deliver_scm_rights(host, process, msg_ptr,
                                                objs)
                if xfer is not None:
                    # Native fds ride the transfer socket: the service
                    # loop runs the shim-side collection dance before
                    # completing the syscall.
                    return ("done_fdxfer", len(data)) + xfer[1:]
            else:
                # Linux rewrites controllen AND msg_flags every return;
                # a reused msghdr must not keep a stale MSG_CTRUNC.
                process.mem.write(msg_ptr + 40, struct.pack("<Q", 0))
                process.mem.write(msg_ptr + 48, struct.pack("<i", 0))
        return _done(len(data))

    @staticmethod
    def _read_msghdr(process, msg_ptr):
        raw = process.mem.read(msg_ptr, 56)
        name_ptr, namelen = struct.unpack_from("<QI", raw, 0)
        iov_ptr, iovlen = struct.unpack_from("<QQ", raw, 16)
        return name_ptr, namelen, iov_ptr, iovlen

    @staticmethod
    def _iovecs(process, iov_ptr, iovlen):
        iovlen = min(iovlen, 64)
        raw = process.mem.read(iov_ptr, 16 * iovlen) if iovlen else b""
        return [_IOVEC.unpack_from(raw, i * 16) for i in range(iovlen)]

    def _gather_iov(self, process, iov_ptr, iovlen) -> bytes:
        out = bytearray()
        for base, length in self._iovecs(process, iov_ptr, iovlen):
            if len(out) >= _MAX_IO:
                break
            out += process.mem.read(base, min(length, _MAX_IO - len(out)))
        return bytes(out)

    def _scatter_iov(self, process, iov_ptr, iovlen, data: bytes) -> int:
        off = 0
        for base, length in self._iovecs(process, iov_ptr, iovlen):
            if off >= len(data):
                break
            chunk = data[off:off + length]
            process.mem.write(base, chunk)
            off += len(chunk)
        return off

    def sys_getsockname(self, host, process, thread, restarted, fd,
                        addr_ptr, len_ptr, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        if isinstance(sock, UnixSocket):
            sa = _pack_sockaddr_un(sock.bound_name or "")
        elif isinstance(sock, NetlinkSocket):
            sa = struct.pack("<HHII", AF_NETLINK, 0, sock.nl_pid, 0)
        else:
            local = sock.local or (0, 0)
            ip = local[0]
            if ip == 0 and getattr(sock, "peer", None):
                ip = host.ip  # == eth0.ip; avoid the lazy plane build
            sa = _pack_sockaddr_in(ip, local[1])
        _write_addr(process, addr_ptr, len_ptr, sa)
        return _done(0)

    def sys_getpeername(self, host, process, thread, restarted, fd,
                        addr_ptr, len_ptr, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        if isinstance(sock, NetlinkSocket):
            sa = struct.pack("<HHII", AF_NETLINK, 0, 0, 0)  # the kernel
            _write_addr(process, addr_ptr, len_ptr, sa)
            return _done(0)
        if getattr(sock, "peer", None) is None:
            return _error(errno.ENOTCONN)
        if isinstance(sock, UnixSocket):
            sa = _pack_sockaddr_un(sock.peer.bound_name or "")
        else:
            sa = _pack_sockaddr_in(*sock.peer)
        _write_addr(process, addr_ptr, len_ptr, sa)
        return _done(0)

    def sys_setsockopt(self, host, process, thread, restarted, fd, level,
                       optname, optval, optlen, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        # TCP_NODELAY (IPPROTO_TCP=6, optname 1) reaches the connection's
        # Nagle switch; SO_REUSEADDR drives bind-time port semantics;
        # other options (buffer sizing hints...) are recorded-but-inert
        # — enough surface for common apps.
        if level == SOL_SOCKET and optname == SO_REUSEADDR and optlen >= 4:
            val = struct.unpack("<i", process.mem.read(optval, 4))[0]
            sock.reuseaddr = bool(val)
            return _done(0)
        if level == 6 and optname == 1 and optlen >= 4:
            val = struct.unpack("<i", process.mem.read(optval, 4))[0]
            if hasattr(sock, "set_nodelay"):  # native-plane proxy
                sock.set_nodelay(host, bool(val))
                return _done(0)
            sock.nodelay = bool(val)
            conn = getattr(sock, "conn", None)
            if conn is not None:
                conn.nodelay = bool(val)
                if conn.nodelay:
                    # Linux flushes Nagle-held data on TCP_NODELAY.
                    conn._push_data(host.now())
                    sock._flush(host)
        return _done(0)

    def sys_getsockopt(self, host, process, thread, restarted, fd, level,
                       optname, optval_ptr, optlen_ptr, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        value = 0
        if level == SOL_SOCKET:
            if optname == SO_ERROR:
                value = getattr(sock, "so_error", 0) or 0
                sock.so_error = 0
            elif optname == SO_REUSEADDR:
                value = 1 if getattr(sock, "reuseaddr", False) else 0
            elif optname == SO_SNDBUF:
                conn = getattr(sock, "conn", None)
                value = (conn.send_buf_max if conn is not None
                         else self.send_buf)
            elif optname == SO_RCVBUF:
                conn = getattr(sock, "conn", None)
                value = (conn.recv_buf_max if conn is not None
                         else self.recv_buf)
            elif optname == SO_TYPE:
                if isinstance(sock, UnixSocket):
                    value = (SOCK_STREAM if sock.stream else SOCK_DGRAM)
                elif isinstance(sock, NetlinkSocket):
                    value = SOCK_DGRAM
                else:
                    from shadow_tpu.net.packet import PROTO_TCP
                    value = (SOCK_STREAM if sock.protocol == PROTO_TCP
                             else SOCK_DGRAM)
            elif optname == SO_DOMAIN:
                if isinstance(sock, UnixSocket):
                    value = AF_UNIX
                elif isinstance(sock, NetlinkSocket):
                    value = AF_NETLINK
                else:
                    value = AF_INET
            elif optname == SO_ACCEPTCONN:
                value = 1 if getattr(sock, "listening", False) else 0
        process.mem.write(optval_ptr, struct.pack("<i", value))
        if optlen_ptr:
            process.mem.write(optlen_ptr, struct.pack("<I", 4))
        return _done(0)

    def sys_shutdown(self, host, process, thread, restarted, fd, how, *_):
        if not self._is_emu(process, fd):
            return _native()
        sock = self._emu(process, fd)
        how_s = {0: "rd", 1: "wr", 2: "rdwr"}.get(how)
        if how_s is None:
            return _error(errno.EINVAL)
        if hasattr(sock, "shutdown"):
            sock.shutdown(host, how_s)
        return _done(0)

    def sys_socketpair(self, host, process, thread, restarted, domain,
                       type_, protocol, sv_ptr, *_):
        base_type = type_ & 0xff
        if domain != AF_UNIX or base_type not in (SOCK_STREAM, SOCK_DGRAM):
            return _error(errno.EOPNOTSUPP)
        a, b = unix_socketpair(host, stream=base_type != SOCK_DGRAM)
        a.nonblocking = b.nonblocking = bool(type_ & SOCK_NONBLOCK)
        cx = bool(type_ & SOCK_CLOEXEC)
        fd1 = self._register(process, a, cloexec=cx)
        fd2 = self._register(process, b, cloexec=cx)
        process.mem.write(sv_ptr, struct.pack("<ii", fd1, fd2))
        return _done(0)

    # ------------------------------------------------------------------
    # Generic fd I/O
    # ------------------------------------------------------------------

    def _file_read(self, host, process, file, n: int, thread=None):
        if isinstance(file, PipeEnd):
            return file.read_bytes(host, n)
        if isinstance(file, EventFd):
            if n < 8:
                raise OSError(errno.EINVAL, "eventfd read < 8 bytes")
            return struct.pack("<Q", file.read_value(host))
        if isinstance(file, TimerFd):
            if n < 8:
                raise OSError(errno.EINVAL, "timerfd read < 8 bytes")
            return struct.pack("<Q", file.read_expirations(host))
        from shadow_tpu.host.files import SignalFd
        if isinstance(file, SignalFd):
            if n < 128:
                raise OSError(errno.EINVAL, "signalfd read < 128 bytes")
            return file.read_infos(host, process, thread, n // 128)
        data, _peer = self._sock_recv(host, file, n)
        self._discard_ancillary(host, file)
        return data

    def _file_write(self, host, process, file, data: bytes) -> int:
        if isinstance(file, PipeEnd):
            return file.write_bytes(host, data)
        if isinstance(file, EventFd):
            if len(data) < 8:
                raise OSError(errno.EINVAL, "eventfd write < 8 bytes")
            file.write_value(host, struct.unpack("<Q", data[:8])[0])
            return 8
        return file.sendto(host, data, None)

    def sys_read(self, host, process, thread, restarted, fd, buf_ptr,
                 count, *_):
        if not self._is_emu(process, fd):
            return _native()
        file = self._emu(process, fd)
        try:
            data = self._file_read(host, process, file,
                                   min(count, _MAX_IO), thread=thread)
        except BlockingIOError:
            if getattr(file, "nonblocking", False):
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=file, mask=S_READABLE))
        process.mem.write(buf_ptr, data)
        return _done(len(data))

    def sys_write(self, host, process, thread, restarted, fd, buf_ptr,
                  count, *_):
        if not self._is_emu(process, fd):
            return _native()
        file = self._emu(process, fd)
        data = process.mem.read(buf_ptr, min(count, _MAX_IO))
        try:
            return _done(self._file_write(host, process, file, data))
        except BlockingIOError:
            if getattr(file, "nonblocking", False):
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=file, mask=S_WRITABLE))

    def sys_readv(self, host, process, thread, restarted, fd, iov_ptr,
                  iovlen, *_):
        if not self._is_emu(process, fd):
            return _native()
        file = self._emu(process, fd)
        total = sum(l for _b, l in self._iovecs(process, iov_ptr, iovlen))
        try:
            data = self._file_read(host, process, file,
                                   min(total, _MAX_IO), thread=thread)
        except BlockingIOError:
            if getattr(file, "nonblocking", False):
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=file, mask=S_READABLE))
        return _done(self._scatter_iov(process, iov_ptr, iovlen, data))

    def sys_writev(self, host, process, thread, restarted, fd, iov_ptr,
                   iovlen, *_):
        if not self._is_emu(process, fd):
            return _native()
        file = self._emu(process, fd)
        data = self._gather_iov(process, iov_ptr, iovlen)
        try:
            return _done(self._file_write(host, process, file, data))
        except BlockingIOError:
            if getattr(file, "nonblocking", False):
                return _error(errno.EWOULDBLOCK)
            return _block(SyscallCondition(file=file, mask=S_WRITABLE))

    def sys_close(self, host, process, thread, restarted, fd, *_):
        if not self._is_emu(process, fd):
            return _native()
        if fd < EMU_FD_BASE:
            close_kernel_side = self._native_fd_exists(process, fd)
            getattr(process, "fds_low").close_fd(host, fd)
            if close_kernel_side:
                return _native()  # close the shadowed kernel fd too
            # dup2 only registered the shadow; no kernel fd exists at
            # this number — succeed emulated rather than surface the
            # kernel's spurious EBADF.
            return _done(0)
        process.fds.close_fd(host, fd - EMU_FD_BASE)
        return _done(0)

    @staticmethod
    def _emu_stat_mode(f) -> int:
        from shadow_tpu.host.files import EventFd, PipeEnd, TimerFd
        from shadow_tpu.host.epoll import EpollFile
        S_IFIFO, S_IFSOCK = 0o010000, 0o140000
        if isinstance(f, PipeEnd):
            return S_IFIFO | 0o600
        from shadow_tpu.host.files import SignalFd
        if isinstance(f, (EventFd, TimerFd, EpollFile, SignalFd)):
            return 0o600  # anon inodes: no file-type bits (like Linux)
        return S_IFSOCK | 0o777

    @staticmethod
    def _emu_ino(f, host) -> int:
        """Stable per-OBJECT inode: dup'd / SCM-transferred fds naming
        the same open file must compare st_ino-equal.  Allocated from a
        per-HOST counter (hosts are single-threaded, so assignment
        order — and with it every inode value — is deterministic even
        under the thread-pool schedulers)."""
        ino = getattr(f, "_emu_ino", None)
        if ino is None:
            nxt = getattr(host, "_emu_ino_next", 0x1000) + 1
            host._emu_ino_next = nxt
            ino = nxt
            f._emu_ino = ino
        return ino

    def _write_emu_stat(self, host, process, f, fd, stat_ptr) -> None:
        """x86-64 struct stat (144 bytes) for an emulated fd."""
        st = struct.pack(
            "<QQQIIIIQqqq",
            0x53,                 # st_dev
            self._emu_ino(f, host),  # st_ino: stable per open file
            1,                    # st_nlink
            self._emu_stat_mode(f), 1000, 1000, 0,  # mode, uid, gid, pad
            0,                    # st_rdev
            0, 4096, 0)           # size, blksize, blocks
        st += struct.pack("<qqqqqq", 0, 0, 0, 0, 0, 0)  # a/m/ctime
        process.mem.write(stat_ptr, st + b"\0" * (144 - len(st)))

    def sys_fstat(self, host, process, thread, restarted, fd, stat_ptr,
                  *_):
        """Apps fstat sockets/pipes to learn the file type; a native
        fstat on our fd numbers would be EBADF."""
        if not self._is_emu(process, fd):
            return _native()
        self._write_emu_stat(host, process, self._emu(process, fd), fd,
                             stat_ptr)
        return _done(0)

    def sys_newfstatat(self, host, process, thread, restarted, dirfd,
                       path_ptr, stat_ptr, flags, *_):
        """glibc's fstat() is newfstatat(fd, "", buf, AT_EMPTY_PATH)
        on modern kernels — route the emulated-fd shape here, leave
        real path lookups native."""
        dirfd = _sext32(dirfd)
        if not self._is_emu(process, dirfd):
            return _native()
        path = process.mem.read_cstr(path_ptr, 256) if path_ptr else b""
        if path:
            return _error(errno.ENOTDIR)  # emulated fds aren't dirs
        self._write_emu_stat(host, process, self._emu(process, dirfd),
                             dirfd, stat_ptr)
        return _done(0)

    def sys_statx(self, host, process, thread, restarted, dirfd,
                  path_ptr, flags, mask, statx_ptr, *_):
        dirfd = _sext32(dirfd)
        if not self._is_emu(process, dirfd):
            return _native()
        path = process.mem.read_cstr(path_ptr, 256) if path_ptr else b""
        if path:
            return _error(errno.ENOTDIR)
        f = self._emu(process, dirfd)
        STATX_BASIC_STATS = 0x7ff
        # statx layout: mask(4) blksize(4) attributes(8) nlink(4)
        # uid(4) gid(4) mode(2) pad(2) ino(8) size(8) blocks(8)
        # attributes_mask(8); timestamps and dev fields stay zeroed.
        buf = struct.pack(
            "<IIQIIIHHQQQQ",
            STATX_BASIC_STATS, 4096, 0, 1, 1000, 1000,
            self._emu_stat_mode(f), 0, self._emu_ino(f, host), 0, 0, 0)
        process.mem.write(statx_ptr, buf + b"\0" * (256 - len(buf)))
        return _done(0)

    def sys_lseek(self, host, process, thread, restarted, fd, *_):
        if not self._is_emu(process, fd):
            return _native()
        return _error(errno.ESPIPE)  # sockets/pipes are not seekable

    def sys_close_range(self, host, process, thread, restarted, first,
                        last, flags, *_):
        """Close/mark the emulated fds in range, then run the native
        close_range too (DO_NATIVE) for the native portion — the two fd
        spaces are disjoint by construction (EMU_FD_BASE split)."""
        CLOSE_RANGE_UNSHARE = 2
        CLOSE_RANGE_CLOEXEC = 4
        if flags & ~(CLOSE_RANGE_UNSHARE | CLOSE_RANGE_CLOEXEC):
            # Validate BEFORE touching any fd (Linux returns EINVAL
            # with nothing closed).
            return _error(errno.EINVAL)
        if first > last:
            return _error(errno.EINVAL)
        if not (flags & CLOSE_RANGE_UNSHARE):
            # UNSHARE privatizes the caller's table before closing so
            # sibling threads keep their fds; our emulated table is
            # process-shared (CLONE_FILES threads), so the emulated
            # half is left untouched under UNSHARE (the native
            # syscall still unshares the native table).
            for fd in [f + EMU_FD_BASE for f in process.fds.open_fds()]:
                if first <= fd <= last:
                    if flags & CLOSE_RANGE_CLOEXEC:
                        process.fds.set_cloexec(fd - EMU_FD_BASE, True)
                    else:
                        process.fds.close_fd(host, fd - EMU_FD_BASE)
            low = getattr(process, "fds_low", None)
            if low is not None:
                for fd in list(low.open_fds()):
                    if first <= fd <= last:
                        if flags & CLOSE_RANGE_CLOEXEC:
                            low.set_cloexec(fd, True)
                        else:
                            low.close_fd(host, fd)
        return _native()

    def sys_dup(self, host, process, thread, restarted, fd, *_):
        if not self._is_emu(process, fd):
            return _native()
        return _done(self._register(process, self._emu(process, fd)))

    @staticmethod
    def _native_fd_exists(process, fd: int) -> bool:
        pid = getattr(process, "native_pid", None)
        if pid is None:
            return False
        return _os.path.exists(f"/proc/{pid}/fd/{fd}")

    @staticmethod
    def _low_table(process):
        low = getattr(process, "fds_low", None)
        if low is None:
            from shadow_tpu.host.descriptor import DescriptorTable
            low = process.fds_low = DescriptorTable()
        return low

    def sys_dup2(self, host, process, thread, restarted, oldfd, newfd, *_,
                 cloexec: bool = False):
        if not self._is_emu(process, oldfd):
            # A native fd dup2'd over a low EMULATED slot restores the
            # native mapping: drop our shadow entry, let the kernel dup.
            # POSIX: a FAILED dup2 must leave newfd untouched — verify
            # the native oldfd exists before mutating the shadow.
            low = getattr(process, "fds_low", None)
            if low is not None and low.get_opt(newfd) is not None:
                if not self._native_fd_exists(process, oldfd):
                    return _error(errno.EBADF)
                low.close_fd(host, newfd)
            return _native()
        obj = self._emu(process, oldfd)  # validates oldfd (EBADF)
        if oldfd == newfd:
            return _done(newfd)  # Linux dup2(fd, fd) is a no-op
        if not self._is_emu(process, newfd) and newfd >= EMU_FD_BASE:
            return _error(errno.EINVAL)  # into the relocated-native zone
        if newfd < EMU_FD_BASE:
            # Emulated object onto a native fd number (stdio
            # redirection before exec — git/shell pipelines).  The
            # kernel-side fd keeps pointing wherever it did; every
            # emulated syscall on `newfd` now routes to `obj`.
            low = self._low_table(process)
            if low.get_opt(newfd) is not None:
                low.close_fd(host, newfd)
            low.register_at(newfd, obj, cloexec=cloexec)
            return _done(newfd)
        try:
            process.fds.close_fd(host, newfd - EMU_FD_BASE)
        except OSError:
            pass
        process.fds.register_at(newfd - EMU_FD_BASE, obj, cloexec=cloexec)
        return _done(newfd)

    def sys_dup3(self, host, process, thread, restarted, oldfd, newfd,
                 flags, *_):
        if oldfd == newfd:
            return _error(errno.EINVAL)  # dup3 requires distinct fds
        return self.sys_dup2(host, process, thread, restarted, oldfd,
                             newfd, cloexec=bool(flags & O_CLOEXEC))

    def sys_fcntl(self, host, process, thread, restarted, fd, cmd, arg, *_):
        if not self._is_emu(process, fd):
            return _native()
        file = self._emu(process, fd)
        table, slot = ((self._low_table(process), fd)
                       if fd < EMU_FD_BASE
                       else (process.fds, fd - EMU_FD_BASE))
        if cmd == F_GETFL:
            # Include the access mode: fdopen() validates it against
            # the requested stream mode (a write-side pipe reported as
            # O_RDONLY makes fdopen(fd, "w") fail EINVAL — git does
            # exactly this on its remote-helper pipes).
            if isinstance(file, PipeEnd):
                acc = O_WRONLY if file.is_writer else 0  # O_RDONLY
            else:
                acc = O_RDWR  # sockets, eventfds, timerfds, epoll
            return _done(acc | (O_NONBLOCK
                                if getattr(file, "nonblocking", False)
                                else 0))
        if cmd == F_SETFL:
            file.nonblocking = bool(arg & O_NONBLOCK)
            return _done(0)
        if cmd in (F_DUPFD, F_DUPFD_CLOEXEC):
            return _done(self._register(process, file,
                                        cloexec=cmd == F_DUPFD_CLOEXEC))
        if cmd == F_GETFD:
            return _done(FD_CLOEXEC if table.get_cloexec(slot) else 0)
        if cmd == F_SETFD:
            table.set_cloexec(slot, bool(arg & FD_CLOEXEC))
            return _done(0)
        return _error(errno.EINVAL)

    def sys_ioctl(self, host, process, thread, restarted, fd, req, argp, *_):
        if not self._is_emu(process, fd):
            return _native()
        file = self._emu(process, fd)
        if req == FIONBIO:
            val = struct.unpack("<i", process.mem.read(argp, 4))[0]
            file.nonblocking = bool(val)
            return _done(0)
        if req == FIONREAD:
            avail = 0
            if isinstance(file, PipeEnd):
                avail = file.bytes_available()
            elif hasattr(file, "bytes_available"):
                avail = file.bytes_available()
            elif hasattr(file, "_recv_q"):
                # UDP SIOCINQ: size of the NEXT pending datagram (Linux
                # udp.c first_packet_length), not the queue total.
                q = file._recv_q
                avail = len(q[0].payload) if q else 0
            process.mem.write(argp, struct.pack("<i", avail))
            return _done(0)
        return _error(errno.ENOTTY)

    # ------------------------------------------------------------------
    # pipes / eventfd / timerfd / epoll
    # ------------------------------------------------------------------

    def _pipe_common(self, host, process, fds_ptr, flags):
        r, w = make_pipe()
        r.nonblocking = w.nonblocking = bool(flags & O_NONBLOCK)
        cloexec = bool(flags & O_CLOEXEC)
        rfd = self._register(process, r, cloexec=cloexec)
        wfd = self._register(process, w, cloexec=cloexec)
        process.mem.write(fds_ptr, struct.pack("<ii", rfd, wfd))
        return _done(0)

    def sys_pipe(self, host, process, thread, restarted, fds_ptr, *_):
        return self._pipe_common(host, process, fds_ptr, 0)

    def sys_pipe2(self, host, process, thread, restarted, fds_ptr, flags,
                  *_):
        return self._pipe_common(host, process, fds_ptr, flags)

    def _eventfd_common(self, host, process, initval, flags):
        ef = EventFd(initval, semaphore=bool(flags & EFD_SEMAPHORE))
        ef.nonblocking = bool(flags & EFD_NONBLOCK)
        return _done(self._register(process, ef,
                                    cloexec=bool(flags & O_CLOEXEC)))

    def sys_eventfd(self, host, process, thread, restarted, initval, *_):
        return self._eventfd_common(host, process, initval, 0)

    def sys_eventfd2(self, host, process, thread, restarted, initval,
                     flags, *_):
        return self._eventfd_common(host, process, initval, flags)

    def sys_timerfd_create(self, host, process, thread, restarted, clockid,
                           flags, *_):
        tf = TimerFd()
        tf.nonblocking = bool(flags & TFD_NONBLOCK)
        return _done(self._register(process, tf,
                                    cloexec=bool(flags & O_CLOEXEC)))

    def sys_timerfd_settime(self, host, process, thread, restarted, fd,
                            flags, new_ptr, old_ptr, *_):
        if not self._is_emu(process, fd):
            return _native()
        tf = self._emu(process, fd)
        if not isinstance(tf, TimerFd):
            return _error(errno.EINVAL)
        raw = process.mem.read(new_ptr, 32)
        int_s, int_ns, val_s, val_ns = struct.unpack("<qqqq", raw)
        interval = int_s * 10**9 + int_ns
        value = val_s * 10**9 + val_ns
        absolute = bool(flags & TIMER_ABSTIME)
        if absolute and value:
            # timerfd absolute times are CLOCK_REALTIME/MONOTONIC-based;
            # both map onto sim time (REALTIME shifted by the epoch).
            emu = value - simtime.EMUTIME_SIMULATION_START
            value = emu if emu >= 0 else value
        if old_ptr:
            self._write_itimerspec(process, old_ptr, tf, host)
        tf.arm(host, value, interval, absolute=absolute)
        return _done(0)

    def sys_timerfd_gettime(self, host, process, thread, restarted, fd,
                            cur_ptr, *_):
        if not self._is_emu(process, fd):
            return _native()
        tf = self._emu(process, fd)
        if not isinstance(tf, TimerFd):
            return _error(errno.EINVAL)
        self._write_itimerspec(process, cur_ptr, tf, host)
        return _done(0)

    @staticmethod
    def _write_itimerspec(process, ptr, tf: TimerFd, host) -> None:
        next_ns, interval = tf.disarm_remaining()
        remaining = max(next_ns - host.now(), 0) if next_ns else 0
        process.mem.write(ptr, struct.pack(
            "<qqqq", interval // 10**9, interval % 10**9,
            remaining // 10**9, remaining % 10**9))

    def _epoll_create(self, host, process, cloexec: bool = False):
        return _done(self._register(process, EpollFile(), cloexec=cloexec))

    def sys_epoll_create(self, host, process, thread, restarted, size, *_):
        return self._epoll_create(host, process)

    def sys_epoll_create1(self, host, process, thread, restarted, flags,
                          *_):
        return self._epoll_create(host, process,
                                  cloexec=bool(flags & O_CLOEXEC))

    def sys_epoll_ctl(self, host, process, thread, restarted, epfd, op, fd,
                      event_ptr, *_):
        if not self._is_emu(process, epfd):
            return _native()
        ep = self._emu(process, epfd)
        if not isinstance(ep, EpollFile):
            return _error(errno.EINVAL)
        if not self._is_emu(process, fd):
            # Native fds can't feed a simulated epoll; the reference
            # virtualizes all fds so this can't happen there.
            return _error(errno.EPERM)
        target = self._emu(process, fd)
        interest, data = 0, 0
        if event_ptr:
            interest, data = _EPOLL_EVENT.unpack(
                process.mem.read(event_ptr, 12))
        ep.ctl(host, op, fd, target, interest, data)
        return _done(0)

    def _epoll_wait_common(self, host, process, thread, restarted, epfd,
                           events_ptr, maxevents, timeout_ns):
        if not self._is_emu(process, epfd):
            return _native()
        ep = self._emu(process, epfd)
        if not isinstance(ep, EpollFile):
            return _error(errno.EINVAL)
        maxevents = max(1, min(maxevents, 1024))
        ready = ep.collect_ready(host, maxevents)
        if ready:
            out = b"".join(_EPOLL_EVENT.pack(ev, data) for ev, data in ready)
            process.mem.write(events_ptr, out)
            return _done(len(ready))
        if restarted and thread.last_condition is not None and \
                thread.last_condition.timed_out:
            return _done(0)
        if timeout_ns == 0:
            return _done(0)
        timeout_at = None if timeout_ns is None or timeout_ns < 0 \
            else host.now() + timeout_ns
        return _block(MultiSyscallCondition([(ep, S_READABLE)],
                                            timeout_at=timeout_at))

    def sys_epoll_wait(self, host, process, thread, restarted, epfd,
                       events_ptr, maxevents, timeout_ms, *_):
        timeout_ns = None if _sext32(timeout_ms) < 0 \
            else _sext32(timeout_ms) * 10**6
        return self._epoll_wait_common(host, process, thread, restarted,
                                       epfd, events_ptr, maxevents,
                                       timeout_ns)

    def sys_epoll_pwait(self, host, process, thread, restarted, epfd,
                        events_ptr, maxevents, timeout_ms, sigmask, *_):
        return self.sys_epoll_wait(host, process, thread, restarted, epfd,
                                   events_ptr, maxevents, timeout_ms)

    # ------------------------------------------------------------------
    # poll / select
    # ------------------------------------------------------------------

    @staticmethod
    def _poll_events_from_status(status: int, want: int) -> int:
        ev = 0
        if status & S_READABLE:
            ev |= POLLIN
        if status & S_WRITABLE:
            ev |= POLLOUT
        if status & S_CLOSED:
            ev |= POLLHUP | POLLIN
        if status & S_ERROR:
            ev |= POLLERR
        return ev & (want | POLLERR | POLLHUP)

    @staticmethod
    def _status_mask_from_poll(want: int) -> int:
        mask = S_CLOSED | S_ERROR
        if want & (POLLIN | POLLPRI):
            mask |= S_READABLE
        if want & POLLOUT:
            mask |= S_WRITABLE
        return mask

    def _poll_common(self, host, process, thread, restarted, fds_ptr, nfds,
                     timeout_ns):
        nfds = min(nfds, 4096)
        raw = process.mem.read(fds_ptr, _POLLFD.size * nfds)
        entries = [_POLLFD.unpack_from(raw, i * _POLLFD.size)
                   for i in range(nfds)]
        if not any(self._is_emu(process, fd) for fd, _e, _r in entries if fd >= 0):
            return _native()
        ready = 0
        out = bytearray(raw)
        watches = []
        for i, (fd, events, _rev) in enumerate(entries):
            revents = 0
            if fd >= 0:
                if self._is_emu(process, fd):
                    try:
                        file = self._emu(process, fd)
                    except OSError:
                        revents = POLLNVAL
                    else:
                        revents = self._poll_events_from_status(file.status,
                                                                events)
                        watches.append(
                            (file, self._status_mask_from_poll(events)))
                # Native fds in a mixed set: treated as never-ready (the
                # hybrid fd-space limitation; see module docstring).
            struct.pack_into("<h", out, i * _POLLFD.size + 6, revents)
            if revents:
                ready += 1
        if ready or timeout_ns == 0:
            process.mem.write(fds_ptr, bytes(out))
            return _done(ready)
        if restarted and thread.last_condition is not None and \
                thread.last_condition.timed_out:
            process.mem.write(fds_ptr, bytes(out))
            return _done(0)
        timeout_at = None if timeout_ns is None or timeout_ns < 0 \
            else host.now() + timeout_ns
        return _block(MultiSyscallCondition(watches, timeout_at=timeout_at))

    def sys_poll(self, host, process, thread, restarted, fds_ptr, nfds,
                 timeout_ms, *_):
        t = _sext32(timeout_ms)
        timeout_ns = None if t < 0 else t * 10**6
        return self._poll_common(host, process, thread, restarted, fds_ptr,
                                 nfds, timeout_ns)

    def sys_ppoll(self, host, process, thread, restarted, fds_ptr, nfds,
                  ts_ptr, sigmask, *_):
        timeout_ns = None
        if ts_ptr:
            sec, nsec = _TIMESPEC.unpack(process.mem.read(ts_ptr, 16))
            timeout_ns = sec * 10**9 + nsec
        return self._poll_common(host, process, thread, restarted, fds_ptr,
                                 nfds, timeout_ns)

    def _select_common(self, host, process, thread, restarted, nfds,
                       rfds_ptr, wfds_ptr, efds_ptr, timeout_ns):
        nfds = min(nfds, 1024)
        nbytes = (nfds + 7) // 8

        def read_set(ptr):
            if not ptr or nbytes == 0:
                return set()
            raw = process.mem.read(ptr, nbytes)
            return {fd for fd in range(nfds)
                    if raw[fd // 8] & (1 << (fd % 8))}

        rset, wset, eset = (read_set(p) for p in
                            (rfds_ptr, wfds_ptr, efds_ptr))
        all_fds = rset | wset | eset
        if not any(self._is_emu(process, fd) for fd in all_fds):
            return _native()

        r_ready, w_ready, e_ready = set(), set(), set()
        watches = []
        for fd in sorted(all_fds):
            if not self._is_emu(process, fd):
                continue  # hybrid limitation: native fds never ready
            try:
                file = self._emu(process, fd)
            except OSError:
                return _error(errno.EBADF)
            st = file.status
            if fd in rset:
                if st & (S_READABLE | S_CLOSED):
                    r_ready.add(fd)
                watches.append((file, S_READABLE | S_CLOSED))
            if fd in wset:
                if st & (S_WRITABLE | S_CLOSED):
                    w_ready.add(fd)
                watches.append((file, S_WRITABLE | S_CLOSED))
            if fd in eset and st & S_ERROR:
                e_ready.add(fd)

        total = len(r_ready) + len(w_ready) + len(e_ready)
        timed_out = (restarted and thread.last_condition is not None
                     and thread.last_condition.timed_out)
        if total or timeout_ns == 0 or timed_out:
            def write_set(ptr, ready):
                if not ptr:
                    return
                buf = bytearray(nbytes)
                for fd in ready:
                    buf[fd // 8] |= 1 << (fd % 8)
                process.mem.write(ptr, bytes(buf))
            write_set(rfds_ptr, r_ready)
            write_set(wfds_ptr, w_ready)
            write_set(efds_ptr, e_ready)
            return _done(total)
        timeout_at = None if timeout_ns is None \
            else host.now() + timeout_ns
        return _block(MultiSyscallCondition(watches, timeout_at=timeout_at))

    def sys_select(self, host, process, thread, restarted, nfds, rfds,
                   wfds, efds, tv_ptr, *_):
        timeout_ns = None
        if tv_ptr:
            sec, usec = _TIMEVAL.unpack(process.mem.read(tv_ptr, 16))
            timeout_ns = sec * 10**9 + usec * 10**3
        return self._select_common(host, process, thread, restarted, nfds,
                                   rfds, wfds, efds, timeout_ns)

    def sys_pselect6(self, host, process, thread, restarted, nfds, rfds,
                     wfds, efds, ts_ptr, sigmask):
        timeout_ns = None
        if ts_ptr:
            sec, nsec = _TIMESPEC.unpack(process.mem.read(ts_ptr, 16))
            timeout_ns = sec * 10**9 + nsec
        return self._select_common(host, process, thread, restarted, nfds,
                                   rfds, wfds, efds, timeout_ns)

    # ------------------------------------------------------------------
    # Time
    # ------------------------------------------------------------------

    def sys_clock_gettime(self, host, process, thread, restarted, clockid,
                          ts_ptr, *_):
        now = host.now()
        if clockid in (0, 5, 11):  # REALTIME, REALTIME_COARSE, TAI
            now += simtime.EMUTIME_SIMULATION_START
        if ts_ptr:
            process.mem.write(ts_ptr, _TIMESPEC.pack(now // 10**9,
                                                     now % 10**9))
        return _done(0)

    def sys_clock_getres(self, host, process, thread, restarted, clockid,
                         ts_ptr, *_):
        if ts_ptr:
            process.mem.write(ts_ptr, _TIMESPEC.pack(0, 1))
        return _done(0)

    def sys_gettimeofday(self, host, process, thread, restarted, tv_ptr,
                         tz_ptr, *_):
        now = host.now() + simtime.EMUTIME_SIMULATION_START
        if tv_ptr:
            process.mem.write(tv_ptr, _TIMEVAL.pack(now // 10**9,
                                                    (now % 10**9) // 1000))
        if tz_ptr:
            process.mem.write(tz_ptr, struct.pack("<ii", 0, 0))
        return _done(0)

    def sys_time(self, host, process, thread, restarted, tloc_ptr, *_):
        secs = (host.now() + simtime.EMUTIME_SIMULATION_START) // 10**9
        if tloc_ptr:
            process.mem.write(tloc_ptr, struct.pack("<q", secs))
        return _done(secs)

    def sys_nanosleep(self, host, process, thread, restarted, req_ptr,
                      rem_ptr, *_):
        if restarted:
            if rem_ptr:
                process.mem.write(rem_ptr, _TIMESPEC.pack(0, 0))
            return _done(0)
        sec, nsec = _TIMESPEC.unpack(process.mem.read(req_ptr, 16))
        duration = sec * 10**9 + nsec
        if duration <= 0:
            return _done(0)
        return _block(SyscallCondition(timeout_at=host.now() + duration))

    def sys_clock_nanosleep(self, host, process, thread, restarted, clockid,
                            flags, req_ptr, rem_ptr, *_):
        if restarted:
            if rem_ptr and not (flags & TIMER_ABSTIME):
                process.mem.write(rem_ptr, _TIMESPEC.pack(0, 0))
            return _done(0)
        sec, nsec = _TIMESPEC.unpack(process.mem.read(req_ptr, 16))
        when = sec * 10**9 + nsec
        if flags & TIMER_ABSTIME:
            if clockid == CLOCK_REALTIME:
                when -= simtime.EMUTIME_SIMULATION_START
            target = when
        else:
            target = host.now() + when
        if target <= host.now():
            return _done(0)
        return _block(SyscallCondition(timeout_at=target))

    # -- ITIMER_REAL / alarm: SIGALRM at a simulated deadline ---------

    @staticmethod
    def _itimer_remaining_ns(host, process) -> int:
        fire_at = getattr(process, "itimer_fire_at", None)
        if fire_at is None:
            return 0
        return max(0, fire_at - host.now())

    @staticmethod
    def _itimer_schedule(host, process, when: int) -> None:
        """Queue a wakeup at `when` unless an already-queued one covers
        it (re-arming alarm(N) per request must not accumulate one dead
        task per call in the event queue — the hot path)."""
        from shadow_tpu.core.event import TaskRef
        wakes = process.__dict__.setdefault("_itimer_wakes", [])
        if any(w <= when for w in wakes):
            return  # an earlier task will re-check fire_at and re-park
        wakes.append(when)
        host.schedule_task_at(when, TaskRef(
            "itimer",
            lambda h, w=when: NativeSyscallHandler._itimer_fire(
                h, process, w)))

    @staticmethod
    def _itimer_fire(host, process, when: int) -> None:
        from shadow_tpu.host.signals import SIGALRM
        wakes = process.__dict__.setdefault("_itimer_wakes", [])
        try:
            wakes.remove(when)
        except ValueError:
            pass
        if process.exited:
            return
        target = getattr(process, "itimer_fire_at", None)
        if target is None:
            return  # disarmed since this task was queued
        if host.now() < target:
            NativeSyscallHandler._itimer_schedule(host, process, target)
            return  # re-armed to a later deadline; re-park once
        if getattr(process, "itimer_interval", 0):
            process.itimer_fire_at = host.now() + process.itimer_interval
            NativeSyscallHandler._itimer_schedule(host, process,
                                                  process.itimer_fire_at)
        else:
            process.itimer_fire_at = None
        from shadow_tpu.host.signals import SI_KERNEL
        process.raise_signal(host, SIGALRM, si_code=SI_KERNEL)

    @staticmethod
    def _itimer_set(host, process, value_ns: int, interval_ns: int) -> None:
        process.itimer_interval = interval_ns
        if value_ns <= 0:
            process.itimer_fire_at = None
            return
        process.itimer_fire_at = host.now() + value_ns
        NativeSyscallHandler._itimer_schedule(host, process,
                                              process.itimer_fire_at)

    def sys_alarm(self, host, process, thread, restarted, seconds, *_):
        remaining = self._itimer_remaining_ns(host, process)
        self._itimer_set(host, process, int(seconds) * 10**9, 0)
        return _done((remaining + 10**9 - 1) // 10**9)

    _ITIMERVAL = struct.Struct("<qqqq")  # interval sec/usec, value sec/usec

    def sys_setitimer(self, host, process, thread, restarted, which,
                      new_ptr, old_ptr, *_):
        if which > 2 or which < 0:
            return _error(errno.EINVAL)  # Linux: EINVAL for bad `which`
        if which != 0:  # ITIMER_VIRTUAL/PROF need modeled cpu time
            from shadow_tpu.utils.shadow_log import LOG
            LOG.warn_once(f"setitimer-{which}",
                          f"setitimer(which={which}) accepted but not "
                          "modeled (no per-process CPU clock); the timer "
                          "never fires")
            if old_ptr:  # Linux always fills *old_value on success
                process.mem.write(old_ptr, self._ITIMERVAL.pack(0, 0, 0, 0))
            return _done(0)
        if old_ptr:
            rem = self._itimer_remaining_ns(host, process)
            iv = getattr(process, "itimer_interval", 0)
            process.mem.write(old_ptr, self._ITIMERVAL.pack(
                iv // 10**9, (iv % 10**9) // 1000,
                rem // 10**9, (rem % 10**9) // 1000))
        if new_ptr:
            isec, iusec, vsec, vusec = self._ITIMERVAL.unpack(
                process.mem.read(new_ptr, 32))
            self._itimer_set(host, process,
                             vsec * 10**9 + vusec * 1000,
                             isec * 10**9 + iusec * 1000)
        return _done(0)

    def sys_getitimer(self, host, process, thread, restarted, which,
                      curr_ptr, *_):
        if which > 2 or which < 0:
            return _error(errno.EINVAL)
        if which != 0:  # VIRTUAL/PROF: accepted-but-unmodeled => disarmed
            if curr_ptr:
                process.mem.write(curr_ptr, self._ITIMERVAL.pack(0, 0, 0, 0))
            return _done(0)
        if curr_ptr:
            rem = self._itimer_remaining_ns(host, process)
            iv = getattr(process, "itimer_interval", 0)
            process.mem.write(curr_ptr, self._ITIMERVAL.pack(
                iv // 10**9, (iv % 10**9) // 1000,
                rem // 10**9, (rem % 10**9) // 1000))
        return _done(0)

    def sys_pause(self, host, process, thread, restarted, *_):
        # Sleep until an (unsupported) signal: park forever — the
        # process's shutdown_time or sim end tears it down.
        return _block(SyscallCondition(timeout_at=simtime.TIME_NEVER - 1))

    # ------------------------------------------------------------------
    # Identity / misc
    # ------------------------------------------------------------------

    def sys_getpid(self, host, process, thread, restarted, *_):
        return _done(process.pid)

    def sys_gettid(self, host, process, thread, restarted, *_):
        return _done(thread.tid)

    def sys_getppid(self, host, process, thread, restarted, *_):
        return _done(process.parent_pid if process.parent_pid else 1)

    def sys_getsid(self, host, process, thread, restarted, pid=0, *_):
        pid = _sext32(pid)
        if pid < 0:
            return _error(errno.ESRCH)
        target = host.processes.get(pid) if pid else process
        if target is None:
            return _error(errno.ESRCH)
        return _done(target.sid)

    def sys_setsid(self, host, process, thread, restarted, *_):
        """New session (daemonize step 2): fails for a group leader,
        exactly like Linux (ref handler/sched-family)."""
        if process.pgid == process.pid:
            return _error(errno.EPERM)
        process.pgid = process.pid
        process.sid = process.pid
        return _done(process.sid)

    def sys_setpgid(self, host, process, thread, restarted, pid, pgid, *_):
        pid, pgid = _sext32(pid), _sext32(pgid)
        if pid < 0 or pgid < 0:
            return _error(errno.EINVAL)
        target = host.processes.get(pid) if pid else process
        if target is None:
            return _error(errno.ESRCH)
        if target is not process and target.parent_pid != process.pid:
            return _error(errno.ESRCH)  # only self or own children
        if target.sid == target.pid:
            return _error(errno.EPERM)  # session leaders are immovable
        if target.sid != process.sid:
            return _error(errno.EPERM)  # child already in another session
        pgid = pgid or target.pid
        # Joining an existing group requires it to live in our session.
        owner = next((p for p in host.processes.values()
                      if p.pgid == pgid and not p.exited), None)
        if pgid != target.pid and (owner is None
                                   or owner.sid != process.sid):
            return _error(errno.EPERM)
        target.pgid = pgid
        return _done(0)

    def sys_getpgid(self, host, process, thread, restarted, pid=0, *_):
        pid = _sext32(pid)
        if pid < 0:
            return _error(errno.ESRCH)
        target = host.processes.get(pid) if pid else process
        if target is None:
            return _error(errno.ESRCH)
        return _done(target.pgid)

    def sys_getpgrp(self, host, process, thread, restarted, *_):
        return _done(process.pgid)

    def sys_getuid(self, host, process, thread, restarted, *_):
        return _done(1000)

    def sys_geteuid(self, host, process, thread, restarted, *_):
        return _done(1000)

    def sys_getgid(self, host, process, thread, restarted, *_):
        return _done(1000)

    def sys_getegid(self, host, process, thread, restarted, *_):
        return _done(1000)

    def sys_uname(self, host, process, thread, restarted, buf_ptr, *_):
        def field(s: str) -> bytes:
            b = s.encode()[:64]
            return b + b"\0" * (65 - len(b))
        data = (field("Linux") + field(host.name) +
                field("5.15.0-shadowtpu") +
                field("#1 SMP shadow-tpu simulated") + field("x86_64") +
                field("(none)"))
        process.mem.write(buf_ptr, data)
        return _done(0)

    def sys_getrusage(self, host, process, thread, restarted, who,
                      usage_ptr, *_):
        """Deterministic rusage: a native getrusage would leak real
        CPU times and fault counts into the simulation.  User time is
        the modeled CPU the latency model billed; all else is zero
        except a fixed maxrss."""
        RUSAGE_SELF, RUSAGE_CHILDREN, RUSAGE_THREAD = 0, -1, 1
        who = _sext32(who)
        if who == RUSAGE_SELF:
            billed = sum(getattr(t, "cpu_total_ns", 0)
                         for t in process.threads)
        elif who == RUSAGE_THREAD:
            billed = getattr(thread, "cpu_total_ns", 0)
        elif who == RUSAGE_CHILDREN:
            billed = 0  # reaped-children usage is not accumulated
        else:
            return _error(errno.EINVAL)
        utime_us = billed // 1000
        # struct rusage: ru_utime, ru_stime (timevals), then 14 longs.
        buf = struct.pack("<qqqq", utime_us // 10**6, utime_us % 10**6,
                          0, 0)
        buf += struct.pack("<q", 16384)  # ru_maxrss (kB), fixed
        buf += b"\0" * (8 * 13)
        process.mem.write(usage_ptr, buf)
        return _done(0)

    def sys_sysinfo(self, host, process, thread, restarted, info_ptr, *_):
        up = host.now() // 10**9
        gib = 1 << 30
        data = struct.pack("<q3Q", up, 0, 0, 0)          # uptime, loads
        data += struct.pack("<6Q", 16 * gib, 8 * gib, 0, 0, 0, 0)
        data += struct.pack("<HH", 1, 0)                  # procs, pad
        data += struct.pack("<QQI", 0, 0, 1)              # high mem, unit
        data += b"\0" * (112 - len(data))
        process.mem.write(info_ptr, data[:112])
        return _done(0)

    def sys_times(self, host, process, thread, restarted, buf_ptr, *_):
        ticks = host.now() // 10_000_000  # 100 Hz clock ticks
        if buf_ptr:
            process.mem.write(buf_ptr, struct.pack("<4q", ticks, 0, 0, 0))
        return _done(ticks)

    def sys_getrandom(self, host, process, thread, restarted, buf_ptr,
                      count, flags, *_):
        n = min(count, _MAX_IO)
        process.mem.write(buf_ptr, host.rng.bytes(n))
        return _done(n)

    def sys_shadow_yield(self, host, process, thread, restarted,
                         sim_ns, *_):
        """Native preemption (preempt.rs): the managed thread burned a
        native CPU slice without syscalls; bill the configured simulated
        interval so the spin loop makes simulated progress (and the
        thread parks until the event queue catches up)."""
        ns = int(sim_ns) if sim_ns > 0 else host.preempt_sim_ns
        thread.add_cpu_latency(ns)
        if host.cpu is not None:
            host.cpu.add_delay(ns)
        return _done(0)

    def sys_sched_getaffinity(self, host, process, thread, restarted,
                              tid, cpusetsize, mask_ptr, *_):
        """One simulated CPU (ref handler/sched.rs): a native answer
        would leak the real machine's core count, which apps use to
        size thread pools — nondeterministic across machines."""
        tid = _sext32(tid)
        if tid and not any(t.tid == tid for t in process.threads):
            return _error(errno.ESRCH)
        if cpusetsize < 8:
            return _error(errno.EINVAL)
        process.mem.write(mask_ptr, struct.pack("<Q", 1))
        return _done(8)  # bytes written, like the kernel

    def sys_sched_setaffinity(self, host, process, thread, restarted,
                              tid, cpusetsize, mask_ptr, *_):
        tid = _sext32(tid)
        if tid and not any(t.tid == tid for t in process.threads):
            return _error(errno.ESRCH)
        # Otherwise accepted and inert: one simulated CPU.
        return _done(0)

    def sys_sched_yield(self, host, process, thread, restarted, *_):
        # The shim forwards one of these per LOCAL_TIME_FORWARD_EVERY
        # locally-answered time reads; bill the batch so time-polling
        # loops advance the clock (handler/mod.rs:271-321).  Scaled by
        # the configured per-syscall latency (0 = model disabled).
        batch_ns = 25 * host.syscall_latency_ns
        thread.add_cpu_latency(batch_ns)
        if host.cpu is not None and batch_ns:
            host.cpu.add_delay(batch_ns)
        return _done(0)

    # ------------------------------------------------------------------
    # Guard rails
    # ------------------------------------------------------------------

    # -- signals (ref: handler/signal.rs + shim/src/signals.rs; our
    #    delivery machinery lives in host/signals.py + managed.py) ----

    _SIG_BLOCK, _SIG_UNBLOCK, _SIG_SETMASK = 0, 1, 2

    def sys_rt_sigaction(self, host, process, thread, restarted, signum,
                         act_ptr, old_ptr, sigsetsize, *_):
        from shadow_tpu.host import signals as S
        if signum < 1 or signum >= S.NSIG or \
                (act_ptr and signum in (S.SIGKILL, S.SIGSTOP)):
            return _error(errno.EINVAL)
        sigs = process.signals
        old = sigs.action(signum)
        if act_ptr:
            handler, flags, restorer, mask = struct.unpack(
                "<QQQQ", process.mem.read(act_ptr, 32))
            sigs.actions[signum] = S.SigAction(handler, flags, restorer,
                                               mask)
        if old_ptr:
            process.mem.write(old_ptr, struct.pack(
                "<QQQQ", old.handler, old.flags, old.restorer, old.mask))
        # Hardware-fault handlers are ALSO installed natively so a real
        # fault in managed code reaches the app handler — except
        # SIGSEGV, whose native slot belongs to the shim's rdtsc trap:
        # the app's action is published through the IPC header and the
        # shim chains real faults to it.  SIGSYS stays the shim's.
        if act_ptr and signum == S.SIGSEGV:
            block = getattr(process, "ipc_block", None)
            if block is not None:
                act = sigs.action(signum)
                block.set_sigsegv_action(act.handler, act.flags)
            return _done(0)
        if act_ptr and signum in S.FAULT_SIGNALS:
            return _native()
        return _done(0)

    def sys_rt_sigprocmask(self, host, process, thread, restarted, how,
                           set_ptr, old_ptr, sigsetsize, *_):
        from shadow_tpu.host import signals as S
        old = thread.sig_mask
        if old_ptr:
            process.mem.write(old_ptr, struct.pack("<Q", old))
        if set_ptr:
            (m,) = struct.unpack("<Q", process.mem.read(set_ptr, 8))
            if how == self._SIG_BLOCK:
                new = old | m
            elif how == self._SIG_UNBLOCK:
                new = old & ~m
            elif how == self._SIG_SETMASK:
                new = m
            else:
                return _error(errno.EINVAL)
            thread.sig_mask = new & ~(S.bit(S.SIGKILL) | S.bit(S.SIGSTOP))
        # Newly unblocked pending signals are picked up at this response
        # point by the ManagedThread delivery check.
        return _done(0)

    def sys_rt_sigpending(self, host, process, thread, restarted, set_ptr,
                          sigsetsize, *_):
        if set_ptr:
            mask = process.signals.pending_mask(thread) & thread.sig_mask
            process.mem.write(set_ptr, struct.pack("<Q", mask))
        return _done(0)

    def sys_rt_sigsuspend(self, host, process, thread, restarted, mask_ptr,
                          sigsetsize, *_):
        from shadow_tpu.core import simtime
        from shadow_tpu.host import signals as S
        if restarted:  # spurious resume without a signal: keep waiting
            return _block(SyscallCondition(
                timeout_at=simtime.TIME_NEVER - 1))
        (m,) = struct.unpack("<Q", process.mem.read(mask_ptr, 8))
        thread._suspend_restore = thread.sig_mask
        thread.sig_mask = m & ~(S.bit(S.SIGKILL) | S.bit(S.SIGSTOP))
        if process.signals.has_deliverable(thread):
            # Deliverable immediately: the response-point check runs the
            # handler, then this EINTR goes out with the mask restored.
            return _error(errno.EINTR)
        return _block(SyscallCondition(timeout_at=simtime.TIME_NEVER - 1))

    def sys_rt_sigtimedwait(self, host, process, thread, restarted,
                            set_ptr, info_ptr, ts_ptr, sigsetsize, *_):
        from shadow_tpu.host import signals as S
        (want,) = struct.unpack("<Q", process.mem.read(set_ptr, 8))
        if restarted:
            got, thread._sigwait_got = thread._sigwait_got, None
            thread._sigwait_set = 0
            if got is None:
                return _error(errno.EAGAIN)  # timed out
            if info_ptr:
                process.mem.write(info_ptr, _pack_siginfo(
                    got, *thread._sigwait_info))
            return _done(got)
        # Already pending?
        pending = sorted(thread.sig_pending |
                         process.signals.pending_process)
        for s in pending:
            if want & S.bit(s):
                thread.sig_pending.discard(s)
                process.signals.pending_process.discard(s)
                process.refresh_signal_fds(host)
                if info_ptr:
                    process.mem.write(info_ptr, _pack_siginfo(
                        s, *process.signals.take_info(s)))
                return _done(s)
        timeout_at = None
        if ts_ptr:
            sec, nsec = _TIMESPEC.unpack(process.mem.read(ts_ptr, 16))
            if sec == 0 and nsec == 0:
                return _error(errno.EAGAIN)
            timeout_at = host.now() + sec * 10**9 + nsec
        else:
            from shadow_tpu.core import simtime
            timeout_at = simtime.TIME_NEVER - 1
        thread._sigwait_set = want
        from shadow_tpu.host.condition import ManualCondition
        return _block(ManualCondition(timeout_at=timeout_at))

    def sys_signalfd4(self, host, process, thread, restarted, fd,
                      mask_ptr, sizemask, flags, *_):
        """signalfd(2): pending signals as readable records (event-loop
        daemons' signal plumbing).  fd == -1 creates; otherwise the
        mask of an existing signalfd is replaced."""
        from shadow_tpu.host.files import SignalFd
        (mask,) = struct.unpack("<Q", process.mem.read(mask_ptr, 8))
        fd = _sext32(fd)
        if fd != -1:
            if not self._is_emu(process, fd):
                return _error(errno.EINVAL)
            sfd = self._emu(process, fd)
            if not isinstance(sfd, SignalFd):
                return _error(errno.EINVAL)
            sfd.mask = mask
            sfd.refresh(host)
            return _done(fd)
        sfd = SignalFd(process, mask)
        sfd.nonblocking = bool(flags & O_NONBLOCK)
        sfd.refresh(host)  # signals may already be pending
        return _done(self._register(process, sfd,
                                    cloexec=bool(flags & O_CLOEXEC)))

    def sys_signalfd(self, host, process, thread, restarted, fd,
                     mask_ptr, sizemask, *_):
        return self.sys_signalfd4(host, process, thread, restarted, fd,
                                  mask_ptr, sizemask, 0)

    def sys_sigaltstack(self, host, process, thread, restarted, *_):
        return _native()  # only affects native (fault) delivery

    def sys_rt_sigreturn(self, host, process, thread, restarted, *_):
        return _native()  # seccomp always allows it; defensive

    def _signal_targets(self, host, process, pid: int):
        """kill(2) addressing: pid > 0 one process; 0 the caller's
        process group; -1 everything except the caller (Linux excludes
        it from broadcast); -pgid that group.  Zombies are included —
        an unreaped member keeps its group alive for existence probes —
        but raise_signal no-ops on them."""
        if pid > 0:
            t = host.processes.get(pid)
            return [t] if t is not None else []
        if pid == 0:
            return [p for p in host.processes.values()
                    if p.pgid == process.pgid]
        if pid == -1:
            return [p for p in host.processes.values() if p is not process]
        return [p for p in host.processes.values() if p.pgid == -pid]

    def sys_kill(self, host, process, thread, restarted, pid, sig, *_):
        from shadow_tpu.host import signals as S
        pid = _sext32(pid)
        if sig < 0 or sig >= S.NSIG:
            return _error(errno.EINVAL)
        targets = self._signal_targets(host, process, pid)
        if not targets:
            return _error(errno.ESRCH)
        if sig == 0:
            return _done(0)
        for target in targets:
            target.raise_signal(host, sig, si_code=S.SI_USER,
                                si_pid=process.pid)
        return _done(0)

    def sys_tkill(self, host, process, thread, restarted, tid, sig, *_):
        return self.sys_tgkill(host, process, thread, restarted,
                               process.pid, tid, sig)

    def sys_tgkill(self, host, process, thread, restarted, tgid, tid, sig,
                   *_):
        from shadow_tpu.host import signals as S
        if sig < 0 or sig >= S.NSIG:
            return _error(errno.EINVAL)
        from shadow_tpu.host.process import ST_EXITED
        target = host.processes.get(tgid)
        if target is None or not any(
                t.tid == tid and t.state != ST_EXITED
                for t in target.threads):
            return _error(errno.ESRCH)
        if sig == 0:
            return _done(0)
        target.raise_signal(host, sig, target_tid=tid, si_code=S.SI_TKILL,
                            si_pid=process.pid)
        return _done(0)

    def sys_prctl(self, host, process, thread, restarted, option, *rest):
        PR_SET_SECCOMP = 22
        if option == PR_SET_SECCOMP:
            return _error(errno.EPERM)
        return _native()

    def sys_seccomp(self, host, process, thread, restarted, *_):
        return _error(errno.EPERM)  # one filter is enough

    # -- threads (clone/futex; ref handler/clone.rs, futex.rs) ---------

    _CLONE_VM = 0x100
    _CLONE_FILES = 0x400
    _CLONE_VFORK = 0x4000
    _CLONE_SETTLS = 0x80000
    _CLONE_THREAD = 0x10000
    _CLONE_CHILD_CLEARTID = 0x200000

    def sys_clone(self, host, process, thread, restarted, flags, stack,
                  ptid, ctid, tls, *_):
        """Thread-creation clone runs the three-way channel handshake
        (managed.py _do_clone); a clone WITHOUT CLONE_THREAD is a fork
        (glibc fork(), posix_spawn()'s CLONE_VM|CLONE_VFORK clone) and
        routes to the fork protocol — the shim runs a plain
        clone(SIGCHLD|CLONE_PARENT), so posix_spawn's shared-VM
        optimization degrades to copy-on-write (its exec-failure errno
        reporting through shared memory is lost; the exec path itself
        works).  CLONE_SETTLS is required for threads: the shim's
        per-thread channel pointer lives in fs-relative TLS."""
        if not (flags & self._CLONE_THREAD):
            # Shared-state clones that COW fork semantics cannot honor
            # are refused rather than silently diverging: CLONE_FILES
            # (shared fd table) always; CLONE_VM only in its vfork-like
            # exec idiom (posix_spawn), where the sharing is unobserved.
            if flags & self._CLONE_FILES:
                return _error(errno.ENOSYS)
            if (flags & self._CLONE_VM) and not (flags & self._CLONE_VFORK):
                return _error(errno.ENOSYS)
            return ("fork",)
        if (flags & self._CLONE_VM) and (flags & self._CLONE_SETTLS):
            return ("clone", flags, ctid)
        return _error(errno.ENOSYS)

    def sys_clone3(self, host, process, thread, restarted, *_):
        return _error(errno.ENOSYS)  # glibc falls back to clone

    def sys_fork(self, host, process, thread, restarted, *_):
        return ("fork",)

    def sys_vfork(self, host, process, thread, restarted, *_):
        # Emulated as fork: the child gets a COW copy instead of the
        # parent's suspended address space.  Safe for the fork+exec
        # pattern vfork exists for.
        return ("fork",)

    def sys_execve(self, host, process, thread, restarted, path_ptr,
                   argv_ptr, envp_ptr, *_):
        """Read path/argv/envp out of the old image, then let the
        ManagedThread replace the native process (managed.py
        _do_execve; ref process.rs:297 spawn_mthread_for_exec)."""
        path = process.mem.read_cstr(path_ptr, 4096).decode(
            errors="surrogateescape")

        def read_ptr_vec(ptr, limit=8192):
            out = []
            for i in range(limit):
                (p,) = struct.unpack(
                    "<Q", process.mem.read(ptr + 8 * i, 8))
                if p == 0:
                    return out
                out.append(process.mem.read_cstr(p, 1 << 17).decode(
                    errors="surrogateescape"))
            # Vector larger than we model: refuse loudly (Linux E2BIG)
            # rather than exec with a silently clipped argv/environment.
            raise OSError(errno.E2BIG, "argv/envp exceeds limit")

        try:
            argv = read_ptr_vec(argv_ptr) if argv_ptr else []
            envp = read_ptr_vec(envp_ptr) if envp_ptr else []
        except OSError as e:
            return _error(e.errno)
        return ("execve", path, argv, envp)

    def sys_set_tid_address(self, host, process, thread, restarted, addr,
                            *_):
        thread.ctid_addr = addr
        return _done(thread.native_tid or thread.tid)

    def sys_set_robust_list(self, host, process, thread, restarted, *_):
        # Robust-mutex recovery after thread death is out of scope; the
        # kernel-side list walk never happens for emulated futexes anyway.
        return _done(0)

    def sys_get_robust_list(self, host, process, thread, restarted, *_):
        return _error(errno.ENOSYS)

    def sys_rseq(self, host, process, thread, restarted, *_):
        return _error(errno.ENOSYS)  # glibc degrades gracefully

    # futex ops (uapi/linux/futex.h)
    _FUTEX_WAIT = 0
    _FUTEX_WAKE = 1
    _FUTEX_REQUEUE = 3
    _FUTEX_CMP_REQUEUE = 4
    _FUTEX_WAKE_OP = 5
    _FUTEX_WAIT_BITSET = 9
    _FUTEX_WAKE_BITSET = 10
    _FUTEX_PRIVATE = 128
    _FUTEX_CLOCK_REALTIME = 256

    def sys_futex(self, host, process, thread, restarted, addr, op, val,
                  timeout_or_val2, addr2, val3):
        """Emulated futexes (ref: futex_table.rs, futex.c, and the futex
        trigger of syscall_condition.c).  Every waiter parks on the
        simulated timeline; wakes come from sibling threads' emulated
        FUTEX_WAKE — never from the native kernel, whose futex queue the
        managed threads bypass entirely."""
        from shadow_tpu.host.condition import ManualCondition

        cmd = op & ~(self._FUTEX_PRIVATE | self._FUTEX_CLOCK_REALTIME)
        table = process.futex_table

        if cmd in (self._FUTEX_WAIT, self._FUTEX_WAIT_BITSET):
            if restarted:
                waiter, thread.futex_waiter = thread.futex_waiter, None
                if waiter is not None and waiter.woken:
                    return _done(0)
                if (thread.last_condition is not None
                        and thread.last_condition.timed_out):
                    return _error(errno.ETIMEDOUT)
                return _done(0)  # spurious wake: apps must re-check anyway
            val &= 0xFFFFFFFF
            cur = process.mem.try_read(addr, 4)
            if cur is None:
                return _error(errno.EFAULT)
            if int.from_bytes(cur, "little") != val:
                return _error(errno.EAGAIN)
            timeout_at = None
            if timeout_or_val2:
                ts = process.mem.try_read(timeout_or_val2, 16)
                if ts is None:
                    return _error(errno.EFAULT)
                sec, nsec = struct.unpack("<qq", ts)
                t = sec * 1_000_000_000 + nsec
                if cmd == self._FUTEX_WAIT:
                    timeout_at = host.now() + t  # relative
                else:
                    # WAIT_BITSET: absolute, in the flagged clock.
                    if op & self._FUTEX_CLOCK_REALTIME:
                        t -= simtime.EMUTIME_SIMULATION_START
                    timeout_at = max(t, host.now())
            bitset = (val3 & 0xFFFFFFFF) \
                if cmd == self._FUTEX_WAIT_BITSET else 0xFFFFFFFF
            if bitset == 0:
                return _error(errno.EINVAL)
            cond = ManualCondition(timeout_at=timeout_at)
            thread.futex_waiter = table.add_waiter(addr, cond, bitset)
            return ("block", cond)

        if cmd in (self._FUTEX_WAKE, self._FUTEX_WAKE_BITSET):
            bitset = (val3 & 0xFFFFFFFF) \
                if cmd == self._FUTEX_WAKE_BITSET else 0xFFFFFFFF
            if bitset == 0:
                return _error(errno.EINVAL)
            return _done(table.wake(host, addr, _sext32(val), bitset))

        if cmd in (self._FUTEX_REQUEUE, self._FUTEX_CMP_REQUEUE):
            if cmd == self._FUTEX_CMP_REQUEUE:
                cur = process.mem.try_read(addr, 4)
                if cur is None:
                    return _error(errno.EFAULT)
                if int.from_bytes(cur, "little") != (val3 & 0xFFFFFFFF):
                    return _error(errno.EAGAIN)
            woken, moved = table.requeue(host, addr, _sext32(val),
                                         _sext32(timeout_or_val2), addr2)
            if cmd == self._FUTEX_CMP_REQUEUE:
                return _done(woken + moved)
            return _done(woken)

        # PI / WAKE_OP and friends: no in-tree consumer yet.  Binaries
        # using PI mutexes or raw WAKE_OP may hang on the ENOSYS, so
        # surface the gap once, visibly (ADVICE parity note).
        from shadow_tpu.utils.shadow_log import LOG
        LOG.warn_once(
            f"futex-op-{cmd}",
            f"unsupported futex op {cmd} from pid {process.pid} "
            f"({process.name}): returning ENOSYS — PI mutexes / "
            f"FUTEX_WAKE_OP are not emulated",
            sim_ns=host.now(), host=host.name)
        return _error(errno.ENOSYS)

    _WNOHANG = 1

    @staticmethod
    def _wait_matches(host, process, pid: int, child) -> bool:
        """waitpid addressing: -1 any child; > 0 that child; 0 children
        in the CALLER's process group; < -1 children in group |pid|."""
        if pid == -1:
            return True
        if pid > 0:
            return child.pid == pid
        if pid == 0:
            return child.pgid == process.pgid
        return child.pgid == -pid

    def _reap_zombie(self, host, process, pid: int, consume: bool = True):
        """Pop (or, under waitid's WNOWAIT, peek) a matching zombie
        child; returns (child_pid, status) or None."""
        for zpid in process.zombies:
            if not self._wait_matches(host, process, pid,
                                      host.processes[zpid]):
                continue
            if consume:
                process.zombies.remove(zpid)
            child = host.processes[zpid]
            if child.term_signal is not None:
                status = child.term_signal & 0x7f
            else:
                status = (int(child.exit_code or 0) & 0xff) << 8
            return zpid, status
        return None

    def _has_children(self, host, process, pid: int) -> bool:
        """Waitable children: live ones plus unreaped zombies (an
        exited-and-reaped child no longer counts — ECHILD)."""
        for p in host.processes.values():
            if p.parent_pid != process.pid:
                continue
            if not self._wait_matches(host, process, pid, p):
                continue
            if not p.exited or p.pid in process.zombies:
                return True
        return False

    _WUNTRACED = 2
    _WCONTINUED = 8

    def _jobctl_report(self, host, process, pid: int, options: int,
                       consume: bool = True):
        """WUNTRACED/WCONTINUED: one report per stop/continue
        transition (Linux wait semantics; waitid's WNOWAIT peeks
        without clearing); returns (child_pid, status) or None.
        Iteration over host.processes is pid-ordered —
        deterministic."""
        if not (options & (self._WUNTRACED | self._WCONTINUED)):
            return None
        for p in host.processes.values():
            if p.exited or p.parent_pid != process.pid or \
                    not self._wait_matches(host, process, pid, p):
                continue
            if (options & self._WUNTRACED) and p.stopped \
                    and p.stop_report is not None:
                sig = p.stop_report
                if consume:
                    p.stop_report = None
                return p.pid, (sig << 8) | 0x7F
            if (options & self._WCONTINUED) and p.continue_report:
                if consume:
                    p.continue_report = False
                return p.pid, 0xFFFF
        return None

    def sys_wait4(self, host, process, thread, restarted, pid, status_ptr,
                  options, rusage_ptr, *_):
        pid = _sext32(pid)
        reaped = self._reap_zombie(host, process, pid)
        if reaped is None:
            reaped = self._jobctl_report(host, process, pid, options)
        if reaped is not None:
            zpid, status = reaped
            if status_ptr:
                process.mem.write(status_ptr, struct.pack("<i", status))
            if rusage_ptr:
                process.mem.write(rusage_ptr, b"\0" * 144)
            return _done(zpid)
        if not self._has_children(host, process, pid):
            return _error(errno.ECHILD)
        if options & self._WNOHANG:
            return _done(0)
        return self._park_wait(process)

    @staticmethod
    def _park_wait(process):
        """Block until a child exits (child_exited fires the cond)."""
        from shadow_tpu.host.condition import ManualCondition
        cond = ManualCondition()
        process._wait_conds.append(cond)

        def drop():
            if cond in process._wait_conds:
                process._wait_conds.remove(cond)
        cond.on_disarm = drop
        return _block(cond)

    def sys_waitid(self, host, process, thread, restarted, idtype, id_,
                   info_ptr, options, rusage_ptr, *_):
        P_ALL, P_PID = 0, 1
        W_EXITED, W_STOPPED, W_CONTINUED = 4, 2, 8
        W_NOWAIT = 0x01000000
        if not (options & (W_EXITED | W_STOPPED | W_CONTINUED)):
            return _error(errno.EINVAL)  # Linux: must name a state set
        consume = not (options & W_NOWAIT)  # WNOWAIT: peek, stay waitable
        if idtype == P_ALL:
            pid = -1
        elif idtype == P_PID:
            if int(id_) <= 0:
                return _error(errno.EINVAL)
            pid = int(id_)
        else:
            return _error(errno.EINVAL)

        from shadow_tpu.host.signals import (CLD_CONTINUED, CLD_STOPPED,
                                             SIGCHLD, SIGCONT)

        def write_info(zpid, code, st):
            info = struct.pack("<iii", SIGCHLD, 0, code)
            info += b"\0" * 4 + struct.pack("<iii", zpid, 1000, st)
            process.mem.write(info_ptr,
                              info + b"\0" * (128 - len(info)))

        if options & W_EXITED:
            reaped = self._reap_zombie(host, process, pid,
                                       consume=consume)
            if reaped is not None:
                zpid, status = reaped
                if info_ptr:
                    CLD_EXITED, CLD_KILLED = 1, 2
                    if status & 0x7f:
                        code, st = CLD_KILLED, status & 0x7f
                    else:
                        code, st = CLD_EXITED, (status >> 8) & 0xff
                    write_info(zpid, code, st)
                return _done(0)
        jc_opts = (self._WUNTRACED if options & W_STOPPED else 0) \
            | (self._WCONTINUED if options & W_CONTINUED else 0)
        jc = self._jobctl_report(host, process, pid, jc_opts,
                                 consume=consume)
        if jc is not None:
            zpid, status = jc
            if info_ptr:
                if status == 0xFFFF:
                    write_info(zpid, CLD_CONTINUED, SIGCONT)
                else:
                    write_info(zpid, CLD_STOPPED, (status >> 8) & 0xFF)
            return _done(0)
        if not self._has_children(host, process, pid):
            return _error(errno.ECHILD)
        if options & self._WNOHANG:
            if info_ptr:
                process.mem.write(info_ptr, b"\0" * 128)
            return _done(0)
        return self._park_wait(process)

    def sys_exit(self, host, process, thread, restarted, code, *_):
        from shadow_tpu.host.managed import ManagedProcess
        if isinstance(process, ManagedProcess) \
                and process.live_managed_threads() > 1:
            return ("thread_exit", code & 0xff)
        return ("exit", code & 0xff)

    def sys_exit_group(self, host, process, thread, restarted, code, *_):
        return ("exit", code & 0xff)


def _sext32(v: int) -> int:
    """Register values arrive zero-extended; poll timeouts are i32."""
    v &= 0xffffffff
    return v - (1 << 32) if v & (1 << 31) else v
