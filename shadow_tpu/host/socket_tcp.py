"""TCP sockets: the simulated-kernel adapter around the sans-I/O
TcpConnection (ref: src/main/host/descriptor/socket/inet/tcp.rs wrapping
src/lib/tcp — same split: protocol logic in the crate, kernel glue here).

A TcpSocket is either a *listener* (accept queue of handshaking children)
or a *stream* (one TcpConnection). Children are created on inbound SYN,
registered under their specific 4-tuple, and surface through accept()
once established.
"""

from __future__ import annotations

import errno
from collections import deque

from shadow_tpu.core.event import TaskRef
from shadow_tpu.host.condition import SyscallCondition
from shadow_tpu.host.status import (S_ACTIVE, S_CLOSED, S_READABLE,
                                    S_WRITABLE, StatusOwner)
from shadow_tpu.net import packet as pkt
from shadow_tpu.net.graph import LOCALHOST_IP
from shadow_tpu.tcp import connection as tcpc
from shadow_tpu.trace.events import TEL_REASM_FULL, TEL_RECVWIN_TRUNC

INADDR_ANY = 0
EPHEMERAL_LO = 32_768
EPHEMERAL_HI = 65_536


# Autotuner clamps live in the connection module (single source of
# truth with the SYN-time window-scale ceiling).
WMEM_MAX = tcpc.WMEM_MAX
RMEM_MAX = tcpc.RMEM_MAX


class TcpSocket(StatusOwner):
    def __init__(self, host, send_buf: int, recv_buf: int,
                 send_autotune: bool = True, recv_autotune: bool = True):
        super().__init__()
        self.protocol = pkt.PROTO_TCP
        self.local = None
        self.peer = None
        self.nonblocking = False
        self.nodelay = False          # TCP_NODELAY, propagated to conns
        self.reuseaddr = False        # SO_REUSEADDR, bind-time semantics
        self._send_buf_max = send_buf
        self._recv_buf_max = recv_buf
        # Per-host TCP stack options (`tcp: {cc, ecn}` config block),
        # captured at socket birth so every connection this socket —
        # or its accept children — creates runs the host's stack.
        self._tcp_cc = getattr(host, "tcp_cc", "reno")
        self._tcp_ecn = getattr(host, "tcp_ecn", False)
        # Dynamic buffer sizing (ref tcp.c _tcp_autotune*Buffer):
        # grow-only, clamped to the bandwidth-delay product.
        self.send_autotune = send_autotune
        self.recv_autotune = recv_autotune
        self._at_bytes_copied = 0
        self._at_space = 0
        self._at_last_adjust = 0
        self._ifaces = []
        self._iface = None            # the interface a stream runs on
        self.conn: tcpc.TcpConnection | None = None
        # Listener state.
        self.listening = False
        self._backlog = 0
        self._accept_q: deque = deque()
        self._listener = None         # backref for children
        self._accept_queued = False
        self._delivered = False       # handed to the app via accept()
        # Egress packets ready for the interface, per interface name.
        self._out_packets: dict[str, deque] = {"lo": deque(), "eth0": deque()}
        self._timer_deadline: int | None = None
        self._status = S_ACTIVE

    # ------------------------------------------------------------------
    # Binding / connecting / listening
    # ------------------------------------------------------------------

    def _pick_interfaces(self, host, ip: int):
        if ip == INADDR_ANY:
            return [host.lo, host.eth0]
        if ip == LOCALHOST_IP:
            return [host.lo]
        if ip == host.eth0.ip:
            return [host.eth0]
        raise OSError(errno.EADDRNOTAVAIL, "cannot bind non-local address")

    def bind(self, host, ip: int, port: int) -> None:
        if self.local is not None:
            raise OSError(errno.EINVAL, "already bound")
        ifaces = self._pick_interfaces(host, ip)
        if port == 0:
            port = self._ephemeral_port(host, ifaces)
        else:
            from shadow_tpu.net.interface import check_bind_port
            check_bind_port(ifaces, self.protocol, port, self.reuseaddr)
        for iface in ifaces:
            iface.associate(self, self.protocol, port)
        self._ifaces = ifaces
        self.local = (ip, port)

    def _ephemeral_port(self, host, ifaces) -> int:
        for _ in range(64):
            port = host.rng.randrange(EPHEMERAL_LO, EPHEMERAL_HI)
            if not any(i.port_in_use(self.protocol, port) for i in ifaces):
                return port
        for port in range(EPHEMERAL_LO, EPHEMERAL_HI):
            if not any(i.port_in_use(self.protocol, port) for i in ifaces):
                return port
        raise OSError(errno.EADDRINUSE, "no free ephemeral ports")

    def listen(self, host, backlog: int = 128) -> None:
        if self.local is None:
            raise OSError(errno.EINVAL, "listen before bind")
        if self.conn is not None:
            raise OSError(errno.EISCONN, "already connected")
        self.listening = True
        self._backlog = max(1, backlog)

    def connect(self, host, ip: int, port: int):
        """Active open. Returns 0 when established, a SyscallCondition
        while the handshake is in flight (caller blocks), raises on
        failure. Re-entered with the same args after unblock (restart
        protocol)."""
        if self.listening:
            raise OSError(errno.EOPNOTSUPP, "listener cannot connect")
        if self.conn is not None:
            if (ip, port) != (self.peer or (None, None)):
                raise OSError(errno.EISCONN, "already connected")
            if self.conn.error:
                code = (errno.ETIMEDOUT if "timed" in self.conn.error
                        else errno.ECONNREFUSED)
                raise OSError(code, self.conn.error)
            if self.conn.state == tcpc.ESTABLISHED:
                return 0
            if self.nonblocking:
                raise OSError(errno.EALREADY, "connect in progress")
            return SyscallCondition(file=self, mask=S_WRITABLE | S_CLOSED)
        if self.local is None:
            dst_local = LOCALHOST_IP if ip == LOCALHOST_IP else host.eth0.ip
            self.bind(host, dst_local, 0)
        self.peer = (ip, port)
        self._iface = host.lo if ip == LOCALHOST_IP else host.eth0
        # Move from wildcard to the specific 4-tuple so multiple
        # connections can share a local port.  Check BEFORE mutating:
        # an exact-4-tuple collision (explicit bind + reconnect to the
        # same peer) must fail cleanly, not leave the socket headless.
        if self._iface.is_associated(self.protocol, self.local[1],
                                     ip, port):
            self.peer = None
            raise OSError(errno.EADDRINUSE, "address already in use")
        for iface in self._ifaces:
            iface.disassociate(self.protocol, self.local[1])
        self._iface.associate(self, self.protocol, self.local[1], ip, port)
        self._ifaces = [self._iface]
        self.conn = tcpc.TcpConnection(
            iss=host.rng.next_u32(), recv_buf_max=self._recv_buf_max,
            send_buf_max=self._send_buf_max,
            congestion=self._tcp_cc, ecn=self._tcp_ecn,
            window_ceiling=(tcpc.RMEM_CEILING if self.recv_autotune
                            else None))
        self.conn.nodelay = self.nodelay
        self.conn.open_active(host.now())
        self._flush(host)
        if self.nonblocking:
            raise OSError(errno.EINPROGRESS, "connect started")
        return SyscallCondition(file=self, mask=S_WRITABLE | S_CLOSED)

    def accept(self, host):
        if not self.listening:
            raise OSError(errno.EINVAL, "not listening")
        if not self._accept_q:
            raise BlockingIOError(errno.EWOULDBLOCK, "no pending connection")
        child = self._accept_q.popleft()
        child._delivered = True  # the app owns it now (fd lifecycle)
        if not self._accept_q:
            self.adjust_status(host, 0, S_READABLE)
        return child

    # ------------------------------------------------------------------
    # Data path (app side)
    # ------------------------------------------------------------------

    def _require_conn(self) -> tcpc.TcpConnection:
        if self.conn is None:
            raise OSError(errno.ENOTCONN, "not connected")
        return self.conn

    def sendto(self, host, data: bytes, dst=None) -> int:
        conn = self._require_conn()
        if conn.error:
            raise OSError(errno.ECONNRESET, conn.error)
        if conn.state not in (tcpc.ESTABLISHED, tcpc.CLOSE_WAIT):
            raise OSError(errno.EPIPE, "not established")
        n = conn.write(data, host.now())
        self._flush(host)
        if n == 0:
            self.adjust_status(host, 0, S_WRITABLE)
            raise BlockingIOError(errno.EWOULDBLOCK, "send buffer full")
        return n

    def recvfrom(self, host, bufsize: int, peek: bool = False):
        return self.recv(host, bufsize, peek=peek), self.peer

    def bytes_available(self) -> int:
        """FIONREAD/SIOCINQ: in-order readable bytes (twin:
        Engine sock_inq in native/netplane.cpp)."""
        return self.conn.readable_bytes() if self.conn is not None else 0

    def recv(self, host, bufsize: int, peek: bool = False) -> bytes:
        conn = self._require_conn()
        if conn.readable_bytes() == 0:
            if conn.at_eof():
                return b""
            if conn.error:
                raise OSError(errno.ECONNRESET, conn.error)
            self.adjust_status(host, 0, S_READABLE)
            raise BlockingIOError(errno.EWOULDBLOCK, "no data")
        if peek:
            return conn.peek(bufsize)
        data = conn.read(bufsize, host.now())
        if self.recv_autotune and data:
            self._autotune_recv(host, conn, len(data))
        self._flush(host)
        if conn.readable_bytes() == 0 and not conn.at_eof():
            self.adjust_status(host, 0, S_READABLE)
        return data

    def shutdown(self, host, how: str = "wr") -> None:
        if self.conn is not None and "w" in how:
            self.conn.close(host.now())
            self._flush(host)

    def close(self, host) -> None:
        if self.listening:
            self.listening = False  # in-flight children abort on completion
            for child in self._accept_q:
                child.close(host)
                from shadow_tpu.utils.object_counter import mark_dealloc
                mark_dealloc(child)
                # Accounting done here; the eventual teardown (once the
                # FIN exchange completes) must not mark a second time.
                child._delivered = True
            self._accept_q.clear()
            self._teardown(host)
            return
        if self.conn is None:
            # Bound but never connected: release the port immediately.
            self._teardown(host)
            return
        if self.conn.state not in (tcpc.CLOSED, tcpc.TIME_WAIT):
            self.conn.close(host.now())
            self._flush(host)
        # The association stays alive until the connection fully closes
        # (TIME_WAIT etc.); _maybe_teardown reaps it from the timer path.
        self._maybe_teardown(host)
        self.adjust_status(host, S_CLOSED, S_ACTIVE)

    def _teardown(self, host) -> None:
        # Fabric-observatory flow lifecycle: teardown is the one event
        # after which the association walk can no longer find this
        # connection, so its FCT record is logged here (netplane.cpp
        # tcp_teardown twin).  Still-associated flows are swept when
        # the artifact is written; dataless flows leave no record.
        if self._ifaces and self.conn is not None \
                and self.local is not None and self.peer is not None:
            from shadow_tpu.trace.fabricstat import flow_row
            row = flow_row(host.id, self.local[1], self.peer[1],
                           self.peer[0], self.conn)
            if row is not None:
                host.fct_log.append(row)
        for iface in self._ifaces:
            if self.local is not None:
                if self.peer is not None:
                    iface.disassociate(self.protocol, self.local[1],
                                       self.peer[0], self.peer[1])
                else:
                    iface.disassociate(self.protocol, self.local[1])
        self._ifaces = []
        self.adjust_status(host, S_CLOSED, S_ACTIVE | S_READABLE | S_WRITABLE)
        if self._listener is not None and not self._delivered \
                and self not in self._listener._accept_q:
            # Pre-accept child dying (listener closed mid-handshake,
            # RST in SYN_RCVD): the app never owned it and never will,
            # so this teardown IS its deallocation.  A child still in
            # the accept queue can yet reach the app via accept() — its
            # lifecycle then ends at the fd table like any other fd.
            from shadow_tpu.utils.object_counter import mark_dealloc
            mark_dealloc(self)

    def _maybe_teardown(self, host) -> None:
        if self.conn is not None and self.conn.state == tcpc.CLOSED \
                and self._ifaces:
            self._teardown(host)

    # ------------------------------------------------------------------
    # Interface protocol (egress)
    # ------------------------------------------------------------------

    def peek_next_packet_priority(self, iface):
        q = self._out_packets[iface.name]
        return q[0].priority if q else None

    def pull_out_packet(self, host, iface):
        q = self._out_packets[iface.name]
        return q.popleft() if q else None

    # ------------------------------------------------------------------
    # Interface protocol (ingress)
    # ------------------------------------------------------------------

    def push_in_packet(self, host, packet) -> bool:
        if self.listening:
            return self._listener_push(host, packet)
        conn = self.conn
        if conn is None:
            host.trace_drop(packet, "tcp-closed")
            return False
        reasm0, trunc0 = conn.reasm_discards, conn.rcvwin_trunc
        conn.on_packet(packet.tcp, packet.payload, host.now(),
                       ecn=packet.ecn)
        # Sim-netstat receiver discards (netplane.cpp tcp_push_in
        # twin): fold the per-packet delta into the host's drop-cause
        # counters — the connection has no host backref.
        host.drop_causes[TEL_REASM_FULL] += conn.reasm_discards - reasm0
        host.drop_causes[TEL_RECVWIN_TRUNC] += \
            conn.rcvwin_trunc - trunc0
        if self.send_autotune and conn.srtt > 0:
            # ACK processing updated cwnd/RTT: grow the send buffer to
            # keep the congestion window fed (tcp.c autotune-on-ack).
            self._autotune_send(host, conn)
        self._flush(host)
        self._update_status(host)
        self._maybe_child_established(host)
        self._maybe_teardown(host)
        return True

    def _listener_push(self, host, packet) -> bool:
        hdr = packet.tcp
        if not (hdr.flags & tcpc.TcpFlags.SYN) or (hdr.flags &
                                                   tcpc.TcpFlags.ACK):
            # Stray segment for a dead connection; traced so every packet
            # reconciles to exactly one RCV or DRP line.
            host.trace_drop(packet, "tcp-stray")
            return False
        if len(self._accept_q) >= self._backlog:
            host.trace_drop(packet, "accept-backlog-full")
            return False
        # Spawn a child socket bound to the specific 4-tuple.
        child = TcpSocket(host, self._send_buf_max, self._recv_buf_max,
                          send_autotune=self.send_autotune,
                          recv_autotune=self.recv_autotune)
        child.local = (packet.dst_ip, packet.dst_port)
        child.peer = (packet.src_ip, packet.src_port)
        child._listener = self
        iface = host.lo if packet.dst_ip == LOCALHOST_IP else host.eth0
        child._iface = iface
        try:
            iface.associate(child, pkt.PROTO_TCP, packet.dst_port,
                            packet.src_ip, packet.src_port)
        except OSError:
            host.trace_drop(packet, "tcp-dup-syn")
            return False  # duplicate SYN for an existing child
        child._ifaces = [iface]
        child.conn = tcpc.TcpConnection(
            iss=host.rng.next_u32(), recv_buf_max=self._recv_buf_max,
            send_buf_max=self._send_buf_max,
            congestion=self._tcp_cc, ecn=self._tcp_ecn,
            window_ceiling=(tcpc.RMEM_CEILING if self.recv_autotune
                            else None))
        child.nodelay = self.nodelay
        child.conn.nodelay = self.nodelay
        child.conn.accept_syn(hdr, host.now())
        child._flush(host)
        return True

    def _maybe_child_established(self, host) -> None:
        if (self._listener is not None and not self._accept_queued
                and self.conn.state == tcpc.ESTABLISHED):
            self._accept_queued = True
            listener = self._listener
            if not listener.listening:
                # Listener closed while our SYN-ACK was in flight: the
                # peer must see a RST, not a half-open black hole.
                self.conn.abort(host.now())
                self._flush(host)
                self._teardown(host)
                return
            listener._accept_q.append(self)
            listener.adjust_status(host, S_READABLE, 0)

    # ------------------------------------------------------------------
    # Egress drain + timers
    # ------------------------------------------------------------------

    @staticmethod
    def _max_mem(host, rtt_ns: int, is_recv: bool) -> int:
        """BDP-derived ceiling, clamped to [X, 10X] of the Linux-default
        sysctl max (tcp.c _tcp_computeMaxRMEM/WMEM)."""
        bw_bits = host.bw_down_bits if is_recv else host.bw_up_bits
        mem = bw_bits * rtt_ns // (8 * 10**9)
        base = RMEM_MAX if is_recv else WMEM_MAX
        return min(max(mem, base), base * 10)

    def _autotune_recv(self, host, conn, bytes_copied: int) -> None:
        """Receiver-side DRS (tcp.c _tcp_autotuneReceiveBuffer): track
        bytes the app drained per sRTT window; advertise space for
        twice that, grow-only, BDP-capped."""
        self._at_bytes_copied += bytes_copied
        space = 2 * self._at_bytes_copied
        if space > self._at_space:
            self._at_space = space
        cur = conn.recv_buf_max
        if self._at_space > cur:
            new = min(self._at_space, self._max_mem(host, conn.srtt, True))
            if new > cur:
                conn.recv_buf_max = new
        now = host.now()
        if self._at_last_adjust == 0:
            self._at_last_adjust = now
        elif conn.srtt > 0 and now - self._at_last_adjust > conn.srtt:
            self._at_last_adjust = now
            self._at_bytes_copied = 0

    def _autotune_send(self, host, conn) -> None:
        """Sender side (tcp.c _tcp_autotuneSendBuffer): room for twice
        the congestion window's worth of the kernel's per-segment
        overhead estimate, grow-only, BDP-capped."""
        demanded = max(1, conn.cwnd // max(conn.eff_mss, 1))
        new = min(2404 * 2 * demanded,
                  self._max_mem(host, conn.srtt, False))
        if new > conn.send_buf_max:
            conn.send_buf_max = new

    def _flush(self, host) -> None:
        conn = self.conn
        if conn is None:
            return
        emitted = False
        iface = self._iface
        while conn.outbox:
            hdr, payload = conn.outbox.popleft()
            seq = host.next_packet_seq()
            p = pkt.Packet(host.id, seq, pkt.PROTO_TCP,
                           self.local[0] if self.local[0] != INADDR_ANY
                           else iface.ip,
                           self.local[1], self.peer[0], self.peer[1],
                           payload=payload, tcp=hdr)
            p.priority = seq
            # ECN-capable transport: data segments carry ECT(0) so a
            # congested queue can mark instead of drop; control
            # segments stay not-ECT (RFC 3168 6.1.1 + the empty-
            # control loss exemption's sibling rule).
            if conn.ecn_active and payload:
                p.ecn = pkt.ECN_ECT0
            self._out_packets[iface.name].append(p)
            emitted = True
        if emitted:
            iface.notify_socket_has_packets(host, self)
        self._arm_timer(host)
        self._update_status(host)

    def _update_status(self, host) -> None:
        conn = self.conn
        if conn is None:
            return
        set_mask = 0
        clear_mask = 0
        if conn.readable_bytes() > 0 or conn.at_eof() or conn.error:
            set_mask |= S_READABLE
        else:
            clear_mask |= S_READABLE
        if conn.state in (tcpc.ESTABLISHED, tcpc.CLOSE_WAIT) \
                and conn.send_space() > 0:
            set_mask |= S_WRITABLE
        elif conn.state not in (tcpc.ESTABLISHED, tcpc.CLOSE_WAIT):
            clear_mask |= S_WRITABLE
        if conn.error or conn.state == tcpc.CLOSED:
            set_mask |= S_CLOSED
        self.adjust_status(host, set_mask, clear_mask & ~set_mask)

    def _arm_timer(self, host) -> None:
        conn = self.conn
        if conn is None:
            return
        deadline = conn.next_timer_expiry()
        if deadline is None or deadline == self._timer_deadline:
            return
        self._timer_deadline = deadline
        host.schedule_task_at(deadline, TaskRef("tcp-timer", self._on_timer))

    def _on_timer(self, host) -> None:
        conn = self.conn
        if conn is None:
            return
        deadline = conn.next_timer_expiry()
        self._timer_deadline = None
        if deadline is not None and host.now() >= deadline:
            conn.on_timer(host.now())
            self._flush(host)
            self._update_status(host)
            self._maybe_teardown(host)
        else:
            self._arm_timer(host)
