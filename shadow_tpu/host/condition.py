"""Blocking-syscall conditions.

Ref: src/main/host/syscall/syscall_condition.c:48,421-480 — the primitive
a blocked syscall parks on: a trigger (file-status change) and/or a
timeout; whichever fires first schedules the thread's wakeup task and
disarms the other. Timer events in the heap can't be revoked, so timeout
tasks re-check an armed flag (the reference revokes via its Timer; same
observable behavior).
"""

from __future__ import annotations

from shadow_tpu.core.event import TaskRef


class SyscallCondition:
    __slots__ = ("_file", "_mask", "_timeout_at", "_armed", "_listener_handle",
                 "_wakeup_fn", "timed_out")

    def __init__(self, file=None, mask: int = 0, timeout_at: int | None = None):
        assert file is not None or timeout_at is not None
        self._file = file
        self._mask = mask
        self._timeout_at = timeout_at
        self._armed = False
        self._listener_handle = None
        self._wakeup_fn = None
        self.timed_out = False

    def arm(self, host, wakeup_fn) -> None:
        """wakeup_fn(host) runs (as a scheduled task) when triggered."""
        assert not self._armed
        self._armed = True
        self._wakeup_fn = wakeup_fn
        if self._file is not None:
            # Fire immediately if the status is already satisfied — the
            # caller checked once before blocking, but a status change can
            # race between check and arm in principle; re-checking keeps
            # the contract obvious.
            if self._file.status & self._mask:
                self._fire(host, timed_out=False)
                return
            self._listener_handle = self._file.add_status_listener(
                self._mask, self._on_status)
        if self._armed and self._timeout_at is not None:
            host.schedule_task_at(self._timeout_at,
                                  TaskRef("condition-timeout", self._on_timeout))

    def disarm(self) -> None:
        self._armed = False
        if self._listener_handle is not None and self._file is not None:
            self._file.remove_status_listener(self._listener_handle)
            self._listener_handle = None

    def _on_status(self, owner, changed, host) -> None:
        if self._armed:
            self._fire(host, timed_out=False)

    def _on_timeout(self, host) -> None:
        if self._armed and host.now() >= self._timeout_at:
            self._fire(host, timed_out=True)

    def _fire(self, host, timed_out: bool) -> None:
        self.disarm()
        self.timed_out = timed_out
        # Wake via a fresh task so the unblocked thread runs from the event
        # loop, not from inside whatever triggered the status change.
        host.schedule_task_at(host.now(), TaskRef("syscall-wakeup",
                                                  self._wakeup_fn))


class ManualCondition:
    """A condition fired explicitly by simulator code (plus an optional
    timeout) — the shape futex waits need: there is no file whose status
    changes, just another thread's FUTEX_WAKE (ref: the futex trigger
    arm of syscall_condition.c:48).  Same arm/disarm/timed_out interface
    as SyscallCondition."""

    __slots__ = ("_timeout_at", "_armed", "_wakeup_fn", "timed_out",
                 "on_disarm")

    def __init__(self, timeout_at: int | None = None):
        self._timeout_at = timeout_at
        self._armed = False
        self._wakeup_fn = None
        self.timed_out = False
        self.on_disarm = None  # cleanup hook (e.g. drop the futex waiter)

    def arm(self, host, wakeup_fn) -> None:
        assert not self._armed
        self._armed = True
        self._wakeup_fn = wakeup_fn
        if self._timeout_at is not None:
            host.schedule_task_at(self._timeout_at,
                                  TaskRef("condition-timeout",
                                          self._on_timeout))

    def disarm(self) -> None:
        self._armed = False
        if self.on_disarm is not None:
            hook, self.on_disarm = self.on_disarm, None
            hook()

    def fire(self, host) -> None:
        """External trigger (e.g. FUTEX_WAKE)."""
        if self._armed:
            self._fire(host, timed_out=False)

    def _on_timeout(self, host) -> None:
        if self._armed and host.now() >= self._timeout_at:
            self._fire(host, timed_out=True)

    def _fire(self, host, timed_out: bool) -> None:
        self.disarm()
        self.timed_out = timed_out
        host.schedule_task_at(host.now(), TaskRef("syscall-wakeup",
                                                  self._wakeup_fn))


class MultiSyscallCondition:
    """poll/select/epoll-style condition: wake when ANY of several files
    gains a watched status bit, or on timeout — the many-trigger shape
    the reference builds from one SyscallCondition per status listener
    plus its timeout (syscall_condition.c:421-480); one object here.

    Same arm/disarm/timed_out interface as SyscallCondition so Thread
    and ManagedThread treat both uniformly.
    """

    __slots__ = ("_watches", "_timeout_at", "_armed", "_handles",
                 "_wakeup_fn", "timed_out")

    def __init__(self, watches: list, timeout_at: int | None = None):
        """watches: [(file, mask), ...]; may be empty for a pure sleep."""
        assert watches or timeout_at is not None
        self._watches = watches
        self._timeout_at = timeout_at
        self._armed = False
        self._handles = []
        self._wakeup_fn = None
        self.timed_out = False

    def arm(self, host, wakeup_fn) -> None:
        assert not self._armed
        self._armed = True
        self._wakeup_fn = wakeup_fn
        for file, mask in self._watches:
            if file.status & mask:
                self._fire(host, timed_out=False)
                return
        for file, mask in self._watches:
            self._handles.append(
                (file, file.add_status_listener(mask, self._on_status)))
        if self._armed and self._timeout_at is not None:
            host.schedule_task_at(self._timeout_at,
                                  TaskRef("condition-timeout",
                                          self._on_timeout))

    def disarm(self) -> None:
        self._armed = False
        for file, handle in self._handles:
            file.remove_status_listener(handle)
        self._handles = []

    def _on_status(self, owner, changed, host) -> None:
        if self._armed:
            self._fire(host, timed_out=False)

    def _on_timeout(self, host) -> None:
        if self._armed and host.now() >= self._timeout_at:
            self._fire(host, timed_out=True)

    def _fire(self, host, timed_out: bool) -> None:
        self.disarm()
        self.timed_out = timed_out
        host.schedule_task_at(host.now(), TaskRef("syscall-wakeup",
                                                  self._wakeup_fn))
