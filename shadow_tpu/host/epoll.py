"""epoll: the readiness multiplexer (ref: src/main/host/descriptor/
epoll/{mod,entry,key}.rs — the Rust epoll, not the legacy C one).

An EpollFile is itself a StatusOwner (epoll fds are pollable and
nestable): it is READABLE whenever any registered entry has a ready
event.  Entries subscribe to their target's status changes; level- and
edge-triggered modes plus EPOLLONESHOT are modeled the way the
reference's entry state machine does it.
"""

from __future__ import annotations

import errno

from shadow_tpu.host.status import (S_ACTIVE, S_CLOSED, S_ERROR, S_READABLE,
                                    S_WRITABLE, StatusOwner)

EPOLLIN = 0x001
EPOLLPRI = 0x002
EPOLLOUT = 0x004
EPOLLERR = 0x008
EPOLLHUP = 0x010
EPOLLRDHUP = 0x2000
EPOLLONESHOT = 1 << 30
EPOLLET = 1 << 31

EPOLL_CTL_ADD = 1
EPOLL_CTL_DEL = 2
EPOLL_CTL_MOD = 3

# epoll events derived from file status bits.
_WATCH_MASK = S_READABLE | S_WRITABLE | S_CLOSED | S_ERROR


def _events_from_status(status: int, interest: int) -> int:
    ev = 0
    if status & S_READABLE:
        ev |= EPOLLIN
    if status & S_WRITABLE:
        ev |= EPOLLOUT
    if status & S_CLOSED:
        ev |= EPOLLHUP | EPOLLIN
    if status & S_ERROR:
        ev |= EPOLLERR
    # EPOLLERR/EPOLLHUP are always reported; the rest filter by interest.
    return ev & (interest | EPOLLERR | EPOLLHUP)


class _Entry:
    __slots__ = ("file", "interest", "data", "handle", "ready",
                 "oneshot_fired", "edge_armed")

    def __init__(self, file, interest: int, data: int):
        self.file = file
        self.interest = interest
        self.data = data  # u64 epoll_data verbatim
        self.handle = None
        self.ready = 0
        self.oneshot_fired = False
        # Edge-triggered: ready only reported after a fresh transition.
        self.edge_armed = True


class EpollFile(StatusOwner):
    def __init__(self):
        super().__init__()
        self._entries: dict[int, _Entry] = {}  # key: registered (virtual) fd
        self.nonblocking = False
        self._status = S_ACTIVE

    # ------------------------------------------------------------------

    def ctl(self, host, op: int, fd: int, file, interest: int,
            data: int) -> None:
        if op == EPOLL_CTL_ADD:
            if fd in self._entries:
                raise OSError(errno.EEXIST, "fd already registered")
            entry = _Entry(file, interest, data)
            entry.handle = file.add_status_listener(
                _WATCH_MASK, lambda owner, changed, h,
                e=entry: self._on_status(e, h))
            self._entries[fd] = entry
            self._refresh_entry(host, entry)
        elif op == EPOLL_CTL_MOD:
            entry = self._entries.get(fd)
            if entry is None:
                raise OSError(errno.ENOENT, "fd not registered")
            entry.interest = interest
            entry.data = data
            entry.oneshot_fired = False
            entry.edge_armed = True
            self._refresh_entry(host, entry)
        elif op == EPOLL_CTL_DEL:
            entry = self._entries.pop(fd, None)
            if entry is None:
                raise OSError(errno.ENOENT, "fd not registered")
            entry.file.remove_status_listener(entry.handle)
            self._update_own_status(host)
        else:
            raise OSError(errno.EINVAL, f"bad epoll_ctl op {op}")

    def _on_status(self, entry: _Entry, host) -> None:
        entry.edge_armed = True
        self._refresh_entry(host, entry)

    def _refresh_entry(self, host, entry: _Entry) -> None:
        if entry.oneshot_fired:
            entry.ready = 0
        else:
            entry.ready = _events_from_status(entry.file.status,
                                              entry.interest)
            if (entry.interest & EPOLLET) and not entry.edge_armed:
                entry.ready = 0
        self._update_own_status(host)

    def _update_own_status(self, host) -> None:
        any_ready = any(e.ready for e in self._entries.values())
        if any_ready:
            self.adjust_status(host, S_READABLE, 0)
        else:
            self.adjust_status(host, 0, S_READABLE)

    # ------------------------------------------------------------------

    def collect_ready(self, host, max_events: int):
        """-> [(events, data_u64)]; consumes edge/oneshot readiness."""
        out = []
        for entry in list(self._entries.values()):
            if not entry.ready:
                continue
            out.append((entry.ready, entry.data))
            if entry.interest & EPOLLONESHOT:
                entry.oneshot_fired = True
                entry.ready = 0
            if entry.interest & EPOLLET:
                entry.edge_armed = False
                entry.ready = 0
            if len(out) >= max_events:
                break
        self._update_own_status(host)
        return out

    def close(self, host) -> None:
        for entry in self._entries.values():
            entry.file.remove_status_listener(entry.handle)
        self._entries.clear()
        self.adjust_status(host, S_CLOSED, S_ACTIVE | S_READABLE)
