"""NETLINK_ROUTE sockets, minimally emulated (ref: socket/netlink.rs,
1,328 LoC).

Real network tools discover interfaces at startup via rtnetlink dumps —
glibc's getifaddrs() sends RTM_GETLINK + RTM_GETADDR and parses the
multipart replies.  This answers exactly those dumps from the simulated
interface table (lo 127.0.0.1/8 + eth0 host-ip/24), which is what the
reference's netlink socket serves too.  Everything else is answered
with NLMSG_ERROR(EOPNOTSUPP) so callers fail loudly instead of hanging.
"""

from __future__ import annotations

import errno
import struct

from shadow_tpu.host.status import (S_ACTIVE, S_CLOSED, S_READABLE,
                                    S_WRITABLE, StatusOwner)

NLMSG_ERROR = 0x2
NLMSG_DONE = 0x3
RTM_NEWLINK = 16
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_GETADDR = 22

NLM_F_MULTI = 0x2
NLM_F_REQUEST = 0x1
NLM_F_DUMP = 0x300

IFLA_IFNAME = 3
IFLA_MTU = 4
IFLA_ADDRESS = 1
IFA_ADDRESS = 1
IFA_LOCAL = 2
IFA_LABEL = 3

ARPHRD_LOOPBACK = 772
ARPHRD_ETHER = 1
IFF_UP = 0x1
IFF_LOOPBACK = 0x8
IFF_RUNNING = 0x40
AF_INET = 2
AF_UNSPEC = 0


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _attr(rta_type: int, payload: bytes) -> bytes:
    hdr = struct.pack("<HH", 4 + len(payload), rta_type)
    return hdr + payload + b"\0" * (_align4(len(payload)) - len(payload))


def _nlmsg(msg_type: int, flags: int, seq: int, pid: int,
           payload: bytes) -> bytes:
    total = 16 + len(payload)
    return struct.pack("<IHHII", total, msg_type, flags, seq, pid) + \
        payload


def _link_msg(seq: int, pid: int, index: int, name: str, hw_type: int,
              flags: int, mtu: int) -> bytes:
    ifinfo = struct.pack("<BBHiII", AF_UNSPEC, 0, hw_type, index, flags,
                         0xffffffff)
    attrs = _attr(IFLA_IFNAME, name.encode() + b"\0")
    attrs += _attr(IFLA_MTU, struct.pack("<I", mtu))
    attrs += _attr(IFLA_ADDRESS, b"\0" * 6)
    return _nlmsg(RTM_NEWLINK, NLM_F_MULTI, seq, pid, ifinfo + attrs)


def _addr_msg(seq: int, pid: int, index: int, name: str, ip: int,
              prefix: int) -> bytes:
    ifaddr = struct.pack("<BBBBi", AF_INET, prefix, 0, 0, index)
    ip_bytes = int(ip).to_bytes(4, "big")
    attrs = _attr(IFA_ADDRESS, ip_bytes) + _attr(IFA_LOCAL, ip_bytes)
    attrs += _attr(IFA_LABEL, name.encode() + b"\0")
    return _nlmsg(RTM_NEWADDR, NLM_F_MULTI, seq, pid, ifaddr + attrs)


LOCALHOST = 0x7f000001


class NetlinkSocket(StatusOwner):
    """One NETLINK_ROUTE endpoint: requests are answered synchronously
    into the receive queue."""

    def __init__(self, host):
        super().__init__()
        self.host = host
        self.nonblocking = False
        self.nl_pid = 0  # autobound on first use (we only have 1 user)
        self._recv_q: list[bytes] = []
        self._status = S_ACTIVE | S_WRITABLE

    def bind(self, host, nl_pid: int) -> None:
        self.nl_pid = nl_pid or host.next_event_seq() + 0x10000

    def sendto(self, host, data: bytes, dest=None) -> int:
        off = 0
        while off + 16 <= len(data):
            length, msg_type, _flags, seq, _pid = struct.unpack_from(
                "<IHHII", data, off)
            if length < 16 or off + length > len(data):
                break
            self._answer(host, msg_type, seq)
            off += _align4(length)
        return len(data)

    def _answer(self, host, msg_type: int, seq: int) -> None:
        pid = self.nl_pid
        if msg_type == RTM_GETLINK:
            self._recv_q.append(_link_msg(
                seq, pid, 1, "lo", ARPHRD_LOOPBACK,
                IFF_UP | IFF_LOOPBACK | IFF_RUNNING, 65536))
            self._recv_q.append(_link_msg(
                seq, pid, 2, "eth0", ARPHRD_ETHER,
                IFF_UP | IFF_RUNNING, 1500))
            self._recv_q.append(_nlmsg(NLMSG_DONE, NLM_F_MULTI, seq,
                                       pid, struct.pack("<i", 0)))
        elif msg_type == RTM_GETADDR:
            self._recv_q.append(_addr_msg(seq, pid, 1, "lo",
                                          LOCALHOST, 8))
            self._recv_q.append(_addr_msg(seq, pid, 2, "eth0",
                                          self.host.ip, 24))
            self._recv_q.append(_nlmsg(NLMSG_DONE, NLM_F_MULTI, seq,
                                       pid, struct.pack("<i", 0)))
        else:
            self._recv_q.append(_nlmsg(
                NLMSG_ERROR, 0, seq, pid,
                struct.pack("<i", -errno.EOPNOTSUPP) + b"\0" * 16))
        self.adjust_status(host, S_READABLE, 0)

    def recvfrom(self, host, bufsize: int, peek: bool = False):
        if not self._recv_q:
            raise BlockingIOError(errno.EWOULDBLOCK, "empty")
        # A short buffer truncates (netlink semantics) — glibc always
        # passes page-sized buffers, and dumps coalesce per recv call.
        out = bytearray()
        taken = 0
        for msg in self._recv_q:
            if taken and len(out) + len(msg) > bufsize:
                break
            out += msg[:max(0, bufsize - len(out))]
            taken += 1
        if not peek:
            del self._recv_q[:taken]
            if not self._recv_q:
                self.adjust_status(host, 0, S_READABLE)
        return bytes(out), ("netlink", 0)

    def bytes_available(self) -> int:
        return len(self._recv_q[0]) if self._recv_q else 0

    def close(self, host) -> None:
        self.adjust_status(host, S_CLOSED,
                           S_ACTIVE | S_READABLE | S_WRITABLE)
