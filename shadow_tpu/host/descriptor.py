"""Descriptor table (ref: src/main/host/descriptor/descriptor_table.rs).

Maps fds to file objects, allocating the lowest available fd like Linux.
File objects are StatusOwner subclasses with a `close(host)` method.
"""

from __future__ import annotations

import errno


class DescriptorTable:
    __slots__ = ("_fds", "_next_hint")

    def __init__(self):
        self._fds: dict[int, object] = {}
        self._next_hint = 0

    # fds 0-2 are reserved for stdio (sys_write special-cases 1/2), so
    # registered files never alias them.
    def register(self, file, min_fd: int = 3) -> int:
        fd = min_fd
        while fd in self._fds:
            fd += 1
        self._fds[fd] = file
        return fd

    def register_at(self, fd: int, file) -> None:
        self._fds[fd] = file

    def get(self, fd: int):
        f = self._fds.get(fd)
        if f is None:
            raise OSError(errno.EBADF, "bad file descriptor")
        return f

    def deregister(self, fd: int):
        f = self._fds.pop(fd, None)
        if f is None:
            raise OSError(errno.EBADF, "bad file descriptor")
        return f

    def close_all(self, host) -> None:
        for fd in sorted(self._fds, reverse=True):
            f = self._fds.pop(fd)
            if hasattr(f, "close"):
                f.close(host)

    def open_fds(self):
        return sorted(self._fds)

    def __len__(self):
        return len(self._fds)
