"""Descriptor table (ref: src/main/host/descriptor/descriptor_table.rs).

Maps fds to file objects, allocating the lowest available fd like Linux.
File objects are StatusOwner subclasses with a `close(host)` method.

Open file descriptions are refcounted on the object (`_open_refs`):
dup() aliases within one table and fork() shares across tables both
bump the count, and the underlying object only really closes when the
last fd referring to it goes away — the same lifecycle the reference
gets from its CompatFile refcounts (descriptor/mod.rs).
"""

from __future__ import annotations

import errno


def _incref(file) -> None:
    file._open_refs = getattr(file, "_open_refs", 0) + 1


class NativeFdRef:
    """A manager-held duplicate (pidfd_getfd) of a managed process's
    NATIVE fd, in flight over an emulated unix socket via SCM_RIGHTS.
    Delivery hands the real fd to the receiving process over its
    transfer socket (ref: socket/unix.rs fd passing; our fd-split
    design keeps file fds native, so passing one crosses the real
    kernel).  Carries `_open_refs` like any descriptor object so the
    in-flight queue's incref/decref lifecycle closes the manager's dup
    exactly once."""

    __slots__ = ("mgr_fd", "_open_refs", "_oc_dead")

    def __init__(self, mgr_fd: int):
        self.mgr_fd = mgr_fd
        from shadow_tpu.utils.object_counter import count_alloc
        count_alloc("NativeFdRef")

    def close(self, host) -> None:
        import os
        if self.mgr_fd >= 0:
            try:
                os.close(self.mgr_fd)
            except OSError:
                pass
            self.mgr_fd = -1


def _decref(file, host) -> None:
    refs = getattr(file, "_open_refs", 1) - 1
    file._open_refs = refs
    if refs <= 0 and hasattr(file, "close"):
        file.close(host)
        from shadow_tpu.utils.object_counter import mark_dealloc
        mark_dealloc(file)


class DescriptorTable:
    __slots__ = ("_fds", "_cloexec", "_next_hint")

    def __init__(self):
        self._fds: dict[int, object] = {}
        self._cloexec: set[int] = set()
        self._next_hint = 0

    # fds 0-2 are reserved for stdio (sys_write special-cases 1/2), so
    # registered files never alias them.
    def register(self, file, min_fd: int = 3, cloexec: bool = False) -> int:
        fd = min_fd
        while fd in self._fds:
            fd += 1
        self._fds[fd] = file
        if cloexec:
            self._cloexec.add(fd)
        _incref(file)
        return fd

    def register_at(self, fd: int, file, cloexec: bool = False) -> None:
        assert fd not in self._fds, "register_at over a live fd"
        self._fds[fd] = file
        if cloexec:
            self._cloexec.add(fd)
        _incref(file)

    def get_opt(self, fd: int):
        """Like get() but returns None instead of raising EBADF."""
        return self._fds.get(fd)

    def get(self, fd: int):
        f = self._fds.get(fd)
        if f is None:
            raise OSError(errno.EBADF, "bad file descriptor")
        return f

    def close_fd(self, host, fd: int) -> None:
        f = self._fds.pop(fd, None)
        self._cloexec.discard(fd)
        if f is None:
            raise OSError(errno.EBADF, "bad file descriptor")
        _decref(f, host)

    def set_cloexec(self, fd: int, on: bool) -> None:
        if fd in self._fds:
            (self._cloexec.add if on else self._cloexec.discard)(fd)

    def get_cloexec(self, fd: int) -> bool:
        return fd in self._cloexec

    def close_all(self, host) -> None:
        for fd in sorted(self._fds, reverse=True):
            _decref(self._fds.pop(fd), host)
        self._cloexec.clear()

    def close_cloexec(self, host) -> None:
        """execve: close close-on-exec fds, keep the rest."""
        for fd in sorted(self._cloexec, reverse=True):
            f = self._fds.pop(fd, None)
            if f is not None:
                _decref(f, host)
        self._cloexec.clear()

    def fork_copy(self) -> "DescriptorTable":
        """Child's table after fork: same open file descriptions,
        independently closable fds (process.rs fork path)."""
        child = DescriptorTable()
        child._fds = dict(self._fds)
        child._cloexec = set(self._cloexec)
        for f in child._fds.values():
            _incref(f)
        return child

    def open_fds(self):
        return sorted(self._fds)

    def items(self):
        """(fd, file) pairs — the public iteration surface."""
        return list(self._fds.items())

    def replace(self, fd: int, new_file) -> None:
        """Swap the object behind an fd (fork-time per-process clones
        like SignalFd); ref accounting moves with it."""
        old = self._fds[fd]
        self._fds[fd] = new_file
        _incref(new_file)
        _decref(old, None)

    def __len__(self):
        return len(self._fds)
