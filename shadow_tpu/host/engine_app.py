"""Engine-resident internal applications.

The tgen traffic apps (host/apps.py) have C++ twins inside the native
data plane (netplane.cpp AppN): the same socket-operation sequence at
the same instants, advanced by engine-local events that draw from the
same shared per-host event-seq counter a Python wake task would — so
the packet trace is byte-identical to running the Python coroutine
apps, while the whole app/syscall/TCP path stays in C++.

This module holds the Python-side bookkeeping proxy the Manager keeps
in `host.processes`: lazily polls the engine for exit state and
formats the same stdout lines the Python app would have written.
"""

from __future__ import annotations

# (config path, argv shape) -> engine app kind
KIND_SERVER = 0
KIND_CLIENT = 1
KIND_UDP_FLOOD = 3
KIND_UDP_SINK = 4
KIND_UDP_MESH = 5
KIND_PHOLD = 7
KIND_UDP_ECHO = 9
KIND_UDP_PING = 10


class _EngineFdView:
    """Fd-table view for the manager's teardown sweep: `close_all` on
    a still-running engine app closes its engine-side sockets exactly
    like the object path's fds.close_all (FINs for mid-stream
    connections, traced at the host's current instant)."""

    __slots__ = ("_proc",)

    def __init__(self, proc):
        self._proc = proc

    def close_all(self, host) -> None:
        p = self._proc
        if p.app_idx is not None and not p.exited:
            host.plane.engine.app_teardown(p.app_idx, host.now())

    def __len__(self) -> int:
        return 0


class _AppThreadView:
    """Thread-table entry the kill/tgkill addressing paths read
    (tid + liveness), polling the ENGINE app that backs the thread."""

    __slots__ = ("tid", "_proc", "_app_idx")

    def __init__(self, tid: int, proc, app_idx: int):
        self.tid = tid
        self._proc = proc
        self._app_idx = app_idx

    @property
    def state(self):
        from shadow_tpu.host.process import ST_EXITED, ST_RUNNABLE
        exited, _c, _t = self._proc.host.plane.engine.app_status(
            self._app_idx)
        return ST_EXITED if exited else ST_RUNNABLE


class EngineAppProcess:
    """Duck-typed stand-in for host/process.py Process, backed by an
    engine-resident app."""

    def __init__(self, host, name: str, expected_final_state: str):
        self.host = host
        self.name = name
        self.pid = host.register_process(self)
        self.expected_final_state = expected_final_state
        self.app_idx: int | None = None   # set right after app_spawn
        self.term_signal = None
        self.stderr = bytearray()
        self.fds = _EngineFdView(self)
        # Process-interface attributes that host-wide machinery (kill
        # addressing, wait4 scans over host.processes) reads on every
        # process, engine-backed or not.
        self.parent_pid: int | None = None
        self.pgid = self.pid
        self.sid = self.pid
        self.zombies: list = []
        self.stop_report: int | None = None
        self.continue_report = False
        self._stopped = False
        self._shielded: list[tuple] = []

    # -- engine state ---------------------------------------------------

    @property
    def threads(self) -> tuple:
        """Live thread-table view: the engine enumerates the process's
        app threads in spawn order (main, accepted handlers — exited
        ones keep their tid slot — then the mesh sender), so tgkill
        addressing matches the Python twin's tid numbering."""
        if self.app_idx is None:
            return ()
        idxs = self.host.plane.engine.app_threads(self.app_idx)
        return tuple(_AppThreadView(self.pid + i, self, idx)
                     for i, idx in enumerate(idxs))

    def _poll(self):
        return self.host.plane.engine.app_poll(self.app_idx)

    @property
    def exited(self) -> bool:
        # app_status: no stdout copy (exited checks run per signal and
        # per process at final accounting — app_poll's bytes copy for
        # each was ~10% of a 10k run).
        return bool(self.host.plane.engine.app_status(self.app_idx)[0])

    @property
    def exit_code(self):
        exited, code, _t = self.host.plane.engine.app_status(self.app_idx)
        return code if exited else None

    @property
    def stdout(self) -> bytearray:
        # The engine builds the exact bytes the Python app would have
        # written as it goes.
        _e, _c, _t, out = self._poll()
        return bytearray(out)

    # -- Process interface the Manager touches --------------------------

    @property
    def stopped(self) -> bool:
        return self._stopped

    def raise_signal(self, host, sig: int, target_tid=None,
                     si_code: int = 0, si_pid: int = 0,
                     si_status: int = 0) -> None:
        """Engine apps install no handlers: apply the DEFAULT action
        — terminate, stop (steppers park; socket timers keep running,
        like a SIGSTOPped real process's kernel state), continue, or
        ignore.  The stop shields non-KILL fatal signals until the
        continue, mirroring Process.raise_signal."""
        from shadow_tpu.host import signals as sigmod
        if self.exited or sig <= 0 or sig >= sigmod.NSIG:
            return
        eng = self.host.plane.engine
        if sig == sigmod.SIGCONT:
            if self._stopped:
                self._stopped = False
                self.stop_report = None
                self.continue_report = True
                eng.app_continue(self.app_idx, host.now())
                shielded, self._shielded = self._shielded, []
                for s, ttid, scode, spid, sstatus in shielded:
                    self.raise_signal(host, s, ttid, scode, spid, sstatus)
            return
        disp = sigmod.ProcessSignals().disposition(sig)
        if sig == sigmod.SIGKILL:
            self.term_signal = sig
            eng.app_kill(self.app_idx, sig, host.now())
            return
        if self._stopped:
            if disp not in ("ignore", "stop"):
                # Full siginfo tuple, like Process._stopped_sigs: the
                # replay must carry target_tid/si_* so a tgkill-targeted
                # signal keeps its provenance through the stop.
                self._shielded.append(
                    (sig, target_tid, si_code, si_pid, si_status))
            return
        if disp == "stop":
            self._stopped = True
            self.stop_report = sig
            self.continue_report = False
            eng.app_stop(self.app_idx)
            return
        if disp != "terminate":
            return
        self.term_signal = sig
        eng.app_kill(self.app_idx, sig, host.now())

    def matches_expected_final_state(self) -> bool:
        from shadow_tpu.host.process import matches_final_state
        exited, code, _t = self.host.plane.engine.app_status(self.app_idx)
        return matches_final_state(self.expected_final_state, exited,
                                   code if exited else None,
                                   self.term_signal)

    def strace_close(self) -> None:
        pass


def engine_app_args(pcfg, host, dns):
    """(kind, a, b, c, d, e) for engine.app_spawn, or None when `pcfg`
    isn't an engine-runnable app."""
    args = list(pcfg.args)
    if pcfg.path == "tgen-server":
        if len(args) != 1:
            return None
        return (KIND_SERVER, int(args[0]), 0, 0, 0, 0)
    if pcfg.path == "tgen-client":
        if len(args) not in (3, 4):
            return None
        ip = dns.ip_for_name(args[0])
        if ip is None:
            return None
        count = int(args[3]) if len(args) > 3 else 1
        return (KIND_CLIENT, ip, int(args[1]), int(args[2]), count, 0)
    if pcfg.path == "udp-flood":
        if len(args) not in (4, 5):
            return None
        ip = dns.ip_for_name(args[0])
        if ip is None:
            return None
        interval = int(args[4]) if len(args) > 4 else 0
        return (KIND_UDP_FLOOD, ip, int(args[1]), int(args[2]),
                int(args[3]), interval)
    if pcfg.path == "udp-sink":
        if len(args) not in (1, 2):
            return None
        expect = int(args[1]) if len(args) > 1 else 0
        has_expect = 1 if len(args) > 1 else 0
        return (KIND_UDP_SINK, int(args[0]), expect, has_expect, 0, 0)
    if pcfg.path == "udp-mesh":
        # udp-mesh <port> <count> <size> <peer...>: peer IPs ride a
        # trailing u32 buffer (variable length; the 5 scalar slots
        # carry port/count/size).
        if len(args) < 4:
            return None
        peers = _pack_peers(dns, args[3:])
        if peers is None:
            return None
        return (KIND_UDP_MESH, int(args[0]), int(args[1]), int(args[2]),
                0, 0, peers)
    if pcfg.path == "udp-echo-server":
        if len(args) != 1:
            return None
        return (KIND_UDP_ECHO, int(args[0]), 0, 0, 0, 0)
    if pcfg.path == "udp-pinger":
        if len(args) != 3:
            return None
        ip = dns.ip_for_name(args[0])
        if ip is None:
            return None
        return (KIND_UDP_PING, ip, int(args[1]), int(args[2]), 0, 0)
    if pcfg.path == "phold":
        # phold <port> <my_index> <n_init> <mean_delay_ns> <peers...>
        if len(args) < 5:
            return None
        peers = _pack_peers(dns, args[4:])
        if peers is None:
            return None
        return (KIND_PHOLD, int(args[0]), int(args[1]), int(args[2]),
                int(args[3]), 0, peers)
    return None


def _pack_peers(dns, names):
    """Resolve peer names into the u32 IP buffer app_spawn takes; None
    when any name is unresolvable (the caller falls back to the Python
    coroutine app, which reports the error the same way)."""
    import struct as _struct
    out = []
    for peer in names:
        ip = dns.ip_for_name(peer)
        if ip is None:
            return None
        out.append(ip)
    return b"".join(_struct.pack("<I", ip) for ip in out)
