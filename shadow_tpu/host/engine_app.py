"""Engine-resident internal applications.

The tgen traffic apps (host/apps.py) have C++ twins inside the native
data plane (netplane.cpp AppN): the same socket-operation sequence at
the same instants, advanced by engine-local events that draw from the
same shared per-host event-seq counter a Python wake task would — so
the packet trace is byte-identical to running the Python coroutine
apps, while the whole app/syscall/TCP path stays in C++.

This module holds the Python-side bookkeeping proxy the Manager keeps
in `host.processes`: lazily polls the engine for exit state and
formats the same stdout lines the Python app would have written.
"""

from __future__ import annotations

# (config path, argv shape) -> engine app kind
KIND_SERVER = 0
KIND_CLIENT = 1
KIND_UDP_FLOOD = 3
KIND_UDP_SINK = 4
KIND_UDP_MESH = 5


class _FdTableStub:
    def close_all(self, host) -> None:
        pass

    def __len__(self) -> int:
        return 0


class EngineAppProcess:
    """Duck-typed stand-in for host/process.py Process, backed by an
    engine-resident app."""

    def __init__(self, host, name: str, expected_final_state: str):
        self.host = host
        self.name = name
        self.pid = host.register_process(self)
        self.expected_final_state = expected_final_state
        self.app_idx: int | None = None   # set right after app_spawn
        self.term_signal = None
        self.stderr = bytearray()
        self.fds = _FdTableStub()

    # -- engine state ---------------------------------------------------

    def _poll(self):
        return self.host.plane.engine.app_poll(self.app_idx)

    @property
    def exited(self) -> bool:
        return bool(self._poll()[0])

    @property
    def exit_code(self):
        exited, code, _t, _x = self._poll()
        return code if exited else None

    @property
    def stdout(self) -> bytearray:
        # The engine builds the exact bytes the Python app would have
        # written as it goes.
        _e, _c, _t, out = self._poll()
        return bytearray(out)

    # -- Process interface the Manager touches --------------------------

    def matches_expected_final_state(self) -> bool:
        expected = self.expected_final_state
        if expected in ("running", "any"):
            return expected == "any" or not self.exited
        if isinstance(expected, str) and expected.startswith("exited"):
            parts = expected.split()
            want = int(parts[1]) if len(parts) > 1 else 0
            return self.exited and self.exit_code == want
        if isinstance(expected, str) and expected.startswith("signaled"):
            return False  # engine apps never die by signal
        return False

    def strace_close(self) -> None:
        pass


def engine_app_args(pcfg, host, dns):
    """(kind, a, b, c, d, e) for engine.app_spawn, or None when `pcfg`
    isn't an engine-runnable app."""
    args = list(pcfg.args)
    if pcfg.path == "tgen-server":
        if len(args) != 1:
            return None
        return (KIND_SERVER, int(args[0]), 0, 0, 0, 0)
    if pcfg.path == "tgen-client":
        if len(args) not in (3, 4):
            return None
        ip = dns.ip_for_name(args[0])
        if ip is None:
            return None
        count = int(args[3]) if len(args) > 3 else 1
        return (KIND_CLIENT, ip, int(args[1]), int(args[2]), count, 0)
    if pcfg.path == "udp-flood":
        if len(args) not in (4, 5):
            return None
        ip = dns.ip_for_name(args[0])
        if ip is None:
            return None
        interval = int(args[4]) if len(args) > 4 else 0
        return (KIND_UDP_FLOOD, ip, int(args[1]), int(args[2]),
                int(args[3]), interval)
    if pcfg.path == "udp-sink":
        if len(args) not in (1, 2):
            return None
        expect = int(args[1]) if len(args) > 1 else 0
        has_expect = 1 if len(args) > 1 else 0
        return (KIND_UDP_SINK, int(args[0]), expect, has_expect, 0, 0)
    if pcfg.path == "udp-mesh":
        # udp-mesh <port> <count> <size> <peer...>: peer IPs ride a
        # trailing u32 buffer (variable length; the 5 scalar slots
        # carry port/count/size).
        if len(args) < 4:
            return None
        import struct as _struct
        ips = []
        for peer in args[3:]:
            ip = dns.ip_for_name(peer)
            if ip is None:
                return None
            ips.append(ip)
        peers = b"".join(_struct.pack("<I", ip) for ip in ips)
        return (KIND_UDP_MESH, int(args[0]), int(args[1]), int(args[2]),
                0, 0, peers)
    return None
