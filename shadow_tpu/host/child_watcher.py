"""Native-child death watcher (ref: src/main/utility/childpid_watcher.rs).

One daemon thread blocks in waitid(P_ALL, WEXITED|WNOWAIT); when a
managed process dies it marks that process's IPC block CLOSED, which
futex-wakes any manager thread parked in the channel recv — the same
close-channel-on-death contract the reference implements with
pidfd+epoll.  This replaces 100ms wall-clock polling slices in every
blocked channel wait (a scheduler tax and flakiness source at scale);
the poll remains only as a long-interval safety net.

WNOWAIT leaves the zombie in place: the owning ManagedThread still
reaps it with waitpid and sees the real status.
"""

from __future__ import annotations

import os
import threading
import time


class ChildWatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._blocks: dict[int, object] = {}   # native_pid -> IpcBlock
        self._notified: set[int] = set()
        self._thread: threading.Thread | None = None

    def register(self, pid: int, block) -> None:
        with self._lock:
            self._blocks[pid] = block
            self._notified.discard(pid)
        self._ensure_thread()

    def unregister(self, pid: int | None) -> None:
        """MUST be called (by the owning manager thread) before the
        block is closed/unmapped: mark_closed runs under the same lock,
        so after unregister returns the watcher can no longer touch the
        block."""
        if pid is None:
            return
        with self._lock:
            self._blocks.pop(pid, None)
            self._notified.discard(pid)

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            t = threading.Thread(target=self._run, name="child-watcher",
                                 daemon=True)
            self._thread = t
        t.start()

    def _notify(self, pid: int) -> bool:
        """Mark `pid` dead and close its block (idempotent).  Returns
        True if this was the first notification."""
        with self._lock:
            if pid in self._notified:
                return False
            self._notified.add(pid)
            block = self._blocks.get(pid)
            if block is not None:
                # Wake the parked channel recv; the ManagedThread sees
                # ChannelClosed and reaps.  Under the lock so an
                # unregister+close cannot race the write.
                block.mark_closed()
        return True

    def _scan_registered(self) -> None:
        """waitid(P_ALL) can keep returning one unreaped zombie;
        per-pid WNOHANG probes keep other deaths from being starved
        behind it."""
        with self._lock:
            pids = [p for p in self._blocks if p not in self._notified]
        for pid in pids:
            try:
                info = os.waitid(os.P_PID, pid,
                                 os.WEXITED | os.WNOWAIT | os.WNOHANG)
            except (ChildProcessError, InterruptedError):
                continue  # reaped already; unregister follows shortly
            if info is not None and info.si_pid == pid:
                self._notify(pid)

    def _run(self) -> None:
        while True:
            try:
                info = os.waitid(os.P_ALL, 0, os.WEXITED | os.WNOWAIT)
            except ChildProcessError:
                time.sleep(0.05)  # no children right now
                continue
            except InterruptedError:
                continue
            if info is None:
                continue
            if not self._notify(info.si_pid):
                # An already-notified zombie awaiting its reap; make
                # sure it cannot shadow other deaths, then back off.
                self._scan_registered()
                time.sleep(0.02)


WATCHER = ChildWatcher()
