"""Syscall dispatch (ref: src/main/host/syscall/handler/mod.rs:116-641).

The single seam between applications and the simulated kernel. Calls are
tuples `(name, *args)`; results are `("done", value)`, `("error",
OSError)`, or `("block", SyscallCondition)` — the Done/Block/Native
triad of the reference minus Native (internal apps have no native fall
through; the interposition backend adds it later).

Blocking protocol: on "block" the thread parks and, when the condition
fires, *re-runs the same call* (restart semantics, handler/mod.rs:127-136)
with `restarted=True` so handlers like nanosleep can tell wakeup-by-
timeout from first entry.
"""

from __future__ import annotations

import errno

from shadow_tpu.core import simtime
from shadow_tpu.host.condition import SyscallCondition
from shadow_tpu.host.socket_udp import UdpSocket
from shadow_tpu.host.status import S_READABLE, S_WRITABLE
from shadow_tpu.net import graph as netgraph
from shadow_tpu.trace.events import SC_PARKED, SC_SERVICED


def _done(value=None):
    return ("done", value)


def _error(code, msg=""):
    return ("error", OSError(code, msg))


def _block(condition):
    return ("block", condition)


def _to_ip(host, addr) -> int:
    """Accept dotted-quad strings, hostnames, or ints."""
    if isinstance(addr, int):
        return addr
    try:
        return netgraph.parse_ip(addr)
    except ValueError:
        ip = host.dns.ip_for_name(addr)
        if ip is None:
            raise OSError(errno.ENOENT, f"unknown host {addr!r}")
        return ip


class SyscallHandler:
    """One instance per manager; stateless w.r.t. hosts (buffer-size
    defaults come from config, configuration.rs:348-592)."""

    def __init__(self, send_buf: int = 131_072, recv_buf: int = 174_760,
                 send_autotune: bool = True, recv_autotune: bool = True):
        self.send_buf = send_buf
        self.recv_buf = recv_buf
        self.send_autotune = send_autotune
        self.recv_autotune = recv_autotune

    def dispatch(self, host, process, thread, call, restarted: bool):
        name = call[0]
        handler = getattr(self, "sys_" + name, None)
        # Syscall observatory: the internal-app seam mirrors the
        # managed-ABI one — disposition counters always on (including
        # the ENOSYS path, so disposition totals stay equal to the
        # dispatch count), wall-time dispatch profiling when
        # host.sc_wall is attached (internal apps have no IPC
        # wait/resume legs; the record channel covers managed
        # processes only, docs/OBSERVABILITY.md).
        sw = host.sc_wall
        t0 = sw.now() if sw is not None else 0
        if handler is None:
            result = _error(errno.ENOSYS, f"unknown syscall {name!r}")
        else:
            try:
                result = handler(host, process, thread, restarted,
                                 *call[1:])
            except BlockingIOError as e:
                # Raised by socket internals; translated to block/error
                # by the specific handlers — reaching here means
                # nonblocking mode.
                result = _error(e.errno or errno.EWOULDBLOCK, str(e))
            except OSError as e:
                result = _error(
                    e.errno if e.errno is not None else errno.EINVAL,
                    str(e))
        host.sc_disp[SC_PARKED if result[0] == "block"
                     else SC_SERVICED] += 1
        if sw is not None:
            # ipc=False + an app: family namespace — internal
            # dispatches must not pollute the managed round-trip stats
            # or share histograms with same-named ABI syscalls.
            sw.trip("app:" + name, 0, sw.now() - t0, 0, ipc=False)
        return result

    # ------------------------------------------------------------------
    # Sockets
    # ------------------------------------------------------------------

    def sys_socket(self, host, process, thread, restarted, kind: str,
                   nonblocking: bool = False):
        native = host.plane is not None
        if kind in ("udp", "dgram"):
            if native:
                from shadow_tpu.host.socket_native import \
                    UdpSocket as NativeUdp
                sock = NativeUdp(host, self.send_buf, self.recv_buf)
            else:
                sock = UdpSocket(host, self.send_buf, self.recv_buf)
        elif kind in ("tcp", "stream"):
            if native:
                from shadow_tpu.host.socket_native import \
                    TcpSocket as NativeTcp
                sock = NativeTcp(host, self.send_buf, self.recv_buf,
                                 send_autotune=self.send_autotune,
                                 recv_autotune=self.recv_autotune)
            else:
                from shadow_tpu.host.socket_tcp import TcpSocket
                sock = TcpSocket(host, self.send_buf, self.recv_buf,
                                 send_autotune=self.send_autotune,
                                 recv_autotune=self.recv_autotune)
        else:
            return _error(errno.EINVAL, f"bad socket kind {kind!r}")
        sock.nonblocking = bool(nonblocking)
        return _done(process.fds.register(sock))

    def sys_bind(self, host, process, thread, restarted, fd, addr):
        sock = process.fds.get(fd)
        ip, port = addr
        sock.bind(host, _to_ip(host, ip), port)
        return _done(0)

    def sys_getsockname(self, host, process, thread, restarted, fd):
        sock = process.fds.get(fd)
        return _done(sock.local)

    def sys_getpeername(self, host, process, thread, restarted, fd):
        sock = process.fds.get(fd)
        if sock.peer is None:
            return _error(errno.ENOTCONN, "not connected")
        return _done(sock.peer)

    def sys_connect(self, host, process, thread, restarted, fd, addr):
        sock = process.fds.get(fd)
        ip, port = addr
        result = sock.connect(host, _to_ip(host, ip), port)
        if isinstance(result, SyscallCondition):  # TCP handshake in flight
            return _block(result)
        return _done(0)

    def sys_sendto(self, host, process, thread, restarted, fd, data,
                   addr=None):
        sock = process.fds.get(fd)
        if addr is not None:
            addr = (_to_ip(host, addr[0]), addr[1])
        try:
            return _done(sock.sendto(host, data, addr))
        except BlockingIOError:
            if sock.nonblocking:
                return _error(errno.EWOULDBLOCK, "send buffer full")
            return _block(SyscallCondition(file=sock, mask=S_WRITABLE))

    def sys_recvfrom(self, host, process, thread, restarted, fd,
                     bufsize=65536):
        sock = process.fds.get(fd)
        try:
            return _done(sock.recvfrom(host, bufsize))
        except BlockingIOError:
            if sock.nonblocking:
                return _error(errno.EWOULDBLOCK, "no data")
            return _block(SyscallCondition(file=sock, mask=S_READABLE))

    def sys_send(self, host, process, thread, restarted, fd, data):
        return self.sys_sendto(host, process, thread, restarted, fd, data,
                               None)

    def sys_recv(self, host, process, thread, restarted, fd, bufsize=65536):
        result = self.sys_recvfrom(host, process, thread, restarted, fd,
                                   bufsize)
        if result[0] == "done":
            return _done(result[1][0])
        return result

    def sys_listen(self, host, process, thread, restarted, fd, backlog=128):
        sock = process.fds.get(fd)
        sock.listen(host, backlog)
        return _done(0)

    def sys_accept(self, host, process, thread, restarted, fd):
        from shadow_tpu.host.status import S_SOCKET_ALLOWING_CONNECT
        sock = process.fds.get(fd)
        try:
            child = sock.accept(host)
        except BlockingIOError:
            if sock.nonblocking:
                return _error(errno.EWOULDBLOCK, "no pending connection")
            return _block(SyscallCondition(file=sock, mask=S_READABLE))
        return _done((process.fds.register(child), child.peer))

    def sys_close(self, host, process, thread, restarted, fd):
        process.fds.close_fd(host, fd)
        return _done(0)

    def sys_set_nonblocking(self, host, process, thread, restarted, fd,
                            enabled):
        process.fds.get(fd).nonblocking = bool(enabled)
        return _done(0)

    def sys_shutdown(self, host, process, thread, restarted, fd, how="wr"):
        sock = process.fds.get(fd)
        if hasattr(sock, "shutdown"):
            sock.shutdown(host, how)
        return _done(0)

    # ------------------------------------------------------------------
    # Time
    # ------------------------------------------------------------------

    def sys_clock_gettime(self, host, process, thread, restarted):
        return _done(simtime.emulated_from_sim(host.now()))

    def sys_sim_time(self, host, process, thread, restarted):
        return _done(host.now())

    def sys_nanosleep(self, host, process, thread, restarted, duration_ns):
        if restarted:
            cond = thread.last_condition
            if cond is not None and cond.timed_out:
                return _done(0)
        if duration_ns <= 0:
            return _done(0)
        return _block(SyscallCondition(
            timeout_at=host.now() + int(duration_ns)))

    # ------------------------------------------------------------------
    # Misc process-level
    # ------------------------------------------------------------------

    def sys_write(self, host, process, thread, restarted, fd, data):
        if isinstance(data, str):
            data = data.encode()
        if fd == 1:
            process.stdout += data
            return _done(len(data))
        if fd == 2:
            process.stderr += data
            return _done(len(data))
        f = process.fds.get(fd)
        if hasattr(f, "sendto"):
            return self.sys_sendto(host, process, thread, restarted, fd, data)
        return _error(errno.EBADF, "write: unsupported fd")

    def sys_getpid(self, host, process, thread, restarted):
        return _done(process.pid)

    def sys_gethostname(self, host, process, thread, restarted):
        return _done(host.name)

    def sys_getrandom(self, host, process, thread, restarted, n):
        return _done(host.rng.bytes(n))

    def sys_resolve(self, host, process, thread, restarted, name):
        """getaddrinfo-equivalent over the simulated DNS."""
        ip = host.dns.ip_for_name(name)
        if ip is None:
            return _error(errno.ENOENT, f"unknown host {name!r}")
        return _done(ip)

    def sys_spawn_thread(self, host, process, thread, restarted, gen_factory):
        """Internal-app thread creation (clone-lite): gen_factory() returns
        a new app generator run as a sibling thread."""
        t = process.spawn_thread(host, gen_factory())
        from shadow_tpu.core.event import TaskRef
        host.schedule_task_at(host.now(), TaskRef("thread-start", t.resume))
        return _done(t.tid)

    def sys_exit(self, host, process, thread, restarted, code=0):
        return ("exit", int(code))
