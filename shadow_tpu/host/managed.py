"""Managed processes: real, unmodified Linux binaries under the sim.

The manager half of the interposition stack (the in-process half lives
in native/shim.c).  Mirrors the reference's resume chain — Process →
Thread → ManagedThread driving the native process over shared-memory
IPC (src/main/host/managed_thread.rs:97-333, process.rs:944,
memory_manager/memory_copier.rs) — with the same protocol:

 - spawn at the scheduled sim instant via posix_spawn with LD_PRELOAD;
 - StartReq/StartRes handshake gates the app's main();
 - resume(): receive Syscall events, dispatch into the simulated
   kernel, answer Complete / DoNative, or park on a SyscallCondition
   and re-run the same syscall when it fires (restart protocol,
   handler/mod.rs:127-136);
 - child death is detected by the ChildWatcher thread closing the dead
   process's IPC block (child_watcher.py; the reference's
   childpid_watcher.rs makes the same close-channel-on-death move with
   pidfd+epoll), with a long-interval waitpid poll as the safety net;
 - an unblocked-syscall CPU-latency model parks the thread every so
   often so syscall-spinning code advances simulated time
   (handler/mod.rs:271-321).
"""

from __future__ import annotations

import ctypes
import os
import shutil
import signal
import socket as _socket
import struct
import threading
import time as _walltime

from shadow_tpu.core.event import TaskRef
from shadow_tpu.host import signals as sigmod
from shadow_tpu.host.child_watcher import WATCHER
from shadow_tpu.host.condition import SyscallCondition
from shadow_tpu.host.futex import FutexTable
from shadow_tpu.host.process import Process, ST_BLOCKED, ST_EXITED, ST_RUNNABLE
from shadow_tpu.host.shim_abi import (ChannelClosed, ChannelTimeout, IpcBlock,
                                      EV_CLONE_DONE, EV_CLONE_RES,
                                      EV_FORK_DONE, EV_FORK_RES, EV_SIGNAL,
                                      EV_SIGNAL_DONE, EV_START_REQ,
                                      EV_START_RES, EV_SYSCALL,
                                      EV_SYSCALL_COMPLETE,
                                      EV_SYSCALL_COMPLETE_FDXFER,
                                      EV_SYSCALL_DO_NATIVE, EV_XFER_DONE)
from shadow_tpu.host.syscalls_native import syscall_name
from shadow_tpu.trace import events as trev

# The unblocked-syscall CPU-latency model (ref configuration.rs:464-480
# — ~1us per syscall, applied in batches by parking the thread, which
# serializes managed syscalls into the deterministic event timeline)
# reads its values from Host.syscall_latency_ns / Host.max_unapplied_ns,
# set from experimental config.

# Channel-wait slice between waitpid fallback polls.  Child death is
# normally detected by the ChildWatcher thread closing the IPC block
# (child_watcher.py); this poll is only a safety net, so it can be
# long without costing latency.  The default; the effective value is
# the experimental.managed_death_poll knob (Host.death_poll_ns,
# surfaced in metrics.wall.ipc.death_poll_ns).
_DEATH_POLL_NS = 2_000_000_000

# Reserved native fd for the manager<->process transfer socket (native
# SCM_RIGHTS delivery), parked just under EMU_FD_BASE so it never
# collides with the kernel's lowest-free allocation in practice.
XFER_FD = 399

# pidfd_open(2) flag: a pidfd for one THREAD (Linux 6.9+), readable
# when the task exits — the event-driven replacement for /proc stat
# polling during thread teardown.
_PIDFD_THREAD = 0x80  # == O_EXCL


def _pidfd_wait(tid: int, flags: int, timeout_s: float):
    """Block until the process/thread exits (pidfd becomes readable).
    True = exited (or already gone); False = timed out; None = the
    kernel lacks pidfd support for this request (caller must fall back
    to polling).  Uses poll(2) — the manager can hold >1024 fds, which
    overflows select()'s fd_set."""
    import errno as _e
    import select as _select
    try:
        fd = os.pidfd_open(tid, flags)
    except OSError as e:
        if e.errno == _e.ESRCH:
            return True  # gone already
        return None      # EINVAL/ENOSYS: unsupported kernel/filter
    try:
        p = _select.poll()
        p.register(fd, _select.POLLIN)
        return bool(p.poll(timeout_s * 1000.0))
    finally:
        os.close(fd)

# personality(2) flag: children inherit it through fork+exec, so setting
# it in the spawning thread gives every managed process a non-randomized
# address space (ref: shadow.rs:429 disable_aslr).  Address-derived
# values otherwise leak real entropy into simulations — OpenSSL's DRBG
# nonce includes pthread_self(), a TCB address, which made TLS
# handshakes differ across byte-identical runs.  personality is a
# per-TASK (thread) attribute and posix_spawn forks from the calling
# thread, so this must run on every scheduler worker thread that
# spawns, not once per process.
_ADDR_NO_RANDOMIZE = 0x0040000
_aslr_tls = threading.local()


def _disable_aslr_once() -> None:
    if getattr(_aslr_tls, "done", False):
        return
    _aslr_tls.done = True
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        cur = libc.personality(0xFFFFFFFF)
        if cur < 0:
            raise OSError(ctypes.get_errno(), "personality query")
        if not (cur & _ADDR_NO_RANDOMIZE):
            if libc.personality(cur | _ADDR_NO_RANDOMIZE) < 0:
                raise OSError(ctypes.get_errno(), "personality")
    except Exception as exc:  # pragma: no cover - sandbox-dependent
        import warnings
        warnings.warn(f"could not disable ASLR ({exc}); address-derived "
                      f"values in managed processes (e.g. OpenSSL DRBG "
                      f"nonces) may be nondeterministic")


class MemoryManager:
    """Zero-copy-ish access to managed-process memory via /proc/pid/mem
    (ref: memory_copier.rs; the remapping MemoryMapper optimization is
    future work — the aggregate accounting below is the measured basis
    for that decision, docs/PARITY.md)."""

    # Aggregate copier accounting across all managed processes
    # (read in sim-stats and by scripts/measure_memcopy.py).
    total_read_bytes = 0
    total_read_ns = 0
    total_write_bytes = 0
    total_write_ns = 0
    total_calls = 0

    def __init__(self, pid: int):
        self.pid = pid
        self._fd = os.open(f"/proc/{pid}/mem", os.O_RDWR)

    def read(self, addr: int, n: int) -> bytes:
        if n <= 0:
            return b""
        t0 = _walltime.perf_counter_ns()  # shadow-lint: allow[wall-clock] memcopy perf counters
        data = os.pread(self._fd, n, addr)
        cls = MemoryManager
        cls.total_read_ns += _walltime.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] memcopy perf counters
        cls.total_read_bytes += len(data)
        cls.total_calls += 1
        if len(data) != n:
            raise OSError(14, "short read from managed process memory")
        return data

    def try_read(self, addr: int, n: int) -> bytes | None:
        try:
            return self.read(addr, n)
        except OSError:
            return None

    def write(self, addr: int, data: bytes) -> None:
        if not data:
            return
        t0 = _walltime.perf_counter_ns()  # shadow-lint: allow[wall-clock] memcopy perf counters
        r = os.pwrite(self._fd, data, addr)
        cls = MemoryManager
        cls.total_write_ns += _walltime.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] memcopy perf counters
        cls.total_write_bytes += len(data)
        cls.total_calls += 1
        if r != len(data):
            raise OSError(14, "short write to managed process memory")

    def read_cstr(self, addr: int, limit: int = 4096) -> bytes:
        """NUL-terminated string; chunk reads may come back short when
        the string sits near the end of a mapping (argv/env strings
        live at the very top of the stack), so accept partial chunks
        and only fault if the NUL is genuinely unreachable."""
        out = bytearray()
        while len(out) < limit:
            chunk_len = min(256, limit - len(out))
            chunk = os.pread(self._fd, chunk_len, addr + len(out))
            nul = chunk.find(b"\0")
            if nul >= 0:
                out += chunk[:nul]
                return bytes(out)
            out += chunk
            if len(chunk) < chunk_len:
                raise OSError(14, "unterminated string at mapping end")
        return bytes(out)

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


def _elf_missing_interp(path: str, _depth: int = 0) -> bool:
    """True when the preload shim cannot ride into `path`: a static
    64-bit ELF (no PT_INTERP), a 32-bit ELF (the shim is 64-bit; ld.so
    would skip it with only a warning and the process would run
    UN-interposed), or a shebang script whose interpreter fails the
    same check (the kernel loads the interpreter directly — there is no
    later execve to catch it).  The reference rejects the static case
    identically ('not a dynamically linked ELF', src/test/static-bin
    asserts that error).  Unreadable/corrupt files return False: the
    kernel's own ENOEXEC path produces the clearer error."""
    import struct as _struct
    try:
        with open(path, "rb") as f:
            hdr = f.read(64)
            if hdr[:2] == b"#!" and _depth < 4:
                line = (hdr + f.read(192)).split(b"\n", 1)[0][2:]
                interp = line.strip().split()
                if not interp:
                    return False
                return _elf_missing_interp(
                    interp[0].decode(errors="replace"), _depth + 1)
            if len(hdr) < 64 or hdr[:4] != b"\x7fELF":
                return False  # not an ELF; let the kernel decide
            if hdr[4] != 2:
                return True   # 32-bit: the 64-bit shim can't load
            phoff = _struct.unpack_from("<Q", hdr, 32)[0]
            phentsize = _struct.unpack_from("<H", hdr, 54)[0]
            phnum = _struct.unpack_from("<H", hdr, 56)[0]
            if phnum == 0 or phentsize < 56:
                return True
            f.seek(phoff)
            phdrs = f.read(phentsize * min(phnum, 128))
            PT_INTERP = 3
            for i in range(min(phnum, 128)):
                if (i + 1) * phentsize > len(phdrs):
                    break  # truncated program headers
                if _struct.unpack_from("<I", phdrs,
                                       i * phentsize)[0] == PT_INTERP:
                    return False
            return True
    except (OSError, _struct.error):
        return False


class ManagedProcess(Process):
    """A Process whose thread drives a real OS process.

    Reuses Process for pid/fd-table/final-state bookkeeping; `stdout`/
    `stderr` fill from the native redirect files at exit so internal and
    managed processes look identical to the manager.
    """

    def __init__(self, host, name, argv, env, expected_final_state="exited 0",
                 work_dir: str | None = None):
        super().__init__(host, name, argv, env, expected_final_state)
        self.work_dir = work_dir or "."
        self.native_pid: int | None = None
        self.mem: MemoryManager | None = None
        self.ipc_block: IpcBlock | None = None
        self.futex_table = FutexTable()
        self._stdout_path: str | None = None
        self._stderr_path: str | None = None

    def live_managed_threads(self) -> int:
        return sum(1 for t in self.threads if t.state != ST_EXITED)

    def _spawn_image(self, host, resolved: str, argv: list,
                     env: dict, truncate_output: bool) -> "ManagedThread":
        """Shared native-image spawn (process start AND execve
        replacement): build/locate the shim, create a fresh IPC block,
        wire LD_PRELOAD / SHADOWTPU_IPC / LD_BIND_NOW, posix_spawn with
        stdio redirected to the process's output files, and register
        the new main thread.  Raises RuntimeError (shim build) or
        OSError (spawn) without touching this process's live state."""
        from shadow_tpu.native import ensure_shim_built
        shim = ensure_shim_built()
        self._exec_count = getattr(self, "_exec_count", 0) + 1
        ipc_path = (f"/dev/shm/shadowtpu-{os.getpid()}-"
                    f"{host.id}-{self.pid}-{self._exec_count}.ipc")
        ipc = IpcBlock(ipc_path)
        try:
            return self._spawn_image_with(host, ipc, ipc_path, shim,
                                          resolved, argv, env,
                                          truncate_output)
        except Exception:
            ipc.close()
            raise

    def _spawn_image_with(self, host, ipc, ipc_path, shim, resolved,
                          argv, env, truncate_output) -> "ManagedThread":
        _disable_aslr_once()
        ipc.set_sim_time(host.now())
        ipc.set_auxv_random(host.rng.next_u64(), host.rng.next_u64())
        ipc.set_self_path(ipc_path)
        if getattr(host, "svc_active", False):
            # Syscall service plane (IPC v8): tell the shim to spin
            # briefly before parking for responses — advisory only.
            from shadow_tpu.host.shim_abi import SVC_ACTIVE
            ipc.set_svc_flags(SVC_ACTIVE)

        env = dict(env)
        # Prepend the shim exactly once (an exec'd app passes through
        # its environ, which already carries it).  The opt-in crypto
        # no-op lib (ref preload-openssl/crypto.c) rides after it.
        chain = [shim]
        crypto_noop = getattr(host, "crypto_noop", None)
        if crypto_noop:  # lib path, resolved once by the Manager
            chain.append(crypto_noop)
        extra = [p for p in env.get("LD_PRELOAD", "").split(":")
                 if p and p not in chain]
        preload = ":".join(chain + extra)
        env["LD_PRELOAD"] = preload
        env["SHADOWTPU_IPC"] = ipc_path
        # Per-process shim diagnostics (ref: .shimlog files).  Absolute:
        # the shim re-resolves the path per message, and the app may
        # chdir at any point.
        env["SHADOWTPU_SHIMLOG"] = os.path.abspath(os.path.join(
            self.work_dir, f"{self.name}.{self.pid}.shimlog"))
        if getattr(host, "preempt_native_ns", 0) > 0:
            env["SHADOWTPU_PREEMPT_NATIVE_US"] = \
                str(max(1, host.preempt_native_ns // 1000))
            env["SHADOWTPU_PREEMPT_SIM_NS"] = str(host.preempt_sim_ns)
        if getattr(host, "native_io_ns_per_kib", 0) > 0:
            env["SHADOWTPU_IO_NS_PER_KIB"] = \
                str(host.native_io_ns_per_kib)
        # Transfer socket for native-fd SCM_RIGHTS delivery: the child
        # gets one end dup2'd to a reserved fd just under EMU_FD_BASE;
        # the manager keeps the other to sendmsg real fds at delivery
        # time (the shim collects and patches the app's cmsg buffer).
        if getattr(self, "_xfer_child_end", None) is None:
            old = getattr(self, "_xfer_sock", None)
            if old is not None:
                old.close()
            mgr_end, child_end = _socket.socketpair(
                _socket.AF_UNIX, _socket.SOCK_DGRAM)
            self._xfer_sock = mgr_end
            self._xfer_child_end = child_end
        env["SHADOWTPU_XFER_FD"] = str(XFER_FD)
        # Eager relocation: keeps ld.so's lazy-binding syscalls out of
        # the simulated timeline.
        env.setdefault("LD_BIND_NOW", "1")
        # OpenSSL determinism (ref: src/lib/preload-openssl/rng.c).  The
        # shim interposes the RAND_* symbols for 1.1-style callers; for
        # OpenSSL 3's provider DRBG — which seeds itself from CPU
        # entropy when available — mask the RDRAND/RDSEED CPUID bits so
        # seeding falls back to the getrandom syscall, which seccomp
        # traps and the manager answers from the host's seeded RNG.
        env.setdefault("OPENSSL_ia32cap", "~0x4000000000000000:~0x40000")
        ipc.set_preload(preload)

        # Always O_APPEND: a fork child's exec'd image opens its own
        # file description on the shared output file, and only append
        # semantics keep concurrent writers from overwriting each other.
        # Process start truncates explicitly instead of O_TRUNC.
        if truncate_output:
            for p in (self._stdout_path, self._stderr_path):
                if p:
                    open(p, "wb").close()
        wflags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
        file_actions = [(os.POSIX_SPAWN_OPEN, 0, "/dev/null",
                         os.O_RDONLY, 0)]
        if self._stdout_path:
            file_actions.append((os.POSIX_SPAWN_OPEN, 1,
                                 self._stdout_path, wflags, 0o644))
        if self._stderr_path:
            file_actions.append((os.POSIX_SPAWN_OPEN, 2,
                                 self._stderr_path, wflags, 0o644))
        # dup2 clears FD_CLOEXEC, so the transfer end survives the exec.
        file_actions.append((os.POSIX_SPAWN_DUP2,
                             self._xfer_child_end.fileno(), XFER_FD))
        argv = list(argv) if argv else [resolved]
        # Spawn-storm taming (docs/ROBUSTNESS.md): wall-only stagger
        # between successive managed spawns, then bounded retry on
        # transient kernel pressure — EAGAIN (fork budget) and ENOMEM
        # ride a short backoff before the containment policy engages.
        from shadow_tpu.svc.containment import (SPAWN_BACKOFF_S,
                                                SPAWN_GATE,
                                                SPAWN_RETRIES)
        import errno as _errno
        SPAWN_GATE.wait(getattr(host, "spawn_stagger_ns", 0))
        for attempt in range(SPAWN_RETRIES + 1):
            try:
                pid = os.posix_spawn(resolved, argv, env,
                                     file_actions=file_actions)
                break
            except OSError as e:
                if e.errno in (_errno.EAGAIN, _errno.ENOMEM) \
                        and attempt < SPAWN_RETRIES:
                    _walltime.sleep(SPAWN_BACKOFF_S * (1 << attempt))  # shadow-lint: allow[wall-clock] bounded posix_spawn retry backoff
                    continue
                ipc.close()
                raise
        # Commit: replace identity state only after the spawn succeeded.
        # The cached pidfd (native-fd SCM_RIGHTS pulls) refers to the
        # OLD native process — drop it or every post-exec pull fails.
        old_pidfd = getattr(self, "_pidfd", None)
        if old_pidfd is not None:
            self._pidfd = None
            try:
                os.close(old_pidfd)
            except OSError:
                pass
        self.native_pid = pid
        if self.mem is not None:
            self.mem.close()
        self.mem = MemoryManager(pid)
        self.ipc_block = ipc
        self.argv = argv
        self._preload = preload
        WATCHER.register(pid, ipc)
        thread = ManagedThread(self, ipc, ipc.channel(0), self._next_tid)
        self._next_tid += 1
        self.threads.append(thread)
        return thread

    def _spawn_failed(self, host, why: str) -> None:
        """Spawn failure (missing/static binary, posix_spawn error
        after the bounded retries): a plugin error under `abort`, a
        contained quarantine under `quarantine`/`restart` (a spawn
        that would not start cannot be healed by restarting —
        docs/ROBUSTNESS.md)."""
        from shadow_tpu.svc.containment import CAUSE_SPAWN
        self.stderr += f"[shadow-tpu] {why}\n".encode()
        self.exited = True
        self.exit_code = 127
        cont = getattr(host, "containment", None)
        if cont is not None and not self.matches_expected_final_state():
            cont.process_failed(host, self, CAUSE_SPAWN, why)

    def start_native(self, host, exe_path: str | None = None) -> None:
        exe = exe_path or (self.argv[0] if self.argv else None)
        resolved = shutil.which(exe) if exe and "/" not in exe else exe
        if not resolved or not os.path.exists(resolved):
            self._spawn_failed(host, f"no such binary: {exe!r}")
            return
        if _elf_missing_interp(resolved):
            self._spawn_failed(host, f"'{resolved}' is not a "
                                     f"dynamically linked ELF")
            return
        os.makedirs(self.work_dir, exist_ok=True)
        self._stdout_path = os.path.join(self.work_dir,
                                         f"{self.name}.{self.pid}.stdout")
        self._stderr_path = os.path.join(self.work_dir,
                                         f"{self.name}.{self.pid}.stderr")
        try:
            thread = self._spawn_image(host, resolved, self.argv,
                                       self.env, truncate_output=True)
        except (RuntimeError, OSError, ValueError) as e:
            # No toolchain / build / spawn failure / oversized preload:
            # a plugin error (or a contained one), not a sim crash —
            # the run completes and reports it.
            self._spawn_failed(host, str(e))
            return
        thread.resume(host)

    def collect_output(self) -> None:
        """Fold new file content into the owning process's buffers.
        Fork children share the parent's output files, so collection
        always happens on the root owner, incrementally — a child that
        outlives its parent still gets its late writes reported."""
        owner = getattr(self, "_output_owner", None) or self
        offsets = owner.__dict__.setdefault("_out_offsets", {})
        for path, buf_name in ((owner._stdout_path, "stdout"),
                               (owner._stderr_path, "stderr")):
            if path and os.path.exists(path):
                with open(path, "rb") as f:
                    f.seek(offsets.get(buf_name, 0))
                    data = f.read()
                offsets[buf_name] = offsets.get(buf_name, 0) + len(data)
                if data:
                    setattr(owner, buf_name,
                            getattr(owner, buf_name) + bytearray(data))

    # -- emulated signals (ref: process.rs signal ingest,
    #    shim/src/signals.rs) --------------------------------------------

    def raise_signal(self, host, sig: int, target_tid: int | None = None,
                     si_code: int = 0, si_pid: int = 0,
                     si_status: int = 0) -> None:
        """Queue `sig` for delivery (kill/tgkill/itimer/shutdown_signal).

        Delivery is deterministic: the chosen thread gets the signal at
        its next syscall response point, and a thread parked in an
        interruptible blocking syscall is woken through the event queue
        to take it (-EINTR / SA_RESTART protocol)."""
        if self.exited or sig <= 0 or sig >= sigmod.NSIG:
            return
        sigs = self.signals
        siginfo = (si_code, si_pid, si_status)
        if sig == sigmod.SIGKILL:
            self.terminate_by_signal(host, sig)
            return
        if sig == sigmod.SIGCONT:
            # The continue side-effect fires at generation time
            # regardless of disposition/blocking (kernel semantics);
            # a SIGCONT handler then delivers through the normal path.
            self.continue_process(host)
        elif self.stopped:
            # The stop shields everything but KILL/CONT until the
            # continue.  Defer the ENTIRE raise — thread targeting,
            # blocked-pending semantics, condition interrupts — to be
            # re-run by continue_process; re-implementing any slice of
            # it here would drop invariants (signalfd's blocked-stays-
            # pending, tgkill's per-thread pending set, EINTR wakes of
            # still-blocked threads).
            if sigs.disposition(sig) != "stop":  # already stopped
                self._stopped_sigs.append((sig, target_tid, si_code,
                                           si_pid, si_status))
            return
        elif sigs.disposition(sig) == "stop":
            # SIGSTOP is unblockable; TSTP/TTIN/TTOU with default
            # disposition stop too (a blocked TSTP would queue, but
            # stop-at-generation matches the kernel's wake-and-stop
            # behavior closely enough for a terminal-less sim).
            self.stop_process(host, sig)
            return
        live = [t for t in self.threads if t.state != ST_EXITED]
        if not live:
            return
        if target_tid is not None:
            target = next((t for t in live if t.tid == target_tid), None)
            if target is None:
                return
        else:
            unblocked = [t for t in live
                         if not (t.sig_mask & sigmod.bit(sig))]
            if not unblocked:
                # BLOCKED signals queue regardless of disposition
                # (kernel sig_ignored() is false for blocked signals) —
                # the sd-event pattern relies on a blocked, default-
                # ignored SIGCHLD staying pending for signalfd.
                self._queue_siginfo(sig, siginfo)
                sigs.pending_process.add(sig)
                self.refresh_signal_fds(host)
                return
            target = min(unblocked, key=lambda t: t.tid)
        if not (target.sig_mask & sigmod.bit(sig)) and \
                sigs.disposition(sig) == "ignore":
            return  # deliverable now and ignored: discarded
        self._queue_siginfo(sig, siginfo, target)
        target.sig_pending.add(sig)
        self.refresh_signal_fds(host)
        if target.sig_mask & sigmod.bit(sig):
            return  # stays pending until the thread unblocks it
        # A sigtimedwait-style waiter consumes the signal directly
        # (no handler runs).
        if getattr(target, "_sigwait_set", 0) & sigmod.bit(sig) and \
                target.state == ST_BLOCKED:
            target.sig_pending.discard(sig)
            self.refresh_signal_fds(host)
            target._sigwait_got = sig
            target._sigwait_info = sigs.take_info(sig)
            if target.last_condition is not None:
                target.last_condition.fire(host)
            return
        if sigs.disposition(sig) == "terminate":
            self.terminate_by_signal(host, sig)
            return
        if target.state == ST_BLOCKED:
            target._sig_interrupted = True
            cond = target.last_condition
            if cond is not None and getattr(cond, "_armed", False):
                cond.disarm()
                target.last_condition = None
                host.schedule_task_at(host.now(),
                                      TaskRef("signal-wake", target._wakeup))
            # else: the condition already fired and a wakeup task is
            # queued; that resume will deliver the signal first.
        # Runnable threads take it at their next response point.

    def _queue_siginfo(self, sig: int, info: tuple, target=None) -> None:
        """Kernel semantics for standard signals: one pending instance;
        the FIRST raiser's siginfo is kept until delivery consumes it —
        a second raise while pending is merged away."""
        pending = sig in self.signals.pending_process or \
            (target is not None and sig in target.sig_pending) or \
            any(sig in t.sig_pending for t in self.threads)
        if not pending:
            self.signals.info[sig] = info

    def terminate_by_signal(self, host, sig: int) -> None:
        """Default-action termination (uncaught fatal signal)."""
        if self.exited:
            return
        self.term_signal = sig
        if self.native_pid is not None:
            try:
                os.kill(self.native_pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            for t in self.threads:
                if isinstance(t, ManagedThread):
                    t._poll_death(host, blocking=True)
                    return
        self.exited = True
        self.exit_code = 128 + sig

    def kill_native(self) -> None:
        """Forced teardown (simulation shutdown with the process still
        running)."""
        if self.native_pid is not None and not self.exited:
            try:
                os.kill(self.native_pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                os.waitpid(self.native_pid, 0)
            except ChildProcessError:
                pass
        for t in self.threads:
            if isinstance(t, ManagedThread):
                t.teardown()


class ManagedThread:
    """Drives one native thread over its IPC channel
    (managed_thread.rs:190-333)."""

    def __init__(self, process: ManagedProcess, block: IpcBlock, chan,
                 tid: int):
        self.process = process
        self.block = block
        self.chan = chan
        self.tid = tid
        self.state = ST_RUNNABLE
        self.native_tid: int | None = None
        self.ctid_addr: int | None = None  # CLONE_CHILD_CLEARTID / set_tid_address
        self.futex_waiter = None           # outcome carrier for FUTEX_WAIT restarts
        self._released = False
        self._pending_response = None  # (kind, value) to send on re-entry
        self._pending_call = None      # (num, args) to re-dispatch
        self.last_condition = None
        self._unapplied_ns = 0
        self.cpu_total_ns = 0  # cumulative modeled CPU (getrusage)
        # Emulated signal state (ref thread.rs:533+ pending signals).
        self.sig_mask = 0              # blocked-signal bitmask
        self.sig_pending: set[int] = set()
        self._sig_interrupted = False  # a signal disarmed our condition
        self._post_handler = []        # continuations parked during handlers
        self._suspend_restore = None   # rt_sigsuspend saved mask
        self._sigwait_set = 0          # rt_sigtimedwait watch set
        self._sigwait_got = None
        self._sigwait_info = (0, 0, 0)
        # Syscall observatory: wall ns the manager spent blocked in
        # the IPC recv that delivered the event currently being
        # serviced (attributed to that syscall's round trip), the
        # snapshot taken at dispatch entry (nested sub-protocol recvs
        # accrue past it and are carved OUT of the resume leg so the
        # wait/dispatch/resume split stays disjoint), and the outcome
        # a handshake sub-protocol reports back to _service.
        self._sc_wait_ns = 0
        self._sc_pre_wait = 0
        self._sc_out = (0, 0)

    # -- latency model ------------------------------------------------

    def add_cpu_latency(self, ns: int) -> None:
        self._unapplied_ns += ns
        self.cpu_total_ns += ns

    # -- channel helpers ----------------------------------------------

    def _recv(self, host):
        """Next shim event, or None if the child died.

        Hang watchdog (docs/ROBUSTNESS.md): with
        `experimental.managed_watchdog` set, a thread that produces no
        IPC event for that much WALL time while its native process is
        alive (userspace spin, a DO_NATIVE syscall that never returns)
        is killed; the death then resolves through the normal path at
        the DETERMINISTIC sim instant this host was servicing, and the
        process's on_failure policy engages."""
        sw = host.sc_wall
        t0 = sw.now() if sw is not None else 0
        cont = getattr(host, "containment", None)
        wd_ns = cont.watchdog_ns if cont is not None else 0
        wd_deadline = (_walltime.monotonic() + wd_ns / 1e9  # shadow-lint: allow[wall-clock] hang-watchdog deadline (wall-only knob)
                       if wd_ns > 0 else None)
        try:
            while True:
                slice_ns = getattr(host, "death_poll_ns",
                                   _DEATH_POLL_NS)
                if wd_deadline is not None:
                    left = wd_deadline - _walltime.monotonic()  # shadow-lint: allow[wall-clock] hang-watchdog deadline (wall-only knob)
                    if left <= 0 and not self._poll_death(host):
                        # Hung: kill the native process; the next
                        # iteration resolves the death (channel close
                        # or waitpid) and _finish engages containment.
                        wd_deadline = None
                        if cont is not None:
                            cont.hang_kill(host, self)
                        continue
                    if left > 0:
                        slice_ns = min(slice_ns,
                                       max(int(left * 1e9), 1_000_000))
                try:
                    ev = self.chan.recv_from_shim(
                        timeout_ns=slice_ns)
                    # Native-I/O latency the shim accrued since its last
                    # event; flows into the standard unapplied-CPU model.
                    ns = self.chan.take_unapplied_ns()
                    if ns:
                        self.add_cpu_latency(ns)
                    # Syscall observatory: locally-answered time reads
                    # the shim counted since its last event (SC_SHIM —
                    # no round trip; the slot protocol orders the read
                    # like take_unapplied_ns).  The drain point is a
                    # function of the event sequence alone, so the
                    # count — and any record — is deterministic.
                    n = self.chan.take_local_count()
                    if n:
                        host.sc_disp[trev.SC_SHIM] += n
                        log = host.sc_log
                        if log is not None:
                            t = host.now()
                            log.rec(t, t, host.id, self.process.pid,
                                    self.tid, -1, trev.RC_OK,
                                    trev.SC_SHIM, n)
                    return ev
                except ChannelTimeout:
                    if self._poll_death(host):
                        return None
                except ChannelClosed:
                    self._poll_death(host, blocking=True)
                    return None
        finally:
            if sw is not None:
                # Accumulate (don't assign): nested receives inside a
                # dispatch's sub-protocol (clone/fork handshakes, the
                # fd-transfer dance) fold into the trip that consumes
                # the accumulator, instead of clobbering the wait the
                # original syscall event already paid.
                self._sc_wait_ns += sw.now() - t0

    def _poll_death(self, host, blocking: bool = False) -> bool:
        pid = self.process.native_pid
        try:
            done, status = os.waitpid(pid, 0 if blocking else os.WNOHANG)
        except ChildProcessError:
            self._finish(host, 126)
            return True
        if done == 0:
            return False
        if os.WIFEXITED(status):
            code = os.WEXITSTATUS(status)
        else:
            code = 128 + os.WTERMSIG(status)
            if self.process.term_signal is None:
                # A NATIVE fatal signal (segfault etc.) is this
                # process's final state, same as an emulated one.
                self.process.term_signal = os.WTERMSIG(status)
        self._finish(host, code)
        return True

    # -- the resume loop ----------------------------------------------

    def resume(self, host) -> None:
        if self.state == ST_EXITED:
            return
        if self.process.stopped:
            # Job control: defer until SIGCONT (the native process
            # stays parked in its channel recv meanwhile).
            self.process._stopped_resumes.append(self.resume)
            return
        self.state = ST_RUNNABLE
        self.block.set_sim_time(host.now())

        if not self._released:
            ev = self._recv(host)
            if ev is None:
                return
            kind, num, _args = ev
            if kind != EV_START_REQ:
                self._protocol_error(host, f"expected StartReq, got {kind}")
                return
            self.native_tid = int(num)
            self.chan.send_to_shim(EV_START_RES)
            self._released = True

        # Emulated signal delivery at the resume boundary: a signal that
        # interrupted a blocked syscall, or arrived while parked for CPU
        # latency, is delivered (handler invoked shim-side) before the
        # owed response goes out.
        if self.process.signals.has_deliverable(self):
            interrupted, self._sig_interrupted = self._sig_interrupted, False
            if interrupted and self._pending_call is not None:
                r = self._deliver_signals(host, self._interrupted_cont)
            elif self._pending_response is not None:
                k, v = self._pending_response
                self._pending_response = None
                r = self._deliver_signals(host, ("resp", k, v, None))
                if r == "none":
                    # Every pending signal turned out ignorable (its
                    # disposition flipped while we were parked): the
                    # owed response must still go out below.
                    self._pending_response = (k, v)
            else:
                r = "none"  # no owed response: next response point takes it
            if r == "dead":
                return
        else:
            self._sig_interrupted = False

        if self.process.stopped:
            # A signal delivered above froze the process: everything
            # owed (response, call re-run, the pump) waits for SIGCONT.
            self.process._stopped_resumes.append(self.resume)
            return

        if self._pending_response is not None:
            kind, value = self._pending_response
            self._pending_response = None
            self.chan.send_to_shim(kind, value)

        if self._pending_call is not None:
            num, args = self._pending_call
            self._pending_call = None
            if not self._service(host, num, args, restarted=True):
                return

        self._pump(host)

    def _pump(self, host) -> None:
        while True:
            ev = self._recv(host)
            if ev is None:
                return
            kind, num, args = ev
            if kind == EV_SIGNAL_DONE:
                if not self._handler_returned(host):
                    return
                continue
            if kind != EV_SYSCALL:
                self._protocol_error(host, f"unexpected event kind {kind}")
                return
            if not self._service(host, num, args, restarted=False):
                return

    # -- emulated signal delivery -------------------------------------

    def _interrupted_cont(self, sig: int):
        """Continuation for the blocked syscall `sig` interrupted:
        SA_RESTART re-runs restartable calls, everything else -EINTR
        (handler/mod.rs restart protocol; man 7 signal)."""
        import errno as _errno
        num, args = self._pending_call
        self._pending_call = None
        self._sigwait_set = 0
        act = self.process.signals.action(sig)
        name = syscall_name(num)
        if (act.flags & sigmod.SA_RESTART) and name in sigmod.RESTARTABLE:
            return ("call", num, args)
        restore = None
        if name == "rt_sigsuspend":
            restore, self._suspend_restore = self._suspend_restore, None
        return ("resp", EV_SYSCALL_COMPLETE, -_errno.EINTR, restore)

    def _deliver_signals(self, host, cont):
        """Deliver the next deliverable pending signal; `cont` (a tuple,
        or a callable sig->tuple) is what to do once the handler
        returns.  Returns "sent" (EV_SIGNAL dispatched, cont parked),
        "dead" (default action terminated the process), or "none"
        (nothing deliverable — caller proceeds with cont itself)."""
        sigs = self.process.signals
        while True:
            sig = sigs.take_deliverable(self)
            if sig is None:
                return "none"
            self.process.refresh_signal_fds(host)
            disp = sigs.disposition(sig)
            if disp == "ignore":
                continue
            if disp == "stop":
                # A pending stop signal whose action reverted to
                # default: freeze the process and stop delivering —
                # the caller's response point parks the owed response
                # (_send_response_or_park) until SIGCONT.
                self.process.stop_process(host, sig)
                return "none"
            if disp == "terminate":
                self.process.terminate_by_signal(host, sig)
                return "dead"
            act = sigs.action(sig)
            saved_mask = self.sig_mask
            self.sig_mask |= act.mask
            if not (act.flags & sigmod.SA_NODEFER):
                self.sig_mask |= sigmod.bit(sig)
            if act.flags & sigmod.SA_RESETHAND:
                sigs.actions.pop(sig, None)
            resolved = cont(sig) if callable(cont) else cont
            self._post_handler.append((resolved, saved_mask))
            si_code, si_pid, si_status = sigs.take_info(sig)
            # The shim builds the handler's siginfo from args[2..4]
            # (si_code, si_pid, si_status) and its ucontext from the
            # live trap frame + args[5] = the emulated blocked mask at
            # delivery (what uc_sigmask restores after the handler —
            # Linux semantics; the native mask would be the shim's).
            mask_i64 = saved_mask - (1 << 64) \
                if saved_mask >= (1 << 63) else saved_mask
            self.chan.send_to_shim(EV_SIGNAL, sig,
                                   (act.handler, act.flags, si_code,
                                    si_pid, si_status, mask_i64))
            return "sent"

    def _handler_returned(self, host) -> bool:
        """EV_SIGNAL_DONE: restore the mask, deliver any further pending
        signal, then run the parked continuation.  Returns False when
        the pump must stop (process died / re-blocked)."""
        if not self._post_handler:
            self._protocol_error(host, "SIGNAL_DONE without handler")
            return False
        cont, saved_mask = self._post_handler.pop()
        self.sig_mask = saved_mask
        r = self._deliver_signals(host, cont)
        if r == "sent":
            return True
        if r == "dead":
            return False
        if cont[0] == "resp":
            _k, rk, rv, restore = cont
            if restore is not None:
                self.sig_mask = restore
            return self._send_response_or_park(host, rk, rv)
        if self.process.stopped:
            # Stop delivered above: defer the SA_RESTART re-dispatch.
            self._pending_call = (cont[1], tuple(cont[2]))
            self.process._stopped_resumes.append(self.resume)
            return False
        _k, num, args = cont  # ("call", ...) — SA_RESTART re-dispatch
        return self._service(host, num, args, restarted=False)

    def _park(self, host, condition, num: int, args) -> None:
        """Block this thread on `condition`, re-running (num, args) on
        wakeup — the single home of the blocking bookkeeping."""
        self._pending_call = (num, tuple(args))
        self.last_condition = condition
        self.state = ST_BLOCKED
        condition.arm(host, self._wakeup)

    def _sc_note(self, host, t_enter: int, num: int, disp: int,
                 rclass: int, t_exit: int | None = None) -> None:
        """Credit this dispatch its single SC_* disposition (always-on
        counters) and append the per-syscall record when the syscall
        observatory's sim channel is recording.  One call per dispatch
        — the conservation contract the `trace sys` report checks
        against strace line counts."""
        host.sc_disp[disp] += 1
        log = host.sc_log
        if log is not None:
            log.rec(t_enter,
                    t_exit if t_exit is not None else host.now(),
                    host.id, self.process.pid, self.tid, num, rclass,
                    disp)

    def _sc_trip(self, sw, num: int, w0: int, w1: int) -> None:
        """Feed the wall profile one round trip: the recv wait that
        delivered this event + dispatch + everything after dispatch
        (strace, signal delivery, response send).  Nested sub-protocol
        waits (clone/fork handshakes, the fd-transfer dance) accrued
        past the dispatch-entry snapshot sit inside [w1, now]; carve
        them out of the resume leg so the three legs stay disjoint.
        No-op when the observatory is off — the single guard for
        every branch."""
        if sw is None:
            return
        nested = self._sc_wait_ns - self._sc_pre_wait
        sw.trip(syscall_name(num), self._sc_wait_ns, w1 - w0,
                max(sw.now() - w1 - nested, 0))
        self._sc_wait_ns = 0

    def _service(self, host, num: int, args, restarted: bool) -> bool:
        """Dispatch one syscall; returns True to keep pumping events."""
        handler = host.syscall_handler_native
        host.count_syscall(syscall_name(num))
        process = self.process
        sc_t0 = host.now()
        sw = host.sc_wall
        w0 = w1 = 0
        if sw is not None:
            w0 = sw.now()
            self._sc_pre_wait = self._sc_wait_ns
        result = handler.dispatch(host, process, self, num, args, restarted)
        if sw is not None:
            w1 = sw.now()
        if process.strace_mode is not None:
            from shadow_tpu.host import strace
            process.strace_write(strace.format_native_call(
                host.now(), self.tid, num, args, result,
                process.strace_mode).encode())
        kind = result[0]

        if kind == "block":
            self._sc_note(host, sc_t0, num, trev.SC_PARKED,
                          trev.RC_NONE)
            self._sc_trip(sw, num, w0, w1)
            self._park(host, result[1], num, args)
            return False

        if kind in ("clone", "fork", "execve"):
            # The handshake sub-protocols report their real outcome
            # through _sc_out (set before each completion send); a
            # conversation that dies mid-dance keeps the SC_PROTO
            # default — the record is noted AFTER the dance, and the
            # trip too (its nested channel waits accumulated into
            # _sc_wait_ns and the dance is this round trip's resume
            # cost).
            self._sc_out = (trev.SC_PROTO, trev.RC_NONE)
            if kind == "clone":
                keep = self._do_clone(host, result[1], result[2])
            elif kind == "fork":
                keep = self._do_fork(host)
            else:
                keep = self._do_execve(host, result[1], result[2],
                                       result[3])
            self._sc_note(host, sc_t0, num, *self._sc_out)
            self._sc_trip(sw, num, w0, w1)
            return keep

        if kind == "thread_exit":
            # A secondary thread exiting (SYS_exit with siblings alive):
            # let the native thread die, then emulate the kernel's
            # CLONE_CHILD_CLEARTID contract against OUR futex table so a
            # pthread_join blocked in the emulated FUTEX_WAIT wakes.
            code = result[1]
            self._sc_note(host, sc_t0, num, trev.SC_NATIVE,
                          trev.RC_NATIVE)
            self._sc_trip(sw, num, w0, w1)
            self.chan.send_to_shim(EV_SYSCALL_DO_NATIVE)
            if not self._await_native_thread_gone():
                # Delivering the CLEARTID wake while ctid may still be
                # nonzero would let a joiner re-park forever; failing
                # the process loudly beats a silent deadlock.
                self._protocol_error(
                    host, f"native tid {self.native_tid} did not tear "
                          f"down within 5s of thread exit")
                return False
            self.state = ST_EXITED
            if self.last_condition is not None:
                self.last_condition.disarm()
                self.last_condition = None
            self.block.free_channel(self.chan.index)
            if self.ctid_addr:
                # The kernel already wrote 0 (we waited for thread
                # teardown above); deliver the wake to emulated waiters.
                self.process.futex_table.wake(host, self.ctid_addr, 1)
            # Record the exit code (a crashed helper thread must not be
            # masked by a clean main thread — process.py invariant).
            self.process.thread_exited(host, self, code)
            return False

        if kind == "exit":
            # Short-circuit (managed_thread.rs:268-282): let the native
            # exit_group run, then reap synchronously.  The wait is
            # event-driven (poll on the process pidfd), not a
            # wall-clock slice loop.
            self._sc_note(host, sc_t0, num, trev.SC_NATIVE,
                          trev.RC_NATIVE)
            self._sc_trip(sw, num, w0, w1)
            self.chan.send_to_shim(EV_SYSCALL_DO_NATIVE)
            if _pidfd_wait(self.process.native_pid, 0, 10.0) is None:
                # No pidfd support: fall back to the timed slice poll.
                deadline = _walltime.monotonic() + 10.0  # shadow-lint: allow[wall-clock] real-OS process-death wait
                while _walltime.monotonic() < deadline:  # shadow-lint: allow[wall-clock] real-OS process-death wait
                    if self._poll_death(host):
                        return False
                    _walltime.sleep(0.001)
            if self._poll_death(host):
                return False
            self._protocol_error(host, "child did not exit after exit_group")
            return False

        if kind == "done_fdxfer":
            # Native fds in an SCM_RIGHTS delivery: run the transfer
            # dance (sendmsg on the xfer socket + shim collection)
            # before the ordinary completion below.
            if not self._do_fdxfer(host, *result[2:]):
                # Receiver died mid-dance: the dispatch happened (and
                # strace logged it) but no response ever lands.
                self._sc_note(host, sc_t0, num, trev.SC_PROTO,
                              trev.RC_NONE)
                self._sc_trip(sw, num, w0, w1)
                return False
            kind, result = "done", ("done", result[1])

        if kind == "native":
            rv_kind, rv_val = EV_SYSCALL_DO_NATIVE, 0
            sc_disp, sc_rc = trev.SC_NATIVE, trev.RC_NATIVE
        elif kind == "done":
            rv_kind, rv_val = EV_SYSCALL_COMPLETE, int(result[1] or 0)
            sc_disp, sc_rc = trev.SC_SERVICED, trev.RC_OK
        elif kind == "error":
            err = result[1]
            rv_kind, rv_val = EV_SYSCALL_COMPLETE, -int(err.errno or 22)
            sc_disp, sc_rc = trev.SC_SERVICED, trev.RC_ERR
        else:  # pragma: no cover
            raise AssertionError(f"bad dispatch result {result!r}")

        # The dispatch may have terminated this very process (a
        # self-directed fatal signal): the channel is gone, stop pumping.
        if self.state == ST_EXITED or process.exited:
            self._sc_note(host, sc_t0, num, sc_disp, sc_rc)
            self._sc_trip(sw, num, w0, w1)
            return False

        # Response point: emulated signals are delivered before the
        # response reaches the app (the kernel's return-to-user check).
        if process.signals.has_deliverable(self):
            restore = None
            if syscall_name(num) == "rt_sigsuspend":
                restore, self._suspend_restore = self._suspend_restore, None
            r = self._deliver_signals(
                host, ("resp", rv_kind, rv_val, restore))
            if r in ("sent", "dead"):
                # The response rides the parked continuation (or never
                # lands at all): the dispatch itself is complete.
                self._sc_note(host, sc_t0, num, sc_disp, sc_rc)
                self._sc_trip(sw, num, w0, w1)
                return r == "sent"
            if restore is not None:
                # rt_sigsuspend with every pending signal consumed as
                # ignored (disposition flipped while blocked): no handler
                # ran, so the kernel would keep waiting with the
                # temporary mask — re-park instead of returning EINTR,
                # and keep the saved mask for the eventual real wakeup.
                from shadow_tpu.core import simtime
                self._sc_note(host, sc_t0, num, trev.SC_PARKED,
                              trev.RC_NONE)
                self._sc_trip(sw, num, w0, w1)
                self._suspend_restore = restore
                self._park(host, SyscallCondition(
                    timeout_at=simtime.TIME_NEVER - 1), num, args)
                return False

        lat = host.syscall_latency_ns
        self.add_cpu_latency(lat)
        if host.cpu is not None:
            host.cpu.add_delay(lat)  # feeds the host CPU model (cpu.rs)
        if self._unapplied_ns >= host.max_unapplied_ns:
            # Apply accumulated CPU time: answer only after the event
            # queue reaches now + latency (possibly next round).
            self._pending_response = (rv_kind, rv_val)
            apply_at = host.now() + self._unapplied_ns
            self._unapplied_ns = 0
            # The response lands at apply_at, not now: the record's
            # exit stamp carries the deferred instant (deterministic —
            # both addends are simulated values).
            self._sc_note(host, sc_t0, num, sc_disp, sc_rc,
                          t_exit=apply_at)
            self._sc_trip(sw, num, w0, w1)
            host.schedule_task_at(apply_at,
                                  TaskRef("cpu-latency", self.resume))
            return False

        self._sc_note(host, sc_t0, num, sc_disp, sc_rc)
        keep = self._send_response_or_park(host, rv_kind, rv_val)
        # Trip AFTER the send so the resume leg includes the response
        # publish + futex wake.
        self._sc_trip(sw, num, w0, w1)
        return keep

    def _send_response_or_park(self, host, rv_kind, rv_val) -> bool:
        """Send a syscall response — unless the process stopped while
        servicing it (a self-directed SIGSTOP, or a stop delivered at
        this response point): the kernel returns from the interrupted
        syscall only after the continue, so park the owed response and
        re-arm through the deferred-resume list.  Returns True to keep
        pumping."""
        if self.process.stopped:
            self._pending_response = (rv_kind, rv_val)
            self.process._stopped_resumes.append(self.resume)
            return False
        self.chan.send_to_shim(rv_kind, rv_val)
        return True

    # -- clone protocol (managed_thread.rs:359 native_clone) ----------

    def _do_clone(self, host, flags: int, ctid: int) -> bool:
        """Three-way handshake: hand the shim a channel index, let it
        run the real clone (child parks immediately), register the new
        ManagedThread, and schedule its start through the event queue so
        thread birth is a deterministic simulation event."""
        idx = self.block.alloc_channel()
        if idx is None:
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, -11)  # EAGAIN
            return True
        self.chan.send_to_shim(EV_CLONE_RES, idx)
        ev = self._recv(host)
        if ev is None:
            return False
        kind, child_tid, _args = ev
        if kind != EV_CLONE_DONE:
            self._protocol_error(host, f"expected CloneDone, got {kind}")
            return False
        child_tid = int(child_tid)
        if child_tid < 0:
            self.block.free_channel(idx)
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, child_tid)
            return True
        process = self.process
        child = ManagedThread(process, self.block, self.block.channel(idx),
                              process._next_tid)
        process._next_tid += 1
        child.native_tid = child_tid
        _CLONE_CHILD_CLEARTID = 0x200000
        if flags & _CLONE_CHILD_CLEARTID:
            child.ctid_addr = ctid
        process.threads.append(child)
        host.schedule_task_at(host.now(), TaskRef("thread-start",
                                                  child.resume))
        self._sc_out = (trev.SC_SERVICED, trev.RC_OK)
        self.chan.send_to_shim(EV_SYSCALL_COMPLETE, child_tid)
        return True

    # -- fork / execve (ref: process.rs:297,944 spawn_mthread_for_exec,
    #    clone-handler fork path) -------------------------------------

    def _do_fdxfer(self, host, pairs, refs, msg_ptr, control_ptr,
                   emu_fds) -> bool:
        """Deliver native fds for an SCM_RIGHTS recvmsg: send the real
        fds (manager-held dups) over the process's transfer socket with
        their cmsg slot addresses as payload, tell the shim to collect
        and patch, and wait for EV_XFER_DONE.  On any failure the cmsg
        is rewritten to carry only the already-registered emulated fds
        (never a -1 hole) with MSG_CTRUNC — like Linux closing
        unclaimed fds.  Returns False if the process died mid-dance."""
        from shadow_tpu.host.descriptor import _decref
        proc = self.process
        sock = getattr(proc, "_xfer_sock", None)
        status = -1
        if sock is not None:
            payload = b"".join(struct.pack("<Q", a) for a, _f in pairs)
            try:
                _socket.send_fds(sock, [payload],
                                 [f for _a, f in pairs])
            except OSError:
                sock = None
        if sock is not None:
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE_FDXFER, len(pairs))
            ev = self._recv(host)
            if ev is None:
                # Receiver died before collecting: drain the queued
                # datagram via our handle on the child-side end, or a
                # fork sibling's next transfer would pop it (and patch
                # stale addresses).
                ce = getattr(proc, "_xfer_child_end", None)
                if ce is not None:
                    try:
                        ce.setblocking(False)
                        _msg, stale_fds, _fl, _ad = _socket.recv_fds(
                            ce, 4096, 64)
                        for f in stale_fds:
                            os.close(f)
                    except OSError:
                        pass
                for r in refs:
                    _decref(r, host)
                return False
            ev_kind, num, _args = ev
            if ev_kind != EV_XFER_DONE:
                for r in refs:
                    _decref(r, host)
                self._protocol_error(
                    host, f"expected XferDone, got {ev_kind}")
                return False
            status = int(num)
        for r in refs:
            _decref(r, host)
        if status != 0:
            # Rewrite the cmsg keeping the emulated fds the receiver
            # already owns; dropping them would orphan live table
            # entries the app could never close.
            MSG_CTRUNC = 0x8
            if emu_fds:
                cmsg = struct.pack("<QII", 16 + 4 * len(emu_fds), 1, 1)
                cmsg += b"".join(struct.pack("<i", f) for f in emu_fds)
                proc.mem.write(control_ptr, cmsg)
                proc.mem.write(msg_ptr + 40,
                               struct.pack("<Q", len(cmsg)))
            else:
                proc.mem.write(msg_ptr + 40, struct.pack("<Q", 0))
            proc.mem.write(msg_ptr + 48, struct.pack("<i", MSG_CTRUNC))
        return True

    def _do_fork(self, host) -> bool:
        """fork/vfork/fork-style clone: create the child ManagedProcess
        and its fresh IPC block, hand the path to the shim (EV_FORK_RES),
        let it run clone(SIGCHLD|CLONE_PARENT) — CLONE_PARENT so the
        manager stays the waitpid()-able parent of every native process
        — then register the child thread on our side."""
        parent = self.process
        child = ManagedProcess(
            host, f"{parent.name}.f", list(parent.argv), dict(parent.env),
            expected_final_state="any", work_dir=parent.work_dir)
        ipc_path = (f"/dev/shm/shadowtpu-{os.getpid()}-"
                    f"{host.id}-{child.pid}.ipc")
        try:
            ipc = IpcBlock(ipc_path)
        except OSError:
            host.processes.pop(child.pid, None)
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, -11)  # EAGAIN
            return True
        ipc.set_sim_time(host.now())
        ipc.set_auxv_random(host.rng.next_u64(), host.rng.next_u64())
        ipc.set_self_path(ipc_path)
        if getattr(host, "svc_active", False):
            from shadow_tpu.host.shim_abi import SVC_ACTIVE
            ipc.set_svc_flags(SVC_ACTIVE)
        preload = getattr(parent, "_preload", "")
        if preload:
            ipc.set_preload(preload)
        child._preload = preload
        child.ipc_block = ipc

        def abort_fork():
            ipc.close()
            host.processes.pop(child.pid, None)

        self.block.set_fork_path(ipc_path)
        self.chan.send_to_shim(EV_FORK_RES)
        ev = self._recv(host)
        if ev is None:
            abort_fork()
            return False
        kind, native_pid, _args = ev
        if kind != EV_FORK_DONE:
            abort_fork()
            self._protocol_error(host, f"expected ForkDone, got {kind}")
            return False
        native_pid = int(native_pid)
        if native_pid < 0:
            abort_fork()
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, native_pid)
            return True

        child.native_pid = native_pid
        child.mem = MemoryManager(native_pid)
        WATCHER.register(native_pid, ipc)
        child.fds = parent.fds.fork_copy()
        plow = getattr(parent, "fds_low", None)
        if plow is not None:
            child.fds_low = plow.fork_copy()
        from shadow_tpu.host.files import SignalFd
        for table in (child.fds, getattr(child, "fds_low", None)):
            if table is None:
                continue
            for cfd, f in table.items():
                if isinstance(f, SignalFd):
                    # Each SignalFd serves one process: the child gets
                    # its own view bound to itself (files.py scope
                    # model).
                    table.replace(cfd, f.clone_for(child))
        child.signals = parent.signals.clone()
        seg = child.signals.action(sigmod.SIGSEGV)
        if seg.handler:
            ipc.set_sigsegv_action(seg.handler, seg.flags)
        child.parent_pid = parent.pid
        child.pgid = parent.pgid  # fork inherits process group/session
        child.sid = parent.sid
        child.strace_mode = parent.strace_mode
        # The child shares the parent's native stdout/stderr fds; it
        # remembers the paths (an exec'd image re-opens them O_APPEND)
        # while collection folds incrementally into the root owner.
        child._stdout_path = parent._stdout_path
        child._stderr_path = parent._stderr_path
        child._output_owner = getattr(parent, "_output_owner",
                                      None) or parent
        # The forked child's fd 399 is the parent's transfer socket
        # (same open description); give the manager an independent
        # handle so each side's teardown closes only its own.
        pxfer = getattr(parent, "_xfer_sock", None)
        if pxfer is not None:
            child._xfer_sock = pxfer.dup()
        pxce = getattr(parent, "_xfer_child_end", None)
        if pxce is not None:
            child._xfer_child_end = pxce.dup()
        thread = ManagedThread(child, ipc, ipc.channel(0), child._next_tid)
        child._next_tid += 1
        thread.sig_mask = self.sig_mask  # fork inherits the caller's mask
        child.threads.append(thread)
        host.schedule_task_at(host.now(), TaskRef("fork-start",
                                                  thread.resume))
        self._sc_out = (trev.SC_SERVICED, trev.RC_OK)
        self.chan.send_to_shim(EV_SYSCALL_COMPLETE, child.pid)
        return True

    def _do_execve(self, host, path: str, argv: list, envp: list) -> bool:
        """execve replaces the native process outright: the inherited
        seccomp filter would SIGSYS-kill a fresh image before its shim
        constructor installs a handler, so (like the reference's
        spawn_mthread_for_exec) we posix_spawn the new image against a
        fresh IPC block, and only once that succeeds kill the old
        native process — spawn failures (ENOENT/EACCES/ENOEXEC) return
        to the caller like a failed execve should.  The emulated
        process identity (pid, fd table, parent) is preserved."""
        import errno as _errno
        process = self.process
        # /proc/self in the CALLER's context, not the manager's.
        if path == "/proc/self/exe":
            try:
                path = os.readlink(f"/proc/{process.native_pid}/exe")
            except OSError:
                pass
        elif path.startswith("/proc/self/"):
            path = f"/proc/{process.native_pid}/" + path[11:]
        if "/" not in path:
            # The kernel does not PATH-search execve (that's execvp's
            # userspace job).
            resolved = None
        elif not path.startswith("/"):
            # Relative to the CALLER's cwd (chdir runs natively in the
            # managed process, so the manager's cwd is unrelated).
            try:
                cwd = os.readlink(f"/proc/{process.native_pid}/cwd")
            except OSError:
                cwd = "/"
            resolved = os.path.normpath(os.path.join(cwd, path))
        else:
            resolved = path
        if not resolved or not os.path.exists(resolved):
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, -_errno.ENOENT)
            return True
        if not os.access(resolved, os.X_OK):
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, -_errno.EACCES)
            return True
        if _elf_missing_interp(resolved):
            # Static ELF: the shim cannot ride into it (see
            # _elf_missing_interp); refuse like a bad format.
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, -_errno.ENOEXEC)
            return True

        env = {}
        for item in envp:
            k, _sep, v = item.partition("=")
            env[k] = v
        old_pid = process.native_pid
        old_block = process.ipc_block
        try:
            new_thread = process._spawn_image(host, resolved,
                                              list(argv) or [resolved],
                                              env, truncate_output=False)
        except (RuntimeError, OSError, ValueError) as e:
            if isinstance(e, OSError) and e.errno:
                code = e.errno
            elif isinstance(e, ValueError):  # oversized env/preload
                code = _errno.E2BIG
            else:
                code = _errno.ENOEXEC
            self._sc_out = (trev.SC_SERVICED, trev.RC_ERR)
            self.chan.send_to_shim(EV_SYSCALL_COMPLETE, -code)
            return True

        # Point of no return: retire the old image.  All its threads
        # die on exec; no response is owed to it.
        for t in process.threads:
            if isinstance(t, ManagedThread) and t is not new_thread \
                    and t.state != ST_EXITED:
                if t.last_condition is not None:
                    t.last_condition.disarm()
                    t.last_condition = None
                t.state = ST_EXITED
        try:
            os.kill(old_pid, signal.SIGKILL)
        except (ProcessLookupError, TypeError):
            pass
        try:
            os.waitpid(old_pid, 0)
        except (ChildProcessError, OSError):
            pass
        WATCHER.unregister(old_pid)
        # Closed only after the kill: a live shim seeing CLOSED would
        # print a channel-teardown complaint into the shared stderr.
        old_block.mark_closed()
        old_block.close()

        # POSIX exec semantics on the emulated state.
        process.fds.close_cloexec(host)
        plow = getattr(process, "fds_low", None)
        if plow is not None:
            plow.close_cloexec(host)
        process.signals.actions = {
            s: a for s, a in process.signals.actions.items()
            if a.handler == 1}  # SIG_IGN survives, handlers reset
        seg = process.signals.action(sigmod.SIGSEGV)
        if seg.handler:
            process.ipc_block.set_sigsegv_action(seg.handler, seg.flags)
        process.futex_table = FutexTable()
        new_thread.sig_mask = self.sig_mask  # exec preserves the mask
        host.schedule_task_at(host.now(), TaskRef("exec-start",
                                                  new_thread.resume))
        self._sc_out = (trev.SC_SERVICED, trev.RC_OK)
        return False  # the old image's pump ends here

    def _await_native_thread_gone(self) -> bool:
        """Busy-poll until the kernel has fully torn the thread down —
        only then has CLONE_CHILD_CLEARTID been honored and the thread
        stack gone quiescent (a joiner may free it the moment it sees
        tid==0).  The thread-group leader's /proc task entry persists as
        a zombie until the whole process exits, so accept state Z/X
        there, not just disappearance.  False on timeout (the caller
        fails the process rather than risking a lost-wake deadlock)."""
        # Mostly event-driven: a thread pidfd (PIDFD_THREAD, Linux
        # 6.9+) becomes readable when the task exits — but a ZOMBIE
        # thread-group leader (main thread gone, workers alive) parks
        # in Z without signalling its pidfd, so interleave short pidfd
        # waits with /proc state checks instead of busy-polling.
        path = (f"/proc/{self.process.native_pid}/task/"
                f"{self.native_tid}/stat")
        deadline = _walltime.monotonic() + 5.0  # shadow-lint: allow[wall-clock] real-OS thread-death wait
        while _walltime.monotonic() < deadline:  # shadow-lint: allow[wall-clock] real-OS thread-death wait
            try:
                with open(path) as f:
                    stat = f.read()
            except OSError:
                return True  # task entry gone
            # State is the field after the parenthesized comm.
            state = stat.rpartition(")")[2].lstrip()[:1]
            if state in ("Z", "X", ""):
                return True
            waited = _pidfd_wait(self.native_tid, _PIDFD_THREAD, 0.05)
            if waited:
                return True
            if waited is None:
                # Pre-6.9 kernel (no PIDFD_THREAD): the /proc check
                # above is the only signal — keep the old short sleep
                # instead of spinning.
                _walltime.sleep(0.0002)
        return False

    def _wakeup(self, host) -> None:
        if self.state == ST_BLOCKED:
            self.resume(host)

    def _protocol_error(self, host, why: str) -> None:
        # Observatory note: dispositions are credited strictly at
        # dispatch level (exactly one per dispatch — the conservation
        # contract); a dispatch whose conversation dies mid-service is
        # credited SC_PROTO by its _service branch, and teardown here
        # adds nothing on top.
        self.process.stderr += (
            f"[shadow-tpu] managed IPC protocol error: {why}\n").encode()
        try:
            os.kill(self.process.native_pid, signal.SIGKILL)
        except (ProcessLookupError, TypeError):
            pass
        self._poll_death(host, blocking=True)

    def _finish(self, host, code: int) -> None:
        """The native *process* is gone (waitpid reaped it): every
        thread is dead, not just this one."""
        if self.state == ST_EXITED:
            return
        process = self.process
        if process.term_signal is not None:
            # Killed by an *emulated* fatal signal (the native reap saw
            # our SIGKILL; report the simulated signal instead).
            code = 128 + process.term_signal
        for t in process.threads:
            if isinstance(t, ManagedThread) and t.state != ST_EXITED:
                t.state = ST_EXITED
                if t.last_condition is not None:
                    t.last_condition.disarm()
                    t.last_condition = None
        self.teardown()
        if process.mem is not None:
            process.mem.close()
        process.collect_output()
        process.thread_exited(host, self, code)
        # Failure containment (docs/ROBUSTNESS.md): an UNEXPECTED
        # death — the process's recorded final state fails its
        # expectation — engages the per-process on_failure policy at
        # this deterministic sim instant.  Expected exits (and the
        # `abort` policy) change nothing.
        cont = getattr(host, "containment", None)
        if cont is not None and process.exited \
                and not process.matches_expected_final_state():
            from shadow_tpu.svc.containment import CAUSE_DEATH
            state = (f"signaled {process.term_signal}"
                     if process.term_signal is not None
                     else f"exited {process.exit_code}")
            cont.process_failed(host, process, CAUSE_DEATH, state)

    def teardown(self) -> None:
        """Close the whole process's IPC block (idempotent)."""
        WATCHER.unregister(self.process.native_pid)
        self.block.mark_closed()
        self.block.close()
        process = self.process
        for attr in ("_xfer_sock", "_xfer_child_end"):
            s = getattr(process, attr, None)
            if s is not None:
                setattr(process, attr, None)
                try:
                    s.close()
                except OSError:
                    pass
        pidfd = getattr(process, "_pidfd", None)
        if pidfd is not None:
            process._pidfd = None
            try:
                os.close(pidfd)
            except OSError:
                pass

    # Process.thread_exited checks thread.state via the same constants;
    # the generator-thread interface ends here.
    def _exit(self, host, code: int) -> None:
        """Forced exit (manager shutdown path), mirror of Thread._exit."""
        if self.state == ST_EXITED:
            return
        try:
            os.kill(self.process.native_pid, signal.SIGKILL)
        except (ProcessLookupError, TypeError):
            pass
        self._poll_death(host, blocking=True)
