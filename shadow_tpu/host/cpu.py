"""Host CPU model (ref: src/main/host/cpu.rs:8-90).

Accounts time the host's modeled CPU has spent executing; when the
accumulated backlog exceeds a threshold, events are pushed back until
the CPU catches up (Host.execute push-back, ref host.rs:760-777) — so
per-host compute cost shapes the event timeline.

Deterministic by construction, unlike the reference: the reference
feeds this from native wall-clock execution timers (perf_timers
feature, off by default, and sim_config.rs:246 hardcodes the threshold
to None), while we feed it from the *modeled* syscall-latency
accounting (Host.syscall_latency_ns), so two runs see identical
delays.  Off by default, enabled by `experimental.host_cpu_threshold`.

All arithmetic is integer nanoseconds; `add_delay` takes native-CPU
nanoseconds and scales by the native:simulated frequency ratio with the
reference's midpoint rounding to `precision`.
"""

from __future__ import annotations


class Cpu:
    __slots__ = ("simulated_freq", "native_freq", "threshold",
                 "precision", "_now", "_time_cpu_available")

    def __init__(self, simulated_freq: int = 1, native_freq: int = 1,
                 threshold: int | None = None,
                 precision: int | None = None):
        """threshold None => never delays; precision None => no
        rounding (both matching cpu.rs semantics)."""
        assert precision is None or precision > 0
        self.simulated_freq = simulated_freq
        self.native_freq = native_freq
        self.threshold = threshold
        self.precision = precision
        self._now = 0
        self._time_cpu_available = 0

    def update_time(self, now: int) -> None:
        self._now = now

    def add_delay(self, native_ns: int) -> None:
        cycles = native_ns * self.native_freq
        adjusted = cycles // self.simulated_freq
        if self.precision is not None:
            remainder = adjusted % self.precision
            adjusted -= remainder
            if remainder >= self.precision // 2:
                adjusted += self.precision  # round up at midpoint
        # Anchor at now: an idle CPU earns no catch-up credit (work
        # starts when the event runs).  The reference accumulates from
        # simulation start, which lets arbitrarily long idle spans
        # absorb arbitrarily large backlogs — meaningless for our
        # deterministic event-cost feed.
        if self._time_cpu_available < self._now:
            self._time_cpu_available = self._now
        self._time_cpu_available += adjusted

    def delay(self) -> int:
        """Simulated ns until this CPU can run the next event (0 when
        idle, below threshold, or the model is disabled)."""
        if self.threshold is None:
            return 0
        built_up = self._time_cpu_available - self._now
        if built_up > self.threshold:
            return built_up
        return 0
