"""Per-process futex table for managed (real-binary) threads.

Ref: src/main/host/futex_table.rs + src/main/host/futex.c — the host
keeps a table keyed by futex word address; blocked threads park on a
condition attached to the word, FUTEX_WAKE pops waiters in FIFO order
(deterministic: arrival order is event-queue order).  Keys are managed-
process virtual addresses, which is exactly the kernel's key for
process-private futexes; we only support private-equivalent use (all
waiters and wakers inside one managed process), the dominant case for
pthreads/glibc.
"""

from __future__ import annotations

from shadow_tpu.host.condition import ManualCondition


class FutexWaiter:
    __slots__ = ("condition", "bitset", "woken", "addr")

    def __init__(self, addr: int, condition: ManualCondition, bitset: int):
        self.addr = addr
        self.condition = condition
        self.bitset = bitset
        self.woken = False


class FutexTable:
    """addr -> FIFO list of waiters."""

    def __init__(self):
        self._waiters: dict[int, list[FutexWaiter]] = {}

    def add_waiter(self, addr: int, condition: ManualCondition,
                   bitset: int = 0xFFFFFFFF) -> FutexWaiter:
        w = FutexWaiter(addr, condition, bitset)
        self._waiters.setdefault(addr, []).append(w)
        # Timeout/teardown must not leave a dead entry in the FIFO.
        condition.on_disarm = lambda: self.discard(w)
        return w

    def discard(self, waiter: FutexWaiter) -> None:
        lst = self._waiters.get(waiter.addr)
        if lst and waiter in lst:
            lst.remove(waiter)
            if not lst:
                del self._waiters[waiter.addr]

    def wake(self, host, addr: int, count: int,
             bitset: int = 0xFFFFFFFF) -> int:
        """Wake up to `count` waiters whose bitset intersects; returns
        how many were woken."""
        lst = self._waiters.get(addr)
        if not lst:
            return 0
        woken = 0
        for w in list(lst):
            if woken >= count:
                break
            if not (w.bitset & bitset):
                continue
            w.woken = True
            # fire() disarms, which runs on_disarm -> discard(w).
            w.condition.fire(host)
            woken += 1
        return woken

    def requeue(self, host, addr: int, wake_count: int, requeue_limit: int,
                addr2: int) -> tuple[int, int]:
        """Wake `wake_count` waiters of `addr`, move up to
        `requeue_limit` of the remainder onto `addr2`.  Returns (woken,
        requeued) — the caller picks the kernel return convention
        (FUTEX_REQUEUE reports woken only; CMP_REQUEUE woken+requeued,
        futex(2))."""
        woken = self.wake(host, addr, wake_count)
        lst = self._waiters.get(addr)
        moved = 0
        while lst and moved < requeue_limit:
            w = lst.pop(0)
            w.addr = addr2
            self._waiters.setdefault(addr2, []).append(w)
            moved += 1
        if lst is not None and not lst:
            self._waiters.pop(addr, None)
        return woken, moved

    def __len__(self) -> int:
        return sum(len(v) for v in self._waiters.values())
