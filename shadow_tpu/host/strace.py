"""Per-process strace-style syscall logs.

Ref: src/main/host/syscall/formatter.rs (the `handle!` wrapper writes one
line per syscall into <process>.strace). `deterministic` mode elides
payload *contents* (lengths only) so two runs — and two schedulers —
byte-diff clean even if app data contains run-varying material; the
reference's deterministic mode elides pointers for the same reason.
"""

from __future__ import annotations

MODE_OFF = "off"
MODE_STANDARD = "standard"
MODE_DETERMINISTIC = "deterministic"


def _fmt_value(v, deterministic: bool):
    if isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        if deterministic or len(b) > 32:
            return f"<{len(b)} bytes>"
        return repr(b)
    if isinstance(v, tuple):
        return "(" + ", ".join(_fmt_value(x, deterministic) for x in v) + ")"
    if callable(v):
        return f"<fn {getattr(v, '__name__', 'anon')}>"
    return repr(v)


def format_native_call(sim_now: int, tid: int, num: int, args, result,
                       mode: str) -> str:
    """Native-ABI variant: raw syscall number + 6 register args.
    Deterministic mode elides the register values (they are pointers
    into a run-varying address space — same policy as the reference's
    pointer elision, formatter.rs)."""
    from shadow_tpu.host.syscalls_native import syscall_name
    deterministic = mode == MODE_DETERMINISTIC
    name = syscall_name(num)
    if deterministic:
        rendered_args = "..."
    else:
        rendered_args = ", ".join(hex(a & (2**64 - 1)) for a in args)
    kind = result[0]
    if kind == "done":
        rendered = str(result[1])
    elif kind == "error":
        e = result[1]
        rendered = f"-1 [errno {e.errno}]"
    elif kind == "block":
        rendered = "<blocked>"
    elif kind == "native":
        rendered = "<native>"
    else:
        rendered = f"<{kind}>"
    sec, ns = divmod(sim_now, 10**9)
    return f"{sec:05d}.{ns:09d} [tid {tid}] {name}({rendered_args}) = {rendered}\n"


def format_call(sim_now: int, tid: int, call: tuple, result,
                mode: str) -> str:
    deterministic = mode == MODE_DETERMINISTIC
    name = call[0]
    args = ", ".join(_fmt_value(a, deterministic) for a in call[1:])
    kind = result[0]
    if kind == "done":
        rendered = _fmt_value(result[1], deterministic)
    elif kind == "error":
        e = result[1]
        rendered = f"-1 ({e.strerror or e.args[-1]}) [errno {e.errno}]"
    elif kind == "block":
        rendered = "<blocked>"
    else:
        rendered = f"<{kind}>"
    sec, ns = divmod(sim_now, 10**9)
    return f"{sec:05d}.{ns:09d} [tid {tid}] {name}({args}) = {rendered}\n"
