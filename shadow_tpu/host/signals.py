"""Emulated POSIX signals for simulated processes.

Manager-side signal state: per-process action table + pending sets,
per-thread masks.  The reference splits this between the simulator
(src/main/host/syscall/handler/signal.rs, process.rs signal ingest) and
the shim (src/lib/shim/src/signals.rs, which runs emulated handlers
in-process); our split is the same — this module decides *what* is
delivered *when*, and the shim invokes the app's handler function when
the manager sends an EV_SIGNAL event down the IPC channel
(native/shim.c).

Design invariants:
 - signals are delivered only at response points (when the manager is
   about to answer a syscall), which is exactly when the managed thread
   is parked in the channel's recv — delivery is therefore a
   deterministic simulation event, never an async interrupt;
 - a signal raised at a thread blocked in an interruptible syscall
   disarms the condition and converts the pending call into -EINTR (or
   a restart when SA_RESTART applies — handler/mod.rs restart protocol);
 - dispositions follow Linux: uncatchable SIGKILL/SIGSTOP, default
   table below, ignored signals discarded at generation time even when
   blocked.
"""

from __future__ import annotations

# Signal numbers (x86-64)
SIGHUP = 1
SIGINT = 2
SIGQUIT = 3
SIGILL = 4
SIGTRAP = 5
SIGABRT = 6
SIGBUS = 7
SIGFPE = 8
SIGKILL = 9
SIGUSR1 = 10
SIGSEGV = 11
SIGUSR2 = 12
SIGPIPE = 13
SIGALRM = 14
SIGTERM = 15
SIGSTKFLT = 16
SIGCHLD = 17
SIGCONT = 18
SIGSTOP = 19
SIGTSTP = 20
SIGTTIN = 21
SIGTTOU = 22
SIGURG = 23
SIGXCPU = 24
SIGXFSZ = 25
SIGVTALRM = 26
SIGPROF = 27
SIGWINCH = 28
SIGIO = 29
SIGPWR = 30
SIGSYS = 31

NSIG = 64

# siginfo si_code values (asm-generic/siginfo.h) delivered to
# SA_SIGINFO handlers through the shim's EV_SIGNAL args.
SI_USER = 0        # kill(2)
SI_KERNEL = 0x80   # kernel-generated (itimer SIGALRM, ...)
SI_TKILL = -6      # tgkill(2)
CLD_EXITED = 1     # child exited normally
CLD_KILLED = 2     # child terminated by signal

_NAMES = {
    "SIGHUP": SIGHUP, "SIGINT": SIGINT, "SIGQUIT": SIGQUIT,
    "SIGILL": SIGILL, "SIGTRAP": SIGTRAP, "SIGABRT": SIGABRT,
    "SIGBUS": SIGBUS, "SIGFPE": SIGFPE, "SIGKILL": SIGKILL,
    "SIGUSR1": SIGUSR1, "SIGSEGV": SIGSEGV, "SIGUSR2": SIGUSR2,
    "SIGPIPE": SIGPIPE, "SIGALRM": SIGALRM, "SIGTERM": SIGTERM,
    "SIGSTKFLT": SIGSTKFLT, "SIGCHLD": SIGCHLD, "SIGCONT": SIGCONT,
    "SIGSTOP": SIGSTOP, "SIGTSTP": SIGTSTP, "SIGTTIN": SIGTTIN,
    "SIGTTOU": SIGTTOU, "SIGURG": SIGURG, "SIGXCPU": SIGXCPU,
    "SIGXFSZ": SIGXFSZ, "SIGVTALRM": SIGVTALRM, "SIGPROF": SIGPROF,
    "SIGWINCH": SIGWINCH, "SIGIO": SIGIO, "SIGPWR": SIGPWR,
    "SIGSYS": SIGSYS,
}
_NUM_TO_NAME = {num: name for name, num in _NAMES.items()}


def parse_signal(spec) -> int:
    """'SIGTERM' | 'TERM' | 15 -> 15 (config shutdown_signal,
    expected_final_state 'signaled ...')."""
    if isinstance(spec, int):
        return spec
    s = str(spec).strip().upper()
    if s.isdigit():
        return int(s)
    if not s.startswith("SIG"):
        s = "SIG" + s
    if s in _NAMES:
        return _NAMES[s]
    raise ValueError(f"unknown signal {spec!r}")


def signal_name(sig: int) -> str:
    return _NUM_TO_NAME.get(sig, f"SIG{sig}")


def bit(sig: int) -> int:
    return 1 << (sig - 1)


# Default dispositions (man 7 signal).  Stop/continue job control IS
# modeled at the process level (stopped processes consume no events
# until SIGCONT; wait4 reports via WUNTRACED/WCONTINUED); there is no
# controlling terminal, so SIGTTIN/SIGTTOU only arrive via explicit
# kill.  SIGCONT's continue side-effect fires at raise time regardless
# of disposition (kernel semantics), so its default action here is
# "ignore".
_DEFAULT_IGNORE = frozenset({SIGCHLD, SIGURG, SIGWINCH, SIGCONT})
_STOP_SIGNALS = frozenset({SIGSTOP, SIGTSTP, SIGTTIN, SIGTTOU})

# SIGCHLD si_code values for job control (uapi/asm-generic/siginfo.h;
# CLD_EXITED/CLD_KILLED live with the other si_code constants above).
CLD_STOPPED, CLD_CONTINUED = 5, 6
SA_NOCLDSTOP = 0x00000001

# Hardware-fault signals: the app's sigaction is additionally installed
# natively so a *real* fault in managed code (e.g. a GC's intentional
# SIGSEGV) reaches the app's handler without a round trip.  Emulated
# kill() delivery for these still goes through the normal path.
FAULT_SIGNALS = frozenset({SIGSEGV, SIGBUS, SIGFPE, SIGILL, SIGTRAP})

# sigaction flags (uapi/asm/signal.h)
SA_SIGINFO = 0x00000004
SA_RESTORER = 0x04000000
SA_ONSTACK = 0x08000000
SA_RESTART = 0x10000000
SA_NODEFER = 0x40000000
SA_RESETHAND = 0x80000000

SIG_DFL = 0
SIG_IGN = 1

# Syscalls re-run after a handler when SA_RESTART is set (Linux restarts
# these for slow devices; everything else returns EINTR).  Names are
# from the syscalls_native SYS table.
RESTARTABLE = frozenset({
    "read", "write", "readv", "writev", "recvfrom", "sendto", "recvmsg",
    "sendmsg", "accept", "accept4", "connect", "wait4", "waitid",
    "futex", "flock",
})


class SigAction:
    __slots__ = ("handler", "flags", "restorer", "mask")

    def __init__(self, handler: int = SIG_DFL, flags: int = 0,
                 restorer: int = 0, mask: int = 0):
        self.handler = handler
        self.flags = flags
        self.restorer = restorer
        self.mask = mask


class ProcessSignals:
    """Per-process emulated signal state (actions are process-wide,
    masks are per-thread and live on the thread objects)."""

    __slots__ = ("actions", "pending_process", "info")

    def __init__(self):
        self.actions: dict[int, SigAction] = {}
        self.pending_process: set[int] = set()
        # Per-pending-signal siginfo: sig -> (si_code, si_pid, si_status).
        # Standard (non-RT) signals carry one instance, like the kernel.
        self.info: dict[int, tuple] = {}

    def action(self, sig: int) -> SigAction:
        act = self.actions.get(sig)
        return act if act is not None else SigAction()

    def clone(self) -> "ProcessSignals":
        """fork: child inherits the action table, not the pending set."""
        child = ProcessSignals()
        child.actions = {
            s: SigAction(a.handler, a.flags, a.restorer, a.mask)
            for s, a in self.actions.items()}
        return child

    def disposition(self, sig: int) -> str:
        """'handler' | 'ignore' | 'terminate' | 'stop'."""
        if sig == SIGKILL:
            return "terminate"
        if sig == SIGSTOP:
            return "stop"  # uncatchable, unblockable
        act = self.actions.get(sig)
        if act is None or act.handler == SIG_DFL:
            if sig in _STOP_SIGNALS:
                return "stop"
            return "ignore" if sig in _DEFAULT_IGNORE else "terminate"
        if act.handler == SIG_IGN:
            return "ignore"
        return "handler"

    # -- pending bookkeeping -----------------------------------------

    def take_deliverable(self, thread) -> int | None:
        """Lowest-numbered pending signal not blocked by `thread`'s
        mask, removed from its pending set (Linux delivers standard
        signals lowest-first — a stable deterministic order)."""
        mask = getattr(thread, "sig_mask", 0)
        candidates = [s for s in getattr(thread, "sig_pending", ())
                      if not (mask & bit(s))]
        candidates += [s for s in self.pending_process
                       if not (mask & bit(s))]
        if not candidates:
            return None
        sig = min(candidates)
        thread.sig_pending.discard(sig)
        self.pending_process.discard(sig)
        return sig

    def take_info(self, sig: int) -> tuple:
        """Pop the queued siginfo for `sig`: (si_code, si_pid, si_status)."""
        return self.info.pop(sig, (0, 0, 0))

    def has_deliverable(self, thread) -> bool:
        mask = getattr(thread, "sig_mask", 0)
        return any(not (mask & bit(s))
                   for s in getattr(thread, "sig_pending", ())) or \
            any(not (mask & bit(s)) for s in self.pending_process)

    def pending_mask(self, thread) -> int:
        m = 0
        for s in getattr(thread, "sig_pending", ()):
            m |= bit(s)
        for s in self.pending_process:
            m |= bit(s)
        return m
