"""Processes and threads for *internal* (Python-coroutine) applications.

Structural mirror of the reference's Process/Thread/ManagedThread resume
chain (src/main/host/process.rs:1188, thread.rs:471-508,
managed_thread.rs:190-333), re-targeted at in-process Python apps: an app
is a generator that `yield`s syscall tuples and receives results; the
Thread drives it exactly like ManagedThread drives a native process over
IPC — dispatch the syscall, continue on Done, park on Block, re-run the
*same* syscall after the condition fires (restart protocol,
handler/mod.rs:127-136).

The interposition backend for real Linux binaries (preload shim + seccomp
over shmem IPC) plugs in at the same SyscallHandler seam in a later
round; nothing above this layer changes.
"""

from __future__ import annotations

import errno

from shadow_tpu.core.event import TaskRef

ST_RUNNABLE = 0
ST_BLOCKED = 1
ST_EXITED = 2


class ProcessExit(Exception):
    def __init__(self, code: int = 0):
        super().__init__(f"exit({code})")
        self.code = code


class Thread:
    def __init__(self, process, gen, tid: int):
        self.process = process
        self.gen = gen
        self.tid = tid
        self.state = ST_RUNNABLE
        self._started = False
        self._pending_call = None   # syscall to re-run after unblock
        self._pending_send = None   # result to feed into the generator
        self._pending_throw = None  # OSError to raise into the generator
        self.last_condition = None
        self.sig_mask = 0
        self.sig_pending: set[int] = set()
        # Syscall transcript (shadow_tpu/ckpt/replay.py): every value
        # fed INTO the generator, recorded so a checkpoint can rebuild
        # the (unpicklable) suspended frame by replay.  None = not
        # recording (no `checkpoint:` block configured).
        self.log = ([] if getattr(process.host, "ckpt_record", False)
                    else None)

    def resume(self, host) -> None:
        """Drive the app generator until it blocks or exits
        (managed_thread.rs:190-333 event loop)."""
        if self.state == ST_EXITED:
            return
        if self.process.stopped:
            # Job control: the process is stopped — park this resume
            # until SIGCONT flushes it.
            self.process._stopped_resumes.append(self.resume)
            return
        self.state = ST_RUNNABLE
        process = self.process
        log = self.log
        while True:
            if self._pending_call is not None:
                call, restarted = self._pending_call, True
                self._pending_call = None
            else:
                try:
                    if self._pending_throw is not None:
                        exc, self._pending_throw = self._pending_throw, None
                        if log is not None:
                            log.append((2, exc))  # ckpt/replay LOG_THROW
                        call = self.gen.throw(exc)
                    elif not self._started:
                        self._started = True
                        if log is not None:
                            log.append((0,))      # ckpt/replay LOG_START
                        call = next(self.gen)
                    else:
                        if log is not None:
                            log.append((1, self._pending_send))  # LOG_SEND
                        call, self._pending_send = (
                            self.gen.send(self._pending_send), None)
                except StopIteration as si:
                    self._exit(host, si.value if isinstance(si.value, int) else 0)
                    return
                except ProcessExit as pe:
                    self._exit(host, pe.code)
                    return
                except Exception as e:
                    # The app let an error escape (syscall OSError or its
                    # own bug): that crashes the *process*, never the
                    # simulation — like a native segfault under the
                    # reference (plugin error, run continues).
                    import traceback
                    self._crash(host, "".join(traceback.format_exception(e)))
                    return
                restarted = False
            if not isinstance(call, tuple) or not call:
                self._crash(host, f"app yielded non-syscall {call!r}")
                return
            result = host.syscall_handler.dispatch(host, process, self, call,
                                                   restarted)
            host.count_syscall(call[0])
            if process.strace_mode is not None:
                from shadow_tpu.host import strace
                process.strace_write(strace.format_call(
                    host.now(), self.tid, call, result,
                    process.strace_mode).encode())
            kind = result[0]
            if kind == "done":
                self._pending_send = result[1]
            elif kind == "exit":
                self._exit(host, result[1])
                return
            elif kind == "error":
                self._pending_throw = result[1]
            elif kind == "block":
                condition = result[1]
                self._pending_call = call
                self.last_condition = condition
                self.state = ST_BLOCKED
                condition.arm(host, self._wakeup)
                return
            else:  # pragma: no cover
                raise AssertionError(f"bad dispatch result {result!r}")

    def _wakeup(self, host) -> None:
        if self.state == ST_BLOCKED:
            self.resume(host)

    def _crash(self, host, why: str) -> None:
        self.process.stderr += f"[shadow-tpu] thread crash: {why}\n".encode()
        self._exit(host, 101)

    def _exit(self, host, code: int) -> None:
        if self.state == ST_EXITED:
            return
        self.state = ST_EXITED
        if self.last_condition is not None:
            self.last_condition.disarm()
        self.gen.close()
        self.process.thread_exited(host, self, code)

    def __getstate__(self):
        # Generator frames cannot be pickled: the checkpoint carries
        # the syscall transcript instead and ckpt/replay.py rebuilds
        # the frame on restore.
        d = dict(self.__dict__)
        d["gen"] = None
        return d


class Process:
    def __init__(self, host, name: str, argv: list[str],
                 env: dict[str, str], expected_final_state="exited 0"):
        self.host = host
        self.name = name
        self.argv = argv
        self.env = env
        self.pid = host.register_process(self)
        self.threads: list[Thread] = []
        self._next_tid = self.pid
        self.exited = False
        self.exit_code: int | None = None
        self.term_signal: int | None = None  # fatal emulated signal
        from shadow_tpu.host.signals import ProcessSignals
        self.signals = ProcessSignals()
        # fork/wait bookkeeping (ref: process.rs zombies & reaping)
        self.parent_pid: int | None = None
        self.zombies: list[int] = []      # exited, unreaped child pids
        self._wait_conds: list = []       # parked wait4 conditions
        # Job control: top-level processes lead their own group/session;
        # fork children inherit the parent's (managed.py _do_fork).
        self.pgid = self.pid
        self.sid = self.pid
        # Stop/continue state (ref: process.rs stop/continue handling):
        # a stopped process consumes no events — thread resumes defer
        # into _stopped_resumes until SIGCONT flushes them.  stop_report
        # / continue_report feed wait4's WUNTRACED/WCONTINUED exactly
        # once per transition.
        self.stopped = False
        self._stopped_resumes: list = []
        self.stop_report: int | None = None
        self.continue_report = False
        # Signals (other than KILL/CONT) raised while stopped: Linux
        # keeps them pending until the continue — the stop shields even
        # fatal defaults (signal.c: only SIGKILL/SIGCONT wake a stopped
        # task).
        self._stopped_sigs: list = []
        self.signal_fds: list = []  # signalfd(2) watchers
        self._nonzero_exit: int | None = None  # first failing thread wins
        self.stdout = bytearray()
        self.stderr = bytearray()
        self.strace_mode: str | None = None  # set by the manager when on
        # Strace lines stream to a file in the host data dir (bounded
        # memory, survives crashes — the reference writes per-process
        # .strace files the same way); the in-memory buffer is the
        # fallback when the host has no data dir.
        self._strace_buf = bytearray()
        self._strace_file = None
        self.expected_final_state = expected_final_state
        self.fds = host_descriptor_table()
        # Internal-app registry path (set by the spawn task): the
        # checkpoint replay rebuilds the main thread's generator via
        # app_registry.lookup(app_path)(process, argv).
        self.app_path: str | None = None

    def __getstate__(self):
        # The streamed strace file handle is process-local wall state;
        # strace configs are refused by the checkpoint domain check, so
        # dropping the handle here only covers direct constructions.
        d = dict(self.__dict__)
        d["_strace_file"] = None
        return d

    def strace_write(self, data: bytes) -> None:
        if self._strace_file is None:
            data_path = getattr(self.host, "data_path", None)
            if data_path:
                import os
                os.makedirs(data_path, exist_ok=True)
                self._strace_file = open(
                    os.path.join(data_path,
                                 f"{self.name}.{self.pid}.strace"), "wb")
            else:
                self._strace_buf += data
                return
        self._strace_file.write(data)

    def strace_close(self) -> None:
        if self._strace_file is not None:
            self._strace_file.close()
            self._strace_file = None

    def spawn_thread(self, host, gen) -> Thread:
        t = Thread(self, gen, self._next_tid)
        self._next_tid += 1
        self.threads.append(t)
        return t

    def start(self, host, gen) -> None:
        """Create the main thread and run it now (process.rs:944 spawn)."""
        t = self.spawn_thread(host, gen)
        t.resume(host)

    def thread_exited(self, host, thread, code: int) -> None:
        if code != 0 and self._nonzero_exit is None:
            self._nonzero_exit = code
        if all(t.state == ST_EXITED for t in self.threads):
            # A crashed helper thread must not be masked by a clean main
            # thread: any nonzero thread exit becomes the process code.
            self.exited = True
            self.exit_code = (self._nonzero_exit
                              if self._nonzero_exit is not None else code)
            self.fds.close_all(host)
            low = getattr(self, "fds_low", None)
            if low is not None:
                low.close_all(host)
            self.strace_close()
            if self.parent_pid is not None:
                parent = host.processes.get(self.parent_pid)
                if parent is not None and not parent.exited:
                    parent.child_exited(host, self)

    def refresh_signal_fds(self, host) -> None:
        """Re-evaluate level-triggered signalfd readiness after any
        pending-set mutation (single invariant point)."""
        for sfd in self.signal_fds:
            sfd.refresh(host)

    def child_exited(self, host, child) -> None:
        """A child became a zombie: wake parked wait4()s, raise SIGCHLD
        (default-ignored unless the app installed a handler)."""
        self.zombies.append(child.pid)
        waiters, self._wait_conds = self._wait_conds, []
        for cond in waiters:
            cond.fire(host)
        from shadow_tpu.host.signals import (CLD_EXITED, CLD_KILLED,
                                             SIGCHLD)
        if child.term_signal is not None:
            code, status = CLD_KILLED, child.term_signal
        else:
            code, status = CLD_EXITED, child.exit_code or 0
        self.raise_signal(host, SIGCHLD, si_code=code, si_pid=child.pid,
                          si_status=status)

    # -- job control (ref: process.rs stop/continue handling) ---------

    def stop_process(self, host, sig: int) -> None:
        """SIGSTOP/SIGTSTP default action: freeze — subsequent thread
        resumes defer until SIGCONT; the parent is notified (SIGCHLD
        CLD_STOPPED unless SA_NOCLDSTOP) and parked wait4s re-check."""
        if self.exited or self.stopped:
            return
        self.stopped = True
        self.stop_report = sig
        self.continue_report = False
        # kernel prepare_signal(): generating a stop signal discards
        # pending SIGCONT.
        from shadow_tpu.host.signals import CLD_STOPPED, SIGCONT
        self.signals.pending_process.discard(SIGCONT)
        for t in self.threads:
            getattr(t, "sig_pending", set()).discard(SIGCONT)
        self._notify_parent_jobctl(host, CLD_STOPPED, sig)

    def continue_process(self, host) -> None:
        """SIGCONT side-effect (fires at raise time regardless of the
        signal's disposition, like the kernel): flush every deferred
        resume back onto the event queue."""
        if self.exited or not self.stopped:
            return
        self.stopped = False
        self.stop_report = None
        self.continue_report = True
        # kernel prepare_signal(): SIGCONT discards pending stop sigs.
        from shadow_tpu.host.signals import (_STOP_SIGNALS, CLD_CONTINUED,
                                             SIGCONT)
        self.signals.pending_process.difference_update(_STOP_SIGNALS)
        for t in self.threads:
            getattr(t, "sig_pending", set()).difference_update(
                _STOP_SIGNALS)
        resumes, self._stopped_resumes = self._stopped_resumes, []
        from shadow_tpu.core.event import TaskRef
        for r in resumes:
            host.schedule_task_at(
                host.now(), TaskRef("sigcont-resume",
                                    lambda h, _r=r: _r(h)))
        self._notify_parent_jobctl(host, CLD_CONTINUED, SIGCONT)
        # Signals the stop shielded are re-raised now, in raise order,
        # through the full raise path (thread targeting, blocked
        # queueing, condition interrupts all re-run).
        shielded, self._stopped_sigs = self._stopped_sigs, []
        for sig, tid, code, pid, status in shielded:
            self.raise_signal(host, sig, target_tid=tid, si_code=code,
                              si_pid=pid, si_status=status)

    def _notify_parent_jobctl(self, host, code: int, sig: int) -> None:
        from shadow_tpu.host.signals import (SA_NOCLDSTOP, SIGCHLD)
        parent = host.processes.get(self.parent_pid) \
            if self.parent_pid is not None else None
        if parent is None or parent.exited:
            return
        waiters, parent._wait_conds = parent._wait_conds, []
        for cond in waiters:
            cond.fire(host)
        act = parent.signals.action(SIGCHLD)
        if not (act.flags & SA_NOCLDSTOP):
            parent.raise_signal(host, SIGCHLD, si_code=code,
                                si_pid=self.pid, si_status=sig)

    def raise_signal(self, host, sig: int, target_tid=None,
                     si_code: int = 0, si_pid: int = 0,
                     si_status: int = 0) -> None:
        """Internal (Python) apps have no handler mechanism: non-ignored
        signals apply the default action — terminate, stop, or continue
        (man 7 signal).  ManagedProcess overrides this with full
        handler delivery."""
        from shadow_tpu.host.signals import NSIG, SIGCONT, SIGKILL
        if self.exited or sig <= 0 or sig >= NSIG:
            return
        if sig == SIGCONT:
            self.continue_process(host)
            return  # default SIGCONT action beyond the continue: ignore
        disp = self.signals.disposition(sig)
        if self.stopped and sig != SIGKILL:
            # The stop shields everything but KILL/CONT until the
            # continue (signal.c: stopped tasks don't wake for them).
            if disp not in ("ignore", "stop"):
                self._stopped_sigs.append((sig, target_tid, si_code,
                                           si_pid, si_status))
            return
        if disp == "ignore":
            return
        if disp == "stop":
            self.stop_process(host, sig)
            return
        self.term_signal = sig
        for t in list(self.threads):
            t._exit(host, 128 + sig)

    def matches_expected_final_state(self) -> bool:
        return matches_final_state(self.expected_final_state,
                                   self.exited, self.exit_code,
                                   self.term_signal)


def matches_final_state(expected, exited: bool, exit_code,
                        term_signal) -> bool:
    """The ONE expected_final_state matcher, shared by Process and
    EngineAppProcess so serial and engine backends can never disagree
    on run success.  Unknown shapes are rejected at config parse
    (core/config._validate_final_state); the permissive True fallback
    here only covers non-config constructions."""
    if expected in ("running", "any"):
        return expected == "any" or not exited
    if isinstance(expected, str) and expected.startswith("exited"):
        parts = expected.split()
        want = int(parts[1]) if len(parts) > 1 else 0
        return exited and exit_code == want and term_signal is None
    if isinstance(expected, str) and expected.startswith("signaled"):
        from shadow_tpu.host.signals import parse_signal
        parts = expected.split()
        if term_signal is None:
            return False
        return len(parts) < 2 or term_signal == parse_signal(parts[1])
    return True


def host_descriptor_table():
    from shadow_tpu.host.descriptor import DescriptorTable
    return DescriptorTable()
