"""AST extractors over the Python twin modules.

No twin module is imported (passes 1-2 must run without JAX, and
importing ops/ pulls heavy deps); everything is read from the AST:

- module-level (and class-level) integer/tuple constants;
- the column schema a span codec *consumes* — every
  `np.frombuffer(d[key], dtype)` reached from `_to_arrays`, including
  reads routed through local helpers (`f`, `pk`) and loops over
  constant tuples;
- the column key set a codec *produces* — every `out[key] = ...`
  reached from `_from_arrays`, including the `ring()` helper.

The mini-interpreter only evaluates what the codecs actually use:
string/int/tuple/dict literals, f-strings, name lookups, tuple
concatenation, and `DICT[var]` subscripts.  Anything else evaluates to
None and the read is reported as unresolvable — the contract test
fails closed instead of silently under-checking.
"""

from __future__ import annotations

import ast

_NP_NAMES = {"np", "numpy", "jnp"}
_DTYPE_NAMES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
                "uint32", "uint64", "float32", "float64", "bool_"}


class _Unresolved(Exception):
    pass


def _const_eval(node, env):
    """Evaluate the literal-ish subset the twin modules use."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise _Unresolved(node.id)
    if isinstance(node, ast.Tuple):
        return tuple(_const_eval(e, env) for e in node.elts)
    if isinstance(node, ast.List):
        return [_const_eval(e, env) for e in node.elts]
    if isinstance(node, ast.Dict):
        return {_const_eval(k, env): _const_eval(v, env)
                for k, v in zip(node.keys, node.values)}
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                parts.append(str(_const_eval(v.value, env)))
            else:
                raise _Unresolved(ast.dump(v))
        return "".join(parts)
    if isinstance(node, ast.Attribute):
        # np.int64 and friends evaluate to the dtype name string
        if isinstance(node.value, ast.Name) and \
                node.value.id in _NP_NAMES and node.attr in _DTYPE_NAMES:
            return node.attr
        raise _Unresolved(ast.dump(node))
    if isinstance(node, ast.Subscript):
        container = _const_eval(node.value, env)
        key = _const_eval(node.slice, env)
        try:
            return container[key]
        except (KeyError, IndexError, TypeError) as exc:
            raise _Unresolved(f"subscript: {exc}") from exc
    if isinstance(node, ast.BinOp):
        left = _const_eval(node.left, env)
        right = _const_eval(node.right, env)
        try:
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.RShift):
                return left >> right
            if isinstance(node.op, ast.BitOr):
                return left | right
            if isinstance(node.op, ast.BitAnd):
                return left & right
            if isinstance(node.op, ast.BitXor):
                return left ^ right
        except (TypeError, ValueError, ZeroDivisionError) as exc:
            raise _Unresolved(f"binop: {exc}") from exc
        raise _Unresolved(ast.dump(node))
    if isinstance(node, ast.UnaryOp):
        v = _const_eval(node.operand, env)
        try:
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.Invert):
                return ~v
        except TypeError as exc:
            raise _Unresolved(f"unaryop: {exc}") from exc
        raise _Unresolved(ast.dump(node))
    raise _Unresolved(ast.dump(node))


def module_env(tree: ast.Module) -> dict:
    """Module-level constants (ints, strings, tuples, dicts)."""
    env: dict = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            try:
                env[stmt.targets[0].id] = _const_eval(stmt.value, env)
            except _Unresolved:
                pass
    return env


def extract_constants(path: str) -> dict:
    """Module-level and class-level integer/tuple constants.

    Class attributes are keyed "ClassName.attr".
    """
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    env = module_env(tree)
    out = {k: v for k, v in env.items()
           if isinstance(v, (int, tuple)) and not isinstance(v, bool)}
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, ast.Assign) and \
                        len(sub.targets) == 1 and \
                        isinstance(sub.targets[0], ast.Name):
                    try:
                        v = _const_eval(sub.value, env)
                    except _Unresolved:
                        continue
                    if isinstance(v, int) and not isinstance(v, bool):
                        out[f"{stmt.name}.{sub.targets[0].id}"] = v
    return out


def _find_method(tree: ast.Module, method: str):
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, ast.FunctionDef) and sub.name == method:
                    return sub
    raise KeyError(f"method {method} not found")


class _CodecScanner:
    """Symbolically executes a codec method far enough to see every
    d[key] read (np.frombuffer) and every out[key] write."""

    MAX_DEPTH = 8

    def __init__(self, module_env_: dict):
        self.env0 = module_env_
        self.consumed: dict = {}     # key -> dtype name (or None)
        self.produced: set = set()
        self.state_written: set = set()  # st[...] keys (_to_arrays)
        self.unresolved: list = []   # (lineno, what)

    # -- helpers -----------------------------------------------------
    def _ev(self, node, env):
        try:
            return _const_eval(node, {**self.env0, **env})
        except _Unresolved:
            return None

    def _record_read(self, key_node, dtype_node, env, lineno):
        key = self._ev(key_node, env)
        if not isinstance(key, str):
            self.unresolved.append((lineno, "column key"))
            return
        dt = self._ev(dtype_node, env) if dtype_node is not None else None
        self.consumed[key] = dt if isinstance(dt, str) else None

    # -- execution ---------------------------------------------------
    def run(self, method_node, depth=0, env=None, funcs=None):
        self.exec_stmts(method_node.body, env or {}, funcs or {}, depth)

    def exec_stmts(self, stmts, env, funcs, depth):
        if depth > self.MAX_DEPTH:
            return
        for stmt in stmts:
            if isinstance(stmt, ast.FunctionDef):
                funcs = {**funcs, stmt.name: stmt}
            elif isinstance(stmt, ast.For):
                self._exec_for(stmt, env, funcs, depth)
            elif isinstance(stmt, (ast.If, ast.While)):
                self.scan_calls(stmt.test, env, funcs, depth)
                self.exec_stmts(stmt.body, env, funcs, depth)
                self.exec_stmts(stmt.orelse, env, funcs, depth)
            else:
                self.scan_calls(stmt, env, funcs, depth)
                self._scan_out_writes(stmt, env)
        return funcs

    def _lenient_tuple(self, node, env):
        """Evaluate a tuple display elementwise; runtime-only elements
        (shape caps etc.) become None instead of poisoning the whole
        iterable — the string keys are what the contract needs."""
        if not isinstance(node, (ast.Tuple, ast.List)):
            return self._ev(node, env)
        items = []
        for el in node.elts:
            if isinstance(el, (ast.Tuple, ast.List)):
                items.append(tuple(self._ev(sub, env) for sub in el.elts))
            else:
                items.append(self._ev(el, env))
        return tuple(items)

    def _exec_for(self, stmt, env, funcs, depth):
        items = self._lenient_tuple(stmt.iter, env)
        if not isinstance(items, (tuple, list)):
            # not a constant iterable: still scan the body once with
            # the loop variable unbound so nested reads surface as
            # unresolved rather than vanishing
            self.exec_stmts(stmt.body, env, funcs, depth)
            return
        for item in items:
            bound = dict(env)
            if isinstance(stmt.target, ast.Name):
                bound[stmt.target.id] = item
            elif isinstance(stmt.target, ast.Tuple):
                for tgt, val in zip(stmt.target.elts, item):
                    if isinstance(tgt, ast.Name):
                        bound[tgt.id] = val
            self.exec_stmts(stmt.body, bound, funcs, depth)

    def _scan_out_writes(self, stmt, env):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id in ("out", "st"):
                        which = tgt.value.id
                        key = self._ev(tgt.slice, env)
                        if isinstance(key, str):
                            (self.produced if which == "out"
                             else self.state_written).add(key)
                        else:
                            self.unresolved.append(
                                (node.lineno, f"{which}[] key"))

    def scan_calls(self, stmt, env, funcs, depth):
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            # np.frombuffer(d[key], dtype=...) / (d[key], np.int64)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "frombuffer" and node.args and \
                    isinstance(node.args[0], ast.Subscript) and \
                    isinstance(node.args[0].value, ast.Name) and \
                    node.args[0].value.id == "d":
                dtype_node = node.args[1] if len(node.args) > 1 else None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype_node = kw.value
                self._record_read(node.args[0].slice, dtype_node, env,
                                  node.lineno)
            # calls into local helper functions: symbolic descent
            elif isinstance(node.func, ast.Name) and node.func.id in funcs:
                fn = funcs[node.func.id]
                bound = dict(env)
                params = [a.arg for a in fn.args.args]
                defaults = fn.args.defaults
                for name, dflt in zip(params[len(params) - len(defaults):],
                                      defaults):
                    bound[name] = self._ev(dflt, env)
                for name, arg in zip(params, node.args):
                    bound[name] = self._ev(arg, env)
                for kw in node.keywords:
                    if kw.arg:
                        bound[kw.arg] = self._ev(kw.value, env)
                self.exec_stmts(fn.body, bound, funcs, depth + 1)


def extract_consumed_schema(path: str, method: str = "_to_arrays"):
    """(consumed {key: dtype-or-None}, unresolved [(line, what)])."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    scanner = _CodecScanner(module_env(tree))
    scanner.run(_find_method(tree, method))
    return scanner.consumed, scanner.unresolved


def extract_produced_keys(path: str, method: str = "_from_arrays"):
    """(produced key set, unresolved [(line, what)])."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    scanner = _CodecScanner(module_env(tree))
    scanner.run(_find_method(tree, method))
    return scanner.produced, scanner.unresolved


def extract_state_keys(path: str, method: str = "_to_arrays"):
    """(state keys the codec writes via st[...], unresolved): the SoA
    column set the residency protocol must classify."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    scanner = _CodecScanner(module_env(tree))
    scanner.run(_find_method(tree, method))
    return scanner.state_written, scanner.unresolved


def extract_residency_sets(path: str) -> dict:
    """Module-level RESIDENT_* frozensets (the dirty-column export
    protocol's classification tables), evaluated with only the
    module's own literal constants in scope."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    env = module_env(tree)
    out: dict = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id.startswith("RESIDENT_"):
            try:
                expr = compile(ast.Expression(stmt.value), path, "eval")
                # constants merged into globals: comprehensions open a
                # new scope that cannot see eval() locals
                val = eval(expr, {"__builtins__": {},
                                  "frozenset": frozenset, **env})
            except Exception:
                continue
            if isinstance(val, frozenset):
                out[stmt.targets[0].id] = val
    return out
