"""Violation record + report formatting shared by all lint passes."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Violation:
    rule: str          # e.g. "twin-constant", "soa-layout", "wall-clock"
    file: str          # repo-relative path the violation anchors to
    message: str
    line: int = 0      # 1-based; 0 when the finding is not line-anchored

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        return f"{loc}: [{self.rule}] {self.message}"


@dataclass
class PassResult:
    name: str
    violations: list = field(default_factory=list)


def format_report(violations, counts=None) -> str:
    lines = [v.render() for v in violations]
    n = len(violations)
    if counts:
        per = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
        lines.append(f"shadow-lint: {n} violation(s) ({per})")
    else:
        lines.append(f"shadow-lint: {n} violation(s)")
    return "\n".join(lines)
