"""Pass 1: twin-constant extraction & cross-check.

Every constant that exists both in native/netplane.cpp and in a Python
module is a silent-divergence hazard: the engine and the device kernels
would disagree byte-for-byte and the mismatch only surfaces minutes
into a differential gate.  This pass extracts the C++ side (regex, no
compiler) and the Python side (AST, no import) and diffs them.

The contract table below is the registry.  To add a new device-span
family's constants: add (cpp_name, [(py_module, py_name), ..]) rows —
the checker fails on missing names on either side, so a half-registered
twin cannot pass silently.
"""

from __future__ import annotations

import os

from shadow_tpu.analysis import cpp_extract, py_extract
from shadow_tpu.analysis.report import Violation

CPP = "native/netplane.cpp"
SHIM = "native/shim.c"

_CONN = "shadow_tpu/tcp/connection.py"
_TCPS = "shadow_tpu/ops/tcp_span.py"
_PHLD = "shadow_tpu/ops/phold_span.py"
_CODEL = "shadow_tpu/net/codel.py"
_BUCKET = "shadow_tpu/net/token_bucket.py"
_STATUS = "shadow_tpu/host/status.py"
_PACKET = "shadow_tpu/net/packet.py"
_STCP = "shadow_tpu/host/socket_tcp.py"
_SUDP = "shadow_tpu/host/socket_udp.py"
_RNG = "shadow_tpu/core/rng.py"
_PLANE = "shadow_tpu/native/plane.py"
_TREV = "shadow_tpu/trace/events.py"
_CKPT = "shadow_tpu/ckpt/format.py"

# cpp_name -> [(python module, python name)]
CONTRACTS = [
    # TCP engine constants (connection.py is the object twin, tcp_span
    # the SoA kernel twin)
    ("MSS", [(_CONN, "MSS"), (_TCPS, "MSS")]),
    ("MAX_WINDOW", [(_CONN, "MAX_WINDOW"), (_TCPS, "MAX_WINDOW")]),
    ("WMEM_MAX", [(_CONN, "WMEM_MAX"), (_TCPS, "WMEM_MAX")]),
    ("RMEM_MAX", [(_CONN, "RMEM_MAX"), (_TCPS, "RMEM_MAX")]),
    ("RMEM_CEILING", [(_CONN, "RMEM_CEILING")]),
    ("MAX_SACK_BLOCKS", [(_CONN, "MAX_SACK_BLOCKS")]),
    ("INIT_RTO_NS", [(_CONN, "INIT_RTO_NS")]),
    ("MIN_RTO_NS", [(_CONN, "MIN_RTO_NS"), (_TCPS, "MIN_RTO_NS")]),
    ("MAX_RTO_NS", [(_CONN, "MAX_RTO_NS"), (_TCPS, "MAX_RTO_NS")]),
    ("TIME_WAIT_NS", [(_CONN, "TIME_WAIT_NS")]),
    ("DUPACK_THRESHOLD", [(_CONN, "DUPACK_THRESHOLD")]),
    ("DELACK_NS", [(_CONN, "DELACK_NS"), (_TCPS, "DELACK_NS")]),
    # TCP states (netplane enum ST_* mirrors connection.py's module
    # constants by order)
    ("ST_CLOSED", [(_CONN, "CLOSED")]),
    ("ST_LISTEN", [(_CONN, "LISTEN")]),
    ("ST_SYN_SENT", [(_CONN, "SYN_SENT")]),
    ("ST_SYN_RECEIVED", [(_CONN, "SYN_RECEIVED")]),
    ("ST_ESTABLISHED", [(_CONN, "ESTABLISHED")]),
    ("ST_FIN_WAIT_1", [(_CONN, "FIN_WAIT_1")]),
    ("ST_FIN_WAIT_2", [(_CONN, "FIN_WAIT_2")]),
    ("ST_CLOSING", [(_CONN, "CLOSING")]),
    ("ST_TIME_WAIT", [(_CONN, "TIME_WAIT")]),
    ("ST_CLOSE_WAIT", [(_CONN, "CLOSE_WAIT")]),
    ("ST_LAST_ACK", [(_CONN, "LAST_ACK")]),
    # TCP header flags
    ("F_FIN", [(_PACKET, "TcpFlags.FIN"), (_TCPS, "F_FIN")]),
    ("F_SYN", [(_PACKET, "TcpFlags.SYN"), (_TCPS, "F_SYN")]),
    ("F_RST", [(_PACKET, "TcpFlags.RST"), (_TCPS, "F_RST")]),
    ("F_PSH", [(_PACKET, "TcpFlags.PSH"), (_TCPS, "F_PSH")]),
    ("F_ACK", [(_PACKET, "TcpFlags.ACK"), (_TCPS, "F_ACK")]),
    ("F_ECE", [(_PACKET, "TcpFlags.ECE"), (_TCPS, "F_ECE")]),
    ("F_CWR", [(_PACKET, "TcpFlags.CWR"), (_TCPS, "F_CWR")]),
    # ECN / DCTCP family (docs/PARITY.md "DCTCP / ECN"): the IP-header
    # codepoints, the fixed-point alpha parameters, the DCTCP-K
    # marking thresholds, the congestion-controller ids and the
    # MARK_* attribution causes — all fail-closed (an unregistered
    # member with any of these prefixes is itself a violation), so K
    # drift / alpha-shift drift / flag-bit swap between the three
    # implementations fails `scripts/lint`, not a differential gate.
    ("ECN_ECT0", [(_PACKET, "ECN_ECT0"), (_TCPS, "ECN_ECT0")]),
    ("ECN_CE", [(_PACKET, "ECN_CE"), (_TCPS, "ECN_CE")]),
    ("DCTCP_SHIFT", [(_CONN, "DCTCP_SHIFT"), (_TCPS, "DCTCP_SHIFT")]),
    ("DCTCP_G_SHIFT", [(_CONN, "DCTCP_G_SHIFT"),
                       (_TCPS, "DCTCP_G_SHIFT")]),
    ("DCTCP_MAX_ALPHA", [(_CONN, "DCTCP_MAX_ALPHA"),
                         (_TCPS, "DCTCP_MAX_ALPHA")]),
    ("DCTCP_K_PKTS", [(_CODEL, "DCTCP_K_PKTS"),
                      (_TCPS, "DCTCP_K_PKTS")]),
    ("DCTCP_K_BYTES", [(_CODEL, "DCTCP_K_BYTES"),
                       (_TCPS, "DCTCP_K_BYTES")]),
    ("CC_RENO", [(_CONN, "CC_RENO")]),
    ("CC_DCTCP", [(_CONN, "CC_DCTCP"), (_TCPS, "CC_DCTCP")]),
    ("MARK_THRESH_PKTS", [(_TREV, "MARK_THRESH_PKTS"),
                          (_TCPS, "MARK_THRESH_PKTS")]),
    ("MARK_THRESH_BYTES", [(_TREV, "MARK_THRESH_BYTES"),
                           (_TCPS, "MARK_THRESH_BYTES")]),
    ("MARK_N", [(_TREV, "MARK_N"), (_TCPS, "MARK_N")]),
    # wire-size constants
    ("PROTO_TCP", [(_PACKET, "PROTO_TCP")]),
    ("PROTO_UDP", [(_PACKET, "PROTO_UDP")]),
    ("MTU", [(_PACKET, "MTU"), (_TCPS, "MTU"), (_PHLD, "MTU")]),
    ("IPV4_HDR", [(_PACKET, "IPV4_HEADER_SIZE")]),
    ("UDP_HDR", [(_PACKET, "UDP_HEADER_SIZE")]),
    ("TCP_HDR", [(_PACKET, "TCP_HEADER_SIZE")]),
    # CoDel / token bucket (router twins)
    ("CODEL_TARGET_NS", [(_CODEL, "TARGET_NS"),
                         (_TCPS, "CODEL_TARGET_NS"),
                         (_PHLD, "CODEL_TARGET_NS")]),
    ("CODEL_INTERVAL_NS", [(_CODEL, "INTERVAL_NS")]),
    ("CODEL_HARD_LIMIT", [(_CODEL, "HARD_LIMIT"),
                          (_TCPS, "CODEL_HARD_LIMIT"),
                          (_PHLD, "CODEL_HARD_LIMIT")]),
    ("REFILL_INTERVAL_NS", [(_BUCKET, "REFILL_INTERVAL_NS"),
                            (_TCPS, "REFILL_NS"), (_PHLD, "REFILL_NS")]),
    # ephemeral port range
    ("EPHEMERAL_LO", [(_STCP, "EPHEMERAL_LO"), (_SUDP, "EPHEMERAL_LO")]),
    ("EPHEMERAL_HI", [(_STCP, "EPHEMERAL_HI"), (_SUDP, "EPHEMERAL_HI")]),
    # status bits
    ("S_ACTIVE", [(_STATUS, "S_ACTIVE")]),
    ("S_READABLE", [(_STATUS, "S_READABLE"), (_TCPS, "S_READABLE"),
                    (_PHLD, "S_READABLE")]),
    ("S_WRITABLE", [(_STATUS, "S_WRITABLE"), (_TCPS, "S_WRITABLE"),
                    (_PHLD, "S_WRITABLE")]),
    ("S_CLOSED", [(_STATUS, "S_CLOSED")]),
    # timer-heap entry kinds
    ("TK_RELAY", [(_TCPS, "TK_RELAY"), (_PHLD, "TK_RELAY")]),
    ("TK_TCP", [(_TCPS, "TK_TCP")]),
    ("TK_APP", [(_TCPS, "TK_APP"), (_PHLD, "TK_APP")]),
    ("TK_APP_TIMEOUT", [(_PHLD, "TK_APP_TIMEOUT")]),
    # engine-app syscall slots
    ("ASYS_SEND", [(_TCPS, "ASYS_SEND")]),
    ("ASYS_RECV", [(_TCPS, "ASYS_RECV")]),
    ("ASYS_SENDTO", [(_PHLD, "ASYS_SENDTO")]),
    ("ASYS_RECVFROM", [(_PHLD, "ASYS_RECVFROM")]),
    ("ASYS_NANOSLEEP", [(_PHLD, "ASYS_NANOSLEEP")]),
    ("ASYS_N", [(_TCPS, "ASYS_N"), (_PHLD, "ASYS_N")]),
    # trace record kinds
    ("TRACE_SND", [(_TCPS, "TR_SND"), (_PHLD, "TR_SND")]),
    ("TRACE_DRP", [(_TCPS, "TR_DRP"), (_PHLD, "TR_DRP")]),
    ("TRACE_RCV", [(_TCPS, "TR_RCV"), (_PHLD, "TR_RCV")]),
    # threefry parity word + engine park sentinel
    ("TF_PARITY", [(_RNG, "_PARITY")]),
    ("R_BLOCK", [(_PLANE, "R_BLOCK")]),
    # flight-recorder record layout + event kinds (trace/events.py;
    # the engine's FlightRec ring must stay byte-compatible with the
    # Python REC struct)
    ("FLIGHT_REC_BYTES", [(_TREV, "FLIGHT_REC_BYTES")]),
    ("FR_ROUND", [(_TREV, "FR_ROUND")]),
    ("FR_SPAN_START", [(_TREV, "FR_SPAN_START")]),
    ("FR_SPAN_COMMIT", [(_TREV, "FR_SPAN_COMMIT")]),
    ("FR_SPAN_ABORT", [(_TREV, "FR_SPAN_ABORT")]),
    # Fault-injection records (docs/CHECKPOINT.md): stamped by the
    # manager's round-loop choke point; the enum lives in the engine
    # because the FR_* namespace is fail-closed there.
    ("FR_FAULT_KILL", [(_TREV, "FR_FAULT_KILL")]),
    ("FR_FAULT_RESTORE", [(_TREV, "FR_FAULT_RESTORE")]),
    ("FR_FAULT_LINK_DOWN", [(_TREV, "FR_FAULT_LINK_DOWN")]),
    ("FR_FAULT_LINK_UP", [(_TREV, "FR_FAULT_LINK_UP")]),
    ("FR_FAULT_BLACKHOLE", [(_TREV, "FR_FAULT_BLACKHOLE")]),
    ("FR_FAULT_CLEAR", [(_TREV, "FR_FAULT_CLEAR")]),
    ("FR_FAULT_QUARANTINE", [(_TREV, "FR_FAULT_QUARANTINE")]),
    ("FR_N", [(_TREV, "FR_N")]),
    # device-eligibility reason codes (one per conservative round)
    ("EL_DEVICE_SPAN", [(_TREV, "EL_DEVICE_SPAN")]),
    ("EL_ENGINE_SPAN", [(_TREV, "EL_ENGINE_SPAN")]),
    ("EL_ENGINE_ROUTED", [(_TREV, "EL_ENGINE_ROUTED")]),
    ("EL_ENGINE_COLD", [(_TREV, "EL_ENGINE_COLD")]),
    ("EL_ENGINE_ABORT", [(_TREV, "EL_ENGINE_ABORT")]),
    ("EL_ENGINE_TRANSIENT", [(_TREV, "EL_ENGINE_TRANSIENT")]),
    ("EL_ENGINE_FAMILY", [(_TREV, "EL_ENGINE_FAMILY")]),
    ("EL_ENGINE_OFF", [(_TREV, "EL_ENGINE_OFF")]),
    ("EL_ENGINE_PYLIMIT", [(_TREV, "EL_ENGINE_PYLIMIT")]),
    ("EL_ROUND_BOUNDARY", [(_TREV, "EL_ROUND_BOUNDARY")]),
    ("EL_ROUND_OUTBOX", [(_TREV, "EL_ROUND_OUTBOX")]),
    ("EL_ROUND_GATE", [(_TREV, "EL_ROUND_GATE")]),
    ("EL_ROUND_CALLBACK", [(_TREV, "EL_ROUND_CALLBACK")]),
    ("EL_ROUND_FORCED", [(_TREV, "EL_ROUND_FORCED")]),
    ("EL_ROUND_SCHED", [(_TREV, "EL_ROUND_SCHED")]),
    ("EL_OBJ_PCAP", [(_TREV, "EL_OBJ_PCAP")]),
    ("EL_OBJ_CPU", [(_TREV, "EL_OBJ_CPU")]),
    ("EL_OBJ_PYTASK", [(_TREV, "EL_OBJ_PYTASK")]),
    ("EL_OBJ_OTHER", [(_TREV, "EL_OBJ_OTHER")]),
    ("EL_DEVICE_SHARDED", [(_TREV, "EL_DEVICE_SHARDED")]),
    ("EL_ENGINE_EXCHANGE", [(_TREV, "EL_ENGINE_EXCHANGE")]),
    ("EL_ENGINE_UNSHARDED", [(_TREV, "EL_ENGINE_UNSHARDED")]),
    ("EL_SVC_QUIESCENT", [(_TREV, "EL_SVC_QUIESCENT")]),
    ("EL_N", [(_TREV, "EL_N")]),
    # Sim-netstat drop-cause codes + the per-connection telemetry
    # record layout (both device-span kernels carry the causes they
    # can attribute, so enum drift would corrupt the conservation
    # counters byte-for-byte).
    ("TEL_CODEL", [(_TREV, "TEL_CODEL"), (_TCPS, "TEL_CODEL"),
                   (_PHLD, "TEL_CODEL")]),
    ("TEL_RTR_LIMIT", [(_TREV, "TEL_RTR_LIMIT"),
                       (_TCPS, "TEL_RTR_LIMIT"),
                       (_PHLD, "TEL_RTR_LIMIT")]),
    ("TEL_LOSS_EDGE", [(_TREV, "TEL_LOSS_EDGE"),
                       (_TCPS, "TEL_LOSS_EDGE"),
                       (_PHLD, "TEL_LOSS_EDGE")]),
    ("TEL_UNREACHABLE", [(_TREV, "TEL_UNREACHABLE"),
                         (_TCPS, "TEL_UNREACHABLE"),
                         (_PHLD, "TEL_UNREACHABLE")]),
    ("TEL_NO_ROUTE", [(_TREV, "TEL_NO_ROUTE"),
                      (_PHLD, "TEL_NO_ROUTE")]),
    ("TEL_NO_SOCKET", [(_TREV, "TEL_NO_SOCKET"),
                       (_PHLD, "TEL_NO_SOCKET")]),
    ("TEL_TCP_STATE", [(_TREV, "TEL_TCP_STATE")]),
    ("TEL_BACKLOG_FULL", [(_TREV, "TEL_BACKLOG_FULL")]),
    ("TEL_UDP_FILTER", [(_TREV, "TEL_UDP_FILTER")]),
    ("TEL_RECVBUF_FULL", [(_TREV, "TEL_RECVBUF_FULL"),
                          (_PHLD, "TEL_RECVBUF_FULL")]),
    ("TEL_BUCKET_DEFER", [(_TREV, "TEL_BUCKET_DEFER")]),
    # Down-host fault masks (docs/ROBUSTNESS.md): both device-span
    # kernels attribute fault drops to these causes, so slot drift
    # would silently mis-attribute device-span fault rounds.
    ("TEL_HOST_DOWN", [(_TREV, "TEL_HOST_DOWN"),
                       (_TCPS, "TEL_HOST_DOWN"),
                       (_PHLD, "TEL_HOST_DOWN")]),
    ("TEL_LINK_DOWN", [(_TREV, "TEL_LINK_DOWN"),
                       (_TCPS, "TEL_LINK_DOWN"),
                       (_PHLD, "TEL_LINK_DOWN")]),
    ("TEL_REASM_FULL", [(_TREV, "TEL_REASM_FULL"),
                        (_TCPS, "TEL_REASM_FULL")]),
    ("TEL_RECVWIN_TRUNC", [(_TREV, "TEL_RECVWIN_TRUNC"),
                           (_TCPS, "TEL_RECVWIN_TRUNC")]),
    ("TEL_WIRE_N", [(_TREV, "TEL_WIRE_N")]),
    ("TEL_N", [(_TREV, "TEL_N"), (_TCPS, "TEL_N"),
               (_PHLD, "TEL_N")]),
    ("TEL_REC_BYTES", [(_TREV, "TEL_REC_BYTES")]),
    # Fabric observatory: the FB_ACT_* activity mask (both device-span
    # kernels compute it per round, so bit drift would silently change
    # which hosts sample), the FCT_F_* flow flags, and both record
    # sizes (the engine's FabRec ring and FctRec flow log must stay
    # byte-compatible with the Python structs).
    ("FB_ACT_CODEL", [(_TREV, "FB_ACT_CODEL"), (_TCPS, "FB_ACT_CODEL"),
                      (_PHLD, "FB_ACT_CODEL")]),
    ("FB_ACT_TB_OUT", [(_TREV, "FB_ACT_TB_OUT"),
                       (_TCPS, "FB_ACT_TB_OUT"),
                       (_PHLD, "FB_ACT_TB_OUT")]),
    ("FB_ACT_TB_IN", [(_TREV, "FB_ACT_TB_IN"),
                      (_TCPS, "FB_ACT_TB_IN"),
                      (_PHLD, "FB_ACT_TB_IN")]),
    ("FB_ACT_LINK", [(_TREV, "FB_ACT_LINK"), (_TCPS, "FB_ACT_LINK"),
                     (_PHLD, "FB_ACT_LINK")]),
    ("FB_REC_BYTES", [(_TREV, "FB_REC_BYTES")]),
    ("FCT_F_COMPLETE", [(_TREV, "FCT_F_COMPLETE")]),
    ("FCT_F_RECEIVER", [(_TREV, "FCT_F_RECEIVER")]),
    ("FCT_REC_BYTES", [(_TREV, "FCT_REC_BYTES")]),
    # Device-kernel observatory stage slots (docs/OBSERVABILITY.md
    # "Device-kernel observatory"): the stages execute in the JAX span
    # kernels, but netplane.cpp is the fail-closed registry — a stage
    # slot drifting between trace/events.py and either kernel would
    # silently mis-attribute every occupancy table, so the KS_ prefix
    # is fail-closed like FR_*/EL_*/TEL_*.  Per-kernel rows list only
    # the stages that family occupies (the phold family has no TCP
    # pipeline stages).
    ("KS_POP", [(_TREV, "KS_POP"), (_TCPS, "KS_POP"),
                (_PHLD, "KS_POP")]),
    ("KS_STEP", [(_TREV, "KS_STEP"), (_TCPS, "KS_STEP"),
                 (_PHLD, "KS_STEP")]),
    ("KS_CODEL", [(_TREV, "KS_CODEL"), (_TCPS, "KS_CODEL"),
                  (_PHLD, "KS_CODEL")]),
    ("KS_ON_PACKET", [(_TREV, "KS_ON_PACKET"),
                      (_TCPS, "KS_ON_PACKET")]),
    ("KS_REASM", [(_TREV, "KS_REASM"), (_TCPS, "KS_REASM")]),
    ("KS_ACK", [(_TREV, "KS_ACK"), (_TCPS, "KS_ACK")]),
    ("KS_PUSH", [(_TREV, "KS_PUSH"), (_TCPS, "KS_PUSH")]),
    ("KS_FLUSH", [(_TREV, "KS_FLUSH"), (_TCPS, "KS_FLUSH")]),
    ("KS_INET_OUT", [(_TREV, "KS_INET_OUT"), (_TCPS, "KS_INET_OUT"),
                     (_PHLD, "KS_INET_OUT")]),
    ("KS_ARM", [(_TREV, "KS_ARM"), (_TCPS, "KS_ARM"),
                (_PHLD, "KS_ARM")]),
    ("KS_TIMERS", [(_TREV, "KS_TIMERS"), (_TCPS, "KS_TIMERS"),
                   (_PHLD, "KS_TIMERS")]),
    ("KS_EXCHANGE", [(_TREV, "KS_EXCHANGE"), (_TCPS, "KS_EXCHANGE"),
                     (_PHLD, "KS_EXCHANGE")]),
    ("KS_N", [(_TREV, "KS_N"), (_TCPS, "KS_N"), (_PHLD, "KS_N")]),
    ("KS_REC_BYTES", [(_TREV, "KS_REC_BYTES")]),
    # Checkpoint plane-blob framing (shadow_tpu/ckpt/format.py is the
    # Python twin — it parses the engine's plane blob for `ckpt info`
    # / `ckpt diff`, so a silently drifted header would misparse every
    # snapshot).  The CK_ prefix is fail-closed like FR_*/EL_*/TEL_*.
    ("CK_PLANE_MAGIC", [(_CKPT, "CK_PLANE_MAGIC")]),
    ("CK_PLANE_VERSION", [(_CKPT, "CK_PLANE_VERSION")]),
    ("CK_PLANE_HDR_BYTES", [(_CKPT, "CK_PLANE_HDR_BYTES")]),
    ("CK_FRAME_HDR_BYTES", [(_CKPT, "CK_FRAME_HDR_BYTES")]),
    ("CK_GLOBAL_FRAME", [(_CKPT, "CK_GLOBAL_FRAME")]),
]

# Trace enum prefixes that may never gain an UNREGISTERED member: any
# FR_*/EL_*/TEL_* constant found in the C++ engine must have a
# CONTRACTS row (and with it a Python twin), so extending the
# flight-record layout or the drop-cause table without updating
# trace/events.py fails closed.
TRACE_ENUM_PREFIXES = ("FR_", "EL_", "TEL_", "FB_", "FCT_", "CK_",
                       "MARK_", "DCTCP_", "ECN_", "CC_", "KS_")

# Shim-side contracts (native/shim.c — the syscall observatory's SC_*
# disposition enum, its record-size pin, and the IPC-layout offset of
# the shim's SC_SHIM sequence counter).  Same fail-closed discipline
# as the netplane contracts: SHIM_TRACE_PREFIXES members without a
# row are violations.
_SABI = "shadow_tpu/host/shim_abi.py"
SHIM_CONTRACTS = [
    ("SC_SERVICED", [(_TREV, "SC_SERVICED")]),
    ("SC_PARKED", [(_TREV, "SC_PARKED")]),
    ("SC_NATIVE", [(_TREV, "SC_NATIVE")]),
    ("SC_SHIM", [(_TREV, "SC_SHIM")]),
    ("SC_PROTO", [(_TREV, "SC_PROTO")]),
    ("SC_N", [(_TREV, "SC_N")]),
    ("SC_REC_BYTES", [(_TREV, "SC_REC_BYTES")]),
    # The per-channel counter offset: shim.c pins the literal to the
    # real struct with a _Static_assert; this row pins the manager's
    # mmap offset to the same literal — so the three-way agreement
    # (struct, shim constant, Python offset) is airtight.
    ("SC_CHAN_LOCAL_OFF", [(_SABI, "CHAN_SC_LOCAL")]),
    # Syscall service plane (IPC v8): the manager-written svc_flags
    # header word, pinned the same three-way way.
    ("SC_SVC_FLAGS_OFF", [(_SABI, "OFF_SVC")]),
]
SHIM_TRACE_PREFIXES = ("SC_",)

# C++ int arrays <-> Python tuples (threefry rotation schedules)
ARRAY_CONTRACTS = [
    ("rot_a", _RNG, "_ROT_A"),
    ("rot_b", _RNG, "_ROT_B"),
]

# Python RSN_* codes <-> index into the C++ REASONS string table
REASON_CONTRACTS = [
    (_TCPS, "RSN_CODEL", "codel"),
    (_TCPS, "RSN_RTRLIMIT", "rtr-limit"),
    (_TCPS, "RSN_LOSS", "inet-loss"),
    (_TCPS, "RSN_UNREACH", "unreachable"),
    (_TCPS, "RSN_HOSTDOWN", "host-down"),
    (_TCPS, "RSN_LINKDOWN", "link-down"),
    (_PHLD, "RSN_NONE", ""),
    (_PHLD, "RSN_RCVBUF", "rcvbuf-full"),
    (_PHLD, "RSN_NOSOCK", "no-socket"),
    (_PHLD, "RSN_NOROUTE", "no-route"),
    (_PHLD, "RSN_LOSS", "inet-loss"),
    (_PHLD, "RSN_UNREACH", "unreachable"),
    (_PHLD, "RSN_HOSTDOWN", "host-down"),
    (_PHLD, "RSN_LINKDOWN", "link-down"),
]

# Python constants derived from several C++ constants
DERIVED_CONTRACTS = [
    (_TCPS, "TCP_TOTAL_HDR",
     lambda C, P: C["IPV4_HDR"] + C["TCP_HDR"], "IPV4_HDR + TCP_HDR"),
    (_PHLD, "PKT_SIZE",
     lambda C, P: P["PAYLOAD_LEN"] + C["UDP_HDR"] + C["IPV4_HDR"],
     "PAYLOAD_LEN + UDP_HDR + IPV4_HDR"),
]


def _diff_contracts(consts: dict, contracts: list, src: str,
                    py_consts, violations: list) -> None:
    """Diff one extracted C constant table against its contract rows
    (shared by the netplane and shim sides)."""
    for cpp_name, twins in contracts:
        if cpp_name not in consts:
            violations.append(Violation(
                "twin-constant", src,
                f"C++ constant {cpp_name} not found by the extractor "
                f"(renamed or removed? update analysis/twin_constants.py)"))
            continue
        for mod, py_name in twins:
            pv = py_consts(mod).get(py_name)
            if pv is None:
                violations.append(Violation(
                    "twin-constant", mod,
                    f"missing twin {py_name} for C++ {cpp_name}"))
            elif pv != consts[cpp_name]:
                violations.append(Violation(
                    "twin-constant", mod,
                    f"{py_name} = {pv} but C++ {cpp_name} = "
                    f"{consts[cpp_name]}"))


def check(repo_root: str, cpp_text: str | None = None,
          shim_text: str | None = None) -> list:
    """Diff the C++ constants against every registered Python twin."""
    if cpp_text is None:
        with open(os.path.join(repo_root, CPP)) as fh:
            cpp_text = fh.read()
    consts = cpp_extract.extract_constants(cpp_text)
    arrays = cpp_extract.extract_int_arrays(cpp_text)
    strings = cpp_extract.extract_string_arrays(cpp_text)

    violations: list[Violation] = []
    py_cache: dict = {}

    def py_consts(mod):
        if mod not in py_cache:
            py_cache[mod] = py_extract.extract_constants(
                os.path.join(repo_root, mod))
        return py_cache[mod]

    _diff_contracts(consts, CONTRACTS, CPP, py_consts, violations)

    # Shim-side constants (native/shim.c): the same extractor family
    # works — shim.c declares its twin-relevant constants as anonymous
    # enums, exactly like the engine.
    if shim_text is None:
        with open(os.path.join(repo_root, SHIM)) as fh:
            shim_text = fh.read()
    shim_consts = cpp_extract.extract_constants(shim_text)
    _diff_contracts(shim_consts, SHIM_CONTRACTS, SHIM, py_consts,
                    violations)
    shim_registered = {name for name, _twins in SHIM_CONTRACTS}
    for name in sorted(shim_consts):
        if name.startswith(SHIM_TRACE_PREFIXES) \
                and name not in shim_registered:
            violations.append(Violation(
                "twin-constant", SHIM,
                f"trace enum {name} has no contract row (register it "
                f"in analysis/twin_constants.py with a "
                f"trace/events.py twin)"))

    for cpp_name, mod, py_name in ARRAY_CONTRACTS:
        cv = arrays.get(cpp_name)
        pv = py_consts(mod).get(py_name)
        if cv is None:
            violations.append(Violation(
                "twin-constant", CPP, f"C++ array {cpp_name} not found"))
        elif pv is None:
            violations.append(Violation(
                "twin-constant", mod,
                f"missing twin {py_name} for C++ array {cpp_name}"))
        elif tuple(pv) != cv:
            violations.append(Violation(
                "twin-constant", mod,
                f"{py_name} = {pv} but C++ {cpp_name} = {cv}"))

    # REASONS tables: every definition must agree, and each Python
    # RSN_* code must index its reason string
    reasons = strings.get("REASONS", [])
    if not reasons:
        violations.append(Violation(
            "twin-constant", CPP, "C++ REASONS table not found"))
    else:
        if any(r != reasons[0] for r in reasons[1:]):
            violations.append(Violation(
                "twin-constant", CPP,
                "the span_import REASONS tables disagree with each other"))
        table = reasons[0]
        for mod, py_name, reason in REASON_CONTRACTS:
            pv = py_consts(mod).get(py_name)
            if pv is None:
                violations.append(Violation(
                    "twin-constant", mod,
                    f"missing reason code {py_name}"))
                continue
            if reason not in table:
                violations.append(Violation(
                    "twin-constant", CPP,
                    f"reason string {reason!r} (for {py_name}) not in "
                    f"REASONS"))
            elif table.index(reason) != pv:
                violations.append(Violation(
                    "twin-constant", mod,
                    f"{py_name} = {pv} but C++ REASONS[{py_name}] is at "
                    f"index {table.index(reason)}"))

    # Trace enums are fail-closed: an FR_*/EL_* member added to the
    # C++ engine without a registered Python twin is itself a
    # violation (a half-registered flight-record layout must not pass).
    registered = {name for name, _twins in CONTRACTS}
    for name in sorted(consts):
        if name.startswith(TRACE_ENUM_PREFIXES) \
                and name not in registered:
            violations.append(Violation(
                "twin-constant", CPP,
                f"trace enum {name} has no contract row (register it "
                f"in analysis/twin_constants.py with a "
                f"trace/events.py twin)"))

    # EL_NAMES: the reason-string table must mirror the EL_* enum
    # order on BOTH sides (the eligibility report and the Chrome
    # export render through it).
    el_names = strings.get("EL_NAMES", [])
    py_el = py_consts(_TREV).get("EL_NAMES")
    if not el_names:
        violations.append(Violation(
            "twin-constant", CPP, "C++ EL_NAMES table not found"))
    elif py_el is None:
        violations.append(Violation(
            "twin-constant", _TREV,
            "missing EL_NAMES twin for the C++ reason table"))
    elif tuple(py_el) != el_names[0]:
        violations.append(Violation(
            "twin-constant", _TREV,
            f"EL_NAMES = {tuple(py_el)} but C++ EL_NAMES = "
            f"{el_names[0]}"))
    else:
        n = consts.get("EL_N")
        if n is not None and len(el_names[0]) != n:
            violations.append(Violation(
                "twin-constant", CPP,
                f"EL_NAMES has {len(el_names[0])} entries but "
                f"EL_N = {n}"))

    # TEL_NAMES: the drop-cause string table must mirror the TEL_*
    # enum order on BOTH sides (the attribution report and the
    # conservation gate render through it).
    tel_names = strings.get("TEL_NAMES", [])
    py_tel = py_consts(_TREV).get("TEL_NAMES")
    if not tel_names:
        violations.append(Violation(
            "twin-constant", CPP, "C++ TEL_NAMES table not found"))
    elif py_tel is None:
        violations.append(Violation(
            "twin-constant", _TREV,
            "missing TEL_NAMES twin for the C++ cause table"))
    elif tuple(py_tel) != tel_names[0]:
        violations.append(Violation(
            "twin-constant", _TREV,
            f"TEL_NAMES = {tuple(py_tel)} but C++ TEL_NAMES = "
            f"{tel_names[0]}"))
    else:
        n = consts.get("TEL_N")
        if n is not None and len(tel_names[0]) != n:
            violations.append(Violation(
                "twin-constant", CPP,
                f"TEL_NAMES has {len(tel_names[0])} entries but "
                f"TEL_N = {n}"))

    # MARK_NAMES: the mark-cause string table must mirror the MARK_*
    # enum order on BOTH sides (the fabric ledger and `trace fabric`
    # render through it).
    mark_names = strings.get("MARK_NAMES", [])
    py_mark = py_consts(_TREV).get("MARK_NAMES")
    if not mark_names:
        violations.append(Violation(
            "twin-constant", CPP, "C++ MARK_NAMES table not found"))
    elif py_mark is None:
        violations.append(Violation(
            "twin-constant", _TREV,
            "missing MARK_NAMES twin for the C++ cause table"))
    elif tuple(py_mark) != mark_names[0]:
        violations.append(Violation(
            "twin-constant", _TREV,
            f"MARK_NAMES = {tuple(py_mark)} but C++ MARK_NAMES = "
            f"{mark_names[0]}"))
    else:
        n = consts.get("MARK_N")
        if n is not None and len(mark_names[0]) != n:
            violations.append(Violation(
                "twin-constant", CPP,
                f"MARK_NAMES has {len(mark_names[0])} entries but "
                f"MARK_N = {n}"))

    # KS_NAMES: the kernel-stage string table must mirror the KS_*
    # enum order on BOTH sides (`trace kern`, the Chrome export and
    # bench's crossover attribution render through it).
    ks_names = strings.get("KS_NAMES", [])
    py_ks = py_consts(_TREV).get("KS_NAMES")
    if not ks_names:
        violations.append(Violation(
            "twin-constant", CPP, "C++ KS_NAMES table not found"))
    elif py_ks is None:
        violations.append(Violation(
            "twin-constant", _TREV,
            "missing KS_NAMES twin for the C++ stage table"))
    elif tuple(py_ks) != ks_names[0]:
        violations.append(Violation(
            "twin-constant", _TREV,
            f"KS_NAMES = {tuple(py_ks)} but C++ KS_NAMES = "
            f"{ks_names[0]}"))
    else:
        n = consts.get("KS_N")
        if n is not None and len(ks_names[0]) != n:
            violations.append(Violation(
                "twin-constant", CPP,
                f"KS_NAMES has {len(ks_names[0])} entries but "
                f"KS_N = {n}"))

    # ASYS_NAMES order must mirror the ASYS_* enum
    asys_names = strings.get("ASYS_NAMES", [])
    if asys_names:
        table = asys_names[0]
        for name, val in consts.items():
            if name.startswith("ASYS_") and name != "ASYS_N":
                want = name[len("ASYS_"):].lower()
                if val >= len(table) or table[val] != want:
                    violations.append(Violation(
                        "twin-constant", CPP,
                        f"ASYS_NAMES[{val}] != {want!r} for enum {name}"))

    for mod, py_name, fn, desc in DERIVED_CONTRACTS:
        pv = py_consts(mod).get(py_name)
        try:
            want = fn(consts, py_consts(mod))
        except KeyError as exc:
            violations.append(Violation(
                "twin-constant", CPP,
                f"derived contract {py_name}: missing input {exc}"))
            continue
        if pv is None:
            violations.append(Violation(
                "twin-constant", mod, f"missing derived twin {py_name}"))
        elif pv != want:
            violations.append(Violation(
                "twin-constant", mod,
                f"{py_name} = {pv} but {desc} = {want}"))

    return violations
