"""Lightweight extractors over native/netplane.cpp.

Not a C++ parser — a disciplined family of regex/brace scanners that
understand exactly the idioms the engine uses for twin-relevant
definitions:

- `constexpr <type> NAME = <int expr>;` (possibly several declarators
  per statement, values referencing earlier constants);
- anonymous `enum { A = 0, B, C };` blocks with implicit increments;
- `static const int name[N] = {..};` / `static const char *NAME[] =
  {"..", ..};` literal arrays;
- the span_export_* / span_import_* column traffic: `put("key",
  bytes_vec(var))`, helper expansions (`put_pk`, `put_tpk` /
  `get_tpk`), the r1/r2 relay loop, and `col<T>(d, "key", ..)` reads.

Everything returns plain dicts so the mutation self-test can perturb
the *text* and assert the downstream pass bites.  If the engine ever
adopts an idiom these scanners don't recognize, the contract tests
fail closed (missing name / missing column), not open.
"""

from __future__ import annotations

import functools
import re

# C++ element type -> numpy dtype name used by the Python codecs.
CTYPE_TO_DTYPE = {
    "int64_t": "int64",
    "uint64_t": "uint64",
    "int32_t": "int32",
    "uint32_t": "uint32",
    "int16_t": "int16",
    "uint16_t": "uint16",
    "int8_t": "int8",
    "uint8_t": "uint8",
}

_INT_SUFFIX = re.compile(r"(?<=[0-9a-fA-F])(?:[uU][lL]{0,2}|[lL]{1,2}[uU]?)\b")


def strip_comments(text: str) -> str:
    """Remove /* */ and // comments, preserving line structure."""
    out = []
    i, n = 0, len(text)
    while i < n:
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("\n" * text.count("\n", i, j))
            i = j
        elif text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j
        elif text[i] in "\"'":
            q = text[i]
            j = i + 1
            while j < n and text[j] != q:
                j += 2 if text[j] == "\\" else 1
            out.append(text[i:j + 1])
            i = j + 1
        else:
            out.append(text[i])
            i += 1
    return "".join(out)


def _eval_int(expr: str, env: dict) -> int | None:
    """Evaluate a C++ integer constant expression against known names."""
    e = _INT_SUFFIX.sub("", expr)
    # strip C casts like (int64_t)X and (uint32_t)X
    e = re.sub(r"\(\s*(?:u?int(?:8|16|32|64)_t|size_t|int|long|unsigned)"
               r"\s*\)", "", e)
    if not re.fullmatch(r"[\w\s()+\-*/%<>|&^~x0-9]+", e):
        return None
    try:
        return int(eval(e, {"__builtins__": {}}, dict(env)))  # noqa: S307
    except Exception:
        return None


def extract_constants(text: str) -> dict:
    """All integer `constexpr` definitions and anonymous-enum members."""
    text = strip_comments(text)
    env: dict = {}

    # constexpr <type> A = expr, B = expr, ...;
    for m in re.finditer(
            r"\bconstexpr\s+(?:u?int(?:8|16|32|64)_t|size_t|int|long long|"
            r"long|unsigned)\s+([^;]+);", text):
        for decl in _split_top(m.group(1), ","):
            dm = re.match(r"\s*(\w+)\s*=\s*(.+)$", decl, re.S)
            if not dm:
                continue
            val = _eval_int(dm.group(2), env)
            if val is not None:
                env[dm.group(1)] = val

    # anonymous enums: enum { A = 0, B, C, ... };
    for m in re.finditer(r"\benum\s*\{([^}]*)\}\s*;", text):
        nxt = 0
        for decl in _split_top(m.group(1), ","):
            decl = decl.strip()
            if not decl:
                continue
            dm = re.match(r"(\w+)\s*(?:=\s*(.+))?$", decl, re.S)
            if not dm:
                continue
            if dm.group(2) is not None:
                val = _eval_int(dm.group(2), env)
                if val is None:
                    continue
            else:
                val = nxt
            env[dm.group(1)] = val
            nxt = val + 1
    return env


def extract_int_arrays(text: str) -> dict:
    """`static const int name[N] = {..};` -> {name: (ints..)}."""
    text = strip_comments(text)
    out = {}
    for m in re.finditer(
            r"\bstatic\s+(?:constexpr\s+)?const\s+int\s+(\w+)\s*\[\s*\d*\s*\]"
            r"\s*=\s*\{([^}]*)\}", text):
        vals = []
        for tok in m.group(2).split(","):
            tok = tok.strip()
            if tok:
                vals.append(int(_INT_SUFFIX.sub("", tok), 0))
        out[m.group(1)] = tuple(vals)
    return out


def extract_string_arrays(text: str) -> dict:
    """`static const char *NAME[..] = {"a", "b"};` -> {name: [(strs..)]}.

    A name may be defined more than once (the two span_import REASONS
    tables); every occurrence is kept so callers can assert agreement.
    """
    text = strip_comments(text)
    out: dict = {}
    for m in re.finditer(
            r"\bstatic\s+const\s+char\s*\*\s*(\w+)\s*\[[^\]]*\]\s*=\s*\{",
            text):
        body = _balanced(text, m.end() - 1, "{", "}")
        strs = tuple(re.findall(r'"((?:[^"\\]|\\.)*)"', body))
        out.setdefault(m.group(1), []).append(strs)
    return out


# ---------------------------------------------------------------------------
# SoA layout extraction (span_export_* / span_import_*)
# ---------------------------------------------------------------------------

def _split_top(s: str, sep: str):
    """Split on `sep` at paren/brace/bracket depth 0."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _balanced(text: str, open_idx: int, op: str, cl: str) -> str:
    """Body between the braces starting at text[open_idx] (exclusive)."""
    assert text[open_idx] == op
    depth = 0
    for i in range(open_idx, len(text)):
        if text[i] == op:
            depth += 1
        elif text[i] == cl:
            depth -= 1
            if depth == 0:
                return text[open_idx + 1:i]
    raise ValueError("unbalanced braces")


def function_body(text: str, name: str) -> str:
    """Brace-matched body of `name(...) { ... }` (first definition)."""
    m = re.search(r"\b" + re.escape(name) + r"\s*\([^;{)]*\)\s*\{", text)
    if m is None:
        raise KeyError(f"function {name} not found")
    return _balanced(text, m.end() - 1, "{", "}")


def _vector_decls(body: str) -> dict:
    """Map variable name -> dtype for std::vector<T> declarations
    (multi-declarator statements and T name[3] arrays included)."""
    out = {}
    for m in re.finditer(r"std::vector<(\w+)>\s+([^;]+);", body):
        dt = CTYPE_TO_DTYPE.get(m.group(1))
        if dt is None:
            continue
        for decl in _split_top(m.group(2), ","):
            dm = re.match(r"\s*(\w+)", decl)
            if dm:
                out[dm.group(1)] = dt
    return out


def _struct_members(text: str, struct_name: str) -> dict:
    """Member name -> dtype for the std::vector members of a struct."""
    m = re.search(r"\bstruct\s+" + re.escape(struct_name) + r"\s*\{", text)
    if m is None:
        raise KeyError(f"struct {struct_name} not found")
    body = _balanced(text, m.end() - 1, "{", "}")
    members = {}
    for vm in re.finditer(r"std::vector<(\w+)>\s+([^;()]+);", body):
        dt = CTYPE_TO_DTYPE.get(vm.group(1))
        if dt is None:
            continue
        for decl in _split_top(vm.group(2), ","):
            dm = re.match(r"\s*(\w+)", decl)
            if dm:
                members[dm.group(1)] = dt
    # sk[6] style arrays keep their base name
    return members


def _pk_helper_schema(helper_body: str, members: dict,
                      sk_names=None) -> list:
    """(suffix, dtype) pairs a put_pk/put_tpk/get_tpk-style helper
    emits, parsed from its own body so member renames are caught."""
    pairs = []
    # put-style: put((p + "_x").c_str(), bytes_vec(c.member)) or
    #            put(p + "_x", bytes_vec(c.member))
    for m in re.finditer(
            r'\(?p\s*\+\s*"(_\w+)"\)?(?:\.c_str\(\))?\s*,\s*'
            r'bytes_vec\(c\.(\w+)\)', helper_body):
        dt = members.get(m.group(2))
        if dt:
            pairs.append((m.group(1), dt))
    # col-style reads: c.member = col<T>(d, (p + "_x").c_str(), ...)
    for m in re.finditer(
            r'c\.(\w+)(?:\[\w+\])?\s*=\s*col<(\w+)>\s*\(\s*d\s*,\s*'
            r'\(p\s*\+\s*"(_\w+)"\)\.c_str\(\)', helper_body):
        dt = CTYPE_TO_DTYPE.get(m.group(2))
        if dt:
            pairs.append((m.group(3), dt))
    # TPK_SK loop: put(p + "_" + TPK_SK[i], bytes_vec(c.sk[i]))
    #          or: c.sk[i] = col<uint32_t>(d, (p + "_" + TPK_SK[i])...)
    skm = re.search(r'p\s*\+\s*"_"\s*\+\s*TPK_SK\[i\]', helper_body)
    if skm and sk_names:
        dt = members.get("sk")
        cm = re.search(r"col<(\w+)>\s*\(\s*d\s*,\s*\(p\s*\+\s*\"_\"\s*\+"
                       r"\s*TPK_SK", helper_body)
        if cm:
            dt = CTYPE_TO_DTYPE.get(cm.group(1), dt)
        for nm in sk_names:
            pairs.append(("_" + nm, dt))
    return pairs


def _mask_lambda_bodies(body: str) -> str:
    """Replace the bodies of in-function lambdas with blanks so the
    direct put/col scans don't re-match a helper's own internals (the
    helper schema is expanded separately at its call sites)."""
    out = body
    for lam in re.finditer(r"=\s*\[&\]\([^)]*\)\s*(?:->\s*[\w:<>]+\s*)?\{",
                           body):
        inner = _balanced(body, lam.end() - 1, "{", "}")
        out = out.replace(inner, " " * len(inner), 1)
    return out


def _relay_prefixes(body: str) -> list:
    """The r1/r2 loop binds `std::string p = <cond> ? "r1" : "r2";`."""
    m = re.search(r'std::string\s+p\s*=\s*\w+\s*==\s*\d+\s*\?\s*"(\w+)"'
                  r'\s*:\s*"(\w+)"', body)
    return [m.group(1), m.group(2)] if m else []


def extract_export_layout(text: str, func: str) -> dict:
    """Column key -> dtype for a span_export_* function.

    Handles: put("key", bytes_vec(var)); put((p + "_sfx").c_str(), ..)
    inside the r1/r2 loop; put_pk / put_tpk helper expansion.
    """
    text = strip_comments(text)
    body = function_body(text, func)
    decls = _vector_decls(body)
    layout: dict = {}

    sk_names = None
    sarr = extract_string_arrays(text)
    if "TPK_SK" in sarr:
        sk_names = sarr["TPK_SK"][0]

    # helper schemas: in-function lambda put_pk, file-level put_tpk
    helpers = {}
    lam = re.search(r"auto\s+put_pk\s*=\s*\[&\]\([^)]*\)\s*\{", body)
    if lam:
        hb = _balanced(body, lam.end() - 1, "{", "}")
        helpers["put_pk"] = _pk_helper_schema(
            hb, _struct_members(text, "PkCols"))
    fm = re.search(r"\bvoid\s+put_tpk\s*\([^)]*\)\s*\{", text)
    if fm:
        hb = _balanced(text, fm.end() - 1, "{", "}")
        helpers["put_tpk"] = _pk_helper_schema(
            hb, _struct_members(text, "TPkCols"), sk_names=sk_names)

    prefixes = _relay_prefixes(body)
    scan = _mask_lambda_bodies(body)

    # direct puts: put("key", bytes_vec(var))
    for m in re.finditer(r'put\(\s*"(\w+)"\s*,\s*bytes_vec\((\w+)', scan):
        dt = decls.get(m.group(2))
        if dt:
            layout[m.group(1)] = dt
    # relay-loop puts: put((p + "_sfx").c_str(), bytes_vec(var[ri]))
    for m in re.finditer(
            r'put\(\s*\(p\s*\+\s*"(_\w+)"\)\.c_str\(\)\s*,\s*'
            r'bytes_vec\((\w+)', scan):
        dt = decls.get(m.group(2))
        if dt:
            for p in prefixes:
                layout[p + m.group(1)] = dt
    # helper calls with a literal prefix: put_pk("rq", rq) /
    # put_tpk(d, "cq", cq, &ok)
    for hname, schema in helpers.items():
        for m in re.finditer(
                re.escape(hname) + r'\(\s*(?:d\s*,\s*)?"(\w+)"', body):
            for sfx, dt in schema:
                layout[m.group(1) + sfx] = dt
        # helper calls with the relay prefix: put_pk((p + "_pk").c_str(),..)
        for m in re.finditer(
                re.escape(hname) +
                r'\(\s*(?:d\s*,\s*)?\(p\s*\+\s*"(_\w+)"\)\.c_str\(\)',
                body):
            for p in prefixes:
                for sfx, dt in schema:
                    layout[p + m.group(1) + sfx] = dt
    return layout


def extract_import_layout(text: str, func: str) -> dict:
    """Column key -> dtype for a span_import_* function (col<T> reads,
    the r1/r2 loop, rd_pk-style lambdas and get_tpk expansion)."""
    text = strip_comments(text)
    body = function_body(text, func)
    layout: dict = {}

    sk_names = None
    sarr = extract_string_arrays(text)
    if "TPK_SK" in sarr:
        sk_names = sarr["TPK_SK"][0]

    helpers = {}
    for lam in re.finditer(r"auto\s+(\w+)\s*=\s*\[&\]\([^)]*\)\s*(?:->\s*"
                           r"[\w:<>]+\s*)?\{", body):
        hb = _balanced(body, lam.end() - 1, "{", "}")
        schema = _pk_helper_schema(hb, {}, sk_names=sk_names)
        if schema:
            helpers[lam.group(1)] = schema
    fm = re.search(r"\bTPkIn\s+get_tpk\s*\([^)]*\)\s*\{", text)
    if fm:
        hb = _balanced(text, fm.end() - 1, "{", "}")
        helpers["get_tpk"] = _pk_helper_schema(
            hb, _struct_members(text, "TPkIn"), sk_names=sk_names)

    prefixes = _relay_prefixes(body)
    scan = _mask_lambda_bodies(body)

    for m in re.finditer(r'col<(\w+)>\s*\(\s*d\s*,\s*"(\w+)"', scan):
        dt = CTYPE_TO_DTYPE.get(m.group(1))
        if dt:
            layout[m.group(2)] = dt
    for m in re.finditer(
            r'col<(\w+)>\s*\(\s*d\s*,\s*\(p\s*\+\s*"(_\w+)"\)\.c_str\(\)',
            scan):
        dt = CTYPE_TO_DTYPE.get(m.group(1))
        if dt:
            for p in prefixes:
                layout[p + m.group(2)] = dt
    for hname, schema in helpers.items():
        for m in re.finditer(
                re.escape(hname) + r'\(\s*(?:d\s*,\s*)?"(\w+)"', body):
            for sfx, dt in schema:
                layout[m.group(1) + sfx] = dt
        for m in re.finditer(
                re.escape(hname) +
                r'\(\s*(?:d\s*,\s*)?\(p\s*\+\s*"(_\w+)"\)\.c_str\(\)',
                body):
            for p in prefixes:
                for sfx, dt in schema:
                    layout[p + m.group(1) + sfx] = dt
    return layout


# ---------------------------------------------------------------------------
# Engine-mutator extraction (state_epoch-bumping entry points)
# ---------------------------------------------------------------------------

_METHOD_ENTRY = re.compile(
    r'\{\s*"(\w+)"\s*,\s*\(PyCFunction\)\s*(\w+)', re.S)

_BUMP = re.compile(r"\bstate_epoch\s*(?:\+\+|\+=)")
_CALLEE = re.compile(r"\b(\w+)\s*\(")

# identifiers the callee scan must never treat as delegated helpers
_NOT_CALLEES = frozenset({
    "if", "for", "while", "switch", "return", "sizeof", "catch",
    "defined", "assert", "static_cast", "reinterpret_cast",
    "const_cast",
})


def extract_method_table(text: str, *, _stripped: bool = False) -> dict:
    """{python method name -> C wrapper function name} from the
    PyMethodDef table (`{"name", (PyCFunction)eng_name, ...}`)."""
    if not _stripped:
        text = strip_comments(text)
    return {m.group(1): m.group(2)
            for m in _METHOD_ENTRY.finditer(text)}


def _bump_depths(body: str):
    """Brace depth of every state_epoch bump inside `body` (0 =
    statement level of the function itself, i.e. on every path)."""
    return [body.count("{", 0, m.start()) - body.count("}", 0, m.start())
            for m in _BUMP.finditer(body)]


_DEF_SITE = re.compile(r"\b(\w+)\s*\([^;{)]*\)\s*\{")


def _def_index(text: str) -> dict:
    """{name -> open-brace index} of the FIRST `name(..) {` site per
    name — one pass, so the per-callee body lookups in
    classify_epoch_effect don't re-scan the whole engine source.
    Matches function_body's first-definition semantics exactly."""
    index: dict = {}
    for m in _DEF_SITE.finditer(text):
        index.setdefault(m.group(1), m.end() - 1)
    return index


def _body_of(text: str, name: str, cache: dict):
    if name not in cache:
        index = cache.get(_DEF_INDEX_KEY)
        if index is None:
            index = cache[_DEF_INDEX_KEY] = _def_index(text)
        pos = index.get(name)
        cache[name] = None if pos is None else _balanced(text, pos,
                                                         "{", "}")
    return cache[name]


_DEF_INDEX_KEY = object()


def classify_epoch_effect(text: str, cfunc: str, cache: dict) -> dict:
    """How (and whether) the wrapper `cfunc` bumps state_epoch.

    Returns {"bump": kind, "via": helper-name-or-None} where kind is
    - "unconditional": a bump at brace depth 0 of the wrapper body, or
      at depth 0 of a directly-called helper's body (the blob-import
      wrappers delegate their bump to *_import_blob);
    - "conditional": bumps exist but only inside nested braces — NOT
      good enough for a declared mutator (some control path mutates
      without invalidating device residency);
    - "none": no bump anywhere reachable at depth <= 1;
    - "missing": the wrapper body itself was not found (fail closed).
    The callee walk is deliberately depth-1 only: the engine's idiom
    is wrapper-level bumps plus at most one delegated helper, and a
    deeper search would start crediting bumps through unrelated
    control flow the brace scan cannot vouch for.
    """
    body = _body_of(text, cfunc, cache)
    if body is None:
        return {"bump": "missing", "via": None}
    depths = _bump_depths(body)
    if depths:
        return {"bump": "unconditional" if 0 in depths else "conditional",
                "via": None}
    best = None
    for cm in _CALLEE.finditer(body):
        name = cm.group(1)
        if name == cfunc or name in _NOT_CALLEES:
            continue
        cb = _body_of(text, name, cache)
        if cb is None:
            continue
        cd = _bump_depths(cb)
        if not cd:
            continue
        if 0 in cd:
            return {"bump": "unconditional", "via": name}
        best = {"bump": "conditional", "via": name}
    return best or {"bump": "none", "via": None}


@functools.lru_cache(maxsize=4)
def extract_epoch_effects(text: str) -> dict:
    """{python method name -> classify_epoch_effect result + "cfunc"}
    for every exported engine entry point — the raw material of
    analysis pass 4a (effects.py) and of `extract_epoch_mutators`.
    Memoized on the text: pass 3 (async-hazard mutator list), pass 4a
    and bench's preflight all consume one computation per source.
    Callers must not mutate the returned dicts."""
    text = strip_comments(text)
    cache: dict = {}
    out = {}
    for pyname, cfunc in extract_method_table(text, _stripped=True).items():
        eff = classify_epoch_effect(text, cfunc, cache)
        eff["cfunc"] = cfunc
        out[pyname] = eff
    return out


def extract_epoch_mutators(text: str) -> set:
    """Python-visible engine method names whose C wrapper bumps
    state_epoch — directly or via a depth-1 delegated helper (the
    blob-import wrappers) — the single source of truth consumed by
    BOTH the `async-hazard` lint rule (analysis pass 3) and the
    engine effect audit (pass 4a), so the two can never drift.

    Fail-closed like the other extractors: an unrecognized method-
    table idiom yields a missing method, which the contract test
    notices — never a silently shorter mutator list."""
    return {name for name, eff in extract_epoch_effects(text).items()
            if eff["bump"] in ("unconditional", "conditional")}
