"""Twin-contract & determinism static analysis.

The determinism guarantee rests on *duplicated* definitions staying in
lockstep: every constant, SoA column layout, and RNG parameter that
exists both in native/netplane.cpp and in its Python twins is a
silent-divergence hazard that otherwise only surfaces at runtime as a
span abort or a byte-mismatch after minutes of XLA compile.  This
package catches that drift in seconds, before the differential gates
(docs/PARITY.md) ever run:

- pass 1 (`twin_constants`): extract named constants from the C++
  engine and diff them against the Python twin modules;
- pass 2 (`soa_layout`): extract the span_export/span_import column
  schemas from the C++ engine and verify the Python codecs consume
  and produce exactly those columns with the same dtypes;
- pass 3 (`determinism`): AST lint over shadow_tpu/ for
  nondeterminism hazards (wall clocks, unseeded RNGs, set iteration,
  host mutation inside jitted bodies, np-vs-jnp confusion, engine
  mutation while an async span dispatch is in flight);
- pass 4 (`effects`): cross-layer effect & ownership audit — every
  engine entry point classified mutator (bumps state_epoch on every
  mutating path) or observer (never bumps), worker-thread writes to
  shared state outside the host-affine ownership law, writes inside
  an open speculative-dispatch window, and the experimental-knob
  registry (validated + documented + digest-classified, cross-checked
  against ckpt/restore.py).

No pass needs JAX (pure parsing); the whole run is a tier-1 gate
(tests/test_twin_contract.py, tests/test_effects.py) and a CLI:
`python -m shadow_tpu.tools.lint` or `scripts/lint`.  Rule catalogue
and pragma syntax: docs/LINT.md.
"""

from __future__ import annotations

from shadow_tpu.analysis.report import Violation, format_report

__all__ = ["Violation", "format_report", "run_all"]


def run_all(repo_root: str, passes=("twin", "layout", "det", "effects")):
    """Run the requested passes; returns (violations, per-pass counts)."""
    from shadow_tpu.analysis import (determinism, effects, soa_layout,
                                     twin_constants)

    violations: list[Violation] = []
    counts: dict[str, int] = {}
    if "twin" in passes:
        v = twin_constants.check(repo_root)
        counts["twin"] = len(v)
        violations += v
    if "layout" in passes:
        v = soa_layout.check(repo_root)
        counts["layout"] = len(v)
        violations += v
    if "det" in passes:
        v = determinism.check(repo_root)
        counts["det"] = len(v)
        violations += v
    if "effects" in passes:
        v = effects.check(repo_root)
        counts["effects"] = len(v)
        violations += v
    return violations, counts
