"""Pass 2: SoA layout contract check.

For every device-span family the C++ engine exports a dict of packed
column bytes and later imports the Python codec's dict back.  Four
schemas must stay in lockstep:

    span_export_*  (C++ writer)  ==  _to_arrays    (Python reader)
    span_import_*  (C++ reader)  ==  _from_arrays  (Python writer)

and the C++ reader's per-column dtype must match the C++ writer's.
Any unread exported column (dead device-link traffic), phantom read
(KeyError at runtime), or dtype skew (silent reinterpretation of raw
bytes!) is flagged here, statically, instead of surfacing as a span
abort or a byte-mismatch at runtime.

To register a new device-span family: add a row to FAMILIES naming the
C++ export/import functions and the Python codec module; the codec
must expose `_to_arrays` / `_from_arrays` methods using the
`np.frombuffer(d[key], dtype)` / `out[key] = ...` idioms.
"""

from __future__ import annotations

import os

from shadow_tpu.analysis import cpp_extract, py_extract
from shadow_tpu.analysis.report import Violation

CPP = "native/netplane.cpp"

FAMILIES = [
    {
        "name": "phold",
        "export_fn": "eng_span_export_phold",
        "import_fn": "eng_span_import_phold",
        "codec": "shadow_tpu/ops/phold_span.py",
        # extraction-sanity floor: fewer keys than this means the
        # extractor lost the function, not that the schema shrank
        "min_columns": 60,
    },
    {
        "name": "tcp",
        "export_fn": "eng_span_export_tcp",
        "import_fn": "eng_span_import_tcp",
        "codec": "shadow_tpu/ops/tcp_span.py",
        "min_columns": 120,
    },
]


def check(repo_root: str, cpp_text: str | None = None) -> list:
    if cpp_text is None:
        with open(os.path.join(repo_root, CPP)) as fh:
            cpp_text = fh.read()

    violations: list[Violation] = []
    for fam in FAMILIES:
        name = fam["name"]
        codec = fam["codec"]
        codec_path = os.path.join(repo_root, codec)
        try:
            exported = cpp_extract.extract_export_layout(
                cpp_text, fam["export_fn"])
            imported = cpp_extract.extract_import_layout(
                cpp_text, fam["import_fn"])
        except KeyError as exc:
            violations.append(Violation(
                "soa-layout", CPP, f"[{name}] {exc.args[0]}"))
            continue
        consumed, unres_c = py_extract.extract_consumed_schema(codec_path)
        produced, unres_p = py_extract.extract_produced_keys(codec_path)

        if len(exported) < fam["min_columns"]:
            violations.append(Violation(
                "soa-layout", CPP,
                f"[{name}] export extractor found only {len(exported)} "
                f"columns (< {fam['min_columns']}); unrecognized idiom?"))
        for line, what in unres_c + unres_p:
            violations.append(Violation(
                "soa-layout", codec,
                f"[{name}] unresolvable {what} (the contract cannot "
                f"see this read/write)", line=line))

        # export -> _to_arrays
        for key in sorted(set(exported) - set(consumed)):
            violations.append(Violation(
                "soa-layout", CPP,
                f"[{name}] exported column {key!r} is never consumed "
                f"by {codec} _to_arrays (dead device-link traffic)"))
        for key in sorted(set(consumed) - set(exported)):
            violations.append(Violation(
                "soa-layout", codec,
                f"[{name}] _to_arrays reads column {key!r} that "
                f"{fam['export_fn']} never exports (KeyError at span "
                f"time)"))
        for key in sorted(set(exported) & set(consumed)):
            if consumed[key] is not None and consumed[key] != exported[key]:
                violations.append(Violation(
                    "soa-layout", codec,
                    f"[{name}] column {key!r} decoded as "
                    f"{consumed[key]} but exported as {exported[key]} "
                    f"(byte reinterpretation)"))

        # _from_arrays -> import
        for key in sorted(set(imported) - set(produced)):
            violations.append(Violation(
                "soa-layout", codec,
                f"[{name}] {fam['import_fn']} requires column {key!r} "
                f"that _from_arrays never produces (import failure)"))
        for key in sorted(set(produced) - set(imported)):
            violations.append(Violation(
                "soa-layout", codec,
                f"[{name}] _from_arrays produces column {key!r} that "
                f"{fam['import_fn']} never reads (dead device-link "
                f"traffic)"))

        # C++ import dtype vs C++ export dtype (same byte layout end
        # to end; only meaningful for columns both sides touch)
        for key in sorted(set(imported) & set(exported)):
            if imported[key] != exported[key]:
                violations.append(Violation(
                    "soa-layout", CPP,
                    f"[{name}] column {key!r} exported as "
                    f"{exported[key]} but imported as {imported[key]}"))

        # Residency classification (the dirty-column export protocol,
        # ISSUE 3): every SoA state column the codec materializes must
        # be classified CARRIED / STATIC / DERIVED in the module's
        # RESIDENT_* tables — a column added to the export without a
        # classification entry would otherwise be reused across
        # device-resident spans with unreviewed dirtiness semantics.
        state_keys, unres_s = py_extract.extract_state_keys(codec_path)
        for line, what in unres_s:
            violations.append(Violation(
                "soa-layout", codec,
                f"[{name}] unresolvable {what} (the residency "
                f"classification cannot see this column)", line=line))
        sets_ = py_extract.extract_residency_sets(codec_path)
        missing_tables = [t for t in ("RESIDENT_STATIC",
                                      "RESIDENT_DERIVED",
                                      "RESIDENT_CARRIED")
                          if t not in sets_]
        if missing_tables:
            violations.append(Violation(
                "soa-layout", codec,
                f"[{name}] residency table(s) missing/unparseable: "
                f"{', '.join(missing_tables)}"))
        else:
            r_static = sets_["RESIDENT_STATIC"]
            r_derived = sets_["RESIDENT_DERIVED"]
            r_carried = sets_["RESIDENT_CARRIED"]
            for a, b in (("STATIC", "DERIVED"), ("STATIC", "CARRIED"),
                         ("DERIVED", "CARRIED")):
                dup = sets_[f"RESIDENT_{a}"] & sets_[f"RESIDENT_{b}"]
                if dup:
                    violations.append(Violation(
                        "soa-layout", codec,
                        f"[{name}] column(s) {sorted(dup)} in both "
                        f"RESIDENT_{a} and RESIDENT_{b}"))
            public = {k for k in state_keys if not k.startswith("_")}
            for key in sorted(public - r_static - r_derived
                              - r_carried):
                violations.append(Violation(
                    "soa-layout", codec,
                    f"[{name}] state column {key!r} has no residency "
                    f"class (dirty-column protocol): add it to "
                    f"RESIDENT_CARRIED / _STATIC / _DERIVED"))
            # DERIVED entries may be kernel-side registers the codec
            # never materializes; STATIC/CARRIED must exist.
            for key in sorted((r_static | r_carried) - public):
                violations.append(Violation(
                    "soa-layout", codec,
                    f"[{name}] residency entry {key!r} names a column "
                    f"the codec no longer produces (stale entry)"))
    return violations
