"""Pass 3: JAX determinism/purity lint over shadow_tpu/.

Byte-identical traces tolerate zero ambient nondeterminism in anything
that feeds simulation state.  This pass walks every module's AST and
flags the hazard patterns; sanctioned exceptions carry an inline
pragma with a reason:

    x = time.perf_counter()  # shadow-lint: allow[wall-clock] pacing only

Rules (catalogue + rationale in docs/LINT.md):

  py-random      stdlib `random` (global, seed-order dependent)
  np-random      `np.random` anywhere — the sanctioned RNG is the
                 counter-based threefry in core/rng.py; even seeded
                 RandomStates are sequential (draw-order dependent)
  wall-clock     time.time/monotonic/perf_counter, datetime.now, ...
  set-iter       iterating a set (unordered -> order-dependent traces)
  host-mutation  global/nonlocal writes or closure-object mutation
                 inside a jitted/traced function body
  tracer-leak    attribute writes (obj.attr = ..) inside a jitted/
                 traced function body — traced values escaping to host
                 objects outlive the trace and go stale
  np-in-jit      np.* calls inside a jitted/traced body where jnp is
                 required (host math on traced values breaks tracing
                 or silently constant-folds)
  sim-channel    wall-clock reads inside a sim-time trace channel
                 (SimChannel in trace/recorder, KernChannel in
                 trace/kernstat, NetstatChannel in
                 trace/netstat, SyscallChannel/HostSyscallLog in
                 trace/sctrace): the channels are DEFINED to be
                 byte-identical across runs, so this rule has NO
                 pragma escape (fail closed)
  async-hazard   an engine-mutating call (state_epoch-bumping entry
                 point, extracted from native/netplane.cpp's method
                 table) while an async span dispatch (`_span_call`)
                 is in flight — before the window is forced
                 (np.asarray / .block_until_ready) or published
                 through the in-flight guard (`_inflight` /
                 `_commit_spec`, ops/span_mesh.py).  A mutation in
                 that gap rebases the window on state the landing
                 check can no longer see (ISSUE 16)

"Jitted/traced bodies" = functions decorated with jit/jax.jit/
partial(jax.jit, ..), functions passed to lax.while_loop/scan/cond/
fori_loop/switch or shard_map, plus everything nested inside them.
"""

from __future__ import annotations

import ast
import os
import re

from shadow_tpu.analysis.report import Violation

RULES = ("py-random", "np-random", "wall-clock", "set-iter",
         "host-mutation", "tracer-leak", "np-in-jit", "sim-channel",
         "async-hazard")

_PRAGMA = re.compile(
    r"#\s*shadow-lint:\s*allow\[([\w\-,\s]+)\]\s*(\S.*)?$")

_WALL_CLOCK_ATTRS = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("time", "perf_counter_ns"), ("time", "process_time"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("date", "today"), ("os", "times"),
}

# names that are wall-clock reads when imported bare
# (`from time import perf_counter`)
_WALL_CLOCK_FROM = {
    "time": {"time", "time_ns", "monotonic", "monotonic_ns",
             "perf_counter", "perf_counter_ns", "process_time"},
    "os": {"times"},
}

_LAX_HOF = {"while_loop", "scan", "cond", "fori_loop", "switch",
            "shard_map", "pmap", "vmap_with_state"}

# np.* calls that are pure scalar/dtype constructors — fine at trace
# time inside a jitted body (they cannot touch a tracer)
_NP_TRACE_SAFE = {"int8", "int16", "int32", "int64", "uint8", "uint16",
                  "uint32", "uint64", "float32", "float64", "bool_",
                  "dtype", "iinfo", "finfo"}


def _pragma_allows(lines, lineno: int, rule: str) -> bool:
    """True if the line (or the line above) carries a matching pragma
    with a non-empty reason."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _PRAGMA.search(lines[ln - 1])
            if m and m.group(2):
                allowed = {r.strip() for r in m.group(1).split(",")}
                if rule in allowed or "*" in allowed:
                    return True
    return False


def _is_set_expr(node) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    return False


class _DeviceFnFinder(ast.NodeVisitor):
    """Collects FunctionDef/Lambda nodes that run under jit/trace."""

    def __init__(self):
        self.device_fns: set = set()
        self._local_defs: dict = {}

    def visit_FunctionDef(self, node):
        self._local_defs[node.name] = node
        for dec in node.decorator_list:
            if self._is_jit(dec):
                self.device_fns.add(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _is_jit(dec) -> bool:
        # @jit / @jax.jit / @partial(jax.jit, ..) / @jax.pmap
        def name_of(n):
            if isinstance(n, ast.Name):
                return n.id
            if isinstance(n, ast.Attribute):
                return n.attr
            return None

        if name_of(dec) in ("jit", "pmap"):
            return True
        if isinstance(dec, ast.Call):
            if name_of(dec.func) in ("jit", "pmap"):
                return True
            if name_of(dec.func) == "partial" and dec.args and \
                    name_of(dec.args[0]) in ("jit", "pmap"):
                return True
        return False

    def visit_Call(self, node):
        # lax.while_loop(cond, body, ..), jit(fn), shard_map(fn, ..):
        # any function-valued argument becomes a device fn
        fname = None
        if isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        elif isinstance(node.func, ast.Name):
            fname = node.func.id
        if fname in _LAX_HOF or fname in ("jit",):
            candidates = list(node.args) + \
                [kw.value for kw in node.keywords]
            for arg in candidates:
                if isinstance(arg, ast.Lambda):
                    self.device_fns.add(arg)
                elif isinstance(arg, ast.Name) and \
                        arg.id in self._local_defs:
                    self.device_fns.add(self._local_defs[arg.id])
        self.generic_visit(node)


def _expand_nested(fns: set) -> set:
    """A function defined inside a device fn is device too."""
    out = set(fns)
    for fn in fns:
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                out.add(sub)
    return out


class _ModuleLinter:
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.violations: list[Violation] = []

    def flag(self, rule: str, node, message: str):
        if not _pragma_allows(self.lines, node.lineno, rule):
            self.violations.append(
                Violation(rule, self.relpath, message, line=node.lineno))

    # -- module-wide rules -------------------------------------------
    def _collect_aliases(self) -> dict:
        """Local name -> canonical dotted module for `import X [as Y]`
        (so `import time as t; t.time()` still matches)."""
        aliases = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        aliases[alias.asname] = alias.name
                    else:
                        # `import os.path` binds the ROOT name `os`
                        root = alias.name.split(".")[0]
                        aliases[root] = root
        # default spellings always resolve to themselves
        for canon in ("time", "datetime", "os", "random", "numpy"):
            aliases.setdefault(canon, canon)
        aliases.setdefault("np", "numpy")
        return aliases

    @staticmethod
    def _dotted(node):
        """Flatten a Name/Attribute chain to its dotted parts, or None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return parts[::-1]

    @staticmethod
    def _is_wall_clock(canon: list) -> bool:
        """THE wall-clock predicate over a canonicalized dotted chain —
        shared by the `wall-clock` and `sim-channel` rules so a new
        pattern added here protects both."""
        return (len(canon) >= 2
                and (canon[-2], canon[-1]) in _WALL_CLOCK_ATTRS
                and canon[0] in ("time", "datetime", "os"))

    def lint_global(self):
        aliases = self._collect_aliases()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "random":
                        self.flag("py-random", node,
                                  "stdlib random is seed-order dependent; "
                                  "use core/rng.py threefry streams")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                root = mod.split(".")[0]
                if root == "random":
                    self.flag("py-random", node,
                              "stdlib random is seed-order dependent; "
                              "use core/rng.py threefry streams")
                elif mod == "numpy.random" or (
                        root == "numpy" and any(
                            a.name == "random" for a in node.names)):
                    self.flag("np-random", node,
                              "numpy.random is a sequential host RNG; "
                              "use core/rng.py threefry streams")
                elif mod in _WALL_CLOCK_FROM and any(
                        a.name in _WALL_CLOCK_FROM[mod]
                        for a in node.names):
                    self.flag("wall-clock", node,
                              f"wall-clock import from {mod} — "
                              f"simulation state must come from sim "
                              f"time")
            elif isinstance(node, ast.Attribute):
                parts = self._dotted(node)
                if parts is None:
                    continue
                # resolve `import X as Y` on the leading name
                canon = aliases.get(parts[0], parts[0]).split(".") \
                    + parts[1:]
                dotted = ".".join(canon)
                if canon[0] == "random":
                    self.flag("py-random", node,
                              f"{dotted}: stdlib random is seed-order "
                              f"dependent")
                elif canon[0] == "numpy" and "random" in canon[1:-1]:
                    self.flag("np-random", node,
                              f"{dotted}: sequential host RNG; use "
                              f"core/rng.py threefry streams")
                elif self._is_wall_clock(canon):
                    self.flag("wall-clock", node,
                              f"{dotted}: wall-clock read — simulation "
                              f"state must come from sim time")
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if _is_set_expr(it):
                    self.flag("set-iter", it if hasattr(it, "lineno")
                              else node,
                              "iterating a set: unordered — sort first "
                              "if order can reach simulation state")

    # -- sim-time trace channel --------------------------------------
    def lint_sim_channel(self):
        """Any wall-clock read inside a sim-time channel class body
        (`SimChannel`, the flight recorder's event stream;
        `NetstatChannel`, the sim-netstat telemetry stream;
        `FabricChannel`/`KernChannel`, the fabric and device-kernel
        observatories; or
        `SyscallChannel`/`HostSyscallLog`, the syscall observatory's
        record stream) is a violation with NO pragma escape: the
        channels' byte-identity contracts (docs/OBSERVABILITY.md)
        admit no sanctioned exception — profiling belongs in
        WallChannel / HostScWall."""
        channels = [cls for cls in ast.walk(self.tree)
                    if isinstance(cls, ast.ClassDef)
                    and cls.name in ("SimChannel", "NetstatChannel",
                                     "FabricChannel",
                                     "KernChannel",
                                     "FixedRecordChannel",
                                     "SyscallChannel",
                                     "HostSyscallLog")]
        if not channels:
            return
        aliases = self._collect_aliases()
        # bare names bound by `from time import perf_counter` etc.
        wall_from: set = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod in _WALL_CLOCK_FROM:
                    for a in node.names:
                        if a.name in _WALL_CLOCK_FROM[mod]:
                            wall_from.add(a.asname or a.name)
        for cls in channels:
            for node in ast.walk(cls):
                hit = None
                if isinstance(node, ast.Attribute):
                    parts = self._dotted(node)
                    if parts is not None:
                        canon = aliases.get(
                            parts[0], parts[0]).split(".") + parts[1:]
                        if self._is_wall_clock(canon):
                            hit = ".".join(canon)
                elif isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in wall_from:
                    hit = node.id
                if hit is not None:
                    self.violations.append(Violation(
                        "sim-channel", self.relpath,
                        f"{hit}: wall-clock read inside the sim-time "
                        f"trace channel (byte-identity contract; no "
                        f"pragma escape)", line=node.lineno))

    # -- device-path rules -------------------------------------------
    def lint_device(self):
        finder = _DeviceFnFinder()
        finder.visit(self.tree)
        # lint only OUTERMOST device fns: each one's walk already
        # covers its nested defs (a while_loop body inside a jitted fn
        # must not be reported twice)
        nested_in_other = set()
        for fn in finder.device_fns:
            nested_in_other |= _expand_nested({fn}) - {fn}
        for fn in finder.device_fns:
            if fn not in nested_in_other:
                self._lint_device_fn(fn)

    def _lint_device_fn(self, fn):
        local_names = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local_names.add(tgt.id)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(node.target, ast.Name):
                    local_names.add(node.target.id)
        if hasattr(fn, "args"):
            for a in getattr(fn.args, "args", []):
                local_names.add(a.arg)

        for node in ast.walk(fn):
            # skip nodes that belong to nested non-device defs: all
            # nested defs ARE device here (by _expand_nested), so no
            # skipping is needed
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                self.flag("host-mutation", node,
                          "global/nonlocal write inside a traced body "
                          "runs at trace time only — stale on cached "
                          "executions")
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        self.flag("tracer-leak", node,
                                  f"attribute write .{tgt.attr} inside "
                                  f"a traced body leaks trace-time "
                                  f"state onto a host object")
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                owner = node.func.value
                attr = node.func.attr
                if isinstance(owner, ast.Name) and \
                        owner.id in ("np", "numpy") and \
                        attr not in _NP_TRACE_SAFE:
                    self.flag("np-in-jit", node,
                              f"np.{attr} inside a traced body: host "
                              f"numpy cannot consume tracers — use jnp")
                elif attr in ("append", "extend", "add", "update",
                              "setdefault", "insert") and \
                        isinstance(owner, ast.Name) and \
                        owner.id not in local_names:
                    self.flag("host-mutation", node,
                              f"{owner.id}.{attr}(..) mutates a closure "
                              f"object at trace time only — stale on "
                              f"cached executions")


    # -- async dispatch hazards (ISSUE 16) ---------------------------
    def lint_async(self, mutators: set):
        """No engine-mutating call while an async span dispatch is in
        flight.  A "window" opens at a `._span_call(..)` invocation
        (the raw jitted dispatch, ops/span_mesh.py) and closes at the
        first of:

          * a force — `np.asarray(..)` or `.block_until_ready()`;
          * publication through the in-flight guard — an assignment
            to a `*_inflight*` attribute or a `._commit_spec(..)`
            call (the guard stamps `state_epoch` at publication, so
            later mutations are caught at landing).

        Between open and close, a call to any `state_epoch`-bumping
        engine entry point (the pass-1 contract list, extracted from
        native/netplane.cpp's method table) rebases the window on
        state no landing check can see — flagged.  The scan is
        per-function in source order; nested defs get their own
        windows."""
        if not mutators:
            return
        fns = [n for n in ast.walk(self.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in fns:
            self._lint_async_fn(fn, mutators)

    def _lint_async_fn(self, fn, mutators: set):
        events = []

        def classify(node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                owner = self._dotted(node.func.value) or []
                if attr == "_span_call":
                    events.append((node.lineno, node.col_offset,
                                   "open", node, attr))
                elif attr in ("block_until_ready", "_commit_spec") or \
                        (attr == "asarray"
                         and owner[:1] in (["np"], ["numpy"])):
                    events.append((node.lineno, node.col_offset,
                                   "close", node, attr))
                elif attr in mutators and \
                        owner[-1:] in (["engine"], ["eng"]):
                    events.append((node.lineno, node.col_offset,
                                   "mutate", node, attr))
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Attribute) and \
                                "_inflight" in sub.attr:
                            events.append((node.lineno, node.col_offset,
                                           "close", node, sub.attr))

        def walk_own(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # separate window scope
                classify(child)
                walk_own(child)

        walk_own(fn)
        events.sort(key=lambda e: (e[0], e[1]))
        open_at = None
        for _ln, _col, kind, node, attr in events:
            if kind == "open":
                open_at = node.lineno
            elif kind == "close":
                open_at = None
            elif open_at is not None:
                self.flag("async-hazard", node,
                          f"engine.{attr}(..) while the span dispatched "
                          f"at line {open_at} is in flight — force it "
                          f"(np.asarray / block_until_ready) or publish "
                          f"it through the in-flight guard "
                          f"(_commit_spec) first")


def epoch_mutators(repo_root: str) -> set:
    """The async-hazard contract list: every C++ engine entry point
    that bumps `state_epoch` (directly or via a depth-1 delegated
    helper), extracted from native/netplane.cpp's method table.  Empty
    set (rule inert) when the native source is absent — the extractor,
    not a hand list, is the source of truth, and pass 4a's engine
    effect audit (analysis/effects.py) consumes the SAME extraction
    (`cpp_extract.extract_epoch_effects`, memoized) and cross-checks
    this set against its declared mutator registry, so the two views
    can never drift."""
    path = os.path.join(repo_root, "native", "netplane.cpp")
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError:
        return set()
    from shadow_tpu.analysis.cpp_extract import extract_epoch_mutators
    return extract_epoch_mutators(text)


def iter_py_files(repo_root: str, subdir: str = "shadow_tpu"):
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(repo_root, subdir)):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", "lib"))
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def check(repo_root: str, paths=None) -> list:
    violations: list[Violation] = []
    files = paths if paths is not None else iter_py_files(repo_root)
    mutators = epoch_mutators(repo_root)
    for path in files:
        rel = os.path.relpath(path, repo_root)
        with open(path) as fh:
            source = fh.read()
        try:
            linter = _ModuleLinter(rel, source)
        except SyntaxError as exc:
            violations.append(Violation(
                "parse-error", rel, str(exc), line=exc.lineno or 0))
            continue
        linter.lint_global()
        linter.lint_device()
        linter.lint_sim_channel()
        linter.lint_async(mutators)
        violations.extend(linter.violations)
    return violations
