"""Analysis pass 4 — cross-layer effect & ownership audit.

Three fail-closed sub-passes over the surfaces the residency protocol
(PR 3), the svc worker pool (PR 13) and the overlapped span pipeline
(PR 16) grew, none of which passes 1-3 cover:

- **4a, engine effect audit** (`effect-*` rules): every exported
  entry point in native/netplane.cpp's PyMethodDef table must be
  classified in `ENTRY_EFFECTS` as a *mutator* (bumps `state_epoch`
  at brace depth 0 — i.e. on every control path — of its wrapper or
  of one delegated helper) or an *observer* (must not bump at all:
  the channel drains, `plane_export`, shape probes).  Unclassified,
  stale, conditionally-bumping-mutator and bumping-observer entries
  are violations.  The classification consumes the SAME extraction
  (`cpp_extract.extract_epoch_effects`) that feeds pass 3's
  `async-hazard` mutator list, so the two can never drift — and an
  explicit `effect-drift` cross-check holds them equal anyway.

- **4b, thread-ownership lint** (`svc-ownership` / `overlap-window`):
  AST reachability from worker entry points (`pool.submit(fn, ..)`,
  `threading.Thread(target=fn)`, `pool.map(fn, ..)`) — any write to
  shared state (self/closure/global attributes or subscripts, or a
  mutating container call on them) outside a `with ..lock..:` block
  violates the host-affine ownership law (`host.id % workers`:
  workers own disjoint host groups and nothing else).  Separately,
  inside an open speculative-dispatch window (`_span_call(..)` not
  yet forced, committed or published as in-flight) writes through a
  deep `self.x.y` chain mutate state the speculation already read.
  Both escape only via the reason-required
  `# shadow-lint: allow[rule] reason` pragma (docs/LINT.md).

- **4c, knob registry** (`knob-*` rules): every `experimental.*` knob
  in core/config.py must be loadable (a from_dict conversion row),
  documented (a row in docs/config_spec.md's experimental table),
  and classified digest-skipped vs digest-included in `KNOB_DIGEST`;
  the skip half must equal ckpt/restore.py's hand-maintained
  `_DIGEST_SKIP_EXPERIMENTAL` tuple, and wall-only knobs must be
  unreachable from the sim-time channel classes.

Every extractor takes injectable text overrides so the mutation
self-tests (tests/test_effects.py) can perturb one surface in memory
and prove the rule bites.  Absent surfaces (no native source, no
docs) make the corresponding rules inert, matching the other passes'
behavior in stripped-down checkouts.
"""

from __future__ import annotations

import ast
import os
import re

from shadow_tpu.analysis.cpp_extract import extract_epoch_effects
from shadow_tpu.analysis.determinism import _pragma_allows, iter_py_files
from shadow_tpu.analysis.report import Violation

RULES = (
    "effect-unclassified", "effect-stale", "effect-mutator-bump",
    "effect-observer-bump", "effect-drift",
    "svc-ownership", "overlap-window",
    "knob-unregistered", "knob-stale", "knob-unloadable",
    "knob-undocumented", "knob-digest-drift", "knob-wall-in-channel",
)

# ---------------------------------------------------------------------------
# 4a: the engine effect registry
# ---------------------------------------------------------------------------

# Every Python-visible engine entry point, by effect.  A new method
# lands only with a row here (effect-unclassified fails closed), and
# the brace-scoped bump scan verifies the declaration against the
# C++ body — a mutator that forgets its bump, or an observer that
# grows one, is caught before any runtime tier.

MUTATORS = frozenset({
    # plane construction / config that future packets observe
    "add_host", "set_callbacks", "set_routing", "set_nt",
    "set_host_rng", "set_host_fault", "set_host_tcp", "set_dctcp_k",
    "set_pcap", "set_tracing", "set_py_work",
    # simulation advance
    "run_until", "run_hosts", "run_hosts_mt", "run_span",
    "advance_clocks", "fire", "deliver", "finish_round",
    "export_round", "scatter_round", "push_inbox", "take_outgoing",
    # device-span import (overwrites host state wholesale)
    "span_import_phold", "span_import_tcp",
    # snapshot import (rebuilds host state wholesale)
    "plane_import", "host_import",
    # sequence allocators (consume deterministic id streams)
    "next_event_seq", "next_packet_seq", "rng_next",
    # app lifecycle
    "app_spawn", "app_kill", "app_stop", "app_continue",
    "app_teardown",
    # sockets & packets
    "tcp_socket", "udp_socket", "sock_bind", "sock_close", "sock_set",
    "tcp_listen", "tcp_connect", "tcp_accept", "tcp_sendto",
    "tcp_recv", "tcp_shutdown", "tcp_set_nodelay", "tcp_bufs",
    "udp_sendto", "udp_recvfrom", "udp_connect", "udp_push_reply",
    "drop_packet", "free_packet", "intern_packet",
})

OBSERVERS = frozenset({
    # channel drains & enables: TRACE state, not SIMULATION state
    # (the set_flight/set_netstat comment in netplane.cpp is the law)
    "flight_take", "netstat_take", "fabric_take", "pcap_take",
    "trace_entries", "set_flight", "set_netstat", "set_fabric",
    "set_devcap_probe", "netstat_sample", "fabric_sample",
    # counters / probes / shape reads
    "counters", "mt_stats", "devcap_counters", "fabric_counters",
    "drop_causes", "mark_causes", "netstat_totals", "fct_flows",
    "round_size", "peek_next", "peek_deadline", "packet_fields",
    "tcp_info", "sock_addr", "sock_inq", "sock_status",
    # app observation
    "app_poll", "app_status", "app_threads", "app_syscalls",
    # snapshot export is read-only; the epoch read is the guard itself
    "plane_export", "state_epoch",
    # device-span export is read-only (the engine stays authoritative;
    # an aborted span simply never imports)
    "span_export_phold", "span_export_tcp",
})

ENTRY_EFFECTS = {name: "mutator" for name in MUTATORS}
ENTRY_EFFECTS.update({name: "observer" for name in OBSERVERS})

_CPP_REL = os.path.join("native", "netplane.cpp")


def _read(repo_root: str, *rel):
    try:
        with open(os.path.join(repo_root, *rel)) as fh:
            return fh.read()
    except OSError:
        return None


def _entry_line(cpp_text: str, name: str) -> int:
    m = re.search(r'\{\s*"' + re.escape(name) + r'"\s*,\s*\(PyCFunction\)',
                  cpp_text)
    return cpp_text.count("\n", 0, m.start()) + 1 if m else 0


def check_engine_effects(repo_root: str, cpp_text=None) -> list:
    """4a.  `cpp_text` overrides native/netplane.cpp for self-tests;
    with neither available the rules are inert (no native source)."""
    from_tree = cpp_text is None
    if from_tree:
        cpp_text = _read(repo_root, "native", "netplane.cpp")
        if cpp_text is None:
            return []
    effects = extract_epoch_effects(cpp_text)
    v: list[Violation] = []
    for name in sorted(effects):
        eff = effects[name]
        line = _entry_line(cpp_text, name)
        declared = ENTRY_EFFECTS.get(name)
        if declared is None:
            v.append(Violation(
                "effect-unclassified", _CPP_REL,
                f"engine entry point `{name}` ({eff['cfunc']}) is not "
                f"classified in analysis/effects.py ENTRY_EFFECTS — "
                f"declare it mutator or observer", line=line))
        elif declared == "mutator" and eff["bump"] != "unconditional":
            how = {"none": "never bumps state_epoch",
                   "conditional": "bumps state_epoch only inside nested "
                                  "braces (some mutating control path "
                                  "returns without bumping)",
                   "missing": "has no findable wrapper body"}[eff["bump"]]
            v.append(Violation(
                "effect-mutator-bump", _CPP_REL,
                f"declared mutator `{name}` ({eff['cfunc']}) {how} — "
                f"device-resident span state would survive the mutation",
                line=line))
        elif declared == "observer" and eff["bump"] != "none":
            via = f" via {eff['via']}" if eff["via"] else ""
            v.append(Violation(
                "effect-observer-bump", _CPP_REL,
                f"declared observer `{name}` ({eff['cfunc']}) bumps "
                f"state_epoch{via} — a read would spuriously invalidate "
                f"device-resident span carries", line=line))
    for name in sorted(set(ENTRY_EFFECTS) - set(effects)):
        v.append(Violation(
            "effect-stale", _CPP_REL,
            f"ENTRY_EFFECTS classifies `{name}` but the method table "
            f"exports no such entry point — delete the stale row"))
    # belt-and-braces drift guard: the pass-3 async-hazard list and
    # this audit's mutator view of the same text must agree exactly
    bumping = {n for n, e in effects.items()
               if e["bump"] in ("unconditional", "conditional")}
    if from_tree:
        from shadow_tpu.analysis.determinism import epoch_mutators
        hazard = epoch_mutators(repo_root)
        if hazard != bumping:
            diff = sorted(hazard.symmetric_difference(bumping))
            v.append(Violation(
                "effect-drift", _CPP_REL,
                f"pass-3 async-hazard mutator list disagrees with the "
                f"pass-4 extraction on: {', '.join(diff)} (the two must "
                f"consume one extraction)"))
    return v


# ---------------------------------------------------------------------------
# 4b: thread-ownership lint
# ---------------------------------------------------------------------------

_MUTATING_METHODS = frozenset({
    "append", "appendleft", "add", "update", "pop", "popleft",
    "popitem", "clear", "extend", "extendleft", "remove", "discard",
    "insert", "setdefault", "put", "put_nowait",
})

# window-closing attribute calls / assignments, same event model as
# pass 3's async-hazard rule (determinism._lint_async_fn)
_FORCE_CALLS = frozenset({"asarray", "block_until_ready"})


def _walk_own(node):
    """Walk a statement without descending into nested function or
    class scopes (those are linted on their own)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                stack.append(child)


def _attr_chain(node):
    """`self.a.b` -> ["self", "a", "b"]; None for non-Name roots."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class _OwnershipLinter:
    """Per-module worker-reachability + speculative-window scan."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.violations: list[Violation] = []
        # every function/method/nested def in the module, by name —
        # reachability is name-based and module-local, which matches
        # how the worker pools are actually fed
        self.defs: dict[str, list] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)

    def flag(self, rule: str, lineno: int, message: str):
        if not _pragma_allows(self.lines, lineno, rule):
            self.violations.append(
                Violation(rule, self.relpath, message, line=lineno))

    # -- worker entry points -----------------------------------------
    def _entry_fns(self):
        """(fn-node, how) for every function handed to a worker."""
        out = []
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            target = None
            how = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("submit", "map") and node.args:
                target = node.args[0]
                how = f".{node.func.attr}()"
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "Thread") or \
                    (isinstance(node.func, ast.Name)
                     and node.func.id == "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                        how = "Thread(target=)"
            if target is None:
                continue
            if isinstance(target, ast.Lambda):
                out.append((target, how))
            else:
                name = None
                if isinstance(target, ast.Name):
                    name = target.id
                elif isinstance(target, ast.Attribute):
                    name = target.attr
                for fn in self.defs.get(name, ()):
                    out.append((fn, how))
        return out

    def _reachable(self, roots):
        """Module-local transitive closure over name-matched calls."""
        seen, work = [], [fn for fn, _ in roots]
        while work:
            fn = work.pop()
            if any(fn is s for s in seen):
                continue
            seen.append(fn)
            if isinstance(fn, ast.Lambda):
                body = [fn.body]
            else:
                body = fn.body
            for stmt in body:
                for n in _walk_own(stmt) if isinstance(stmt, ast.stmt) \
                        else ast.walk(stmt):
                    if not isinstance(n, ast.Call):
                        continue
                    name = None
                    if isinstance(n.func, ast.Name):
                        name = n.func.id
                    elif isinstance(n.func, ast.Attribute) and \
                            isinstance(n.func.value, ast.Name) and \
                            n.func.value.id == "self":
                        name = n.func.attr
                    for cand in self.defs.get(name, ()):
                        work.append(cand)
        return seen

    # -- the ownership scan ------------------------------------------
    @staticmethod
    def _locals_of(fn) -> set:
        if isinstance(fn, ast.Lambda):
            names = {a.arg for a in fn.args.args}
            return names
        names = {a.arg for a in fn.args.args + fn.args.kwonlyargs +
                 fn.args.posonlyargs}
        if fn.args.vararg:
            names.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            names.add(fn.args.kwarg.arg)
        for stmt in fn.body:
            for n in _walk_own(stmt):
                if isinstance(n, (ast.Assign, ast.AugAssign,
                                  ast.AnnAssign)):
                    tgts = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in tgts:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name) and \
                                    isinstance(leaf.ctx, ast.Store):
                                names.add(leaf.id)
                elif isinstance(n, (ast.For,)):
                    for leaf in ast.walk(n.target):
                        if isinstance(leaf, ast.Name):
                            names.add(leaf.id)
                elif isinstance(n, ast.With):
                    for item in n.items:
                        if isinstance(item.optional_vars, ast.Name):
                            names.add(item.optional_vars.id)
                elif isinstance(n, ast.comprehension):
                    for leaf in ast.walk(n.target):
                        if isinstance(leaf, ast.Name):
                            names.add(leaf.id)
        return names

    @staticmethod
    def _is_lock_ctx(item) -> bool:
        try:
            src = ast.unparse(item.context_expr)
        except Exception:  # pragma: no cover - unparse is total on 3.9+
            return False
        return "lock" in src.lower()

    def lint_workers(self):
        entries = self._entry_fns()
        if not entries:
            return
        for fn in self._reachable(entries):
            locals_ = self._locals_of(fn)
            where = getattr(fn, "name", "<lambda>")
            body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
            self._scan_stmts(body, locals_, False, where)

    def _scan_stmts(self, stmts, locals_, in_lock, where):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.With):
                locked = in_lock or any(self._is_lock_ctx(i)
                                        for i in st.items)
                self._scan_stmts(st.body, locals_, locked, where)
                continue
            if isinstance(st, (ast.If, ast.While)):
                if not in_lock:
                    self._scan_expr(st.test, locals_, where)
                self._scan_stmts(st.body, locals_, in_lock, where)
                self._scan_stmts(st.orelse, locals_, in_lock, where)
                continue
            if isinstance(st, ast.For):
                if not in_lock:
                    self._scan_expr(st.iter, locals_, where)
                self._scan_stmts(st.body, locals_, in_lock, where)
                self._scan_stmts(st.orelse, locals_, in_lock, where)
                continue
            if isinstance(st, ast.Try):
                for blk in (st.body, st.orelse, st.finalbody):
                    self._scan_stmts(blk, locals_, in_lock, where)
                for h in st.handlers:
                    self._scan_stmts(h.body, locals_, in_lock, where)
                continue
            if not in_lock:
                self._scan_expr(st, locals_, where)

    def _scan_expr(self, node, locals_, where):
        for n in _walk_own(node) if isinstance(node, ast.stmt) \
                else ast.walk(node):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in tgts:
                    self._check_target(t, locals_, where, n.lineno)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _MUTATING_METHODS:
                chain = _attr_chain(n.func.value)
                if chain and (chain[0] == "self"
                              or chain[0] not in locals_):
                    self.flag(
                        "svc-ownership", n.lineno,
                        f"worker-reachable `{where}` mutates shared "
                        f"`{'.'.join(chain)}.{n.func.attr}(..)` outside "
                        f"a lock — workers own only their host group "
                        f"(host.id % workers)")

    def _check_target(self, t, locals_, where, lineno):
        if isinstance(t, ast.Tuple):
            for el in t.elts:
                self._check_target(el, locals_, where, lineno)
            return
        if isinstance(t, ast.Attribute):
            chain = _attr_chain(t)
            if chain and (chain[0] == "self" or chain[0] not in locals_):
                self.flag(
                    "svc-ownership", lineno,
                    f"worker-reachable `{where}` writes shared "
                    f"`{'.'.join(chain)}` outside a lock — workers own "
                    f"only their host group (host.id % workers)")
        elif isinstance(t, ast.Subscript):
            chain = _attr_chain(t.value)
            if chain and chain[0] != "self" and chain[0] in locals_:
                return
            if chain:
                self.flag(
                    "svc-ownership", lineno,
                    f"worker-reachable `{where}` writes shared "
                    f"`{'.'.join(chain)}[..]` outside a lock — workers "
                    f"own only their host group (host.id % workers)")

    # -- the speculative-window scan ---------------------------------
    def lint_overlap_windows(self):
        for fn in ast.walk(self.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._lint_window_fn(fn)

    def _lint_window_fn(self, fn):
        events = []  # (lineno, col, kind, payload)
        for stmt in fn.body:
            for n in _walk_own(stmt):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute):
                    attr = n.func.attr
                    if attr == "_span_call":
                        events.append((n.lineno, n.col_offset, "open",
                                       None))
                    elif attr in _FORCE_CALLS or attr == "_commit_spec" \
                            or "inflight" in attr:
                        events.append((n.lineno, n.col_offset, "close",
                                       None))
                elif isinstance(n, (ast.Assign, ast.AugAssign)):
                    tgts = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in tgts:
                        chain = _attr_chain(t) \
                            if isinstance(t, ast.Attribute) else None
                        if chain and "inflight" in chain[-1]:
                            events.append((n.lineno, n.col_offset,
                                           "close", None))
                        elif chain and chain[0] == "self" and \
                                len(chain) >= 3:
                            events.append((n.lineno, n.col_offset,
                                           "write", ".".join(chain)))
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _MUTATING_METHODS:
                    chain = _attr_chain(n.func.value)
                    if chain and chain[0] == "self" and len(chain) >= 2:
                        events.append(
                            (n.lineno, n.col_offset, "write",
                             f"{'.'.join(chain)}.{n.func.attr}(..)"))
        events.sort(key=lambda e: (e[0], e[1]))
        open_ = False
        for lineno, _col, kind, payload in events:
            if kind == "open":
                open_ = True
            elif kind == "close":
                open_ = False
            elif kind == "write" and open_:
                self.flag(
                    "overlap-window", lineno,
                    f"`{fn.name}` mutates `{payload}` while a "
                    f"speculative span dispatch is in flight — force "
                    f"the window (np.asarray / block_until_ready) or "
                    f"publish it (_commit_spec / _inflight) first")


def check_thread_ownership(repo_root: str, paths=None) -> list:
    """4b over shadow_tpu/ (or explicit `paths` for self-tests)."""
    violations: list[Violation] = []
    files = paths if paths is not None else iter_py_files(repo_root)
    for path in files:
        rel = os.path.relpath(path, repo_root)
        with open(path) as fh:
            source = fh.read()
        try:
            linter = _OwnershipLinter(rel, source)
        except SyntaxError as exc:
            violations.append(Violation(
                "parse-error", rel, str(exc), line=exc.lineno or 0))
            continue
        linter.lint_workers()
        linter.lint_overlap_windows()
        violations.extend(linter.violations)
    return violations


# ---------------------------------------------------------------------------
# 4c: the knob registry
# ---------------------------------------------------------------------------

# Every `experimental.*` knob, classified for the checkpoint config
# digest (ckpt/restore.py config_digest): "digest" knobs shape
# simulation bytes and stay in the hash; "skip" knobs are wall-side
# routing/observability only and a resume may change them freely.
# The "skip" half is cross-checked against _DIGEST_SKIP_EXPERIMENTAL
# (knob-digest-drift), so neither table can rot alone.
KNOB_DIGEST = {
    "scheduler": "skip",
    "runahead": "digest",
    "use_dynamic_runahead": "digest",
    "interface_qdisc": "digest",
    "socket_send_buffer": "digest",
    "socket_recv_buffer": "digest",
    "socket_send_autotune": "digest",
    "socket_recv_autotune": "digest",
    "strace_logging_mode": "digest",
    "max_unapplied_cpu_latency": "digest",
    "unblocked_syscall_latency": "digest",
    "unblocked_vdso_latency": "digest",
    "host_cpu_threshold": "digest",
    "host_cpu_precision": "digest",
    "host_cpu_event_cost": "digest",
    "native_preemption_enabled": "digest",
    "native_preemption_native_interval": "digest",
    "native_preemption_sim_interval": "digest",
    "native_file_io_bandwidth": "digest",
    "tpu_max_packets_per_round": "skip",
    "tpu_min_device_batch": "skip",
    "tpu_shards": "skip",
    "tpu_exchange_capacity": "skip",
    "native_dataplane": "skip",
    "tpu_device_spans": "skip",
    "tpu_donate_buffers": "skip",
    "span_overlap": "skip",
    "pallas_queue_kernels": "skip",
    "dev_span_k_init": "skip",
    "dev_span_k_floor": "skip",
    "dev_span_k_shrink": "skip",
    "flight_recorder": "digest",
    "sim_netstat": "digest",
    "netstat_interval": "digest",
    "sim_fabricstat": "digest",
    "fabricstat_interval": "digest",
    "chrome_top_n": "skip",
    "syscall_observatory": "digest",
    "kernel_observatory": "digest",
    "syscall_service_plane": "skip",
    "managed_death_poll": "skip",
    "managed_watchdog": "skip",
    "managed_spawn_stagger": "skip",
    "pcap_span_cap": "skip",
    "dctcp_k_pkts": "digest",
    "dctcp_k_bytes": "digest",
    "openssl_crypto_noop": "digest",
    "use_cpu_pinning": "skip",
    "use_perf_timers": "digest",
    "report_errors_to_stderr": "skip",
}

# Knobs that shape WALL behavior only (poll cadences, pinning, stderr
# mirroring): they must be unreachable from the sim-time channel
# classes, whose byte-identity contract admits no wall influence.
WALL_ONLY = frozenset({
    "use_cpu_pinning", "managed_death_poll", "managed_watchdog",
    "managed_spawn_stagger", "report_errors_to_stderr",
})

_CHANNEL_CLASSES = frozenset({
    "SimChannel", "NetstatChannel", "FabricChannel", "KernChannel",
    "FixedRecordChannel", "SyscallChannel", "HostSyscallLog",
})

_CONFIG_REL = os.path.join("shadow_tpu", "core", "config.py")
_RESTORE_REL = os.path.join("shadow_tpu", "ckpt", "restore.py")
_DOCS_REL = os.path.join("docs", "config_spec.md")


def _experimental_yaml_keys(config_text: str) -> dict:
    """{yaml key -> lineno} from to_processed_dict()'s experimental
    dict — the serialization surface, i.e. what actually reaches
    processed-config.yaml and the digest."""
    tree = ast.parse(config_text)
    for fn in ast.walk(tree):
        if isinstance(fn, ast.FunctionDef) and \
                fn.name == "to_processed_dict":
            for n in ast.walk(fn):
                if isinstance(n, ast.Dict):
                    keys = {k.value: k.lineno for k in n.keys
                            if isinstance(k, ast.Constant)}
                    if "scheduler" in keys and "runahead" in keys:
                        return keys
    return {}


def _experimental_fields(config_text: str) -> set:
    """Dataclass attribute names of ExperimentalConfig."""
    tree = ast.parse(config_text)
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef) and \
                cls.name == "ExperimentalConfig":
            return {st.target.id for st in cls.body
                    if isinstance(st, ast.AnnAssign)
                    and isinstance(st.target, ast.Name)}
    return set()


def _loader_map(config_text: str) -> dict:
    """{yaml key -> attr} from from_dict's (yaml, attr, conv) rows —
    a row is what makes a knob loadable AND validated (the conv)."""
    fields = _experimental_fields(config_text)
    tree = ast.parse(config_text)
    out: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, ast.FunctionDef) and fn.name == "from_dict":
            for n in ast.walk(fn):
                if isinstance(n, ast.Tuple) and len(n.elts) == 3 and \
                        isinstance(n.elts[0], ast.Constant) and \
                        isinstance(n.elts[1], ast.Constant) and \
                        n.elts[1].value in fields:
                    out[n.elts[0].value] = n.elts[1].value
    return out


def _digest_skip_tuple(restore_text: str):
    """(set of yaml keys, lineno) of _DIGEST_SKIP_EXPERIMENTAL."""
    tree = ast.parse(restore_text)
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                n.targets[0].id == "_DIGEST_SKIP_EXPERIMENTAL" and \
                isinstance(n.value, (ast.Tuple, ast.List)):
            return ({e.value for e in n.value.elts
                     if isinstance(e, ast.Constant)}, n.lineno)
    return None, 0


def _documented_tokens(docs_text: str):
    """(exact tokens, `_`-suffix tokens, heading lineno) from the
    experimental table's first column.  Combined rows list several
    backticked keys; shorthand like `` `_sim_interval` `` documents
    any key ending in that suffix."""
    exact, suffixes = set(), set()
    lines = docs_text.splitlines()
    start = None
    for i, line in enumerate(lines):
        if re.match(r"##\s+`?experimental`?\s*$", line):
            start = i
            break
    if start is None:
        return exact, suffixes, 0
    for line in lines[start + 1:]:
        if line.startswith("## "):
            break
        if not line.startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 2:
            continue
        for tok in re.findall(r"`([\w.]+)`", cells[1]):
            (suffixes if tok.startswith("_") else exact).add(tok)
    return exact, suffixes, start + 1


def _wall_knob_channel_hits(repo_root: str, attr_names: set,
                            channel_paths=None):
    """(relpath, lineno, attr) for wall-only knob attribute reads
    inside sim-time channel class bodies."""
    hits = []
    files = channel_paths if channel_paths is not None \
        else iter_py_files(repo_root)
    for path in files:
        rel = os.path.relpath(path, repo_root)
        with open(path) as fh:
            source = fh.read()
        if not any(a in source for a in attr_names):
            continue
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError:
            continue
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            base_names = {b.id for b in cls.bases
                          if isinstance(b, ast.Name)} | \
                         {b.attr for b in cls.bases
                          if isinstance(b, ast.Attribute)}
            if cls.name not in _CHANNEL_CLASSES and \
                    not base_names & _CHANNEL_CLASSES:
                continue
            for n in ast.walk(cls):
                if isinstance(n, ast.Attribute) and \
                        n.attr in attr_names:
                    hits.append((rel, n.lineno, n.attr))
    return hits


def check_knob_registry(repo_root: str, config_text=None,
                        restore_text=None, docs_text=None,
                        channel_paths=None) -> list:
    """4c.  Text overrides inject perturbed surfaces for self-tests."""
    if config_text is None:
        config_text = _read(repo_root, "shadow_tpu", "core", "config.py")
        if config_text is None:
            return []
    if restore_text is None:
        restore_text = _read(repo_root, "shadow_tpu", "ckpt",
                             "restore.py")
    if docs_text is None:
        docs_text = _read(repo_root, "docs", "config_spec.md")

    v: list[Violation] = []
    yaml_keys = _experimental_yaml_keys(config_text)
    loader = _loader_map(config_text)

    for key in sorted(yaml_keys):
        line = yaml_keys[key]
        if key not in KNOB_DIGEST:
            v.append(Violation(
                "knob-unregistered", _CONFIG_REL,
                f"experimental knob `{key}` has no digest "
                f"classification in analysis/effects.py KNOB_DIGEST — "
                f"declare it \"digest\" or \"skip\"", line=line))
        if key not in loader:
            v.append(Violation(
                "knob-unloadable", _CONFIG_REL,
                f"experimental knob `{key}` is serialized by "
                f"to_processed_dict but has no from_dict "
                f"(yaml, attr, conv) row — it cannot be loaded or "
                f"validated", line=line))
    for key in sorted(set(KNOB_DIGEST) - set(yaml_keys)):
        v.append(Violation(
            "knob-stale", _CONFIG_REL,
            f"KNOB_DIGEST classifies `{key}` but to_processed_dict "
            f"serializes no such experimental knob — delete the stale "
            f"row"))

    if docs_text is not None:
        exact, suffixes, heading = _documented_tokens(docs_text)
        for key in sorted(yaml_keys):
            if key in exact or any(key.endswith(s) for s in suffixes):
                continue
            v.append(Violation(
                "knob-undocumented", _DOCS_REL,
                f"experimental knob `{key}` has no row in the "
                f"`## experimental` table", line=heading))

    if restore_text is not None:
        skip_tuple, line = _digest_skip_tuple(restore_text)
        if skip_tuple is not None:
            declared_skip = {k for k, kind in KNOB_DIGEST.items()
                             if kind == "skip"}
            if skip_tuple != declared_skip:
                only_restore = sorted(skip_tuple - declared_skip)
                only_registry = sorted(declared_skip - skip_tuple)
                detail = []
                if only_restore:
                    detail.append("only in _DIGEST_SKIP_EXPERIMENTAL: "
                                  + ", ".join(only_restore))
                if only_registry:
                    detail.append("only in KNOB_DIGEST: "
                                  + ", ".join(only_registry))
                v.append(Violation(
                    "knob-digest-drift", _RESTORE_REL,
                    "_DIGEST_SKIP_EXPERIMENTAL and KNOB_DIGEST's "
                    "\"skip\" set disagree (" + "; ".join(detail) + ")",
                    line=line))

    wall_attrs = {loader.get(k, k) for k in WALL_ONLY} | WALL_ONLY
    for rel, lineno, attr in _wall_knob_channel_hits(
            repo_root, wall_attrs, channel_paths=channel_paths):
        v.append(Violation(
            "knob-wall-in-channel", rel,
            f"wall-only knob `{attr}` read inside a sim-time channel "
            f"class — wall knobs must never reach channel bytes",
            line=lineno))
    return v


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def check(repo_root: str, cpp_text=None, paths=None, config_text=None,
          restore_text=None, docs_text=None, channel_paths=None) -> list:
    """Run all three sub-passes; keyword overrides inject in-memory
    surfaces for the mutation self-tests (tests/test_effects.py)."""
    return (check_engine_effects(repo_root, cpp_text=cpp_text)
            + check_thread_ownership(repo_root, paths=paths)
            + check_knob_registry(repo_root, config_text=config_text,
                                  restore_text=restore_text,
                                  docs_text=docs_text,
                                  channel_paths=channel_paths))
