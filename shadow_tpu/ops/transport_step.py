"""SoA transport stepping on device — the receive-chain kernel.

The north star (SURVEY.md §7.6) is transport state as struct-of-arrays
stepped by vectorized JAX functions.  This module is the beachhead: the
per-host *receive chain* — router CoDel AQM (RFC 8289) followed by the
inet-in token-bucket relay (download bandwidth) — stepped for a whole
batch of arrivals across all hosts in one `vmap(lax.scan)` program.

Semantics are extracted, instant for instant, from the object path
(net/codel.py `CoDelQueue.pop`, net/token_bucket.py, net/relay.py
`Relay._forward_until_blocked`; ref codel_queue.rs:65-303,
token_bucket.rs, relay/mod.rs:201-273):

 - the relay loop runs at discrete activation instants (an arrival when
   idle, a refill wakeup when a packet is parked); every CoDel pop in
   one activation shares that activation's `now`;
 - packet i's pop instant is `max(e_i, f_{i-1})` where `f_{i-1}` is the
   instant the previous packet finished (forwarded or dropped);
 - whenever the queue drains (`e_i > f_{i-1}`), the empty-dequeue reset
   fires (`first_above = 0`, `dropping = False`);
 - CoDel's drop machine is per-dequeue: a three-phase automaton (fresh
   pop / inside the drop-while-loop / the dequeue following an entry
   drop) carried packet-to-packet;
 - a forwarded packet conforms to the token bucket at its pop instant
   or at the first refill boundary with enough balance (closed form of
   the park/wakeup loop; capacity >= MTU guarantees convergence).

`receive_chain_scalar` is the Python-int twin; `build_receive_chain`
returns the jitted device program producing bit-identical integers.
The object path stays authoritative for the simulator until the
integration flips; differential tests drive all three against each
other (tests/test_transport_step.py).

Known contract bounds (callers must respect):
 - arrivals are presented FIFO (sorted by enqueue instant per host);
 - the CoDel hard limit (1000 queued) is NOT modeled — callers check
   the returned pop instants for occupancy and fall back to the object
   path for saturated hosts;
 - batch boundaries must be *drain points*: every arrival in batch N+1
   must be strictly later than every pop/forward instant of batch N
   (i.e. the queue emptied and the relay went idle in between).  CoDel's
   queued-bytes test looks across the whole queue, so a pop that would
   interleave with later-batch arrivals needs those arrivals in the
   same batch.  Callers detect a non-drained host (`state.f_prev >=`
   the next batch's first arrival) and either merge batches or fall
   back to the object path for it.
"""

from __future__ import annotations

from math import isqrt

from shadow_tpu.net.codel import INTERVAL_NS, TARGET_NS
from shadow_tpu.net.packet import MTU

PHASE_FRESH = 0
PHASE_INLOOP = 1   # inside pop()'s drop-while-loop
PHASE_ENTER2 = 2   # the dequeue right after an entry drop


def _control_time(t: int, count: int) -> int:
    """next drop time = t + INTERVAL / sqrt(count) (integer ns)."""
    return t + (INTERVAL_NS << 16) // isqrt(count << 32)


class ChainState:
    """Per-host receive-chain state carried between batches."""

    __slots__ = ("f_prev", "phase", "dropping", "count", "last_count",
                 "first_above", "drop_next", "balance", "next_refill",
                 "capacity", "refill_size", "refill_interval")

    def __init__(self, capacity: int, refill_size: int,
                 refill_interval: int):
        self.f_prev = 0
        self.phase = PHASE_FRESH
        self.dropping = False
        self.count = 0
        self.last_count = 0
        self.first_above = 0
        self.drop_next = 0
        self.balance = capacity
        self.next_refill = 0
        self.capacity = capacity
        self.refill_size = refill_size
        self.refill_interval = refill_interval


def receive_chain_scalar(state: ChainState, arrivals, sizes):
    """Step one batch through CoDel + token bucket for one host.

    arrivals: enqueue instants, sorted ascending; sizes: packet bytes.
    Returns (dropped, fwd_time, pop_now) lists; mutates `state`.
    """
    n = len(arrivals)
    prefix = [0] * (n + 1)
    for i, s in enumerate(sizes):
        prefix[i + 1] = prefix[i] + s

    dropped = [False] * n
    fwd = [0] * n
    pops = [0] * n

    for i in range(n):
        e, size = arrivals[i], sizes[i]
        pop_now = e if e > state.f_prev else state.f_prev
        if e > state.f_prev:
            # Queue drained since the previous packet: empty-dequeue
            # reset (codel.py _dequeue_raw empty branch + pop()).
            state.first_above = 0
            state.dropping = False
            state.phase = PHASE_FRESH
        pops[i] = pop_now

        # _dequeue_raw(pop_now) for this packet.
        # Bytes still queued after removing it: arrivals j>i with
        # e_j <= pop_now.
        hi = i + 1
        while hi < n and arrivals[hi] <= pop_now:
            hi += 1
        bytes_after = prefix[hi] - prefix[i + 1]
        sojourn = pop_now - e
        if sojourn < TARGET_NS or bytes_after <= MTU:
            state.first_above = 0
            ok = False
        elif state.first_above == 0:
            state.first_above = pop_now + INTERVAL_NS
            ok = False
        else:
            ok = pop_now >= state.first_above

        # pop()'s drop machine, one dequeue at a time.
        drop = False
        phase = state.phase
        if phase == PHASE_FRESH:
            if state.dropping:
                if not ok:
                    state.dropping = False
                elif pop_now >= state.drop_next:
                    drop = True
                    state.count += 1
                    state.phase = PHASE_INLOOP
            elif ok and (pop_now - state.drop_next < INTERVAL_NS or
                         pop_now - state.first_above >= INTERVAL_NS):
                drop = True
                state.phase = PHASE_ENTER2
        elif phase == PHASE_INLOOP:
            if not ok:
                state.dropping = False
            else:
                state.drop_next = _control_time(state.drop_next,
                                                state.count)
                if pop_now >= state.drop_next:
                    drop = True
                    state.count += 1
                    state.phase = PHASE_INLOOP
        else:  # PHASE_ENTER2
            state.dropping = True
            if pop_now - state.drop_next < INTERVAL_NS:
                state.count = (state.count - state.last_count
                               if state.count > 2 else 1)
            else:
                state.count = 1
            state.last_count = state.count
            state.drop_next = _control_time(pop_now, state.count)

        if drop:
            dropped[i] = True
            state.f_prev = pop_now
            continue
        state.phase = PHASE_FRESH

        # Token bucket (token_bucket.py _advance/try_remove + the
        # relay's park/wakeup loop, in closed form).
        if state.next_refill == 0:
            state.next_refill = pop_now + state.refill_interval
        elif pop_now >= state.next_refill:
            k = 1 + (pop_now - state.next_refill) // state.refill_interval
            state.balance = min(state.capacity,
                                state.balance + k * state.refill_size)
            state.next_refill += k * state.refill_interval
        if size <= state.balance:
            state.balance -= size
            t_fwd = pop_now
        else:
            need = size - state.balance
            k = -(-need // state.refill_size)  # ceil
            t_fwd = state.next_refill + (k - 1) * state.refill_interval
            state.balance = min(state.capacity,
                                state.balance + k * state.refill_size) \
                - size
            state.next_refill += k * state.refill_interval
        fwd[i] = t_fwd
        state.f_prev = t_fwd

    return dropped, fwd, pops


def build_receive_chain(max_slots: int):
    """Jitted device program: step `max_slots` arrival slots for H hosts.

    Inputs (int64 unless noted):
      e[H,S] sorted arrival instants (TIME_NEVER-padded), size[H,S],
      valid[H,S] bool, plus the ChainState arrays (f_prev, phase,
      dropping, count, last_count, first_above, drop_next, balance,
      next_refill)[H] and bucket config (capacity, refill_size,
      refill_interval)[H].

    Returns (dropped[H,S] bool, fwd[H,S], pop[H,S], new state tuple) —
    bit-identical to receive_chain_scalar.
    """
    import jax
    import jax.numpy as jnp

    target = jnp.int64(TARGET_NS)
    interval = jnp.int64(INTERVAL_NS)
    mtu = jnp.int64(MTU)

    def _isqrt(x):
        """Exact floor-sqrt for 0 < x < 2^52 in integer ops (the CPU
        twin uses math.isqrt; the control law must match bit-for-bit)."""
        g = jnp.maximum(
            jnp.int64(1),
            jnp.sqrt(x.astype(jnp.float32)).astype(jnp.int64))
        for _ in range(4):
            g = (g + x // g) >> 1
        g = jnp.where(g * g > x, g - 1, g)
        g = jnp.where((g + 1) * (g + 1) <= x, g + 1, g)
        g = jnp.where(g * g > x, g - 1, g)
        return g

    def _control(t, count):
        # count is clamped: the FRESH branch computes this speculatively
        # even when count==0, and integer division by zero is undefined
        # per XLA backend.
        return t + (interval << 16) // _isqrt(
            jnp.maximum(count, 1) << 32)

    def host_scan(e, size, valid, f_prev, phase, dropping, count,
                  last_count, first_above, drop_next, balance,
                  next_refill, capacity, refill_size, refill_interval):
        prefix = jnp.concatenate(
            [jnp.zeros((1,), jnp.int64),
             jnp.cumsum(jnp.where(valid, size, 0))])

        def step(carry, xs):
            (f_prev, phase, dropping, count, last_count, first_above,
             drop_next, balance, next_refill) = carry
            e_i, size_i, valid_i, i = xs

            fresh_arrival = e_i > f_prev
            pop_now = jnp.maximum(e_i, f_prev)
            first_above = jnp.where(fresh_arrival, 0, first_above)
            dropping = jnp.where(fresh_arrival, False, dropping)
            phase = jnp.where(fresh_arrival, PHASE_FRESH, phase)

            hi = jnp.searchsorted(e, pop_now, side="right")
            bytes_after = prefix[hi] - prefix[i + 1]
            sojourn = pop_now - e_i
            below = (sojourn < target) | (bytes_after <= mtu)
            fa_zero = first_above == 0
            first_above = jnp.where(
                below, 0,
                jnp.where(fa_zero, pop_now + interval, first_above))
            ok = jnp.logical_not(below) & jnp.logical_not(fa_zero) \
                & (pop_now >= first_above)

            # Drop machine.
            is_fresh = phase == PHASE_FRESH
            is_inloop = phase == PHASE_INLOOP
            is_enter2 = phase == PHASE_ENTER2

            # FRESH
            fresh_drop = jnp.where(
                dropping,
                ok & (pop_now >= drop_next),
                ok & ((pop_now - drop_next < interval) |
                      (pop_now - first_above >= interval)))
            fresh_phase = jnp.where(
                fresh_drop,
                jnp.where(dropping, PHASE_INLOOP, PHASE_ENTER2),
                PHASE_FRESH)
            fresh_dropping = jnp.where(dropping & jnp.logical_not(ok),
                                       False, dropping)
            fresh_count = jnp.where(dropping & fresh_drop, count + 1,
                                    count)

            # INLOOP
            in_dn = _control(drop_next, count)
            in_drop = ok & (pop_now >= in_dn)
            in_dropping = jnp.where(jnp.logical_not(ok), False, dropping)
            in_count = jnp.where(in_drop, count + 1, count)
            in_drop_next = jnp.where(ok, in_dn, drop_next)

            # ENTER2
            en_count = jnp.where(
                pop_now - drop_next < interval,
                jnp.where(count > 2, count - last_count, 1),
                jnp.int64(1))
            en_drop_next = _control(pop_now, en_count)

            drop = jnp.where(is_fresh, fresh_drop,
                             jnp.where(is_inloop, in_drop, False))
            count = jnp.where(is_fresh, fresh_count,
                              jnp.where(is_inloop, in_count, en_count))
            last_count = jnp.where(is_enter2, en_count, last_count)
            drop_next = jnp.where(is_fresh, drop_next,
                                  jnp.where(is_inloop, in_drop_next,
                                            en_drop_next))
            dropping = jnp.where(is_fresh, fresh_dropping,
                                 jnp.where(is_inloop, in_dropping, True))
            phase = jnp.where(is_fresh, fresh_phase,
                              jnp.where(is_inloop,
                                        jnp.where(in_drop, PHASE_INLOOP,
                                                  PHASE_FRESH),
                                        PHASE_FRESH))

            # Token bucket for forwarded packets.
            anchor = next_refill == 0
            adv = jnp.logical_not(anchor) & (pop_now >= next_refill)
            k_adv = jnp.where(
                adv, 1 + (pop_now - next_refill) // refill_interval, 0)
            balance_adv = jnp.where(
                adv,
                jnp.minimum(capacity, balance + k_adv * refill_size),
                balance)
            next_refill_adv = jnp.where(
                anchor, pop_now + refill_interval,
                next_refill + k_adv * refill_interval)

            conforms = size_i <= balance_adv
            need = size_i - balance_adv
            k = jnp.where(conforms, 0,
                          -((-need) // refill_size))  # ceil for need>0
            t_fwd = jnp.where(
                conforms, pop_now,
                next_refill_adv + (k - 1) * refill_interval)
            balance_fwd = jnp.where(
                conforms, balance_adv - size_i,
                jnp.minimum(capacity, balance_adv + k * refill_size)
                - size_i)
            next_refill_fwd = next_refill_adv + k * refill_interval

            fwd_taken = valid_i & jnp.logical_not(drop)
            balance = jnp.where(fwd_taken, balance_fwd, balance)
            next_refill = jnp.where(fwd_taken, next_refill_fwd,
                                    next_refill)
            phase = jnp.where(fwd_taken, PHASE_FRESH, phase)
            f_prev_new = jnp.where(fwd_taken, t_fwd, pop_now)

            # Padding slots: pass everything through untouched.
            def keep(new, old):
                return jnp.where(valid_i, new, old)

            carry_out = (keep(f_prev_new, f_prev), keep(phase, carry[1]),
                         keep(dropping, carry[2]), keep(count, carry[3]),
                         keep(last_count, carry[4]),
                         keep(first_above, carry[5]),
                         keep(drop_next, carry[6]),
                         keep(balance, carry[7]),
                         keep(next_refill, carry[8]))
            out = (valid_i & drop,
                   jnp.where(fwd_taken, t_fwd, 0),
                   jnp.where(valid_i, pop_now, 0))
            return carry_out, out

        idx = jnp.arange(e.shape[0], dtype=jnp.int64)
        carry0 = (f_prev, phase, dropping, count, last_count,
                  first_above, drop_next, balance, next_refill)
        carry, (dropped, fwd, pops) = jax.lax.scan(
            step, carry0, (e, size, valid, idx))
        return dropped, fwd, pops, carry

    vmapped = jax.vmap(host_scan)

    @jax.jit
    def program(e, size, valid, state, bucket_cfg):
        (f_prev, phase, dropping, count, last_count, first_above,
         drop_next, balance, next_refill) = state
        capacity, refill_size, refill_interval = bucket_cfg
        return vmapped(e, size, valid, f_prev, phase, dropping, count,
                       last_count, first_above, drop_next, balance,
                       next_refill, capacity, refill_size,
                       refill_interval)

    return program
