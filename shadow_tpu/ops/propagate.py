"""Batched cross-host packet propagation — the TPU data path.

This is the north-star kernel (SURVEY.md section 3.4): the reference
walks every in-flight packet through `Worker::send_packet` — a scalar,
lock-per-push path doing a latency lookup, a sequential-RNG loss draw,
and a clamp (src/main/core/worker.rs:324-397). Here a whole round's
packets, across *all* hosts, become one jitted XLA program:

    latency  = L[src_node, dst_node]          # vectorized gather
    bits     = threefry2x32(key, (src_host, packet_seq))
    drop     = bits < T[src_node, dst_node]   # counter-based, order-free
    deliver  = max(t_send + latency, window_end)
    barrier  = min(deliver | keep)            # feeds the round reduction

Shapes are padded to power-of-two buckets so XLA compiles a handful of
programs total; `window_end`/`bootstrap_end` ride as dynamic scalars.
Byte-identical to the scalar path by construction: same integer latency
matrix, same integer thresholds, same threefry bits (tests/test_parity).

Multi-device sharding of the host dimension (ops sharded over a Mesh,
`lax.pmin` barrier) layers on top in shadow_tpu/parallel/.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.event import Event, KIND_PACKET
from shadow_tpu.core.rng import STREAM_PACKET_LOSS, mix_key, threefry2x32_jax
from shadow_tpu.core.simtime import TIME_NEVER
from shadow_tpu.net import packet as pktmod

_I64_MAX = (1 << 63) - 1
_MIN_BUCKET = 256

# DeviceRouteModel.decide() outcomes.
ROUTE_HOST = 0    # run the bit-identical host/numpy (or C++ twin) path
ROUTE_DEVICE = 1  # dispatch on device: measured and winning (or forced)
ROUTE_PROBE = 2   # host path serves the round; measure the device OFF
#                   the critical path (async) to keep the model honest


def _export_native_packet(plane, pkt_id: int):
    """Materialize an engine packet as a Python Packet (mixed-plane
    delivery to an object-path host) and free the native slot."""
    (src_host, seq, proto, src_ip, sport, dst_ip, dport, payload,
     ecn, tcp) = plane.engine.packet_fields(pkt_id)
    hdr = None
    if tcp is not None:
        tseq, ack, flags, window, wscale, mss, sacks, ts_val, \
            ts_ecr = tcp
        hdr = pktmod.TcpHeader(
            seq=tseq, ack=ack, flags=flags, window=window,
            window_scale=None if wscale < 0 else wscale,
            mss=None if mss < 0 else mss, sack_blocks=tuple(sacks),
            timestamp=ts_val, timestamp_echo=ts_ecr)
    p = pktmod.Packet(src_host, seq, proto, src_ip, sport, dst_ip, dport,
                      payload=payload, tcp=hdr)
    p.priority = seq
    p.ecn = ecn  # ECT/CE survives the cross-plane seam
    plane.engine.free_packet(pkt_id)
    return p


def _intern_python_packet(plane, p) -> int:
    """Opposite direction: object-path packet delivered to an engine
    host becomes a native store entry."""
    tcp = None
    if p.tcp is not None:
        h = p.tcp
        tcp = (h.seq, h.ack, h.flags, h.window,
               -1 if h.window_scale is None else h.window_scale,
               -1 if h.mss is None else h.mss, tuple(h.sack_blocks),
               h.timestamp or 0, h.timestamp_echo or 0)
    return plane.engine.intern_packet(
        p.src_host_id, p.seq, p.protocol, p.src_ip, p.src_port, p.dst_ip,
        p.dst_port, p.payload, p.ecn, tcp)


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def deliver_to_host(dst_host, t: int, src_id: int, seq: int, pkt) -> None:
    """Deliver a kept object-path packet to its destination on either
    plane: engine hosts get the packet interned into the native store
    and pushed into the engine inbox; object-path hosts get a Python
    packet event.  The single definition keeps the byte-identical-trace
    contract in one place."""
    if dst_host.plane is not None:
        pid = _intern_python_packet(dst_host.plane, pkt)
        dst_host.plane.engine.push_inbox(dst_host.id, t, src_id, seq, pid)
    else:
        pkt.arrival_time = t
        dst_host.deliver_packet_event(Event(t, KIND_PACKET, src_id, seq, pkt))


def deliver_engine_exports(hosts, exports) -> None:
    """Engine-origin packets whose destination host runs the object
    path (mixed sims): materialize and deliver as Python events."""
    for pkt_id, dst, evt_seq, t, src in exports:
        plane = hosts[src].plane
        p = _export_native_packet(plane, pkt_id)
        p.arrival_time = t
        hosts[dst].deliver_packet_event(Event(t, KIND_PACKET, src,
                                              evt_seq, p))


class DeviceRouteModel:
    """Online device-vs-host dispatch routing.

    Both paths produce bit-identical decisions (same integer matrices,
    same threefry bits), so routing is purely a performance choice —
    and device latency varies wildly between a local chip and a
    tunnelled one, so measure, don't guess.  EWMA ns/packet for the
    host path, EWMA ns/dispatch per bucket size for the device; when
    the device is losing at a size, re-probe with exponential backoff
    (a catastrophic loss jumps straight to the cap: over a tunnel every
    probe costs a ~100ms round trip).
    """

    # Initial re-probe cadence at a bucket size the model routes to the
    # host path (keeps the model honest if device latency improves
    # mid-run, e.g. a tunnel warming up).
    REPROBE_EVERY = 64
    REPROBE_CAP = 4096
    # Measurement overhead cap: probes may consume at most this fraction
    # of elapsed wall.  A local chip (~100µs/dispatch) probes freely; a
    # ~0.66s tunnelled dispatch waits until the run has earned it —
    # a short benchmark run never pays a probe at all.
    PROBE_BUDGET_FRAC = 0.01

    def __init__(self, min_device_batch: int, kind: str = "single"):
        import time as _time
        self.min_device_batch = min_device_batch
        self._t_start_ns = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
        self.probe_spent_ns = 0.0
        # Dispatch kind for the process-wide floor: a sharded SPMD
        # step's time (all_to_all included) is not comparable to a
        # single-chip dispatch, so floors share only within a kind.
        self.kind = kind
        self.host_ns_per_pkt: float | None = None
        self._dev_ns_by_bucket: dict[int, float] = {}
        self._probe_countdown: dict[int, int] = {}
        self._probe_interval: dict[int, int] = {}
        self._compiled: set[int] = set()
        # Smallest measured device dispatch time at ANY bucket: the
        # round-trip floor (tunnel RTT, driver overhead) is bucket-
        # independent, so one catastrophic probe teaches us about all
        # sizes — without this, every bucket pays its own ~RTT probe.
        self.dev_floor_ns: float | None = None

    # The floor is a property of the PLATFORM (per dispatch kind), not
    # of one simulation: share it across model instances so a warm
    # process (bench trials, repeated sims) stops re-paying the
    # discovery probe — and persist it across PROCESSES (keyed by the
    # jax platform) so fresh runs start informed.  Routing never
    # affects traces (both paths are bit-identical); it only moves
    # perf and the audit counters, and a stale persisted floor
    # self-corrects: unmeasured buckets re-probe on the normal backoff
    # cadence.  Tests reset this (conftest) so audit assertions stay
    # order-independent.
    _shared_floor: dict = {}
    _persist_loaded = False
    _persist_disabled = False

    @staticmethod
    def _persist_path() -> str:
        import os
        base = os.environ.get("XDG_CACHE_HOME",
                              os.path.expanduser("~/.cache"))
        return os.path.join(base, "shadow_tpu", "route_floor.json")

    @staticmethod
    def _platform() -> str:
        try:
            import jax
            return jax.devices()[0].platform
        except Exception:
            return "unknown"

    @classmethod
    def _load_persisted(cls) -> None:
        if cls._persist_loaded:
            return
        cls._persist_loaded = True
        import json
        import os
        try:
            with open(cls._persist_path()) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        plat = data.get(cls._platform())
        if isinstance(plat, dict):
            for kind, ns in plat.items():
                if isinstance(ns, (int, float)) and ns > 0 \
                        and kind not in cls._shared_floor:
                    cls._shared_floor[kind] = float(ns)

    @classmethod
    def _persist(cls) -> None:
        if cls._persist_disabled:
            return  # tests must not clobber the user's real cache
        import json
        import os
        path = cls._persist_path()
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                with open(path) as f:
                    data = json.load(f)
            except (OSError, ValueError):
                data = {}
            # Merge per-kind minimum with what is already on disk: the
            # in-memory dict may hold only a subset of kinds (forced-
            # device paths skip the load), and a wholesale write would
            # drop the rest.
            plat = data.get(cls._platform())
            merged = dict(plat) if isinstance(plat, dict) else {}
            for kind, ns in cls._shared_floor.items():
                prev = merged.get(kind)
                if not isinstance(prev, (int, float)) or ns < prev:
                    merged[kind] = ns
            data[cls._platform()] = merged
            tmp = f"{path}.{os.getpid()}.tmp"  # unique per writer
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
        except OSError:
            pass  # read-only home: in-process sharing still works

    @classmethod
    def reset_shared(cls) -> None:
        cls._shared_floor.clear()
        cls._persist_loaded = True   # tests: no disk reads...
        cls._persist_disabled = True  # ...and no disk writes

    def decide(self, n: int, b: int) -> int:
        """Routing choice for a round of n packets at bucket size b.
        Probe order: host first (cheap, bounded ~µs/packet — also the
        only way to ever measure it when all rounds are large), then
        device, then compare.

        ROUTE_DEVICE is returned only when the device is *measured* and
        winning (or forced); any dispatch whose purpose is measurement
        comes back as ROUTE_PROBE so the caller can take it off the
        critical path — through a ~100ms tunnel a single synchronous
        probe inside the measured window costs more than whole rounds
        of host-path work (VERDICT r4 weak #1)."""
        if self.min_device_batch <= 0:
            return ROUTE_DEVICE  # forced-device mode (parity, audits)
        if n < self.min_device_batch:
            return ROUTE_HOST
        if self.host_ns_per_pkt is None:
            return ROUTE_HOST  # host probe
        dev = self._dev_ns_by_bucket.get(b)
        if dev is None:
            # Unmeasured bucket: only probe when even the cross-bucket
            # dispatch FLOOR could win at this round size — through a
            # ~100ms tunnel that one check saves a probe per bucket.
            floor = self.dev_floor_ns
            if floor is None:
                DeviceRouteModel._load_persisted()
                floor = DeviceRouteModel._shared_floor.get(self.kind)
            if floor is not None and floor > self.host_ns_per_pkt * n:
                dev = floor  # treat as losing; fall into backoff below
            elif self._probe_allowed(floor):
                return ROUTE_PROBE
            else:
                return ROUTE_HOST
        if dev <= self.host_ns_per_pkt * n:
            # Winning: fully reset the backoff (interval AND countdown —
            # a stale countdown would defer the next losing-side probe
            # by thousands of rounds).
            self._probe_interval.pop(b, None)
            self._probe_countdown.pop(b, None)
            return ROUTE_DEVICE
        # Device currently losing at this size: re-probe with backoff.
        interval = self._probe_interval.get(b, self.REPROBE_EVERY)
        left = self._probe_countdown.get(b, interval) - 1
        if left <= 0:
            if not self._probe_allowed(dev):
                # Over budget: stay on the host path and ask again a
                # full interval from now (the budget grows with wall).
                self._probe_countdown[b] = interval
                return ROUTE_HOST
            # Ask again next round unless a probe actually starts —
            # the backoff advances in probe_started(), so a declined
            # probe (one already in flight) cannot rail the interval
            # to the cap with zero measurements taken.
            self._probe_countdown[b] = 1
            return ROUTE_PROBE
        self._probe_countdown[b] = left
        return ROUTE_HOST

    def probe_started(self, b: int, n: int) -> None:
        """A probe for bucket b was actually submitted: advance the
        re-probe backoff (decide() leaves it untouched so declined
        probes retry immediately instead of doubling toward the cap)."""
        dev = self._dev_ns_by_bucket.get(b)
        host = self.host_ns_per_pkt
        interval = self._probe_interval.get(b, self.REPROBE_EVERY)
        nxt = (self.REPROBE_CAP
               if dev is not None and host is not None
               and dev > 16 * host * n
               else min(interval * 2, self.REPROBE_CAP))
        self._probe_interval[b] = nxt
        self._probe_countdown[b] = nxt

    def _probe_allowed(self, expected_ns: float | None) -> bool:
        """Cap measurement overhead at PROBE_BUDGET_FRAC of elapsed
        wall.  An expected cost of None (nothing known about this
        platform yet) counts as free: the first probe must happen or
        the model can never learn."""
        import time as _time
        elapsed = _time.perf_counter_ns() - self._t_start_ns  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
        budget = elapsed * self.PROBE_BUDGET_FRAC
        return self.probe_spent_ns + (expected_ns or 0.0) <= budget

    def use_device(self, n: int, b: int) -> bool:
        """Synchronous-dispatch view of decide() for callers without an
        async probe path (the sharded MeshPropagator): probes dispatch
        inline, exactly the pre-round-5 behavior."""
        return self.decide(n, b) != ROUTE_HOST

    def device_measured_winning(self, n: int) -> bool:
        """Has this model MEASURED the device beating the host path at
        round size n?  The propagators' span gate: a measured-winning
        accelerator must keep getting per-round dispatches instead of
        being silently preempted by the host twin."""
        if not n or self.host_ns_per_pkt is None:
            return False
        dev = self._dev_ns_by_bucket.get(_bucket(n))
        return dev is not None and dev <= self.host_ns_per_pkt * n

    def record_device(self, b: int, dt_ns: float, n: int,
                      fresh_compile: bool | None = None) -> None:
        """Record a measured device dispatch.  A dispatch that paid a
        one-time XLA compile must not be recorded — it would poison the
        estimate for thousands of rounds.  By default that is detected
        by the first-use of bucket `b`; callers whose compiled shapes
        are NOT keyed by `b` (the sharded step compiles per chunk
        bucket) pass `fresh_compile` explicitly."""
        if fresh_compile is None:
            fresh_compile = b not in self._compiled
        if b not in self._compiled:
            self._compiled.add(b)
        if fresh_compile:
            # A compile is pure measurement cost — debit the probe
            # budget (it is the most expensive probe there is) but
            # record no estimate.
            self.probe_spent_ns += dt_ns
            return
        if self.dev_floor_ns is None or dt_ns < self.dev_floor_ns:
            self.dev_floor_ns = dt_ns
        shared = DeviceRouteModel._shared_floor
        prev = shared.get(self.kind)
        if prev is None or dt_ns < prev:
            shared[self.kind] = dt_ns
            DeviceRouteModel._persist()
        prev = self._dev_ns_by_bucket.get(b)
        host = self.host_ns_per_pkt
        if prev is None or (host is not None and prev > host * n):
            # First real sample, or a re-probe while routed away from
            # the device: trust the fresh measurement over the stale
            # average so recovery is immediate.
            self._dev_ns_by_bucket[b] = dt_ns
        else:
            self._dev_ns_by_bucket[b] = 0.7 * prev + 0.3 * dt_ns
        # A dispatch that loses to the host path was by definition a
        # measurement, whoever made it (async worker or a sync caller
        # like the sharded backend) — debit the probe budget so the
        # 1%-of-wall cap closes for every probing path.
        if host is not None and self._dev_ns_by_bucket[b] > host * n:
            self.probe_spent_ns += dt_ns

    def record_host(self, dt_ns: float, n: int) -> None:
        per_pkt = dt_ns / max(n, 1)
        prev = self.host_ns_per_pkt
        self.host_ns_per_pkt = per_pkt if prev is None \
            else 0.7 * prev + 0.3 * per_pkt


_KERNEL_CACHE: dict = {}


def build_propagate_kernel(latency_ns: np.ndarray, thresholds: np.ndarray,
                           k0: int, k1: int):
    """Returns a jitted fn(src_node, dst_node, src_host, pkt_seq, t_send,
    is_ctl, valid, window_end, after_bootstrap_mask_base) -> arrays.

    The routing matrices are closed over and transferred to the device
    once; per-round traffic is O(packets), not O(V^2).  Kernels are
    cached per (matrices, keys): a fresh Manager for the same config
    (bench trials, repeated sims in one process) reuses the jitted
    function — and with it XLA's compiled executables — instead of
    paying a recompile per run (through a tunnelled device that tax is
    seconds per trial).
    """
    import hashlib

    lat_c = np.ascontiguousarray(latency_ns, dtype=np.int64)
    thr_c = np.ascontiguousarray(thresholds, dtype=np.int64)
    key = (lat_c.shape, hashlib.sha1(lat_c.tobytes()).hexdigest(),
           hashlib.sha1(thr_c.tobytes()).hexdigest(), int(k0), int(k1))
    cached = _KERNEL_CACHE.get(key)
    if cached is not None:
        return cached

    import jax
    import jax.numpy as jnp

    lat = jnp.asarray(latency_ns, dtype=jnp.int64)
    thr = jnp.asarray(thresholds, dtype=jnp.int64)
    key0 = jnp.uint32(k0)
    key1 = jnp.uint32(k1)

    @jax.jit
    def kernel(src_node, dst_node, src_host, pkt_seq, t_send, is_ctl, valid,
               window_end, bootstrap_end):
        latency = lat[src_node, dst_node]
        reachable = latency < TIME_NEVER
        bits, _ = threefry2x32_jax(key0, key1, src_host.astype(jnp.uint32),
                                   pkt_seq)
        threshold = thr[src_node, dst_node]
        lossy = (bits.astype(jnp.int64) < threshold) \
            & jnp.logical_not(is_ctl) & (t_send >= bootstrap_end)
        deliver = jnp.maximum(t_send + latency, window_end)
        keep = valid & reachable & jnp.logical_not(lossy)
        min_deliver = jnp.min(jnp.where(keep, deliver, _I64_MAX))
        # Dynamic-runahead feedback over *delivered* packets only — the
        # scalar path never observes a dropped packet's latency, and the
        # two must drive identical window boundaries.
        min_latency = jnp.min(jnp.where(keep, latency, _I64_MAX))
        return deliver, keep, reachable, lossy, min_deliver, min_latency

    _KERNEL_CACHE[key] = kernel
    return kernel


class TpuPropagator:
    """Drop-in replacement for ScalarPropagator behind `--scheduler=tpu`.

    send() only buffers metadata; the kernel runs once per round in
    finish_round(), then kept packets scatter into destination inboxes in
    outbox order (per-source order preserved => identical event seqs)."""

    def __init__(self, hosts, dns, latency_ns, loss_thresholds, seed: int,
                 bootstrap_end_ns: int, max_batch: int = 1 << 20,
                 runahead=None, min_device_batch: int = 2048):
        self.hosts = hosts
        self.dns = dns
        k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
        self._keys = (k0, k1)
        self.kernel = build_propagate_kernel(latency_ns, loss_thresholds,
                                             k0, k1)
        self._lat_np = np.asarray(latency_ns, dtype=np.int64)
        self._thr_np = np.asarray(loss_thresholds, dtype=np.int64)
        self.bootstrap_end = bootstrap_end_ns
        self.max_batch = max_batch
        # Rounds smaller than min_device_batch always run the same
        # integer math on the host CPU (numpy threefry — bit-identical
        # to the device kernel by construction) instead of paying a
        # device dispatch round trip.  Above it, the online cost model
        # decides (DeviceRouteModel).
        self.route = DeviceRouteModel(min_device_batch)
        self.runahead = runahead
        self.window_end = 0
        self.engine = None  # native plane engine (set by the Manager)
        # Outbox: one tuple per packet (hot path = a single list append).
        # (src_host_obj, dst_host_obj, evt_seq, packet_or_native_id,
        #  pkt_seq, t_send, is_ctl)
        self._outbox: list = []
        # Flight-recorder wall channel (trace/recorder.WallChannel) or
        # None: per-round dispatch phase walls — profiling only.
        self.wall = None
        self.rounds_dispatched = 0
        self.packets_batched = 0
        # Auditability (VERDICT r3): how much propagation actually ran
        # on the accelerator vs the bit-identical host path.
        self.rounds_device = 0
        self.packets_device = 0
        # Async probe worker (one in flight, daemon thread): measurement
        # dispatches run on copied columns while the host path serves
        # the round.
        self._probe_pending = False
        self._probe_closed = False
        self.probes_async = 0
        # Last engine-round size/decision: the Manager's span gate asks
        # whether a measured-winning device should preempt C++ spans.
        self._last_engine_n = 0

    def begin_round(self, window_start: int, window_end: int) -> None:
        self.window_end = window_end

    def send(self, src_host, packet) -> None:
        if src_host.link_down:
            # NIC link down: egress drop before the event-seq draw
            # (scalar/engine twins check at the same position).
            src_host.trace_drop(packet, "link-down")
            return
        dst_id = self.dns.host_id_for_ip(packet.dst_ip)
        if dst_id is None:
            src_host.trace_drop(packet, "no-route")
            return
        self._outbox.append((src_host, self.hosts[dst_id],
                             src_host.next_event_seq(), packet, packet.seq,
                             src_host.now(), packet.is_empty_control()))

    def finish_round(self):
        global_min_deliver = _I64_MAX
        global_min_latency = _I64_MAX
        # Object-path sends (CPU-plane hosts in mixed sims).
        total = len(self._outbox)
        if total:
            for lo in range(0, total, self.max_batch):
                hi = min(lo + self.max_batch, total)
                md, ml = self._dispatch_chunk(lo, hi)
                global_min_deliver = min(global_min_deliver, md)
                global_min_latency = min(global_min_latency, ml)
            self.packets_batched += total
            self._outbox.clear()
        # Engine-batched sends (native-plane hosts): the whole
        # propagation phase — threefry loss, latency, clamp, delivery
        # into destination inboxes — runs in one engine call (or on
        # device above the cost-model threshold via export/scatter).
        eng = self.engine
        if eng is not None:
            n = eng.round_size()
            if n:
                md, ml = self._engine_round(n)
                global_min_deliver = min(global_min_deliver, md)
                global_min_latency = min(global_min_latency, ml)
                self.packets_batched += n

        if self.runahead is not None and global_min_latency < _I64_MAX:
            self.runahead.update_lowest_used_latency(global_min_latency)
        return global_min_deliver if global_min_deliver < _I64_MAX else None

    def _engine_round(self, n: int):
        import time as _time

        eng = self.engine
        b = _bucket(n)
        self._last_engine_n = n
        t0 = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
        route = self.route.decide(n, b)
        if route == ROUTE_DEVICE and self._probe_pending:
            # An in-flight probe shares the device/tunnel: a critical-
            # path dispatch now would serialize behind it and both
            # timings would record queueing delay, not dispatch cost.
            # The host path is bit-identical, so defer the device round.
            route = ROUTE_HOST
        if route == ROUTE_DEVICE:
            md, ml, exports = self._engine_device_round(n, b)
            dt = _time.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
            self.route.record_device(b, dt, n)
            if self.wall is not None:
                self.wall.add("propagate-device", dt, t0)
            self.rounds_device += 1
            self.packets_device += n
        else:
            if route == ROUTE_PROBE:
                # export_round builds independent byte copies, so the
                # probe's inputs survive finish_round consuming the
                # outbox (np.frombuffer is zero-copy over those
                # immutable bytes).
                sn_b, dn_b, _dh, sh_b, ps_b, ts_b, ctl_b = \
                    eng.export_round()
                self._submit_probe(
                    (np.frombuffer(sn_b, np.int32),
                     np.frombuffer(dn_b, np.int32),
                     np.frombuffer(sh_b, np.int64),
                     np.frombuffer(ps_b, np.uint32),
                     np.frombuffer(ts_b, np.int64),
                     np.frombuffer(ctl_b, np.bool_)), n, b)
            _nf, md, ml, exports = eng.finish_round(self.window_end)
            dt = _time.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
            self.route.record_host(dt, n)
            if self.wall is not None:
                self.wall.add("propagate-host", dt, t0)
        self.rounds_dispatched += 1
        if exports is not None:
            self._deliver_exports(exports)
        return (md if md < _I64_MAX else _I64_MAX,
                ml if ml < _I64_MAX else _I64_MAX)

    def _submit_probe(self, cols, n: int, b: int) -> None:
        """Measure a device dispatch off the critical path: the kernel
        runs in a worker thread on copied columns (results discarded —
        the host path already served the round bit-identically), and
        the timing feeds the route model.  One probe in flight: a probe
        through a slow tunnel must not queue up behind itself."""
        if self._probe_pending or self._probe_closed:
            # One probe in flight: decline.  decide() left the backoff
            # un-advanced (countdown 1), so the next eligible round
            # simply asks again.
            return
        self._probe_pending = True
        self.route.probe_started(b, n)
        window_end = self.window_end
        bootstrap_end = self.bootstrap_end
        kernel = self.kernel
        route = self.route

        def job():
            try:
                import time as _time

                import jax
                import jax.numpy as jnp

                def pad(col):
                    a = np.zeros(b, dtype=col.dtype)
                    a[:n] = col
                    return a

                padded = [pad(c) for c in cols]
                valid = np.concatenate([np.ones(n, bool),
                                        np.zeros(b - n, bool)])
                t0 = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
                out = kernel(*padded, valid, jnp.int64(window_end),
                             jnp.int64(bootstrap_end))
                jax.block_until_ready(out)
                # record_device debits the probe budget (compiles and
                # losing dispatches both count as measurement spend).
                route.record_device(b, _time.perf_counter_ns() - t0, n)  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
                self.probes_async += 1  # shadow-lint: allow[svc-ownership] single probe thread (pending-flag gate); wall metric only
            except Exception:
                pass  # a failed probe just leaves the bucket unmeasured
            finally:
                self._probe_pending = False  # shadow-lint: allow[svc-ownership] the flag handoff IS the protocol: set before spawn, cleared only here

        import threading
        # A daemon thread, not an executor: concurrent.futures joins
        # its non-daemon workers at interpreter exit, so a hung tunnel
        # dispatch would hang process shutdown.
        threading.Thread(target=job, name="route-probe",
                         daemon=True).start()

    def span_gate(self) -> bool:
        """May the Manager serve the next rounds with the C++ span loop?
        False when the route model has MEASURED the device winning at
        the typical engine-round size.  (Probes stay reachable because
        spawn-phase and post-span rounds still run per-round.)"""
        return not self.route.device_measured_winning(
            self._last_engine_n)

    def close(self) -> None:
        """Stop accepting probes; an in-flight one runs out on its
        daemon thread and cannot block interpreter exit."""
        self._probe_closed = True

    def _engine_device_round(self, n: int, b: int):
        """Device path over engine-exported columns: same jitted kernel,
        decisions scattered back by the engine."""
        import jax.numpy as jnp

        eng = self.engine
        sn_b, dn_b, _dh_b, sh_b, ps_b, ts_b, ctl_b = eng.export_round()

        def pad(buf, dtype, width):
            col = np.frombuffer(buf, dtype=dtype)
            a = np.zeros(b, dtype=dtype)
            a[:n] = col
            return a

        valid = np.concatenate([np.ones(n, bool), np.zeros(b - n, bool)])
        deliver, keep, reachable, lossy, md, ml = self.kernel(
            pad(sn_b, np.int32, 4), pad(dn_b, np.int32, 4),
            pad(sh_b, np.int64, 8), pad(ps_b, np.uint32, 4),
            pad(ts_b, np.int64, 8), pad(ctl_b, np.bool_, 1), valid,
            jnp.int64(self.window_end), jnp.int64(self.bootstrap_end))
        _nf, _md2, _ml2, exports = eng.scatter_round(
            np.ascontiguousarray(np.asarray(keep)[:n], dtype=np.uint8),
            np.ascontiguousarray(np.asarray(deliver)[:n], dtype=np.int64),
            np.ascontiguousarray(np.asarray(reachable)[:n],
                                 dtype=np.uint8),
            np.ascontiguousarray(np.asarray(lossy)[:n], dtype=np.uint8))
        return int(md), int(ml), exports

    def _deliver_exports(self, exports) -> None:
        deliver_engine_exports(self.hosts, exports)

    def _dispatch_chunk(self, lo: int, hi: int):
        import time as _time

        n = hi - lo
        b = _bucket(n)
        t0 = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
        route = self.route.decide(n, b)
        if route == ROUTE_DEVICE and self._probe_pending:
            route = ROUTE_HOST  # don't serialize behind the probe
        if route == ROUTE_DEVICE:
            deliver, keep, reachable, lossy, min_deliver, min_latency = \
                self._compute_device(lo, hi, b)
            self.route.record_device(b, _time.perf_counter_ns() - t0, n)  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
            self.rounds_device += 1
            self.packets_device += n
        else:
            if route == ROUTE_PROBE:
                sn, dn, sh, ps, ts, ctl = self._chunk_columns(lo, hi)
                self._submit_probe((sn, dn, sh, ps, ts, ctl), n, b)
            deliver, keep, reachable, lossy, min_deliver, min_latency = \
                self._compute_host(lo, hi)
            self.route.record_host(_time.perf_counter_ns() - t0, n)  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
        self.rounds_dispatched += 1

        # Scatter (outbox order => per-source event order is preserved).
        # ndarray.tolist() up front: per-element python-int access is far
        # cheaper than indexing numpy scalars in the loop.
        deliver_l = deliver.tolist()
        keep_l = keep.tolist()
        outbox = self._outbox
        for i in range(n):
            src_host, dst_host, seq, packet, _pseq, t_send, _ = \
                outbox[lo + i]
            if keep_l[i]:
                deliver_to_host(dst_host, deliver_l[i], src_host.id, seq,
                                packet)
            elif not reachable[i]:
                src_host.trace_drop(packet, "unreachable", at_time=t_send)
            elif lossy[i]:
                packet.record(pktmod.ST_INET_DROPPED)
                src_host.trace_drop(packet, "inet-loss", at_time=t_send)
        return int(min_deliver), int(min_latency)

    def _chunk_columns(self, lo: int, hi: int):
        """Transpose the outbox slice into numpy columns."""
        src_h, dst_h, _seq, _pkts, pseqs, t_send, is_ctl = \
            zip(*self._outbox[lo:hi])
        src_node = np.fromiter((h.node_index for h in src_h), np.int32,
                               hi - lo)
        dst_node = np.fromiter((h.node_index for h in dst_h), np.int32,
                               hi - lo)
        src_host = np.fromiter((h.id for h in src_h), np.int64, hi - lo)
        pkt_seq = np.fromiter((s & 0xFFFFFFFF for s in pseqs), np.uint32,
                              hi - lo)
        t_send = np.asarray(t_send, dtype=np.int64)
        is_ctl = np.asarray(is_ctl, dtype=bool)
        return src_node, dst_node, src_host, pkt_seq, t_send, is_ctl

    def _compute_device(self, lo: int, hi: int, b: int):
        import jax.numpy as jnp

        n = hi - lo
        pad = b - n
        src_node, dst_node, src_host, pkt_seq, t_send, is_ctl = \
            self._chunk_columns(lo, hi)

        def arr(col):
            a = np.zeros(b, dtype=col.dtype)
            a[:n] = col
            return a

        deliver, keep, reachable, lossy, min_deliver, min_latency = \
            self.kernel(
                arr(src_node), arr(dst_node), arr(src_host), arr(pkt_seq),
                arr(t_send), arr(is_ctl),
                np.concatenate([np.ones(n, bool), np.zeros(pad, bool)]),
                jnp.int64(self.window_end), jnp.int64(self.bootstrap_end))
        return (np.asarray(deliver), np.asarray(keep),
                np.asarray(reachable), np.asarray(lossy),
                int(min_deliver), int(min_latency))

    def _compute_host(self, lo: int, hi: int):
        """Same integer math as the device kernel, in numpy — used for
        rounds too small to amortize a device dispatch.  Bit-identical
        by construction (same matrices, same threefry bits; the parity
        tests cover all three paths: scalar, host-batch, device)."""
        from shadow_tpu.core.rng import threefry2x32_np

        src_node, dst_node, src_host, pkt_seq, t_send, is_ctl = \
            self._chunk_columns(lo, hi)

        latency = self._lat_np[src_node, dst_node]
        reachable = latency < TIME_NEVER
        k0, k1 = self._keys
        bits, _ = threefry2x32_np(np.uint32(k0), np.uint32(k1),
                                  src_host.astype(np.uint32), pkt_seq)
        threshold = self._thr_np[src_node, dst_node]
        lossy = (bits.astype(np.int64) < threshold) & ~is_ctl \
            & (t_send >= self.bootstrap_end)
        deliver = np.maximum(t_send + latency, self.window_end)
        keep = reachable & ~lossy
        min_deliver = int(np.min(np.where(keep, deliver, _I64_MAX),
                                 initial=_I64_MAX))
        min_latency = int(np.min(np.where(keep, latency, _I64_MAX),
                                 initial=_I64_MAX))
        return deliver, keep, reachable, lossy, min_deliver, min_latency
