"""Batched cross-host packet propagation — the TPU data path.

This is the north-star kernel (SURVEY.md section 3.4): the reference
walks every in-flight packet through `Worker::send_packet` — a scalar,
lock-per-push path doing a latency lookup, a sequential-RNG loss draw,
and a clamp (src/main/core/worker.rs:324-397). Here a whole round's
packets, across *all* hosts, become one jitted XLA program:

    latency  = L[src_node, dst_node]          # vectorized gather
    bits     = threefry2x32(key, (src_host, packet_seq))
    drop     = bits < T[src_node, dst_node]   # counter-based, order-free
    deliver  = max(t_send + latency, window_end)
    barrier  = min(deliver | keep)            # feeds the round reduction

Shapes are padded to power-of-two buckets so XLA compiles a handful of
programs total; `window_end`/`bootstrap_end` ride as dynamic scalars.
Byte-identical to the scalar path by construction: same integer latency
matrix, same integer thresholds, same threefry bits (tests/test_parity).

Multi-device sharding of the host dimension (ops sharded over a Mesh,
`lax.pmin` barrier) layers on top in shadow_tpu/parallel/.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.event import Event, KIND_PACKET
from shadow_tpu.core.rng import STREAM_PACKET_LOSS, mix_key, threefry2x32_jax
from shadow_tpu.core.simtime import TIME_NEVER
from shadow_tpu.net import packet as pktmod

_I64_MAX = (1 << 63) - 1
_MIN_BUCKET = 256


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def build_propagate_kernel(latency_ns: np.ndarray, thresholds: np.ndarray,
                           k0: int, k1: int):
    """Returns a jitted fn(src_node, dst_node, src_host, pkt_seq, t_send,
    is_ctl, valid, window_end, after_bootstrap_mask_base) -> arrays.

    The routing matrices are closed over and transferred to the device
    once; per-round traffic is O(packets), not O(V^2).
    """
    import jax
    import jax.numpy as jnp

    lat = jnp.asarray(latency_ns, dtype=jnp.int64)
    thr = jnp.asarray(thresholds, dtype=jnp.int64)
    key0 = jnp.uint32(k0)
    key1 = jnp.uint32(k1)

    @jax.jit
    def kernel(src_node, dst_node, src_host, pkt_seq, t_send, is_ctl, valid,
               window_end, bootstrap_end):
        latency = lat[src_node, dst_node]
        reachable = latency < TIME_NEVER
        bits, _ = threefry2x32_jax(key0, key1, src_host.astype(jnp.uint32),
                                   pkt_seq)
        threshold = thr[src_node, dst_node]
        lossy = (bits.astype(jnp.int64) < threshold) \
            & jnp.logical_not(is_ctl) & (t_send >= bootstrap_end)
        deliver = jnp.maximum(t_send + latency, window_end)
        keep = valid & reachable & jnp.logical_not(lossy)
        min_deliver = jnp.min(jnp.where(keep, deliver, _I64_MAX))
        # Dynamic-runahead feedback over *delivered* packets only — the
        # scalar path never observes a dropped packet's latency, and the
        # two must drive identical window boundaries.
        min_latency = jnp.min(jnp.where(keep, latency, _I64_MAX))
        return deliver, keep, reachable, lossy, min_deliver, min_latency

    return kernel


class TpuPropagator:
    """Drop-in replacement for ScalarPropagator behind `--scheduler=tpu`.

    send() only buffers metadata; the kernel runs once per round in
    finish_round(), then kept packets scatter into destination inboxes in
    outbox order (per-source order preserved => identical event seqs)."""

    def __init__(self, hosts, dns, latency_ns, loss_thresholds, seed: int,
                 bootstrap_end_ns: int, max_batch: int = 1 << 20,
                 runahead=None, min_device_batch: int = 2048):
        self.hosts = hosts
        self.dns = dns
        k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
        self._keys = (k0, k1)
        self.kernel = build_propagate_kernel(latency_ns, loss_thresholds,
                                             k0, k1)
        self._lat_np = np.asarray(latency_ns, dtype=np.int64)
        self._thr_np = np.asarray(loss_thresholds, dtype=np.int64)
        self.bootstrap_end = bootstrap_end_ns
        self.max_batch = max_batch
        # Rounds smaller than this run the same integer math on the host
        # CPU (numpy threefry — bit-identical to the device kernel by
        # construction) instead of paying a device dispatch round trip;
        # only batches big enough to amortize the transfer go to the TPU.
        self.min_device_batch = min_device_batch
        self.runahead = runahead
        self.window_end = 0
        # Outbox: parallel scalar lists + the packet/event bookkeeping.
        self._src_node: list[int] = []
        self._dst_node: list[int] = []
        self._src_host: list[int] = []
        self._pkt_seq: list[int] = []
        self._t_send: list[int] = []
        self._is_ctl: list[bool] = []
        self._meta: list = []  # (src_host_obj, dst_host_obj, evt_seq, packet)
        self.rounds_dispatched = 0
        self.packets_batched = 0

    def begin_round(self, window_start: int, window_end: int) -> None:
        self.window_end = window_end

    def send(self, src_host, packet) -> None:
        dst_id = self.dns.host_id_for_ip(packet.dst_ip)
        if dst_id is None:
            src_host.trace_drop(packet, "no-route")
            return
        dst_host = self.hosts[dst_id]
        seq = src_host.next_event_seq()
        self._src_node.append(src_host.node_index)
        self._dst_node.append(dst_host.node_index)
        self._src_host.append(src_host.id)
        self._pkt_seq.append(packet.seq & 0xFFFFFFFF)
        self._t_send.append(src_host.now())
        self._is_ctl.append(packet.is_empty_control())
        self._meta.append((src_host, dst_host, seq, packet))

    def finish_round(self):
        total = len(self._meta)
        if total == 0:
            return None
        # Honor the configured per-dispatch cap (device-memory bound):
        # oversized rounds run as several kernel dispatches.
        global_min_deliver = _I64_MAX
        global_min_latency = _I64_MAX
        for lo in range(0, total, self.max_batch):
            hi = min(lo + self.max_batch, total)
            md, ml = self._dispatch_chunk(lo, hi)
            global_min_deliver = min(global_min_deliver, md)
            global_min_latency = min(global_min_latency, ml)
        self.packets_batched += total

        if self.runahead is not None and global_min_latency < _I64_MAX:
            self.runahead.update_lowest_used_latency(global_min_latency)

        self._src_node.clear()
        self._dst_node.clear()
        self._src_host.clear()
        self._pkt_seq.clear()
        self._t_send.clear()
        self._is_ctl.clear()
        self._meta.clear()
        return global_min_deliver if global_min_deliver < _I64_MAX else None

    def _dispatch_chunk(self, lo: int, hi: int):
        n = hi - lo
        if n < self.min_device_batch:
            deliver, keep, reachable, lossy, min_deliver, min_latency = \
                self._compute_host(lo, hi)
        else:
            deliver, keep, reachable, lossy, min_deliver, min_latency = \
                self._compute_device(lo, hi)
        self.rounds_dispatched += 1

        # Scatter (outbox order => per-source event order is preserved).
        for i in range(n):
            src_host, dst_host, seq, packet = self._meta[lo + i]
            if keep[i]:
                t = int(deliver[i])
                packet.arrival_time = t
                dst_host.deliver_packet_event(
                    Event(t, KIND_PACKET, src_host.id, seq, packet))
            elif not reachable[i]:
                src_host.trace_drop(packet, "unreachable",
                                    at_time=self._t_send[lo + i])
            elif lossy[i]:
                packet.record(pktmod.ST_INET_DROPPED)
                src_host.trace_drop(packet, "inet-loss",
                                    at_time=self._t_send[lo + i])
        return int(min_deliver), int(min_latency)

    def _compute_device(self, lo: int, hi: int):
        import jax.numpy as jnp

        n = hi - lo
        b = _bucket(n)
        pad = b - n

        def arr(lst, dtype):
            a = np.zeros(b, dtype=dtype)
            a[:n] = lst[lo:hi]
            return a

        deliver, keep, reachable, lossy, min_deliver, min_latency = \
            self.kernel(
                arr(self._src_node, np.int32), arr(self._dst_node, np.int32),
                arr(self._src_host, np.int64), arr(self._pkt_seq, np.uint32),
                arr(self._t_send, np.int64), arr(self._is_ctl, bool),
                np.concatenate([np.ones(n, bool), np.zeros(pad, bool)]),
                jnp.int64(self.window_end), jnp.int64(self.bootstrap_end))
        return (np.asarray(deliver), np.asarray(keep),
                np.asarray(reachable), np.asarray(lossy),
                int(min_deliver), int(min_latency))

    def _compute_host(self, lo: int, hi: int):
        """Same integer math as the device kernel, in numpy — used for
        rounds too small to amortize a device dispatch.  Bit-identical
        by construction (same matrices, same threefry bits; the parity
        tests cover all three paths: scalar, host-batch, device)."""
        from shadow_tpu.core.rng import threefry2x32_np

        src_node = np.asarray(self._src_node[lo:hi], dtype=np.int32)
        dst_node = np.asarray(self._dst_node[lo:hi], dtype=np.int32)
        src_host = np.asarray(self._src_host[lo:hi], dtype=np.int64)
        pkt_seq = np.asarray(self._pkt_seq[lo:hi], dtype=np.uint32)
        t_send = np.asarray(self._t_send[lo:hi], dtype=np.int64)
        is_ctl = np.asarray(self._is_ctl[lo:hi], dtype=bool)

        latency = self._lat_np[src_node, dst_node]
        reachable = latency < TIME_NEVER
        k0, k1 = self._keys
        bits, _ = threefry2x32_np(np.uint32(k0), np.uint32(k1),
                                  src_host.astype(np.uint32), pkt_seq)
        threshold = self._thr_np[src_node, dst_node]
        lossy = (bits.astype(np.int64) < threshold) & ~is_ctl \
            & (t_send >= self.bootstrap_end)
        deliver = np.maximum(t_send + latency, self.window_end)
        keep = reachable & ~lossy
        min_deliver = int(np.min(np.where(keep, deliver, _I64_MAX),
                                 initial=_I64_MAX))
        min_latency = int(np.min(np.where(keep, latency, _I64_MAX),
                                 initial=_I64_MAX))
        return deliver, keep, reachable, lossy, min_deliver, min_latency
