"""Lane-parallel queue-scan kernels (ISSUE 16, second leg).

The kernel observatory attributes the span slope's low-occupancy tail
to two stages both families run every micro-iteration: the
token-bucket refill/conformance scan and the CoDel head
classification of the relay drains.  Both are pure elementwise
integer laws over the host lane — exactly the shape pallas maps to
the vector lanes — so they live here once as a lax reference (the
form both span kernels inline when `experimental.pallas_queue_kernels`
is off, and the byte-identity oracle for the tests) plus a pallas
twin built from the SAME law, `interpret=True` on the CPU backend so
tier-1 runs the real kernel path.

Both laws are integer-exact (no float ops — the CoDel control-time
Newton isqrt stays OUTSIDE these kernels, in the span modules), so
byte identity of all five sim channels holds with the kernels on; the
differential gate is tests/test_overlap.py, not an assumption.  The
REFILL_NS / CODEL_TARGET_NS / MTU constants stay defined in the span
modules (the pass-1 twin-constant contract extracts them there) and
are passed in at build time.
"""

from __future__ import annotations

import numpy as np

# CoDel's control-law interval (netplane codel_pop twin): the
# first_above arm horizon.  Same literal the span modules inline.
CODEL_INTERVAL_NS = 100_000_000


def bucket_step_ref(jnp, refill_ns, bal, nxt, refill, cap, unlimited,
                    size, now):
    """Token-bucket refill + conformance for every host lane at once
    (netplane token_bucket twin): lazy catch-up refill of `k` whole
    intervals, then the conformance check/debit.  Returns
    (bal3, nxt2, ok); the caller owns the masked writeback."""
    first = nxt == 0
    k = jnp.maximum(np.int64(0),
                    1 + (now - nxt) // np.int64(refill_ns))
    do_ref = ~first & (now >= nxt)
    bal2 = jnp.where(do_ref, jnp.minimum(cap, bal + k * refill),
                     bal)
    nxt2 = jnp.where(first, now + np.int64(refill_ns),
                     jnp.where(do_ref,
                               nxt + k * np.int64(refill_ns),
                               nxt))
    ok = unlimited | (size <= bal2)
    bal3 = jnp.where(~unlimited & ok, bal2 - size, bal2)
    return bal3, nxt2, ok


def codel_head_ref(jnp, target_ns, mtu, pop, none, now, enq,
                   bytes_after, first_above):
    """CoDel head classification of one relay dequeue per lane
    (netplane codel_pop dequeue_raw twin): sojourn vs target with the
    MTU standing-queue escape, first_above arming and the ok bit.
    `bytes_after` is the queue byte count AFTER the pop's decrement.
    Returns (quiet, above, arm, cok, fa_new); the drop chain / sniff
    unrolling stays in the span modules."""
    sojourn = now - enq
    quiet = pop & ((sojourn < target_ns) | (bytes_after <= mtu))
    above = pop & ~quiet
    arm = above & (first_above == 0)
    cok = above & ~arm & (now >= first_above)
    fa_new = jnp.where(
        quiet | none, 0,
        jnp.where(arm, now + np.int64(CODEL_INTERVAL_NS),
                  first_above))
    return quiet, above, arm, cok, fa_new


def _interpret(jax) -> bool:
    """Compiled pallas needs a real accelerator backend; the CPU
    backend runs the same kernel body through the pallas interpreter
    so tier-1 exercises the kernel path without TPU hardware."""
    return jax.default_backend() == "cpu"


def make_bucket_step(jax, jnp, H, refill_ns, use_pallas):
    """Build the bucket scan for an H-lane span kernel: the lax
    reference, or its pallas twin when `use_pallas`.  Signature of
    the returned fn: (bal, nxt, refill, cap, unlimited, size, now)
    -> (bal3, nxt2, ok) — i64 lanes except the bool unlimited/ok."""
    if not use_pallas:
        def step(bal, nxt, refill, cap, unlimited, size, now):
            return bucket_step_ref(jnp, refill_ns, bal, nxt, refill,
                                   cap, unlimited, size, now)
        return step

    from jax.experimental import pallas as pl

    def kernel(bal_ref, nxt_ref, refill_ref, cap_ref, unl_ref,
               size_ref, now_ref, bal_out, nxt_out, ok_out):
        bal3, nxt2, ok = bucket_step_ref(
            jnp, refill_ns, bal_ref[:], nxt_ref[:], refill_ref[:],
            cap_ref[:], unl_ref[:], size_ref[:], now_ref[:])
        bal_out[:] = bal3
        nxt_out[:] = nxt2
        ok_out[:] = ok

    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((H,), jnp.int64),
                   jax.ShapeDtypeStruct((H,), jnp.int64),
                   jax.ShapeDtypeStruct((H,), jnp.bool_)),
        interpret=_interpret(jax))

    def step(bal, nxt, refill, cap, unlimited, size, now):
        # The span kernels pass the span clock (and sometimes the
        # packet size) as scalars; pallas refs are lane-shaped.
        args = tuple(jnp.broadcast_to(jnp.asarray(a), (H,))
                     for a in (bal, nxt, refill, cap, unlimited,
                               size, now))
        return call(*args)
    return step


def make_codel_head(jax, jnp, H, target_ns, mtu, use_pallas):
    """Build the CoDel head classification for an H-lane span kernel:
    the lax reference, or its pallas twin when `use_pallas`.
    Signature of the returned fn: (pop, none, now, enq, bytes_after,
    first_above) -> (quiet, above, arm, cok, fa_new)."""
    if not use_pallas:
        def head(pop, none, now, enq, bytes_after, first_above):
            return codel_head_ref(jnp, target_ns, mtu, pop, none,
                                  now, enq, bytes_after, first_above)
        return head

    from jax.experimental import pallas as pl

    def kernel(pop_ref, none_ref, now_ref, enq_ref, bytes_ref,
               fa_ref, quiet_out, above_out, arm_out, cok_out,
               fa_out):
        quiet, above, arm, cok, fa_new = codel_head_ref(
            jnp, target_ns, mtu, pop_ref[:], none_ref[:], now_ref[:],
            enq_ref[:], bytes_ref[:], fa_ref[:])
        quiet_out[:] = quiet
        above_out[:] = above
        arm_out[:] = arm
        cok_out[:] = cok
        fa_out[:] = fa_new

    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((H,), jnp.bool_),
                   jax.ShapeDtypeStruct((H,), jnp.bool_),
                   jax.ShapeDtypeStruct((H,), jnp.bool_),
                   jax.ShapeDtypeStruct((H,), jnp.bool_),
                   jax.ShapeDtypeStruct((H,), jnp.int64)),
        interpret=_interpret(jax))

    def head(pop, none, now, enq, bytes_after, first_above):
        args = tuple(jnp.broadcast_to(jnp.asarray(a), (H,))
                     for a in (pop, none, now, enq, bytes_after,
                               first_above))
        return call(*args)
    return head
