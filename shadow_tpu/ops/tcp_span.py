"""Device-resident multi-round loop for the tgen steady-stream TCP
family (ISSUE 1 tentpole).

Per-connection TCP control state — cwnd/ssthresh, RTO + backoff, the
SACK scoreboard, send/recv buffer cursors, delack/persist timers —
exports as struct-of-arrays (netplane.cpp span_export_tcp), steps
inside the same conservative-window `lax.while_loop` shape as
ops/phold_span.py, and imports back transactionally.  The modelled
domain is the fixed-connection bulk-transfer stretch (no handshake, no
FIN/RST, no accept churn — netgen.tcp_stream_yaml): every live
connection ESTABLISHED, every client app mid-receive, every handler
mid-send.  Anything else aborts the span (AB_STRUCT) and the engine's
C++ path re-runs those rounds — fallback, never corruption.

Layout: host-major arrays carry the shared per-host machinery (event
seqs, CoDel, token-bucket relays, timer heap, inbox) exactly like the
PHOLD kernel; connection-major arrays carry the TCP state machine,
indexed through a per-host `cur` register (a host advances ONE micro-op
at a time, so two lanes never touch one connection).  Packets carry
their full TCP header through every ring (20 columns) because the
receiver's state machine — not a fixed-size twin — interprets them.

The twin contract is byte-identical packet-delivery traces against the
serial object path, including lossy edges and retransmission
(tests/test_tcp_span.py).
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.rng import STREAM_PACKET_LOSS, mix_key, threefry2x32_jax
from shadow_tpu.core.simtime import TIME_NEVER
from shadow_tpu.ops.span_mesh import SpanMeshMixin

I64_MAX = np.int64(1 << 62)
SEQ_HALF = np.int64(1 << 31)
SEQ_MOD = np.int64(1 << 32)

# Continuations (one per host lane).
C_IDLE = 0
C_R1 = 1       # relay inet-out drain (one packet per micro-op)
C_R2 = 2       # relay inet-in drain
C_TCPIN = 3    # on_packet minus the push_data / reassembly loops
C_DRAIN = 4    # reassembly drain (one chunk per micro-op)
C_ACKDATA = 5  # ack_data decision after in-order delivery
C_PUSH = 6     # push_data (one segment per micro-op)
C_FLUSH = 7    # tcp_flush's notify decision
C_ARM = 8      # tcp_flush's arm-timer + update-status tail
C_APP = 9      # app stepper (client recv / handler send)
C_TMR = 10     # TK_TCP timer fire

# Timer kinds / status bits (netplane.cpp).
TK_RELAY = 0
TK_TCP = 1
TK_APP = 2
S_READABLE = 1 << 1
S_WRITABLE = 1 << 2
ASYS_SEND = 3
ASYS_RECV = 4
ASYS_N = 16

# TCP constants (tcp/connection.py twins).
F_FIN = 0x01
F_SYN = 0x02
F_RST = 0x04
F_PSH = 0x08
F_ACK = 0x10
F_ECE = 0x40
F_CWR = 0x80
MSS = 1460

# ECN / DCTCP (net/packet.py, tcp/connection.py, net/codel.py twins;
# registered fail-closed in analysis pass 1).  The alpha EWMA is
# fixed-point (scaled by 2**DCTCP_SHIFT) so this kernel, the C++
# engine and the Python object path compute bit-identical values.
ECN_ECT0 = 2
ECN_CE = 3
DCTCP_SHIFT = 10
DCTCP_G_SHIFT = 4
DCTCP_MAX_ALPHA = 1024
DCTCP_K_PKTS = 20
DCTCP_K_BYTES = 30_000
CC_DCTCP = 1
MARK_THRESH_PKTS = 0
MARK_THRESH_BYTES = 1
MARK_N = 2
MAX_WINDOW = 65_535
TCP_TOTAL_HDR = 40  # IPv4 20 + TCP 20; options are not size-modelled
MIN_RTO_NS = 200_000_000
MAX_RTO_NS = 60_000_000_000
DELACK_NS = 40_000_000
WMEM_MAX = 4_194_304
RMEM_MAX = 6_291_456

MTU = 1500
CODEL_TARGET_NS = 5_000_000
CODEL_HARD_LIMIT = 1000
REFILL_NS = 1_000_000

TR_SND = 0
TR_DRP = 1
TR_RCV = 2
RSN_CODEL = 1
RSN_RTRLIMIT = 2
RSN_LOSS = 6
RSN_UNREACH = 7
RSN_HOSTDOWN = 9
RSN_LINKDOWN = 10

# Sim-netstat drop-cause slots touched by this kernel (netplane.cpp
# TEL_* twins; registered in analysis pass 1).  The per-host
# (H, TEL_N) `drop_causes` column round-trips through the span codec
# so the engine's counters stay authoritative across device spans.
TEL_CODEL = 0
TEL_RTR_LIMIT = 1
TEL_LOSS_EDGE = 2
TEL_UNREACHABLE = 3
TEL_HOST_DOWN = 11
TEL_LINK_DOWN = 12
TEL_REASM_FULL = 13
TEL_RECVWIN_TRUNC = 14
TEL_N = 15

# Fabric-observatory activity mask (netplane.cpp FB_ACT_* twins;
# registered in analysis pass 1): a host's queues are sampled in a
# round iff any bit is set.
FB_ACT_CODEL = 1
FB_ACT_TB_OUT = 2
FB_ACT_TB_IN = 4
FB_ACT_LINK = 8

# Device-kernel observatory stage slots this family occupies
# (netplane.cpp KS_* twins, registered fail-closed in analysis
# pass 1; docs/OBSERVABILITY.md "Device-kernel observatory").
KS_POP = 0
KS_STEP = 1
KS_CODEL = 2
KS_ON_PACKET = 3
KS_REASM = 4
KS_ACK = 5
KS_PUSH = 6
KS_FLUSH = 7
KS_INET_OUT = 8
KS_ARM = 9
KS_TIMERS = 10
KS_EXCHANGE = 11
KS_N = 12

# Telemetry sample fields (trace/events.py TEL_REC order after the
# identity header) -> the SoA column each samples.
TEL_FIELDS = (("cwnd", "c_cwnd"), ("ssthresh", "c_ssthresh"),
              ("srtt", "c_srtt"), ("rto", "c_rto"),
              ("backoff", "c_rtobackoff"), ("sndbuf", "c_sblen"),
              ("rcvbuf", "c_rblen"), ("rtx", "c_rtxcount"),
              ("sacks", "c_sackskip"), ("marks", "c_ceseen"))
ST_ESTABLISHED = 4  # every in-domain connection's state

# Packet columns: routing identity + the TCP header + the IP ECN
# codepoint (the queues' marking law rewrites it in flight).
ROUTE_KEYS = ("srchost", "pseq", "sip", "sport", "dip", "dport")
TCP_KEYS = ("tseq", "tack", "tflags", "twin", "tsv", "tse", "plen",
            "nsk", "sk0s", "sk0e", "sk1s", "sk1e", "sk2s", "sk2e",
            "ecn")
PK_KEYS = ROUTE_KEYS + TCP_KEYS
PK_DTYPES = {
    "srchost": np.int32, "pseq": np.int64, "sip": np.uint32,
    "sport": np.int32, "dip": np.uint32, "dport": np.int32,
    "tseq": np.uint32, "tack": np.uint32, "tflags": np.int32,
    "twin": np.int64, "tsv": np.int64, "tse": np.int64,
    "plen": np.int32, "nsk": np.int32,
    "sk0s": np.uint32, "sk0e": np.uint32, "sk1s": np.uint32,
    "sk1e": np.uint32, "sk2s": np.uint32, "sk2e": np.uint32,
    "ecn": np.int32,
}

# Abort reason bits (phold_span twin semantics; AB_EXCH = the sharded
# cross-shard exchange overflowed its per-shard capacity — grown and
# retried like the other capacity bits, never silently truncated).
# The values are ops/span_mesh.py's canonical set (one definition for
# both families — the mixin's abort-kind classifier depends on it).
from shadow_tpu.ops.span_mesh import (AB_EXCH, AB_OUT,  # noqa: E402
                                      AB_STRUCT, AB_TRACE)

_FN_CACHE: dict = {}

# ---- Residency classification (the dirty-column export protocol) ----
# Same protocol as ops/phold_span.py: every state key the codec
# (_to_arrays) produces falls in exactly one class, and analysis
# pass 2 fails scripts/lint when an export column is missing here.
# CARRIED: the span's device output is the next input while the
# engine's state_epoch is unchanged.  STATIC: per-sim constants
# (connection identity, negotiated options, buckets) — cached at the
# first export.  DERIVED: device-local chain registers every fresh
# export re-initializes; reattaching the same init is by construction
# identical to the export path.
RESIDENT_STATIC = frozenset({
    "bw_up", "bw_down", "eth_ip",
    "r1_refill", "r1_cap", "r1_unlimited",
    "r2_refill", "r2_cap", "r2_unlimited",
    "c_host", "c_role", "c_lip", "c_lport", "c_pip", "c_pport",
    "c_iss", "c_irs", "c_wsoff", "c_ourws", "c_peerws", "c_effmss",
    "c_nodelay", "c_congmss", "c_sat", "c_rat", "c_atotal",
    "c_ecnact", "c_cc",
})
RESIDENT_DERIVED = frozenset(
    {"cont", "then", "ret", "cur", "eflag", "parkp", "had_holes",
     "park_ctr", "cd_chain", "cd_sniff", "_n_conns"}
    | {f"ar_{kk}" for kk in PK_KEYS})
# CARRIED: the span's own device output is the next input (all
# ring/heap columns plus the mutable scalars).  Ring packet
# columns follow PK_KEYS so a header-field addition classifies
# itself; every scalar column is listed explicitly so adding an
# export column without classifying it fails scripts/lint.
RESIDENT_CARRIED = frozenset(
    {
     "app_sys", "c_agot", "c_atcopied", "c_atlast", "c_atspace",
     "c_await", "c_awaitseq", "c_cwnd", "c_delackdl", "c_dupacks",
     "c_fastrec", "c_persistdl", "c_persistiv", "c_queued",
     "c_rblen", "c_rbmax", "c_rcvnxt", "c_recover", "c_rto",
     "c_rtobackoff", "c_rtodl", "c_rttvar", "c_rtxcount",
     "c_sackskip", "c_sblen", "c_sbmax", "c_segsrecv",
     "c_segssent", "c_sndnxt", "c_snduna", "c_sndwnd", "c_srtt",
     "c_ssa", "c_ssthresh", "c_status", "c_tmrdl", "c_tsrecent",
     "c_wakep", "c_fbyte", "c_lbyte", "c_bin", "c_bout",
     "c_ece", "c_cwrp", "c_cwrend", "c_alpha", "c_ceack",
     "c_totack", "c_dwend", "c_ceseen",
     "codel_bytes", "codel_count", "codel_drop_next",
     "codel_dropped", "codel_dropping", "codel_first_above",
     "codel_enq_pkts", "codel_enq_bytes", "codel_drop_bytes",
     "codel_peak", "codel_marked", "drop_causes", "mark_causes",
     "codel_last_count", "cq_enq", "cq_len", "cq_pos",
     "eth_brecv", "eth_bsent", "eth_precv", "eth_psent",
     "event_seq", "events_run", "ib_len", "ib_pos", "ib_seq",
     "ib_src", "ib_time", "now", "op_len", "op_pos", "packet_seq",
     "pkts_dropped", "pkts_recv", "pkts_sent", "r1_bal",
     "r1_next", "r1_pending", "r1_pk_valid", "r1_stalls",
     "r1_fwd_pkts", "r1_fwd_bytes",
     "r2_bal", "r2_next",
     "r2_pending", "r2_pk_valid", "r2_stalls",
     "r2_fwd_pkts", "r2_fwd_bytes",
     "ra_plen", "ra_seq", "ra_valid",
     "rtx_len", "rtx_plen", "rtx_pos", "rtx_rtxed", "rtx_sacked",
     "rtx_sent", "rtx_seq", "th_kind", "th_seq", "th_tgt",
     "th_time", "th_valid", "h_fault"}
    | {f"{p}_{kk}" for p in ('cq', 'ib', 'op', 'r1_pk', 'r2_pk')
       for kk in PK_KEYS})


class TcpSpanRunner(SpanMeshMixin):
    """Builds and drives the jitted multi-round device loop for the
    tgen steady-stream TCP family.  One instance per Manager."""

    # Ring capacities (compile-time; export refuses state beyond half
    # of each, and the device aborts transactionally on overflow).
    CAP_I = 512    # inbox (one window's arrivals can be a full cwnd)
    # Timer heap: EVERY new ack restarts the RTO deadline, and the
    # engine (like the kernel) pushes a fresh heap entry per change —
    # stale entries only drain as their times pop, so the heap carries
    # roughly one RTO's worth of ack churn (hundreds per busy server).
    CAP_T = 4096
    CAP_CQ = 2048  # CoDel ring (covers the 1000-entry hard limit)
    CAP_RT = 256   # rtx queue (>= the max in-flight segment count)
    CAP_RA = 256   # reassembly (an early hole strands ~a window)
    CAP_OP = 256   # socket egress ring
    MAX_ROUNDS = 256
    # Sim-netstat: per-round telemetry rows buffered on device.  Spans
    # are clamped to TEL_ROWS rounds while the channel records, so the
    # (TEL_ROWS, CC) sample buffers can never overflow (sampled rounds
    # <= rounds <= TEL_ROWS) — a silent skip would break cross-path
    # byte-parity.
    TEL_ROWS = 64
    # Fabric observatory: per-round queue-sample rows buffered on
    # device; spans clamp to FAB_ROWS rounds while the channel
    # records (same overflow-proof rule as TEL_ROWS).
    FAB_ROWS = 64

    def __init__(self, engine, latency_ns, thresholds, host_node,
                 host_ips, seed, bootstrap_end, tracing: bool):
        self.engine = engine
        self.tracing = bool(tracing)
        k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
        self._k = (np.uint32(k0), np.uint32(k1))
        self._lat = np.ascontiguousarray(latency_ns, dtype=np.int64)
        self._thr = np.ascontiguousarray(thresholds, dtype=np.int64)
        self._node = np.ascontiguousarray(host_node, dtype=np.int32)
        ips = np.ascontiguousarray(host_ips, dtype=np.uint32)
        order = np.argsort(ips)
        self._ips_sorted = ips[order]
        self._ips_perm = order.astype(np.int32)
        self.bootstrap_end = int(bootstrap_end)
        self._fn = None
        self._H = len(host_ips)
        self._CC = 0          # conn capacity (set from export)
        # A round can carry a full congestion window from EVERY conn
        # (~120 segments at the default 174 KiB windows), and traces
        # accumulate across the whole span — pre-size so the grow-and-
        # recompile abort path stays the rare case, not the norm.
        self.cap_out = max(4096, 128 * self._H)
        self.cap_tr = max(1 << 18, 1024 * self._H)
        self.spans = 0
        self.rounds = 0
        self.aborts = 0
        self.ineligible = 0
        self.over_caps = 0
        self.compiled = False
        self.last_was_cold = False
        # True right after an export that was transiently out of the
        # domain: the span router shortens the following C++ span so
        # the device is retried soon (a full-length C++ span would
        # serve the whole sim and the device would never get a shot).
        self.last_transient = False
        self.mesh = None  # optional jax.sharding.Mesh ("hosts" axis)
        # Fused micro-op dispatch (default); False rebuilds the
        # one-micro-op-per-iteration reference schedule.
        self.fused = True
        self.micro_iters = 0  # while-iterations across all spans
        self.last_abort_code = 0  # AB_* bits of the last abort
        # Device-resident state between dispatches (phold_span twin).
        self._res_st = None
        self._res_token = None
        self._static_cols = None
        self.resident_hits = 0
        self.stale_drops = 0
        # Flight-recorder wall channel (trace/recorder.WallChannel)
        # or None: per-dispatch phase walls (export / convert /
        # compile / execute / import) — profiling only.  _timed_fns:
        # built-fn ids already dispatched once, so the compile-vs-
        # execute split survives capacity-regrow rebuilds.
        self.wall = None
        self._timed_fns: set = set()
        # Sim-netstat channel (trace/netstat.NetstatChannel) or None:
        # the kernel buffers per-round per-connection samples on
        # device (round_body), and the driver packs them into TEL_REC
        # records in the canonical (host, lport, rport, rip) order.
        self.netstat = None
        self._tel_ident = None  # (host, lport, rport, rip, perm, n)
        # Fabric-observatory channel (trace/fabricstat.FabricChannel)
        # or None: round_body buffers per-round per-host queue samples
        # on device; the driver packs the ACTIVE hosts into FB_REC
        # records at span commit.
        self.fabric = None
        # DCTCP-K marking threshold (experimental.dctcp_k_pkts/_bytes;
        # the manager overrides) — static kernel closure constants.
        self.dctcp_k = (DCTCP_K_PKTS, DCTCP_K_BYTES)

    def _caps(self):
        return (self.CAP_I, self.CAP_T, self.CAP_CQ, self.CAP_RT,
                self.CAP_RA, self.CAP_OP)

    # ------------------------------------------------------------------
    # Export bytes <-> numpy state
    # ------------------------------------------------------------------

    def _to_arrays(self, d: dict) -> dict:
        H = self._H
        I, T, CQ, RT, RA, OP = self._caps()

        def f(k, dt, shape=None):
            a = np.frombuffer(d[k], dtype=dt)
            a = a.reshape(shape) if shape is not None else a
            return a.copy()

        n_conns = int(np.frombuffer(d["n_conns"], np.int64)[0])
        CC = 8
        while CC < n_conns:
            CC <<= 1
        self._CC = CC
        st = {"_n_conns": n_conns}

        def pk(prefix, shape):
            for kk in PK_KEYS:
                a = f(f"{prefix}_{kk}", PK_DTYPES[kk], shape)
                if a.dtype == np.int32 and kk in ("tflags", "nsk"):
                    a = a.astype(np.int32)
                st[f"{prefix}_{kk}"] = a

        for k in ("now", "event_seq", "packet_seq", "bw_up", "bw_down",
                  "codel_bytes", "codel_count", "codel_last_count",
                  "codel_first_above", "codel_drop_next",
                  "codel_dropped", "codel_enq_pkts", "codel_enq_bytes",
                  "codel_drop_bytes", "codel_peak", "codel_marked",
                  "pkts_sent",
                  "pkts_recv", "pkts_dropped", "events_run",
                  "eth_psent", "eth_precv", "eth_bsent", "eth_brecv"):
            st[k] = f(k, np.int64)
        st["eth_ip"] = f("eth_ip", np.uint32)
        # Down-host fault mask (docs/ROBUSTNESS.md): bit0 down, bit1
        # link_down, bit2 blackhole.  Constant within a span (faults
        # apply only at round boundaries, which cap span `limit`);
        # CARRIED so resident reuse keeps the engine's live flags.
        st["h_fault"] = f("h_fault", np.uint8).astype(np.int32)
        st["codel_dropping"] = f("codel_dropping", np.uint8).astype(
            np.int32)
        st["cq_len"] = f("cq_len", np.int32)
        pk("cq", (H, CQ))
        st["cq_enq"] = f("cq_enq", np.int64, (H, CQ))
        for r in (1, 2):
            st[f"r{r}_pending"] = f(f"r{r}_pending", np.uint8).astype(
                np.int32)
            st[f"r{r}_unlimited"] = f(f"r{r}_unlimited",
                                      np.uint8).astype(np.int32)
            for k in ("bal", "next", "refill", "cap", "stalls",
                      "fwd_pkts", "fwd_bytes"):
                st[f"r{r}_{k}"] = f(f"r{r}_{k}", np.int64)
            st[f"r{r}_pk_valid"] = f(f"r{r}_pk_valid",
                                     np.uint8).astype(np.int32)
            pk(f"r{r}_pk", None)
        st["ib_len"] = f("ib_len", np.int32)
        st["ib_time"] = f("ib_time", np.int64, (H, I))
        st["ib_src"] = f("ib_src", np.int32, (H, I))
        st["ib_seq"] = f("ib_seq", np.int64, (H, I))
        pk("ib", (H, I))
        st["th_time"] = f("th_time", np.int64, (H, T))
        st["th_seq"] = f("th_seq", np.int64, (H, T))
        st["th_kind"] = f("th_kind", np.uint8, (H, T)).astype(np.int32)
        st["th_tgt"] = f("th_tgt", np.int32, (H, T))
        st["th_valid"] = (np.arange(T)[None, :]
                          < f("th_len", np.int32)[:, None])
        st["app_sys"] = f("app_sys", np.int64, (H, ASYS_N))
        st["drop_causes"] = f("drop_causes", np.int64, (H, TEL_N))
        st["mark_causes"] = f("mark_causes", np.int64, (H, MARK_N))

        # conn-major
        for k, dt in (("c_host", np.int32), ("c_lport", np.int32),
                      ("c_pport", np.int32), ("c_ourws", np.int32),
                      ("c_peerws", np.int32), ("c_effmss", np.int32),
                      ("c_wsoff", np.int32), ("c_ssa", np.int32),
                      ("c_congmss", np.int32), ("c_dupacks", np.int32),
                      ("c_rtobackoff", np.int32), ("c_cc", np.int32)):
            st[k] = f(k, dt)
        for k in ("c_lip", "c_pip", "c_iss", "c_irs", "c_snduna",
                  "c_sndnxt", "c_rcvnxt", "c_recover", "c_status",
                  "c_cwrend", "c_dwend"):
            st[k] = f(k, np.uint32)
        st["c_await"] = f("c_await", np.uint32)
        for k in ("c_role", "c_nodelay", "c_fastrec", "c_queued",
                  "c_sat", "c_rat", "c_wakep", "c_ecnact", "c_ece",
                  "c_cwrp"):
            st[k] = f(k, np.uint8).astype(np.int32)
        for k in ("c_sndwnd", "c_sblen", "c_sbmax", "c_rblen",
                  "c_rbmax", "c_delackdl", "c_persistdl",
                  "c_persistiv", "c_cwnd", "c_ssthresh", "c_srtt",
                  "c_rttvar", "c_rto", "c_rtodl", "c_tsrecent",
                  "c_segssent", "c_segsrecv", "c_rtxcount",
                  "c_sackskip", "c_tmrdl", "c_atcopied", "c_atspace",
                  "c_atlast", "c_awaitseq", "c_agot", "c_atotal",
                  "c_fbyte", "c_lbyte", "c_bin", "c_bout",
                  "c_alpha", "c_ceack", "c_totack", "c_ceseen"):
            st[k] = f(k, np.int64)
        st["rtx_len"] = f("rtx_len", np.int32)
        st["rtx_seq"] = f("rtx_seq", np.uint32, (CC, RT))
        st["rtx_plen"] = f("rtx_plen", np.int32, (CC, RT))
        st["rtx_rtxed"] = f("rtx_rtxed", np.uint8, (CC, RT)).astype(
            np.int32)
        st["rtx_sacked"] = f("rtx_sacked", np.uint8, (CC, RT)).astype(
            np.int32)
        st["rtx_sent"] = f("rtx_sent", np.int64, (CC, RT))
        st["ra_plen"] = f("ra_plen", np.int32, (CC, RA))
        st["ra_seq"] = f("ra_seq", np.uint32, (CC, RA))
        st["ra_valid"] = (np.arange(RA)[None, :]
                          < f("ra_len", np.int32)[:, None])
        st["op_len"] = f("op_len", np.int32)
        pk("op", (CC, OP))

        for k in ("cq_pos", "ib_pos", "rtx_pos", "op_pos"):
            st[k] = np.zeros(H if k in ("cq_pos", "ib_pos") else CC,
                             np.int32)
        for k in ("cont", "then", "ret", "cur"):
            st[k] = np.full(H, C_IDLE if k in ("cont", "then", "ret")
                            else -1, np.int32)
        # per-host chain registers
        st["eflag"] = np.zeros(H, np.int32)     # emitted since flush
        st["parkp"] = np.zeros(H, np.int32)     # sendto EAGAIN pending
        st["had_holes"] = np.zeros(H, np.int32)
        # arrival register (the packet C_TCPIN is processing)
        for kk in PK_KEYS:
            st[f"ar_{kk}"] = np.zeros(H, PK_DTYPES[kk])
        # park-order counter: per-host relative (import remaps)
        park0 = np.zeros(H, np.int64)
        np.maximum.at(park0, st["c_host"][:n_conns],
                      st["c_awaitseq"][:n_conns] + 1)
        st["park_ctr"] = park0
        # padded-slot invariants
        st["ib_time"][np.arange(I)[None, :] >= st["ib_len"][:, None]] \
            = I64_MAX
        # conn lanes beyond n_conns must never match: park their host
        # at an impossible id
        st["c_host"][n_conns:] = -1
        return st

    def _from_arrays(self, st: dict) -> dict:
        """Back to the engine's packed-byte import layout (rings
        re-packed from their head positions)."""
        H = self._H
        I, T, CQ, RT, RA, OP = self._caps()
        CC = self._CC
        out = {}

        def npv(k):
            return np.asarray(st[k])

        def ring(pfx, cap, pos_k, len_k, modulo, rows, extra=()):
            pos = npv(pos_k).astype(np.int64)
            ln = npv(len_k).astype(np.int64)
            ar = np.arange(cap, dtype=np.int64)[None, :]
            idx = (pos[:, None] + ar) % cap if modulo \
                else np.minimum(pos[:, None] + ar, cap - 1)
            for kk in PK_KEYS:
                a = np.take_along_axis(npv(f"{pfx}_{kk}"), idx, axis=1)
                out[f"{pfx}_{kk}"] = np.ascontiguousarray(a).tobytes()
            for kk in extra:
                a = np.take_along_axis(npv(kk), idx, axis=1)
                out[kk] = np.ascontiguousarray(a).tobytes()
            out[len_k] = (ln - pos).astype(np.int32).tobytes()

        ring("cq", CQ, "cq_pos", "cq_len", True, H, extra=("cq_enq",))
        ring("ib", I, "ib_pos", "ib_len", False, H,
             extra=("ib_time", "ib_src", "ib_seq"))
        ring("op", OP, "op_pos", "op_len", True, CC)
        # rtx ring: non-PK columns, same pos/len repack
        pos = npv("rtx_pos").astype(np.int64)
        ln = npv("rtx_len").astype(np.int64)
        ar = np.arange(RT, dtype=np.int64)[None, :]
        idx = (pos[:, None] + ar) % RT
        for kk, dt in (("rtx_seq", np.uint32), ("rtx_plen", np.int32),
                       ("rtx_sent", np.int64)):
            a = np.take_along_axis(npv(kk), idx, axis=1)
            out[kk] = np.ascontiguousarray(a.astype(dt)).tobytes()
        for kk in ("rtx_rtxed", "rtx_sacked"):
            a = np.take_along_axis(npv(kk), idx, axis=1)
            out[kk] = np.ascontiguousarray(a.astype(np.uint8)).tobytes()
        out["rtx_len"] = (ln - pos).astype(np.int32).tobytes()
        # reassembly: compact valid entries
        rv = npv("ra_valid")
        order = np.argsort(~rv, axis=1, kind="stable")
        for kk, dt in (("ra_seq", np.uint32), ("ra_plen", np.int32)):
            a = np.take_along_axis(npv(kk), order, axis=1)
            out[kk] = np.ascontiguousarray(a.astype(dt)).tobytes()
        out["ra_len"] = rv.sum(axis=1).astype(np.int32).tobytes()
        # timer heap: compact valid entries
        tv = npv("th_valid")
        order = np.argsort(~tv, axis=1, kind="stable")
        for k, dt in (("th_time", np.int64), ("th_seq", np.int64),
                      ("th_tgt", np.int32)):
            a = np.take_along_axis(npv(k), order, axis=1)
            out[k] = np.ascontiguousarray(a.astype(dt)).tobytes()
        a = np.take_along_axis(npv("th_kind"), order, axis=1)
        out["th_kind"] = np.ascontiguousarray(
            a.astype(np.uint8)).tobytes()
        out["th_len"] = tv.sum(axis=1).astype(np.int32).tobytes()

        for k in ("now", "event_seq", "packet_seq", "codel_bytes",
                  "codel_count", "codel_last_count",
                  "codel_first_above", "codel_drop_next",
                  "codel_dropped", "codel_enq_pkts", "codel_enq_bytes",
                  "codel_drop_bytes", "codel_peak", "codel_marked",
                  "pkts_sent",
                  "pkts_recv", "pkts_dropped", "events_run",
                  "eth_psent", "eth_precv", "eth_bsent", "eth_brecv"):
            out[k] = npv(k).astype(np.int64).tobytes()
        out["codel_dropping"] = npv("codel_dropping").astype(
            np.uint8).tobytes()
        out["h_fault"] = npv("h_fault").astype(np.uint8).tobytes()
        for r in (1, 2):
            out[f"r{r}_pending"] = npv(f"r{r}_pending").astype(
                np.uint8).tobytes()
            out[f"r{r}_pk_valid"] = npv(f"r{r}_pk_valid").astype(
                np.uint8).tobytes()
            out[f"r{r}_bal"] = npv(f"r{r}_bal").astype(
                np.int64).tobytes()
            out[f"r{r}_next"] = npv(f"r{r}_next").astype(
                np.int64).tobytes()
            out[f"r{r}_stalls"] = npv(f"r{r}_stalls").astype(
                np.int64).tobytes()
            out[f"r{r}_fwd_pkts"] = npv(f"r{r}_fwd_pkts").astype(
                np.int64).tobytes()
            out[f"r{r}_fwd_bytes"] = npv(f"r{r}_fwd_bytes").astype(
                np.int64).tobytes()
            for kk in PK_KEYS:
                out[f"r{r}_pk_{kk}"] = np.ascontiguousarray(
                    npv(f"r{r}_pk_{kk}").astype(
                        PK_DTYPES[kk])).tobytes()
        out["app_sys"] = npv("app_sys").astype(np.int64).tobytes()
        out["drop_causes"] = npv("drop_causes").astype(
            np.int64).tobytes()
        out["mark_causes"] = npv("mark_causes").astype(
            np.int64).tobytes()
        for k, dt in (("c_snduna", np.uint32), ("c_sndnxt", np.uint32),
                      ("c_rcvnxt", np.uint32), ("c_recover", np.uint32),
                      ("c_status", np.uint32), ("c_await", np.uint32),
                      ("c_cwrend", np.uint32), ("c_dwend", np.uint32)):
            out[k] = npv(k).astype(dt).tobytes()
        for k in ("c_sndwnd", "c_sblen", "c_sbmax", "c_rblen",
                  "c_rbmax", "c_delackdl", "c_persistdl",
                  "c_persistiv", "c_cwnd", "c_ssthresh", "c_srtt",
                  "c_rttvar", "c_rto", "c_rtodl", "c_tsrecent",
                  "c_segssent", "c_segsrecv", "c_rtxcount",
                  "c_sackskip", "c_tmrdl", "c_atcopied", "c_atspace",
                  "c_atlast", "c_awaitseq", "c_agot",
                  "c_fbyte", "c_lbyte", "c_bin", "c_bout",
                  "c_alpha", "c_ceack", "c_totack", "c_ceseen"):
            out[k] = npv(k).astype(np.int64).tobytes()
        for k in ("c_ssa", "c_dupacks", "c_rtobackoff"):
            out[k] = npv(k).astype(np.int32).tobytes()
        for k in ("c_fastrec", "c_queued", "c_wakep", "c_ece",
                  "c_cwrp"):
            out[k] = npv(k).astype(np.uint8).tobytes()
        return out

    # ------------------------------------------------------------------
    # The jitted multi-round step
    # ------------------------------------------------------------------

    def _netstat_params(self):
        """(enabled, interval_ns>=1) — static for the built kernel."""
        if self.netstat is None:
            return (False, 1)
        return (True, max(int(self.netstat.interval_ns), 1))

    def _fabric_params(self):
        """(enabled, interval_ns>=1) — static for the built kernel."""
        if self.fabric is None:
            return (False, 1)
        return (True, max(int(self.fabric.interval_ns), 1))

    def _cached_build(self):
        key = (self._H, self._CC, self._caps(), self.cap_out,
               self.cap_tr, self.tracing, self.fused,
               self._netstat_params(), self._fabric_params(),
               self.kern is not None,
               self.dctcp_k, self.mesh, self.exchange_cap,
               self.pallas_queues)
        return self._cache_fn(_FN_CACHE, key, self._build)

    def _build(self):
        import jax
        import jax.numpy as jnp

        H = self._H
        CC = self._CC
        I, T, CQ, RT, RA, OP = self._caps()
        O = self.cap_out
        TR = self.cap_tr
        tracing = self.tracing
        fused = self.fused    # static: fused vs reference dispatch
        n_shards = self.n_shards  # static: mesh width (1 = unsharded)
        exchange = (self._build_exchange(jax, jnp)
                    if n_shards > 1 else None)
        netstat, tel_iv = self._netstat_params()
        TELR = self.TEL_ROWS
        fabric, fab_iv = self._fabric_params()
        FABR = self.FAB_ROWS
        kern = self.kern is not None  # static: stage counters on
        # DCTCP-K marking threshold: static closure constants (config-
        # constant per Manager; part of the _FN_CACHE key).
        k_pkts, k_bytes = self.dctcp_k
        hidx = jnp.arange(H, dtype=jnp.int32)
        OOB = jnp.int32(H + 1)
        COOB = jnp.int32(CC + 1)

        # Lane-parallel queue-scan kernels (ISSUE 16, phold_span
        # twin): shared bucket/CoDel-head laws from pallas_queues —
        # inline lax reference, or the pallas twin when the knob is
        # on (unsharded only).  Static: part of the _FN_CACHE key.
        from shadow_tpu.ops import pallas_queues as plq
        pq = self.pallas_queues and n_shards == 1
        bucket_step = plq.make_bucket_step(jax, jnp, H, REFILL_NS, pq)
        codel_head = plq.make_codel_head(jax, jnp, H, CODEL_TARGET_NS,
                                         MTU, pq)

        def mrows(mask):
            return jnp.where(mask, hidx, OOB)

        def s_i64(a):
            return a.astype(jnp.int64)

        def s_sub(a, b):
            d = (s_i64(a) - s_i64(b)) & jnp.int64(0xFFFFFFFF)
            return d - jnp.where(d >= SEQ_HALF, SEQ_MOD, jnp.int64(0))

        def s_add(a, n):
            return (s_i64(a) + s_i64(n)).astype(jnp.uint32)

        def s_lt(a, b):
            return s_sub(a, b) < 0

        def s_leq(a, b):
            return s_sub(a, b) <= 0

        def mark_abort(st, cond, bit, site=0):
            st = dict(st)
            hit = cond if getattr(cond, "ndim", 0) == 0 else cond.any()
            st["abort_code"] = st["abort_code"] | jnp.where(
                hit, jnp.int32(bit), jnp.int32(0))
            st["abort_site"] = jnp.where(
                hit & (st["abort_site"] == 0), jnp.int32(site),
                st["abort_site"])
            return st

        def ks_count(st, code, mask):
            """Device-kernel observatory (phold_span twin): credit one
            stage with this iteration's active lanes.  Pure counters
            in the carry — never simulation state."""
            if not kern:
                return st
            st = dict(st)
            n = mask.sum().astype(jnp.int64)
            st["ks_lanes"] = st["ks_lanes"].at[code].add(n)
            st["ks_fires"] = st["ks_fires"].at[code].add(
                (n > 0).astype(jnp.int64))
            return st

        def ks_count_pop(st, mask, window_end):
            """All due lanes fire the pop stage (timer HANDLING is
            counted at its handler stage — op_tmr/op_app/relays run
            in the same fused iteration)."""
            if not kern:
                return st
            ib_t, th_t = next_event_time(st)
            due = mask & (jnp.minimum(ib_t, th_t) < window_end)
            return ks_count(st, KS_POP, due)

        def draw_seq(st, mask):
            v = st["event_seq"]
            st = dict(st)
            st["event_seq"] = jnp.where(mask, v + 1, v)
            return st, v

        def th_push(st, mask, time, seq, kind, tgt):
            free = jnp.argmin(st["th_valid"], axis=1)
            overflow = mask & st["th_valid"].all(axis=1)
            mask = mask & ~overflow
            rows = mrows(mask)
            st = dict(st)
            st["th_time"] = st["th_time"].at[rows, free].set(
                time, mode="drop")
            st["th_seq"] = st["th_seq"].at[rows, free].set(
                seq, mode="drop")
            st["th_kind"] = st["th_kind"].at[rows, free].set(
                jnp.full(H, kind, jnp.int32) if np.isscalar(kind)
                else kind, mode="drop")
            st["th_tgt"] = st["th_tgt"].at[rows, free].set(
                tgt, mode="drop")
            st["th_valid"] = st["th_valid"].at[rows, free].set(
                True, mode="drop")
            return mark_abort(st, overflow.any(), AB_STRUCT, 1)

        def th_min(st):
            t = jnp.where(st["th_valid"], st["th_time"], I64_MAX)
            best_t = t.min(axis=1)
            s = jnp.where(t == best_t[:, None], st["th_seq"], I64_MAX)
            slot = jnp.argmin(s, axis=1)
            return (best_t, st["th_kind"][hidx, slot],
                    st["th_tgt"][hidx, slot], slot)

        # -------- conn gather/scatter via the per-host cur register --

        def cg(st, key):
            return st[key][jnp.clip(st["cur"], 0, CC - 1)]

        def crows(st, mask):
            return jnp.where(mask & (st["cur"] >= 0), st["cur"], COOB)

        def cset(st, mask, **vals):
            rows = crows(st, mask)
            st = dict(st)
            for key, v in vals.items():
                st[key] = st[key].at[rows].set(v, mode="drop")
            return st

        def fct_touch(st, mask, nbytes, inbound):
            """Flow-lifecycle update (connection.py _fct_touch twin):
            first/last data-byte stamps plus the byte counter, on the
            masked lanes' cur conns."""
            now = st["now"]
            fb = cg(st, "c_fbyte")
            key = "c_bin" if inbound else "c_bout"
            vals = {
                "c_fbyte": jnp.where(mask & (fb < 0), now, fb),
                "c_lbyte": jnp.where(mask, now, cg(st, "c_lbyte")),
                key: cg(st, key) + jnp.where(mask, nbytes,
                                             jnp.int64(0)),
            }
            return cset(st, mask, **vals)

        # -------- trace / outbox appends (flat buffers) --------------

        def seq_append(st, cap_total, mask, cols, count_key, abort_bit):
            st = dict(st)
            n = st[count_key]
            rank = jnp.cumsum(mask) - 1
            slot = jnp.where(mask, n + rank, cap_total + 8)
            for key, v in cols.items():
                st[key] = st[key].at[slot].set(v, mode="drop")
            total = n + mask.sum()
            st[count_key] = total
            return mark_abort(st, total > cap_total - H, abort_bit)

        def tr_append(st, mask, time, kind, pk, reason):
            if not tracing:
                return st
            return seq_append(
                st, TR, mask,
                {"tr_t": time,
                 "tr_kind": jnp.full(H, kind, jnp.int32),
                 "tr_srchost": pk["srchost"], "tr_pseq": pk["pseq"],
                 "tr_sip": pk["sip"], "tr_sport": pk["sport"],
                 "tr_dip": pk["dip"], "tr_dport": pk["dport"],
                 "tr_plen": pk["plen"],
                 "tr_reason": jnp.full(H, reason, jnp.int32),
                 "tr_owner": hidx}, "tr_n", AB_TRACE)

        # -------- TCP helpers (connection.py twins, lane-vectorized) --

        def recv_window(st):
            cap = s_i64(jnp.int64(MAX_WINDOW)) << cg(st, "c_ourws")
            space = jnp.maximum(jnp.int64(0),
                                cg(st, "c_rbmax") - cg(st, "c_rblen"))
            return jnp.minimum(cap, space)

        def wire_window(st):
            # non-SYN segments only in-domain: always scaled
            return jnp.minimum(recv_window(st) >> cg(st, "c_ourws"),
                               jnp.int64(MAX_WINDOW))

        def sack_blocks(st):
            """Merged reassembly runs for the host lanes' cur conns:
            (nsk, s0,e0,s1,e1,s2,e2) — connection.py _sack_blocks."""
            cur = jnp.clip(st["cur"], 0, CC - 1)
            valid = st["ra_valid"][cur]                     # (H, RA)
            seq = st["ra_seq"][cur]
            plen = st["ra_plen"][cur]
            base = cg(st, "c_rcvnxt")[:, None]
            rel = jnp.where(valid, s_sub(seq, base), I64_MAX)
            order = jnp.argsort(rel, axis=1)
            take = jnp.take_along_axis
            rs = take(rel, order, axis=1)                   # starts
            re = rs + take(jnp.where(valid, plen, 0), order,
                           axis=1).astype(jnp.int64)        # ends
            sv = take(valid, order, axis=1)
            # merged-run boundaries: start beyond the running max end
            prev_end = jnp.concatenate(
                [jnp.full((H, 1), -I64_MAX),
                 jax.lax.cummax(re, axis=1)[:, :-1]], axis=1)
            newrun = sv & (rs > prev_end)
            run_id = jnp.cumsum(newrun, axis=1)             # 1-based
            run_end = jax.lax.cummax(jnp.where(sv, re, -I64_MAX),
                                     axis=1)
            nsk = jnp.minimum(run_id.max(axis=1), 3).astype(jnp.int32)
            outs = []
            for r in range(3):
                inr = sv & (run_id == r + 1)
                srel = jnp.min(jnp.where(newrun & (run_id == r + 1),
                                         rs, I64_MAX), axis=1)
                erel = jnp.max(jnp.where(inr, run_end, -I64_MAX),
                               axis=1)
                has = inr.any(axis=1)
                s_abs = jnp.where(has, s_add(cg(st, "c_rcvnxt"), srel),
                                  jnp.uint32(0))
                e_abs = jnp.where(has, s_add(cg(st, "c_rcvnxt"), erel),
                                  jnp.uint32(0))
                outs += [s_abs, e_abs]
            return (nsk,) + tuple(outs)

        def take_ts_echo(st, mask):
            tse = cg(st, "c_tsrecent")
            st = cset(st, mask, c_tsrecent=jnp.where(
                mask, jnp.int64(0), cg(st, "c_tsrecent")))
            return st, tse

        def emit(st, mask, tseq, plen, flags, with_sacks, track,
                 fresh=False):
            """One segment from each masked lane's cur conn into its
            egress ring — the outbox+flush collapse: emission order IS
            flush order, so pseq assignment at emission is identical.
            All in-domain emissions carry ACK (note_ack_sent).
            ECN: the receiver latch echoes ECE on every segment
            (connection.py _emit twin — in-domain segments never carry
            SYN), `fresh` data consumes a pending one-shot CWR
            (_data_flags twin), and ECN-active data carries ECT(0)."""
            now = st["now"]
            win = wire_window(st)
            if with_sacks:
                nsk, s0, e0, s1, e1, s2, e2 = sack_blocks(st)
            else:
                z = jnp.zeros(H, jnp.uint32)
                nsk = jnp.zeros(H, jnp.int32)
                s0 = e0 = s1 = e1 = s2 = e2 = z
            st, tse = take_ts_echo(st, mask)
            fl = jnp.full(H, flags, jnp.int32) \
                | jnp.where(cg(st, "c_ece") == 1, jnp.int32(F_ECE),
                            jnp.int32(0))
            if fresh:
                do_cwr = mask & (plen > 0) & (cg(st, "c_cwrp") == 1) \
                    & (cg(st, "c_ecnact") == 1)
                fl = fl | jnp.where(do_cwr, jnp.int32(F_CWR),
                                    jnp.int32(0))
                st = cset(st, do_cwr, c_cwrp=jnp.int32(0))
            ecn = jnp.where((cg(st, "c_ecnact") == 1) & (plen > 0),
                            jnp.int32(ECN_ECT0), jnp.int32(0))
            pseq = st["packet_seq"]
            st = dict(st)
            st["packet_seq"] = jnp.where(mask, pseq + 1, pseq)
            cur = jnp.clip(st["cur"], 0, CC - 1)
            tail = (st["op_len"][cur] % OP).astype(jnp.int32)
            over = mask & (st["op_len"][cur] - st["op_pos"][cur]
                           >= OP - 1)
            st = mark_abort(st, over.any(), AB_STRUCT, 2)
            st = dict(st)
            rows = crows(st, mask)
            vals = {"srchost": hidx, "pseq": pseq,
                    "sip": cg(st, "c_lip"), "sport": cg(st, "c_lport"),
                    "dip": cg(st, "c_pip"), "dport": cg(st, "c_pport"),
                    "tseq": tseq, "tack": cg(st, "c_rcvnxt"),
                    "tflags": fl,
                    "twin": win, "tsv": now + 1, "tse": tse,
                    "plen": plen.astype(jnp.int32), "nsk": nsk,
                    "sk0s": s0, "sk0e": e0, "sk1s": s1, "sk1e": e1,
                    "sk2s": s2, "sk2e": e2, "ecn": ecn}
            for kk in PK_KEYS:
                st[f"op_{kk}"] = st[f"op_{kk}"].at[rows, tail].set(
                    vals[kk], mode="drop")
            st["op_len"] = st["op_len"].at[rows].add(1, mode="drop")
            st["c_segssent"] = st["c_segssent"].at[rows].add(
                1, mode="drop")
            # note_ack_sent: segs_since_ack=0, delack cleared
            st["c_ssa"] = st["c_ssa"].at[rows].set(0, mode="drop")
            st["c_delackdl"] = st["c_delackdl"].at[rows].set(
                jnp.int64(-1), mode="drop")
            st["eflag"] = jnp.where(mask, 1, st["eflag"])
            if track:
                rtail = (st["rtx_len"][cur] % RT).astype(jnp.int32)
                rover = mask & (st["rtx_len"][cur]
                                - st["rtx_pos"][cur] >= RT - 1)
                st = mark_abort(st, rover.any(), AB_STRUCT, 3)
                st = dict(st)
                st["rtx_seq"] = st["rtx_seq"].at[rows, rtail].set(
                    tseq, mode="drop")
                st["rtx_plen"] = st["rtx_plen"].at[rows, rtail].set(
                    plen.astype(jnp.int32), mode="drop")
                st["rtx_rtxed"] = st["rtx_rtxed"].at[rows, rtail].set(
                    0, mode="drop")
                st["rtx_sacked"] = st["rtx_sacked"].at[rows, rtail].set(
                    0, mode="drop")
                st["rtx_sent"] = st["rtx_sent"].at[rows, rtail].set(
                    now, mode="drop")
                st["rtx_len"] = st["rtx_len"].at[rows].add(
                    1, mode="drop")
                # emit(track): arm RTO if not armed
                arm = mask & (cg(st, "c_rtodl") < 0)
                st = cset(st, arm, c_rtodl=now + cg(st, "c_rto"))
            return st

        def emit_ack(st, mask):
            return emit(st, mask, cg(st, "c_sndnxt"),
                        jnp.zeros(H, jnp.int64), F_ACK,
                        with_sacks=True, track=False)

        # -------- token bucket / relays ------------------------------

        def bucket_try(st, r, now, mask, size):
            bal = st[f"r{r}_bal"]
            nxt = st[f"r{r}_next"]
            bal3, nxt2, ok = bucket_step(
                bal, nxt, st[f"r{r}_refill"], st[f"r{r}_cap"],
                st[f"r{r}_unlimited"] == 1, size, now)
            st = dict(st)
            st[f"r{r}_bal"] = jnp.where(mask, bal3, bal)
            st[f"r{r}_next"] = jnp.where(mask, nxt2, nxt)
            return st, ok, nxt2

        def control_time(t, count):
            v = count << 32
            g = jnp.sqrt(v.astype(jnp.float64)).astype(jnp.int64)
            g = jnp.where(g * g > v, g - 1, g)
            g = jnp.where(g * g > v, g - 1, g)
            g = jnp.where((g + 1) * (g + 1) <= v, g + 1, g)
            g = jnp.where((g + 1) * (g + 1) <= v, g + 1, g)
            g = jnp.maximum(g, 1)
            return t + (np.int64(100_000_000) << 16) // g

        def op_relay1(st, mask):
            """inet-out drain: iface_pop over the host's queued conns
            (min head priority = the engine's per-iface qdisc heap),
            SND trace, token bucket, cross-host outbox."""
            now = st["now"]
            use_pend = mask & (st["r1_pk_valid"] == 1)
            # qdisc selection: min head-pseq among queued conns
            head = (st["op_pos"] % OP).astype(jnp.int32)
            cidx = jnp.arange(CC, dtype=jnp.int32)
            nonempty = st["op_len"] > st["op_pos"]
            eligible = (st["c_queued"] == 1) & nonempty \
                & (st["c_host"] >= 0)
            head_prio = st["op_pseq"][cidx, head]
            chost_safe = jnp.where(st["c_host"] >= 0, st["c_host"], H)
            best = jnp.full(H + 1, I64_MAX, jnp.int64).at[
                chost_safe].min(jnp.where(eligible, head_prio,
                                          I64_MAX))[:H]
            src_avail = mask & ~use_pend & (best < I64_MAX)
            sel_match = eligible & (head_prio == best[chost_safe
                                                      .clip(0, H - 1)])
            sel = jnp.full(H + 1, -1, jnp.int32).at[chost_safe].max(
                jnp.where(sel_match, cidx, -1))[:H]
            sel_safe = jnp.clip(sel, 0, CC - 1)
            hsel = head[sel_safe]
            pk = {kk: jnp.where(use_pend, st[f"r1_pk_{kk}"],
                                st[f"op_{kk}"][sel_safe, hsel])
                  for kk in PK_KEYS}
            pop = src_avail
            st = dict(st)
            st["r1_pk_valid"] = jnp.where(use_pend, 0,
                                          st["r1_pk_valid"])
            # iface_pop: dequeue + requeue-if-more + SND trace + eth
            rows = jnp.where(pop, sel, COOB)
            st["op_pos"] = st["op_pos"].at[rows].add(1, mode="drop")
            still = st["op_len"][sel_safe] > st["op_pos"][sel_safe]
            st["c_queued"] = st["c_queued"].at[rows].set(
                jnp.where(still, 1, 0), mode="drop")
            size = s_i64(pk["plen"]) + TCP_TOTAL_HDR
            st["eth_psent"] = jnp.where(pop, st["eth_psent"] + 1,
                                        st["eth_psent"])
            st["eth_bsent"] = jnp.where(pop, st["eth_bsent"] + size,
                                        st["eth_bsent"])
            st = tr_append(st, pop, now, TR_SND, pk, 0)
            st = dict(st)

            has_pkt = use_pend | pop
            st, ok, when = bucket_try(st, 1, now, has_pkt, size)
            throttled = has_pkt & ~ok
            st = dict(st)
            st["r1_stalls"] = st["r1_stalls"] + throttled
            st["r1_pending"] = jnp.where(throttled, 1,
                                         st["r1_pending"])
            st["r1_pk_valid"] = jnp.where(throttled, 1,
                                          st["r1_pk_valid"])
            for kk in PK_KEYS:
                st[f"r1_pk_{kk}"] = jnp.where(throttled, pk[kk],
                                              st[f"r1_pk_{kk}"])
            st, sq = draw_seq(st, throttled)
            st = th_push(st, throttled, when, sq, TK_RELAY,
                         jnp.full(H, 1, jnp.int32))
            st = dict(st)

            fwd = has_pkt & ok
            st["r1_fwd_pkts"] = st["r1_fwd_pkts"] + fwd
            st["r1_fwd_bytes"] = st["r1_fwd_bytes"] \
                + jnp.where(fwd, size, jnp.int64(0))
            st["pkts_sent"] = jnp.where(fwd, st["pkts_sent"] + 1,
                                        st["pkts_sent"])
            # NIC link down (device_push twin): the send dies at the
            # egress instant, BEFORE the dst lookup and the event-seq
            # draw (docs/ROBUSTNESS.md).
            linkdn = fwd & ((st["h_fault"] & 2) != 0)
            st["pkts_dropped"] = jnp.where(
                linkdn, st["pkts_dropped"] + 1, st["pkts_dropped"])
            st["drop_causes"] = st["drop_causes"].at[
                mrows(linkdn), TEL_LINK_DOWN].add(1, mode="drop")
            st = tr_append(st, linkdn, now, TR_DRP, pk, RSN_LINKDOWN)
            st = dict(st)
            fwd = fwd & ~linkdn
            # device_push(dev=2): dst must be a remote engine host
            dslot = jnp.minimum(
                jnp.searchsorted(st["_ips_sorted"], pk["dip"]), H - 1)
            found = st["_ips_sorted"][dslot] == pk["dip"]
            dst = st["_ips_perm"][dslot]
            bad = fwd & (~found | (dst == hidx))
            st = mark_abort(st, bad.any(), AB_STRUCT, 4)
            st = dict(st)
            hit = fwd & found
            st, sq = draw_seq(st, hit)
            cols = {"out_src": hidx, "out_dst": dst, "out_seq": sq,
                    "out_t": now}
            for kk in PK_KEYS:
                cols[f"out_{kk}"] = pk[kk]
            st = seq_append(st, O, hit, cols, "out_n", AB_OUT)
            st = dict(st)
            done = mask & ~has_pkt | throttled
            st["cont"] = jnp.where(done, st["then"], st["cont"])
            return st

        def op_relay2(st, mask):
            """inet-in drain: CoDel pop -> token bucket ->
            iface_receive -> conn match -> hand to C_TCPIN."""
            now = st["now"]
            use_pend = mask & (st["r2_pk_valid"] == 1)
            src_avail = mask & ~use_pend & (st["cq_len"]
                                            > st["cq_pos"])
            pos = st["cq_pos"] % CQ
            pk = {kk: jnp.where(use_pend, st[f"r2_pk_{kk}"],
                                st[f"cq_{kk}"][hidx, pos])
                  for kk in PK_KEYS}
            enq = st["cq_enq"][hidx, pos]
            pop = mask & ~use_pend & src_avail
            none = mask & ~use_pend & ~src_avail
            size = s_i64(pk["plen"]) + TCP_TOTAL_HDR

            st = dict(st)
            st["r2_pk_valid"] = jnp.where(use_pend, 0,
                                          st["r2_pk_valid"])
            st["cq_pos"] = jnp.where(pop, st["cq_pos"] + 1,
                                     st["cq_pos"])
            st["codel_bytes"] = jnp.where(
                pop, st["codel_bytes"] - size, st["codel_bytes"])
            # dequeue_raw's ok/first_above law (pallas_queues)
            quiet, above, arm, cok, fa_new = codel_head(
                pop, none, now, enq, st["codel_bytes"],
                st["codel_first_above"])
            st["codel_first_above"] = fa_new
            st["codel_dropping"] = jnp.where(none, 0,
                                             st["codel_dropping"])
            st["cd_chain"] = jnp.where(none, 0, st["cd_chain"])
            st["cd_sniff"] = jnp.where(none, 0, st["cd_sniff"])

            in_sniff = st["cd_sniff"] == 1
            in_chain = (st["cd_chain"] == 1) & ~in_sniff
            top = pop & ~in_sniff & ~in_chain

            sg = pop & in_sniff
            cnt_new = jnp.where(
                now - st["codel_drop_next"] < np.int64(100_000_000),
                jnp.where(st["codel_count"] > 2,
                          st["codel_count"] - st["codel_last_count"],
                          1), 1)
            st["codel_dropping"] = jnp.where(sg, 1,
                                             st["codel_dropping"])
            st["codel_count"] = jnp.where(sg, cnt_new,
                                          st["codel_count"])
            st["codel_last_count"] = jnp.where(
                sg, cnt_new, st["codel_last_count"])
            st["codel_drop_next"] = jnp.where(
                sg, control_time(now, cnt_new), st["codel_drop_next"])
            st["cd_sniff"] = jnp.where(sg, 0, st["cd_sniff"])

            cg_ = pop & in_chain
            cg_exit = cg_ & ~cok
            st["codel_dropping"] = jnp.where(cg_exit, 0,
                                             st["codel_dropping"])
            st["cd_chain"] = jnp.where(cg_exit, 0, st["cd_chain"])
            cg_ok = cg_ & cok
            dn2 = control_time(st["codel_drop_next"],
                               st["codel_count"])
            st["codel_drop_next"] = jnp.where(cg_ok, dn2,
                                              st["codel_drop_next"])
            cg_drop = cg_ok & (now >= st["codel_drop_next"])
            cg_deliver = cg_ok & ~cg_drop
            st["cd_chain"] = jnp.where(cg_deliver, 0, st["cd_chain"])

            td = top & (st["codel_dropping"] == 1)
            td_exit = td & ~cok
            st["codel_dropping"] = jnp.where(td_exit, 0,
                                             st["codel_dropping"])
            td_ok = td & cok
            td_drop = td_ok & (now >= st["codel_drop_next"])
            st["cd_chain"] = jnp.where(td_drop, 1, st["cd_chain"])

            tl = top & ~td & cok & (
                (now - st["codel_drop_next"] < np.int64(100_000_000))
                | (now - st["codel_first_above"]
                   >= np.int64(100_000_000)))
            st["cd_sniff"] = jnp.where(tl, 1, st["cd_sniff"])

            codel_drop = cg_drop | td_drop | tl
            st["codel_count"] = jnp.where(
                cg_drop | td_drop, st["codel_count"] + 1,
                st["codel_count"])
            st["codel_dropped"] = jnp.where(
                codel_drop, st["codel_dropped"] + 1,
                st["codel_dropped"])
            st["codel_drop_bytes"] = jnp.where(
                codel_drop, st["codel_drop_bytes"] + size,
                st["codel_drop_bytes"])
            st["pkts_dropped"] = jnp.where(
                codel_drop, st["pkts_dropped"] + 1,
                st["pkts_dropped"])
            st["drop_causes"] = st["drop_causes"].at[
                mrows(codel_drop), TEL_CODEL].add(1, mode="drop")
            st = tr_append(st, codel_drop, now, TR_DRP, pk, RSN_CODEL)
            st = dict(st)
            pop = pop & ~codel_drop

            has_pkt = use_pend | pop
            st, ok, when = bucket_try(st, 2, now, has_pkt, size)
            throttled = has_pkt & ~ok
            st = dict(st)
            st["r2_stalls"] = st["r2_stalls"] + throttled
            st["r2_pending"] = jnp.where(throttled, 1,
                                         st["r2_pending"])
            st["r2_pk_valid"] = jnp.where(throttled, 1,
                                          st["r2_pk_valid"])
            for kk in PK_KEYS:
                st[f"r2_pk_{kk}"] = jnp.where(throttled, pk[kk],
                                              st[f"r2_pk_{kk}"])
            st, sq = draw_seq(st, throttled)
            st = th_push(st, throttled, when, sq, TK_RELAY,
                         jnp.full(H, 2, jnp.int32))
            st = dict(st)

            fwd = has_pkt & ok
            st["r2_fwd_pkts"] = st["r2_fwd_pkts"] + fwd
            st["r2_fwd_bytes"] = st["r2_fwd_bytes"] \
                + jnp.where(fwd, size, jnp.int64(0))
            # iface_receive: eth counters, then the association match
            st["eth_precv"] = jnp.where(fwd, st["eth_precv"] + 1,
                                        st["eth_precv"])
            st["eth_brecv"] = jnp.where(fwd, st["eth_brecv"] + size,
                                        st["eth_brecv"])
            st = mark_abort(st, (fwd & (pk["dip"]
                                        != st["eth_ip"])).any(),
                            AB_STRUCT, 5)
            st = dict(st)
            # conn lookup: (dsthost, src-ip-host, sport) key
            sslot = jnp.minimum(
                jnp.searchsorted(st["_ips_sorted"], pk["sip"]), H - 1)
            sfound = st["_ips_sorted"][sslot] == pk["sip"]
            sidx = st["_ips_perm"][sslot]
            akey = (s_i64(hidx) * H + s_i64(sidx)) * 65536 \
                + s_i64(pk["sport"])
            kslot = jnp.minimum(
                jnp.searchsorted(st["_ckeys"], akey), CC - 1)
            kfound = sfound & (st["_ckeys"][kslot] == akey)
            conn = st["_ckperm"][kslot]
            good_port = kfound & (st["c_lport"][conn] == pk["dport"])
            st = mark_abort(st, (fwd & ~good_port).any(), AB_STRUCT, 6)
            st = dict(st)
            hit = fwd & good_port
            # delivered: trace RCV at arrival (sort key separates it
            # from same-instant SND/DRP lines; append order is free)
            st["pkts_recv"] = jnp.where(hit, st["pkts_recv"] + 1,
                                        st["pkts_recv"])
            st = tr_append(st, hit, now, TR_RCV, pk, 0)
            st = dict(st)
            # hand to the state machine: C_TCPIN on this conn
            st["cur"] = jnp.where(hit, conn, st["cur"])
            for kk in PK_KEYS:
                st[f"ar_{kk}"] = jnp.where(hit, pk[kk],
                                           st[f"ar_{kk}"])
            st["ret"] = jnp.where(hit, C_R2, st["ret"])
            st["cont"] = jnp.where(hit, C_TCPIN, st["cont"])
            # r2 drains only ever start from an event (arrival /
            # TK_RELAY wake), so the return is always idle — `then`
            # stays r1's register (the nested flush->r1 drains inside
            # this chain would clobber a shared one).
            done = none | throttled
            st["cont"] = jnp.where(done, C_IDLE, st["cont"])
            return st

        # -------- TCP state machine ----------------------------------

        def update_rtt(st, mask, sample):
            sample = jnp.maximum(sample, 1)
            srtt = cg(st, "c_srtt")
            rttvar = cg(st, "c_rttvar")
            first = srtt == 0
            n_srtt = jnp.where(first, sample,
                               (7 * srtt + sample) // 8)
            err = jnp.abs(srtt - sample)
            n_var = jnp.where(first, sample // 2,
                              (3 * rttvar + err) // 4)
            rto = n_srtt + jnp.maximum(4 * n_var,
                                       jnp.int64(1_000_000))
            rto = jnp.clip(rto, MIN_RTO_NS, MAX_RTO_NS)
            return cset(st, mask, c_srtt=n_srtt, c_rttvar=n_var,
                        c_rto=rto)

        def rtx_rows(st):
            """Gathered rtx rings for the cur conns: (H, RT) views in
            ring order plus the valid mask."""
            cur = jnp.clip(st["cur"], 0, CC - 1)
            pos = st["rtx_pos"][cur][:, None]
            ln = st["rtx_len"][cur][:, None]
            ar = jnp.arange(RT, dtype=jnp.int32)[None, :]
            idx = ((pos + ar) % RT).astype(jnp.int32)
            take = jnp.take_along_axis
            rows = {k: take(st[k][cur], idx, axis=1)
                    for k in ("rtx_seq", "rtx_plen", "rtx_rtxed",
                              "rtx_sacked", "rtx_sent")}
            rows["valid"] = ar < (ln - pos)
            rows["idx"] = idx
            return rows

        def rtx_scatter(st, mask, rows, keys):
            st = dict(st)
            rmask = crows(st, mask)[:, None]  # broadcasts with idx
            for k in keys:
                st[k] = st[k].at[rmask, rows["idx"]].set(
                    rows[k], mode="drop")
            return st

        def clear_acked(st, mask):
            """Pop leading fully-acked rtx entries (ring-order run)."""
            rows = rtx_rows(st)
            end = s_add(rows["rtx_seq"], rows["rtx_plen"])
            una = cg(st, "c_snduna")[:, None]
            covered = rows["valid"] & s_leq(end, una)
            lead = jnp.cumprod(covered.astype(jnp.int32), axis=1)
            pops = lead.sum(axis=1).astype(jnp.int32)
            cur = jnp.clip(st["cur"], 0, CC - 1)
            # pos/len grow monotonically (mod applied at access, like
            # every other ring here): popping only advances pos
            new_pos = st["rtx_pos"][cur] + pops
            st = dict(st)
            r = crows(st, mask)
            st["rtx_pos"] = st["rtx_pos"].at[r].set(new_pos,
                                                    mode="drop")
            return st

        def retransmit_one(st, mask):
            """First non-SACKed rtx entry (head fallback), re-stamped
            and re-emitted with the current scoreboard attached."""
            now = st["now"]
            rows = rtx_rows(st)
            ar = jnp.arange(RT)[None, :]
            cand = rows["valid"] & (rows["rtx_sacked"] == 0)
            first = jnp.where(cand.any(axis=1),
                              jnp.argmax(cand, axis=1), 0)
            has = mask & rows["valid"].any(axis=1)
            sel = first
            seq = jnp.take_along_axis(rows["rtx_seq"], sel[:, None],
                                      axis=1)[:, 0]
            plen = jnp.take_along_axis(rows["rtx_plen"], sel[:, None],
                                       axis=1)[:, 0]
            slot = jnp.take_along_axis(rows["idx"], sel[:, None],
                                       axis=1)[:, 0]
            r = crows(st, has)
            st = dict(st)
            st["rtx_sent"] = st["rtx_sent"].at[r, slot].set(
                now, mode="drop")
            st["rtx_rtxed"] = st["rtx_rtxed"].at[r, slot].set(
                1, mode="drop")
            st["c_rtxcount"] = st["c_rtxcount"].at[r].add(
                1, mode="drop")
            del ar
            return emit(st, has, seq, s_i64(plen), F_ACK | F_PSH,
                        with_sacks=True, track=False)

        def op_tcpin(st, mask):
            """on_packet minus the push_data / reassembly-drain loops
            (those continue as C_PUSH / C_DRAIN)."""
            now = st["now"]
            pk = {kk: st[f"ar_{kk}"] for kk in PK_KEYS}
            plen = s_i64(pk["plen"])
            st = cset(st, mask,
                      c_segsrecv=cg(st, "c_segsrecv")
                      + jnp.where(mask, 1, 0))
            # in-domain wire: synchronized-state segments only
            bad = mask & (((pk["tflags"] & (F_SYN | F_FIN | F_RST))
                           != 0) | ((pk["tflags"] & F_ACK) == 0))
            # a data segment arriving at a sender (or acking unsent
            # data) leaves the modelled tgen roles
            bad |= mask & (plen > 0) & (cg(st, "c_role") == 1)
            bad |= mask & s_lt(cg(st, "c_sndnxt"), pk["tack"])
            st = mark_abort(st, bad.any(), AB_STRUCT, 7)
            st = dict(st)
            # RFC 3168 receiver (connection.py on_packet twin): CWR
            # ends the echo episode, a CE-marked arrival (re)starts
            # it — in that order.
            ecnact = cg(st, "c_ecnact") == 1
            cwr_in = mask & ecnact & ((pk["tflags"] & F_CWR) != 0)
            st = cset(st, cwr_in, c_ece=jnp.int32(0))
            ce_in = mask & ecnact & (pk["ecn"] == ECN_CE)
            st = cset(st, ce_in, c_ece=jnp.int32(1),
                      c_ceseen=cg(st, "c_ceseen") + 1)
            # RFC 7323 ts_recent update (covering the ack point)
            span = jnp.maximum(plen, 1)
            upd = mask & (pk["tsv"] != 0) \
                & s_leq(pk["tseq"], cg(st, "c_rcvnxt")) \
                & s_lt(cg(st, "c_rcvnxt"), s_add(pk["tseq"], span))
            st = cset(st, upd, c_tsrecent=jnp.where(upd, pk["tsv"],
                                                    cg(st,
                                                       "c_tsrecent")))
            # RTTM: sample only from a segment acking NEW data
            samp = mask & (pk["tse"] != 0) \
                & (cg(st, "c_rtobackoff") == 0) \
                & s_lt(cg(st, "c_snduna"), pk["tack"]) \
                & s_leq(pk["tack"], cg(st, "c_sndnxt"))
            st = update_rtt(st, samp, now - (pk["tse"] - 1))
            # ---- on_ack ----
            ack = pk["tack"]
            wnd = pk["twin"] << cg(st, "c_peerws")
            wchanged = wnd != cg(st, "c_sndwnd")
            st = cset(st, mask, c_sndwnd=jnp.where(
                mask, wnd, cg(st, "c_sndwnd")))
            open_persist = mask & (wnd > 0) \
                & (cg(st, "c_persistdl") >= 0)
            st = cset(st, open_persist,
                      c_persistdl=jnp.int64(-1),
                      c_persistiv=jnp.int64(0))
            # SACK scoreboard marks
            have_sack = mask & (pk["nsk"] > 0)
            rows = rtx_rows(st)
            end = s_add(rows["rtx_seq"], rows["rtx_plen"])
            cov = jnp.zeros((H, RT), bool)
            for b in range(3):
                bs = pk[f"sk{b}s"][:, None]
                be = pk[f"sk{b}e"][:, None]
                bv = (pk["nsk"] > b)[:, None]
                cov |= bv & s_leq(bs, rows["rtx_seq"]) \
                    & s_leq(end, be)
            newly = have_sack[:, None] & rows["valid"] \
                & (rows["rtx_sacked"] == 0) & cov
            rows["rtx_sacked"] = jnp.where(newly, 1,
                                           rows["rtx_sacked"])
            st = rtx_scatter(st, have_sack, rows, ("rtx_sacked",))
            st = cset(st, have_sack,
                      c_sackskip=cg(st, "c_sackskip")
                      + newly.sum(axis=1))
            # ECN sender side (connection.py _on_ack twin, the same
            # position: after the SACK marks, before the new-ack/
            # dupack dispatch — snd_una still pre-ack).
            ece_fl = mask & ecnact & ((pk["tflags"] & F_ECE) != 0)
            new_ack0 = mask & s_lt(cg(st, "c_snduna"), pk["tack"])
            acked0 = s_sub(pk["tack"], cg(st, "c_snduna"))
            is_d = cg(st, "c_cc") == CC_DCTCP
            acc = new_ack0 & ecnact & is_d
            st = cset(st, acc,
                      c_totack=cg(st, "c_totack")
                      + jnp.where(acc, acked0, jnp.int64(0)),
                      c_ceack=cg(st, "c_ceack")
                      + jnp.where(acc & ece_fl, acked0, jnp.int64(0)))
            # window boundary: fold the echo fraction into alpha
            # (fixed-point EWMA — reads the just-accumulated counters)
            wb = acc & s_lt(cg(st, "c_dwend"), pk["tack"])
            alpha = cg(st, "c_alpha")
            nalpha = jnp.minimum(
                jnp.int64(DCTCP_MAX_ALPHA),
                alpha - (alpha >> DCTCP_G_SHIFT)
                + (cg(st, "c_ceack") << (DCTCP_SHIFT - DCTCP_G_SHIFT))
                // jnp.maximum(cg(st, "c_totack"), 1))
            st = cset(st, wb, c_alpha=nalpha, c_ceack=jnp.int64(0),
                      c_totack=jnp.int64(0),
                      c_dwend=cg(st, "c_sndnxt"))
            # one cut per window; CWR announces it on fresh data
            red = ece_fl & (cg(st, "c_fastrec") == 0) \
                & s_lt(cg(st, "c_cwrend"), pk["tack"])
            mss_e = s_i64(cg(st, "c_congmss"))
            flight0 = s_sub(cg(st, "c_sndnxt"), cg(st, "c_snduna"))
            cw0 = cg(st, "c_cwnd")
            r_cw = jnp.maximum(flight0 // 2, 2 * mss_e)
            d_cw = jnp.maximum(
                cw0 - ((cw0 * cg(st, "c_alpha")) >> (DCTCP_SHIFT + 1)),
                2 * mss_e)
            ncw = jnp.where(is_d, d_cw, r_cw)
            st = cset(st, red,
                      c_cwnd=jnp.where(red, ncw, cw0),
                      c_ssthresh=jnp.where(red, ncw,
                                           cg(st, "c_ssthresh")),
                      c_cwrend=cg(st, "c_sndnxt"),
                      c_cwrp=jnp.int32(1))
            # new ack / dupack
            rtx_nonempty = (st["rtx_len"][jnp.clip(st["cur"], 0,
                                                   CC - 1)]
                            > st["rtx_pos"][jnp.clip(st["cur"], 0,
                                                     CC - 1)])
            new_ack = mask & s_lt(cg(st, "c_snduna"), ack)
            pure = (plen == 0)
            dup = mask & ~new_ack & (ack == cg(st, "c_snduna")) \
                & rtx_nonempty & pure & ~wchanged
            # handle_new_ack
            acked = s_sub(ack, cg(st, "c_snduna"))
            st = cset(st, new_ack,
                      c_snduna=jnp.where(new_ack, ack,
                                         cg(st, "c_snduna")),
                      c_dupacks=jnp.int32(0),
                      c_rtobackoff=jnp.int32(0))
            st = clear_acked(st, new_ack)
            has_srtt = new_ack & (cg(st, "c_srtt") > 0)
            rto2 = jnp.clip(cg(st, "c_srtt")
                            + jnp.maximum(4 * cg(st, "c_rttvar"),
                                          jnp.int64(1_000_000)),
                            MIN_RTO_NS, MAX_RTO_NS)
            st = cset(st, has_srtt, c_rto=rto2)
            in_rec = new_ack & (cg(st, "c_fastrec") == 1)
            rec_exit = in_rec & (s_lt(cg(st, "c_recover"), ack)
                                 | (ack == cg(st, "c_recover")))
            st = cset(st, rec_exit, c_fastrec=jnp.int32(0),
                      c_cwnd=cg(st, "c_ssthresh"))
            partial = in_rec & ~rec_exit
            st = retransmit_one(st, partial)
            # reno on_new_ack (not in recovery; an ack that just
            # triggered the ECN cut must not also grow the window)
            plain = new_ack & ~in_rec & ~red
            mss_c = s_i64(cg(st, "c_congmss"))
            cwnd = cg(st, "c_cwnd")
            ss = plain & (cwnd < cg(st, "c_ssthresh"))
            cwnd2 = jnp.where(ss, cwnd + jnp.minimum(acked, 2 * mss_c),
                              cwnd + jnp.maximum(jnp.int64(1),
                                                 mss_c * mss_c
                                                 // jnp.maximum(cwnd,
                                                                1)))
            st = cset(st, plain, c_cwnd=jnp.where(plain, cwnd2, cwnd))
            # RTO restart
            rtx_ne2 = (st["rtx_len"][jnp.clip(st["cur"], 0, CC - 1)]
                       > st["rtx_pos"][jnp.clip(st["cur"], 0,
                                                CC - 1)])
            st = cset(st, new_ack,
                      c_rtodl=jnp.where(rtx_ne2, now + cg(st, "c_rto"),
                                        jnp.int64(-1)))
            # handle_dupack
            st = cset(st, dup, c_dupacks=cg(st, "c_dupacks")
                      + jnp.where(dup, 1, 0))
            d_rec = dup & (cg(st, "c_fastrec") == 1)
            st = cset(st, d_rec, c_cwnd=cg(st, "c_cwnd")
                      + s_i64(cg(st, "c_congmss")))
            d_thr = dup & ~d_rec & (cg(st, "c_dupacks") == 3)
            flight = s_sub(cg(st, "c_sndnxt"), cg(st, "c_snduna"))
            st = cset(st, d_thr,
                      c_ssthresh=jnp.maximum(flight // 2,
                                             2 * s_i64(
                                                 cg(st, "c_congmss"))),
                      c_fastrec=jnp.int32(1),
                      c_recover=cg(st, "c_sndnxt"))
            st = cset(st, d_thr, c_cwnd=cg(st, "c_ssthresh")
                      + 3 * s_i64(cg(st, "c_congmss")))
            st = retransmit_one(st, d_thr)
            # ---- on_data (receiver side; plen > 0) ----
            data = mask & (plen > 0)
            offset = s_sub(cg(st, "c_rcvnxt"), pk["tseq"])
            dup_data = data & (offset >= plen)
            st = emit_ack(st, dup_data)
            live = data & ~dup_data
            eff_seq = jnp.where(offset > 0, cg(st, "c_rcvnxt"),
                                pk["tseq"])
            eff_len = jnp.where(offset > 0, plen - offset, plen)
            future = live & (s_sub(eff_seq, cg(st, "c_rcvnxt")) != 0)
            # reassembly setdefault (bounded by the receive buffer)
            cur = jnp.clip(st["cur"], 0, CC - 1)
            rav = st["ra_valid"][cur]
            ras = st["ra_seq"][cur]
            exists = (rav & (ras == eff_seq[:, None])).any(axis=1)
            in_win = s_sub(eff_seq, cg(st, "c_rcvnxt")) \
                < cg(st, "c_rbmax")
            store_it = future & in_win & ~exists
            # beyond the reassembly window: receiver discard
            # (connection.py reasm_discards / TEL_REASM_FULL twins)
            st = dict(st)
            st["drop_causes"] = st["drop_causes"].at[
                mrows(future & ~in_win), TEL_REASM_FULL].add(
                1, mode="drop")
            free = jnp.argmin(rav, axis=1)
            ra_over = store_it & rav.all(axis=1)
            st = mark_abort(st, ra_over.any(), AB_STRUCT, 8)
            st = dict(st)
            rrows = crows(st, store_it & ~ra_over)
            st["ra_seq"] = st["ra_seq"].at[rrows, free].set(
                eff_seq, mode="drop")
            st["ra_plen"] = st["ra_plen"].at[rrows, free].set(
                eff_len.astype(jnp.int32), mode="drop")
            st["ra_valid"] = st["ra_valid"].at[rrows, free].set(
                True, mode="drop")
            st = emit_ack(st, future)
            # in-order delivery
            inord = live & ~future
            had_holes = rav.any(axis=1)
            st = dict(st)
            st["had_holes"] = jnp.where(inord,
                                        had_holes.astype(jnp.int32),
                                        st["had_holes"])
            space = cg(st, "c_rbmax") - cg(st, "c_rblen")
            take = jnp.minimum(space, eff_len)
            take = jnp.maximum(take, 0)
            # in-order bytes past the receive buffer: unacked tail,
            # the sender retransmits (TcpConn::deliver twin)
            st = dict(st)
            st["drop_causes"] = st["drop_causes"].at[
                mrows(inord & (eff_len > take)),
                TEL_RECVWIN_TRUNC].add(1, mode="drop")
            st = cset(st, inord,
                      c_rblen=cg(st, "c_rblen")
                      + jnp.where(inord, take, 0),
                      c_rcvnxt=jnp.where(
                          inord, s_add(cg(st, "c_rcvnxt"), take),
                          cg(st, "c_rcvnxt")))
            st = fct_touch(st, inord & (take > 0), take,
                           inbound=True)
            # ---- continuation ----
            st = dict(st)
            nxt = jnp.where(
                inord, C_DRAIN,
                jnp.where(data, C_FLUSH, C_PUSH))
            st["cont"] = jnp.where(mask, nxt, st["cont"])
            return st

        def op_drain(st, mask):
            """One reassembly chunk per micro-op (connection.py's
            while-rcv_nxt-in-reassembly loop)."""
            cur = jnp.clip(st["cur"], 0, CC - 1)
            rav = st["ra_valid"][cur]
            ras = st["ra_seq"][cur]
            rap = st["ra_plen"][cur]
            match = rav & (ras == cg(st, "c_rcvnxt")[:, None])
            has = mask & match.any(axis=1)
            slot = jnp.argmax(match, axis=1)
            plen = jnp.take_along_axis(rap, slot[:, None],
                                       axis=1)[:, 0]
            space = cg(st, "c_rbmax") - cg(st, "c_rblen")
            take = jnp.clip(jnp.minimum(space, s_i64(plen)), 0, None)
            st = dict(st)
            st["drop_causes"] = st["drop_causes"].at[
                mrows(has & (s_i64(plen) > take)),
                TEL_RECVWIN_TRUNC].add(1, mode="drop")
            st = cset(st, has,
                      c_rblen=cg(st, "c_rblen")
                      + jnp.where(has, take, 0),
                      c_rcvnxt=jnp.where(
                          has, s_add(cg(st, "c_rcvnxt"), take),
                          cg(st, "c_rcvnxt")))
            st = fct_touch(st, has & (take > 0), take, inbound=True)
            st = dict(st)
            rr = crows(st, has)
            st["ra_valid"] = st["ra_valid"].at[rr, slot].set(
                False, mode="drop")
            st["cont"] = jnp.where(mask & ~has, C_ACKDATA,
                                   st["cont"])
            return st

        def op_ackdata(st, mask):
            """ack_data: every second in-order segment acks now; holes
            or a pinched window force it; else the 40ms delack."""
            now = st["now"]
            st = cset(st, mask, c_ssa=cg(st, "c_ssa")
                      + jnp.where(mask, 1, 0))
            cur = jnp.clip(st["cur"], 0, CC - 1)
            fire = mask & ((st["had_holes"] == 1)
                           | (cg(st, "c_ssa") >= 2)
                           | st["ra_valid"][cur].any(axis=1)
                           | (recv_window(st)
                              < s_i64(cg(st, "c_effmss"))))
            st = emit_ack(st, fire)
            arm = mask & ~fire & (cg(st, "c_delackdl") < 0)
            st = cset(st, arm, c_delackdl=now + DELACK_NS)
            st = dict(st)
            st["had_holes"] = jnp.where(mask, 0, st["had_holes"])
            st["cont"] = jnp.where(mask, C_FLUSH, st["cont"])
            return st

        def op_push(st, mask):
            """push_data: one eff_mss segment per micro-op within
            min(cwnd, peer window); Nagle holds a sub-MSS tail."""
            now = st["now"]
            window = jnp.minimum(cg(st, "c_cwnd"), cg(st, "c_sndwnd"))
            flight = s_sub(cg(st, "c_sndnxt"), cg(st, "c_snduna"))
            can = mask & (cg(st, "c_sblen") > 0) & (flight < window)
            budget = jnp.minimum(window - flight,
                                 s_i64(cg(st, "c_effmss")))
            nagle_hold = can & (cg(st, "c_nodelay") == 0) \
                & (cg(st, "c_sblen") < budget) & (flight > 0)
            chunk = jnp.minimum(cg(st, "c_sblen"), budget)
            do = can & ~nagle_hold & (chunk > 0)
            st = emit(st, do, cg(st, "c_sndnxt"), chunk,
                      F_ACK | F_PSH, with_sacks=False, track=True,
                      fresh=True)
            st = cset(st, do,
                      c_sblen=cg(st, "c_sblen")
                      - jnp.where(do, chunk, 0),
                      c_sndnxt=jnp.where(
                          do, s_add(cg(st, "c_sndnxt"), chunk),
                          cg(st, "c_sndnxt")))
            st = fct_touch(st, do, chunk, inbound=False)
            stop = mask & ~do
            # zero-window persist arming
            cur = jnp.clip(st["cur"], 0, CC - 1)
            rtx_empty = ~(st["rtx_len"][cur] > st["rtx_pos"][cur])
            parm = stop & (cg(st, "c_sndwnd") == 0) \
                & (cg(st, "c_sblen") > 0) & rtx_empty \
                & (cg(st, "c_persistdl") < 0)
            st = cset(st, parm, c_persistiv=cg(st, "c_rto"),
                      c_persistdl=now + cg(st, "c_rto"))
            st = dict(st)
            st["cont"] = jnp.where(stop, C_FLUSH, st["cont"])
            return st

        def op_flush(st, mask):
            """tcp_flush's notify: register the socket with the iface
            qdisc and kick the inet-out relay if it is idle."""
            need = mask & (st["eflag"] == 1) \
                & (cg(st, "c_queued") == 0)
            st = cset(st, need, c_queued=jnp.int32(1))
            st = dict(st)
            st["eflag"] = jnp.where(mask, 0, st["eflag"])
            kick = need & (st["r1_pending"] == 0)
            st["cont"] = jnp.where(mask, C_ARM, st["cont"])
            st["cont"] = jnp.where(kick, C_R1, st["cont"])
            st["then"] = jnp.where(kick, C_ARM, st["then"])
            return st

        def op_arm(st, mask):
            """tcp_arm_timer + tcp_update_status (+ the deferred
            sendto-EAGAIN park)."""
            now = st["now"]
            dls = [cg(st, "c_rtodl"), cg(st, "c_delackdl"),
                   cg(st, "c_persistdl")]
            nxt = jnp.full(H, I64_MAX, jnp.int64)
            for d in dls:
                nxt = jnp.where((d >= 0) & (d < nxt), d, nxt)
            have = nxt < I64_MAX
            arm = mask & have & (nxt != cg(st, "c_tmrdl"))
            st = cset(st, arm, c_tmrdl=jnp.where(arm, nxt,
                                                 cg(st, "c_tmrdl")))
            st, sq = draw_seq(st, arm)
            st = th_push(st, arm, nxt, sq,
                         jnp.full(H, TK_TCP, jnp.int32), st["cur"])
            # update_status (ESTABLISHED lanes only in-domain)
            readable = cg(st, "c_rblen") > 0
            space = (cg(st, "c_sbmax") - cg(st, "c_sblen")) > 0
            old = cg(st, "c_status")
            set_bits = jnp.where(readable, jnp.uint32(S_READABLE),
                                 jnp.uint32(0)) \
                | jnp.where(space, jnp.uint32(S_WRITABLE),
                            jnp.uint32(0))
            clear_bits = jnp.where(~readable, jnp.uint32(S_READABLE),
                                   jnp.uint32(0)) & ~set_bits
            new = (old | set_bits) & ~clear_bits
            changed = jnp.where(mask, old ^ new, jnp.uint32(0))
            st = cset(st, mask, c_status=jnp.where(mask, new, old))
            wake = mask & ((changed & cg(st, "c_await")) != 0) \
                & (cg(st, "c_wakep") == 0)
            st, sq = draw_seq(st, wake)
            st = th_push(st, wake, now, sq,
                         jnp.full(H, TK_APP, jnp.int32), st["cur"])
            st = cset(st, wake, c_wakep=jnp.int32(1))
            # deferred sendto-EAGAIN: clear WRITABLE, park the stepper
            park = mask & (st["parkp"] == 1)
            st = cset(st, park,
                      c_status=cg(st, "c_status")
                      & ~jnp.uint32(S_WRITABLE),
                      c_await=jnp.uint32(S_WRITABLE),
                      c_awaitseq=st["park_ctr"])
            st = dict(st)
            st["park_ctr"] = jnp.where(park, st["park_ctr"] + 1,
                                       st["park_ctr"])
            st["parkp"] = jnp.where(park, 0, st["parkp"])
            st["cont"] = jnp.where(mask, jnp.where(park, C_IDLE,
                                                   st["ret"]),
                                   st["cont"])
            return st

        # -------- app steppers / timers ------------------------------

        def max_mem(bw, rtt, base):
            mem = bw * rtt // np.int64(8 * 1_000_000_000)
            return jnp.clip(mem, base, 10 * base)

        def op_app(st, mask):
            """One tcp_recv (client) / tcp_sendto (handler) per
            micro-op — the engine app loop with syscalls counted at
            the same points."""
            now = st["now"]
            client = mask & (cg(st, "c_role") == 0)
            handler = mask & (cg(st, "c_role") == 1)
            st = dict(st)
            st["app_sys"] = st["app_sys"].at[:, ASYS_RECV].add(
                jnp.where(client, 1, 0))
            st["app_sys"] = st["app_sys"].at[:, ASYS_SEND].add(
                jnp.where(handler, 1, 0))
            # ---- client: recv 64 KiB or park ----
            empty = client & (cg(st, "c_rblen") == 0)
            st = cset(st, empty, c_await=jnp.uint32(S_READABLE),
                      c_awaitseq=st["park_ctr"])
            st["park_ctr"] = jnp.where(empty, st["park_ctr"] + 1,
                                       st["park_ctr"])
            st["cont"] = jnp.where(empty, C_IDLE, st["cont"])
            got = client & ~empty
            take = jnp.minimum(cg(st, "c_rblen"),
                               jnp.int64(1 << 16))
            win_before = recv_window(st)
            st = cset(st, got, c_rblen=cg(st, "c_rblen")
                      - jnp.where(got, take, 0))
            winupd = got & (win_before < MSS) \
                & (recv_window(st) >= MSS)
            st = emit_ack(st, winupd)
            # autotune_recv (socket_tcp.py twin)
            at = got & (cg(st, "c_rat") == 1)
            copied = cg(st, "c_atcopied") + jnp.where(at, take, 0)
            space2 = 2 * copied
            at_space = jnp.maximum(cg(st, "c_atspace"), space2)
            grow = at & (at_space > cg(st, "c_rbmax"))
            nw = jnp.minimum(at_space,
                             max_mem(st["bw_down"], cg(st, "c_srtt"),
                                     np.int64(RMEM_MAX)))
            st = cset(st, at, c_atcopied=copied, c_atspace=at_space)
            st = cset(st, grow & (nw > cg(st, "c_rbmax")),
                      c_rbmax=nw)
            fresh = at & (cg(st, "c_atlast") == 0)
            st = cset(st, fresh, c_atlast=now)
            roll = at & ~fresh & (cg(st, "c_srtt") > 0) \
                & (now - cg(st, "c_atlast") > cg(st, "c_srtt"))
            st = cset(st, roll, c_atlast=now,
                      c_atcopied=jnp.int64(0))
            ngot = cg(st, "c_agot") + jnp.where(got, take, 0)
            st = cset(st, got, c_agot=ngot)
            # transfer completion leaves the modelled domain (close)
            st = mark_abort(st, (got & (ngot >= cg(st, "c_atotal"))
                                 ).any(), AB_STRUCT, 9)
            st = dict(st)
            st["ret"] = jnp.where(got, C_APP, st["ret"])
            st["cont"] = jnp.where(got, C_FLUSH, st["cont"])
            # ---- handler: send up to 64 KiB or park ----
            want = jnp.minimum(jnp.int64(1 << 16),
                               cg(st, "c_atotal") - cg(st, "c_agot"))
            space = cg(st, "c_sbmax") - cg(st, "c_sblen")
            w = jnp.clip(jnp.minimum(want, space), 0, None)
            blocked = handler & (w == 0)
            st = dict(st)
            st["parkp"] = jnp.where(blocked, 1, st["parkp"])
            st["ret"] = jnp.where(handler, C_APP, st["ret"])
            st["cont"] = jnp.where(blocked, C_FLUSH, st["cont"])
            wrote = handler & ~blocked
            nsent = cg(st, "c_agot") + jnp.where(wrote, w, 0)
            st = cset(st, wrote,
                      c_sblen=cg(st, "c_sblen")
                      + jnp.where(wrote, w, 0),
                      c_agot=nsent)
            # send completion -> shutdown_wr: out of the domain
            st = mark_abort(st, (wrote & (nsent >= cg(st, "c_atotal"))
                                 ).any(), AB_STRUCT, 10)
            st = dict(st)
            st["cont"] = jnp.where(wrote, C_PUSH, st["cont"])
            return st

        def op_tmr(st, mask):
            """TK_TCP fire: tcp_on_timer — stale entries re-arm; due
            deadlines run delack/persist/RTO in the engine's fixed
            order, then the flush chain."""
            now = st["now"]
            st = cset(st, mask, c_tmrdl=jnp.int64(-1))
            dls = [cg(st, "c_rtodl"), cg(st, "c_delackdl"),
                   cg(st, "c_persistdl")]
            nxt = jnp.full(H, I64_MAX, jnp.int64)
            for d in dls:
                nxt = jnp.where((d >= 0) & (d < nxt), d, nxt)
            have = nxt < I64_MAX
            fire = mask & have & (now >= nxt)
            stale = mask & ~fire
            rearm = stale & have
            st = cset(st, rearm, c_tmrdl=jnp.where(rearm, nxt,
                                                   jnp.int64(-1)))
            st, sq = draw_seq(st, rearm)
            st = th_push(st, rearm, nxt, sq,
                         jnp.full(H, TK_TCP, jnp.int32), st["cur"])
            st = dict(st)
            st["cont"] = jnp.where(stale, C_IDLE, st["cont"])
            # ---- on_timer (fire lanes) ----
            d_f = fire & (cg(st, "c_delackdl") >= 0) \
                & (now >= cg(st, "c_delackdl"))
            st = emit_ack(st, d_f)
            p_f = fire & (cg(st, "c_persistdl") >= 0) \
                & (now >= cg(st, "c_persistdl"))
            st = cset(st, p_f, c_persistdl=jnp.int64(-1))
            cur = jnp.clip(st["cur"], 0, CC - 1)
            rtx_ne = st["rtx_len"][cur] > st["rtx_pos"][cur]
            probe = p_f & (cg(st, "c_sndwnd") == 0) \
                & (cg(st, "c_sblen") > 0) & ~rtx_ne
            st = emit(st, probe, cg(st, "c_sndnxt"),
                      jnp.ones(H, jnp.int64), F_ACK | F_PSH,
                      with_sacks=False, track=True, fresh=True)
            st = cset(st, probe,
                      c_sblen=cg(st, "c_sblen")
                      - jnp.where(probe, 1, 0),
                      c_sndnxt=jnp.where(
                          probe, s_add(cg(st, "c_sndnxt"),
                                       jnp.int64(1)),
                          cg(st, "c_sndnxt")))
            st = fct_touch(st, probe, jnp.ones(H, jnp.int64),
                           inbound=False)
            niv = jnp.minimum(
                jnp.where(cg(st, "c_persistiv") > 0,
                          2 * cg(st, "c_persistiv"),
                          cg(st, "c_rto")), MAX_RTO_NS)
            st = cset(st, probe, c_persistiv=niv,
                      c_persistdl=now + niv)
            # RTO
            r_f = fire & (cg(st, "c_rtodl") >= 0) \
                & (now >= cg(st, "c_rtodl"))
            cur = jnp.clip(st["cur"], 0, CC - 1)
            rtx_ne = st["rtx_len"][cur] > st["rtx_pos"][cur]
            r_empty = r_f & ~rtx_ne
            st = cset(st, r_empty, c_rtodl=jnp.int64(-1))
            r_go = r_f & rtx_ne
            flight = s_sub(cg(st, "c_sndnxt"), cg(st, "c_snduna"))
            st = cset(st, r_go,
                      c_ssthresh=jnp.maximum(
                          flight // 2,
                          2 * s_i64(cg(st, "c_congmss"))),
                      c_cwnd=s_i64(cg(st, "c_congmss")),
                      c_dupacks=jnp.int32(0),
                      c_fastrec=jnp.int32(0))
            # SACK reneging: forget every mark on RTO
            rows = rtx_rows(st)
            rows["rtx_sacked"] = jnp.where(
                r_go[:, None], 0, rows["rtx_sacked"])
            st = rtx_scatter(st, r_go, rows, ("rtx_sacked",))
            st = cset(st, r_go,
                      c_rto=jnp.minimum(2 * cg(st, "c_rto"),
                                        MAX_RTO_NS),
                      c_rtobackoff=cg(st, "c_rtobackoff") + 1)
            st = retransmit_one(st, r_go)
            st = cset(st, r_go, c_rtodl=now + cg(st, "c_rto"))
            st = dict(st)
            st["ret"] = jnp.where(fire, C_IDLE, st["ret"])
            st["cont"] = jnp.where(fire, C_FLUSH, st["cont"])
            return st

        # -------- event pop ------------------------------------------

        def next_event_time(st):
            pos = st["ib_pos"]
            safe = jnp.minimum(pos, I - 1)
            ib_t = jnp.where(st["ib_len"] > pos,
                             st["ib_time"][hidx, safe], I64_MAX)
            th_t = jnp.where(st["th_valid"], st["th_time"],
                             I64_MAX).min(axis=1)
            return ib_t, th_t

        def op_pop_event(st, mask, window_end):
            pos = st["ib_pos"]
            safe = jnp.minimum(pos, I - 1)
            ib_t, _ = next_event_time(st)
            tmin, tkind, ttgt, tslot = th_min(st)
            pick_ib = jnp.where(ib_t != tmin, ib_t < tmin,
                                ib_t < I64_MAX)
            et = jnp.minimum(ib_t, tmin)
            due = mask & (et < window_end)
            st = dict(st)
            st["now"] = jnp.where(due, et, st["now"])
            st["events_run"] = jnp.where(due, st["events_run"] + 1,
                                         st["events_run"])
            # Down-host fault mask (docs/ROBUSTNESS.md; run_until
            # twin): arrivals at a dead/link-down/blackholed host die
            # at their recorded arrival instant, never touching the
            # CoDel ledger; a dead host's timers discard silently.
            h_down = (st["h_fault"] & 1) != 0
            nic_dead = st["h_fault"] != 0

            # arrival: inbox -> codel -> relay 2
            arr = due & pick_ib
            st["ib_pos"] = jnp.where(arr, pos + 1, pos)
            pk_arr = {kk: st[f"ib_{kk}"][hidx, safe]
                      for kk in PK_KEYS}
            size = s_i64(pk_arr["plen"]) + TCP_TOTAL_HDR
            arr_f = arr & nic_dead
            st["pkts_dropped"] = jnp.where(
                arr_f, st["pkts_dropped"] + 1, st["pkts_dropped"])
            st["drop_causes"] = st["drop_causes"].at[
                mrows(arr_f & h_down), TEL_HOST_DOWN].add(
                1, mode="drop")
            st["drop_causes"] = st["drop_causes"].at[
                mrows(arr_f & ~h_down), TEL_LINK_DOWN].add(
                1, mode="drop")
            st = tr_append(st, arr_f & h_down, et, TR_DRP, pk_arr,
                           RSN_HOSTDOWN)
            st = tr_append(st, arr_f & ~h_down, et, TR_DRP, pk_arr,
                           RSN_LINKDOWN)
            st = dict(st)
            arr = arr & ~nic_dead
            st["codel_enq_pkts"] = jnp.where(
                arr, st["codel_enq_pkts"] + 1, st["codel_enq_pkts"])
            st["codel_enq_bytes"] = jnp.where(
                arr, st["codel_enq_bytes"] + size,
                st["codel_enq_bytes"])
            limit_full = arr & (st["cq_len"] - st["cq_pos"]
                                >= CODEL_HARD_LIMIT)
            st["codel_dropped"] = jnp.where(
                limit_full, st["codel_dropped"] + 1,
                st["codel_dropped"])
            st["codel_drop_bytes"] = jnp.where(
                limit_full, st["codel_drop_bytes"] + size,
                st["codel_drop_bytes"])
            st["pkts_dropped"] = jnp.where(
                limit_full, st["pkts_dropped"] + 1,
                st["pkts_dropped"])
            st["drop_causes"] = st["drop_causes"].at[
                mrows(limit_full), TEL_RTR_LIMIT].add(1, mode="drop")
            st = tr_append(st, limit_full, et, TR_DRP, pk_arr,
                           RSN_RTRLIMIT)
            st = dict(st)
            arr = arr & ~limit_full
            st = mark_abort(st, (arr & (st["cq_len"] - st["cq_pos"]
                                        >= CQ - 1)).any(), AB_STRUCT, 11)
            st = dict(st)
            # DCTCP-K instantaneous marking law (net/codel.py push /
            # netplane CoDelN::push twins): an ECT(0) arrival meeting
            # the threshold — queue state BEFORE this enqueue, packets
            # leg first — is rewritten to CE and enqueued normally.
            depth = s_i64(st["cq_len"] - st["cq_pos"])
            ect = arr & (pk_arr["ecn"] == ECN_ECT0)
            mark_p = ect & (depth >= k_pkts)
            mark_b = ect & ~mark_p \
                & (st["codel_bytes"] >= k_bytes)
            mark = mark_p | mark_b
            st["codel_marked"] = jnp.where(
                mark, st["codel_marked"] + 1, st["codel_marked"])
            st["mark_causes"] = st["mark_causes"].at[
                mrows(mark_p), MARK_THRESH_PKTS].add(1, mode="drop")
            st["mark_causes"] = st["mark_causes"].at[
                mrows(mark_b), MARK_THRESH_BYTES].add(1, mode="drop")
            pk_arr = dict(pk_arr)
            pk_arr["ecn"] = jnp.where(mark, jnp.int32(ECN_CE),
                                      pk_arr["ecn"])
            tail = st["cq_len"] % CQ
            rows = mrows(arr)
            for kk in PK_KEYS:
                st[f"cq_{kk}"] = st[f"cq_{kk}"].at[rows, tail].set(
                    pk_arr[kk], mode="drop")
            st["cq_enq"] = st["cq_enq"].at[rows, tail].set(
                et, mode="drop")
            st["cq_len"] = jnp.where(arr, st["cq_len"] + 1,
                                     st["cq_len"])
            st["codel_peak"] = jnp.maximum(
                st["codel_peak"],
                jnp.where(arr, s_i64(st["cq_len"] - st["cq_pos"]),
                          jnp.int64(0)))
            st["codel_bytes"] = jnp.where(
                arr, st["codel_bytes"] + size, st["codel_bytes"])
            go2 = arr & (st["r2_pending"] == 0)
            st["cont"] = jnp.where(go2, C_R2, st["cont"])
            st["then"] = jnp.where(go2, C_IDLE, st["then"])

            # timer
            tim = due & ~pick_ib
            st["th_valid"] = st["th_valid"].at[mrows(tim), tslot].set(
                False, mode="drop")
            # A dead host's timers discard silently (run_until's down
            # branch: tpop only — no relay/TCP/app effects).
            tim = tim & ~h_down
            is_relay = tim & (tkind == TK_RELAY)
            for r in (1, 2):
                rw = is_relay & (ttgt == r)
                st[f"r{r}_pending"] = jnp.where(rw, 0,
                                                st[f"r{r}_pending"])
                st["cont"] = jnp.where(rw, C_R1 if r == 1 else C_R2,
                                       st["cont"])
                st["then"] = jnp.where(rw, C_IDLE, st["then"])
            bad_tgt = tim & (tkind != TK_RELAY) & (ttgt < 0)
            st = mark_abort(st, bad_tgt.any(), AB_STRUCT, 12)
            st = dict(st)
            is_tcp = tim & (tkind == TK_TCP) & (ttgt >= 0)
            st["cur"] = jnp.where(is_tcp | (tim & (tkind == TK_APP)
                                            & (ttgt >= 0)),
                                  ttgt, st["cur"])
            st["cont"] = jnp.where(is_tcp, C_TMR, st["cont"])
            st["ret"] = jnp.where(is_tcp, C_IDLE, st["ret"])
            is_app = tim & (tkind == TK_APP) & (ttgt >= 0)
            st = cset(st, is_app, c_wakep=jnp.int32(0),
                      c_await=jnp.uint32(0))
            st = dict(st)
            st["cont"] = jnp.where(is_app, C_APP, st["cont"])
            st["ret"] = jnp.where(is_app, C_APP, st["ret"])
            return st

        # -------- per-iteration dispatcher ---------------------------

        def micro_iter(carry):
            st, window_end, iters = carry
            if fused:
                # Fused dispatch (phold_span twin): ops consume the
                # LIVE continuation in dataflow order — a delivered
                # segment's whole chain (pop -> codel drain -> tcpin
                # -> reassembly -> ack decision -> push -> flush ->
                # inet-out -> arm) runs inside ONE while-iteration.
                # Per-host micro-op order is untouched (each stage
                # still advances exactly one micro-op for its lanes),
                # and hosts are independent within a round, so the
                # compressed schedule is state-identical; the
                # outbox/trace interleave it changes is erased by the
                # downstream canonical sorts (inbox lexsort,
                # Host.trace_lines).  Each stage is guarded by an
                # any-lane-active cond so XLA skips the vectorized
                # body of stages nobody occupies this iteration.
                def guard(st, mask, fn, code=None):
                    st = ks_count(st, code, mask) \
                        if code is not None else st
                    return jax.lax.cond(mask.any(), fn,
                                        lambda s, _m: s, st, mask)

                st = ks_count_pop(st, st["cont"] == C_IDLE,
                                  window_end)
                st = op_pop_event(st, st["cont"] == C_IDLE, window_end)
                st = guard(st, st["cont"] == C_TMR, op_tmr, KS_TIMERS)
                st = guard(st, st["cont"] == C_APP, op_app, KS_STEP)
                st = guard(st, st["cont"] == C_R2, op_relay2,
                           KS_CODEL)
                st = guard(st, st["cont"] == C_TCPIN, op_tcpin,
                           KS_ON_PACKET)
                for _ in range(2):
                    st = guard(st, st["cont"] == C_DRAIN, op_drain,
                               KS_REASM)
                st = guard(st, st["cont"] == C_ACKDATA, op_ackdata,
                           KS_ACK)
                st = guard(st, st["cont"] == C_PUSH, op_push, KS_PUSH)
                st = guard(st, st["cont"] == C_FLUSH, op_flush,
                           KS_FLUSH)
                for _ in range(2):
                    st = guard(st, st["cont"] == C_R1, op_relay1,
                               KS_INET_OUT)
                st = guard(st, st["cont"] == C_ARM, op_arm, KS_ARM)
            else:
                # Reference (unfused) schedule: snapshot — one
                # micro-op per host per iteration.  Kept as the
                # differential comparator for the fused path.
                cont0 = st["cont"]
                st = ks_count(st, KS_INET_OUT, cont0 == C_R1)
                st = ks_count(st, KS_CODEL, cont0 == C_R2)
                st = ks_count(st, KS_ON_PACKET, cont0 == C_TCPIN)
                st = ks_count(st, KS_REASM, cont0 == C_DRAIN)
                st = ks_count(st, KS_ACK, cont0 == C_ACKDATA)
                st = ks_count(st, KS_PUSH, cont0 == C_PUSH)
                st = ks_count(st, KS_FLUSH, cont0 == C_FLUSH)
                st = ks_count(st, KS_ARM, cont0 == C_ARM)
                st = ks_count(st, KS_STEP, cont0 == C_APP)
                st = ks_count(st, KS_TIMERS, cont0 == C_TMR)
                st = op_relay1(st, cont0 == C_R1)
                st = op_relay2(st, cont0 == C_R2)
                st = op_tcpin(st, cont0 == C_TCPIN)
                st = op_drain(st, cont0 == C_DRAIN)
                st = op_ackdata(st, cont0 == C_ACKDATA)
                st = op_push(st, cont0 == C_PUSH)
                st = op_flush(st, cont0 == C_FLUSH)
                st = op_arm(st, cont0 == C_ARM)
                st = op_app(st, cont0 == C_APP)
                st = op_tmr(st, cont0 == C_TMR)
                # Counted against the state op_pop_event will read.
                st = ks_count_pop(st, cont0 == C_IDLE, window_end)
                st = op_pop_event(st, cont0 == C_IDLE, window_end)
            # Per-round runaway valve: a legitimate hot round is a few
            # thousand micro-iterations; a continuation-cycle bug must
            # abort in minutes, not hours (each iteration is a full
            # vectorized body on the CPU backend).
            st = mark_abort(st, iters > (np.int64(1) << 17), AB_STRUCT,
                            13)
            return st, window_end, iters + 1

        def micro_cond(carry):
            st, window_end, iters = carry
            ib_t, th_t = next_event_time(st)
            due = jnp.minimum(ib_t, th_t) < window_end
            busy = st["cont"] != C_IDLE
            return (busy | due).any() & (st["abort_code"] == 0)

        # -------- round end: propagation + inbox merge ---------------

        def propagate(st, window_end):
            n = st["out_n"]
            valid = jnp.arange(O) < n
            src = st["out_src"]
            dst = st["out_dst"]
            node = st["_node"]
            latency = st["_lat"][node[src], node[dst]]
            reachable = latency < TIME_NEVER
            bits, _ = threefry2x32_jax(
                st["_k0"], st["_k1"], src.astype(jnp.uint32),
                (st["out_pseq"] & 0xFFFFFFFF).astype(jnp.uint32))
            thr_v = st["_thr"][node[src], node[dst]]
            # pure acks are empty-control packets: never lossy
            lossy = ((bits.astype(jnp.int64) < thr_v)
                     & (st["out_plen"] > 0)
                     & (st["out_t"] >= st["_bootstrap"]))
            deliver = jnp.maximum(st["out_t"] + latency, window_end)
            keep = valid & reachable & ~lossy
            min_lat = jnp.min(jnp.where(keep, latency, I64_MAX))
            st = dict(st)
            for miss, rsn, tel in (
                    (valid & ~reachable, RSN_UNREACH, TEL_UNREACHABLE),
                    (valid & reachable & lossy, RSN_LOSS,
                     TEL_LOSS_EDGE)):
                st["pkts_dropped"] = st["pkts_dropped"].at[
                    jnp.where(miss, src, OOB)].add(1, mode="drop")
                st["drop_causes"] = st["drop_causes"].at[
                    jnp.where(miss, src, OOB), tel].add(1, mode="drop")
                if tracing:
                    nt_ = st["tr_n"]
                    rank = jnp.cumsum(miss) - 1
                    slot = jnp.where(miss, nt_ + rank, TR + 8)
                    cols = (("tr_t", st["out_t"]),
                            ("tr_kind", jnp.full(O, TR_DRP,
                                                 jnp.int32)),
                            ("tr_srchost", st["out_srchost"]),
                            ("tr_pseq", st["out_pseq"]),
                            ("tr_sip", st["out_sip"]),
                            ("tr_sport", st["out_sport"]),
                            ("tr_dip", st["out_dip"]),
                            ("tr_dport", st["out_dport"]),
                            ("tr_plen", st["out_plen"]),
                            ("tr_reason", jnp.full(O, rsn,
                                                   jnp.int32)),
                            ("tr_owner", src))
                    for key, v in cols:
                        st[key] = st[key].at[slot].set(v, mode="drop")
                    tot = nt_ + miss.sum()
                    st["tr_n"] = tot
                    st = mark_abort(st, tot > TR - O, AB_TRACE)
                    st = dict(st)

            rem = (st["ib_len"] - st["ib_pos"]).astype(jnp.int32)
            shift = jnp.minimum(
                st["ib_pos"][:, None] + jnp.arange(I)[None, :], I - 1)
            live = jnp.arange(I)[None, :] < rem[:, None]

            def compact(a, fill):
                return jnp.where(live,
                                 jnp.take_along_axis(a, shift, axis=1),
                                 fill)

            ib_time = compact(st["ib_time"], I64_MAX)
            ib_src = compact(st["ib_src"], 0)
            ib_seq = compact(st["ib_seq"], I64_MAX)
            ib_pk = {kk: compact(st[f"ib_{kk}"],
                                 np.zeros((), PK_DTYPES[kk]))
                     for kk in PK_KEYS}
            d_dst, d_time, d_src, d_seq = dst, deliver, src, \
                st["out_seq"]
            d_pk = {kk: st[f"out_{kk}"] for kk in PK_KEYS}
            d_keep, DN = keep, O
            if n_shards > 1:
                # On-device cross-shard exchange (phold_span twin;
                # ISSUE 11): capacity-bounded per-destination-shard
                # staging (span_mesh.py law) ahead of the shard-local
                # inbox scatter; AB_EXCH on overflow, and the inbox
                # lexsort (time, src, seq — strict total order) makes
                # a clean hop invisible to the packet trace.
                stage, SE = exchange
                hs = H // n_shards
                cols = {"dst": (dst, H), "time": (deliver, I64_MAX),
                        "src": (src, 0), "seq": (st["out_seq"],
                                                 I64_MAX)}
                cols.update({kk: (st[f"out_{kk}"],
                                  np.zeros((), PK_DTYPES[kk])[()])
                             for kk in PK_KEYS})
                ex, over = stage(keep, dst // hs, cols)
                # Observatory: exchange is a per-ROUND stage — lanes
                # are packets staged, fires bounded by rounds.
                st = ks_count(st, KS_EXCHANGE, keep)
                st = mark_abort(st, over.any(), AB_EXCH, 15)
                st = dict(st)
                d_dst, d_time = ex["dst"], ex["time"]
                d_src, d_seq = ex["src"], ex["seq"]
                d_pk = {kk: ex[kk] for kk in PK_KEYS}
                d_keep, DN = ex["dst"] < H, SE
            seg = jnp.where(d_keep, d_dst, H)
            order = jnp.argsort(seg.astype(jnp.int64) * (DN + 1)
                                + jnp.arange(DN))
            sseg = seg[order]
            rank0 = jnp.arange(DN) - jnp.searchsorted(sseg, sseg,
                                                      side="left")
            rank = jnp.zeros(DN, jnp.int32).at[order].set(
                rank0.astype(jnp.int32))
            slot = rem[jnp.minimum(seg, H - 1)] + rank
            ok_slot = d_keep & (slot < I - 1)
            st = mark_abort(st, (d_keep & (slot >= I - 1)).any(),
                            AB_STRUCT, 14)
            st = dict(st)
            rows = jnp.where(ok_slot, d_dst, OOB)
            ib_time = ib_time.at[rows, slot].set(d_time, mode="drop")
            ib_src = ib_src.at[rows, slot].set(d_src, mode="drop")
            ib_seq = ib_seq.at[rows, slot].set(d_seq, mode="drop")
            for kk in PK_KEYS:
                ib_pk[kk] = ib_pk[kk].at[rows, slot].set(d_pk[kk],
                                                         mode="drop")
            add = jnp.zeros(H, jnp.int32).at[rows].add(1, mode="drop")
            sort_idx = jnp.lexsort((ib_seq, ib_src, ib_time), axis=1)
            take = jnp.take_along_axis
            st["ib_time"] = take(ib_time, sort_idx, axis=1)
            st["ib_src"] = take(ib_src, sort_idx, axis=1)
            st["ib_seq"] = take(ib_seq, sort_idx, axis=1)
            for kk in PK_KEYS:
                st[f"ib_{kk}"] = take(ib_pk[kk], sort_idx, axis=1)
            st["ib_pos"] = jnp.zeros(H, jnp.int32)
            st["ib_len"] = rem + add
            st["out_n"] = jnp.int64(0)
            return st, n, min_lat

        # -------- the multi-round while loop -------------------------

        def round_cond(carry):
            (st, start, runahead, rounds, busy_rounds, packets,
             busy_end, stop, limit, max_rounds, iters) = carry
            return ((rounds < max_rounds) & (start < limit)
                    & (start < stop) & (st["abort_code"] == 0))

        def round_body(carry):
            (st, start, runahead, rounds, busy_rounds, packets,
             busy_end, stop, limit, max_rounds, iters) = carry
            window_end = jnp.minimum(start + runahead, stop)
            st, _we, it = jax.lax.while_loop(
                micro_cond, micro_iter,
                (st, window_end, jnp.int64(0)))
            st, n_out, min_lat = propagate(st, window_end)
            if netstat:
                # Sim-netstat sample at the round boundary: the same
                # stateless grid-crossing rule as the engine's
                # tel_sample_round and the object path — the sampled-
                # round set is path-independent by construction.
                do = (start // np.int64(tel_iv)
                      != window_end // np.int64(tel_iv))
                row = jnp.where(do, st["tel_n"],
                                jnp.int32(TELR + 8))
                st = dict(st)
                st["tel_t"] = st["tel_t"].at[row].set(
                    window_end, mode="drop")
                for name, srccol in TEL_FIELDS:
                    st[f"tel_{name}"] = st[f"tel_{name}"].at[row].set(
                        st[srccol].astype(jnp.int64), mode="drop")
                st["tel_n"] = st["tel_n"] + do.astype(jnp.int32)
            if fabric:
                # Fabric observatory at the round boundary: same
                # grid-crossing rule as the engine's fab_sample_round
                # and the object path; the activity mask is computed
                # per host and the driver filters inactive rows.
                do = (start // np.int64(fab_iv)
                      != window_end // np.int64(fab_iv))
                row = jnp.where(do, st["fab_n"],
                                jnp.int32(FABR + 8))
                depth = s_i64(st["cq_len"] - st["cq_pos"])
                flags = (jnp.where(depth > 0, FB_ACT_CODEL, 0)
                         | jnp.where(st["r1_pending"] == 1,
                                     FB_ACT_TB_OUT, 0)
                         | jnp.where(st["r2_pending"] == 1,
                                     FB_ACT_TB_IN, 0)
                         | jnp.where(st["eth_psent"]
                                     + st["eth_precv"] > 0,
                                     FB_ACT_LINK, 0))
                head = st["cq_enq"][hidx, st["cq_pos"] % CQ]
                sojourn = jnp.where(depth > 0, window_end - head,
                                    jnp.int64(0))

                def bucket_peek(r):
                    nr = st[f"r{r}_next"]
                    bal = st[f"r{r}_bal"]
                    k = 1 + (window_end - nr) // np.int64(REFILL_NS)
                    adv = jnp.minimum(st[f"r{r}_cap"],
                                      bal + k * st[f"r{r}_refill"])
                    return jnp.where((nr == 0) | (window_end < nr),
                                     bal, adv)

                st = dict(st)
                st["fab_t"] = st["fab_t"].at[row].set(
                    window_end, mode="drop")
                st["fab_flags"] = st["fab_flags"].at[row].set(
                    flags.astype(jnp.int32), mode="drop")
                for name, val in (
                        ("qdepth", depth),
                        ("qbytes", st["codel_bytes"]),
                        ("sojourn", sojourn),
                        ("qenq", st["codel_enq_pkts"]),
                        ("qdrops", st["codel_dropped"]),
                        ("qmarks", st["codel_marked"]),
                        ("r1_bal", bucket_peek(1)),
                        ("r1_stalls", s_i64(st["r1_stalls"])),
                        ("r2_bal", bucket_peek(2)),
                        ("r2_stalls", s_i64(st["r2_stalls"])),
                        ("psent", st["eth_psent"]),
                        ("bsent", st["eth_bsent"]),
                        ("precv", st["eth_precv"]),
                        ("brecv", st["eth_brecv"])):
                    st[f"fab_{name}"] = st[f"fab_{name}"].at[
                        row].set(val.astype(jnp.int64), mode="drop")
                st["fab_n"] = st["fab_n"] + do.astype(jnp.int32)
            runahead = jnp.where(
                (min_lat > 0) & (min_lat < runahead), min_lat,
                runahead)
            ib_t, th_t = next_event_time(st)
            start = jnp.minimum(ib_t, th_t).min()
            return (st, start, runahead, rounds + 1,
                    busy_rounds + (n_out > 0).astype(jnp.int64),
                    packets + n_out, window_end, stop, limit,
                    max_rounds, iters + it)

        # Donation is gated by experimental.tpu_donate_buffers behind
        # the compile-cache-safe guard (span_mesh.donation_cache_safe;
        # BASELINE.md r6: donated executables + the persistent
        # compilation cache corrupt the heap on cache-hit runs, so
        # that exact combination is refused).
        def run(st, lat, thr, node, ips_sorted, ips_perm, k0, k1,
                bootstrap_end, start, stop, limit, runahead,
                max_rounds):
            st = dict(st)
            st["_lat"] = lat
            st["_thr"] = thr
            st["_node"] = node
            st["_ips_sorted"] = ips_sorted
            st["_ips_perm"] = ips_perm
            st["_k0"] = k0
            st["_k1"] = k1
            st["_bootstrap"] = bootstrap_end
            st["abort_code"] = jnp.int32(0)
            st["abort_site"] = jnp.int32(0)
            st["cd_chain"] = jnp.zeros(H, jnp.int32)
            st["cd_sniff"] = jnp.zeros(H, jnp.int32)
            # conn lookup keys: (host, peer-ip-index, peer-port)
            pslot = jnp.minimum(
                jnp.searchsorted(ips_sorted, st["c_pip"]), H - 1)
            pidx = ips_perm[pslot].astype(jnp.int64)
            ckey = (st["c_host"].astype(jnp.int64) * H + pidx) \
                * 65536 + st["c_pport"].astype(jnp.int64)
            ckey = jnp.where(st["c_host"] >= 0, ckey,
                             I64_MAX - jnp.arange(CC))
            order = jnp.argsort(ckey)
            st["_ckeys"] = ckey[order]
            st["_ckperm"] = order.astype(jnp.int32)
            st["out_n"] = jnp.int64(0)
            st["out_src"] = jnp.zeros(O, jnp.int32)
            st["out_dst"] = jnp.zeros(O, jnp.int32)
            st["out_seq"] = jnp.zeros(O, jnp.int64)
            st["out_t"] = jnp.zeros(O, jnp.int64)
            for kk in PK_KEYS:
                st[f"out_{kk}"] = jnp.zeros(O, PK_DTYPES[kk])
            if netstat:
                st["tel_n"] = jnp.int32(0)
                st["tel_t"] = jnp.zeros(TELR, jnp.int64)
                for name, _src in TEL_FIELDS:
                    st[f"tel_{name}"] = jnp.zeros((TELR, CC),
                                                  jnp.int64)
            if fabric:
                st["fab_n"] = jnp.int32(0)
                st["fab_t"] = jnp.zeros(FABR, jnp.int64)
                st["fab_flags"] = jnp.zeros((FABR, H), jnp.int32)
                for name in ("qdepth", "qbytes", "sojourn", "qenq",
                             "qdrops", "qmarks", "r1_bal",
                             "r1_stalls", "r2_bal", "r2_stalls",
                             "psent", "bsent", "precv", "brecv"):
                    st[f"fab_{name}"] = jnp.zeros((FABR, H),
                                                  jnp.int64)
            if kern:
                # Span-local stage counters (KS_REC fires/lanes) —
                # output only, never engine state.
                st["ks_fires"] = jnp.zeros(KS_N, jnp.int64)
                st["ks_lanes"] = jnp.zeros(KS_N, jnp.int64)
            if tracing:
                st["tr_n"] = jnp.int64(0)
                for k, dt in (("tr_t", jnp.int64),
                              ("tr_kind", jnp.int32),
                              ("tr_srchost", jnp.int32),
                              ("tr_pseq", jnp.int64),
                              ("tr_sip", jnp.uint32),
                              ("tr_sport", jnp.int32),
                              ("tr_dip", jnp.uint32),
                              ("tr_dport", jnp.int32),
                              ("tr_plen", jnp.int32),
                              ("tr_reason", jnp.int32),
                              ("tr_owner", jnp.int32)):
                    st[k] = jnp.zeros(TR, dt)

            carry = (st, jnp.int64(start), jnp.int64(runahead),
                     jnp.int64(0), jnp.int64(0), jnp.int64(0),
                     jnp.int64(start), jnp.int64(stop),
                     jnp.int64(limit), jnp.int64(max_rounds),
                     jnp.int64(0))
            (st, start, runahead, rounds, busy_rounds, packets,
             busy_end, _s, _l, _m, iters) = jax.lax.while_loop(
                round_cond, round_body, carry)
            # Only mutated columns go back over the device link: the
            # residency tables ARE the drop set (statics the host
            # already has, deriveds the next input re-derives), so a
            # column added to either class stays off the link without
            # touching this site.  The `_`-prefix filter below covers
            # `_n_conns`.
            drop = RESIDENT_STATIC | RESIDENT_DERIVED
            # the span-local outbox was fully consumed by propagate
            drop |= {"out_n", "out_src", "out_dst", "out_seq", "out_t"}
            drop |= {f"out_{kk}" for kk in PK_KEYS}
            st = {k: v for k, v in st.items()
                  if not k.startswith("_") and k not in drop}
            return (st, start, runahead, rounds, busy_rounds, packets,
                    busy_end, iters)

        return self._span_jit(jax, run)

    # ------------------------------------------------------------------
    # Driver
    # ------------------------------------------------------------------

    def _export_state(self):
        """Fresh engine export -> state dict, or the int/None
        eligibility verdict passed through from span_export_tcp."""
        w = self.wall
        t0 = w.now() if w is not None else 0
        d = self.engine.span_export_tcp(*self._caps())
        if w is not None:
            t1 = w.now()
            w.add("export", t1 - t0, t0)
        if d is None or isinstance(d, int):
            return d
        # Codec byte volume, engine -> host (dispatch attribution).
        self.export_bytes += sum(
            len(v) for v in d.values()
            if isinstance(v, (bytes, bytearray, memoryview)))
        st = self._to_arrays(d)  # also sets self._CC
        if self.netstat is not None:
            # Telemetry identity + canonical order, captured while the
            # static columns are still host-side numpy.
            n = st["_n_conns"]
            host = st["c_host"][:n].astype(np.int32)
            lport = st["c_lport"][:n].astype(np.uint16)
            rport = st["c_pport"][:n].astype(np.uint16)
            rip = st["c_pip"][:n].astype(np.uint32)
            perm = np.lexsort((rip, rport, lport, host))
            self._tel_ident = (host[perm], lport[perm], rport[perm],
                               rip[perm], perm, n)
        # Cache the static config as committed device arrays
        # (phold_span twin): paid once per export, reused by every
        # later dispatch — fresh or resident — without re-paying the
        # host->device transfer.  _n_conns stays a host int.
        import jax
        self._static_cols = {
            k: self._put_static(jax, st[k]) for k in RESIDENT_STATIC}
        st.update(self._static_cols)
        self._static_cols["_n_conns"] = st["_n_conns"]
        if w is not None:
            t2 = w.now()
            w.add("convert", t2 - t1, t1)
        return st

    def _resident_input(self):
        """Rebuild the span input from the resident device output
        (phold_span twin): static columns reattach from the cache;
        the device-local chain registers re-initialize exactly as
        every fresh export initializes them."""
        H = self._H
        st = {k: v for k, v in self._res_st.items()
              if k not in ("abort_code", "abort_site")
              and not k.startswith("tr_")
              and not k.startswith("tel_")
              and not k.startswith("fab_")
              and not k.startswith("ks_")}
        st.update(self._static_cols)
        n = self._static_cols["_n_conns"]
        for k in ("cont", "then", "ret"):
            st[k] = np.full(H, C_IDLE, np.int32)
        st["cur"] = np.full(H, -1, np.int32)
        for k in ("eflag", "parkp", "had_holes"):
            st[k] = np.zeros(H, np.int32)
        for kk in PK_KEYS:
            st[f"ar_{kk}"] = np.zeros(H, PK_DTYPES[kk])
        # Device-side scatter-max (phold twin uses jnp.maximum): both
        # operands already live on device, so an np rebuild would pay
        # a blocking device->host sync per resident hit.
        import jax.numpy as jnp
        st["park_ctr"] = (
            jnp.zeros(H, jnp.int64)
            .at[self._static_cols["c_host"][:n]]
            .max(st["c_awaitseq"][:n] + 1))
        return st

    def _emit_netstat(self, st_np) -> None:
        """Pack the span's device-sampled telemetry rows into TEL_REC
        records — per sampled round, connections in the canonical
        (host, lport, rport, rip) order — and append them to the
        channel.  Byte-identical to the engine ring's records for the
        same rounds (the cross-path parity gate's device leg)."""
        if self.netstat is None or self._tel_ident is None:
            return
        tn = int(st_np.get("tel_n", 0))
        host, lport, rport, rip, perm, n = self._tel_ident
        if tn == 0 or n == 0:
            return
        from shadow_tpu.trace.events import TEL_DTYPE
        arr = np.zeros(tn * n, dtype=np.dtype(TEL_DTYPE))
        arr["t"] = np.repeat(st_np["tel_t"][:tn].astype(np.int64), n)
        arr["host"] = np.tile(host, tn)
        arr["lport"] = np.tile(lport, tn)
        arr["rport"] = np.tile(rport, tn)
        arr["rip"] = np.tile(rip, tn)
        arr["state"] = ST_ESTABLISHED
        for name, _src in TEL_FIELDS:
            arr[name] = st_np[f"tel_{name}"][:tn][:, perm].reshape(-1)
        self.netstat.extend(arr.tobytes())

    def _emit_fabric(self, st_np) -> None:
        """Pack the span's device-sampled queue rows into FB_REC
        records — per sampled round, ACTIVE hosts in ascending id
        order — and append them to the channel.  Byte-identical to
        the engine ring's records for the same rounds."""
        from shadow_tpu.trace.fabricstat import emit_device_rows
        emit_device_rows(self.fabric, st_np, self._H)

    def _clamp_mr(self, mr: int | None) -> int:
        """The effective max-rounds law for one dispatch (phold_span
        twin) — shared by the normal and the speculative path so an
        in-flight window's recorded params land against the same
        clamp.  Clamp span length: the flat trace buffer accumulates
        across the whole span, and TCP rounds carry ~100x phold's
        traffic."""
        mr = self.MAX_ROUNDS if mr is None \
            else min(mr, self.MAX_ROUNDS)
        if self.netstat is not None:
            # Sampled rounds <= rounds <= TEL_ROWS: the device-side
            # telemetry buffers can never overflow (a silent skip
            # would break cross-path byte-parity).
            mr = min(mr, self.TEL_ROWS)
        if self.fabric is not None:
            mr = min(mr, self.FAB_ROWS)  # same overflow-proof clamp
        return mr

    def try_span(self, start: int, stop: int, limit: int,
                 runahead: int, dynamic: bool,
                 max_rounds: int | None = None, spec_mr: int = 0):
        """Export -> device span -> import.  Returns (rounds,
        busy_rounds, packets, next_start, busy_end, runahead) or None
        when ineligible / transiently out of domain / aborted.

        Residency (phold_span twin): while the engine's state_epoch
        is unchanged since our last import, the previous span's
        device-resident output is reused and the export+conversion
        leg of the dispatch is skipped; any other engine call forces
        a fresh export.

        Overlap (ISSUE 16, phold_span twin): with `spec_mr > 0` and
        span_overlap on, a clean commit dispatches window K+1
        asynchronously before the host-side import runs; the NEXT
        try_span lands it through _take_inflight iff the params match
        and the engine epoch is unchanged."""
        self.last_transient = False
        import os
        import sys
        import time as _time
        dbg = os.environ.get("SHADOWTPU_TCPSPAN_DBG")
        if dbg:
            _t0 = _time.perf_counter()  # shadow-lint: allow[wall-clock] debug span timing
        mr = self._clamp_mr(max_rounds)
        landed = self._take_inflight(
            (int(start), int(stop), int(limit), int(runahead),
             bool(dynamic), mr))
        if landed is not None:
            # The speculative dispatch consumed the resident carry's
            # arrays as its input; an abort retry must re-export.
            resident = True
            n_conns = self._static_cols["_n_conns"]
        else:
            eng_epoch = self.engine.state_epoch()
            resident = (self._res_st is not None
                        and self._res_token == eng_epoch)
            if self._res_st is not None and not resident:
                self.stale_drops += 1
                self._res_st = None
            if resident:
                self.resident_hits += 1
                st = self._resident_input()
                self._res_st = None  # consumed by this dispatch
            else:
                st = self._export_state()
                if st is None:
                    self.ineligible += 1
                    return None
                if isinstance(st, int):
                    # transiently outside the steady-stream domain
                    # (handshake, close, over-caps): the router
                    # retries soon
                    self.over_caps += 1
                    self.last_transient = True
                    return None
            st = dict(st)
            st.pop("_n_conns", None)
            n_conns = self._static_cols["_n_conns"]
            if dbg:
                print(f"[tcp_span] export ok: {n_conns} conns, "
                      f"CC={self._CC}, start={start}, "
                      f"resident={resident}", file=sys.stderr,
                      flush=True)
            self._fn = self._cached_build()
            if self.mesh is not None:
                st = self._mesh_put(st)
        w = self.wall
        for _grow in range(4):
            _tw = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
            spec_rec, landed = landed, None
            if spec_rec is not None:
                fresh_fn = False
                out = spec_rec["out"]
            else:
                fresh_fn = id(self._fn) not in self._timed_fns
                out = self._span_call(
                    self._fn,
                    st, self._lat, self._thr, self._node,
                    self._ips_sorted, self._ips_perm,
                    np.uint32(self._k[0]), np.uint32(self._k[1]),
                    np.int64(self.bootstrap_end),
                    start, stop, limit, runahead, mr)
            (st_out, next_start, ra, rounds, busy_rounds, packets,
             busy_end, span_iters) = out
            st_np = {k: np.asarray(v) for k, v in st_out.items()}
            code = int(st_np["abort_code"])
            # First dispatch through a given built fn pays trace+XLA
            # compile (capacity regrows rebuild it); the split feeds
            # the explicit fn_cache accounting
            # (metrics.wall.dispatch.fn_cache).
            _dt = _time.perf_counter_ns() - _tw  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
            self._timed_fns.add(id(self._fn))
            self.device_wall_ns += _dt
            if spec_rec is not None:
                # A landed window's force wait is host idle (the
                # device was already running); its dispatch->force
                # wall is the pipe the idle fractions divide by.
                self.overlap_wait_ns += _dt
                self.overlap_pipe_ns += \
                    _time.perf_counter_ns() - spec_rec["t_disp"]  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                if w is not None:
                    w.add("overlap-land", _dt, _tw)
            else:
                if fresh_fn:
                    self._credit_build(self._fn, _dt)
                if w is not None:
                    w.add("compile" if fresh_fn else "execute",
                          _dt, _tw)
            if code != 0:
                # Speculative-window waste: an aborted dispatch's
                # wall and its stepped rounds roll back unused.
                self.rollback_wall_ns += _dt
                self.rolled_back_rounds += int(rounds)
                self._note_abort_kind(code)
            if dbg:
                print(f"[tcp_span] span done in "
                      f"{_time.perf_counter() - _t0:.1f}s: "  # shadow-lint: allow[wall-clock] debug span timing
                      f"rounds={int(rounds)} abort={code} "
                      f"site={int(st_np.get('abort_site', 0))}",
                      file=sys.stderr, flush=True)
            if code == 0:
                break
            if code & AB_STRUCT:
                self.last_abort_code = code
                # Hard abort regardless of residency (and before any
                # re-export the next statement would discard — a
                # domain-drifted re-export here would misaccount the
                # structural abort as transient and keep the router
                # re-probing a broken kernel); the consumed resident
                # carry was already cleared above.
                self.aborts += 1
                return None
            if resident or self.donate_active():
                # The resident carry was consumed by the aborted
                # dispatch — and under donation the FRESH input's
                # buffers were donated to it too, so either way the
                # retry needs new arrays; the engine — kept
                # authoritative by the per-span imports — re-exports
                # the same state.  Abort accounting follows the
                # fresh-dispatch convention: a capacity grow that
                # then succeeds counts zero.
                resident = False
                _tr = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                st = self._export_state()
                self.rollback_reexport_ns += \
                    _time.perf_counter_ns() - _tr  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                if st is None:
                    self.ineligible += 1
                    return None
                if isinstance(st, int):
                    # the state drifted out of the steady-stream
                    # domain (handshake/close): retry-soon, not a
                    # hard abort, or the router would disable the
                    # family after three domain excursions
                    self.over_caps += 1
                    self.last_transient = True
                    return None
                st = dict(st)
                st.pop("_n_conns", None)
                if self.mesh is not None:
                    st = self._mesh_put(st)
            if code & AB_TRACE:
                self.cap_tr *= 4
            if code & AB_OUT:
                self.cap_out *= 4
            if code & AB_EXCH:
                # Exchange overflow: grow the per-shard capacity and
                # retry (the retry re-applied mesh sharding above).
                # Grow from the EFFECTIVE capacity (the kernel builds
                # with E = max(exchange_cap, 8)), so a tiny configured
                # capacity cannot waste a retry on an identical shape.
                self.exchange_cap = max(self.exchange_cap, 8) * 4
                self.exch_grows += 1
            self._fn = self._cached_build()
        else:
            self.last_abort_code = code
            self.aborts += 1
            return None
        if int(rounds) == 0:
            # The untouched carry stays resident (the output is the
            # identical state).
            self._res_st = st_out
            self._res_token = self.engine.state_epoch()
            return (0, 0, 0, int(start), int(start), int(runahead))
        # Overlap (phold_span twin): dispatch window K+1
        # asynchronously NOW, so the device executes it while the
        # host does this window's codec conversion + engine import
        # below.  Committed (epoch-stamped and published) only after
        # the import below bumped the epoch.
        ra_out = int(ra) if dynamic else int(runahead)
        spec = None
        if self.overlap and spec_mr > 0 and not self.donate_active() \
                and int(next_start) < int(stop) \
                and int(next_start) < int(limit):
            spec = self._speculate(st_out, int(next_start), int(stop),
                                   int(limit), ra_out, dynamic,
                                   spec_mr)
        traces = None
        if self.tracing:
            n = int(st_np["tr_n"])
            traces = {
                "n": n,
                "t": st_np["tr_t"][:n].astype(np.int64).tobytes(),
                "kind": st_np["tr_kind"][:n].astype(
                    np.uint8).tobytes(),
                "srchost": st_np["tr_srchost"][:n].astype(
                    np.int32).tobytes(),
                "pseq": st_np["tr_pseq"][:n].astype(
                    np.int64).tobytes(),
                "sip": st_np["tr_sip"][:n].astype(
                    np.uint32).tobytes(),
                "sport": st_np["tr_sport"][:n].astype(
                    np.int32).tobytes(),
                "dip": st_np["tr_dip"][:n].astype(np.uint32).tobytes(),
                "dport": st_np["tr_dport"][:n].astype(
                    np.int32).tobytes(),
                "size": st_np["tr_plen"][:n].astype(
                    np.int64).tobytes(),
                "reason": st_np["tr_reason"][:n].astype(
                    np.uint8).tobytes(),
                "owner": st_np["tr_owner"][:n].astype(
                    np.int32).tobytes(),
            }
        st_np["_n_conns"] = n_conns
        _tw = w.now() if w is not None else 0
        # tel_*/fab_*/ks_* sample buffers are span-local output, not
        # engine state.
        back = self._from_arrays(
            {k: v for k, v in st_np.items()
             if not k.startswith("tel_")
             and not k.startswith("fab_")
             and not k.startswith("ks_")})
        # Codec byte volume, host -> engine (dispatch attribution).
        self.import_bytes += sum(
            len(v) for v in back.values()
            if isinstance(v, (bytes, bytearray, memoryview)))
        self.engine.span_import_tcp(back, *self._caps(), traces)
        self._emit_netstat(st_np)
        self._emit_fabric(st_np)
        if self.kern is not None:
            # One KS_REC per committed span (aborted spans rolled
            # back and recorded nothing — the conservation law).
            from shadow_tpu.trace.events import FAM_TCP
            self.kern.record_span(
                int(start), FAM_TCP, self._H, int(rounds),
                int(span_iters), st_np["ks_fires"], st_np["ks_lanes"])
        if w is not None:
            w.add("import", w.now() - _tw, _tw)
        # Record AFTER the import's own epoch bump: the resident copy
        # is valid exactly until anything else touches the engine.
        self._res_st = st_out
        self._res_token = self.engine.state_epoch()
        self.last_was_cold = not self.compiled
        self.compiled = True
        self.spans += 1
        self.rounds += int(rounds)
        self.micro_iters += int(span_iters)
        if spec is not None:
            self._commit_spec(spec)
        return (int(rounds), int(busy_rounds), int(packets),
                int(next_start), int(busy_end), ra_out)

    def _speculate(self, st_out, start, stop, limit, runahead,
                   dynamic, spec_mr):
        """Async double-buffered dispatch of window K+1 (phold_span
        twin): rebuild the span input from the just-committed device
        output via the residency law and dispatch WITHOUT forcing —
        XLA executes on its own threads while the caller runs the
        host-side import.  SpanMeshMixin owns the record's
        commit/land/refuse protocol."""
        import time as _time
        mr = self._clamp_mr(spec_mr)
        saved = self._res_st
        self._res_st = st_out
        st = self._resident_input()
        self._res_st = saved
        st = dict(st)
        st.pop("_n_conns", None)
        if self.mesh is not None:
            st = self._mesh_put(st)
        w = self.wall
        t0 = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
        out = self._span_call(
            self._fn,
            st, self._lat, self._thr, self._node,
            self._ips_sorted, self._ips_perm,
            np.uint32(self._k[0]), np.uint32(self._k[1]),
            np.int64(self.bootstrap_end),
            start, stop, limit, runahead, mr)
        self.overlap_windows += 1
        if w is not None:
            w.add("dispatch",
                  _time.perf_counter_ns() - t0, t0)  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
        return self._speculate_record(
            out, t0, (start, stop, limit, runahead, bool(dynamic),
                      mr))
