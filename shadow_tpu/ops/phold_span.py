"""Device-resident multi-round loop for PHOLD-pure simulations.

The blueprint's core promise (SURVEY.md:19-23): socket/app state becomes
struct-of-arrays stepped by vectorized JAX functions, and whole
conservative windows iterate ON DEVICE (`lax.while_loop`) — propagation,
the min barrier, inbox merge, and app stepping in one dispatch, so the
host<->device round trip amortizes over K rounds instead of being paid
per round (VERDICT r4 missing #1/#2).

Scope: PHOLD (the classic PDES benchmark, ref src/test/phold) — every
host one APP_PHOLD LP + one APP_PHOLD_SEED over a single bound UDP
socket.  The model is a field-for-field twin of the engine's event loop
(netplane.cpp run_until + the UDP data-plane chain): same event total
order (time, packet-before-local, (src, seq)), same event-seq draw
points, same token-bucket/CoDel/recv-buffer arithmetic, same status-
change wake fan-out — so packet traces and sim-stats are byte-identical
to the serial/engine paths (gated in tests/test_phold_span.py).

Transactional: the engine exports a read-only snapshot
(span_export_phold), the device steps K windows, and the result imports
back ONLY on a clean run (no capacity/validity abort).  An aborted span
costs nothing — the engine re-runs those rounds on the C++ path, so
rare-path divergence degrades to fallback, never to corruption.

The micro-op interpreter: per while-iteration each host advances ONE
micro-op — pop its next due event, or continue a relay drain / app
stepper continuation.  This flattens the engine's nested control flow
(app step -> relay forward -> bucket park) into a vectorized state
machine with no data-dependent Python control flow inside jit.
"""

from __future__ import annotations

import time

import numpy as np

from shadow_tpu.core.rng import STREAM_PACKET_LOSS, mix_key, threefry2x32_jax
from shadow_tpu.core.simtime import TIME_NEVER
from shadow_tpu.ops.span_mesh import SpanMeshMixin

I64_MAX = np.int64(1 << 62)  # "no event" sentinel (== TIME_NEVER)

# Continuations (one per host).
C_IDLE = 0
C_R1 = 1      # relay inet-out drain
C_R2 = 2      # relay inet-in drain
C_M_STEP = 3  # main app stepper entry (sleep-restart + send)
C_S_STEP = 4  # seeder stepper entry
C_M_RECV = 5  # main recv phase (after a send's relay drain returns)
C_S_POST = 6  # seeder post-send bookkeeping

# Timer kinds / status bits / syscall slots (netplane.cpp).
TK_RELAY = 0
TK_APP = 2
TK_APP_TIMEOUT = 3
S_READABLE = 1 << 1
S_WRITABLE = 1 << 2
ASYS_SENDTO = 13
ASYS_RECVFROM = 14
ASYS_NANOSLEEP = 15
ASYS_N = 16

PKT_SIZE = 33   # 5-byte "phold" payload + UDP(8) + IPv4(20) headers
PAYLOAD_LEN = 5  # trace records carry the payload length, not total
MTU = 1500
CODEL_TARGET_NS = 5_000_000
CODEL_HARD_LIMIT = 1000
REFILL_NS = 1_000_000

# Trace kinds / drop reason codes (span_import_phold REASONS order).
TR_SND = 0
TR_DRP = 1
TR_RCV = 2
RSN_NONE = 0
RSN_RCVBUF = 3
RSN_NOSOCK = 4
RSN_NOROUTE = 5
RSN_LOSS = 6
RSN_UNREACH = 7
RSN_HOSTDOWN = 9
RSN_LINKDOWN = 10

# Sim-netstat drop-cause slots touched by this kernel (netplane.cpp
# TEL_* twins; the per-host (H, TEL_N) `drop_causes` column round-
# trips through the span codec so the engine's attribution counters
# stay authoritative across device spans).
TEL_CODEL = 0
TEL_RTR_LIMIT = 1
TEL_LOSS_EDGE = 2
TEL_UNREACHABLE = 3
TEL_NO_ROUTE = 4
TEL_NO_SOCKET = 5
TEL_RECVBUF_FULL = 9
TEL_HOST_DOWN = 11
TEL_LINK_DOWN = 12
TEL_N = 15

# Fabric-observatory activity mask (netplane.cpp FB_ACT_* twins;
# registered in analysis pass 1).
FB_ACT_CODEL = 1
FB_ACT_TB_OUT = 2
FB_ACT_TB_IN = 4
FB_ACT_LINK = 8

# Device-kernel observatory stage slots this family occupies
# (netplane.cpp KS_* twins, registered fail-closed in analysis
# pass 1; docs/OBSERVABILITY.md "Device-kernel observatory").  The
# kernel threads a (KS_N,) fire-count and active-lane-sum pair
# through the while_loop carry; the driver packs one KS_REC per
# committed span.
KS_POP = 0
KS_STEP = 1
KS_CODEL = 2
KS_INET_OUT = 8
KS_ARM = 9
KS_TIMERS = 10
KS_EXCHANGE = 11
KS_N = 12

PK_KEYS = ("srchost", "pseq", "sip", "sport", "dip", "dport")

# Abort reason bits: trace/outbox overflows are capacity problems the
# driver fixes by growing the buffer and retrying; structural bits mean
# the state left the modelled domain (fall back to the C++ path).
# AB_EXCH: the sharded cross-shard exchange overflowed its per-shard
# capacity — attributed (EL_ENGINE_EXCHANGE when spans fall back) and
# grown like the other capacity bits, never silently truncated.
# The values are ops/span_mesh.py's canonical set (one definition for
# both families — the mixin's abort-kind classifier depends on it).
from shadow_tpu.ops.span_mesh import (AB_EXCH, AB_OUT,  # noqa: E402
                                      AB_STRUCT, AB_TRACE)


# Compiled step cache: repeated sims of the same shape (bench trials,
# gates running serial-vs-device pairs) must not re-trace/re-compile the
# large while_loop body per Manager.
_FN_CACHE: dict = {}

# ---- Residency classification (the dirty-column export protocol) ----
# Every state key the codec (_to_arrays) produces falls in exactly one
# class.  CARRIED: the span's own device output is the next span's
# input while the engine's state_epoch is unchanged.  STATIC: build-
# time config — cached at the first export and reattached on reuse.
# DERIVED: re-derived at span entry by the same law _to_arrays applies
# to a fresh export (all are provably at their derived value at every
# clean span boundary).  shadow_tpu/analysis pass 2 cross-checks this
# table against the codec: a column added to _to_arrays without a
# classification entry fails scripts/lint, so stale-column reuse is a
# lint error before it can become a runtime hazard.
RESIDENT_STATIC = frozenset({
    "peers", "n_peers", "m_port", "m_mean", "s_count", "eth_ip",
    "recv_max", "send_max", "r1_refill", "r1_cap", "r1_unlimited",
    "r2_refill", "r2_cap", "r2_unlimited",
})
RESIDENT_DERIVED = frozenset({
    "cont", "then", "park_ctr", "out_first", "cd_chain", "cd_sniff",
})
# CARRIED: the span's own device output is the next input (all
# ring/heap columns plus the mutable scalars).  Ring packet
# columns follow PK_KEYS so a header-field addition classifies
# itself; every scalar column is listed explicitly so adding an
# export column without classifying it fails scripts/lint.
RESIDENT_CARRIED = frozenset(
    {
     "app_pkts_dropped", "app_pkts_recv", "app_pkts_sent",
     "app_sys", "codel_bytes", "drop_causes", "codel_count", "codel_drop_next",
     "codel_dropped", "codel_dropping", "codel_first_above",
     "codel_enq_pkts", "codel_enq_bytes", "codel_drop_bytes",
     "codel_peak", "codel_marked", "r1_stalls", "r2_stalls",
     "r1_fwd_pkts", "r1_fwd_bytes", "r2_fwd_pkts", "r2_fwd_bytes",
     "codel_last_count", "cq_enq", "cq_len", "cq_pos",
     "eth_brecv", "eth_bsent", "eth_precv", "eth_psent",
     "event_seq", "events_run", "ib_len", "ib_pos", "ib_seq",
     "ib_src", "ib_time", "m_exit_time", "m_exited", "m_gotn",
     "m_lcg", "m_partdone", "m_state", "m_target", "m_waitmask",
     "m_waitseq", "m_wakep", "now", "packet_seq", "queued",
     "r1_bal", "r1_next", "r1_pending", "r1_pk_valid", "r2_bal",
     "r2_next", "r2_pending", "r2_pk_valid", "recv_bytes",
     "rq_len", "rq_pos", "s_exit_time", "s_exited", "s_partdone",
     "s_senti", "s_state", "s_target", "s_waitmask", "s_waitseq",
     "s_wakep", "send_bytes", "sock_closed", "sq_len", "sq_pos",
     "status", "th_kind", "th_seq", "th_tgt", "th_time",
     "th_valid", "h_fault"}
    | {f"{p}_{kk}" for p in ('rq', 'sq', 'cq', 'ib', 'r1_pk', 'r2_pk')
       for kk in PK_KEYS})


class PholdSpanRunner(SpanMeshMixin):
    """Builds and drives the jitted multi-round device loop for one
    simulation.  One instance per Manager."""

    # Ring capacities (compile-time; export refuses state beyond half
    # of each, and the device aborts transactionally on overflow).
    CAP_I = 64    # inbox
    CAP_T = 16    # timer heap
    CAP_R = 256   # socket recv queue (mesh backlogs run deep)
    CAP_S = 256   # socket send queue (ring ops are indexed, not
    #               scanned, so the larger caps cost ~nothing)
    CAP_C = 2048  # CoDel ring (covers the engine's 1000-entry hard limit)
    CAP_P = 4096  # peers
    MAX_ROUNDS = 256
    # Fabric observatory: per-round queue-sample rows buffered on
    # device; spans clamp to FAB_ROWS rounds while the channel
    # records so the (FAB_ROWS, H) buffers can never overflow.
    FAB_ROWS = 64

    def __init__(self, engine, latency_ns, thresholds, host_node,
                 host_ips, seed, bootstrap_end, tracing: bool):
        self.engine = engine
        self.tracing = bool(tracing)
        k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
        self._k = (np.uint32(k0), np.uint32(k1))
        self._lat = np.ascontiguousarray(latency_ns, dtype=np.int64)
        self._thr = np.ascontiguousarray(thresholds, dtype=np.int64)
        self._node = np.ascontiguousarray(host_node, dtype=np.int32)
        ips = np.ascontiguousarray(host_ips, dtype=np.uint32)
        order = np.argsort(ips)
        self._ips_sorted = ips[order]
        self._ips_perm = order.astype(np.int32)
        self.bootstrap_end = int(bootstrap_end)
        self._fn = None
        self._H = len(host_ips)
        self.cap_out = max(512, 16 * self._H)
        self.cap_tr = max(1 << 14, 64 * self._H)
        self.spans = 0
        self.rounds = 0
        self.aborts = 0
        self.ineligible = 0
        self.over_caps = 0
        # First successful span pays the while_loop's XLA compile; its
        # wall time must not poison the auto-router's estimate.
        self.compiled = False
        self.last_was_cold = False
        # Optional jax.sharding.Mesh with a "hosts" axis: state shards
        # over it (H-major arrays -> PartitionSpec("hosts"), the rest
        # replicated) and GSPMD partitions the whole multi-round loop —
        # XLA inserts the cross-shard collectives for the inbox
        # scatter.  Requires H % mesh size == 0.
        self.mesh = None
        self.family = 0      # 0 phold, 1 udp-mesh (set from export)
        self._pay = 5        # uniform payload bytes (set from export)
        # Fused micro-op dispatch (default): ops chain within one
        # while-iteration.  False rebuilds the one-micro-op-per-
        # iteration reference schedule (differential gate).
        self.fused = True
        # Device-resident state between dispatches: the engine's
        # mutation epoch at our last import; export is skipped while
        # it still matches (see try_span).
        self._res_st = None
        self._res_token = None
        self._static_cols = None
        self.resident_hits = 0
        self.stale_drops = 0
        self.micro_iters = 0  # while-iterations across all spans
        self.last_abort_code = 0  # AB_* bits of the last abort
        # Flight-recorder wall channel (trace/recorder.WallChannel)
        # or None: per-dispatch phase walls (export / convert /
        # compile / execute / import).  Never the sim channel — a
        # dispatch's wall time is profiling, not simulation state.
        # _timed_fns: built-fn ids already dispatched once, so the
        # compile-vs-execute split survives capacity-regrow rebuilds.
        self.wall = None
        self._timed_fns: set = set()
        # Fabric-observatory channel (trace/fabricstat.FabricChannel)
        # or None: round_body buffers per-round per-host queue
        # samples; the driver packs ACTIVE hosts into FB_REC records
        # at span commit (the phold family has no TCP connections, so
        # no netstat/FCT side here).
        self.fabric = None

    # ------------------------------------------------------------------
    # Export bytes <-> numpy state
    # ------------------------------------------------------------------

    def _to_arrays(self, d: dict) -> dict:
        H = self._H
        I, T, R, S, C = (self.CAP_I, self.CAP_T, self.CAP_R,
                         self.CAP_S, self.CAP_C)

        def f(k, dt, shape=None):
            a = np.frombuffer(d[k], dtype=dt)
            a = a.reshape(shape) if shape is not None else a
            return a.copy()

        st = {}
        for k in ("now", "event_seq", "packet_seq", "recv_bytes",
                  "recv_max", "send_bytes", "send_max", "codel_bytes",
                  "codel_dropped", "m_waitseq", "m_gotn", "m_mean",
                  "s_waitseq", "s_senti", "s_count", "s_exit_time"):
            st[k] = f(k, np.int64)
        st["app_pkts_sent"] = f("pkts_sent", np.int64)
        st["app_pkts_recv"] = f("pkts_recv", np.int64)
        st["app_pkts_dropped"] = f("pkts_dropped", np.int64)
        st["drop_causes"] = f("drop_causes", np.int64, (H, TEL_N))
        for k in ("events_run", "eth_psent", "eth_precv", "eth_bsent",
                  "eth_brecv"):
            st[k] = f(k, np.int64)
        for k in ("eth_ip", "status", "m_waitmask", "s_waitmask",
                  "m_lcg", "m_target", "s_target"):
            st[k] = f(k, np.uint32)
        for k in ("queued", "m_state", "m_wakep", "s_state", "s_wakep",
                  "s_exited", "m_exited", "m_partdone", "s_partdone",
                  "sock_closed"):
            st[k] = f(k, np.uint8).astype(np.int32)
        # Down-host fault mask (docs/ROBUSTNESS.md): bit0 down, bit1
        # link_down, bit2 blackhole.  Constant within a span (faults
        # apply only at round boundaries, which cap span `limit`);
        # CARRIED so resident reuse keeps the engine's live flags.
        st["h_fault"] = f("h_fault", np.uint8).astype(np.int32)
        st["m_exit_time"] = f("m_exit_time", np.int64)
        st["out_first"] = np.zeros(H, np.int32)
        st["cd_chain"] = np.zeros(H, np.int32)
        st["cd_sniff"] = np.zeros(H, np.int32)
        self.family = int(np.frombuffer(d["family"], np.uint8)[0])
        self._pay = int(np.frombuffer(d["pay_size"], np.int64)[0])
        # codel AQM bookkeeping rides along untouched; the device only
        # runs while the queue is quiescent (abort otherwise).
        st["codel_dropping"] = f("codel_dropping", np.uint8).astype(
            np.int32)
        st["codel_first_above"] = f("codel_first_above", np.int64)
        for k in ("codel_count", "codel_last_count", "codel_drop_next",
                  "codel_enq_pkts", "codel_enq_bytes",
                  "codel_drop_bytes", "codel_peak", "codel_marked"):
            st[k] = f(k, np.int64)
        st["m_port"] = f("m_port", np.int32)
        st["n_peers"] = f("n_peers", np.int32)
        P = len(np.frombuffer(d["peers"], np.uint32)) // H
        st["peers"] = f("peers", np.uint32, (H, P))
        st["app_sys"] = f("app_sys", np.int64, (H, ASYS_N))
        for pfx, cap in (("rq", R), ("sq", S), ("cq", C), ("ib", I)):
            for kk, dt in (("srchost", np.int32), ("pseq", np.int64),
                           ("sip", np.uint32), ("sport", np.int32),
                           ("dip", np.uint32), ("dport", np.int32)):
                st[f"{pfx}_{kk}"] = f(f"{pfx}_{kk}", dt, (H, cap))
            st[f"{pfx}_len"] = f(f"{pfx}_len", np.int32)
        st["cq_enq"] = f("cq_enq", np.int64, (H, C))
        st["ib_time"] = f("ib_time", np.int64, (H, I))
        st["ib_src"] = f("ib_src", np.int32, (H, I))
        st["ib_seq"] = f("ib_seq", np.int64, (H, I))
        st["th_time"] = f("th_time", np.int64, (H, T))
        st["th_seq"] = f("th_seq", np.int64, (H, T))
        st["th_kind"] = f("th_kind", np.uint8, (H, T)).astype(np.int32)
        st["th_tgt"] = f("th_tgt", np.uint8, (H, T)).astype(np.int32)
        st["th_valid"] = (np.arange(T)[None, :]
                          < f("th_len", np.int32)[:, None])
        for r in (1, 2):
            st[f"r{r}_pending"] = f(f"r{r}_pending", np.uint8).astype(
                np.int32)
            st[f"r{r}_unlimited"] = f(f"r{r}_unlimited",
                                      np.uint8).astype(np.int32)
            for k in ("bal", "next", "refill", "cap", "stalls",
                      "fwd_pkts", "fwd_bytes"):
                st[f"r{r}_{k}"] = f(f"r{r}_{k}", np.int64)
            st[f"r{r}_pk_valid"] = f(f"r{r}_pk_valid",
                                     np.uint8).astype(np.int32)
            for kk, dt in (("srchost", np.int32), ("pseq", np.int64),
                           ("sip", np.uint32), ("sport", np.int32),
                           ("dip", np.uint32), ("dport", np.int32)):
                st[f"r{r}_pk_{kk}"] = f(f"r{r}_pk_{kk}", dt)
        for k in ("rq_pos", "sq_pos", "cq_pos", "ib_pos"):
            st[k] = np.zeros(H, np.int32)
        st["cont"] = np.zeros(H, np.int32)
        st["then"] = np.zeros(H, np.int32)
        st["park_ctr"] = np.maximum(st["m_waitseq"],
                                    st["s_waitseq"]) + 1
        # padded-slot invariants the sort/argmin tricks rely on
        st["ib_time"][np.arange(I)[None, :] >= st["ib_len"][:, None]] \
            = I64_MAX
        return st

    def _from_arrays(self, st: dict) -> dict:
        """Back to the engine's packed-byte import layout (rings
        re-packed from their head positions)."""
        H = self._H
        out = {}

        def npv(k):
            return np.asarray(st[k])

        def ring(pfx, cap, pos_k, len_k, modulo, extra=()):
            pos = npv(pos_k).astype(np.int64)
            ln = npv(len_k).astype(np.int64)
            ar = np.arange(cap, dtype=np.int64)[None, :]
            idx = (pos[:, None] + ar) % cap if modulo \
                else np.minimum(pos[:, None] + ar, cap - 1)
            for kk in PK_KEYS:
                a = np.take_along_axis(npv(f"{pfx}_{kk}"), idx, axis=1)
                out[f"{pfx}_{kk}"] = np.ascontiguousarray(a).tobytes()
            for kk in extra:
                a = np.take_along_axis(npv(kk), idx, axis=1)
                out[kk] = np.ascontiguousarray(a).tobytes()
            out[len_k] = (ln - pos).astype(np.int32).tobytes()

        ring("rq", self.CAP_R, "rq_pos", "rq_len", True)
        ring("sq", self.CAP_S, "sq_pos", "sq_len", True)
        ring("cq", self.CAP_C, "cq_pos", "cq_len", True,
             extra=("cq_enq",))
        # inbox is linear (pos resets to 0 at each round's merge)
        ring("ib", self.CAP_I, "ib_pos", "ib_len", False,
             extra=("ib_time", "ib_src", "ib_seq"))
        # timer heap: compact valid entries to the front
        tv = npv("th_valid")
        order = np.argsort(~tv, axis=1, kind="stable")
        for k in ("th_time", "th_seq"):
            a = np.take_along_axis(npv(k), order, axis=1)
            out[k] = np.ascontiguousarray(a).tobytes()
        for k in ("th_kind", "th_tgt"):
            a = np.take_along_axis(npv(k), order, axis=1)
            out[k] = np.ascontiguousarray(a.astype(np.uint8)).tobytes()
        out["th_len"] = tv.sum(axis=1).astype(np.int32).tobytes()
        for k in ("now", "event_seq", "packet_seq", "recv_bytes",
                  "send_bytes", "codel_bytes", "codel_count",
                  "codel_last_count", "codel_first_above",
                  "codel_drop_next", "codel_dropped",
                  "codel_enq_pkts", "codel_enq_bytes",
                  "codel_drop_bytes", "codel_peak", "codel_marked",
                  "m_waitseq",
                  "m_gotn", "s_waitseq", "s_senti", "s_exit_time"):
            out[k] = npv(k).astype(np.int64).tobytes()
        out["pkts_sent"] = npv("app_pkts_sent").astype(np.int64).tobytes()
        out["pkts_recv"] = npv("app_pkts_recv").astype(np.int64).tobytes()
        out["pkts_dropped"] = npv("app_pkts_dropped").astype(
            np.int64).tobytes()
        out["drop_causes"] = npv("drop_causes").astype(
            np.int64).tobytes()
        for k in ("events_run", "eth_psent", "eth_precv", "eth_bsent",
                  "eth_brecv"):
            out[k] = npv(k).astype(np.int64).tobytes()
        for k in ("status", "m_waitmask", "s_waitmask", "m_lcg",
                  "m_target", "s_target"):
            out[k] = npv(k).astype(np.uint32).tobytes()
        for k in ("queued", "m_state", "m_wakep", "s_state", "s_wakep",
                  "s_exited", "codel_dropping", "m_exited",
                  "m_partdone", "s_partdone", "sock_closed",
                  "out_first", "h_fault"):
            out[k] = npv(k).astype(np.uint8).tobytes()
        out["m_exit_time"] = npv("m_exit_time").astype(
            np.int64).tobytes()
        for r in (1, 2):
            out[f"r{r}_pending"] = npv(f"r{r}_pending").astype(
                np.uint8).tobytes()
            out[f"r{r}_pk_valid"] = npv(f"r{r}_pk_valid").astype(
                np.uint8).tobytes()
            out[f"r{r}_bal"] = npv(f"r{r}_bal").astype(
                np.int64).tobytes()
            out[f"r{r}_next"] = npv(f"r{r}_next").astype(
                np.int64).tobytes()
            out[f"r{r}_stalls"] = npv(f"r{r}_stalls").astype(
                np.int64).tobytes()
            out[f"r{r}_fwd_pkts"] = npv(f"r{r}_fwd_pkts").astype(
                np.int64).tobytes()
            out[f"r{r}_fwd_bytes"] = npv(f"r{r}_fwd_bytes").astype(
                np.int64).tobytes()
            for kk in PK_KEYS:
                out[f"r{r}_pk_{kk}"] = np.ascontiguousarray(
                    npv(f"r{r}_pk_{kk}")).tobytes()

        out["app_sys"] = npv("app_sys").astype(np.int64).tobytes()
        return out

    # ------------------------------------------------------------------
    # The jitted multi-round step
    # ------------------------------------------------------------------

    def _fabric_params(self):
        """(enabled, interval_ns>=1) — static for the built kernel."""
        if self.fabric is None:
            return (False, 1)
        return (True, max(int(self.fabric.interval_ns), 1))

    def _cached_build(self, P: int):
        key = (self._H, P, self._lat.shape, self.CAP_I, self.CAP_T,
               self.CAP_R, self.CAP_S, self.CAP_C, self.cap_out,
               self.cap_tr, self.tracing, self.family, self.fused,
               self._fabric_params(), self.kern is not None,
               self.mesh, self.exchange_cap, self.pallas_queues)
        return self._cache_fn(_FN_CACHE, key, lambda: self._build(P))

    def _build(self, P: int):
        import jax
        import jax.numpy as jnp

        H = self._H
        I, T, R, S, C = (self.CAP_I, self.CAP_T, self.CAP_R,
                         self.CAP_S, self.CAP_C)
        O = self.cap_out
        TR = self.cap_tr
        tracing = self.tracing
        family = self.family  # static: compiled per family
        fused = self.fused    # static: fused vs reference dispatch
        n_shards = self.n_shards  # static: mesh width (1 = unsharded)
        exchange = (self._build_exchange(jax, jnp)
                    if n_shards > 1 else None)
        fabric, fab_iv = self._fabric_params()
        FABR = self.FAB_ROWS
        kern = self.kern is not None  # static: stage counters on
        hidx = jnp.arange(H, dtype=jnp.int32)
        OOB = jnp.int32(H + 1)  # mode="drop" sink for masked-out lanes

        # Lane-parallel queue-scan kernels (ISSUE 16): the bucket and
        # CoDel-head laws live in ops/pallas_queues.py — the lax
        # reference inline, or its pallas twin when the knob is on
        # (unsharded only: the GSPMD partitioner owns the sharded
        # while_loop body).  Static, so part of the _FN_CACHE key.
        from shadow_tpu.ops import pallas_queues as plq
        pq = self.pallas_queues and n_shards == 1
        bucket_step = plq.make_bucket_step(jax, jnp, H, REFILL_NS, pq)
        codel_head = plq.make_codel_head(jax, jnp, H, CODEL_TARGET_NS,
                                         MTU, pq)

        def mrows(mask):
            return jnp.where(mask, hidx, OOB)

        # -------- primitive helpers ------------------------------

        def mark_abort(st, cond, bit):
            st = dict(st)
            st["abort_code"] = st["abort_code"] | jnp.where(
                cond, jnp.int32(bit), jnp.int32(0))
            return st

        def ks_count(st, code, mask):
            """Device-kernel observatory: credit one stage with this
            iteration's active lanes (fires += any-lane, lanes +=
            popcount).  Pure counters in the carry — never touches
            simulation state, so the forced-device differentials hold
            with the observatory on."""
            if not kern:
                return st
            st = dict(st)
            n = mask.sum().astype(jnp.int64)
            st["ks_lanes"] = st["ks_lanes"].at[code].add(n)
            st["ks_fires"] = st["ks_fires"].at[code].add(
                (n > 0).astype(jnp.int64))
            return st

        def ks_count_pop(st, mask, window_end):
            """The pop stage's counters, split from op_pop_event's own
            law (same ib-vs-timer pick rule): all due lanes fire the
            pop stage; timer pops additionally fire `timers` — this
            family handles them inline in the pop micro-op."""
            if not kern:
                return st
            ib_t, th_t = next_event_time(st)
            due = mask & (jnp.minimum(ib_t, th_t) < window_end)
            pick_ib = jnp.where(ib_t != th_t, ib_t < th_t,
                                ib_t < I64_MAX)
            st = ks_count(st, KS_POP, due)
            return ks_count(st, KS_TIMERS, due & ~pick_ib)

        def th_push(st, mask, time, seq, kind, tgt):
            free = jnp.argmin(st["th_valid"], axis=1)
            overflow = mask & st["th_valid"].all(axis=1)
            mask = mask & ~overflow
            rows = mrows(mask)
            st = dict(st)
            for key, v in (("th_time", time), ("th_seq", seq)):
                st[key] = st[key].at[rows, free].set(v, mode="drop")
            st["th_kind"] = st["th_kind"].at[rows, free].set(
                kind, mode="drop")
            st["th_tgt"] = st["th_tgt"].at[rows, free].set(
                tgt, mode="drop")
            st["th_valid"] = st["th_valid"].at[rows, free].set(
                True, mode="drop")
            return mark_abort(st, overflow.any(), AB_STRUCT)

        def th_min(st):
            t = jnp.where(st["th_valid"], st["th_time"], I64_MAX)
            best_t = t.min(axis=1)
            s = jnp.where(t == best_t[:, None], st["th_seq"], I64_MAX)
            slot = jnp.argmin(s, axis=1)
            return (best_t, st["th_kind"][hidx, slot],
                    st["th_tgt"][hidx, slot], slot)

        def draw_seq(st, mask):
            v = st["event_seq"]
            st = dict(st)
            st["event_seq"] = jnp.where(mask, v + 1, v)
            return st, v

        def lcg_next(st, mask):
            v = st["m_lcg"]
            nv = v * jnp.uint32(1664525) + jnp.uint32(1013904223)
            st = dict(st)
            st["m_lcg"] = jnp.where(mask, nv, v)
            return st, nv

        def seq_append(st, prefix, cap_total, mask, cols: dict,
                       count_key, abort_bit):
            """Ordered multi-append into a flat buffer (outbox/trace):
            lanes rank by host index — order among same-iteration
            emitters is not semantically load-bearing (see netplane.cpp
            run_hosts_mt outbox-merge comment)."""
            st = dict(st)
            n = st[count_key]
            rank = jnp.cumsum(mask) - 1
            slot = jnp.where(mask, n + rank, cap_total + 8)
            for key, v in cols.items():
                st[key] = st[key].at[slot].set(v, mode="drop")
            total = n + mask.sum()
            st[count_key] = total
            return mark_abort(st, total > cap_total - H, abort_bit)

        def tr_append(st, mask, time, kind, pk, reason):
            if not tracing:
                return st
            return seq_append(
                st, "tr", TR, mask,
                {"tr_t": time,
                 "tr_kind": jnp.full(H, kind, jnp.int32),
                 "tr_srchost": pk["srchost"], "tr_pseq": pk["pseq"],
                 "tr_sip": pk["sip"], "tr_sport": pk["sport"],
                 "tr_dip": pk["dip"], "tr_dport": pk["dport"],
                 "tr_reason": jnp.full(H, reason, jnp.int32),
                 "tr_owner": hidx}, "tr_n", AB_TRACE)

        def wake_check(st, changed_bits, time):
            """adjust_status's app_wake fan-out, ordered by wait_seq
            when both siblings qualify."""
            m_ok = ((st["m_wakep"] == 0) & (st["m_exited"] == 0)
                    & ((changed_bits & st["m_waitmask"]) != 0))
            s_ok = ((st["s_wakep"] == 0) & (st["s_exited"] == 0)
                    & ((changed_bits & st["s_waitmask"]) != 0))
            both = m_ok & s_ok
            first_is_s = (both & (st["s_waitseq"] < st["m_waitseq"])) \
                | (s_ok & ~m_ok)
            first = m_ok | s_ok
            st, sq1 = draw_seq(st, first)
            st = th_push(st, first & first_is_s, time, sq1, TK_APP, 1)
            st = th_push(st, first & ~first_is_s, time, sq1, TK_APP, 0)
            st = dict(st)
            st["s_wakep"] = jnp.where(first & first_is_s, 1,
                                      st["s_wakep"])
            st["m_wakep"] = jnp.where(first & ~first_is_s, 1,
                                      st["m_wakep"])
            st, sq2 = draw_seq(st, both)
            st = th_push(st, both & first_is_s, time, sq2, TK_APP, 0)
            st = th_push(st, both & ~first_is_s, time, sq2, TK_APP, 1)
            st = dict(st)
            st["m_wakep"] = jnp.where(both & first_is_s, 1,
                                      st["m_wakep"])
            st["s_wakep"] = jnp.where(both & ~first_is_s, 1,
                                      st["s_wakep"])
            return st

        def set_status(st, set_bits, clear_bits, mask, time):
            cur = st["status"]
            nw = (cur | set_bits) & ~clear_bits
            changed = jnp.where(mask, cur ^ nw, jnp.uint32(0))
            st = dict(st)
            st["status"] = jnp.where(mask, nw, cur)
            return wake_check(st, changed, time)

        def bucket_try(st, r, now, mask):
            bal = st[f"r{r}_bal"]
            nxt = st[f"r{r}_next"]
            bal3, nxt2, ok = bucket_step(
                bal, nxt, st[f"r{r}_refill"], st[f"r{r}_cap"],
                st[f"r{r}_unlimited"] == 1, st["_psize"], now)
            st = dict(st)
            st[f"r{r}_bal"] = jnp.where(mask, bal3, bal)
            st[f"r{r}_next"] = jnp.where(mask, nxt2, nxt)
            return st, ok, nxt2

        # -------- micro-op: relay drains -------------------------

        def op_relay(st, r, mask):
            now = st["now"]
            pend_valid = st[f"r{r}_pk_valid"] == 1
            use_pend = mask & pend_valid
            if r == 1:
                src_avail = mask & (st["queued"] == 1) & (
                    st["sq_len"] > st["sq_pos"])
                pos = st["sq_pos"] % S
                pk = {kk: jnp.where(use_pend, st[f"r1_pk_{kk}"],
                                    st[f"sq_{kk}"][hidx, pos])
                      for kk in PK_KEYS}
            else:
                src_avail = mask & (st["cq_len"] > st["cq_pos"])
                pos = st["cq_pos"] % C
                pk = {kk: jnp.where(use_pend, st[f"r2_pk_{kk}"],
                                    st[f"cq_{kk}"][hidx, pos])
                      for kk in PK_KEYS}
                enq = st["cq_enq"][hidx, pos]
            pop = mask & ~use_pend & src_avail
            none = mask & ~use_pend & ~src_avail

            st = dict(st)
            st[f"r{r}_pk_valid"] = jnp.where(use_pend, 0,
                                             st[f"r{r}_pk_valid"])
            if r == 1:
                # iface_pop twin: dequeue, writable status, SND trace
                st["sq_pos"] = jnp.where(pop, st["sq_pos"] + 1,
                                         st["sq_pos"])
                st["send_bytes"] = jnp.where(
                    pop, st["send_bytes"] - st["_psize"], st["send_bytes"])
                st["queued"] = jnp.where(
                    pop, (st["sq_len"] > st["sq_pos"]).astype(jnp.int32),
                    st["queued"])
                # pull_out_packet guards the writable set with
                # !(status & S_CLOSED) — a closed (process-exited)
                # socket's draining queue must not re-set the bit
                st = set_status(st, jnp.uint32(S_WRITABLE),
                                jnp.uint32(0),
                                pop & (st["sock_closed"] == 0), now)
                st = dict(st)
                st["eth_psent"] = jnp.where(pop, st["eth_psent"] + 1,
                                            st["eth_psent"])
                st["eth_bsent"] = jnp.where(
                    pop, st["eth_bsent"] + st["_psize"], st["eth_bsent"])
                st = tr_append(st, pop, now, TR_SND, pk, RSN_NONE)
            else:
                # full CoDel (codel_pop twin, netplane.cpp): one
                # dequeue_raw per micro-op; the drop while-loop and the
                # leading-drop sniff unroll across micro-ops via the
                # cd_chain / cd_sniff substates.
                st["cq_pos"] = jnp.where(pop, st["cq_pos"] + 1,
                                         st["cq_pos"])
                st["codel_bytes"] = jnp.where(
                    pop, st["codel_bytes"] - st["_psize"],
                    st["codel_bytes"])
                # dequeue_raw's ok/first_above law (pallas_queues)
                quiet, above, arm, cok, fa_new = codel_head(
                    pop, none, now, enq, st["codel_bytes"],
                    st["codel_first_above"])
                st["codel_first_above"] = fa_new
                st["codel_dropping"] = jnp.where(none, 0,
                                                 st["codel_dropping"])
                st["cd_chain"] = jnp.where(none, 0, st["cd_chain"])
                st["cd_sniff"] = jnp.where(none, 0, st["cd_sniff"])

                def control_time(t, count):
                    v = count << 32
                    g = jnp.sqrt(v.astype(jnp.float64)).astype(jnp.int64)
                    g = jnp.where(g * g > v, g - 1, g)
                    g = jnp.where(g * g > v, g - 1, g)
                    g = jnp.where((g + 1) * (g + 1) <= v, g + 1, g)
                    g = jnp.where((g + 1) * (g + 1) <= v, g + 1, g)
                    g = jnp.maximum(g, 1)
                    return t + (np.int64(100_000_000) << 16) // g

                in_sniff = st["cd_sniff"] == 1
                in_chain = (st["cd_chain"] == 1) & ~in_sniff
                top = pop & ~in_sniff & ~in_chain

                # --- sniff resolution (the dequeue after a leading
                # drop): becomes the drop-state entry, id delivered
                # regardless of its own ok bit.
                sg = pop & in_sniff
                cnt_new = jnp.where(
                    now - st["codel_drop_next"] < np.int64(100_000_000),
                    jnp.where(st["codel_count"] > 2,
                              st["codel_count"] - st["codel_last_count"],
                              1), 1)
                st["codel_dropping"] = jnp.where(sg, 1,
                                                 st["codel_dropping"])
                st["codel_count"] = jnp.where(sg, cnt_new,
                                              st["codel_count"])
                st["codel_last_count"] = jnp.where(
                    sg, cnt_new, st["codel_last_count"])
                st["codel_drop_next"] = jnp.where(
                    sg, control_time(now, cnt_new),
                    st["codel_drop_next"])
                st["cd_sniff"] = jnp.where(sg, 0, st["cd_sniff"])

                # --- chain continuation: post-dequeue drop_next update
                # (engine does it after each ok re-dequeue), then the
                # while condition decides drop-or-deliver.
                cg = pop & in_chain
                cg_exit = cg & ~cok
                st["codel_dropping"] = jnp.where(cg_exit, 0,
                                                 st["codel_dropping"])
                st["cd_chain"] = jnp.where(cg_exit, 0, st["cd_chain"])
                cg_ok = cg & cok
                dn2 = control_time(st["codel_drop_next"],
                                   st["codel_count"])
                st["codel_drop_next"] = jnp.where(
                    cg_ok, dn2, st["codel_drop_next"])
                cg_drop = cg_ok & (now >= st["codel_drop_next"])
                cg_deliver = cg_ok & ~cg_drop
                st["cd_chain"] = jnp.where(cg_deliver, 0,
                                           st["cd_chain"])

                # --- top entry while in drop state
                td = top & (st["codel_dropping"] == 1)
                td_exit = td & ~cok
                st["codel_dropping"] = jnp.where(td_exit, 0,
                                                 st["codel_dropping"])
                td_ok = td & cok
                td_drop = td_ok & (now >= st["codel_drop_next"])
                st["cd_chain"] = jnp.where(td_drop, 1, st["cd_chain"])

                # --- leading-edge drop (AQM trigger).  `~td`: a lane
                # that ENTERED this dequeue in drop-state took the
                # if-branch (engine's else-if) even when it just
                # cleared dropping.
                tl = top & ~td & cok & (
                    (now - st["codel_drop_next"] < np.int64(100_000_000))
                    | (now - st["codel_first_above"]
                       >= np.int64(100_000_000)))
                st["cd_sniff"] = jnp.where(tl, 1, st["cd_sniff"])

                codel_drop = cg_drop | td_drop | tl
                # chain drops advance count; the leading drop does not
                st["codel_count"] = jnp.where(
                    cg_drop | td_drop, st["codel_count"] + 1,
                    st["codel_count"])
                st["codel_dropped"] = jnp.where(
                    codel_drop, st["codel_dropped"] + 1,
                    st["codel_dropped"])
                st["codel_drop_bytes"] = jnp.where(
                    codel_drop, st["codel_drop_bytes"] + st["_psize"],
                    st["codel_drop_bytes"])
                st["app_pkts_dropped"] = jnp.where(
                    codel_drop, st["app_pkts_dropped"] + 1,
                    st["app_pkts_dropped"])
                st["drop_causes"] = st["drop_causes"].at[
                    mrows(codel_drop), TEL_CODEL].add(1, mode="drop")
                st = tr_append(st, codel_drop, now, TR_DRP, pk, 1)
                st = dict(st)
                # dropped lanes stay in the drain (next micro-op
                # re-dequeues); delivered lanes carry on below
                pop = pop & ~codel_drop

            has_pkt = use_pend | pop
            st, ok, when = bucket_try(st, r, now, has_pkt)
            throttled = has_pkt & ~ok
            st = dict(st)
            st[f"r{r}_stalls"] = st[f"r{r}_stalls"] + throttled
            st[f"r{r}_pending"] = jnp.where(throttled, 1,
                                            st[f"r{r}_pending"])
            st[f"r{r}_pk_valid"] = jnp.where(throttled, 1,
                                             st[f"r{r}_pk_valid"])
            for kk in PK_KEYS:
                st[f"r{r}_pk_{kk}"] = jnp.where(throttled, pk[kk],
                                                st[f"r{r}_pk_{kk}"])
            st, sq = draw_seq(st, throttled)
            st = th_push(st, throttled, when, sq, TK_RELAY, r)
            st = dict(st)

            fwd = has_pkt & ok
            st[f"r{r}_fwd_pkts"] = st[f"r{r}_fwd_pkts"] + fwd
            st[f"r{r}_fwd_bytes"] = st[f"r{r}_fwd_bytes"] \
                + jnp.where(fwd, st["_psize"], jnp.int64(0))
            if r == 1:
                # device_push(dev=2): cross-host send into the outbox
                dslot = jnp.minimum(
                    jnp.searchsorted(st["_ips_sorted"], pk["dip"]),
                    H - 1)
                found = st["_ips_sorted"][dslot] == pk["dip"]
                dst = st["_ips_perm"][dslot]
                st["app_pkts_sent"] = jnp.where(
                    fwd, st["app_pkts_sent"] + 1, st["app_pkts_sent"])
                # NIC link down (device_push twin): the send dies at
                # the egress instant, BEFORE the event-seq draw — the
                # same position as the no-route drop.
                linkdn = fwd & ((st["h_fault"] & 2) != 0)
                st["app_pkts_dropped"] = jnp.where(
                    linkdn, st["app_pkts_dropped"] + 1,
                    st["app_pkts_dropped"])
                st["drop_causes"] = st["drop_causes"].at[
                    mrows(linkdn), TEL_LINK_DOWN].add(1, mode="drop")
                st = tr_append(st, linkdn, now, TR_DRP, pk,
                               RSN_LINKDOWN)
                st = dict(st)
                fwd = fwd & ~linkdn
                miss = fwd & ~found
                st["app_pkts_dropped"] = jnp.where(
                    miss, st["app_pkts_dropped"] + 1,
                    st["app_pkts_dropped"])
                st["drop_causes"] = st["drop_causes"].at[
                    mrows(miss), TEL_NO_ROUTE].add(1, mode="drop")
                st = tr_append(st, miss, now, TR_DRP, pk, RSN_NOROUTE)
                hit = fwd & found
                st, sq = draw_seq(st, hit)
                st = seq_append(
                    st, "out", O, hit,
                    {"out_src": hidx, "out_dst": dst, "out_seq": sq,
                     "out_pseq": pk["pseq"], "out_sip": pk["sip"],
                     "out_sport": pk["sport"], "out_dip": pk["dip"],
                     "out_dport": pk["dport"], "out_t": now}, "out_n",
                    AB_OUT)
            else:
                # iface_receive -> udp_push_in
                st["eth_precv"] = jnp.where(fwd, st["eth_precv"] + 1,
                                            st["eth_precv"])
                st["eth_brecv"] = jnp.where(
                    fwd, st["eth_brecv"] + st["_psize"], st["eth_brecv"])
                wrong = fwd & ((pk["dport"] != st["m_port"])
                               | (st["sock_closed"] == 1))
                st["app_pkts_dropped"] = jnp.where(
                    wrong, st["app_pkts_dropped"] + 1,
                    st["app_pkts_dropped"])
                st["drop_causes"] = st["drop_causes"].at[
                    mrows(wrong), TEL_NO_SOCKET].add(1, mode="drop")
                st = tr_append(st, wrong, now, TR_DRP, pk, RSN_NOSOCK)
                st = dict(st)
                deliver = fwd & ~wrong
                full = deliver & (st["recv_bytes"] + st["_psize"]
                                  > st["recv_max"])
                st["app_pkts_dropped"] = jnp.where(
                    full, st["app_pkts_dropped"] + 1,
                    st["app_pkts_dropped"])
                st["drop_causes"] = st["drop_causes"].at[
                    mrows(full), TEL_RECVBUF_FULL].add(1, mode="drop")
                st = tr_append(st, full, now, TR_DRP, pk, RSN_RCVBUF)
                st = dict(st)
                good = deliver & ~full
                st = mark_abort(st, (good & (st["rq_len"] - st["rq_pos"]
                                              >= R - 1)).any(), AB_STRUCT)
                st = dict(st)
                tail = st["rq_len"] % R
                rows = mrows(good)
                for kk in PK_KEYS:
                    st[f"rq_{kk}"] = st[f"rq_{kk}"].at[rows, tail].set(
                        pk[kk], mode="drop")
                st["rq_len"] = jnp.where(good, st["rq_len"] + 1,
                                         st["rq_len"])
                st["recv_bytes"] = jnp.where(
                    good, st["recv_bytes"] + st["_psize"],
                    st["recv_bytes"])
                st = set_status(st, jnp.uint32(S_READABLE),
                                jnp.uint32(0), good, now)
                st = dict(st)
                st["app_pkts_recv"] = jnp.where(
                    good, st["app_pkts_recv"] + 1, st["app_pkts_recv"])
                st = tr_append(st, good, now, TR_RCV, pk, RSN_NONE)
                st = dict(st)

            done = none | throttled
            st["cont"] = jnp.where(done, st["then"], st["cont"])
            st["then"] = jnp.where(done, C_IDLE, st["then"])
            return st

        # -------- micro-op: app steppers -------------------------

        def phold_send_phase(st, mask, is_seed):
            """One phold_send attempt; returns (st, sent, parked,
            notify_relay1)."""
            now = st["now"]
            state_k = "s_state" if is_seed else "m_state"
            tgt_k = "s_target" if is_seed else "m_target"
            fresh = mask & (st[state_k] != 3)
            st, rnd = lcg_next(st, fresh)
            npeers = jnp.maximum(st["n_peers"], 1).astype(jnp.uint32)
            pick = st["peers"][hidx, (rnd % npeers).astype(jnp.int32)]
            st = dict(st)
            st[tgt_k] = jnp.where(fresh, pick, st[tgt_k])
            st[state_k] = jnp.where(fresh, 3, st[state_k])
            st["app_sys"] = st["app_sys"].at[:, ASYS_SENDTO].add(
                jnp.where(mask, 1, 0))
            over = mask & (st["send_bytes"] + st["_psize"]
                           > st["send_max"])
            st = set_status(st, jnp.uint32(0), jnp.uint32(S_WRITABLE),
                            over, now)
            st = dict(st)
            wm_k = "s_waitmask" if is_seed else "m_waitmask"
            ws_k = "s_waitseq" if is_seed else "m_waitseq"
            st[wm_k] = jnp.where(over, jnp.uint32(S_WRITABLE),
                                 st[wm_k])
            st[ws_k] = jnp.where(over, st["park_ctr"], st[ws_k])
            st["park_ctr"] = jnp.where(over, st["park_ctr"] + 1,
                                       st["park_ctr"])
            sent = mask & ~over
            pseq = st["packet_seq"]
            st["packet_seq"] = jnp.where(sent, pseq + 1,
                                         st["packet_seq"])
            st = mark_abort(st, (sent & (st["sq_len"] - st["sq_pos"]
                                         >= S - 1)).any(), AB_STRUCT)
            st = dict(st)
            tail = st["sq_len"] % S
            rows = mrows(sent)
            vals = {"srchost": hidx, "pseq": pseq, "sip": st["eth_ip"],
                    "sport": st["m_port"], "dip": st[tgt_k],
                    "dport": st["m_port"]}
            for kk in PK_KEYS:
                st[f"sq_{kk}"] = st[f"sq_{kk}"].at[rows, tail].set(
                    vals[kk], mode="drop")
            st["sq_len"] = jnp.where(sent, st["sq_len"] + 1,
                                     st["sq_len"])
            st["send_bytes"] = jnp.where(
                sent, st["send_bytes"] + st["_psize"], st["send_bytes"])
            st[state_k] = jnp.where(sent, 0, st[state_k])
            newly = sent & (st["queued"] == 0)
            st["queued"] = jnp.where(newly, 1, st["queued"])
            notify = newly & (st["r1_pending"] == 0)
            return st, sent, over, notify

        def arm_sleep(st, mask, is_seed):
            now = st["now"]
            st = dict(st)
            st["app_sys"] = st["app_sys"].at[:, ASYS_NANOSLEEP].add(
                jnp.where(mask, 1, 0))
            st, r1 = lcg_next(st, mask)
            st, r2 = lcg_next(st, mask)
            u = ((r1 % jnp.uint32(1000)).astype(jnp.int64)
                 + (r2 % jnp.uint32(1000)).astype(jnp.int64) + 1)
            d = jnp.maximum(1, (u * st["m_mean"]) // 1000)
            state_k = "s_state" if is_seed else "m_state"
            wake_k = "s_wakep" if is_seed else "m_wakep"
            st = dict(st)
            st[state_k] = jnp.where(mask, 1, st[state_k])
            st[wake_k] = jnp.where(mask, 1, st[wake_k])
            st, sq = draw_seq(st, mask)
            return th_push(st, mask, now + d, sq, TK_APP_TIMEOUT,
                           1 if is_seed else 0)

        def mesh_try_exit(st, mask):
            """mesh_try_exit twin: when both thread parts are done,
            the process exits — fd closes WITHOUT a counted syscall
            (fds.close_all), recv queue dies with it, send queue keeps
            draining."""
            now = st["now"]
            both = mask & (st["m_partdone"] == 1) \
                & (st["s_partdone"] == 1) & (st["sock_closed"] == 0)
            st = dict(st)
            st["sock_closed"] = jnp.where(both, 1, st["sock_closed"])
            # udp_close's adjust_status: set CLOSED, clear
            # ACTIVE|READABLE|WRITABLE (no wakes: both parts done)
            st = set_status(st, jnp.uint32(1 << 3),
                            jnp.uint32((1 << 0) | S_READABLE
                                       | S_WRITABLE), both, now)
            st = dict(st)
            st["rq_pos"] = jnp.where(both, st["rq_len"], st["rq_pos"])
            st["recv_bytes"] = jnp.where(both, 0, st["recv_bytes"])
            st["m_exited"] = jnp.where(both, 1, st["m_exited"])
            st["m_exit_time"] = jnp.where(both, now,
                                          st["m_exit_time"])
            return st

        def op_step_mesh(st, mask, is_seed):
            """udp-mesh micro-ops (app_step_mesh / app_step_mesh_snd
            twins): the sender streams one datagram per micro-op
            (engine: one udp_sendto per loop pass, each notifying the
            relay synchronously); the main sinks one datagram per
            micro-op."""
            now = st["now"]
            st = dict(st)
            if is_seed:
                first = mask & (st["s_state"] == 0)
                st["app_sys"] = st["app_sys"].at[:, 7].add(
                    jnp.where(first, st["n_peers"], 0))  # ASYS_RESOLVE
                st["s_state"] = jnp.where(first, 1, st["s_state"])
                sending = mask & (st["s_senti"] < st["s_count"])
                st["app_sys"] = st["app_sys"].at[:, ASYS_SENDTO].add(
                    jnp.where(sending, 1, 0))
                over = sending & (st["send_bytes"] + st["_psize"]
                                  > st["send_max"])
                st = set_status(st, jnp.uint32(0),
                                jnp.uint32(S_WRITABLE), over, now)
                st = dict(st)
                st["s_waitmask"] = jnp.where(over,
                                             jnp.uint32(S_WRITABLE),
                                             st["s_waitmask"])
                st["s_waitseq"] = jnp.where(over, st["park_ctr"],
                                            st["s_waitseq"])
                st["park_ctr"] = jnp.where(over, st["park_ctr"] + 1,
                                           st["park_ctr"])
                st["cont"] = jnp.where(over, C_IDLE, st["cont"])
                sent = sending & ~over
                pseq = st["packet_seq"]
                st["packet_seq"] = jnp.where(sent, pseq + 1,
                                             st["packet_seq"])
                st = mark_abort(st, (sent & (st["sq_len"] - st["sq_pos"]
                                             >= S - 1)).any(), AB_STRUCT)
                st = dict(st)
                npeers = jnp.maximum(st["n_peers"], 1)
                pick = st["peers"][
                    hidx, (st["s_senti"]
                           % npeers.astype(jnp.int64)).astype(jnp.int32)]
                tail = st["sq_len"] % S
                rows = mrows(sent)
                vals = {"srchost": hidx, "pseq": pseq,
                        "sip": st["eth_ip"], "sport": st["m_port"],
                        "dip": pick, "dport": st["m_port"]}
                for kk in PK_KEYS:
                    st[f"sq_{kk}"] = st[f"sq_{kk}"].at[rows, tail].set(
                        vals[kk], mode="drop")
                st["sq_len"] = jnp.where(sent, st["sq_len"] + 1,
                                         st["sq_len"])
                st["send_bytes"] = jnp.where(
                    sent, st["send_bytes"] + st["_psize"],
                    st["send_bytes"])
                st["s_senti"] = jnp.where(sent, st["s_senti"] + 1,
                                          st["s_senti"])
                newly = sent & (st["queued"] == 0)
                st["queued"] = jnp.where(newly, 1, st["queued"])
                notify = newly & (st["r1_pending"] == 0)
                # keep sending (possibly via a relay drain first)
                st["cont"] = jnp.where(notify, C_R1,
                                       jnp.where(sent, C_S_STEP,
                                                 st["cont"]))
                st["then"] = jnp.where(notify, C_S_STEP, st["then"])
                done = mask & ~sending
                st["app_sys"] = st["app_sys"].at[:, 6].add(
                    jnp.where(done, 1, 0))  # ASYS_WRITE ("mesh sent")
                st["out_first"] = jnp.where(
                    done & (st["out_first"] == 0), 2, st["out_first"])
                st["s_partdone"] = jnp.where(done, 1,
                                             st["s_partdone"])
                st["s_exited"] = jnp.where(done, 1, st["s_exited"])
                st["s_exit_time"] = jnp.where(done, now,
                                              st["s_exit_time"])
                st["s_waitmask"] = jnp.where(done, jnp.uint32(0),
                                             st["s_waitmask"])
                st["cont"] = jnp.where(done, C_IDLE, st["cont"])
                st = mesh_try_exit(st, done)
            else:
                expect = st["s_count"] * st["_pay"]
                st["app_sys"] = st["app_sys"].at[:, ASYS_RECVFROM].add(
                    jnp.where(mask, 1, 0))
                empty = mask & (st["rq_len"] <= st["rq_pos"])
                st["m_waitmask"] = jnp.where(empty,
                                             jnp.uint32(S_READABLE),
                                             st["m_waitmask"])
                st["m_waitseq"] = jnp.where(empty, st["park_ctr"],
                                            st["m_waitseq"])
                st["park_ctr"] = jnp.where(empty, st["park_ctr"] + 1,
                                           st["park_ctr"])
                st["cont"] = jnp.where(empty, C_IDLE, st["cont"])
                got = mask & ~empty
                st["rq_pos"] = jnp.where(got, st["rq_pos"] + 1,
                                         st["rq_pos"])
                st["recv_bytes"] = jnp.where(
                    got, st["recv_bytes"] - st["_psize"],
                    st["recv_bytes"])
                now_empty = got & (st["rq_len"] <= st["rq_pos"])
                st = set_status(st, jnp.uint32(0),
                                jnp.uint32(S_READABLE), now_empty, now)
                st = dict(st)
                st["m_gotn"] = jnp.where(got,
                                         st["m_gotn"] + st["_pay"],
                                         st["m_gotn"])
                more = got & (st["m_gotn"] < expect)
                st["cont"] = jnp.where(more, C_M_STEP, st["cont"])
                fin = got & ~more
                st["app_sys"] = st["app_sys"].at[:, 6].add(
                    jnp.where(fin, 1, 0))  # ASYS_WRITE ("mesh received")
                st["out_first"] = jnp.where(
                    fin & (st["out_first"] == 0), 1, st["out_first"])
                st["m_partdone"] = jnp.where(fin, 1, st["m_partdone"])
                st["m_waitmask"] = jnp.where(fin, jnp.uint32(0),
                                             st["m_waitmask"])
                st["cont"] = jnp.where(fin, C_IDLE, st["cont"])
                st = mesh_try_exit(st, fin)
            return st

        def op_step(st, mask, is_seed):
            """C_M_STEP / C_S_STEP micro-op."""
            if family == 1:
                return op_step_mesh(st, mask, is_seed)
            state_k = "s_state" if is_seed else "m_state"
            st = dict(st)
            restart = mask & (st[state_k] == 1)
            st["app_sys"] = st["app_sys"].at[:, ASYS_NANOSLEEP].add(
                jnp.where(restart, 1, 0))
            st[state_k] = jnp.where(restart, 2, st[state_k])
            has_send = mask & ((st[state_k] == 2)
                               | (st[state_k] == 3))
            st, sent, parked, notify = phold_send_phase(st, has_send,
                                                        is_seed)
            st = dict(st)
            if is_seed:
                st["s_senti"] = jnp.where(sent, st["s_senti"] + 1,
                                          st["s_senti"])
            nxt = C_S_POST if is_seed else C_M_RECV
            to_next = (mask & ~has_send) | sent
            go_drain = notify & sent
            st["cont"] = jnp.where(
                go_drain, C_R1, jnp.where(to_next, nxt,
                                          jnp.where(parked, C_IDLE,
                                                    st["cont"])))
            st["then"] = jnp.where(go_drain, nxt, st["then"])
            return st

        def op_stage2(st, mask):
            """C_M_RECV / C_S_POST micro-op (phold only; mesh
            steppers never use these continuations)."""
            if family == 1:
                return st
            now = st["now"]
            m_recv = mask & (st["cont"] == C_M_RECV)
            s_post = mask & (st["cont"] == C_S_POST)
            st = dict(st)
            st["app_sys"] = st["app_sys"].at[:, ASYS_RECVFROM].add(
                jnp.where(m_recv, 1, 0))
            empty = m_recv & (st["rq_len"] <= st["rq_pos"])
            st["m_waitmask"] = jnp.where(empty, jnp.uint32(S_READABLE),
                                         st["m_waitmask"])
            st["m_waitseq"] = jnp.where(empty, st["park_ctr"],
                                        st["m_waitseq"])
            st["park_ctr"] = jnp.where(empty, st["park_ctr"] + 1,
                                       st["park_ctr"])
            st["cont"] = jnp.where(empty, C_IDLE, st["cont"])
            got = m_recv & ~empty
            st["rq_pos"] = jnp.where(got, st["rq_pos"] + 1,
                                     st["rq_pos"])
            st["recv_bytes"] = jnp.where(
                got, st["recv_bytes"] - st["_psize"], st["recv_bytes"])
            now_empty = got & (st["rq_len"] <= st["rq_pos"])
            st = set_status(st, jnp.uint32(0), jnp.uint32(S_READABLE),
                            now_empty, now)
            st = dict(st)
            st["m_gotn"] = jnp.where(got, st["m_gotn"] + 1,
                                     st["m_gotn"])
            st = arm_sleep(st, got, False)
            st = dict(st)
            st["cont"] = jnp.where(got, C_IDLE, st["cont"])

            done = s_post & (st["s_senti"] >= st["s_count"])
            st["s_exited"] = jnp.where(done, 1, st["s_exited"])
            st["s_exit_time"] = jnp.where(done, now,
                                          st["s_exit_time"])
            st["s_waitmask"] = jnp.where(done, jnp.uint32(0),
                                         st["s_waitmask"])
            st["cont"] = jnp.where(done, C_IDLE, st["cont"])
            more = s_post & ~done
            st = arm_sleep(st, more, True)
            st = dict(st)
            st["cont"] = jnp.where(more, C_IDLE, st["cont"])
            return st

        # -------- micro-op: event pop ----------------------------

        def next_event_time(st):
            pos = st["ib_pos"]
            safe = jnp.minimum(pos, I - 1)
            ib_t = jnp.where(st["ib_len"] > pos,
                             st["ib_time"][hidx, safe], I64_MAX)
            th_t = jnp.where(st["th_valid"], st["th_time"],
                             I64_MAX).min(axis=1)
            return ib_t, th_t

        def op_pop_event(st, mask, window_end):
            pos = st["ib_pos"]
            safe = jnp.minimum(pos, I - 1)
            ib_t, _ = next_event_time(st)
            tmin, tkind, ttgt, tslot = th_min(st)
            pick_ib = jnp.where(ib_t != tmin, ib_t < tmin,
                                ib_t < I64_MAX)
            et = jnp.minimum(ib_t, tmin)
            due = mask & (et < window_end)
            st = dict(st)
            st["now"] = jnp.where(due, et, st["now"])
            st["events_run"] = jnp.where(due, st["events_run"] + 1,
                                         st["events_run"])

            # Down-host fault mask (docs/ROBUSTNESS.md; run_until
            # twin): arrivals at a dead/link-down/blackholed host die
            # at their recorded (path-independent) arrival instant —
            # never touching the CoDel ledger; a dead host's timers
            # discard silently.  The mask is constant within a span.
            h_down = (st["h_fault"] & 1) != 0
            nic_dead = st["h_fault"] != 0

            # arrival: inbox -> codel -> relay 2.  At the engine's
            # hard limit CoDelN::push refuses and the arrival drops
            # with an rtr-limit breadcrumb (run_until twin).
            arr = due & pick_ib
            st["ib_pos"] = jnp.where(arr, pos + 1, pos)
            pk_arr = {kk: st[f"ib_{kk}"][hidx, safe] for kk in PK_KEYS}
            arr_f = arr & nic_dead
            st["app_pkts_dropped"] = jnp.where(
                arr_f, st["app_pkts_dropped"] + 1,
                st["app_pkts_dropped"])
            st["drop_causes"] = st["drop_causes"].at[
                mrows(arr_f & h_down), TEL_HOST_DOWN].add(
                1, mode="drop")
            st["drop_causes"] = st["drop_causes"].at[
                mrows(arr_f & ~h_down), TEL_LINK_DOWN].add(
                1, mode="drop")
            st = tr_append(st, arr_f & h_down, et, TR_DRP, pk_arr,
                           RSN_HOSTDOWN)
            st = tr_append(st, arr_f & ~h_down, et, TR_DRP, pk_arr,
                           RSN_LINKDOWN)
            st = dict(st)
            arr = arr & ~nic_dead
            st["codel_enq_pkts"] = jnp.where(
                arr, st["codel_enq_pkts"] + 1, st["codel_enq_pkts"])
            st["codel_enq_bytes"] = jnp.where(
                arr, st["codel_enq_bytes"] + st["_psize"],
                st["codel_enq_bytes"])
            limit_full = arr & (st["cq_len"] - st["cq_pos"]
                                >= CODEL_HARD_LIMIT)
            # DCTCP-K marking law (net/codel.py push twin): fires only
            # for ECT(0) arrivals.  This family's packets are UDP —
            # never ECN-capable — so the law is provably inert here;
            # the codel_marked counter still rides the codec so the
            # fabric channel's qmarks series samples the live value.
            st["codel_dropped"] = jnp.where(
                limit_full, st["codel_dropped"] + 1,
                st["codel_dropped"])
            st["codel_drop_bytes"] = jnp.where(
                limit_full, st["codel_drop_bytes"] + st["_psize"],
                st["codel_drop_bytes"])
            st["app_pkts_dropped"] = jnp.where(
                limit_full, st["app_pkts_dropped"] + 1,
                st["app_pkts_dropped"])
            st["drop_causes"] = st["drop_causes"].at[
                mrows(limit_full), TEL_RTR_LIMIT].add(1, mode="drop")
            st = tr_append(st, limit_full, et, TR_DRP, pk_arr, 2)
            st = dict(st)
            arr = arr & ~limit_full
            st = mark_abort(st, (arr & (st["cq_len"] - st["cq_pos"]
                                        >= C - 1)).any(), AB_STRUCT)
            st = dict(st)
            tail = st["cq_len"] % C
            rows = mrows(arr)
            for kk in PK_KEYS:
                st[f"cq_{kk}"] = st[f"cq_{kk}"].at[rows, tail].set(
                    st[f"ib_{kk}"][hidx, safe], mode="drop")
            st["cq_enq"] = st["cq_enq"].at[rows, tail].set(
                et, mode="drop")
            st["cq_len"] = jnp.where(arr, st["cq_len"] + 1,
                                     st["cq_len"])
            st["codel_peak"] = jnp.maximum(
                st["codel_peak"],
                jnp.where(arr,
                          (st["cq_len"] - st["cq_pos"]).astype(
                              jnp.int64),
                          jnp.int64(0)))
            st["codel_bytes"] = jnp.where(
                arr, st["codel_bytes"] + st["_psize"], st["codel_bytes"])
            go2 = arr & (st["r2_pending"] == 0)
            st["cont"] = jnp.where(go2, C_R2, st["cont"])
            st["then"] = jnp.where(go2, C_IDLE, st["then"])

            # timer
            tim = due & ~pick_ib
            st["th_valid"] = st["th_valid"].at[mrows(tim), tslot].set(
                False, mode="drop")
            # A dead host's timers discard silently (run_until's down
            # branch: tpop only — no seq draw, no relay/app effects).
            tim = tim & ~h_down
            is_relay = tim & (tkind == TK_RELAY)
            for r in (1, 2):
                rw = is_relay & (ttgt == r)
                # relay._wakeup: state -> idle; the parked packet stays
                st[f"r{r}_pending"] = jnp.where(rw, 0,
                                                st[f"r{r}_pending"])
                st["cont"] = jnp.where(rw, C_R1 if r == 1 else C_R2,
                                       st["cont"])
                st["then"] = jnp.where(rw, C_IDLE, st["then"])

            is_to = tim & (tkind == TK_APP_TIMEOUT)
            st, sq = draw_seq(st, is_to)
            st = th_push(st, is_to & (ttgt == 0), et, sq, TK_APP, 0)
            st = th_push(st, is_to & (ttgt == 1), et, sq, TK_APP, 1)
            st = dict(st)

            is_app = tim & (tkind == TK_APP)
            m_app = is_app & (ttgt == 0)
            s_app = is_app & (ttgt == 1)
            st["m_wakep"] = jnp.where(m_app, 0, st["m_wakep"])
            st["s_wakep"] = jnp.where(s_app, 0, st["s_wakep"])
            st["m_waitmask"] = jnp.where(m_app, jnp.uint32(0),
                                         st["m_waitmask"])
            st["s_waitmask"] = jnp.where(s_app, jnp.uint32(0),
                                         st["s_waitmask"])
            s_live = s_app & (st["s_exited"] == 0)
            m_live = m_app & (st["m_exited"] == 0)
            st["cont"] = jnp.where(m_live, C_M_STEP,
                                   jnp.where(s_live, C_S_STEP,
                                             st["cont"]))
            return st

        # -------- per-iteration dispatcher -----------------------

        def micro_iter(carry):
            st, window_end, iters = carry
            if fused:
                # Fused dispatch: ops consume the LIVE continuation in
                # dataflow order, so a host flows through its whole
                # event chain (pop -> app step -> relay drain ->
                # recv/arm) inside ONE while-iteration instead of one
                # micro-op per iteration.  Per-host op order is
                # untouched — each op still advances exactly one
                # micro-op for the lanes it masks, sequentially — and
                # hosts are independent within a round (netplane.cpp
                # run_hosts_mt), so the schedule compression cannot
                # change any per-host state; the outbox/trace
                # interleave changes, which downstream canonical sorts
                # (inbox lexsort, Host.trace_lines) erase.  Gated by
                # the fused-vs-unfused differential in
                # tests/test_phold_span.py.
                # Each stage is guarded by an any-lane-active cond:
                # XLA skips the whole vectorized stage body at runtime
                # when no host sits in that continuation (the common
                # case — chains concentrate activity in 2-3 stages per
                # iteration).
                def guard(st, mask, fn, code=None):
                    st = ks_count(st, code, mask) \
                        if code is not None else st
                    return jax.lax.cond(mask.any(), fn,
                                        lambda s, _m: s, st, mask)

                st = ks_count_pop(st, st["cont"] == C_IDLE,
                                  window_end)
                st = op_pop_event(st, st["cont"] == C_IDLE, window_end)
                st = guard(st, st["cont"] == C_M_STEP,
                           lambda s, m: op_step(s, m, False), KS_STEP)
                st = guard(st, st["cont"] == C_S_STEP,
                           lambda s, m: op_step(s, m, True), KS_STEP)
                # Two relay passes per iteration: the second pass lets
                # a drain that just emptied its source take the
                # exhausted-exit in the same iteration (streaming
                # senders then sustain one datagram per iteration).
                for _ in range(2):
                    st = guard(st, st["cont"] == C_R1,
                               lambda s, m: op_relay(s, 1, m),
                               KS_INET_OUT)
                    st = guard(st, st["cont"] == C_R2,
                               lambda s, m: op_relay(s, 2, m),
                               KS_CODEL)
                st = guard(st, (st["cont"] == C_M_RECV)
                           | (st["cont"] == C_S_POST), op_stage2,
                           KS_ARM)
            else:
                # Reference (unfused) schedule: snapshot — each host
                # advances ONE micro-op per iteration (a host another
                # op just moved waits for the next one) — matching the
                # engine's one-op-at-a-time per host order.  Kept as
                # the differential comparator for the fused path.
                cont0 = st["cont"]
                st = ks_count(st, KS_INET_OUT, cont0 == C_R1)
                st = ks_count(st, KS_CODEL, cont0 == C_R2)
                st = ks_count(st, KS_STEP, (cont0 == C_M_STEP)
                              | (cont0 == C_S_STEP))
                st = ks_count(st, KS_ARM, (cont0 == C_M_RECV)
                              | (cont0 == C_S_POST))
                st = op_relay(st, 1, cont0 == C_R1)
                st = op_relay(st, 2, cont0 == C_R2)
                st = op_step(st, cont0 == C_M_STEP, False)
                st = op_step(st, cont0 == C_S_STEP, True)
                st = op_stage2(st, (cont0 == C_M_RECV)
                               | (cont0 == C_S_POST))
                # Counted against the state op_pop_event will actually
                # read (earlier ops may have armed timers).
                st = ks_count_pop(st, cont0 == C_IDLE, window_end)
                st = op_pop_event(st, cont0 == C_IDLE, window_end)
            st = mark_abort(st, iters > (np.int64(1) << 22), AB_STRUCT)
            return st, window_end, iters + 1

        def micro_cond(carry):
            st, window_end, iters = carry
            ib_t, th_t = next_event_time(st)
            due = jnp.minimum(ib_t, th_t) < window_end
            busy = st["cont"] != C_IDLE
            return (busy | due).any() & (st["abort_code"] == 0)

        # -------- round end: propagation + inbox merge -----------

        def propagate(st, window_end):
            n = st["out_n"]
            valid = jnp.arange(O) < n
            src = st["out_src"]
            dst = st["out_dst"]
            node = st["_node"]
            latency = st["_lat"][node[src], node[dst]]
            reachable = latency < TIME_NEVER
            bits, _ = threefry2x32_jax(
                st["_k0"], st["_k1"], src.astype(jnp.uint32),
                (st["out_pseq"] & 0xFFFFFFFF).astype(jnp.uint32))
            thr_v = st["_thr"][node[src], node[dst]]
            lossy = ((bits.astype(jnp.int64) < thr_v)
                     & (st["out_t"] >= st["_bootstrap"]))
            deliver = jnp.maximum(st["out_t"] + latency, window_end)
            keep = valid & reachable & ~lossy
            min_lat = jnp.min(jnp.where(keep, latency, I64_MAX))
            st = dict(st)
            for miss, rsn, tel in (
                    (valid & ~reachable, RSN_UNREACH, TEL_UNREACHABLE),
                    (valid & reachable & lossy, RSN_LOSS,
                     TEL_LOSS_EDGE)):
                st["app_pkts_dropped"] = st["app_pkts_dropped"].at[
                    jnp.where(miss, src, OOB)].add(1, mode="drop")
                st["drop_causes"] = st["drop_causes"].at[
                    jnp.where(miss, src, OOB), tel].add(1, mode="drop")
                if tracing:
                    nt_ = st["tr_n"]
                    rank = jnp.cumsum(miss) - 1
                    slot = jnp.where(miss, nt_ + rank, TR + 8)
                    for key, v in (
                            ("tr_t", st["out_t"]),
                            ("tr_kind", jnp.full(O, TR_DRP, jnp.int32)),
                            ("tr_srchost", src),
                            ("tr_pseq", st["out_pseq"]),
                            ("tr_sip", st["out_sip"]),
                            ("tr_sport", st["out_sport"]),
                            ("tr_dip", st["out_dip"]),
                            ("tr_dport", st["out_dport"]),
                            ("tr_reason",
                             jnp.full(O, rsn, jnp.int32)),
                            ("tr_owner", src)):
                        st[key] = st[key].at[slot].set(v, mode="drop")
                    tot = nt_ + miss.sum()
                    st["tr_n"] = tot
                    st = mark_abort(st, tot > TR - O, AB_TRACE)
                    st = dict(st)

            # scatter kept packets into destination inboxes: compact
            # the un-consumed remainder, append arrivals per dst, then
            # re-sort each row by (time, src, seq) — the inbox heap's
            # total order.
            rem = (st["ib_len"] - st["ib_pos"]).astype(jnp.int32)
            shift = jnp.minimum(
                st["ib_pos"][:, None] + jnp.arange(I)[None, :], I - 1)
            live = jnp.arange(I)[None, :] < rem[:, None]

            def compact(a, fill):
                return jnp.where(live,
                                 jnp.take_along_axis(a, shift, axis=1),
                                 fill)

            ib_time = compact(st["ib_time"], I64_MAX)
            ib_src = compact(st["ib_src"], 0)
            ib_seq = compact(st["ib_seq"], I64_MAX)
            ib_pk = {kk: compact(st[f"ib_{kk}"], 0) for kk in PK_KEYS}
            new = {"srchost": src, "pseq": st["out_pseq"],
                   "sip": st["out_sip"], "sport": st["out_sport"],
                   "dip": st["out_dip"], "dport": st["out_dport"]}
            d_dst, d_time, d_src, d_seq = dst, deliver, src, \
                st["out_seq"]
            d_pk, d_keep, DN = new, keep, O
            if n_shards > 1:
                # On-device cross-shard exchange (ISSUE 11): kept
                # packets hop to their destination shard through the
                # capacity-bounded staging law in span_mesh.py before
                # the shard-local inbox scatter below.  Overflow is
                # an AB_EXCH abort, and the delivered multiset is
                # unchanged on a clean run, so the post-scatter inbox
                # lexsort (time, src, seq — a strict total order)
                # makes the hop invisible to the packet trace.
                stage, SE = exchange
                hs = H // n_shards
                cols = {"dst": (dst, H), "time": (deliver, I64_MAX),
                        "src": (src, 0), "seq": (st["out_seq"],
                                                 I64_MAX)}
                cols.update({kk: (new[kk], 0) for kk in PK_KEYS})
                ex, over = stage(keep, dst // hs, cols)
                # Observatory: the exchange is a per-ROUND stage —
                # lanes are packets staged through the cross-shard
                # hop, fires bounded by rounds (not trips).
                st = ks_count(st, KS_EXCHANGE, keep)
                st = mark_abort(st, over.any(), AB_EXCH)
                st = dict(st)
                d_dst, d_time = ex["dst"], ex["time"]
                d_src, d_seq = ex["src"], ex["seq"]
                d_pk = {kk: ex[kk] for kk in PK_KEYS}
                d_keep, DN = ex["dst"] < H, SE
            # stable per-destination rank in delivery order
            seg = jnp.where(d_keep, d_dst, H)
            order = jnp.argsort(seg.astype(jnp.int64) * (DN + 1)
                                + jnp.arange(DN))
            sseg = seg[order]
            rank0 = jnp.arange(DN) - jnp.searchsorted(sseg, sseg,
                                                      side="left")
            rank = jnp.zeros(DN, jnp.int32).at[order].set(
                rank0.astype(jnp.int32))
            slot = rem[jnp.minimum(seg, H - 1)] + rank
            ok_slot = d_keep & (slot < I - 1)
            st = mark_abort(st, (d_keep & (slot >= I - 1)).any(),
                            AB_STRUCT)
            st = dict(st)
            rows = jnp.where(ok_slot, d_dst, OOB)
            ib_time = ib_time.at[rows, slot].set(d_time, mode="drop")
            ib_src = ib_src.at[rows, slot].set(d_src, mode="drop")
            ib_seq = ib_seq.at[rows, slot].set(d_seq, mode="drop")
            for kk in PK_KEYS:
                ib_pk[kk] = ib_pk[kk].at[rows, slot].set(d_pk[kk],
                                                         mode="drop")
            add = jnp.zeros(H, jnp.int32).at[rows].add(1, mode="drop")
            sort_idx = jnp.lexsort((ib_seq, ib_src, ib_time), axis=1)
            take = jnp.take_along_axis
            st["ib_time"] = take(ib_time, sort_idx, axis=1)
            st["ib_src"] = take(ib_src, sort_idx, axis=1)
            st["ib_seq"] = take(ib_seq, sort_idx, axis=1)
            for kk in PK_KEYS:
                st[f"ib_{kk}"] = take(ib_pk[kk], sort_idx, axis=1)
            st["ib_pos"] = jnp.zeros(H, jnp.int32)
            st["ib_len"] = rem + add
            st["out_n"] = jnp.int64(0)
            return st, n, min_lat

        # -------- the multi-round while loop ---------------------

        def round_cond(carry):
            (st, start, runahead, rounds, busy_rounds, packets,
             busy_end, stop, limit, max_rounds, iters) = carry
            return ((rounds < max_rounds) & (start < limit)
                    & (start < stop) & (st["abort_code"] == 0))

        def round_body(carry):
            (st, start, runahead, rounds, busy_rounds, packets,
             busy_end, stop, limit, max_rounds, iters) = carry
            window_end = jnp.minimum(start + runahead, stop)
            st, _we, it = jax.lax.while_loop(
                micro_cond, micro_iter,
                (st, window_end, jnp.int64(0)))
            st, n_out, min_lat = propagate(st, window_end)
            if fabric:
                # Fabric observatory at the round boundary: same
                # grid-crossing rule as the engine's fab_sample_round
                # and the object path (trace/fabricstat.py).
                do = (start // np.int64(fab_iv)
                      != window_end // np.int64(fab_iv))
                row = jnp.where(do, st["fab_n"],
                                jnp.int32(FABR + 8))
                depth = (st["cq_len"] - st["cq_pos"]).astype(
                    jnp.int64)
                flags = (jnp.where(depth > 0, FB_ACT_CODEL, 0)
                         | jnp.where(st["r1_pending"] == 1,
                                     FB_ACT_TB_OUT, 0)
                         | jnp.where(st["r2_pending"] == 1,
                                     FB_ACT_TB_IN, 0)
                         | jnp.where(st["eth_psent"]
                                     + st["eth_precv"] > 0,
                                     FB_ACT_LINK, 0))
                head = st["cq_enq"][hidx, st["cq_pos"] % C]
                sojourn = jnp.where(depth > 0, window_end - head,
                                    jnp.int64(0))

                def bucket_peek(r):
                    nr = st[f"r{r}_next"]
                    bal = st[f"r{r}_bal"]
                    k = 1 + (window_end - nr) // np.int64(REFILL_NS)
                    adv = jnp.minimum(st[f"r{r}_cap"],
                                      bal + k * st[f"r{r}_refill"])
                    return jnp.where((nr == 0) | (window_end < nr),
                                     bal, adv)

                st = dict(st)
                st["fab_t"] = st["fab_t"].at[row].set(
                    window_end, mode="drop")
                st["fab_flags"] = st["fab_flags"].at[row].set(
                    flags.astype(jnp.int32), mode="drop")
                for name, val in (
                        ("qdepth", depth),
                        ("qbytes", st["codel_bytes"]),
                        ("sojourn", sojourn),
                        ("qenq", st["codel_enq_pkts"]),
                        ("qdrops", st["codel_dropped"]),
                        ("qmarks", st["codel_marked"]),
                        ("r1_bal", bucket_peek(1)),
                        ("r1_stalls", st["r1_stalls"]),
                        ("r2_bal", bucket_peek(2)),
                        ("r2_stalls", st["r2_stalls"]),
                        ("psent", st["eth_psent"]),
                        ("bsent", st["eth_bsent"]),
                        ("precv", st["eth_precv"]),
                        ("brecv", st["eth_brecv"])):
                    st[f"fab_{name}"] = st[f"fab_{name}"].at[
                        row].set(val.astype(jnp.int64), mode="drop")
                st["fab_n"] = st["fab_n"] + do.astype(jnp.int32)
            runahead = jnp.where(
                (min_lat > 0) & (min_lat < runahead), min_lat,
                runahead)
            ib_t, th_t = next_event_time(st)
            start = jnp.minimum(ib_t, th_t).min()
            return (st, start, runahead, rounds + 1,
                    busy_rounds + (n_out > 0).astype(jnp.int64),
                    packets + n_out, window_end, stop, limit,
                    max_rounds, iters + it)

        # Donation (donate_argnums=0: in-place reuse of the resident
        # carry) is gated by experimental.tpu_donate_buffers behind
        # span_mesh.donation_cache_safe(): a donated executable
        # round-tripped through the persistent XLA compilation cache
        # (JAX_COMPILATION_CACHE_DIR, which bench.py relies on to
        # amortize this kernel's multi-second compile) corrupts the
        # glibc heap on deserialization-hit runs — reproduced on the
        # CPU backend with MALLOC_CHECK_ (BASELINE.md round 6) — so
        # the guard refuses exactly that combination.
        def run(st, lat, thr, node, ips_sorted, ips_perm, k0, k1,
                bootstrap_end, pay, start, stop, limit, runahead,
                max_rounds):
            st = dict(st)
            st["_pay"] = jnp.int64(pay)
            st["_psize"] = jnp.int64(pay) + 28
            st["_lat"] = lat
            st["_thr"] = thr
            st["_node"] = node
            st["_ips_sorted"] = ips_sorted
            st["_ips_perm"] = ips_perm
            st["_k0"] = k0
            st["_k1"] = k1
            st["_bootstrap"] = bootstrap_end
            st["abort_code"] = jnp.int32(0)
            st["out_n"] = jnp.int64(0)
            for k, dt in (("out_src", jnp.int32), ("out_dst", jnp.int32),
                          ("out_seq", jnp.int64),
                          ("out_pseq", jnp.int64),
                          ("out_sip", jnp.uint32),
                          ("out_sport", jnp.int32),
                          ("out_dip", jnp.uint32),
                          ("out_dport", jnp.int32),
                          ("out_t", jnp.int64)):
                st[k] = jnp.zeros(O, dt)
            if tracing:
                st["tr_n"] = jnp.int64(0)
                for k, dt in (("tr_t", jnp.int64),
                              ("tr_kind", jnp.int32),
                              ("tr_srchost", jnp.int32),
                              ("tr_pseq", jnp.int64),
                              ("tr_sip", jnp.uint32),
                              ("tr_sport", jnp.int32),
                              ("tr_dip", jnp.uint32),
                              ("tr_dport", jnp.int32),
                              ("tr_reason", jnp.int32),
                              ("tr_owner", jnp.int32)):
                    st[k] = jnp.zeros(TR, dt)
            if fabric:
                st["fab_n"] = jnp.int32(0)
                st["fab_t"] = jnp.zeros(FABR, jnp.int64)
                st["fab_flags"] = jnp.zeros((FABR, H), jnp.int32)
                for name in ("qdepth", "qbytes", "sojourn", "qenq",
                             "qdrops", "qmarks", "r1_bal", "r1_stalls",
                             "r2_bal", "r2_stalls", "psent", "bsent",
                             "precv", "brecv"):
                    st[f"fab_{name}"] = jnp.zeros((FABR, H),
                                                  jnp.int64)
            if kern:
                # Span-local stage counters (KS_REC fires/lanes) —
                # output only, never engine state.
                st["ks_fires"] = jnp.zeros(KS_N, jnp.int64)
                st["ks_lanes"] = jnp.zeros(KS_N, jnp.int64)

            carry = (st, jnp.int64(start), jnp.int64(runahead),
                     jnp.int64(0), jnp.int64(0), jnp.int64(0),
                     jnp.int64(start), jnp.int64(stop),
                     jnp.int64(limit), jnp.int64(max_rounds),
                     jnp.int64(0))
            (st, start, runahead, rounds, busy_rounds, packets,
             busy_end, _s, _l, _m, iters) = jax.lax.while_loop(
                round_cond, round_body, carry)
            # Only mutated columns go back over the device link: the
            # routing tables, peer lists, and static socket/app config
            # are inputs the host already has, and the span-local
            # outbox was fully consumed by propagate.  The derived
            # chain registers re-derive on every input (out_first
            # stays: the import codec reads it).
            drop = (RESIDENT_STATIC
                    | (RESIDENT_DERIVED - {"out_first"})
                    | {"out_n", "out_src", "out_dst", "out_seq",
                       "out_pseq", "out_sip", "out_sport", "out_dip",
                       "out_dport", "out_t"})
            st = {k: v for k, v in st.items()
                  if not k.startswith("_") and k not in drop}
            return (st, start, runahead, rounds, busy_rounds, packets,
                    busy_end, iters)

        return self._span_jit(jax, run)

    # ------------------------------------------------------------------
    # Driver
    # ------------------------------------------------------------------

    def _export_state(self):
        """Fresh engine export -> state dict, or the int/None
        eligibility verdict passed through from span_export_phold."""
        w = self.wall
        t0 = w.now() if w is not None else 0
        d = self.engine.span_export_phold(
            self.CAP_I, self.CAP_T, self.CAP_R, self.CAP_S,
            self.CAP_C, self.CAP_P)
        if w is not None:
            t1 = w.now()
            w.add("export", t1 - t0, t0)
        if d is None or isinstance(d, int):
            return d
        # Codec byte volume, engine -> host (dispatch attribution).
        self.export_bytes += sum(
            len(v) for v in d.values()
            if isinstance(v, (bytes, bytearray, memoryview)))
        st = self._to_arrays(d)  # also sets self.family/_pay
        # Cache the static config as committed device arrays: the
        # host->device transfer of the largest columns (peers is
        # H x P) is paid once per export, and every later dispatch —
        # fresh or resident — reuses the device copies (device_put
        # on an already-placed array is a no-op).
        import jax
        self._static_cols = {
            k: self._put_static(jax, st[k]) for k in RESIDENT_STATIC}
        st.update(self._static_cols)
        if w is not None:
            t2 = w.now()
            w.add("convert", t2 - t1, t1)
        return st

    def _resident_input(self):
        """Rebuild the span input from the resident device output:
        static config reattaches from the cache; derived columns
        re-derive by the same law _to_arrays applies to a fresh
        export (their fresh-export values hold at every clean span
        boundary: all continuations idle, drains quiescent)."""
        import jax.numpy as jnp
        st = {k: v for k, v in self._res_st.items()
              if k != "abort_code" and not k.startswith("tr_")
              and not k.startswith("fab_")
              and not k.startswith("ks_")}
        st.update(self._static_cols)
        z = np.zeros(self._H, np.int32)
        for k in ("cont", "then", "out_first", "cd_chain", "cd_sniff"):
            st[k] = z
        st["park_ctr"] = jnp.maximum(st["m_waitseq"],
                                     st["s_waitseq"]) + 1
        return st

    def _clamp_mr(self, mr: int | None) -> int:
        """The effective max-rounds law for one dispatch — shared by
        the normal and the speculative path so an in-flight window's
        recorded params land against the same clamp."""
        mr = self.MAX_ROUNDS if mr is None else mr
        if self.fabric is not None:
            # Sampled rounds <= rounds <= FAB_ROWS: the device-side
            # sample buffers can never overflow (a silent skip would
            # break cross-path byte-parity).
            mr = min(mr, self.FAB_ROWS)
        return mr

    def try_span(self, start: int, stop: int, limit: int,
                 runahead: int, dynamic: bool,
                 max_rounds: int | None = None, spec_mr: int = 0):
        """Export -> device span -> import.  Returns (rounds,
        busy_rounds, packets, next_start, busy_end, runahead) or None
        when ineligible / zero-progress / aborted.

        Residency: while the engine's state_epoch is unchanged since
        our last import (nothing but this runner touched host state),
        the previous span's device-resident output is reused directly
        and the export+conversion leg of the dispatch tunnel is
        skipped; ANY other engine call in between makes the resident
        copy stale and forces a fresh export (never silent reuse).

        Overlap (ISSUE 16): with `spec_mr > 0` and span_overlap on, a
        clean commit dispatches window K+1 asynchronously (max
        `spec_mr` rounds) before the host-side import work runs; the
        NEXT try_span lands it through _take_inflight iff the window
        params match and the engine epoch is unchanged — otherwise
        the unforced record is discarded unimported (SpanMeshMixin)."""
        mr = self._clamp_mr(max_rounds)
        landed = self._take_inflight(
            (int(start), int(stop), int(limit), int(runahead),
             bool(dynamic), mr))
        if landed is not None:
            # The speculative dispatch consumed the resident carry's
            # arrays as its input; an abort retry must re-export.
            resident = True
        else:
            eng_epoch = self.engine.state_epoch()
            resident = (self._res_st is not None
                        and self._res_token == eng_epoch)
            if self._res_st is not None and not resident:
                self.stale_drops += 1
                self._res_st = None
            if resident:
                self.resident_hits += 1
                st = self._resident_input()
                self._res_st = None  # consumed by this dispatch
            else:
                st = self._export_state()
                if st is None:
                    # structurally not a phold sim — permanent for
                    # this run
                    self.ineligible += 1
                    return None
                if isinstance(st, int):
                    # transiently beyond the ring caps (burst): retry
                    # later
                    self.over_caps += 1
                    return None
            # Re-resolve per span (a dict lookup when nothing
            # changed) so a runner.fused toggle between spans takes
            # effect — the tcp twin does the same.
            self._fn = self._cached_build(
                self._static_cols["peers"].shape[1])
            if self.mesh is not None:
                st = self._mesh_put(st)
        w = self.wall
        for _grow in range(4):
            t0 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
            spec_rec, landed = landed, None
            if spec_rec is not None:
                fresh_fn = False
                out = spec_rec["out"]
            else:
                fresh_fn = id(self._fn) not in self._timed_fns
                out = self._span_call(
                    self._fn,
                    st, self._lat, self._thr, self._node,
                    self._ips_sorted, self._ips_perm,
                    np.uint32(self._k[0]), np.uint32(self._k[1]),
                    np.int64(self.bootstrap_end), np.int64(self._pay),
                    start, stop, limit, runahead, mr)
            (st_out, next_start, ra, rounds, busy_rounds, packets,
             busy_end, span_iters) = out
            st_np = {k: np.asarray(v) for k, v in st_out.items()}
            code = int(st_np["abort_code"])
            # The first dispatch THROUGH A GIVEN BUILT FN pays
            # trace+XLA compile (capacity regrows rebuild the fn and
            # recompile): credit those separately so "execute" stays
            # the steady state (the np.asarray forced device
            # completion).  The same split feeds the explicit
            # fn_cache accounting (metrics.wall.dispatch.fn_cache).
            dt = time.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
            self._timed_fns.add(id(self._fn))
            self.device_wall_ns += dt
            if spec_rec is not None:
                # A landed window's force wait is host idle (the
                # device was already running); its dispatch->force
                # wall is the pipe the idle fractions divide by.
                self.overlap_wait_ns += dt
                self.overlap_pipe_ns += \
                    time.perf_counter_ns() - spec_rec["t_disp"]  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                if w is not None:
                    w.add("overlap-land", dt, t0)
            else:
                if fresh_fn:
                    self._credit_build(self._fn, dt)
                if w is not None:
                    w.add("compile" if fresh_fn else "execute", dt, t0)
            if code == 0:
                break
            # Speculative-window waste: the aborted dispatch's wall
            # and its stepped-then-discarded rounds roll back unused.
            self.rollback_wall_ns += dt
            self.rolled_back_rounds += int(rounds)
            self._note_abort_kind(code)
            if code & AB_STRUCT:
                self.last_abort_code = code
                # Hard abort regardless of residency (and before any
                # re-export the next statement would discard); the
                # consumed resident carry was already cleared above.
                self.aborts += 1
                return None
            if resident or self.donate_active():
                # The resident carry was consumed by the aborted
                # dispatch — and under donation the FRESH input's
                # buffers were donated to it too, so either way the
                # retry needs new arrays; the engine — kept
                # authoritative by the per-span imports — re-exports
                # the same state.  Abort accounting follows the
                # fresh-dispatch convention: a capacity grow that
                # then succeeds counts zero.
                resident = False
                _tr = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                st = self._export_state()
                self.rollback_reexport_ns += \
                    time.perf_counter_ns() - _tr  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                if st is None:
                    # structurally no longer phold-shaped
                    self.ineligible += 1
                    return None
                if isinstance(st, int):
                    # transiently beyond the ring caps
                    self.over_caps += 1
                    return None
                if self.mesh is not None:
                    st = self._mesh_put(st)
            # Trace/outbox/exchange overflow: a capacity problem, not
            # a domain problem — grow the buffer and re-run the span
            # (the input state was never mutated; export is read-only,
            # and the retry re-applies mesh sharding above).
            if code & AB_TRACE:
                self.cap_tr *= 4
            if code & AB_OUT:
                self.cap_out *= 4
            if code & AB_EXCH:
                # Grow from the EFFECTIVE capacity (the kernel builds
                # with E = max(exchange_cap, 8)), so a tiny configured
                # capacity cannot waste a retry on an identical shape.
                self.exchange_cap = max(self.exchange_cap, 8) * 4
                self.exch_grows += 1
            self._fn = self._cached_build(
                self._static_cols["peers"].shape[1])
        else:
            self.last_abort_code = code
            self.aborts += 1
            return None
        if int(rounds) == 0:
            # Legitimate zero progress (start at/past the limit
            # boundary): nothing changed, nothing to import — NOT a
            # failure.  Callers distinguish this from None.  The
            # untouched carry stays resident (the output is the
            # identical state).
            self._res_st = st_out
            self._res_token = self.engine.state_epoch()
            return (0, 0, 0, int(start), int(start), int(runahead))
        # Overlap: dispatch window K+1 asynchronously NOW, so the
        # device executes it while the host does this window's codec
        # conversion + engine import below.  Donation is excluded (a
        # donated carry cannot serve as both resident state and the
        # speculative input).  The record is committed (epoch-stamped
        # and published) only after the import below bumped the
        # epoch — the async-hazard lint rule holds this window open.
        ra_out = int(ra) if dynamic else int(runahead)
        spec = None
        if self.overlap and spec_mr > 0 and not self.donate_active() \
                and int(next_start) < int(stop) \
                and int(next_start) < int(limit):
            spec = self._speculate(st_out, int(next_start), int(stop),
                                   int(limit), ra_out, dynamic,
                                   spec_mr)
        traces = None
        if self.tracing:
            n = int(st_np["tr_n"])
            traces = {
                "n": n,
                "t": st_np["tr_t"][:n].astype(np.int64).tobytes(),
                "kind": st_np["tr_kind"][:n].astype(
                    np.uint8).tobytes(),
                "srchost": st_np["tr_srchost"][:n].astype(
                    np.int32).tobytes(),
                "pseq": st_np["tr_pseq"][:n].astype(
                    np.int64).tobytes(),
                "sip": st_np["tr_sip"][:n].astype(
                    np.uint32).tobytes(),
                "sport": st_np["tr_sport"][:n].astype(
                    np.int32).tobytes(),
                "dip": st_np["tr_dip"][:n].astype(np.uint32).tobytes(),
                "dport": st_np["tr_dport"][:n].astype(
                    np.int32).tobytes(),
                "size": np.full(n, self._pay, np.int64).tobytes(),
                "reason": st_np["tr_reason"][:n].astype(
                    np.uint8).tobytes(),
                "owner": st_np["tr_owner"][:n].astype(
                    np.int32).tobytes(),
            }
        t0 = w.now() if w is not None else 0
        # fab_*/ks_* sample buffers are span-local output, not engine
        # state.
        back = self._from_arrays(
            {k: v for k, v in st_np.items()
             if not k.startswith("fab_")
             and not k.startswith("ks_")})
        # Codec byte volume, host -> engine (dispatch attribution).
        self.import_bytes += sum(
            len(v) for v in back.values()
            if isinstance(v, (bytes, bytearray, memoryview)))
        self.engine.span_import_phold(
            back, self.CAP_I, self.CAP_T, self.CAP_R, self.CAP_S,
            self.CAP_C, self.CAP_P, traces)
        if self.fabric is not None:
            from shadow_tpu.trace.fabricstat import emit_device_rows
            emit_device_rows(self.fabric, st_np, self._H)
        if self.kern is not None:
            # One KS_REC per committed span (aborted spans rolled
            # back above and recorded nothing — the conservation law).
            from shadow_tpu.trace.events import FAM_PHOLD
            self.kern.record_span(
                int(start), FAM_PHOLD, self._H, int(rounds),
                int(span_iters), st_np["ks_fires"], st_np["ks_lanes"])
        if w is not None:
            w.add("import", w.now() - t0, t0)
        # The import itself bumps the epoch; record it AFTER, so the
        # resident copy is valid exactly until anything else touches
        # the engine.
        self._res_st = st_out
        self._res_token = self.engine.state_epoch()
        self.last_was_cold = not self.compiled
        self.compiled = True
        self.spans += 1
        self.rounds += int(rounds)
        self.micro_iters += int(span_iters)
        if spec is not None:
            self._commit_spec(spec)
        return (int(rounds), int(busy_rounds), int(packets),
                int(next_start), int(busy_end), ra_out)

    def _speculate(self, st_out, start, stop, limit, runahead,
                   dynamic, spec_mr):
        """Async double-buffered dispatch of window K+1 (ISSUE 16):
        rebuild the span input from the just-committed device output
        (the residency law — _resident_input — so no export touches
        the engine) and dispatch WITHOUT forcing; jax async dispatch
        returns unforced device arrays and XLA executes them on its
        own threads while the caller runs the host-side import.  The
        returned record is a Future in all but name; SpanMeshMixin
        owns its commit/land/refuse protocol."""
        mr = self._clamp_mr(spec_mr)
        saved = self._res_st
        self._res_st = st_out
        st = self._resident_input()
        self._res_st = saved
        if self.mesh is not None:
            st = self._mesh_put(st)
        w = self.wall
        t0 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
        out = self._span_call(
            self._fn,
            st, self._lat, self._thr, self._node,
            self._ips_sorted, self._ips_perm,
            np.uint32(self._k[0]), np.uint32(self._k[1]),
            np.int64(self.bootstrap_end), np.int64(self._pay),
            start, stop, limit, runahead, mr)
        self.overlap_windows += 1
        if w is not None:
            w.add("dispatch",
                  time.perf_counter_ns() - t0, t0)  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
        return self._speculate_record(
            out, t0, (start, stop, limit, runahead, bool(dynamic),
                      mr))
