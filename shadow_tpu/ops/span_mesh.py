"""Shared device-placement helpers for the span-runner twins.

Both device-span families (ops/phold_span.py, ops/tcp_span.py) cache
their static SoA columns as committed device arrays and, when a
sharded mesh is attached, commit every span input with host-major
columns sharded on the "hosts" axis.  The placement law is identical
for both runners, so it lives here once; the runners mix it in and
provide `self.mesh` and `self._H`.
"""

from __future__ import annotations

import os
import sys

_donate_warned = False


def donation_cache_safe() -> bool:
    """The compile-cache-safe donation guard (BASELINE.md round 6):
    a donated executable loaded back from the PERSISTENT XLA
    compilation cache corrupts the glibc heap on deserialization-hit
    runs, so `experimental.tpu_donate_buffers: on` donates ONLY when
    no persistent cache is configured — never the corrupting
    combination.  Checked once per kernel build (the cache dir is
    process-static in practice)."""
    global _donate_warned
    import jax
    cache_dir = (getattr(jax.config, "jax_compilation_cache_dir", None)
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    if cache_dir:
        if not _donate_warned:
            _donate_warned = True
            print("[shadow-tpu] tpu_donate_buffers=on ignored: a "
                  "persistent XLA compilation cache is configured "
                  f"({cache_dir!r}) and donated executables corrupt "
                  "the heap on cache-hit runs (BASELINE.md r6)",
                  file=sys.stderr)
        return False
    return True


class SpanMeshMixin:
    """Device placement for span inputs: `mesh` (optional
    jax.sharding.Mesh with a "hosts" axis) and `_H` (host count)
    come from the concrete runner."""

    # Cross-shard exchange capacity (per destination shard per span
    # round) when a mesh with >1 devices is attached: seeded from
    # experimental.tpu_exchange_capacity by the manager's runner
    # factory, grown transactionally on an AB_EXCH abort (exchange
    # overflow is an attributed capacity abort, never truncation).
    exchange_cap = 1 << 12
    exch_grows = 0

    @property
    def n_shards(self) -> int:
        """Mesh width the kernel builds for (1 = unsharded).  The
        placement law requires H % n_shards == 0 — the manager never
        attaches a mesh to an unaligned host axis."""
        if self.mesh is None:
            return 1
        return int(self.mesh.devices.size)

    # experimental.tpu_donate_buffers (set by the manager's runner
    # factory): the jitted span loop donates its carry (argnums 0) so
    # XLA reuses the resident buffers in place — behind the
    # cache-safe guard above.
    donate = False

    def _span_jit(self, jax, run):
        """jit the span loop, donating the carry when allowed."""
        if self.donate and donation_cache_safe():
            return jax.jit(run, donate_argnums=(0,))
        return jax.jit(run)

    def donate_active(self) -> bool:
        """Whether the built span fn donates its carry — the
        capacity-abort retry path must re-materialize the input then
        (a donated buffer cannot be dispatched twice)."""
        return self.donate and donation_cache_safe()

    def _put_static(self, jax, v):
        if self.mesh is None:
            return jax.device_put(v)
        from jax.sharding import NamedSharding, PartitionSpec
        spec = (PartitionSpec("hosts")
                if getattr(v, "ndim", 0) >= 1 and v.shape[0] == self._H
                else PartitionSpec())
        return jax.device_put(v, NamedSharding(self.mesh, spec))

    def _build_exchange(self, jax, jnp):
        """The sharded span kernels' cross-shard exchange law (ISSUE
        11 tentpole), shared by both families.  Kept outbox packets
        route to their destination shard through a fixed-capacity
        staging buffer — the slot law is round_step.py's (stable
        cumulative rank per destination shard, capacity E slots per
        shard pair) — and the staged block is sharding-constrained to
        the hosts axis so the partitioner lowers the hop to the
        cross-shard collective (the `lax.all_to_all` of the per-round
        mesh path, in the GSPMD idiom the span while_loop runs in).
        Overflow never truncates: the caller marks AB_EXCH and the
        driver grows `exchange_cap` and retries transactionally.

        Returns (stage, SE): `stage(keep, dst_shard, cols)` maps
        {name: (values[N], fill)} to ({name: staged[SE]}, over[N]).
        """
        from jax.sharding import NamedSharding, PartitionSpec
        spec = NamedSharding(self.mesh, PartitionSpec("hosts"))
        S = self.n_shards
        E = max(int(self.exchange_cap), 8)
        SE = S * E

        def stage(keep, dst_shard, cols):
            onehot = (dst_shard[None, :]
                      == jnp.arange(S)[:, None]) & keep
            rank = jnp.cumsum(onehot, axis=1) - 1
            slot = jnp.take_along_axis(
                rank, dst_shard[None, :], axis=0)[0]
            fits = keep & (slot < E)
            over = keep & ~fits
            flat = jnp.where(fits, dst_shard * E + slot, SE)
            out = {}
            for name, (v, fill) in cols.items():
                buf = jnp.full(SE, fill, v.dtype).at[flat].set(
                    v, mode="drop")
                out[name] = jax.lax.with_sharding_constraint(
                    buf.reshape(S, E), spec).reshape(SE)
            return out, over
        return stage, SE

    def _mesh_put(self, st):
        """Commit every span input to the device mesh: host-major
        columns shard on the hosts axis, everything else replicates.
        Already-committed arrays (the static cache) pass through."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        shard = NamedSharding(self.mesh, PartitionSpec("hosts"))
        repl = NamedSharding(self.mesh, PartitionSpec())
        H = self._H
        return {k: jax.device_put(
                    v, shard if (getattr(v, "ndim", 0) >= 1
                                 and v.shape[0] == H) else repl)
                for k, v in st.items()}
