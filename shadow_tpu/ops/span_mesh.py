"""Shared device-placement helpers for the span-runner twins.

Both device-span families (ops/phold_span.py, ops/tcp_span.py) cache
their static SoA columns as committed device arrays and, when a
sharded mesh is attached, commit every span input with host-major
columns sharded on the "hosts" axis.  The placement law is identical
for both runners, so it lives here once; the runners mix it in and
provide `self.mesh` and `self._H`.
"""

from __future__ import annotations

import os
import sys
import time

_donate_warned = False

# Abort reason bits — ONE canonical set for both span families (the
# kernels re-export these as module constants; core/manager imports
# AB_EXCH for exchange-capacity attribution).  Trace/outbox overflows
# are capacity problems the driver fixes by growing the buffer and
# retrying; AB_STRUCT means the state left the modelled domain (fall
# back to the C++ path); AB_EXCH is the sharded cross-shard exchange
# overflowing its per-shard capacity — grown and retried, never
# silently truncated.
AB_TRACE = 1
AB_OUT = 2
AB_STRUCT = 4
AB_EXCH = 8

# AOT-compiled span executables, keyed on the _FN_CACHE entry's
# identity (the caches never evict, so id() is stable): one XLA
# compile per built kernel across every Manager in the process —
# the same warm-run property as the jit call cache.  Each value is
# (jax.stages.Compiled, cost_analysis summary dict).
_AOT_CACHE: dict = {}


def donation_cache_safe() -> bool:
    """The compile-cache-safe donation guard (BASELINE.md round 6):
    a donated executable loaded back from the PERSISTENT XLA
    compilation cache corrupts the glibc heap on deserialization-hit
    runs, so `experimental.tpu_donate_buffers: on` donates ONLY when
    no persistent cache is configured — never the corrupting
    combination.  Checked once per kernel build (the cache dir is
    process-static in practice)."""
    global _donate_warned
    import jax
    cache_dir = (getattr(jax.config, "jax_compilation_cache_dir", None)
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    if cache_dir:
        if not _donate_warned:
            _donate_warned = True
            print("[shadow-tpu] tpu_donate_buffers=on ignored: a "
                  "persistent XLA compilation cache is configured "
                  f"({cache_dir!r}) and donated executables corrupt "
                  "the heap on cache-hit runs (BASELINE.md r6)",
                  file=sys.stderr)
        return False
    return True


class SpanMeshMixin:
    """Device placement for span inputs: `mesh` (optional
    jax.sharding.Mesh with a "hosts" axis) and `_H` (host count)
    come from the concrete runner."""

    # Cross-shard exchange capacity (per destination shard per span
    # round) when a mesh with >1 devices is attached: seeded from
    # experimental.tpu_exchange_capacity by the manager's runner
    # factory, grown transactionally on an AB_EXCH abort (exchange
    # overflow is an attributed capacity abort, never truncation).
    exchange_cap = 1 << 12
    exch_grows = 0

    @property
    def n_shards(self) -> int:
        """Mesh width the kernel builds for (1 = unsharded).  The
        placement law requires H % n_shards == 0 — the manager never
        attaches a mesh to an unaligned host axis."""
        if self.mesh is None:
            return 1
        return int(self.mesh.devices.size)

    # experimental.tpu_donate_buffers (set by the manager's runner
    # factory): the jitted span loop donates its carry (argnums 0) so
    # XLA reuses the resident buffers in place — behind the
    # cache-safe guard above.
    donate = False

    # ---- Device-kernel observatory (docs/OBSERVABILITY.md) ----------
    # `kern` is the sim-time KernChannel (or None) the driver records
    # one KS_REC into per committed span; `kern_wall` enables the
    # wall-side dispatch attribution (explicit _FN_CACHE accounting,
    # AOT cost_analysis, export/import byte volume) — both set by the
    # manager's runner factory from experimental.kernel_observatory.
    # The integer counters below are class attributes that become
    # instance attributes on first `+=` (the exchange_cap pattern):
    # they live in metrics.wall.dispatch, never in simulation bytes.
    kern = None
    kern_wall = False
    fn_cache_hits = 0        # _FN_CACHE served an already-built fn
    fn_cache_misses = 0      # a fresh kernel build (trace pending)
    fn_cache_build_ns = 0    # wall of each missed fn's FIRST dispatch
    #                          (where jit pays trace + XLA compile)
    device_wall_ns = 0       # wall of every span dispatch, all fates
    rollback_wall_ns = 0     # wall of dispatches that ABORTED (the
    #                          speculative window rolled back unused)
    rollback_reexport_ns = 0  # wall of re-exports an abort forced
    rolled_back_rounds = 0   # rounds stepped then discarded by aborts
    export_bytes = 0         # codec bytes engine -> host, cumulative
    import_bytes = 0         # codec bytes host -> engine, cumulative
    _aot = None              # fn ids whose cost this runner logged
    _aot_off = False         # AOT path disabled after a failure
    kernel_costs = None      # Compiled.cost_analysis() per built fn

    # ---- Overlapped span pipeline (ISSUE 16) ------------------------
    # `overlap` (experimental.span_overlap, set by the manager's
    # runner factory) double-buffers dispatch: after a clean commit
    # the driver dispatches the NEXT speculative window asynchronously
    # (jax async dispatch — unforced device arrays) and records it in
    # `_inflight` together with the window params and the post-import
    # engine state_epoch; the host-side import/codec/service work for
    # the committed window then runs while the device executes.  The
    # next try_span LANDS the record iff the params match exactly and
    # the epoch has not moved — any drift refuses the window (the
    # record is discarded UNIMPORTED, so nothing speculative ever
    # reaches engine bytes: byte identity by construction).
    # `pallas_queues` (experimental.pallas_queue_kernels) routes the
    # token-bucket/CoDel scans through ops/pallas_queues.py.
    overlap = False
    pallas_queues = False
    _inflight = None         # {"out", "t_disp", "params", "epoch",
    #                          "t_flush", "ready_at_flush"} or None
    overlap_windows = 0      # speculative windows dispatched
    overlap_hits = 0         # ...landed and consumed
    overlap_refusals = 0     # ...refused (params/epoch mismatch)
    overlap_stale = 0        # refusals caused by state_epoch drift
    overlap_wait_ns = 0      # HOST idle: wall blocked forcing a
    #                          landed window (device still running)
    overlap_idle_ns = 0      # DEVICE idle (lower bound): flush->land
    #                          gap, counted only when the window was
    #                          already ready at flush time
    overlap_pipe_ns = 0      # dispatch->force wall of landed windows

    def _speculate_record(self, out, t_disp, params):
        """The Future-shaped in-flight record: unforced device arrays
        plus everything the landing check needs.  `epoch` is stamped
        at _commit_spec time (AFTER the committed window's import
        bumped it) — the async-hazard lint rule (analysis pass 3)
        enforces that no engine mutator runs between dispatch and
        that commit point."""
        return {"out": out, "t_disp": t_disp, "params": params,
                "epoch": None, "t_flush": 0, "ready_at_flush": False}

    def _commit_spec(self, spec) -> None:
        """Commit point of an async dispatch: stamp the engine epoch
        (all host-side work for the committed window has run; any
        LATER engine mutation invalidates the record at landing) and
        probe — without blocking — whether the device already
        finished, so the flush->land gap can be attributed as device
        idle honestly (ready_at_flush False keeps it a lower bound)."""
        spec["epoch"] = self.engine.state_epoch()
        try:
            spec["ready_at_flush"] = bool(
                spec["out"][0]["abort_code"].is_ready())
        except Exception:
            spec["ready_at_flush"] = False
        spec["t_flush"] = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
        self._inflight = spec

    def _take_inflight(self, params):
        """Land (or refuse) the in-flight window for this try_span
        call.  Returns the record on a hit, None otherwise; ALWAYS
        clears `_inflight` — a refused window is discarded unimported
        (the committed resident state still serves the normal path,
        so refusal costs one dispatch, never correctness)."""
        spec, self._inflight = self._inflight, None
        if spec is None:
            return None
        if spec["params"] != params:
            self.overlap_refusals += 1
            return None
        if self.engine.state_epoch() != spec["epoch"]:
            self.overlap_refusals += 1
            self.overlap_stale += 1
            return None
        self.overlap_hits += 1
        # A landed window is residency-served: its input was rebuilt
        # from the resident device output at speculate time, and no
        # export ran — the residency counter keeps meaning
        # "dispatches served without an engine export".
        self.resident_hits += 1
        if spec["ready_at_flush"]:
            now = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
            self.overlap_idle_ns += now - spec["t_flush"]
        return spec

    def overlap_summary(self) -> dict:
        """The per-family `overlap` block in metrics.wall.dispatch."""
        pipe = max(self.overlap_pipe_ns, 1)
        return {
            "windows": self.overlap_windows,
            "hits": self.overlap_hits,
            "refusals": self.overlap_refusals,
            "stale_refusals": self.overlap_stale,
            "host_idle_wall_s": round(self.overlap_wait_ns / 1e9, 3),
            "device_idle_wall_s": round(self.overlap_idle_ns / 1e9, 3),
            "pipe_wall_s": round(self.overlap_pipe_ns / 1e9, 3),
            "host_idle_frac": round(self.overlap_wait_ns / pipe, 4),
            "device_idle_frac": round(self.overlap_idle_ns / pipe, 4),
        }

    def _cache_fn(self, cache: dict, key, build):
        """THE _FN_CACHE lookup both runners use: explicit hit/miss
        accounting instead of the old compile-vs-execute guessing
        (`metrics.wall.dispatch.fn_cache`).  The build wall lands in
        fn_cache_build_ns at the missed fn's first dispatch — jit
        defers trace+compile to the call, so the insert itself is
        free."""
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = build()
            self.fn_cache_misses += 1
            self.__dict__.setdefault("_built_fns", set()).add(id(fn))
        else:
            self.fn_cache_hits += 1
        return fn

    def _credit_build(self, fn, dt_ns: int) -> None:
        """Credit a first dispatch's wall to fn_cache.build_wall_s
        ONLY when this runner actually built the fn — a cache-served
        kernel's first (warm) dispatch is not a build."""
        if id(fn) in self.__dict__.get("_built_fns", ()):
            self.fn_cache_build_ns += dt_ns

    def abort_kind_counts(self) -> dict:
        """Lazily-created {kind: count} of abort codes seen by this
        runner (struct / exchange-capacity / capacity) — what `trace
        explain` names when rollback waste dominates."""
        d = self.__dict__.get("_abort_kinds")
        if d is None:
            d = self.__dict__["_abort_kinds"] = {}
        return d

    def _note_abort_kind(self, code: int) -> None:
        """Classify one aborted dispatch as exactly ONE kind —
        priority struct > exchange-capacity > capacity (a code can
        carry several bits; counting per bit would make kind counts
        exceed aborted dispatches and skew `trace explain`'s
        dominant-abort ranking).  The AB_* bits are this module's
        canonical constants, re-exported by both kernels."""
        kinds = self.abort_kind_counts()
        if code & AB_STRUCT:
            kind = "struct"
        elif code & AB_EXCH:
            kind = "exchange-capacity"
        else:
            kind = "capacity"
        kinds[kind] = kinds.get(kind, 0) + 1

    def _span_call(self, fn, *args):
        """Dispatch the built span fn.  Under the observatory's wall
        mode (unsharded only — AOT lowering pins input shardings) the
        first dispatch per built fn goes through the explicit AOT path
        (trace -> lower -> compile), so the build wall splits into its
        trace and XLA-compile legs and `Compiled.cost_analysis()`
        yields real flops/bytes per cached kernel instead of a
        heuristic.  The Compiled is cached GLOBALLY alongside the
        _FN_CACHE entry (keyed on the cached fn's identity, which the
        never-evicting cache pins) so a later Manager's runner reuses
        it exactly like the jit call cache — warm runs stay warm.
        Any AOT failure falls back to plain jit dispatch permanently —
        attribution degrades, correctness never."""
        if not self.kern_wall or self.mesh is not None \
                or self._aot_off:
            return fn(*args)
        if self._aot is None:
            self._aot = set()   # fn ids whose cost this runner logged
            self.kernel_costs = []
        ent = _AOT_CACHE.get(id(fn))
        if ent is None:
            try:
                t0 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                lowered = fn.lower(*args)
                t1 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                comp = lowered.compile()
                t2 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] dispatch attribution (metrics.wall)
                cost = comp.cost_analysis()
                if isinstance(cost, (list, tuple)):
                    cost = cost[0] if cost else {}
                ent = _AOT_CACHE[id(fn)] = (comp, {
                    "flops": float(cost.get("flops", 0.0)),
                    "bytes_accessed": float(
                        cost.get("bytes accessed", 0.0)),
                    "trace_wall_s": round((t1 - t0) / 1e9, 3),
                    "compile_wall_s": round((t2 - t1) / 1e9, 3),
                })
            except Exception:
                self._aot_off = True
                return fn(*args)
        if id(fn) not in self._aot:
            self._aot.add(id(fn))
            self.kernel_costs.append(dict(ent[1]))
        return ent[0](*args)

    def _span_jit(self, jax, run):
        """jit the span loop, donating the carry when allowed."""
        if self.donate and donation_cache_safe():
            return jax.jit(run, donate_argnums=(0,))
        return jax.jit(run)

    def donate_active(self) -> bool:
        """Whether the built span fn donates its carry — the
        capacity-abort retry path must re-materialize the input then
        (a donated buffer cannot be dispatched twice)."""
        return self.donate and donation_cache_safe()

    def _put_static(self, jax, v):
        if self.mesh is None:
            return jax.device_put(v)
        from jax.sharding import NamedSharding, PartitionSpec
        spec = (PartitionSpec("hosts")
                if getattr(v, "ndim", 0) >= 1 and v.shape[0] == self._H
                else PartitionSpec())
        return jax.device_put(v, NamedSharding(self.mesh, spec))

    def _build_exchange(self, jax, jnp):
        """The sharded span kernels' cross-shard exchange law (ISSUE
        11 tentpole), shared by both families.  Kept outbox packets
        route to their destination shard through a fixed-capacity
        staging buffer — the slot law is round_step.py's (stable
        cumulative rank per destination shard, capacity E slots per
        shard pair) — and the staged block is sharding-constrained to
        the hosts axis so the partitioner lowers the hop to the
        cross-shard collective (the `lax.all_to_all` of the per-round
        mesh path, in the GSPMD idiom the span while_loop runs in).
        Overflow never truncates: the caller marks AB_EXCH and the
        driver grows `exchange_cap` and retries transactionally.

        Returns (stage, SE): `stage(keep, dst_shard, cols)` maps
        {name: (values[N], fill)} to ({name: staged[SE]}, over[N]).
        """
        from jax.sharding import NamedSharding, PartitionSpec
        spec = NamedSharding(self.mesh, PartitionSpec("hosts"))
        S = self.n_shards
        E = max(int(self.exchange_cap), 8)
        SE = S * E

        def stage(keep, dst_shard, cols):
            onehot = (dst_shard[None, :]
                      == jnp.arange(S)[:, None]) & keep
            rank = jnp.cumsum(onehot, axis=1) - 1
            slot = jnp.take_along_axis(
                rank, dst_shard[None, :], axis=0)[0]
            fits = keep & (slot < E)
            over = keep & ~fits
            flat = jnp.where(fits, dst_shard * E + slot, SE)
            out = {}
            for name, (v, fill) in cols.items():
                buf = jnp.full(SE, fill, v.dtype).at[flat].set(
                    v, mode="drop")
                out[name] = jax.lax.with_sharding_constraint(
                    buf.reshape(S, E), spec).reshape(SE)
            return out, over
        return stage, SE

    def _mesh_put(self, st):
        """Commit every span input to the device mesh: host-major
        columns shard on the hosts axis, everything else replicates.
        Already-committed arrays (the static cache) pass through."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        shard = NamedSharding(self.mesh, PartitionSpec("hosts"))
        repl = NamedSharding(self.mesh, PartitionSpec())
        H = self._H
        return {k: jax.device_put(
                    v, shard if (getattr(v, "ndim", 0) >= 1
                                 and v.shape[0] == H) else repl)
                for k, v in st.items()}
