"""Shared device-placement helpers for the span-runner twins.

Both device-span families (ops/phold_span.py, ops/tcp_span.py) cache
their static SoA columns as committed device arrays and, when a
sharded mesh is attached, commit every span input with host-major
columns sharded on the "hosts" axis.  The placement law is identical
for both runners, so it lives here once; the runners mix it in and
provide `self.mesh` and `self._H`.
"""

from __future__ import annotations

import os
import sys

_donate_warned = False


def donation_cache_safe() -> bool:
    """The compile-cache-safe donation guard (BASELINE.md round 6):
    a donated executable loaded back from the PERSISTENT XLA
    compilation cache corrupts the glibc heap on deserialization-hit
    runs, so `experimental.tpu_donate_buffers: on` donates ONLY when
    no persistent cache is configured — never the corrupting
    combination.  Checked once per kernel build (the cache dir is
    process-static in practice)."""
    global _donate_warned
    import jax
    cache_dir = (getattr(jax.config, "jax_compilation_cache_dir", None)
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    if cache_dir:
        if not _donate_warned:
            _donate_warned = True
            print("[shadow-tpu] tpu_donate_buffers=on ignored: a "
                  "persistent XLA compilation cache is configured "
                  f"({cache_dir!r}) and donated executables corrupt "
                  "the heap on cache-hit runs (BASELINE.md r6)",
                  file=sys.stderr)
        return False
    return True


class SpanMeshMixin:
    """Device placement for span inputs: `mesh` (optional
    jax.sharding.Mesh with a "hosts" axis) and `_H` (host count)
    come from the concrete runner."""

    # experimental.tpu_donate_buffers (set by the manager's runner
    # factory): the jitted span loop donates its carry (argnums 0) so
    # XLA reuses the resident buffers in place — behind the
    # cache-safe guard above.
    donate = False

    def _span_jit(self, jax, run):
        """jit the span loop, donating the carry when allowed."""
        if self.donate and donation_cache_safe():
            return jax.jit(run, donate_argnums=(0,))
        return jax.jit(run)

    def donate_active(self) -> bool:
        """Whether the built span fn donates its carry — the
        capacity-abort retry path must re-materialize the input then
        (a donated buffer cannot be dispatched twice)."""
        return self.donate and donation_cache_safe()

    def _put_static(self, jax, v):
        if self.mesh is None:
            return jax.device_put(v)
        from jax.sharding import NamedSharding, PartitionSpec
        spec = (PartitionSpec("hosts")
                if getattr(v, "ndim", 0) >= 1 and v.shape[0] == self._H
                else PartitionSpec())
        return jax.device_put(v, NamedSharding(self.mesh, spec))

    def _mesh_put(self, st):
        """Commit every span input to the device mesh: host-major
        columns shard on the hosts axis, everything else replicates.
        Already-committed arrays (the static cache) pass through."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        shard = NamedSharding(self.mesh, PartitionSpec("hosts"))
        repl = NamedSharding(self.mesh, PartitionSpec())
        H = self._H
        return {k: jax.device_put(
                    v, shard if (getattr(v, "ndim", 0) >= 1
                                 and v.shape[0] == H) else repl)
                for k, v in st.items()}
