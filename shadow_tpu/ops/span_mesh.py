"""Shared device-placement helpers for the span-runner twins.

Both device-span families (ops/phold_span.py, ops/tcp_span.py) cache
their static SoA columns as committed device arrays and, when a
sharded mesh is attached, commit every span input with host-major
columns sharded on the "hosts" axis.  The placement law is identical
for both runners, so it lives here once; the runners mix it in and
provide `self.mesh` and `self._H`.
"""

from __future__ import annotations


class SpanMeshMixin:
    """Device placement for span inputs: `mesh` (optional
    jax.sharding.Mesh with a "hosts" axis) and `_H` (host count)
    come from the concrete runner."""

    def _put_static(self, jax, v):
        if self.mesh is None:
            return jax.device_put(v)
        from jax.sharding import NamedSharding, PartitionSpec
        spec = (PartitionSpec("hosts")
                if getattr(v, "ndim", 0) >= 1 and v.shape[0] == self._H
                else PartitionSpec())
        return jax.device_put(v, NamedSharding(self.mesh, spec))

    def _mesh_put(self, st):
        """Commit every span input to the device mesh: host-major
        columns shard on the hosts axis, everything else replicates.
        Already-committed arrays (the static cache) pass through."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        shard = NamedSharding(self.mesh, PartitionSpec("hosts"))
        repl = NamedSharding(self.mesh, PartitionSpec())
        H = self._H
        return {k: jax.device_put(
                    v, shard if (getattr(v, "ndim", 0) >= 1
                                 and v.shape[0] == H) else repl)
                for k, v in st.items()}
